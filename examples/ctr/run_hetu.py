"""CTR training entrypoint (reference parity: examples/ctr/run_hetu.py —
same CLI surface: --model, --comm-mode (None/PS/Hybrid), --bsp, --cache,
--all/--val/--timing metrics loop printing loss/acc/AUC per epoch).

PS mode defaults to the TPU-native device cache (``--cache Device``),
which keeps embedding rows in HBM with bounded-staleness drains to the
C++ parameter server — see hetu_tpu/ps/device_cache.py.

    python examples/ctr/run_hetu.py --model wdl_criteo --timing
    heturun -c settings/local_ps.yml python examples/ctr/run_hetu.py \
        --model wdl_criteo --comm-mode PS --timing
"""
import argparse
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import hetu_tpu as ht                               # noqa: E402
from hetu_tpu.models import ctr as ctr_models       # noqa: E402
from hetu_tpu.metrics import auc                    # noqa: E402

MODELS = ["wdl_criteo", "dcn_criteo", "dc_criteo", "deepfm_criteo",
          "wdl_adult"]


def load_criteo(args):
    """Criteo-format arrays from HETU_DATA_DIR, else a synthetic stand-in
    with Criteo's shape and a planted signal (reference load_data.py
    requires the downloaded dataset)."""
    ddir = os.environ.get("HETU_DATA_DIR", "datasets")
    path = os.path.join(ddir, "criteo")
    if os.path.exists(os.path.join(path, "train_dense_feats.npy")):
        dense = np.load(os.path.join(path, "train_dense_feats.npy"))
        sparse = np.load(os.path.join(path, "train_sparse_feats.npy"))
        labels = np.load(os.path.join(path, "train_labels.npy"))
        return (dense.astype(np.float32), sparse.astype(np.int64),
                labels.reshape(-1, 1).astype(np.float32))
    rng = np.random.RandomState(0)
    n = args.nsamples
    dense = rng.randn(n, 13).astype(np.float32)
    sparse = (rng.zipf(1.3, size=(n, 26)) - 1) % args.dim
    labels = ((dense[:, 0] + (sparse[:, 0] % 2)) > 0.9).astype(
        np.float32).reshape(-1, 1)
    return dense, sparse, labels


def ensure_local_ps():
    """Single-process convenience: when no heturun launcher provided a
    server fleet (HETU_PS_PORTS unset), run one server in-process."""
    if os.environ.get("HETU_PS_PORTS"):
        return
    from hetu_tpu.ps import server as ps_server
    from hetu_tpu.ps import client as ps_client
    port = ps_server.pick_free_port()
    os.environ["HETU_PS_PORTS"] = str(port)
    os.environ["HETU_PS_HOSTS"] = "127.0.0.1"
    ps_server.ensure_server(port=port, nworkers=1)
    ps_client.set_default_client(ps_client.PSClient(rank=0, nworkers=1))


def worker(args):
    if args.comm_mode in ("PS", "Hybrid"):
        ensure_local_ps()
    model = getattr(ctr_models, args.model)
    dense, sparse, labels = load_criteo(args)
    n_train = int(len(labels) * 0.9)

    batch = args.batch_size
    dense_input = ht.dataloader_op([
        ht.Dataloader(dense[:n_train], batch, "train"),
        ht.Dataloader(dense[n_train:], batch, "validate")])
    sparse_input = ht.dataloader_op([
        ht.Dataloader(sparse[:n_train], batch, "train"),
        ht.Dataloader(sparse[n_train:], batch, "validate")])
    y_ = ht.dataloader_op([
        ht.Dataloader(labels[:n_train], batch, "train"),
        ht.Dataloader(labels[n_train:], batch, "validate")])

    if args.model == "wdl_adult":
        loss, y, y_, train_op = model(dense_input, sparse_input, y_)
    else:
        loss, y, y_, train_op = model(
            dense_input, sparse_input, y_, feature_dimension=args.dim,
            learning_rate=args.learning_rate)

    eval_nodes = {"train": [loss, y, y_, train_op]}
    if args.val:
        eval_nodes["validate"] = [loss, y, y_]
    kwargs = {}
    if args.comm_mode in ("PS", "Hybrid"):
        kwargs = dict(cstable_policy=args.cache, bsp=args.bsp,
                      cache_bound=args.bound)
    executor = ht.Executor(eval_nodes, comm_mode=args.comm_mode, **kwargs)

    results = {}
    for ep in range(args.nepoch):
        ep_st = time.perf_counter()
        train_loss, train_acc, train_auc = [], [], []
        batches = executor.get_batch_num("train")
        if args.all:
            # metrics loop: one host sync per step (reference behavior)
            for _ in range(batches):
                loss_val, predict_y, y_val, _ = executor.run(
                    "train", convert_to_numpy_ret_vals=True)
                acc = np.equal(y_val, predict_y > 0.5).astype(np.float32)
                train_loss.append(float(np.mean(loss_val)))
                train_acc.append(float(np.mean(acc)))
                if len(np.unique(y_val)) > 1:
                    train_auc.append(auc(predict_y, y_val))
        else:
            # throughput loop: lax.scan blocks, one sync per epoch
            kblock = min(args.block_steps, batches)
            done = 0
            while done < batches:
                k = min(kblock, batches - done)
                out = executor.run_batches([{}] * k, name="train")
                done += k
            out[-1][0].asnumpy()
        ep_time = time.perf_counter() - ep_st
        sps = batches * batch / ep_time
        msg = f"epoch {ep}"
        if args.all and train_loss:
            msg += (f": loss {np.mean(train_loss):.4f} "
                    f"acc {np.mean(train_acc):.4f}")
            if train_auc:
                msg += f" auc {np.mean(train_auc):.4f}"
        if args.timing:
            msg += f" | {ep_time:.2f}s/epoch, {sps:.0f} samples/sec"
        print(msg, flush=True)
        results.update(epoch_time=ep_time, samples_per_sec=sps)
        if args.all and train_loss:
            results.update(loss=float(np.mean(train_loss)))
        if args.val:
            val_loss, val_acc, val_auc = [], [], []
            for _ in range(executor.get_batch_num("validate")):
                loss_val, pred, y_val = executor.run(
                    "validate", convert_to_numpy_ret_vals=True)
                val_loss.append(float(np.mean(loss_val)))
                val_acc.append(float(np.mean(
                    np.equal(y_val, pred > 0.5))))
                if len(np.unique(y_val)) > 1:
                    val_auc.append(auc(pred, y_val))
            msg = (f"validate: loss {np.mean(val_loss):.4f} "
                   f"acc {np.mean(val_acc):.4f}")
            if val_auc:
                msg += f" auc {np.mean(val_auc):.4f}"
            print(msg, flush=True)
            results.update(val_loss=float(np.mean(val_loss)))
    executor.close()
    return results


def parse_args(argv=None):
    parser = argparse.ArgumentParser()
    parser.add_argument("--model", default="wdl_criteo",
                        help=f"one of {MODELS}")
    parser.add_argument("--comm-mode", default=None,
                        help="None / PS / Hybrid / AllReduce")
    parser.add_argument("--batch-size", type=int, default=128)
    parser.add_argument("--learning-rate", type=float, default=0.01)
    parser.add_argument("--nepoch", type=int, default=3)
    parser.add_argument("--dim", type=int, default=1_000_000,
                        help="embedding rows (synthetic data)")
    parser.add_argument("--nsamples", type=int, default=128 * 600,
                        help="synthetic dataset size")
    parser.add_argument("--val", action="store_true")
    parser.add_argument("--all", action="store_true",
                        help="compute loss/acc/AUC each step")
    parser.add_argument("--timing", action="store_true")
    parser.add_argument("--bsp", action="store_true",
                        help="synchronous PS training (barrier per step)")
    parser.add_argument("--cache", default="Device",
                        help="Device (HBM cache) / LRU / LFU / LFUOpt")
    parser.add_argument("--bound", type=int, default=100,
                        help="staleness bound (drain cadence)")
    parser.add_argument("--block-steps", type=int, default=20,
                        help="steps per compiled lax.scan block in the "
                             "throughput loop")
    args = parser.parse_args(argv)
    assert args.model in MODELS, f"model {args.model} not supported"
    return args


if __name__ == "__main__":
    worker(parse_args())
