"""GPT causal-LM trainer — the decoder-only counterpart of the BERT
example (no reference equivalent: the reference's NLP zoo stops at
encoders; this family exists for the causal long-context path).

Data: a token-id corpus from ``HETU_DATA_DIR/lm/corpus.npy`` when
present ([N] int array, chunked into sequences); otherwise a synthetic
Markov corpus (each token is a deterministic function of the previous
two) that a working decoder drives far below the uniform-loss floor —
the hermetic stand-in for text.

    python examples/nlp/train_hetu_gpt.py --timing
    python examples/nlp/train_hetu_gpt.py --sequence-parallel ring
"""
import argparse
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import hetu_tpu as ht                                   # noqa: E402
from hetu_tpu.models import GPTConfig, GPTLMHeadModel   # noqa: E402


def load_corpus(args):
    path = os.path.join(os.environ.get("HETU_DATA_DIR", "datasets"),
                        "lm", "corpus.npy")
    if os.path.exists(path):
        flat = np.load(path).astype(np.int64)
        assert flat.max() < args.vocab_size and flat.min() >= 0, (
            f"corpus ids span [{flat.min()}, {flat.max()}] but "
            f"--vocab-size is {args.vocab_size}; the embedding gather "
            "would silently clamp out-of-range ids")
    else:
        rng = np.random.RandomState(0)
        n = args.nsamples * args.seq_len
        flat = np.empty(n, np.int64)
        flat[0], flat[1] = rng.randint(0, args.vocab_size, 2)
        # order-2 Markov rule: learnable, not memorizable marginals
        for i in range(2, n):
            flat[i] = (3 * flat[i - 1] + 5 * flat[i - 2] + 7) \
                % args.vocab_size
    nseq = len(flat) // args.seq_len
    return flat[:nseq * args.seq_len].reshape(nseq, args.seq_len)


def main(args):
    data = load_corpus(args)
    cfg = GPTConfig(
        vocab_size=args.vocab_size, hidden_size=args.hidden_size,
        num_hidden_layers=args.num_layers,
        num_attention_heads=args.num_heads,
        max_position_embeddings=args.seq_len,
        hidden_dropout_prob=args.dropout,
        use_flash_attention=True,
        sequence_parallel=args.sequence_parallel)
    model = GPTLMHeadModel(cfg)
    ids = ht.Variable("input_ids", trainable=False)
    labels = ht.Variable("labels", trainable=False)
    _, loss = model(ids, labels)
    lm_loss = ht.reduce_mean_op(loss, [0, 1])
    opt = ht.optim.AdamOptimizer(learning_rate=args.learning_rate)
    train_op = opt.minimize(lm_loss)
    executor = ht.Executor([lm_loss, train_op])

    nbatch = max(1, len(data) // args.batch_size)
    results = {}
    for epoch in range(args.nepoch):
        t0 = time.time()
        losses = []
        for b in range(nbatch):
            x = data[b * args.batch_size:(b + 1) * args.batch_size]
            # shift by one; the final position has no next token — pad
            # with the sparse-CE op's ignored_index so it trains nothing
            y = np.concatenate(
                [x[:, 1:], np.full((len(x), 1), -1, np.int64)], axis=1)
            out = executor.run(feed_dict={ids: x, labels: y},
                               convert_to_numpy_ret_vals=True)
            losses.append(float(out[0]))
        msg = f"epoch {epoch}: loss {np.mean(losses):.4f}"
        if args.timing:
            msg += f", {time.time() - t0:.2f}s"
        print(msg, flush=True)
        results["loss"] = float(np.mean(losses))
    return results


def parse_args(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--vocab-size", type=int, default=256)
    p.add_argument("--hidden-size", type=int, default=128)
    p.add_argument("--num-layers", type=int, default=2)
    p.add_argument("--num-heads", type=int, default=8)
    p.add_argument("--seq-len", type=int, default=128)
    p.add_argument("--batch-size", type=int, default=16)
    p.add_argument("--nsamples", type=int, default=256)
    p.add_argument("--nepoch", type=int, default=2)
    p.add_argument("--learning-rate", type=float, default=1e-3)
    p.add_argument("--dropout", type=float, default=0.0)
    p.add_argument("--timing", action="store_true")
    p.add_argument("--sequence-parallel", default=None,
                   choices=[None, "ring", "ulysses"])
    return p.parse_args(argv)


if __name__ == "__main__":
    main(parse_args())
