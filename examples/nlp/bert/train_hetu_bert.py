"""BERT pre-training entrypoint (reference parity:
examples/nlp/bert/train_hetu_bert.py — MLM+NSP joint loss, Adam, per-step
loss/time printing). TPU-native: bf16 mixed precision and the Pallas
flash-attention kernel are on by default; data falls back to synthetic
token streams when no corpus is prepared (the reference requires a
preprocessed wikicorpus).

    python examples/nlp/bert/train_hetu_bert.py --timing --num-steps 50
"""
import argparse
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "..",
                                ".."))

import hetu_tpu as ht                       # noqa: E402
import hetu_tpu.models as M                 # noqa: E402


def synthetic_batch(rng, batch, seq_len, vocab):
    input_ids = rng.randint(0, vocab, (batch, seq_len))
    token_type_ids = np.zeros((batch, seq_len), np.int64)
    token_type_ids[:, seq_len // 2:] = 1
    attention_mask = np.ones((batch, seq_len), np.float32)
    masked_lm_labels = np.where(rng.rand(batch, seq_len) < 0.15,
                                input_ids, -1)
    next_sentence_label = rng.randint(0, 2, (batch,))
    return (input_ids, token_type_ids, attention_mask, masked_lm_labels,
            next_sentence_label)


def run(args):
    import jax.numpy as jnp

    cfg = M.BertConfig(
        vocab_size=args.vocab_size, hidden_size=args.hidden_size,
        num_hidden_layers=args.num_layers,
        num_attention_heads=args.num_heads,
        intermediate_size=args.hidden_size * 4,
        max_position_embeddings=args.seq_length,
        use_flash_attention=not args.no_flash)
    model = M.BertForPreTraining(cfg)

    input_ids = ht.Variable("input_ids", trainable=False)
    token_type_ids = ht.Variable("token_type_ids", trainable=False)
    attention_mask = ht.Variable("attention_mask", trainable=False)
    mlm_labels = ht.Variable("masked_lm_labels", trainable=False)
    nsp_label = ht.Variable("next_sentence_label", trainable=False)
    _, _, mlm_loss, nsp_loss = model(input_ids, token_type_ids,
                                     attention_mask, mlm_labels, nsp_label)
    loss = ht.reduce_mean_op(mlm_loss, [0, 1]) + \
        ht.reduce_mean_op(nsp_loss, [0])
    opt = ht.optim.AdamOptimizer(learning_rate=args.lr)
    train_op = opt.minimize(loss)

    executor = ht.Executor(
        [loss, train_op], comm_mode=args.comm_mode,
        dtype=None if args.fp32 else jnp.bfloat16)

    rng = np.random.RandomState(0)
    feed_nodes = (input_ids, token_type_ids, attention_mask, mlm_labels,
                  nsp_label)
    results = {}
    t0 = time.perf_counter()
    window_tokens = 0
    for step in range(args.num_steps):
        values = synthetic_batch(rng, args.batch_size, args.seq_length,
                                 args.vocab_size)
        out = executor.run(
            feed_dict=dict(zip(feed_nodes, values)))
        window_tokens += args.batch_size * args.seq_length
        if (step + 1) % args.log_every == 0:
            loss_val = float(np.asarray(out[0].asnumpy()))
            dt = time.perf_counter() - t0
            tps = window_tokens / dt
            msg = f"step {step + 1}: loss {loss_val:.4f}"
            if args.timing:
                msg += f", {tps:.0f} tokens/sec"
            print(msg, flush=True)
            results.update(loss=loss_val, tokens_per_sec=tps)
            t0 = time.perf_counter()
            window_tokens = 0
    return results


def parse_args(argv=None):
    parser = argparse.ArgumentParser()
    parser.add_argument("--batch-size", type=int, default=64)
    parser.add_argument("--seq-length", type=int, default=128)
    parser.add_argument("--vocab-size", type=int, default=30522)
    parser.add_argument("--hidden-size", type=int, default=768)
    parser.add_argument("--num-layers", type=int, default=12)
    parser.add_argument("--num-heads", type=int, default=12)
    parser.add_argument("--lr", type=float, default=1e-4)
    parser.add_argument("--num-steps", type=int, default=100)
    parser.add_argument("--log-every", type=int, default=10)
    parser.add_argument("--timing", action="store_true")
    parser.add_argument("--fp32", action="store_true",
                        help="disable bf16 mixed precision")
    parser.add_argument("--no-flash", action="store_true",
                        help="disable the Pallas flash-attention kernel")
    parser.add_argument("--comm-mode", default=None)
    return parser.parse_args(argv)


if __name__ == "__main__":
    run(parse_args())
