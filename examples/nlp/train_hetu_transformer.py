"""Seq2seq Transformer trainer (reference parity:
examples/nlp/train_hetu_transformer.py — MT-style training over
(source, shifted-target) pairs with label smoothing).

Data: token-id pairs from ``HETU_DATA_DIR/mt/{src,tgt}.npy`` when
present ([N, T] int arrays, 0 = pad, 1 = BOS); otherwise a synthetic
sequence-transduction task (copy with reversal) that a working model
drives to near-zero loss — the hermetic stand-in for translation.

    python examples/nlp/train_hetu_transformer.py --timing
"""
import argparse
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import hetu_tpu as ht                                   # noqa: E402
from hetu_tpu.models import (Transformer,               # noqa: E402
                             TransformerConfig)


def load_pairs(args):
    ddir = os.environ.get("HETU_DATA_DIR", "datasets")
    sp, tp = (os.path.join(ddir, "mt", n) for n in ("src.npy", "tgt.npy"))
    if os.path.exists(sp) and os.path.exists(tp):
        return np.load(sp), np.load(tp), None
    rng = np.random.RandomState(0)
    n = args.nsamples
    src = rng.randint(2, args.vocab_size, (n, args.maxlen))
    tgt = src[:, ::-1].copy()      # transduction rule: reverse the source
    return src, tgt, rng


def main(args):
    src_arr, tgt_arr, _ = load_pairs(args)
    n, t1 = src_arr.shape
    t2 = tgt_arr.shape[1] + 1      # BOS-shifted decoder input
    cfg = TransformerConfig(
        vocab_size=args.vocab_size, d_model=args.d_model,
        d_ff=args.d_ff, num_blocks=args.num_blocks,
        num_heads=args.num_heads, maxlen1=t1, maxlen2=t2,
        batch_size=args.batch_size, dropout_rate=args.dropout,
        label_smoothing=args.label_smoothing)
    model = Transformer(cfg)

    src = ht.Variable("src_ids", trainable=False)
    dec = ht.Variable("dec_ids", trainable=False)
    tgt = ht.Variable("tgt_ids", trainable=False)
    loss = model(src, dec, tgt)
    train_op = ht.optim.AdamOptimizer(args.learning_rate).minimize(loss)
    exe = ht.Executor([loss, train_op], comm_mode=args.comm_mode)

    bos = np.ones((args.batch_size, 1), np.int64)
    steps_per_epoch = n // args.batch_size
    results = {}
    for ep in range(args.nepoch):
        ep_st = time.time()
        ep_loss = []
        for i in range(steps_per_epoch):
            lo = i * args.batch_size
            s = src_arr[lo:lo + args.batch_size]
            t = tgt_arr[lo:lo + args.batch_size]
            d = np.concatenate([bos, t[:, :-1]], 1)
            out = exe.run(feed_dict={src: s, dec: d, tgt: t})
            ep_loss.append(float(out[0].asnumpy()))
        dt = time.time() - ep_st
        msg = f"epoch {ep}: loss {np.mean(ep_loss):.4f}"
        if args.timing:
            tps = steps_per_epoch * args.batch_size * (t2 - 1) / dt
            msg += f", {dt:.2f}s ({tps:.0f} target tokens/sec)"
            results["tokens_per_sec"] = tps
        print(msg, flush=True)
        results["loss"] = float(np.mean(ep_loss))
    exe.close()
    return results


def parse_args(argv=None):
    parser = argparse.ArgumentParser()
    parser.add_argument("--vocab-size", type=int, default=2000)
    parser.add_argument("--d-model", type=int, default=256)
    parser.add_argument("--d-ff", type=int, default=1024)
    parser.add_argument("--num-blocks", type=int, default=4)
    parser.add_argument("--num-heads", type=int, default=8)
    parser.add_argument("--maxlen", type=int, default=24)
    parser.add_argument("--batch-size", type=int, default=64)
    parser.add_argument("--nsamples", type=int, default=64 * 200)
    parser.add_argument("--nepoch", type=int, default=5)
    parser.add_argument("--learning-rate", type=float, default=1e-3)
    parser.add_argument("--dropout", type=float, default=0.1)
    parser.add_argument("--label-smoothing", type=float, default=0.1)
    parser.add_argument("--timing", action="store_true")
    parser.add_argument("--comm-mode", default=None)
    return parser.parse_args(argv)


if __name__ == "__main__":
    main(parse_args())
