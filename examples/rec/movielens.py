"""MovieLens data for NCF (reference parity: examples/rec/movielens.py).

Produces the same artifacts as the reference preprocessor — a train set
of ``(user_input, item_input, labels)`` with ``num_negatives`` sampled
negatives per positive, and a leave-latest-out test matrix ``[num_users,
100]`` whose column 0 is the held-out positive item and columns 1..99
are sampled negatives (movielens.py:66-104).

This environment has no network egress, so instead of downloading the
zip we (in order): load a previously preprocessed ``train.npz`` +
``test.npy``; preprocess a ``ratings.csv``/``ratings.dat`` already on
disk; else synthesize an implicit-feedback dataset with planted
block structure so HR@10 is a meaningful signal (a trained model must
beat the 10/100 random baseline by a wide margin).
"""
from __future__ import annotations

import os

import numpy as np

CARDINALITIES = {
    "ml-1m": (6040, 3706),
    "ml-20m": (138493, 26744),
    "ml-25m": (162541, 59047),
}


def _preprocess_ratings(path, num_users=None, num_items=None,
                        num_negatives=4, seed=0):
    """ratings.csv/.dat -> (train dict, test matrix), the reference's
    leave-latest-out protocol (movielens.py:42-104). Cardinalities are
    inferred from the file when not given (custom dataset dirs)."""
    rng = np.random.RandomState(seed)
    sep = "::" if path.endswith(".dat") else ","
    item_map, next_item = {}, 0
    seen = set()
    latest = {}
    max_user = -1
    with open(path, "r") as fr:
        first = fr.readline()
        if not first or first[0].isdigit():     # .dat has no header
            fr.seek(0)
        for line in fr:
            e = line.strip().split(sep)
            user, item, rating, ts = (int(e[0]) - 1, int(e[1]),
                                      float(e[2]), int(e[-1]))
            if rating <= 0:
                continue
            if item not in item_map:
                item_map[item] = next_item
                next_item += 1
            reitem = item_map[item]
            seen.add((user, reitem))
            max_user = max(max_user, user)
            if user not in latest or latest[user][0] < ts:
                latest[user] = (ts, reitem)
    if num_users is None:
        num_users = max_user + 1
    if num_items is None:
        num_items = next_item

    test = np.zeros((num_users, 100), dtype=np.int32)
    for u in range(num_users):
        test[u, 0] = latest.get(u, (0, 0))[1]
        for k in range(1, 100):
            j = rng.randint(num_items)
            while (u, j) in seen:
                j = rng.randint(num_items)
            test[u, k] = j

    pos = [(u, i) for (u, i) in seen if latest.get(u, (0, -1))[1] != i]
    n = (1 + num_negatives) * len(pos)
    user_input = np.empty(n, dtype=np.int32)
    item_input = np.empty(n, dtype=np.int32)
    labels = np.empty(n, dtype=np.int32)
    idx = 0
    for (u, i) in pos:
        user_input[idx], item_input[idx], labels[idx] = u, i, 1
        idx += 1
        for _ in range(num_negatives):
            k = rng.randint(num_items)
            while (u, k) in seen:
                k = rng.randint(num_items)
            user_input[idx], item_input[idx], labels[idx] = u, k, 0
            idx += 1
    train = {"user_input": user_input, "item_input": item_input,
             "labels": labels}
    return train, test, num_users, num_items


def make_synthetic(num_users=800, num_items=600, num_negatives=4,
                   interactions_per_user=40, nclusters=6, seed=0):
    """Implicit feedback with planted co-clusters: user u's positives
    come from item cluster u%nclusters (plus noise), so embeddings can
    learn the structure and HR@10 climbs well above the 0.1 random
    floor."""
    rng = np.random.RandomState(seed)
    item_cluster = rng.randint(0, nclusters, num_items)
    cluster_items = [np.nonzero(item_cluster == c)[0]
                     for c in range(nclusters)]
    seen = set()
    users, items = [], []
    held = {}
    for u in range(num_users):
        mine = cluster_items[u % nclusters]
        k = min(interactions_per_user, len(mine))
        picks = rng.choice(mine, size=k, replace=False)
        # hold out an IN-CLUSTER positive: the model can only rank it
        # from the cluster structure it learned off the other positives
        held[u] = int(picks[0])
        # a little cross-cluster noise keeps it from being separable
        noise = rng.randint(0, num_items, max(1, k // 8))
        for i in np.concatenate([picks, noise]):
            if (u, int(i)) not in seen:
                seen.add((u, int(i)))
                users.append(u)
                items.append(int(i))

    test = np.zeros((num_users, 100), dtype=np.int32)
    for u in range(num_users):
        test[u, 0] = held[u]
        negs = rng.randint(0, num_items, 99)
        for k in range(99):
            while (u, int(negs[k])) in seen:
                negs[k] = rng.randint(num_items)
        test[u, 1:] = negs

    user_input, item_input, labels = [], [], []
    for u, i in zip(users, items):
        if i == held[u]:
            continue
        user_input.append(u)
        item_input.append(i)
        labels.append(1)
        for _ in range(num_negatives):
            k = rng.randint(num_items)
            while (u, k) in seen:
                k = rng.randint(num_items)
            user_input.append(u)
            item_input.append(k)
            labels.append(0)
    order = rng.permutation(len(labels))
    train = {"user_input": np.asarray(user_input, np.int32)[order],
             "item_input": np.asarray(item_input, np.int32)[order],
             "labels": np.asarray(labels, np.int32)[order]}
    return train, test, num_users, num_items


def getdata(dataset="ml-25m", data_dir=None):
    """(train dict, test matrix, num_users, num_items)."""
    data_dir = data_dir or os.environ.get("HETU_DATA_DIR", "datasets")
    sub = os.path.join(data_dir, dataset)
    train_p = os.path.join(sub, "train.npz")
    test_p = os.path.join(sub, "test.npy")
    num_users, num_items = CARDINALITIES.get(dataset, (None, None))
    if os.path.exists(train_p) and os.path.exists(test_p):
        return (dict(np.load(train_p)), np.load(test_p),
                num_users, num_items)
    for name in ("ratings.csv", "ratings.dat"):
        p = os.path.join(sub, name)
        if os.path.exists(p):
            train, test, num_users, num_items = _preprocess_ratings(
                p, num_users, num_items)
            os.makedirs(sub, exist_ok=True)
            np.savez(train_p, **train)
            np.save(test_p, test)
            return train, test, num_users, num_items
    print(f"[movielens] {sub} not found - synthesizing implicit-feedback "
          "data (set HETU_DATA_DIR to use the real dataset)", flush=True)
    return make_synthetic()
