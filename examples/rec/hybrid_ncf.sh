#!/bin/bash
# Hybrid-mode NCF: embeddings via PS device cache, dense tower AllReduce
# (reference parity: examples/rec/hybrid_ncf.sh)
cd "$(dirname "$0")"
../../bin/heturun -c settings/local_ps.yml \
    python run_hetu.py --comm Hybrid --cache Device --timing "$@"
