"""NCF training entrypoint (reference parity: examples/rec/run_hetu.py —
same CLI surface: --val HR@10/NDCG@10 retrieval eval, --comm for
None/PS/Hybrid, --bsp/--cache/--bound PS knobs, --all for the full
dataset).  The embedding tables are the PS sparse parameters; Hybrid
runs them through the HBM device cache while the MLP tower rides
AllReduce — the reference's canonical Hybrid workload (hybrid_ncf.sh).

    python examples/rec/run_hetu.py --val --timing
    heturun -c settings/local_ps.yml python examples/rec/run_hetu.py \
        --comm PS --timing
"""
import argparse
import math
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import hetu_tpu as ht                               # noqa: E402
from hetu_tpu.models.ncf import neural_mf           # noqa: E402
from movielens import getdata                       # noqa: E402


def hit_ratio(ranklist, gt_item):
    return int(gt_item in ranklist)


def ndcg(ranklist, gt_item):
    for i, item in enumerate(ranklist):
        if item == gt_item:
            return math.log(2) / math.log(i + 2)
    return 0.0


def ensure_local_ps():
    if os.environ.get("HETU_PS_PORTS"):
        return
    from hetu_tpu.ps import server as ps_server
    from hetu_tpu.ps import client as ps_client
    port = ps_server.pick_free_port()
    os.environ["HETU_PS_PORTS"] = str(port)
    os.environ["HETU_PS_HOSTS"] = "127.0.0.1"
    ps_server.ensure_server(port=port, nworkers=1)
    ps_client.set_default_client(ps_client.PSClient(rank=0, nworkers=1))


def worker(args):
    if args.comm in ("PS", "Hybrid"):
        ensure_local_ps()

    train, test, num_users, num_items = getdata(args.dataset)
    train_users = train["user_input"]
    train_items = train["item_input"]
    train_labels = train["labels"].astype(np.float32).reshape(-1, 1)
    if not args.all:   # reference default: first 1,024,000 samples
        cap = min(len(train_labels), 1_024_000)
        train_users, train_items, train_labels = (
            train_users[:cap], train_items[:cap], train_labels[:cap])
    if num_users is None:
        # test rows are indexed by user id; test cells are item ids
        num_users = int(max(train_users.max() + 1, test.shape[0]))
        num_items = int(max(train_items.max(), test.max()) + 1)
    test_user_input = np.repeat(
        np.arange(test.shape[0], dtype=np.int32), 100)
    test_item_input = test.reshape(-1).astype(np.int32)

    batch = args.batch_size
    topk = 10
    # score eval_users users' 100 candidates per dispatch: the reference
    # runs one user per step (run_hetu.py:44-61), which on a remote TPU
    # tunnel serializes num_users round trips — batching users changes
    # nothing numerically (the model is pointwise over [B] ids)
    eval_batch = 100 * args.eval_users
    # drop_last=False: every user gets scored (the tail batch stays a
    # multiple of 100 because the total and eval_batch both are)
    user_input = ht.dataloader_op([
        ht.Dataloader(train_users, batch, "train"),
        ht.Dataloader(test_user_input, eval_batch, "validate",
                      drop_last=False)])
    item_input = ht.dataloader_op([
        ht.Dataloader(train_items, batch, "train"),
        ht.Dataloader(test_item_input, eval_batch, "validate",
                      drop_last=False)])
    y_ = ht.dataloader_op([
        ht.Dataloader(train_labels, batch, "train")])

    embed_ctx = ht.cpu(0) if args.comm in ("PS", "Hybrid") else None
    loss, y, train_op = neural_mf(
        user_input, item_input, y_, num_users, num_items,
        learning_rate=args.learning_rate, embed_ctx=embed_ctx)

    kwargs = {}
    if args.comm in ("PS", "Hybrid"):
        kwargs = dict(cstable_policy=args.cache, bsp=args.bsp,
                      cache_bound=args.bound)
    executor = ht.Executor({"train": [loss, train_op], "validate": [y]},
                           comm_mode=args.comm, **kwargs)

    def validate():
        hits, ndcgs = [], []
        nbatches = executor.get_batch_num("validate")
        done = 0
        for _ in range(nbatches):
            pred = executor.run("validate",
                                convert_to_numpy_ret_vals=True)[0]
            nu = len(pred) // 100
            scores = pred.reshape(nu, 100)
            items = test_item_input[done:done + nu * 100].reshape(nu, 100)
            done += nu * 100
            # rank each user's 100 candidates; col 0 is the held-out item
            order = np.argsort(-scores, axis=1)[:, :topk]
            for u in range(nu):
                ranklist = items[u, order[u]].tolist()
                hits.append(hit_ratio(ranklist, int(items[u, 0])))
                ndcgs.append(ndcg(ranklist, int(items[u, 0])))
        return float(np.mean(hits)), float(np.mean(ndcgs))

    results = {}
    start = time.time()
    for ep in range(args.nepoch):
        ep_st = time.time()
        train_loss = []
        nbatch = executor.get_batch_num("train")
        if args.metrics_every_step:
            for _ in range(nbatch):
                loss_val = executor.run(
                    "train", convert_to_numpy_ret_vals=True)
                train_loss.append(float(loss_val[0]))
        else:
            kblock = min(args.block_steps, nbatch)
            done = 0
            while done < nbatch:
                k = min(kblock, nbatch - done)
                out = executor.run_batches([{}] * k, name="train")
                done += k
                # first asnumpy syncs the block; the rest read slices of
                # the already-materialized stacked output
                train_loss.extend(
                    float(np.mean(o[0].asnumpy())) for o in out)
        ep_time = time.time() - ep_st
        msg = f"epoch {ep}: train_loss {np.mean(train_loss):.4f}"
        if args.val:
            hr, nd = validate()
            msg += f", HR@{topk} {hr:.4f}, NDCG@{topk} {nd:.4f}"
            results.update(hr=hr, ndcg=nd)
        if args.timing:
            sps = nbatch * batch / ep_time
            msg += f", train_time {ep_time:.2f}s ({sps:.0f} samples/sec)"
            results.update(samples_per_sec=sps)
        print(msg, flush=True)
        results.update(loss=float(np.mean(train_loss)))
    print(f"all time: {time.time() - start:.2f}s", flush=True)
    executor.close()
    return results


def parse_args(argv=None):
    parser = argparse.ArgumentParser()
    parser.add_argument("--val", action="store_true",
                        help="HR@10/NDCG@10 retrieval eval per epoch")
    parser.add_argument("--all", action="store_true",
                        help="use the full train set (default 1,024,000)")
    parser.add_argument("--comm", default=None,
                        help="None / PS / Hybrid")
    parser.add_argument("--bsp", action="store_true")
    parser.add_argument("--cache", default="Device",
                        help="Device (HBM cache) / LRU / LFU / LFUOpt")
    parser.add_argument("--bound", type=int, default=100)
    parser.add_argument("--dataset", default="ml-25m")
    parser.add_argument("--batch-size", type=int, default=1024)
    parser.add_argument("--learning-rate", type=float, default=0.01)
    parser.add_argument("--nepoch", type=int, default=7)
    parser.add_argument("--timing", action="store_true")
    parser.add_argument("--eval-users", type=int, default=50,
                        help="users scored per validation dispatch")
    parser.add_argument("--metrics-every-step", action="store_true",
                        help="host-sync the loss every step (reference "
                             "loop); default uses compiled scan blocks")
    parser.add_argument("--block-steps", type=int, default=50)
    return parser.parse_args(argv)


if __name__ == "__main__":
    worker(parse_args())
