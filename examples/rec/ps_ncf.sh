#!/bin/bash
# PS-mode NCF (reference parity: examples/rec/ps_ncf.sh)
cd "$(dirname "$0")"
../../bin/heturun -c settings/local_ps.yml \
    python run_hetu.py --comm PS --cache Device --timing "$@"
