"""CNN/MLP training entrypoint (reference parity:
examples/cnn/main.py — same CLI surface, same models, same --timing
output shape), TPU-native execution via the XLA-compiled Executor.

    python examples/cnn/main.py --model mlp --dataset CIFAR10 --timing
    heturun -w 8 python examples/cnn/main.py --model resnet18 \
        --dataset CIFAR10 --comm-mode AllReduce --timing
"""
import argparse
import logging
import os
import sys
from time import time

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import hetu_tpu as ht              # noqa: E402
from hetu_tpu import models        # noqa: E402

logging.basicConfig(level=logging.INFO,
                    format="%(asctime)s - %(name)s - %(message)s")
logger = logging.getLogger("hetu.examples.cnn")

MODELS = ["alexnet", "cnn_3_layers", "digits_cnn", "lenet", "logreg",
          "lstm", "mlp", "resnet18", "resnet34", "rnn", "vgg16", "vgg19"]
CONV_MODELS = {"alexnet", "cnn_3_layers", "digits_cnn", "lenet",
               "resnet18", "resnet34", "vgg16", "vgg19"}


def build_optimizer(name, lr):
    if name == "sgd":
        return ht.optim.SGDOptimizer(learning_rate=lr)
    if name == "momentum":
        return ht.optim.MomentumOptimizer(learning_rate=lr)
    if name == "nesterov":
        return ht.optim.MomentumOptimizer(learning_rate=lr, nesterov=True)
    if name == "adagrad":
        return ht.optim.AdaGradOptimizer(learning_rate=lr)
    return ht.optim.AdamOptimizer(learning_rate=lr)


def load_dataset(name, model):
    """(train_x, train_y, val_x, val_y); images are NCHW for conv nets,
    flat for dense nets (reference main.py's per-model reshapes)."""
    conv = model in CONV_MODELS
    assert model != "digits_cnn" or name == "DIGITS", \
        "digits_cnn is the 8x8-geometry conv net: use --dataset DIGITS"
    if name == "MNIST":
        (tx, ty), (vx, vy), _ = ht.data.mnist()
        if conv:
            tx = tx.reshape(-1, 1, 28, 28)
            vx = vx.reshape(-1, 1, 28, 28)
    elif name == "DIGITS":
        # the checked-in real shard (hetu_tpu/data.py digits()); conv
        # path is digits_cnn (8x8 geometry — the 28x28 stacks don't fit)
        assert not conv or model == "digits_cnn", \
            "DIGITS supports logreg/mlp/digits_cnn (8x8 images)"
        (tx, ty), (vx, vy), _ = ht.data.digits()
    elif name in ("CIFAR10", "CIFAR100"):
        loader = ht.data.cifar10 if name == "CIFAR10" else ht.data.cifar100
        tx, ty, vx, vy = loader()
        if not conv:
            tx = tx.reshape(tx.shape[0], -1)
            vx = vx.reshape(vx.shape[0], -1)
    else:
        raise ValueError(f"dataset {name} not supported")
    if model in ("rnn", "lstm"):
        tx = tx.reshape(-1, 28, 28)
        vx = vx.reshape(-1, 28, 28)
    return tx, ty, vx, vy


def run(args):
    model = getattr(models, args.model)
    tx, ty, vx, vy = load_dataset(args.dataset, args.model)

    x = ht.dataloader_op([ht.Dataloader(tx, args.batch_size, "train"),
                          ht.Dataloader(vx, args.batch_size, "validate")])
    y_ = ht.dataloader_op([ht.Dataloader(ty, args.batch_size, "train"),
                           ht.Dataloader(vy, args.batch_size, "validate")])
    kwargs = {}
    if args.model in ("logreg", "mlp"):
        # dense models take the flattened feature width of whatever
        # dataset was loaded (784 MNIST, 64 DIGITS, 3072 CIFAR)
        kwargs["input_dim"] = int(tx.shape[1])
    loss, y = model(x, y_, **kwargs)
    opt = build_optimizer(args.opt, args.learning_rate)
    train_op = opt.minimize(loss)

    eval_nodes = {"train": [loss, y, y_, train_op]}
    if args.validate:
        eval_nodes["validate"] = [loss, y, y_]
    executor = ht.Executor(eval_nodes, comm_mode=args.comm_mode)

    results = {}
    for epoch in range(args.num_epochs):
        ep_st = time()
        train_loss, train_acc = [], []
        for _ in range(executor.get_batch_num("train")):
            loss_val, predict_y, y_val, _ = executor.run(
                "train", convert_to_numpy_ret_vals=True)
            train_loss.append(loss_val[0] if np.ndim(loss_val) else loss_val)
            train_acc.append(np.mean(np.argmax(y_val, 1)
                                     == np.argmax(predict_y, 1)))
        ep_en = time()
        msg = (f"Epoch {epoch}: train loss {np.mean(train_loss):.4f}, "
               f"train acc {np.mean(train_acc):.4f}")
        if args.timing:
            msg += f", epoch time {ep_en - ep_st:.3f}s"
            results["epoch_time"] = ep_en - ep_st
        if args.validate:
            val_loss, val_acc = [], []
            for _ in range(executor.get_batch_num("validate")):
                loss_val, val_y_pred, y_val = executor.run(
                    "validate", convert_to_numpy_ret_vals=True)
                val_loss.append(loss_val[0]
                                if np.ndim(loss_val) else loss_val)
                val_acc.append(np.mean(np.argmax(y_val, 1)
                                       == np.argmax(val_y_pred, 1)))
            msg += (f", val loss {np.mean(val_loss):.4f}, "
                    f"val acc {np.mean(val_acc):.4f}")
            results["val_acc"] = float(np.mean(val_acc))
        logger.info(msg)
        results["train_loss"] = float(np.mean(train_loss))
        results["train_acc"] = float(np.mean(train_acc))
    return results


def parse_args(argv=None):
    parser = argparse.ArgumentParser()
    parser.add_argument("--model", required=True,
                        help=f"one of {MODELS}")
    parser.add_argument("--dataset", required=True,
                        help="MNIST / CIFAR10 / CIFAR100")
    parser.add_argument("--batch-size", type=int, default=128)
    parser.add_argument("--learning-rate", type=float, default=0.1)
    parser.add_argument("--opt", default="sgd",
                        choices=["sgd", "momentum", "nesterov", "adagrad",
                                 "adam"])
    parser.add_argument("--num-epochs", type=int, default=10)
    parser.add_argument("--validate", action="store_true")
    parser.add_argument("--timing", action="store_true")
    parser.add_argument("--comm-mode", default=None,
                        help="None / AllReduce / PS / Hybrid")
    args = parser.parse_args(argv)
    assert args.model in MODELS, f"model {args.model} not supported"
    return args


if __name__ == "__main__":
    run(parse_args())
