"""Minibatch GraphSAGE over sampled subgraphs (reference parity:
examples/gnn/run_single.py — the reference samples per-batch subgraphs
through GraphMix graph servers and double-buffers them with
``GNNDataLoaderOp.step``; GraphMix is an empty submodule in the
snapshot, so the sampler here is an in-process numpy neighbor sampler
playing the same role).

TPU-first design point: every sampled subgraph is padded to a FIXED
node and edge budget (isolated dummy nodes / zero-valued edges), so the
whole training step compiles once — no per-batch recompiles from
ragged subgraph shapes.

    python examples/gnn/train_sampled_sage.py --timing
"""
import argparse
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import hetu_tpu as ht                                   # noqa: E402
from hetu_tpu.dataloader import GNNDataLoaderOp         # noqa: E402
from hetu_tpu.models import graphsage                   # noqa: E402


def make_graph(n=4000, deg=8, fdim=64, ncls=7, seed=0):
    """Planted-signal random graph (same recipe as train_hetu_gcn)."""
    import scipy.sparse as sp
    rng = np.random.RandomState(seed)
    rows = np.repeat(np.arange(n), deg)
    cols = rng.randint(0, n, n * deg)
    adj = sp.coo_matrix((np.ones(n * deg, np.float32), (rows, cols)),
                        shape=(n, n)).tocsr()
    y = rng.randint(0, ncls, n)
    feat = rng.randn(n, fdim).astype(np.float32)
    block = fdim // ncls
    for c in range(ncls):
        feat[y == c, c * block:(c + 1) * block] += 0.4
    return adj, feat, np.eye(ncls, dtype=np.float32)[y]


class SubgraphSampler:
    """Seed-batch -> fixed-budget induced subgraph with degree-normalized
    CSR adjacency (the GraphMix-server role, in process)."""

    def __init__(self, adj, feat, onehot, batch_seeds, fanout=8, seed=0):
        self.adj = adj
        self.feat = feat
        self.onehot = onehot
        self.batch_seeds = batch_seeds
        self.fanout = fanout
        self.rng = np.random.RandomState(seed)
        self.n_sub = batch_seeds * (fanout + 1)
        self.nnz_budget = self.n_sub * (fanout + 2)
        self.order = self.rng.permutation(adj.shape[0])
        self.cursor = 0

    def _neighbors(self, v):
        return self.adj.indices[self.adj.indptr[v]:self.adj.indptr[v + 1]]

    def next(self):
        n = self.adj.shape[0]
        if self.cursor + self.batch_seeds > n:
            self.order = self.rng.permutation(n)
            self.cursor = 0
        seeds = self.order[self.cursor:self.cursor + self.batch_seeds]
        self.cursor += self.batch_seeds

        nodes = list(seeds)
        seen = set(int(s) for s in seeds)
        for s in seeds:
            nbrs = self._neighbors(int(s))
            if len(nbrs) > self.fanout:
                nbrs = self.rng.choice(nbrs, self.fanout, replace=False)
            for v in nbrs:
                v = int(v)
                if v not in seen and len(nodes) < self.n_sub:
                    seen.add(v)
                    nodes.append(v)
        nodes = np.asarray(nodes, np.int64)
        n_real = len(nodes)
        loc = {int(g): i for i, g in enumerate(nodes)}

        rows, cols = [], []
        for i, g in enumerate(nodes):
            rows.append(i)
            cols.append(i)                      # self loop
            for v in self._neighbors(int(g)):
                j = loc.get(int(v))
                if j is not None:
                    rows.append(i)
                    cols.append(j)
        rows = np.asarray(rows)[:self.nnz_budget]
        cols = np.asarray(cols)[:self.nnz_budget]
        deg = np.bincount(rows, minlength=self.n_sub).astype(np.float32)
        vals = (1.0 / np.maximum(deg, 1.0))[rows]

        # fixed-budget CSR: pad rows beyond n_real empty, absorb unused
        # nnz as zero-valued self-edges of node 0 (no numeric effect)
        pad = self.nnz_budget - len(rows)
        indptr = np.zeros(self.n_sub + 1, np.int32)
        counts = np.bincount(rows, minlength=self.n_sub)
        order = np.argsort(rows, kind="stable")
        data = np.concatenate([vals[order],
                               np.zeros(pad, np.float32)])
        indices = np.concatenate([cols[order],
                                  np.zeros(pad, np.int32)]).astype(
                                      np.int32)
        counts[self.n_sub - 1] += pad           # padding lives in last row
        indptr[1:] = np.cumsum(counts)

        feat = np.zeros((self.n_sub, self.feat.shape[1]), np.float32)
        feat[:n_real] = self.feat[nodes]
        y = np.zeros((self.n_sub, self.onehot.shape[1]), np.float32)
        y[:n_real] = self.onehot[nodes]
        mask = np.zeros(self.n_sub, np.float32)
        mask[:len(seeds)] = 1.0                 # loss on seed nodes only
        sp_adj = ht.ND_Sparse_Array(data, indptr, indices,
                                    nrow=self.n_sub, ncol=self.n_sub)
        return {"feat": feat, "y": y, "mask": mask, "adj": sp_adj}


def main(args):
    adj, feat_arr, onehot = make_graph(args.nodes, fdim=args.features,
                                       ncls=args.classes)
    sampler = SubgraphSampler(adj, feat_arr, onehot, args.batch_seeds,
                              fanout=args.fanout)

    feat = GNNDataLoaderOp(lambda g: g["feat"])
    y_ = GNNDataLoaderOp(lambda g: g["y"])
    mask_ = GNNDataLoaderOp(lambda g: g["mask"])
    norm_adj = GNNDataLoaderOp(lambda g: g["adj"])
    loss, y, train_op = graphsage(
        feat, y_, mask_, norm_adj, args.features, args.hidden_size,
        args.classes, lr=args.learning_rate)
    train_loss = ht.reduce_mean_op(ht.mul_op(loss, mask_), [0])
    exe = ht.Executor([train_loss, train_op])

    # double-buffer bring-up: current + next (reference run_single.py)
    GNNDataLoaderOp.step(sampler.next())
    GNNDataLoaderOp.step(sampler.next())
    nbatches = args.nodes // args.batch_seeds
    results = {}
    for ep in range(args.num_epoch):
        ep_st = time.time()
        ep_loss = []
        for _ in range(nbatches):
            GNNDataLoaderOp.step(sampler.next())   # prepare next batch
            out = exe.run(feed_dict={})
            ep_loss.append(float(np.mean(out[0].asnumpy())))
        dt = time.time() - ep_st
        msg = f"epoch {ep}: loss {np.mean(ep_loss):.4f}"
        if args.timing:
            sps = nbatches * args.batch_seeds / dt
            msg += f", {dt:.2f}s ({sps:.0f} seed nodes/sec)"
            results["nodes_per_sec"] = sps
        print(msg, flush=True)
        results["loss"] = float(np.mean(ep_loss))
    assert len(exe.subexecutors["default"].compiled) == 1, \
        "fixed budgets must yield exactly one compiled step"
    exe.close()
    return results


def parse_args(argv=None):
    parser = argparse.ArgumentParser()
    parser.add_argument("--nodes", type=int, default=4000)
    parser.add_argument("--features", type=int, default=64)
    parser.add_argument("--classes", type=int, default=7)
    parser.add_argument("--hidden-size", type=int, default=64)
    parser.add_argument("--batch-seeds", type=int, default=64)
    parser.add_argument("--fanout", type=int, default=8)
    parser.add_argument("--num-epoch", type=int, default=5)
    parser.add_argument("--learning-rate", type=float, default=0.5)
    parser.add_argument("--timing", action="store_true")
    return parser.parse_args(argv)


if __name__ == "__main__":
    main(parse_args())
