"""GCN / GraphSAGE full-batch training (reference parity:
examples/gnn/train_hetu_gcn.py — normalized-adjacency CSR graph, masked
cross-entropy, per-epoch loss/acc/time). Loads an OGB-style npz graph
from HETU_DATA_DIR else synthesizes an arxiv-scale random graph.

    python examples/gnn/train_hetu_gcn.py --model gcn --timing
"""
import argparse
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import hetu_tpu as ht                       # noqa: E402
from hetu_tpu.models import gcn, graphsage  # noqa: E402


def load_graph(args):
    """(norm_adj CSR, features, onehot labels, train mask)."""
    import scipy.sparse as sp
    ddir = os.environ.get("HETU_DATA_DIR", "datasets")
    path = os.path.join(ddir, "graph.npz")
    if os.path.exists(path):
        z = np.load(path)
        adj = sp.csr_matrix((z["data"], z["indices"], z["indptr"]),
                            shape=tuple(z["shape"]))
        feat, y = z["features"], z["labels"]
        mask = z.get("train_mask", np.ones(adj.shape[0], np.float32))
        ncls = int(y.max()) + 1
    else:
        rng = np.random.RandomState(0)
        n, deg, ncls = args.nodes, 7, args.classes
        rows = np.repeat(np.arange(n), deg)
        cols = rng.randint(0, n, n * deg)
        adj = sp.coo_matrix(
            (np.ones(n * deg, np.float32), (rows, cols)),
            shape=(n, n)).tocsr()
        feat = rng.randn(n, args.features).astype(np.float32)
        y = rng.randint(0, ncls, n)
        # plant signal: label shifts a feature block mean
        block = args.features // ncls
        for c in range(ncls):
            feat[y == c, c * block:(c + 1) * block] += 0.3
        mask = np.ones(n, np.float32)
    adj = adj + sp.eye(adj.shape[0], format="csr", dtype=np.float32)
    d = np.asarray(adj.sum(1)).ravel()
    dinv = sp.diags(1.0 / np.sqrt(d))
    norm = (dinv @ adj @ dinv).tocsr()
    onehot = np.eye(ncls, dtype=np.float32)[y]
    return norm, feat.astype(np.float32), onehot, mask.astype(np.float32)


def run(args):
    norm, feat_np, y_np, mask_np = load_graph(args)
    n, fdim = feat_np.shape
    ncls = y_np.shape[1]

    feat = ht.Variable("feat", trainable=False)
    y_ = ht.Variable("y_", trainable=False)
    mask_ = ht.Variable("mask_", trainable=False)
    norm_adj = ht.Variable("norm_adj", trainable=False)
    builder = gcn if args.model == "gcn" else graphsage
    loss, y, train_op = builder(feat, y_, mask_, norm_adj, fdim,
                                args.hidden, ncls,
                                lr=args.learning_rate)
    executor = ht.Executor([ht.reduce_mean_op(loss, [0]), y, train_op])

    sp_adj = ht.ND_Sparse_Array(
        norm.data.astype(np.float32), norm.indptr.astype(np.int32),
        norm.indices.astype(np.int32), nrow=n, ncol=n)
    feeds = {feat: feat_np, y_: y_np, mask_: mask_np, norm_adj: sp_adj}
    import jax
    from hetu_tpu import ndarray
    feeds = {k: (ndarray.CSRValue.from_sparse_array(v)
                 if isinstance(v, ndarray.ND_Sparse_Array)
                 else jax.device_put(v)) for k, v in feeds.items()}

    results = {}
    for ep in range(args.num_epochs):
        t0 = time.perf_counter()
        loss_val, y_pred, _ = executor.run(feed_dict=feeds,
                                           convert_to_numpy_ret_vals=True)
        dt = time.perf_counter() - t0
        acc = float(np.mean(np.argmax(y_pred, 1) == np.argmax(y_np, 1)))
        msg = f"epoch {ep}: loss {float(np.mean(loss_val)):.4f} acc {acc:.4f}"
        if args.timing:
            msg += f" | {dt * 1000:.1f} ms/epoch"
        print(msg, flush=True)
        results.update(loss=float(np.mean(loss_val)), acc=acc,
                       epoch_time=dt)
    return results


def parse_args(argv=None):
    parser = argparse.ArgumentParser()
    parser.add_argument("--model", default="gcn",
                        choices=["gcn", "graphsage"])
    parser.add_argument("--hidden", type=int, default=256)
    parser.add_argument("--learning-rate", type=float, default=0.01)
    parser.add_argument("--num-epochs", type=int, default=10)
    parser.add_argument("--nodes", type=int, default=169_343)
    parser.add_argument("--features", type=int, default=128)
    parser.add_argument("--classes", type=int, default=40)
    parser.add_argument("--timing", action="store_true")
    return parser.parse_args(argv)


if __name__ == "__main__":
    run(parse_args())
