#!/bin/bash
# CNN parity sweep (reference parity: all_cnn_tests.sh): the conv model
# under every dispatch split must reproduce the single-device base loss
# series. Hermetic form — 8 virtual CPU devices; drop the two exports
# to run on real TPU chips.
set -e
cd "$(dirname "$0")"
export JAX_PLATFORMS=cpu
export XLA_FLAGS="--xla_force_host_platform_device_count=8 ${XLA_FLAGS:-}"
HETURUN=../../../bin/heturun
mkdir -p results

$HETURUN -c config1.yml python test_cnn_base.py --save --log results/base.npy

$HETURUN -c config2.yml python test_cnn_mp.py --split left   --log results/res0.npy
$HETURUN -c config2.yml python test_cnn_mp.py --split middle --log results/res1.npy
$HETURUN -c config2.yml python test_cnn_mp.py --split right  --log results/res2.npy

python validate_results.py 3
echo "all CNN parallel configs match the base loss series"
