"""Tensor-parallel configs: the middle matmul's operands are dispatched
across mesh devices; every --split must reproduce the base loss series
(reference examples/runner/parallel/test_mlp_mp.py — same split
vocabulary, same validation workflow).

TPU-native: ``ht.dispatch`` parts become PartitionSpecs over the device
mesh and XLA inserts the collectives, instead of the reference's manual
split/concat + NCCL send/recv planner (SURVEY.md §7 step 6).

    heturun -c config2.yml python test_mlp_mp.py --split left \
        --log results/res1.npy
"""
import argparse

import common
import hetu_tpu as ht


def main(args):
    common.ensure_std()
    act_parts, w_parts = common.SPLITS[args.split]
    # device count = batch-rows x contraction x weight-cols (the three
    # parallel axes of y = a @ w; max() would undercount composite '2')
    ndev = act_parts[0] * act_parts[1] * w_parts[1]
    devices = tuple(common.device(i) for i in range(ndev))

    with ht.context(common.device(0)):
        x = ht.Variable("dataloader_x", trainable=False)
        act = common.fc(x, "mlp_fc1", with_relu=True)

    with ht.context(devices):
        w = ht.Variable("special_weight",
                        value=common.load_std("special_weight"))
        act = ht.dispatch(act, act_parts)
        w = ht.dispatch(w, w_parts)
        act = ht.matmul_op(act, w)

    with ht.context(common.device(0)):
        act = ht.dispatch(act, (1, 1))
        act = ht.relu_op(act)
        y_pred = common.fc(act, "mlp_fc2", with_relu=False)
        y_ = ht.Variable("dataloader_y", trainable=False)
        loss = ht.reduce_mean_op(
            ht.softmaxcrossentropy_op(y_pred, y_), [0])
        train_op = ht.optim.SGDOptimizer(
            learning_rate=args.learning_rate).minimize(loss)
        executor = ht.Executor([loss, train_op])
    common.train_and_log(executor, x, y_, args.steps, args.log,
                         batch_size=args.batch_size)


if __name__ == "__main__":
    parser = argparse.ArgumentParser()
    parser.add_argument("--steps", type=int, default=8)
    parser.add_argument("--batch-size", type=int, default=64)
    parser.add_argument("--learning-rate", type=float, default=0.01)
    parser.add_argument("--split", default="left",
                        choices=sorted(common.SPLITS))
    parser.add_argument("--log", default=None)
    main(parser.parse_args())
