"""Two-host data parallelism through the launcher's ssh path (reference
examples/runner/parallel/dist_data_pipeline_mlp.py + dist_config8.yml):
each host contributes one SPMD worker process; the dp mesh spans both
processes and gradients AllReduce over it, so the loss series matches
the single-device base run bit-for-bit.

    heturun -c dist_config2.yml python dist_data_mlp.py --log res.npy
"""
import argparse
import os

# one device per worker process: the 2-process dp mesh is exactly the
# two hosts (set before jax initializes via common's import)
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
os.environ["JAX_PLATFORMS"] = "cpu"

import numpy as np                                      # noqa: E402

import common                                           # noqa: E402
import hetu_tpu as ht                                   # noqa: E402
from hetu_tpu.executor import (Executor, HetuConfig,    # noqa: E402
                               maybe_init_distributed)


def main(args):
    maybe_init_distributed()     # joins the 2-process JAX job
    import jax
    from jax.sharding import Mesh
    assert jax.process_count() == 2, jax.process_count()
    common.ensure_std()
    x = ht.Variable("dataloader_x", trainable=False)
    act = common.fc(x, "mlp_fc1", with_relu=True)
    w = ht.Variable("special_weight",
                    value=common.load_std("special_weight"))
    act = ht.relu_op(ht.matmul_op(act, w))
    y_pred = common.fc(act, "mlp_fc2", with_relu=False)
    y_ = ht.Variable("dataloader_y", trainable=False)
    loss = ht.reduce_mean_op(
        ht.softmaxcrossentropy_op(y_pred, y_), [0])
    train_op = ht.optim.SGDOptimizer(
        learning_rate=args.learning_rate).minimize(loss)
    mesh = Mesh(np.asarray(jax.devices()), ("dp",))
    config = HetuConfig(eval_node_list=[loss, train_op], mesh=mesh)
    config.nrank = jax.process_count()
    executor = Executor({"default": [loss, train_op]}, config=config)
    log = args.log
    if log and int(os.environ.get("HETU_PROC_ID", "0")) != 0:
        log = None               # rank 0 writes the comparison artifact
    common.train_and_log(executor, x, y_, args.steps, log,
                         batch_size=args.batch_size)


if __name__ == "__main__":
    parser = argparse.ArgumentParser()
    parser.add_argument("--steps", type=int, default=8)
    parser.add_argument("--batch-size", type=int, default=64)
    parser.add_argument("--learning-rate", type=float, default=0.01)
    parser.add_argument("--log", default=None)
    main(parser.parse_args())
