"""Shared pieces of the parallel-config zoo (reference parity:
examples/runner/parallel/ — fixed ``std/`` weights so every config
trains the SAME model, loss series logged to ``results/*.npy``,
``validate_results.py`` asserts allclose against the base run).

TPU-native notes: the reference runs each config as an mpirun fleet;
here a config is ONE SPMD process over a device mesh — ``device(i)``
returns the i-th mesh device (real TPU chips, or the virtual CPU mesh
when ``JAX_PLATFORMS=cpu`` + ``--xla_force_host_platform_device_count``
are set, which ``all_mlp_tests.sh`` exports).
"""
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", "..", ".."))

# the axon TPU-tunnel site plugin overrides JAX_PLATFORMS from the
# environment; pin the choice through jax.config (tests/conftest.py does
# the same)
if os.environ.get("JAX_PLATFORMS", "").split(",")[0] == "cpu":
    import jax
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_default_matmul_precision", "highest")

import hetu_tpu as ht                                   # noqa: E402

HERE = os.path.dirname(os.path.abspath(__file__))
STD = os.path.join(HERE, "std")
RESULTS = os.path.join(HERE, "results")

DIMS = dict(in_dim=784, hidden1=256, special=512, out_dim=10)


def device(i):
    """i-th mesh device: TPU when available, else the virtual CPU mesh."""
    import jax
    if jax.default_backend() == "tpu":
        return ht.tpu(i)
    return ht.cpu(i)


def ensure_std(force=False):
    """Write the fixed weights every config loads (the reference keeps a
    pre-generated std/ dir; we generate deterministically on first use —
    ``force`` regenerates after a DIMS/init edit)."""
    os.makedirs(STD, exist_ok=True)
    rng = np.random.RandomState(42)
    specs = {
        "mlp_fc1_weight": (DIMS["in_dim"], DIMS["hidden1"]),
        "mlp_fc1_bias": (DIMS["hidden1"],),
        "special_weight": (DIMS["hidden1"], DIMS["special"]),
        "mlp_fc2_weight": (DIMS["special"], DIMS["out_dim"]),
        "mlp_fc2_bias": (DIMS["out_dim"],),
    }
    for name, shape in specs.items():
        path = os.path.join(STD, name + ".npy")
        if force or not os.path.exists(path):
            np.save(path, (rng.randn(*shape) * 0.05).astype(np.float32))


def ensure_cnn_std(force=False):
    """Fixed weights for the CNN zoo variants (reference
    all_cnn_tests.sh trains the same conv model under every split)."""
    os.makedirs(STD, exist_ok=True)
    rng = np.random.RandomState(43)
    specs = {
        "cnn_conv1_weight": (32, 1, 5, 5),
        "special_cnn_weight": (32, 32, 5, 5),
        "cnn_fc_weight": (32 * 7 * 7, 10),
        "cnn_fc_bias": (10,),
    }
    for name, shape in specs.items():
        path = os.path.join(STD, name + ".npy")
        if force or not os.path.exists(path):
            np.save(path, (rng.randn(*shape) * 0.04).astype(np.float32))


def conv_relu(x, name, ctx=None):
    """5x5/pad2 conv + relu from fixed std/ weights (reference
    test_model_cnn_base.py conv_relu)."""
    w = ht.Variable(name, value=load_std(name), ctx=ctx)
    return ht.relu_op(ht.conv2d_op(x, w, padding=2, stride=1))


def load_std(name):
    return np.load(os.path.join(STD, name + ".npy"))


# conv split vocabulary -> (data parts, filter parts) over NCHW x OIHW
# (reference test_model_cnn.py --split): 'left' batch-splits the data,
# 'right' splits the filter's output channels, 'middle' splits the
# contracted input channels on both operands
CNN_SPLITS = {
    "left": ((2, 1, 1, 1), (1, 1, 1, 1)),
    "right": ((1, 1, 1, 1), (2, 1, 1, 1)),
    "middle": ((1, 2, 1, 1), (1, 2, 1, 1)),
}


def fc(x, name, with_relu=True, ctx=None):
    """Linear layer from fixed std/ weights (reference
    test_mlp_mp_pp.py:8-17)."""
    weight = ht.Variable(name + "_weight", value=load_std(name + "_weight"),
                         ctx=ctx)
    bias = ht.Variable(name + "_bias", value=load_std(name + "_bias"),
                       ctx=ctx)
    x = ht.matmul_op(x, weight)
    x = x + ht.broadcastto_op(bias, x)
    if with_relu:
        x = ht.relu_op(x)
    return x


def batches(batch_size=64, batch_num=5, seed=7):
    """Deterministic MNIST-shaped batches (real MNIST files when present,
    ht.data.mnist()'s planted-signal stand-in otherwise — equivalence
    only needs both runs to see identical data)."""
    (tx, ty), _, _ = ht.data.mnist()
    rng = np.random.RandomState(seed)
    idx = rng.permutation(len(tx))[:batch_size * batch_num]
    xs = tx[idx].reshape(batch_num, batch_size, -1)
    ys = ty[idx].reshape(batch_num, batch_size, -1)
    return xs, ys


def train_and_log(executor, x, y_, steps, log_path, batch_size=64):
    """Run ``steps`` steps over the fixed batches; save the loss series
    (the artifact validate_results.py compares)."""
    xs, ys = batches(batch_size=batch_size)
    losses = []
    for i in range(steps):
        out = executor.run(feed_dict={x: xs[i % len(xs)],
                                      y_: ys[i % len(ys)]})
        losses.append(float(np.asarray(out[0].asnumpy()).reshape(())))
    print("losses:", [round(v, 6) for v in losses])
    if log_path:
        os.makedirs(os.path.dirname(os.path.abspath(log_path)),
                    exist_ok=True)
        np.save(log_path, np.asarray(losses))
    return losses


# the reference's split vocabulary -> (activation parts, weight parts)
# for y = a @ w (test_mlp_mp_pp.py:66-89): 'left' row-splits the batch,
# 'right' col-splits the weight, 'middle' splits the contraction dim,
# '0'-'4' are the 4-way composites
SPLITS = {
    "left": ((2, 1), (1, 1)),
    "right": ((1, 1), (1, 2)),
    "middle": ((1, 2), (2, 1)),
    "0": ((4, 1), (1, 1)),
    "1": ((2, 2), (2, 1)),
    "2": ((2, 1), (1, 2)),
    "3": ((1, 2), (2, 2)),
    "4": ((1, 1), (1, 4)),
}
