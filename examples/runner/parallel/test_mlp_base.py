"""Ground truth: the zoo MLP on one device (reference
examples/runner/parallel/test_mlp_base.py).

    heturun -c config1.yml python test_mlp_base.py --save \
        --log results/base.npy
"""
import argparse

import common
import hetu_tpu as ht


def main(args):
    common.ensure_std(force=args.save)
    with ht.context(common.device(0)):
        x = ht.Variable("dataloader_x", trainable=False)
        act = common.fc(x, "mlp_fc1", with_relu=True)
        w = ht.Variable("special_weight",
                        value=common.load_std("special_weight"))
        act = ht.matmul_op(act, w)
        act = ht.relu_op(act)
        y_pred = common.fc(act, "mlp_fc2", with_relu=False)
        y_ = ht.Variable("dataloader_y", trainable=False)
        loss = ht.reduce_mean_op(
            ht.softmaxcrossentropy_op(y_pred, y_), [0])
        train_op = ht.optim.SGDOptimizer(
            learning_rate=args.learning_rate).minimize(loss)
        executor = ht.Executor([loss, train_op])
    common.train_and_log(executor, x, y_, args.steps, args.log,
                         batch_size=args.batch_size)


if __name__ == "__main__":
    parser = argparse.ArgumentParser()
    parser.add_argument("--steps", type=int, default=8)
    parser.add_argument("--batch-size", type=int, default=64)
    parser.add_argument("--learning-rate", type=float, default=0.01)
    parser.add_argument("--save", action="store_true",
                        help="(re)generate the std/ fixed weights")
    parser.add_argument("--log", default=None)
    main(parser.parse_args())
