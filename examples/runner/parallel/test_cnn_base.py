"""CNN zoo ground truth: the conv model on one device (reference
examples/runner/parallel/test_model_cnn_base.py).

    heturun -c config1.yml python test_cnn_base.py --save \
        --log results/cnn_base.npy
"""
import argparse

import common
import hetu_tpu as ht


def build(device0, special_ctx=None, split=None):
    """The shared zoo conv model; ``split`` dispatches the special
    conv's operands (test_cnn_mp.py passes it)."""
    with ht.context(device0):
        x = ht.Variable("dataloader_x", trainable=False)
        act = ht.array_reshape_op(x, (-1, 1, 28, 28))
        act = common.conv_relu(act, "cnn_conv1_weight")
        act = ht.max_pool2d_op(act, 2, 2, stride=2)

    with ht.context(special_ctx or device0):
        w = ht.Variable("special_cnn_weight",
                        value=common.load_std("special_cnn_weight"))
        if split is not None:
            act_parts, w_parts = common.CNN_SPLITS[split]
            act = ht.dispatch(act, act_parts)
            w = ht.dispatch(w, w_parts)
        act = ht.conv2d_op(act, w, padding=2, stride=1)

    with ht.context(device0):
        if split is not None:
            act = ht.dispatch(act, (1, 1, 1, 1))
        act = ht.relu_op(act)
        act = ht.max_pool2d_op(act, 2, 2, stride=2)
        act = ht.array_reshape_op(act, (-1, 32 * 7 * 7))
        y_pred = common.fc(act, "cnn_fc", with_relu=False)
        y_ = ht.Variable("dataloader_y", trainable=False)
        loss = ht.reduce_mean_op(
            ht.softmaxcrossentropy_op(y_pred, y_), [0])
    return x, y_, loss


def main(args):
    common.ensure_std()
    common.ensure_cnn_std(force=args.save)
    x, y_, loss = build(common.device(0))
    with ht.context(common.device(0)):
        train_op = ht.optim.SGDOptimizer(
            learning_rate=args.learning_rate).minimize(loss)
        executor = ht.Executor([loss, train_op])
    common.train_and_log(executor, x, y_, args.steps, args.log,
                         batch_size=args.batch_size)


if __name__ == "__main__":
    parser = argparse.ArgumentParser()
    parser.add_argument("--steps", type=int, default=8)
    parser.add_argument("--batch-size", type=int, default=32)
    parser.add_argument("--learning-rate", type=float, default=0.01)
    parser.add_argument("--save", action="store_true")
    parser.add_argument("--log", default=None)
    main(parser.parse_args())
