"""Assert every results/res{i}.npy matches results/base.npy (reference
examples/runner/parallel/validate_results.py — the zoo's parity gate).

    python validate_results.py 3 --rtol 1e-4
"""
import argparse
import os.path as osp

import numpy as np

if __name__ == "__main__":
    parser = argparse.ArgumentParser()
    parser.add_argument("number", type=int,
                        help="how many results/res{i}.npy to check")
    parser.add_argument("--rtol", type=float, default=1e-4)
    parser.add_argument("--dir", default="results")
    args = parser.parse_args()

    base = np.load(osp.join(args.dir, "base.npy"))
    print("Ground truth:", base)
    for i in range(args.number):
        res = np.load(osp.join(args.dir, f"res{i}.npy"))
        np.testing.assert_allclose(base, res, rtol=args.rtol, atol=1e-6)
        print(f"Result id {i} passed test.", res)
