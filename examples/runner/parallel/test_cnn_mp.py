"""CNN model-parallel zoo variants: the special conv's operands are
dispatched across mesh devices — batch ('left'), output channels
('right'), or the contracted input channels ('middle') — and every
split must reproduce the base loss series (reference
examples/runner/parallel/test_model_cnn.py + all_cnn_tests.sh).

TPU-native: dispatch parts lower to PartitionSpecs and XLA inserts the
conv collectives (the in-channel split contracts with a psum), instead
of the reference's manual split/concat planner.

    heturun -c config2.yml python test_cnn_mp.py --split middle \
        --log results/cnn_res1.npy
"""
import argparse

import common
import hetu_tpu as ht
from test_cnn_base import build


def main(args):
    common.ensure_std()
    common.ensure_cnn_std()
    act_parts, w_parts = common.CNN_SPLITS[args.split]
    ndev = act_parts[0] * act_parts[1] * w_parts[0]
    devices = tuple(common.device(i) for i in range(ndev))
    x, y_, loss = build(common.device(0), special_ctx=devices,
                        split=args.split)
    with ht.context(common.device(0)):
        train_op = ht.optim.SGDOptimizer(
            learning_rate=args.learning_rate).minimize(loss)
        executor = ht.Executor([loss, train_op])
    common.train_and_log(executor, x, y_, args.steps, args.log,
                         batch_size=args.batch_size)


if __name__ == "__main__":
    parser = argparse.ArgumentParser()
    parser.add_argument("--steps", type=int, default=8)
    parser.add_argument("--batch-size", type=int, default=32)
    parser.add_argument("--learning-rate", type=float, default=0.01)
    parser.add_argument("--split", default="left",
                        choices=sorted(common.CNN_SPLITS))
    parser.add_argument("--log", default=None)
    main(parser.parse_args())
