#!/bin/bash
# The zoo's parity sweep (reference parity: all_mlp_tests.sh): the base
# run is ground truth; every parallel config must reproduce its loss
# series. Hermetic form — 8 virtual CPU devices; drop the two exports
# to run on real TPU chips.
set -e
cd "$(dirname "$0")"
export JAX_PLATFORMS=cpu
export XLA_FLAGS="--xla_force_host_platform_device_count=8 ${XLA_FLAGS:-}"
HETURUN=../../../bin/heturun
mkdir -p results

$HETURUN -c config1.yml python test_mlp_base.py --save --log results/base.npy

$HETURUN -c config2.yml python test_mlp_pp.py --log results/res0.npy

$HETURUN -c config2.yml python test_mlp_mp.py --split left   --log results/res1.npy
$HETURUN -c config2.yml python test_mlp_mp.py --split middle --log results/res2.npy
$HETURUN -c config2.yml python test_mlp_mp.py --split right  --log results/res3.npy
$HETURUN -c config4.yml python test_mlp_mp.py --split 0      --log results/res4.npy
$HETURUN -c config4.yml python test_mlp_mp.py --split 1      --log results/res5.npy
$HETURUN -c config4.yml python test_mlp_mp.py --split 2      --log results/res6.npy
$HETURUN -c config4.yml python test_mlp_mp.py --split 3      --log results/res7.npy
$HETURUN -c config4.yml python test_mlp_mp.py --split 4      --log results/res8.npy

$HETURUN -c config4.yml python test_mlp_mp_pp.py --split left   --log results/res9.npy
$HETURUN -c config4.yml python test_mlp_mp_pp.py --split middle --log results/res10.npy
$HETURUN -c config4.yml python test_mlp_mp_pp.py --split right  --log results/res11.npy
$HETURUN -c config8.yml python test_mlp_mp_pp.py --split 1      --log results/res12.npy

python validate_results.py 13
echo "all parallel configs match the base loss series"
