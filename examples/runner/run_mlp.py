"""MLP-on-MNIST trainer for heturun configs (reference parity:
examples/runner/run_mlp.py — the runner family's dense workload; comm
mode comes from the launcher env / --comm-mode, not the script).

    python examples/runner/run_mlp.py --timing --validate
    bin/heturun -c examples/runner/local_ps.yml \
        python examples/runner/run_mlp.py --comm-mode PS --timing
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", "cnn"))
import main as cnn_main                              # noqa: E402


def parse_args(argv=None):
    parser = argparse.ArgumentParser()
    parser.add_argument("--batch-size", type=int, default=128)
    parser.add_argument("--learning-rate", type=float, default=0.1)
    parser.add_argument("--opt", default="sgd",
                        choices=["sgd", "momentum", "nesterov", "adagrad",
                                 "adam"])
    parser.add_argument("--num-epochs", type=int, default=10)
    parser.add_argument("--validate", action="store_true")
    parser.add_argument("--timing", action="store_true")
    parser.add_argument("--comm-mode", default=None)
    return parser.parse_args(argv)


if __name__ == "__main__":
    a = parse_args()
    argv = ["--model", "mlp", "--dataset", "MNIST",
            "--batch-size", str(a.batch_size),
            "--learning-rate", str(a.learning_rate), "--opt", a.opt,
            "--num-epochs", str(a.num_epochs)]
    if a.validate:
        argv.append("--validate")
    if a.timing:
        argv.append("--timing")
    if a.comm_mode:
        argv += ["--comm-mode", a.comm_mode]
    cnn_main.run(cnn_main.parse_args(argv))
