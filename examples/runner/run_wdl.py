"""Wide&Deep-on-Criteo trainer for heturun configs (reference parity:
examples/runner/run_wdl.py — the runner family's sparse/PS workload).

    bin/heturun -c examples/runner/local_ps.yml \
        python examples/runner/run_wdl.py --comm-mode PS --timing
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", "ctr"))
import run_hetu as ctr_main                          # noqa: E402


def parse_args(argv=None):
    parser = argparse.ArgumentParser()
    parser.add_argument("--batch-size", type=int, default=128)
    parser.add_argument("--learning-rate", type=float, default=0.01)
    parser.add_argument("--nepoch", type=int, default=3)
    parser.add_argument("--val", action="store_true")
    parser.add_argument("--timing", action="store_true")
    parser.add_argument("--all", action="store_true")
    parser.add_argument("--comm-mode", default=None,
                        help="None / PS / Hybrid")
    parser.add_argument("--cache", default="Device")
    parser.add_argument("--bound", type=int, default=100)
    parser.add_argument("--bsp", action="store_true")
    return parser.parse_args(argv)


if __name__ == "__main__":
    a = parse_args()
    argv = ["--model", "wdl_criteo", "--batch-size", str(a.batch_size),
            "--learning-rate", str(a.learning_rate),
            "--nepoch", str(a.nepoch), "--cache", a.cache,
            "--bound", str(a.bound)]
    if a.val:
        argv.append("--val")
    if a.timing:
        argv.append("--timing")
    if a.all:
        argv.append("--all")
    if a.bsp:
        argv.append("--bsp")
    if a.comm_mode:
        argv += ["--comm-mode", a.comm_mode]
    ctr_main.worker(ctr_main.parse_args(argv))
