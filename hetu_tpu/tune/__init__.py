"""Kernel autotuning: sweep-once, cache-forever config selection.

* ``autotune.py`` — the generic engine: ``autotune(name, key,
  candidates, measure)`` with an env-controlled persistent JSON cache
  (``HETU_AUTOTUNE``, ``HETU_AUTOTUNE_CACHE``). Kernel-agnostic by
  design; flash-attention block sizes are the first consumer
  (``ops/pallas_attention.py``), scan-block sizes and pipeline tick
  fusing can ride the same cache later.
* ``probe.py`` — segment-timing harness: per-kernel tuned-vs-static
  milliseconds and full-step fwd/bwd/remainder attribution
  (``python -m hetu_tpu.tune.probe``).
"""
from .autotune import (AutotuneTable, autotune, configure,
                       default_cache_path, get_table, platform_tag,
                       reset, timeit, tuning_mode)
from .probe import attribute_step, probe_attention

__all__ = ["AutotuneTable", "autotune", "configure",
           "default_cache_path", "get_table", "platform_tag", "reset",
           "timeit", "tuning_mode", "attribute_step", "probe_attention",
           "chosen_configs"]


def chosen_configs(prefix=None):
    """{key_string: config} of every cached decision in the
    process-global table — what ``bench.py`` stamps into each round's
    artifact so the chosen (bq, bk) per kernel is recorded."""
    return get_table().chosen(prefix=prefix)
