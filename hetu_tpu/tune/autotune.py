"""Generic kernel autotuner: sweep-once, cache-forever config selection.

The production-attention lesson (FlashAttention, Megatron-LM) is that
tile-size choices dominate kernel throughput and the right choice is a
function of shape/dtype/platform, not a constant — so treat the chosen
config as a first-class cached artifact. This module is the
kernel-agnostic half: ``autotune(name, key, candidates, measure)``
sweeps ``candidates`` through the caller's ``measure`` on the first
compile of a given (platform, name, key), records the winner in an
in-process table backed by a persistent JSON file, and returns the
cached winner for free on every later lookup (including later
processes). ``ops/pallas_attention.py`` consumes it for flash-attention
block sizes; the engine carries nothing attention-specific, so scan
block sizes or pipeline tick fusing can ride the same cache later.

Environment:

* ``HETU_AUTOTUNE`` — ``0`` disables tuning entirely (callers keep
  their static defaults), ``1`` is use-cache-only (a miss returns the
  default with NO sweep — deterministic CI runs), ``force`` re-sweeps
  even on a cache hit; unset/``auto`` sweeps on miss, hits otherwise.
* ``HETU_AUTOTUNE_CACHE`` — cache file (or directory, file named
  ``autotune.json`` inside); default ``~/.cache/hetu_tpu/autotune.json``.

Telemetry (process-global registry): ``autotune_cache_hit`` /
``autotune_cache_miss`` / ``autotune_sweeps`` counters, and one
``autotune_sweep`` span per sweep whose attrs carry the kernel, key,
chosen config and per-candidate milliseconds — the sweep is visible in
the trace instead of reading as an unexplained slow first step.
"""
from __future__ import annotations

import json
import os
import threading
import time

__all__ = ["AutotuneTable", "autotune", "get_table", "configure",
           "reset", "tuning_mode", "default_cache_path", "platform_tag",
           "timeit"]

_MODE_ENV = "HETU_AUTOTUNE"
_CACHE_ENV = "HETU_AUTOTUNE_CACHE"
_VERSION = 1


def tuning_mode():
    """'off' | 'cache' | 'force' | 'auto' from ``HETU_AUTOTUNE``."""
    raw = os.environ.get(_MODE_ENV, "").strip().lower()
    if raw in ("0", "off", "false", "no"):
        return "off"
    if raw in ("1", "cache"):
        return "cache"
    if raw == "force":
        return "force"
    return "auto"


def default_cache_path():
    p = os.environ.get(_CACHE_ENV)
    if not p:
        return os.path.join(os.path.expanduser("~"), ".cache",
                            "hetu_tpu", "autotune.json")
    p = os.path.expanduser(p)
    if p.endswith(".json"):
        return p
    return os.path.join(p, "autotune.json")


_PLATFORM = None


def platform_tag():
    """Cache partition for the attached accelerator: configs tuned on
    one chip generation must not be served to another. Memoized — the
    serving prefill path resolves blocks per request and must not pay
    a jax.devices() call each time."""
    global _PLATFORM
    if _PLATFORM is None:
        try:
            import jax
            dev = jax.devices()[0]
            kind = (getattr(dev, "device_kind", "")
                    or jax.default_backend())
            _PLATFORM = "".join(          # lock-ok: HT605 idempotent memo: racing writers compute identical values, swap is atomic
                c if c.isalnum() else "_"
                for c in str(kind).strip().lower()) or "unknown"
        except Exception:
            return "unknown"        # uninitialized backend: don't pin
    return _PLATFORM


def _key_string(name, key):
    parts = [platform_tag(), str(name)]
    if isinstance(key, (tuple, list)):
        parts += [str(k) for k in key]
    else:
        parts.append(str(key))
    return "|".join(parts)


def _freeze(cfg):
    """JSON round-trips tuples as lists; hand configs back frozen so a
    cache hit and a fresh sweep return the same type."""
    if isinstance(cfg, list):
        return tuple(_freeze(c) for c in cfg)
    return cfg


def timeit(run, sync=None, reps=3, windows=2):
    """Seconds per ``run()`` call: one warmup (compile), then the best
    of ``windows`` timed windows of ``reps`` back-to-back dispatches
    ended by ``sync(out)`` — callers sync by readback, never
    ``block_until_ready`` (which returns early over a remote device
    tunnel, docs/performance.md measurement discipline)."""
    out = run()
    if sync is not None:
        sync(out)
    best = float("inf")
    for _ in range(max(1, windows)):
        t0 = time.perf_counter()
        for _ in range(max(1, reps)):
            out = run()
        if sync is not None:
            sync(out)
        best = min(best, (time.perf_counter() - t0) / max(1, reps))
    return best


def _telemetry():
    from .. import telemetry
    return telemetry.get_telemetry()


class AutotuneTable:
    """In-process config table backed by one JSON cache file.

    ``mode=None`` re-reads ``HETU_AUTOTUNE`` at every lookup, so tests
    and CLI runs can flip the env without rebuilding the table.
    """

    def __init__(self, path=None, mode=None):
        self.path = default_cache_path() if path is None else \
            os.fspath(path)
        self._mode = mode
        self._entries = None            # lazy: {key_str: entry dict}
        self._lock = threading.RLock()
        self._inflight = {}             # key_str -> Event (sweep runs)

    # -- persistence -----------------------------------------------------
    def _load(self):
        if self._entries is not None:
            return self._entries
        entries = {}
        try:
            with open(self.path) as f:
                doc = json.load(f)
            if isinstance(doc, dict) and doc.get("version") == _VERSION:
                entries = dict(doc.get("entries") or {})
        except (OSError, ValueError):
            pass                        # cold or corrupt cache: resweep
        self._entries = entries
        return entries

    def save(self):
        """Atomic write (temp + rename): a concurrently-reading process
        sees the old file or the new one, never a torn write. Merges
        with whatever is on disk first (our entries win) so two
        processes tuning DIFFERENT kernels against one cache file don't
        drop each other's winners — the read-merge-write runs under an
        advisory flock on a sidecar .lock file so two ranks saving
        simultaneously serialize instead of racing the re-read
        (best-effort: platforms without fcntl fall back to the atomic
        rename alone, where a lost entry just re-sweeps next run)."""
        with self._lock:
            d = os.path.dirname(self.path)
            if d:
                os.makedirs(d, exist_ok=True)
            lf = None
            try:
                try:
                    import fcntl
                    lf = open(self.path + ".lock", "w")
                    fcntl.flock(lf, fcntl.LOCK_EX)
                except (ImportError, OSError):
                    pass
                entries = self._load()
                try:
                    with open(self.path) as f:
                        doc = json.load(f)
                    if isinstance(doc, dict) and \
                            doc.get("version") == _VERSION:
                        disk = dict(doc.get("entries") or {})
                        disk.update(entries)
                        self._entries = entries = disk
                except (OSError, ValueError):
                    pass
                tmp = f"{self.path}.{os.getpid()}.tmp"
                with open(tmp, "w") as f:
                    json.dump({"version": _VERSION, "entries": entries},
                              f, indent=1, sort_keys=True)
                os.replace(tmp, self.path)
            finally:
                if lf is not None:
                    lf.close()      # closing releases the flock

    # -- table access ----------------------------------------------------
    def get(self, name, key):
        with self._lock:
            ent = self._load().get(_key_string(name, key))
        return _freeze(ent["config"]) if ent else None

    def put(self, name, key, config, picked_ms=None, candidates_ms=None):
        """Record a config directly (tests, offline tuning runs)."""
        ent = {"config": list(config) if isinstance(config, tuple)
               else config, "ts": time.time()}
        if picked_ms is not None:
            ent["picked_ms"] = round(float(picked_ms), 4)
        if candidates_ms is not None:
            ent["candidates_ms"] = candidates_ms
        with self._lock:
            self._load()[_key_string(name, key)] = ent
            self.save()

    def chosen(self, prefix=None):
        """{key_string: config} of every cached decision (optionally
        filtered by kernel-name prefix) — what the bench records into
        each round's artifact."""
        with self._lock:
            items = list(self._load().items())
        out = {}
        for ks, ent in items:
            name = ks.split("|", 2)[1] if ks.count("|") >= 2 else ks
            if prefix is None or name.startswith(prefix):
                out[ks] = _freeze(ent["config"])
        return out

    # -- the engine ------------------------------------------------------
    def lookup(self, name, key, candidates, measure, default=None):
        """The cached winner for (platform, name, key), sweeping
        ``candidates`` through ``measure(config) -> seconds`` when the
        mode calls for it. ``default`` is returned when tuning is off,
        on a use-cache-only miss, or when every candidate fails."""
        mode = self._mode or tuning_mode()
        if mode == "off" or not candidates:
            return default
        tel = _telemetry()
        ks = _key_string(name, key)
        # cache check and in-flight registration share ONE locked
        # section: checking in one section and claiming ownership in a
        # later one would let a thread that missed just before the
        # previous owner persisted re-run the whole multi-second sweep
        wait_ev = ev = None
        with self._lock:
            if mode != "force":
                ent = self._load().get(ks)
                if ent is not None:
                    tel.inc("autotune_cache_hit")
                    return _freeze(ent["config"])
            if mode != "cache":
                ev = self._inflight.get(ks)
                if ev is None:
                    self._inflight[ks] = ev = threading.Event()
                else:
                    wait_ev, ev = ev, None
        if mode == "cache":
            tel.inc("autotune_cache_miss")
            return default
        if wait_ev is not None:
            # single-flight per key: a second thread first-tracing the
            # same shape waits for the running sweep instead of
            # duplicating seconds of device time
            wait_ev.wait(timeout=600.0)
            with self._lock:
                ent = self._load().get(ks)
            if ent is not None:
                tel.inc("autotune_cache_hit")
                return _freeze(ent["config"])
            return default          # the owner's sweep failed entirely
        try:
            return self._sweep(name, ks, candidates, measure, default)
        finally:
            with self._lock:
                self._inflight.pop(ks, None)
            ev.set()

    def _sweep(self, name, key_str, candidates, measure, default):
        tel = _telemetry()
        tel.inc("autotune_sweeps")
        t0 = tel.clock()
        wall0 = time.perf_counter()
        results = {}
        state = {"cfg": None, "dt": float("inf")}

        def run_candidates():
            # measure() runs jax computations eagerly. Lookups usually
            # fire at TRACE time of the caller's step function, and jax
            # trace state is thread-local — a dedicated thread gives the
            # measurements a clean (non-tracing) context, so candidate
            # inputs stay concrete and each timed call really executes.
            for cfg in candidates:
                try:
                    dt = float(measure(cfg))
                except Exception:
                    # candidate does not compile / does not fit (e.g.
                    # VMEM overflow at the largest tiles): skip, never
                    # abort the sweep — some candidate always works
                    results[str(cfg)] = None
                    continue
                results[str(cfg)] = round(dt * 1000, 4)
                if dt < state["dt"]:
                    state["cfg"], state["dt"] = cfg, dt

        worker = threading.Thread(target=run_candidates,
                                  name="hetu-autotune-sweep")
        worker.start()
        worker.join()
        best_cfg, best_dt = state["cfg"], state["dt"]
        if best_cfg is None:
            return default
        ent = {"config": list(best_cfg) if isinstance(best_cfg, tuple)
               else best_cfg, "picked_ms": round(best_dt * 1000, 4),
               "candidates_ms": results, "ts": time.time()}
        with self._lock:
            self._load()[key_str] = ent
            try:
                self.save()
            except OSError:
                pass                    # read-only FS: in-process only
        if tel.enabled:
            tel.complete("autotune_sweep", t0,
                         t0 + int((time.perf_counter() - wall0) * 1e9),
                         args={"kernel": str(name), "key": key_str,
                               "chosen": str(best_cfg),
                               "picked_ms": ent["picked_ms"],
                               "candidates_ms": results})
        return _freeze(best_cfg) if isinstance(best_cfg, (tuple, list)) \
            else best_cfg


_table = None
_table_lock = threading.Lock()


def get_table():
    """The process-global table (default cache path, env-driven mode)."""
    global _table
    with _table_lock:
        if _table is None:
            _table = AutotuneTable()
        return _table


def configure(path=None, mode=None):
    """Install a fresh process-global table and return it."""
    global _table
    with _table_lock:
        _table = AutotuneTable(path=path, mode=mode)
        return _table


def reset():
    """Drop the process-global table (tests)."""
    global _table
    with _table_lock:
        _table = None


def autotune(name, key, candidates, measure, default=None):
    """Module-level shorthand for ``get_table().lookup(...)``."""
    return get_table().lookup(name, key, candidates, measure,
                              default=default)
