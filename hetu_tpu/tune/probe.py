"""Segment-timing probe: where does a long-sequence step's time go?

``bert_s2048`` runs at a fraction of roofline and the open question is
"kernel or XLA remainder". This harness answers it with data instead of
a guess: it times the tuned flash forward(+lse) and fused backward in
isolation on the exact attention shape a model runs, times the STATIC
default blocks beside them (the kernel-level before/after of the
autotuner), and splits a measured full-step time into
attention-fwd / attention-bwd / XLA-remainder.

Results flow through the process-global telemetry — one ``attn_probe``
span per probed kernel with the chosen blocks and milliseconds in its
attrs, plus ``probe_attn_{fwd,bwd,remainder}_ms`` gauges — so the
attribution lands in the same trace as the step it explains.

CLI::

    python -m hetu_tpu.tune.probe --batch 8 --heads 8 --seq 2048 \
        --head-dim 64 --dtype bfloat16 [--causal] [--no-mask] \
        [--step-ms 58.3 --layers 4]

prints one JSON document; with ``--step-ms`` it includes the
full-step attribution.
"""
from __future__ import annotations

import argparse
import json
import time

import numpy as np

__all__ = ["probe_attention", "attribute_step", "main"]


def _telemetry():
    from .. import telemetry
    return telemetry.get_telemetry()


def probe_attention(batch, heads, seq, head_dim, dtype="bfloat16",
                    sm_scale=None, causal=False, has_mask=True,
                    interpret=None, reps=5, include_static=True):
    """Per-kernel milliseconds for the flash fwd(+lse)/bwd on one shape.

    Returns ``{"fwd_ms", "fwd_lse_ms", "bwd_ms", "blocks": {kind:
    (bq, bk)}}`` plus ``static_*_ms`` twins measured with the untuned
    ``_block_sizes`` defaults when ``include_static`` (the in-repo
    tuned-vs-static evidence). Uses the tuned path, so a cold autotune
    cache sweeps here — which is the point: the probe pays the sweep
    the training step would have paid, and the cache makes both free
    afterwards."""
    import jax
    import jax.numpy as jnp

    from ..ops import pallas_attention as pk
    from .autotune import timeit

    if interpret is None:
        # off-TPU the kernels only run in interpret mode; timings there
        # are emulation, not device truth, but the plumbing still works
        interpret = pk.INTERPRET or jax.default_backend() != "tpu"
    dtype = jnp.dtype(dtype)
    if sm_scale is None:
        sm_scale = 1.0 / float(np.sqrt(head_dim))
    rng = np.random.RandomState(0)

    def mk():
        return jnp.asarray(
            rng.randn(batch, heads, seq, head_dim) * 0.3, dtype)

    q, k, v = mk(), mk(), mk()
    mask = (jnp.zeros((batch, 1, 1, seq), jnp.float32)
            if has_mask else None)

    def sync(out):
        first = out[0] if isinstance(out, tuple) else out
        return float(jnp.sum(first.astype(jnp.float32)))

    tel = _telemetry()
    out = {"shape": {"batch": batch, "heads": heads, "seq": seq,
                     "head_dim": head_dim, "dtype": dtype.name,
                     "causal": causal, "mask": has_mask},
           "blocks": {}}

    tuned = {}
    for kind in ("fwd", "fwd_lse", "bwd"):
        tuned[kind] = pk._tuned_block_sizes(
            kind, batch, heads, seq, head_dim, dtype, sm_scale, causal,
            has_mask, interpret)
        out["blocks"][kind] = list(tuned[kind])
    static = pk._block_sizes(seq, head_dim)

    def run_fwd(blocks, need_lse):
        bq, bk = blocks
        return lambda: pk._flash_attention_jit(
            q, k, v, mask, sm_scale, causal, interpret, bq, bk,
            need_lse)

    o, lse = pk._flash_attention_jit(q, k, v, mask, sm_scale, causal,
                                     interpret, *tuned["fwd_lse"], True)
    do = mk()

    def run_bwd(blocks):
        bq, bk = blocks
        return lambda: pk._flash_attention_bwd_jit(
            q, k, v, mask, o, lse, do, sm_scale, causal, interpret,
            bq, bk)

    plan = [("fwd_ms", run_fwd(tuned["fwd"], False), tuned["fwd"]),
            ("fwd_lse_ms", run_fwd(tuned["fwd_lse"], True),
             tuned["fwd_lse"]),
            ("bwd_ms", run_bwd(tuned["bwd"]), tuned["bwd"])]
    if include_static:
        plan += [("static_fwd_ms", run_fwd(static, False), static),
                 ("static_fwd_lse_ms", run_fwd(static, True), static),
                 ("static_bwd_ms", run_bwd(static), static)]
    for name, run, blocks in plan:
        t0 = tel.clock()
        wall0 = time.perf_counter()
        ms = timeit(run, sync, reps=reps, windows=2) * 1000
        out[name] = round(ms, 4)
        if tel.enabled:
            tel.complete(
                "attn_probe", t0,
                t0 + int((time.perf_counter() - wall0) * 1e9),
                args={"kernel": name[:-3], "ms": out[name],
                      "blocks": str(tuple(blocks)), "seq": seq,
                      "head_dim": head_dim, "dtype": dtype.name})
    return out


def attribute_step(step_ms, layers, fwd_ms, bwd_ms):
    """Split a measured training-step time into attention-forward,
    attention-backward and everything-else ("XLA remainder": matmuls,
    LN, softmax head, optimizer, data movement). ``fwd_ms``/``bwd_ms``
    are per-layer kernel times from :func:`probe_attention` — pass the
    ``fwd_lse_ms`` twin for a training step, since that is the kernel
    the fused-backward forward actually runs."""
    attn_fwd = layers * float(fwd_ms)
    attn_bwd = layers * float(bwd_ms)
    remainder = max(0.0, float(step_ms) - attn_fwd - attn_bwd)
    tel = _telemetry()
    tel.set_gauge("probe_attn_fwd_ms", attn_fwd)
    tel.set_gauge("probe_attn_bwd_ms", attn_bwd)
    tel.set_gauge("probe_attn_remainder_ms", remainder)
    return {"step_ms": round(float(step_ms), 3),
            "attn_fwd_ms": round(attn_fwd, 3),
            "attn_bwd_ms": round(attn_bwd, 3),
            "xla_remainder_ms": round(remainder, 3),
            "attn_fraction": round((attn_fwd + attn_bwd)
                                   / max(float(step_ms), 1e-9), 4)}


def main(argv=None):
    parser = argparse.ArgumentParser(
        prog="python -m hetu_tpu.tune.probe",
        description="time flash attention fwd/bwd kernels in isolation "
                    "and attribute a full step")
    parser.add_argument("--batch", type=int, default=8)
    parser.add_argument("--heads", type=int, default=8)
    parser.add_argument("--seq", type=int, default=2048)
    parser.add_argument("--head-dim", type=int, default=64)
    parser.add_argument("--dtype", default="bfloat16")
    parser.add_argument("--causal", action="store_true")
    parser.add_argument("--no-mask", dest="mask", action="store_false")
    parser.add_argument("--reps", type=int, default=5)
    parser.add_argument("--step-ms", type=float, default=None,
                        help="measured full-step ms to attribute")
    parser.add_argument("--layers", type=int, default=4)
    args = parser.parse_args(argv)
    out = probe_attention(args.batch, args.heads, args.seq,
                          args.head_dim, dtype=args.dtype,
                          causal=args.causal, has_mask=args.mask,
                          reps=args.reps)
    if args.step_ms is not None:
        out["attribution"] = attribute_step(
            args.step_ms, args.layers, out["fwd_lse_ms"], out["bwd_ms"])
    print(json.dumps(out, indent=1))
    return 0


if __name__ == "__main__":
    import sys
    sys.exit(main())
