"""KV-cache autoregressive decode for the GPT family.

``GPTDecoder`` lifts a trained ``GPTLMHeadModel`` checkpoint (or a live
``InferenceSession``) into a weight-level decode loop:

* **prefill**: one full causal forward over the prompt (the flash-
  attention path on TPU) that also writes the prompt's K/V rows into
  preallocated ``[B, H, S_max, D]`` buffers,
* **decode**: a jit-compiled cached single-token forward
  (``models/gpt.py:gpt_cached_step``) — write-index into the K/V
  buffers, position-indexed learned embeddings, no ``[S, S]`` mask —
  with the cache donated so the update happens in place in HBM,
* **generate**: greedy or temperature sampling, numerically pinned
  against the full-sequence graph forward (tests/test_serving.py).

Compile accounting: the decode step's jit cache keys only on batch size
(position is a traced scalar). Prefill keys on (batch, prompt length) —
so ``generate()`` buckets ragged prompt lengths (``prompt_buckets``,
power-of-two ladder by default) the same way ``InferenceSession``
buckets batch: a serving loop compiles once per (batch, prompt-bucket)
pair plus once per batch for the step, and never again. Bare
``prefill()`` calls are exact-shape by design (callers needing the
per-position prompt logits get exactly their length back).
"""
from __future__ import annotations

import functools
import os
import time

import numpy as np

import jax
import jax.numpy as jnp

from .. import telemetry as _telemetry
from ..models.gpt import (gpt_cached_step, gpt_prefill, gpt_serving_params,
                          init_kv_cache)
from .session import next_bucket

__all__ = ["GPTDecoder"]


class GPTDecoder:
    def __init__(self, config, lookup, max_len=None, prompt_buckets=None,
                 telemetry=None):
        """``lookup(name) -> array`` resolves checkpoint parameter names
        (see ``models/gpt.py:gpt_param_names``); use the classmethods for
        the common sources. ``prompt_buckets`` bounds generate()'s
        prefill compiles under ragged prompt lengths (None = powers of
        two)."""
        self.config = config
        self.prompt_buckets = (tuple(sorted(prompt_buckets))
                               if prompt_buckets else None)
        self.max_len = int(max_len or config.max_position_embeddings)
        if self.max_len > config.max_position_embeddings:
            raise ValueError(
                f"max_len {self.max_len} exceeds the model's learned "
                f"positions ({config.max_position_embeddings})")
        self.telemetry = _telemetry.resolve(telemetry)
        self.params = gpt_serving_params(config, lookup)
        nh = config.num_attention_heads
        act = getattr(config, "hidden_act", "gelu")
        # donate the kv argument: the cache buffers update in place
        self._prefill = jax.jit(
            functools.partial(gpt_prefill, num_heads=nh, hidden_act=act),
            donate_argnums=(1,))
        self._step = jax.jit(
            functools.partial(gpt_cached_step, num_heads=nh,
                              hidden_act=act),
            donate_argnums=(1,))

    # ------------------------------------------------------------------
    @classmethod
    def from_session(cls, session, config, **kw):
        """From a live :class:`InferenceSession` over the same model
        (shares the session's device-resident parameters)."""
        params = session.params_by_name()
        return cls(config, params.__getitem__, **kw)

    @classmethod
    def from_checkpoint(cls, config, path, **kw):
        """From an ``Executor.save`` checkpoint directory (frozen:
        reads only the per-parameter ``.npy`` files)."""
        def lookup(name):
            f = os.path.join(path, name + ".npy")
            if not os.path.exists(f):
                raise FileNotFoundError(
                    f"checkpoint {path} has no parameter {name!r} "
                    f"(expected {f})")
            return np.load(f)
        return cls(config, lookup, **kw)

    # ------------------------------------------------------------------
    def prefill(self, ids, real_len=None):
        """Prompt phase over ``ids [B, P]``: returns
        ``(logits [B, P, V], kv)`` with K/V rows ``0..P-1`` written.

        ``real_len`` is the true prompt length when ``ids`` arrives
        already bucket-padded (generate() passes it): the
        ``decode_prefill_tokens`` counter counts only REAL tokens, and
        the padding overhead lands in ``decode_prefill_pad_tokens`` so
        bucketing waste stays visible instead of inflating the work
        counter."""
        ids = jnp.asarray(ids, jnp.int32)
        kv = init_kv_cache(self.config, ids.shape[0], self.max_len)
        logits, kv = self._prefill(self.params, kv, ids)
        if self.telemetry.enabled:
            b, p = ids.shape
            real = b * min(int(real_len), p) if real_len is not None \
                else b * p
            self.telemetry.inc("decode_prefill_tokens", real)
            if b * p > real:
                self.telemetry.inc("decode_prefill_pad_tokens",
                                   b * p - real)
        return logits, kv

    def decode_step(self, kv, tokens, pos):
        """One cached step: ``tokens [B]`` at position ``pos``. Returns
        ``(logits [B, V], kv)``. The passed ``kv`` is consumed
        (donated)."""
        return self._step(self.params, kv, jnp.asarray(tokens, jnp.int32),
                          jnp.int32(pos))

    # ------------------------------------------------------------------
    def generate(self, prompts, max_new_tokens, temperature=0.0, seed=0,
                 return_prompt=False):
        """Autoregressive continuation of ``prompts [B, P]``.

        ``temperature=0`` is greedy argmax; otherwise tokens sample from
        ``softmax(logits / temperature)``. Returns ``[B, T]`` numpy
        (``[B, P+T]`` with ``return_prompt=True``)."""
        prompts = np.asarray(prompts)
        b, p = prompts.shape
        if p < 1:
            raise ValueError("generate() needs at least one prompt token")
        if max_new_tokens < 1:
            return prompts.copy() if return_prompt else \
                np.empty((b, 0), np.int32)
        if p + max_new_tokens > self.max_len:
            raise ValueError(
                f"prompt {p} + {max_new_tokens} new tokens exceeds the "
                f"decoder's max_len {self.max_len}")
        key = jax.random.PRNGKey(seed)
        tel = self.telemetry
        t_start = time.perf_counter() if tel.enabled else 0.0
        out = []            # device arrays; ONE host transfer at the end
        # prompt-length bucketing: prefill compiles once per (batch,
        # bucket), not once per exact length. The padded tail writes
        # junk K/V rows at positions >= p, but decode overwrites row j
        # at pos=j BEFORE the first step whose validity mask
        # (arange <= pos) admits it — generation from pos=p proceeds
        # sequentially, so no padded row is ever attended
        pb = min(next_bucket(p, self.prompt_buckets), self.max_len)
        if pb > p:
            pad = np.repeat(prompts[:, -1:], pb - p, axis=1)
            logits, kv = self.prefill(
                np.concatenate([prompts, pad], axis=1), real_len=p)
        else:
            logits, kv = self.prefill(prompts)
        last = logits[:, p - 1]
        if tel.enabled:
            # the same fleet-level TTFT histogram the continuous-
            # batching engine records, so the serving A/B compares
            # like-for-like; the block is the price of an honest
            # measurement under async dispatch (telemetry-on only)
            jax.block_until_ready(last)
            t_first = time.perf_counter()
            tel.observe("serve_ttft_ms", (t_first - t_start) * 1e3)
        for t in range(max_new_tokens):
            if temperature and temperature > 0.0:
                tok = jax.random.categorical(
                    jax.random.fold_in(key, t), last / temperature,
                    axis=-1)
            else:
                tok = jnp.argmax(last, axis=-1)
            tok = tok.astype(jnp.int32)
            out.append(tok)     # stays on device: no per-token sync
            if t + 1 < max_new_tokens:
                last, kv = self.decode_step(kv, tok, p + t)
        if tel.enabled:
            tel.inc("decode_tokens", b * max_new_tokens)
        gen = np.asarray(jnp.stack(out, axis=1))
        if tel.enabled:
            # host transfer above is the sync: all decode steps are done
            tel.observe("serve_tpot_ms",
                        (time.perf_counter() - t_first) * 1e3
                        / max(1, max_new_tokens - 1))
        if return_prompt:
            return np.concatenate([prompts, gen], axis=1)
        return gen
