"""Iteration-level continuous batching over the paged KV cache.

``GPTDecoder.generate`` + ``MicroBatcher`` is *request-level* batching:
a tick's requests fuse into one batch that prefills together, decodes
together, and finishes together — every sequence pays the longest
member's generation length, a late arrival waits for the whole batch,
and each batch allocates dense ``[B, H, S_max, D]`` cache buffers.

:class:`ContinuousBatchingEngine` schedules at *iteration* granularity
instead (Orca, OSDI '22), over the block-paged cache of
``serving/kvcache.py`` (vLLM's PagedAttention, SOSP '23). Every
scheduler step:

1. **finish** — sequences that produced their last token leave the
   batch immediately, resolve their Future, and free their KV blocks;
2. **admit** — waiting requests join while batch width and free KV
   blocks allow. Admission is the only gate on cache memory:
   ``admission="queue"`` (default) holds the FIFO head until blocks
   free up, ``admission="reject"`` fails its Future with
   :class:`EngineOverloaded` instead (load shedding at the engine). A
   request that could NEVER fit the pool raises
   :class:`~hetu_tpu.serving.kvcache.KVCacheExhausted` at submit;
3. **prefill** — newly admitted prompts run one causal forward
   (grouped per prompt bucket) that scatters their K/V rows into the
   pool via ``models/gpt.py:gpt_paged_prefill``;
4. **decode** — ALL running sequences take one token step in ONE jit
   program (``gpt_paged_step``): per-sequence position vectors make
   the batch ragged-safe, block tables make it gather from the pool.

**The HT901 contract is load-bearing here.** Sequences join/leave every
step, so naive shapes would retrace constantly. Instead every dispatch
snaps to precomputed ladders — batch width to the power-of-two ladder
(``session.py:next_bucket``), context length to a block-aligned ladder,
prompt length to the decoder's prompt ladder — so distinct jit
signatures are bounded by :attr:`compile_bound` =
``|batch| x (|prompt| + |ctx|)`` ladder products no matter how churny
the trace (the serving test measures exactly this).

``reserve="full"`` (default) allocates a request's whole
``prompt + max_new_tokens`` block budget at admission — no mid-decode
exhaustion, ever. ``reserve="lazy"`` allocates blocks as positions are
written (higher occupancy) and, on exhaustion, **preempts** the
youngest running sequence: its blocks free, it requeues at the waiting
head, and because sampling is keyed on ``(seed, token_index)`` the
recompute reproduces the exact tokens it lost.

**Prefix caching** (``prefix_cache=True``): admission resolves each
prompt against the pool's :class:`~hetu_tpu.serving.kvcache.PrefixCache`
— the cached prefix's blocks are *shared* (refcount bumped, zero new
blocks, zero prefill compute) and only the non-cached suffix is
allocated, charged, and prefilled (``gpt_paged_suffix_prefill`` starts
at the first non-cached position). Finished prefills publish their
blocks back to the cache; retired requests leave cached blocks resident
for the next hit (LRU-evicted only under allocation pressure). Shared
blocks are copy-on-write: a sequence extending into one (suffix prefill
into a shared tail, or the first decode write past a cache-frozen
prompt tail) copies it first, so sharers never see each other's writes
— with ``reserve="full"`` admission charges those copies up front and
the no-mid-decode-exhaustion guarantee stands; a genuine multi-sharer
shortfall preempts the youngest sequence exactly like lazy exhaustion.

**Chunked prefill** (``prefill_chunk=N``): a prompt longer than ``N``
non-cached tokens prefills one chunk per engine step, interleaved with
the running batch's decode, so one long cold prompt no longer stalls
TPOT for every running sequence. Chunk widths snap to their own pow2
ladder and the suffix-prefill program keys on (batch, chunk, ctx)
buckets, so :attr:`compile_bound` stays a finite ladder product —
HT901 holds with both features on.
"""
from __future__ import annotations

import collections
import contextlib
import functools
import itertools
import os
import threading
import time
from concurrent.futures import Future

import numpy as np

from .. import telemetry as _telemetry
from ..models.gpt import (gpt_paged_prefill, gpt_paged_step,
                          gpt_paged_suffix_prefill, gpt_serving_params)
from . import lifecycle as _lifecycle
from .kvcache import DEFAULT_BLOCK_SIZE, KVCacheExhausted, PagedKVCache
from .lifecycle import RequestTimeline, mint_request_id
from .router import SLOWindow
from .session import next_bucket

__all__ = ["ContinuousBatchingEngine", "EngineOverloaded"]


class EngineOverloaded(RuntimeError):
    """Admission control shed this request: the waiting queue is full,
    or ``admission="reject"`` and the KV pool can't hold it right now."""


def _pow2_ladder(start, cap):
    """Power-of-two ladder from ``start`` capped (and terminated) at
    ``cap`` — the finite bucket set one dispatch dimension snaps to."""
    ladder, b = [], max(1, int(start))
    while b < cap:
        ladder.append(b)
        b *= 2
    ladder.append(int(cap))
    return tuple(ladder)


def _choose_token(logits_row, temperature, seed, idx):
    """Greedy or temperature sampling, host-side. Randomness is keyed
    on ``(seed, token_index)`` — NOT on any global stream — so a
    preempted sequence's recompute reproduces the tokens it already
    produced."""
    if temperature and temperature > 0.0:
        z = logits_row.astype(np.float64) / float(temperature)
        z -= z.max()
        p = np.exp(z)
        p /= p.sum()
        rng = np.random.default_rng([int(seed) & 0xFFFFFFFF, int(idx)])
        return int(rng.choice(len(p), p=p))
    return int(np.argmax(logits_row))


class _Seq:
    __slots__ = ("id", "prompt", "max_new", "temperature", "seed",
                 "future", "generated", "pending", "n_written",
                 "t_submit", "preempts", "rid", "tl", "tokens_lost",
                 "cached_tokens", "prefill_pos")

    def __init__(self, sid, prompt, max_new, temperature, seed, rid,
                 tl):
        self.id = sid
        self.prompt = prompt
        self.max_new = int(max_new)
        self.temperature = float(temperature)
        self.seed = int(seed)
        self.future = Future()
        self.generated = []     # chosen tokens, pending included
        self.pending = None     # chosen but not yet written to the cache
        self.n_written = 0      # cache rows written (prompt + decode)
        self.t_submit = time.perf_counter()
        self.preempts = 0
        self.rid = rid          # request id (caller-supplied or minted)
        self.tl = tl            # RequestTimeline, None when tel disabled
        # tokens the last preemption threw away; while
        # len(generated) <= tokens_lost the sequence is re-earning them
        # (its episodes are "replay", and live introspection says so)
        self.tokens_lost = 0
        # prompt tokens the prefix cache resolved at admission (their
        # K/V was already resident — never recomputed)
        self.cached_tokens = 0
        # next prompt position to prefill; < len(prompt) means the
        # sequence is still in (possibly chunked) prefill
        self.prefill_pos = 0

    def prefilling(self):
        return self.prefill_pos < self.prompt.shape[0]

    def replaying(self):
        return self.tokens_lost > 0 and \
            len(self.generated) <= self.tokens_lost


class ContinuousBatchingEngine:
    """See the module docstring. ``lookup(name) -> array`` resolves
    checkpoint parameter names exactly as for
    :class:`~hetu_tpu.serving.decode.GPTDecoder`; use the classmethods
    for the common sources.

    With ``start=True`` (default) a daemon scheduler thread drives
    :meth:`step` whenever work exists; with ``start=False`` the caller
    drives ``step()`` directly (deterministic tests) — never both.

    ``submit()`` returns a Future resolving to the generated tokens as
    a 1-D int32 array of length ``max_new_tokens``."""

    def __init__(self, config, lookup, *, num_blocks=None,
                 block_size=DEFAULT_BLOCK_SIZE, budget=None, max_len=None,
                 max_batch_size=8, admission="queue", max_queue=256,
                 reserve="full", prefix_cache=False, prefill_chunk=None,
                 slo_p99_ms=None, slo_error_rate=None,
                 slo_window=128, slo_ttft_p99_ms=None, telemetry=None,
                 name="engine", start=True):
        import jax
        if admission not in ("queue", "reject"):
            raise ValueError(f"admission must be 'queue' or 'reject', "
                             f"got {admission!r}")
        if reserve not in ("full", "lazy"):
            raise ValueError(f"reserve must be 'full' or 'lazy', "
                             f"got {reserve!r}")
        if prefill_chunk is not None and int(prefill_chunk) < 1:
            raise ValueError(f"prefill_chunk must be >= 1, "
                             f"got {prefill_chunk}")
        self.config = config
        self.max_len = int(max_len or config.max_position_embeddings)
        if self.max_len > config.max_position_embeddings:
            raise ValueError(
                f"max_len {self.max_len} exceeds the model's learned "
                f"positions ({config.max_position_embeddings})")
        self.max_batch_size = int(max_batch_size)
        self.admission = admission
        self.max_queue = int(max_queue)
        self.reserve = reserve
        self.name = name
        self.telemetry = _telemetry.resolve(telemetry)
        self.slo = SLOWindow(slo_p99_ms, slo_error_rate, slo_window,
                             ttft_p99_ms=slo_ttft_p99_ms)
        self.prefix_cache = bool(prefix_cache)
        self.prefill_chunk = int(prefill_chunk) \
            if prefill_chunk is not None else None
        # prefix hits and chunking both mean "prefill from a token
        # offset into an existing table" — one suffix-prefill program
        # serves both, so either knob switches prefill onto it
        self._suffix_mode = self.prefix_cache \
            or self.prefill_chunk is not None
        self.params = gpt_serving_params(config, lookup)
        self.cache = PagedKVCache(config, num_blocks=num_blocks,
                                  block_size=block_size, budget=budget,
                                  telemetry=self.telemetry,
                                  prefix_cache=self.prefix_cache)
        # HT901 ladders: every dispatch dimension snaps to one of these,
        # so signatures stay bounded under per-step churn
        self.batch_buckets = _pow2_ladder(1, self.max_batch_size)
        self.prompt_buckets = _pow2_ladder(1, self.max_len)
        self.ctx_buckets = _pow2_ladder(self.cache.block_size,
                                        self.max_len)
        self.chunk_buckets = _pow2_ladder(
            1, min(self.prefill_chunk or self.max_len, self.max_len))
        nh = config.num_attention_heads
        act = getattr(config, "hidden_act", "gelu")
        self._prefill_fn = jax.jit(
            functools.partial(gpt_paged_prefill, num_heads=nh,
                              hidden_act=act), donate_argnums=(1,))
        self._step_fn = jax.jit(
            functools.partial(gpt_paged_step, num_heads=nh,
                              hidden_act=act), donate_argnums=(1,))
        self._sprefill_fn = jax.jit(
            functools.partial(gpt_paged_suffix_prefill, num_heads=nh,
                              hidden_act=act), donate_argnums=(1,))
        self._signatures = set()
        self._ids = itertools.count()
        self._waiting = collections.deque()
        self._running = []
        self._cond = threading.Condition()
        self._closed = False
        self._thread = None
        _lifecycle.register(self)   # crash-time in-flight dumps
        if start:
            self._thread = threading.Thread(
                target=self._loop, daemon=True, name=f"{name}-scheduler")
            self._thread.start()

    # ------------------------------------------------------------------
    @classmethod
    def from_session(cls, session, config, **kw):
        """From a live :class:`InferenceSession` over the same model
        (shares the session's device-resident parameters)."""
        params = session.params_by_name()
        return cls(config, params.__getitem__, **kw)

    @classmethod
    def from_checkpoint(cls, config, path, **kw):
        """From an ``Executor.save`` checkpoint directory."""
        def lookup(name):
            f = os.path.join(path, name + ".npy")
            if not os.path.exists(f):
                raise FileNotFoundError(
                    f"checkpoint {path} has no parameter {name!r} "
                    f"(expected {f})")
            return np.load(f)
        return cls(config, lookup, **kw)

    # ------------------------------------------------------------------
    @property
    def compile_bound(self):
        """The HT901 ladder-product bound on distinct jit signatures:
        prefill keys on (batch, prompt) buckets, decode on (batch, ctx)
        buckets, suffix prefill (prefix cache / chunked prefill) on
        (batch, chunk, ctx) buckets — churn can never compile more
        programs than this."""
        bound = len(self.batch_buckets) * (len(self.prompt_buckets)
                                           + len(self.ctx_buckets))
        if self._suffix_mode:
            bound += (len(self.batch_buckets) * len(self.chunk_buckets)
                      * len(self.ctx_buckets))
        return bound

    @property
    def jit_compiles(self):
        """Distinct jit signatures dispatched so far (always <=
        :attr:`compile_bound`; the serving test asserts it)."""
        return len(self._signatures)

    def health(self):
        """(healthy, reason) under the configured SLOs — the same probe
        contract as ``ServingHTTPServer.health`` / ``/healthz``, so the
        replica router treats engines and HTTP replicas uniformly."""
        return self.slo.health()

    def inflight_requests(self):
        """Live in-flight table (``GET /v1/requests`` and the
        crash-dump ``requests_rank<r>.json``): one row per waiting or
        running request — id, phase (waiting / preempted / running /
        replay), tokens done vs budget, KV blocks held, preemption
        count, age. Works with telemetry disabled."""
        now = time.perf_counter()
        with self._cond:
            snap = [(s, "waiting" if s.preempts == 0 else "preempted")
                    for s in self._waiting]
            snap += [(s, "replay" if s.replaying() else "running")
                     for s in self._running]
        tables = self.cache.tables
        return [{"request_id": s.rid,
                 "phase": phase,
                 "tokens_done": len(s.generated),
                 "tokens_budget": s.max_new,
                 "kv_blocks": len(tables.get(s.id, ())),
                 "cached_tokens": s.cached_tokens,
                 "preempts": s.preempts,
                 "age_ms": round((now - s.t_submit) * 1e3, 3)}
                for s, phase in snap]

    def stats(self):
        """One engine snapshot for ``GET /stats``: queue depths, KV
        pressure, HT901 compile accounting, SLO verdict."""
        with self._cond:
            running, waiting = len(self._running), len(self._waiting)
        healthy, reason = self.health()
        out = {"name": self.name,
               "kind": "ContinuousBatchingEngine",
               "running": running,
               "waiting": waiting,
               "max_batch_size": self.max_batch_size,
               "admission": self.admission,
               "reserve": self.reserve,
               "kv_blocks": self.cache.num_blocks,
               "kv_blocks_used": self.cache.used_blocks,
               "kv_hbm_utilization": round(self.cache.utilization, 4),
               "jit_compiles": self.jit_compiles,
               "compile_bound": self.compile_bound,
               "healthy": healthy,
               "health_reason": reason}
        out["prefix_cache"] = self.prefix_cache
        out["prefill_chunk"] = self.prefill_chunk
        if self.prefix_cache:
            # utilization above counts only sequence-referenced blocks;
            # the cached-unreferenced remainder is reclaimable HBM
            out["kv_blocks_cached"] = self.cache.cached_blocks
            out["kv_hbm_utilization_cached"] = round(
                self.cache.cached_utilization, 4)
            out["serve_prefix_hit_rate"] = round(
                self.cache.prefix.hit_rate(), 4)
            out["serve_cow_copies"] = self.cache.cow_copies
            out["serve_prefix_evictions"] = self.cache.prefix.evictions
        return out

    # ------------------------------------------------------------------
    def submit(self, prompt, max_new_tokens, temperature=0.0, seed=0,
               request_id=None):
        """Enqueue one request; returns a Future resolving to the
        generated tokens (1-D int32, length ``max_new_tokens``).

        ``request_id`` is the end-to-end tracing id (minted here when
        the caller — HTTP ingress, router — didn't supply one); every
        lifecycle span, in-flight table row, and flight-ring event for
        this request carries it."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        p = prompt.shape[0]
        if p < 1:
            raise ValueError("submit() needs at least one prompt token")
        if max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        if p + int(max_new_tokens) > self.max_len:
            raise ValueError(
                f"prompt {p} + {max_new_tokens} new tokens exceeds the "
                f"engine's max_len {self.max_len}")
        if not self.cache.fits_at_all(p + int(max_new_tokens)):
            # no amount of queueing serves this: the pool is too small
            raise KVCacheExhausted(
                f"request of {p}+{max_new_tokens} tokens needs "
                f"{self.cache.allocator.blocks_for_tokens(p + int(max_new_tokens))} "
                f"blocks; the pool has {self.cache.num_blocks}")
        tel = self.telemetry
        rid = str(request_id) if request_id is not None \
            else mint_request_id()
        tl = RequestTimeline(rid, time.perf_counter_ns()) \
            if tel.enabled else None
        seq = _Seq(next(self._ids), prompt, max_new_tokens, temperature,
                   seed, rid, tl)
        if tel.enabled:
            tel.flight_record("serve", "submit", tag=rid)
        with self._cond:
            if self._closed:
                raise RuntimeError("engine closed")
            if len(self._waiting) >= self.max_queue:
                raise EngineOverloaded(
                    f"waiting queue full ({self.max_queue} requests)")
            self._waiting.append(seq)
            self._set_depth_locked()
            self._cond.notify()
        return seq.future

    def _set_depth_locked(self):
        if self.telemetry.enabled:
            self.telemetry.set_gauge(f"{self.name}_queue_depth",
                                     len(self._waiting))

    # ------------------------------------------------------------------
    def step(self):
        """One scheduler iteration (admit -> prefill -> decode ->
        finish); returns the number of sequences still running."""
        tel = self.telemetry
        t0 = time.perf_counter()
        with self._cond:
            admitted = self._admit_locked()
        if not admitted and not self._running:
            return 0
        width = len(self._running)
        cm = tel.span("step", subgraph="serving_engine") \
            if tel.enabled else contextlib.nullcontext()
        with cm:
            if self._suffix_mode:
                # chunked/prefix prefill: EVERY still-prefilling
                # sequence (not just this step's admissions) computes
                # one chunk, then the running batch decodes — long cold
                # prompts interleave with decode instead of stalling it
                prefilling = [s for s in self._running if s.prefilling()]
                if prefilling:
                    self._prefill_suffix_step(prefilling)
            elif admitted:
                self._prefill_admitted(admitted)
            self._finish_done()
            if self._running:
                self._decode_once()
                self._finish_done()
        if tel.enabled:
            tel.observe(f"{self.name}_step_ms",
                        (time.perf_counter() - t0) * 1e3)
            tel.observe(f"{self.name}_batch_width", width)
        return len(self._running)

    def _can_admit_locked(self, seq, reserve_tokens):
        """Block check for one admission. Without a prefix cache this is
        plain free-list arithmetic; with one, the request is charged
        only its non-cached remainder plus the copy-on-write spares its
        writes into shared blocks will need, against free + evictable
        blocks (matched blocks excluded — sharing them un-LRUs them
        before any eviction could touch them)."""
        if not self.prefix_cache:
            return self.cache.can_admit(reserve_tokens)
        need = self.cache.admit_blocks_needed(seq.prompt, reserve_tokens)
        matched, _ = self.cache.match_prefix(seq.prompt)
        evictable = max(0, self.cache.cached_blocks - len(matched))
        return need <= self.cache.allocator.available + evictable

    def _admit_locked(self):
        admitted = []
        while self._waiting and \
                len(self._running) + len(admitted) < self.max_batch_size:
            seq = self._waiting[0]
            p = seq.prompt.shape[0]
            reserve_tokens = p + (seq.max_new
                                  if self.reserve == "full" else 0)
            if not self._can_admit_locked(seq, reserve_tokens):
                if self.admission == "reject":
                    self._waiting.popleft()
                    seq.future.set_exception(EngineOverloaded(
                        f"KV admission rejected request: "
                        f"{self.cache.allocator.blocks_for_tokens(reserve_tokens)} "
                        f"block(s) needed, "
                        f"{self.cache.allocator.available} free"))
                    continue
                # queue policy: the FIFO head waits for blocks — later
                # arrivals never jump it (no starvation)
                break
            self._waiting.popleft()
            if self.prefix_cache:
                _, cached = self.cache.add_seq_prefix(
                    seq.id, reserve_tokens, seq.prompt)
                seq.cached_tokens = cached
                seq.prefill_pos = cached
                seq.n_written = cached   # cached rows are resident
                if cached and self.telemetry.enabled:
                    self.telemetry.inc(
                        f"{self.name}_prefill_cached_tokens", cached)
            else:
                self.cache.add_seq(seq.id, reserve_tokens)
                seq.cached_tokens = 0
                seq.prefill_pos = 0
            admitted.append(seq)
        self._set_depth_locked()
        self._running.extend(admitted)
        if admitted and self.telemetry.enabled:
            # close each admitted sequence's waiting episode: queue on
            # first admission, replay-wait after a preemption bounce
            now = time.perf_counter_ns()
            for s in admitted:
                if s.tl is not None:
                    s.tl.note("queue" if s.preempts == 0 else "replay",
                              s.tl.t_wait_start, now)
                    self.telemetry.flight_record("serve", "admit",
                                                 tag=s.rid)
        return admitted

    # ------------------------------------------------------------------
    def _dispatch(self, key, fn, *args):
        """Run one jit program, accounting compiles the way the
        executor does (HT901's runtime half): first sighting of a
        signature key incs ``jit_compiles`` under a ``jit_compile``
        span, steady-state dispatches ride ``device_dispatch``."""
        tel = self.telemetry
        if key not in self._signatures:
            self._signatures.add(key)
            if tel.enabled:
                with tel.span("jit_compile", subgraph="serving_engine",
                              shape_key=str(key)):
                    out = fn(*args)
                tel.inc("jit_compiles")
                return out
            return fn(*args)
        if tel.enabled:
            with tel.span("device_dispatch", subgraph="serving_engine"):
                return fn(*args)
        return fn(*args)

    def _prefill_admitted(self, admitted):
        import jax.numpy as jnp
        tel = self.telemetry
        groups = {}
        for s in admitted:
            pb = next_bucket(s.prompt.shape[0], self.prompt_buckets)
            groups.setdefault(pb, []).append(s)
        for pb, group in sorted(groups.items()):
            bb = next_bucket(len(group), self.batch_buckets)
            ids = np.zeros((bb, pb), np.int32)
            slots = np.zeros((bb, pb), np.int32)   # 0 = scratch block
            for i, s in enumerate(group):
                p = s.prompt.shape[0]
                ids[i, :p] = s.prompt
                ids[i, p:] = s.prompt[-1]   # edge pad stays in-vocab
                slots[i, :p] = self.cache.slot_mapping(s.id, 0, p)
            t0 = time.perf_counter_ns() if tel.enabled else 0
            logits, pools = self._dispatch(
                ("prefill", bb, pb), self._prefill_fn, self.params,
                self.cache.pools, jnp.asarray(ids), jnp.asarray(slots))
            self.cache.pools = pools
            last = np.asarray(
                logits[jnp.arange(len(group)),
                       jnp.asarray([s.prompt.shape[0] - 1
                                    for s in group])])
            # episode ends AFTER the host sync above — the wall between
            # t0 and t1 is the prefill compute each member rode
            t1 = time.perf_counter_ns() if tel.enabled else 0
            for i, s in enumerate(group):
                p = s.prompt.shape[0]
                tok = _choose_token(last[i], s.temperature, s.seed, 0)
                s.generated.append(tok)
                s.pending = tok
                s.n_written = p
                s.prefill_pos = p
                if s.tl is not None:
                    s.tl.note("replay" if s.replaying() else "prefill",
                              t0, t1, {"cached_tokens": 0,
                                       "computed_tokens": p})
                    if s.tl.t_first_token is None:
                        s.tl.t_first_token = t1     # TTFT point
            if tel.enabled:
                real = sum(s.prompt.shape[0] for s in group)
                tel.inc(f"{self.name}_prefill_tokens", real)
                tel.inc(f"{self.name}_prefill_pad_tokens",
                        bb * pb - real)
                tel.inc(f"{self.name}_tokens", len(group))

    def _cow_or_preempt(self, s, start, stop):
        """Copy-on-write the blocks positions ``[start, stop)`` touch
        before ``s`` writes them, preempting the youngest running
        sequence when the copy can't be allocated (same victim policy
        as lazy-reserve exhaustion; the victim replays exactly).
        Returns False when ``s`` itself was the last resort victim."""
        while True:
            try:
                self.cache.ensure_writable(s.id, start, stop)
                return True
            except KVCacheExhausted:
                victim = self._running[-1]
                self._preempt(victim)
                if victim is s:
                    return False

    def _prefill_suffix_step(self, prefilling):
        """One chunk of prompt prefill per still-prefilling sequence:
        the prefix-cache/chunked path (``gpt_paged_suffix_prefill``).
        Each sequence computes ``min(prefill_chunk, remaining)`` tokens
        from its ``prefill_pos`` — the first non-cached position on a
        fresh admission — grouped per chunk bucket; the final chunk
        samples token 0 and publishes the prompt's blocks to the prefix
        cache."""
        import jax.numpy as jnp
        tel = self.telemetry
        chunk = self.prefill_chunk or self.max_len
        groups = {}
        for s in prefilling:
            if s not in self._running:
                continue        # preempted by an earlier group's CoW
            w = min(chunk, s.prompt.shape[0] - s.prefill_pos)
            # shared blocks this chunk writes into copy FIRST, so the
            # write slots below point at private storage
            if not self._cow_or_preempt(s, s.prefill_pos,
                                        s.prefill_pos + w):
                continue
            cw = next_bucket(w, self.chunk_buckets)
            groups.setdefault(cw, []).append((s, w))
        for cw, group in sorted(groups.items()):
            group = [(s, w) for s, w in group if s in self._running]
            if not group:
                continue
            bb = next_bucket(len(group), self.batch_buckets)
            sb = next_bucket(max(s.prefill_pos + w for s, w in group),
                             self.ctx_buckets)
            ids = np.zeros((bb, cw), np.int32)
            starts = np.zeros(bb, np.int32)
            write_slots = np.zeros((bb, cw), np.int32)  # 0 = scratch
            slot_grid = np.zeros((bb, sb), np.int32)
            slot_grid[:len(group)] = self.cache.gather_slots(
                [s.id for s, _ in group], sb)
            for i, (s, w) in enumerate(group):
                pos = s.prefill_pos
                ids[i, :w] = s.prompt[pos:pos + w]
                ids[i, w:] = s.prompt[pos + w - 1]   # edge pad in-vocab
                starts[i] = pos
                write_slots[i, :w] = self.cache.slot_mapping(
                    s.id, pos, pos + w)
            t0 = time.perf_counter_ns() if tel.enabled else 0
            logits, pools = self._dispatch(
                ("sprefill", bb, cw, sb), self._sprefill_fn,
                self.params, self.cache.pools, jnp.asarray(ids),
                jnp.asarray(starts), jnp.asarray(slot_grid),
                jnp.asarray(write_slots))
            self.cache.pools = pools
            finishing = [(i, s, w) for i, (s, w) in enumerate(group)
                         if s.prefill_pos + w >= s.prompt.shape[0]]
            last = np.asarray(
                logits[jnp.asarray([i for i, _, _ in finishing]),
                       jnp.asarray([w - 1 for _, _, w in finishing])]) \
                if finishing else None
            t1 = time.perf_counter_ns() if tel.enabled else 0
            for j, (i, s, w) in enumerate(finishing):
                tok = _choose_token(last[j], s.temperature, s.seed, 0)
                s.generated.append(tok)
                s.pending = tok
            cached_resolved = 0
            for i, (s, w) in enumerate(group):
                first_chunk = s.prefill_pos == s.cached_tokens
                if first_chunk:
                    cached_resolved += s.cached_tokens
                s.prefill_pos += w
                s.n_written = s.prefill_pos
                if s.tl is not None:
                    s.tl.note(
                        "replay" if s.replaying() else "prefill", t0, t1,
                        {"cached_tokens": s.cached_tokens
                         if first_chunk else 0, "computed_tokens": w})
                if not s.prefilling():
                    # prompt fully resident: publish it for later hits
                    # (the cache freezes these blocks; the first decode
                    # write past the tail copy-on-writes)
                    self.cache.insert_prefix(s.id, s.prompt)
                    if s.tl is not None and s.tl.t_first_token is None:
                        s.tl.t_first_token = t1     # TTFT point
            if tel.enabled:
                computed = sum(w for _, w in group)
                tel.complete("serve_prefill_chunk", t0, t1,
                             {"seqs": len(group),
                              "tokens": int(computed),
                              "bucket": int(cw),
                              "cached": int(cached_resolved)})
                tel.inc(f"{self.name}_prefill_tokens", computed)
                tel.inc(f"{self.name}_prefill_pad_tokens",
                        bb * cw - computed)
                tel.inc(f"{self.name}_tokens", len(finishing))

    def _ensure_capacity_lazy(self, active):
        """Lazy-reserve growth: make every active sequence's table
        cover its write position, preempting the youngest running
        sequence on exhaustion. Returns the surviving active list."""
        for s in list(active):
            if s not in self._running:
                continue            # already preempted as a victim
            while s.n_written + 1 > self.cache.capacity_tokens(s.id):
                try:
                    self.cache.extend_seq(s.id, s.n_written + 1)
                except KVCacheExhausted:
                    victim = self._running[-1]
                    self._preempt(victim)
                    if victim is s:
                        break
        return [s for s in active if s in self._running]

    def _preempt(self, victim):
        """Free a sequence's blocks and requeue it at the waiting head;
        recompute reproduces its tokens ((seed, index)-keyed
        sampling)."""
        self.cache.free_seq(victim.id)
        lost = len(victim.generated)
        victim.tokens_lost = lost
        victim.generated = []
        victim.pending = None
        victim.n_written = 0
        victim.prefill_pos = 0
        victim.cached_tokens = 0
        victim.preempts += 1
        with self._cond:
            self._running.remove(victim)
            self._waiting.appendleft(victim)
            self._set_depth_locked()
        if self.telemetry.enabled:
            self.telemetry.inc(f"{self.name}_preemptions")
            self.telemetry.instant("serve_preempt",
                                   request_id=victim.rid, tokens=lost)
            self.telemetry.flight_record("serve", "preempt",
                                         tag=victim.rid)
            if victim.tl is not None:
                # the replay-wait episode starts now and closes at
                # re-admission (_admit_locked)
                victim.tl.t_wait_start = time.perf_counter_ns()

    def _decode_once(self):
        import jax.numpy as jnp
        active = [s for s in self._running
                  if len(s.generated) < s.max_new
                  and not s.prefilling()]
        if self.reserve == "lazy":
            active = self._ensure_capacity_lazy(active)
        if self.prefix_cache:
            # the first write past a cached/frozen prompt tail lands in
            # a shared block — copy it before computing write slots
            # (reserve="full" admission pre-charged this block)
            for s in list(active):
                if s in self._running:
                    self._cow_or_preempt(s, s.n_written, s.n_written + 1)
            active = [s for s in active if s in self._running]
        if not active:
            return
        bb = next_bucket(len(active), self.batch_buckets)
        cb = next_bucket(max(s.n_written for s in active) + 1,
                         self.ctx_buckets)
        tokens = np.zeros(bb, np.int32)
        positions = np.zeros(bb, np.int32)
        write_slots = np.zeros(bb, np.int32)       # 0 = scratch block
        slot_grid = np.zeros((bb, cb), np.int32)
        slot_grid[:len(active)] = self.cache.gather_slots(
            [s.id for s in active], cb)
        for i, s in enumerate(active):
            tokens[i] = s.pending
            positions[i] = s.n_written
            write_slots[i] = self.cache.slot_of(s.id, s.n_written)
        tel = self.telemetry
        t0 = time.perf_counter_ns() if tel.enabled else 0
        logits, pools = self._dispatch(
            ("decode", bb, cb), self._step_fn, self.params,
            self.cache.pools, jnp.asarray(tokens),
            jnp.asarray(positions), jnp.asarray(slot_grid),
            jnp.asarray(write_slots))
        self.cache.pools = pools
        last = np.asarray(logits[:len(active)])
        t1 = time.perf_counter_ns() if tel.enabled else 0
        for i, s in enumerate(active):
            s.n_written += 1
            tok = _choose_token(last[i], s.temperature, s.seed,
                                len(s.generated))
            s.generated.append(tok)
            s.pending = tok
            if s.tl is not None:
                # a preempted sequence re-earning lost tokens is in
                # "replay", not "decode" — the doctor's replay bucket
                s.tl.note("replay" if s.replaying() else "decode",
                          t0, t1)
        if tel.enabled:
            tel.inc(f"{self.name}_tokens", len(active))

    def _finish_done(self):
        tel = self.telemetry
        with self._cond:
            done = [s for s in self._running
                    if len(s.generated) >= s.max_new]
            for s in done:
                self._running.remove(s)
        for s in done:
            self.cache.free_seq(s.id)
            ms = (time.perf_counter() - s.t_submit) * 1e3
            ttft_ms = None
            if s.tl is not None:
                t_retire = time.perf_counter_ns()
                _lifecycle.emit_request(tel, s.tl, t_retire,
                                        len(s.generated), s.preempts)
                tel.flight_record("serve", "retire", tag=s.rid)
                if s.tl.t_first_token is not None:
                    ttft_ms = (s.tl.t_first_token - s.tl.t_submit) / 1e6
                    tel.observe("serve_ttft_ms", ttft_ms)
                    tel.observe(
                        "serve_tpot_ms",
                        (t_retire - s.tl.t_first_token) / 1e6
                        / max(1, len(s.generated) - 1))
                tel.observe("serve_queue_wait_ms",
                            sum(t1 - t0
                                for ph, t0, t1, _ in s.tl.episodes
                                if ph == "queue") / 1e6)
                tel.observe("serve_preempts", s.preempts)
            self.slo.note(True, ms, ttft_ms=ttft_ms)
            if tel.enabled:
                tel.observe(f"{self.name}_latency_ms", ms)
                tel.inc(f"{self.name}_requests")
            s.future.set_result(
                np.asarray(s.generated[:s.max_new], np.int32))

    # ------------------------------------------------------------------
    def _loop(self):
        try:
            while True:
                with self._cond:
                    while not self._closed and not self._waiting \
                            and not self._running:
                        self._cond.wait()
                    if self._closed and not self._waiting \
                            and not self._running:
                        return
                    if self._closed:
                        break       # drain what's in flight, then fail
                self.step()
        except BaseException as e:  # noqa: BLE001 — scheduler died
            self._fail_outstanding(
                RuntimeError(f"engine scheduler died: {e!r}"))
            raise
        # closed with work outstanding: fail it rather than hang callers
        self._fail_outstanding(RuntimeError("engine closed"))

    def _fail_outstanding(self, exc):
        with self._cond:
            self._closed = True
            leftovers = list(self._waiting) + list(self._running)
            self._waiting.clear()
            self._running.clear()
            self._cond.notify_all()
        for s in leftovers:
            self.cache.free_seq(s.id)
            if not s.future.done():
                s.future.set_exception(exc)

    def close(self):
        """Stop the scheduler; outstanding Futures fail with
        "engine closed". Idempotent."""
        with self._cond:
            self._closed = True
            self._cond.notify_all()
        if self._thread is not None:
            self._thread.join()
            self._thread = None
        self._fail_outstanding(RuntimeError("engine closed"))

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False
