"""Online inference: the subsystem that turns a trained checkpoint into
something that answers requests.

The training stack (executor/PS/telemetry) already owns compilation,
checkpoints and sparse tables; serving composes them into four pieces:

* :class:`~hetu_tpu.serving.session.InferenceSession` — frozen-graph
  sessions over eval nodes + an ``Executor.save`` checkpoint dir, with
  mandatory shape bucketing so ragged traffic cannot cause a retrace
  storm (``jit_compiles`` is bounded by the bucket count).
* :class:`~hetu_tpu.serving.batcher.MicroBatcher` — thread-safe dynamic
  micro-batching: concurrent ``submit()`` calls coalesce into one padded
  batch per tick (``max_batch_size`` / ``max_wait_ms``), results split
  back per request, queue-depth / latency / occupancy metrics exported
  through ``hetu_tpu/telemetry/metrics.py``.
* :class:`~hetu_tpu.serving.decode.GPTDecoder` — KV-cache autoregressive
  decode for the GPT family (prefill on the flash-attention path, O(S)
  single-token steps, greedy + temperature sampling), numerically pinned
  against the full-sequence graph forward.
* :mod:`~hetu_tpu.serving.embedding` — PS-backed sparse serving: eval
  graphs rewritten to pull embedding rows from the parameter server
  read-only (a push from a serving session raises), with a host row
  cache and hit-rate gauge.
* :class:`~hetu_tpu.serving.http.ServingHTTPServer` — minimal stdlib
  JSON frontend over a session or batcher (``/v1/predict``, ``/healthz``,
  ``/metrics``).
* the continuous-batching plane —
  :class:`~hetu_tpu.serving.kvcache.PagedKVCache` (block-paged pooled
  K/V + free-list allocator, HBM-budgeted via HT4xx),
  :class:`~hetu_tpu.serving.scheduler.ContinuousBatchingEngine`
  (iteration-level join/leave scheduling over the paged cache, HT901
  bucketed jit signatures, KV-block admission control), and
  :class:`~hetu_tpu.serving.router.ReplicaRouter` (SLO-probed
  least-inflight routing + load shedding over N replicas).
* :mod:`~hetu_tpu.serving.lifecycle` — request-level observability:
  end-to-end request ids minted at ingress and propagated through
  router/engine/batcher, per-request phase timelines exported as
  ``serve_request``/``serve_phase`` trace spans (the serving doctor's
  input: ``python -m hetu_tpu.telemetry.doctor --serving``), live
  ``inflight_requests()``/``stats()`` introspection behind
  ``GET /v1/requests`` and ``GET /stats``, and crash-time
  ``requests_rank<r>.json`` dumps the black-box analyzer ingests.
"""
from .session import InferenceSession, next_bucket
from .batcher import MicroBatcher
from .decode import GPTDecoder
from .embedding import ReadOnlyPSClient, serve_embeddings_from_ps
from .http import ServingHTTPServer
from .kvcache import (BlockAllocator, KVCacheExhausted, PagedKVCache,
                      PrefixCache)
from .lifecycle import RequestTimeline, mint_request_id
from .router import ReplicaRouter, RouterOverloaded, SLOWindow
from .scheduler import ContinuousBatchingEngine, EngineOverloaded

__all__ = ["InferenceSession", "MicroBatcher", "GPTDecoder",
           "ReadOnlyPSClient", "serve_embeddings_from_ps",
           "ServingHTTPServer", "next_bucket",
           "BlockAllocator", "KVCacheExhausted", "PagedKVCache",
           "PrefixCache",
           "ContinuousBatchingEngine", "EngineOverloaded",
           "ReplicaRouter", "RouterOverloaded", "SLOWindow",
           "RequestTimeline", "mint_request_id"]
