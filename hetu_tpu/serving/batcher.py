"""Dynamic micro-batching (Orca/TF-Serving shape, host-side tick loop).

Concurrent ``submit()`` calls coalesce into ONE forward per tick: the
batcher thread claims up to ``max_batch_size`` rows, waiting at most
``max_wait_ms`` for stragglers after the first request arrives, then
concatenates the feeds along the batch dim, runs ``serve_fn`` once, and
splits the outputs back per request. Requests up to ``max_batch_size``
stay whole — their rows never split across ticks; wider requests split
server-side into adjacent chunks that resolve through one Future.

Telemetry (through ``hetu_tpu/telemetry/metrics.py``): ``<name>_queue_depth``
gauge, ``<name>_latency_ms`` p50/p95/p99 histogram (submit -> result),
``<name>_batch_size`` / ``<name>_batch_occupancy`` histograms, and
``<name>_requests`` / ``<name>_batches`` counters — plus the
fleet-level ``serve_queue_wait_ms`` histogram (submit -> tick claim),
the same bucket the continuous-batching engine records, so the serving
A/B compares queue wait like-for-like.
"""
from __future__ import annotations

import collections
import functools
import threading
import time
from concurrent.futures import Future

import numpy as np

from .. import telemetry as _telemetry
from . import lifecycle as _lifecycle
from .lifecycle import mint_request_id

__all__ = ["MicroBatcher"]


def _stitch_chunks(results, n):
    """Reassemble per-chunk serve outputs into one request's view:
    row-sliced outputs (chunk first-dims summing to ``n``) concatenate
    back in chunk order; whole-batch passthrough outputs (the
    ``_serve`` non-sliceable case) are identical per chunk, so the
    first chunk's copy stands for the request."""
    single = not isinstance(results[0], (list, tuple))
    width = 1 if single else len(results[0])
    out = []
    for j in range(width):
        pieces = [r if single else r[j] for r in results]
        if all(getattr(p, "ndim", 0) for p in pieces) and \
                sum(p.shape[0] for p in pieces) == n:
            out.append(np.concatenate([np.asarray(p) for p in pieces]))
        else:
            out.append(pieces[0])
    return out[0] if single else out


class _Request:
    __slots__ = ("feeds", "n", "future", "t_submit", "rid")

    def __init__(self, feeds, n, future, rid):
        self.feeds = feeds
        self.n = n
        self.future = future
        self.t_submit = time.perf_counter()
        self.rid = rid


class MicroBatcher:
    """Coalesce concurrent requests into one forward per tick.

    ``serve_fn(feeds)`` takes ``{key: np.ndarray}`` with a shared leading
    batch dim and returns an array, or a list/tuple of arrays, each with
    that same leading dim (an ``InferenceSession.predict`` bound method
    fits directly; so does a jitted decode step)."""

    def __init__(self, serve_fn, *, max_batch_size=32, max_wait_ms=2.0,
                 telemetry=None, name="serve"):
        self.serve_fn = serve_fn
        self.max_batch_size = int(max_batch_size)
        self.max_wait = float(max_wait_ms) / 1e3
        self.telemetry = _telemetry.resolve(telemetry)
        self.name = name
        self._queue = collections.deque()
        self._cond = threading.Condition()
        self._closed = False
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name=f"{name}-batcher")
        _lifecycle.register(self)   # crash-time in-flight dumps
        self._thread.start()

    # ------------------------------------------------------------------
    def submit(self, feeds, request_id=None):
        """Enqueue one request (each value ``[n, ...]``); returns a
        Future resolving to ``serve_fn``'s output sliced to this
        request's rows. ``request_id`` is the end-to-end tracing id
        (minted here when the caller didn't supply one)."""
        arrays = {k: np.asarray(v) for k, v in feeds.items()}
        sizes = {v.shape[0] for v in arrays.values() if v.ndim}
        if len(sizes) != 1:
            raise ValueError(
                f"request feeds disagree on batch size: {sorted(sizes)}")
        n = sizes.pop()
        rid = str(request_id) if request_id is not None \
            else mint_request_id()
        if n > self.max_batch_size:
            # oversized requests split server-side across ticks: the
            # chunks enqueue adjacently (FIFO keeps row order), and ONE
            # combining Future stitches the per-chunk outputs back in
            # request row order
            return self._submit_split(arrays, n, rid)
        req = _Request(arrays, n, Future(), rid)
        with self._cond:
            # submit/close race contract (pinned by the racecheck
            # stress test): a submit that wins the lock before close()
            # flips _closed is queued and WILL be served by the drain
            # loop; one that loses raises here — never hangs, never
            # silently drops
            if self._closed:
                raise RuntimeError("batcher closed")
            self._queue.append(req)
            self._set_depth()
            self._cond.notify()
        return req.future

    def _submit_split(self, arrays, n, rid):
        """Split an ``n > max_batch_size`` request into consecutive
        chunks enqueued atomically (they stay adjacent in the FIFO, so
        the rows come back in submission order even when they land in
        different ticks) and return ONE Future resolving to the stitched
        outputs. The first chunk failure fails the whole request; every
        chunk carries the parent's request id."""
        size = self.max_batch_size
        chunks = []
        for off in range(0, n, size):
            sub = {k: (v[off:off + size] if v.ndim else v)
                   for k, v in arrays.items()}
            chunks.append(_Request(sub, min(size, n - off), Future(),
                                   rid))
        combined = Future()
        state_lock = threading.Lock()
        pending = [len(chunks)]
        results = [None] * len(chunks)

        def _done(i, fut):
            with state_lock:
                exc = fut.exception()
                if exc is not None:
                    if not combined.done():
                        combined.set_exception(exc)
                    return
                results[i] = fut.result()
                pending[0] -= 1
                if pending[0] == 0 and not combined.done():
                    try:
                        combined.set_result(_stitch_chunks(results, n))
                    except Exception as e:          # noqa: BLE001
                        combined.set_exception(e)

        with self._cond:
            if self._closed:
                raise RuntimeError("batcher closed")
            self._queue.extend(chunks)
            self._set_depth()
            self._cond.notify_all()
        if self.telemetry.enabled:
            self.telemetry.inc(f"{self.name}_split_requests")
        for i, req in enumerate(chunks):
            req.future.add_done_callback(functools.partial(_done, i))
        return combined

    def _set_depth(self):
        if self.telemetry.enabled:
            self.telemetry.set_gauge(f"{self.name}_queue_depth",
                                     len(self._queue))

    # ------------------------------------------------------------------
    def _take_tick(self):
        """Block for the next tick's requests (None = closed + drained)."""
        with self._cond:
            while not self._queue:
                if self._closed:
                    return None
                self._cond.wait()
            # the wait budget runs from the FIRST request's submit, not
            # from when this thread got around to looking — a request
            # that already queued behind a slow tick must not wait the
            # full max_wait again
            deadline = self._queue[0].t_submit + self.max_wait
            batch, total = [], 0
            keys = frozenset(self._queue[0].feeds)
            try:
                while True:
                    while self._queue and \
                            frozenset(self._queue[0].feeds) == keys and \
                            (not batch
                             or total + self._queue[0].n
                             <= self.max_batch_size):
                        req = self._queue.popleft()
                        batch.append(req)
                        total += req.n
                    if total >= self.max_batch_size or self._closed:
                        break
                    if self._queue:
                        # head doesn't fit, or carries a DIFFERENT
                        # feed-key set (coalescing it would drop its
                        # extra keys): it starts the next tick
                        break
                    remaining = deadline - time.perf_counter()
                    if remaining <= 0:
                        break
                    self._cond.wait(remaining)
            except BaseException:
                # crash mid-coalesce (e.g. an interrupt landing in the
                # straggler wait): put the claimed requests back so
                # _loop's crash handler fails THEIR futures too instead
                # of stranding them in this frame's local
                self._queue.extendleft(reversed(batch))
                raise
            self._set_depth()
            return batch

    def _loop(self):
        batch = None
        try:
            while True:
                batch = self._take_tick()
                if batch is None:
                    return
                self._serve(batch)
                batch = None
        except BaseException as e:      # noqa: BLE001 — tick machinery died
            # _serve guards serve_fn, but a crash in the tick machinery
            # itself (or a KeyboardInterrupt landing on this thread)
            # must not strand every queued/future submit in a silent
            # hang: refuse new requests and fail the queued ones AND
            # the in-flight batch already popped off the queue
            with self._cond:
                self._closed = True
                pending = list(self._queue)
                self._queue.clear()
                self._cond.notify_all()
            for r in (batch or []) + pending:
                if not r.future.done():
                    r.future.set_exception(
                        RuntimeError(f"batcher thread died: {e!r}"))
            raise

    def _serve(self, batch):
        # queue wait ends when the tick claims the batch — measured
        # before serve_fn so it carries coalescing/straggler wait only,
        # the same serve_queue_wait_ms bucket the engine records (the
        # serving A/B compares like-for-like)
        t_claim = time.perf_counter()
        # the WHOLE tick is guarded: a malformed request (ragged trailing
        # dims, mismatched feed keys) must fail that tick's futures, not
        # kill the batcher thread and strand every later submit
        try:
            keys = list(batch[0].feeds)
            feeds = {k: (np.concatenate([r.feeds[k] for r in batch])
                         if len(batch) > 1 else batch[0].feeds[k])
                     for k in keys}
            outs = self.serve_fn(feeds)
        except Exception as e:                          # noqa: BLE001
            for r in batch:
                r.future.set_exception(e)
            return
        single = not isinstance(outs, (list, tuple))
        outs = [outs] if single else list(outs)
        total = sum(r.n for r in batch)
        off = 0
        now = time.perf_counter()
        tel = self.telemetry
        try:
            for r in batch:
                sl = [o[off:off + r.n]
                      if getattr(o, "ndim", 0) and o.shape[0] >= total
                      else o for o in outs]
                r.future.set_result(sl[0] if single else sl)
                off += r.n
                if tel.enabled:
                    tel.observe(f"{self.name}_latency_ms",
                                (now - r.t_submit) * 1e3)
                    tel.observe("serve_queue_wait_ms",
                                (t_claim - r.t_submit) * 1e3)
        except Exception as e:                          # noqa: BLE001
            for r in batch:
                if not r.future.done():
                    r.future.set_exception(e)
            return
        if tel.enabled:
            tel.inc(f"{self.name}_requests", len(batch))
            tel.inc(f"{self.name}_batches")
            tel.observe(f"{self.name}_batch_size", total)
            tel.observe(f"{self.name}_batch_occupancy",
                        total / self.max_batch_size)

    # ------------------------------------------------------------------
    def inflight_requests(self):
        """Live in-flight table (``GET /v1/requests`` and the
        crash-dump ``requests_rank<r>.json``): queued requests with id,
        row count, and age."""
        now = time.perf_counter()
        with self._cond:
            snap = list(self._queue)
        return [{"request_id": r.rid, "phase": "waiting", "rows": r.n,
                 "age_ms": round((now - r.t_submit) * 1e3, 3)}
                for r in snap]

    def stats(self):
        """One batcher snapshot for ``GET /stats``."""
        with self._cond:
            waiting = len(self._queue)
        return {"name": self.name, "kind": "MicroBatcher",
                "waiting": waiting,
                "max_batch_size": self.max_batch_size,
                "max_wait_ms": self.max_wait * 1e3}

    # ------------------------------------------------------------------
    def close(self):
        """Stop accepting requests, serve what's queued, join the
        thread. Idempotent; safe to race with submit() — see the
        contract note there."""
        with self._cond:
            self._closed = True
            self._cond.notify_all()
        self._thread.join()
        # belt-and-braces: if the loop died (crash path above) between
        # a submit and its tick, nothing serves the leftovers — fail
        # them instead of letting .result() hang forever
        with self._cond:
            leftover = list(self._queue)
            self._queue.clear()
        for r in leftover:
            if not r.future.done():
                r.future.set_exception(RuntimeError("batcher closed"))

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False
