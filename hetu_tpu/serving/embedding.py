"""PS-backed sparse serving: read-only embedding resolution.

Two pieces:

* :func:`serve_embeddings_from_ps` rewrites an eval graph's
  ``EmbeddingLookUp(table, ids)`` nodes into
  ``ParameterServerSparsePullOp`` host ops, so a serving session resolves
  rows through the PS client per request instead of materializing the
  (potentially trillion-parameter) table on the worker — the inference
  analogue of the training sparse-pull path.
* :class:`ReadOnlyPSClient` wraps the PS client for serving sessions:
  every mutating RPC (push, set_param, ...) raises — a serving session
  that would push is a bug, not a mode — and ``sparse_pull`` goes through
  a host LRU row cache whose hit rate exports as the
  ``serve_embed_cache_hit_rate`` gauge.
"""
from __future__ import annotations

import collections

import numpy as np

from .. import telemetry as _telemetry
from ..graph.autodiff import find_topo_sort
from ..ops.comm import parameterServerSparsePull_op
from ..ops.embedding import EmbeddingLookUp
from ..ops.variable import PlaceholderOp

__all__ = ["ReadOnlyPSClient", "serve_embeddings_from_ps"]


def serve_embeddings_from_ps(eval_node_list, tables=None):
    """Rewrite PS-managed embedding lookups to read-only sparse pulls.

    ``tables`` limits the rewrite to the given table nodes; ``None``
    rewrites every lookup into a trainable placeholder table. The tables
    must already be registered on the PS server (a training run or an
    explicit ``init_tensor``/``set_param``). Mutates the graph in place
    (including ``eval_node_list`` entries) and returns the new pull ops.
    """
    topo = find_topo_sort(eval_node_list)
    replaced = {}
    for n in topo:
        if not isinstance(n, EmbeddingLookUp):
            continue
        tbl = n.inputs[0]
        if not (isinstance(tbl, PlaceholderOp) and tbl.trainable):
            continue
        if tables is not None and tbl not in tables:
            continue
        replaced[n] = parameterServerSparsePull_op(tbl, n.inputs[1])
    if replaced:
        for n in topo:
            n.inputs = [replaced.get(i, i) for i in n.inputs]
        for i, n in enumerate(eval_node_list):
            if n in replaced:
                eval_node_list[i] = replaced[n]
    return list(replaced.values())


# RPCs that mutate server state; everything else delegates verbatim
_BLOCKED = frozenset({
    "push", "sparse_push", "push_embedding", "dd_pushpull", "sd_pushpull",
    "ss_pushpull", "set_param", "init_tensor", "push_data", "load_param",
})


class ReadOnlyPSClient:
    """Read-only facade over a :class:`~hetu_tpu.ps.client.PSClient`.

    Serving guard: calling any mutating RPC raises ``RuntimeError``.
    Row cache: ``cache_rows > 0`` keeps that many embedding rows (per
    table) in host memory with LRU eviction — rows a hot serving id set
    touches repeatedly skip the server RPC entirely. The cache has no
    invalidation protocol (serving reads a frozen table); call
    ``invalidate()`` after the server's values change.
    """

    def __init__(self, client, cache_rows=0, telemetry=None):
        self._client = client
        self.cache_rows = int(cache_rows)
        self._cache = {}        # tid -> OrderedDict[id -> row]
        self.hits = 0
        self.misses = 0
        self.telemetry = _telemetry.resolve(telemetry)

    def __getattr__(self, name):
        if name in _BLOCKED:
            def _blocked(*args, **kwargs):
                raise RuntimeError(
                    f"read-only serving PS client: {name}() would "
                    f"mutate parameter-server state; serving sessions "
                    f"never push")
            return _blocked
        return getattr(self._client, name)

    # ------------------------------------------------------------------
    def invalidate(self):
        self._cache.clear()

    @property
    def hit_rate(self):
        total = self.hits + self.misses
        return self.hits / total if total else 0.0

    def _note(self, hits, misses):
        self.hits += hits
        self.misses += misses
        tel = self.telemetry
        if tel.enabled:
            if hits:
                tel.inc("serve_embed_cache_hits", hits)
            if misses:
                tel.inc("serve_embed_cache_misses", misses)
            tel.set_gauge("serve_embed_cache_hit_rate", self.hit_rate)

    def sparse_pull(self, tid, indices, width):
        idx = np.asarray(indices)
        if not self.cache_rows:
            self._note(0, idx.size)
            return self._client.sparse_pull(tid, idx, width)
        cache = self._cache.setdefault(tid, collections.OrderedDict())
        flat = idx.ravel().astype(np.int64)
        uniq, inv = np.unique(flat, return_inverse=True)
        rows = np.empty((len(uniq), int(width)), np.float32)
        miss_pos = []
        for i, eid in enumerate(uniq):
            row = cache.get(int(eid))
            if row is None:
                miss_pos.append(i)
            else:
                cache.move_to_end(int(eid))
                rows[i] = row
        self._note(len(uniq) - len(miss_pos), len(miss_pos))
        if miss_pos:
            miss_ids = uniq[miss_pos]
            fetched = self._client.sparse_pull(tid, miss_ids, width)
            fetched = np.asarray(fetched).reshape(len(miss_ids), width)
            for i, eid, row in zip(miss_pos, miss_ids, fetched):
                rows[i] = row
                # copy: caching a view would pin the WHOLE fetched
                # batch array for as long as any one row survives LRU
                cache[int(eid)] = row.copy()
                while len(cache) > self.cache_rows:
                    cache.popitem(last=False)
        return rows[inv].reshape(tuple(idx.shape) + (int(width),))
