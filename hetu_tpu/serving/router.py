"""Replica routing over N serving backends, driven by the SLO probe.

Two pieces:

* :class:`SLOWindow` — the rolling (ok, latency_ms) window behind the
  ``/healthz`` SLO probe, extracted from ``ServingHTTPServer`` so the
  router, the HTTP frontend, and the continuous-batching engine all
  share ONE definition of "breached": p99 latency over the window
  against ``slo_p99_ms``, error rate against ``slo_error_rate``.
* :class:`ReplicaRouter` — a thin router over N replica backends
  (anything with ``submit(...) -> Future``: a
  :class:`~hetu_tpu.serving.scheduler.ContinuousBatchingEngine`, a
  :class:`~hetu_tpu.serving.batcher.MicroBatcher`, ...). Each submit
  goes to the healthy replica with the fewest in-flight requests
  (round-robin on ties); completion latency and errors feed that
  replica's window, so a degraded replica drains itself out of the
  rotation exactly the way the load balancer behind ``/healthz``
  would. When EVERY replica is breached the router sheds load:
  :class:`RouterOverloaded` — a fast 503, not a slow timeout.

A replica that exposes its own ``health()`` (the engine, an HTTP
frontend) is consulted in preference to the router's outside view —
the replica knows about queue pressure the router can't see.
"""
from __future__ import annotations

import threading
import time
from collections import deque

import numpy as np

from .. import telemetry as _telemetry
from . import lifecycle as _lifecycle

__all__ = ["SLOWindow", "ReplicaRouter", "RouterOverloaded"]


class RouterOverloaded(RuntimeError):
    """Every replica is breaching its SLO — the request is shed, not
    queued behind a fleet-wide stall."""


class SLOWindow:
    """Rolling window of request outcomes + the SLO breach verdict.

    ``note(ok, ms, ttft_ms=None)`` records one request; ``health()``
    returns ``(healthy, reason)`` — healthy whenever no SLO is
    configured or the window is empty, breached when the windowed error
    rate exceeds ``error_rate``, the windowed p99 of
    successful-request latency exceeds ``p99_ms``, or the windowed p99
    of time-to-first-token exceeds ``ttft_p99_ms`` (the
    streaming-experience SLO: a request can meet its e2e budget while
    its first token arrived unacceptably late). TTFT is recorded by
    producers that know it (the continuous-batching engine, when
    telemetry is on); requests noted without one simply don't count
    toward the TTFT percentile. Thread-safe."""

    def __init__(self, p99_ms=None, error_rate=None, window=128,
                 ttft_p99_ms=None):
        self.p99_ms = p99_ms
        self.error_rate = error_rate
        self.ttft_p99_ms = ttft_p99_ms
        # (ok, latency_ms, ttft_ms-or-None)
        self._window = deque(maxlen=int(window))
        self._lock = threading.Lock()

    def note(self, ok, ms, ttft_ms=None):
        with self._lock:
            self._window.append(
                (bool(ok), float(ms),
                 None if ttft_ms is None else float(ttft_ms)))

    def health(self):
        """(healthy, reason) under the configured SLOs."""
        if self.p99_ms is None and self.error_rate is None \
                and self.ttft_p99_ms is None:
            return True, "ok"
        with self._lock:
            window = list(self._window)
        if not window:
            return True, "ok (no traffic)"
        if self.error_rate is not None:
            rate = sum(1 for ok, _, _ in window if not ok) / len(window)
            if rate > self.error_rate:
                return False, (f"error rate {rate:.3f} > SLO "
                               f"{self.error_rate:.3f} over "
                               f"{len(window)} requests")
        if self.p99_ms is not None:
            lats = [ms for ok, ms, _ in window if ok]
            if lats:
                p99 = float(np.percentile(lats, 99))
                if p99 > self.p99_ms:
                    return False, (f"serve_latency_ms p99 {p99:.1f} > "
                                   f"SLO {self.p99_ms:.1f} over "
                                   f"{len(lats)} requests")
        if self.ttft_p99_ms is not None:
            ttfts = [t for ok, _, t in window if ok and t is not None]
            if ttfts:
                p99 = float(np.percentile(ttfts, 99))
                if p99 > self.ttft_p99_ms:
                    return False, (f"serve_ttft_ms p99 {p99:.1f} > "
                                   f"SLO {self.ttft_p99_ms:.1f} over "
                                   f"{len(ttfts)} requests")
        return True, "ok"


class _ReplicaState:
    __slots__ = ("replica", "window", "inflight", "routed")

    def __init__(self, replica, window):
        self.replica = replica
        self.window = window
        self.inflight = 0
        self.routed = 0

    def health(self):
        probe = getattr(self.replica, "health", None)
        if callable(probe):
            return probe()
        return self.window.health()


class ReplicaRouter:
    """Least-inflight routing over replicas, SLO-probed per replica."""

    def __init__(self, replicas, *, slo_p99_ms=None, slo_error_rate=None,
                 slo_window=128, telemetry=None, name="router"):
        if not replicas:
            raise ValueError("router needs at least one replica")
        self.telemetry = _telemetry.resolve(telemetry)
        self.name = name
        self._lock = threading.Lock()
        self._rr = 0
        self._states = [
            _ReplicaState(r, SLOWindow(slo_p99_ms, slo_error_rate,
                                       slo_window))
            for r in replicas]
        _lifecycle.register(self)   # crash-time in-flight dumps

    @property
    def replicas(self):
        return [s.replica for s in self._states]

    def stats(self):
        """Per-replica routing snapshot for ``GET /stats``: inflight /
        routed counts and the breach verdict, plus each replica's own
        ``stats()`` when it has one."""
        out = []
        for i, s in enumerate(self._states):
            ok, reason = s.health()
            entry = {"index": i, "inflight": s.inflight,
                     "routed": s.routed, "healthy": ok,
                     "reason": reason}
            sub = getattr(s.replica, "stats", None)
            if callable(sub):
                try:
                    entry["replica"] = sub()
                except Exception:   # noqa: BLE001 — introspection only
                    pass
            out.append(entry)
        return {"name": self.name, "kind": "ReplicaRouter",
                "replicas": out}

    def inflight_requests(self):
        """Fleet in-flight table: the union of every replica's
        ``inflight_requests()``, each row tagged with its replica
        index."""
        rows = []
        for i, s in enumerate(self._states):
            fn = getattr(s.replica, "inflight_requests", None)
            if not callable(fn):
                continue
            try:
                for row in fn():
                    row = dict(row)
                    row["replica"] = i
                    rows.append(row)
            except Exception:       # noqa: BLE001 — introspection only
                continue
        return rows

    def health(self):
        """(healthy, reason): healthy while ANY replica is."""
        reasons = []
        for i, s in enumerate(self._states):
            ok, reason = s.health()
            if ok:
                return True, "ok"
            reasons.append(f"replica {i}: {reason}")
        return False, "; ".join(reasons)

    def _pick(self):
        with self._lock:
            healthy = [(i, s) for i, s in enumerate(self._states)
                       if s.health()[0]]
            if not healthy:
                raise RouterOverloaded(
                    "all replicas breaching SLO — "
                    + self.health()[1])
            lo = min(s.inflight for _, s in healthy)
            tied = [(i, s) for i, s in healthy if s.inflight == lo]
            i, state = tied[self._rr % len(tied)]
            self._rr += 1
            state.inflight += 1
            state.routed += 1
            return i, state

    def submit(self, *args, **kwargs):
        """Route one request; returns the replica's Future. Raises
        :class:`RouterOverloaded` when every replica is breached.
        All arguments (``request_id=`` included) pass through to the
        chosen replica, so end-to-end tracing survives the hop."""
        i, state = self._pick()
        tel = self.telemetry
        if tel.enabled:
            tel.inc(f"{self.name}_requests")
            tel.inc(f"{self.name}_replica{i}_requests")
        t0 = time.perf_counter()
        try:
            fut = state.replica.submit(*args, **kwargs)
        except Exception:
            with self._lock:
                state.inflight -= 1
            state.window.note(False, (time.perf_counter() - t0) * 1e3)
            raise

        def _done(f):
            ms = (time.perf_counter() - t0) * 1e3
            with self._lock:
                state.inflight -= 1
            state.window.note(f.exception() is None, ms)
            if tel.enabled:
                tel.observe(f"{self.name}_latency_ms", ms)

        fut.add_done_callback(_done)
        return fut
