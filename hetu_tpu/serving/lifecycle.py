"""Per-request serving lifecycle: ids, phase timelines, in-flight dumps.

The serving plane used to be observable only at engine granularity
(``step`` spans + counters); this module is the request-level layer the
whole plane shares:

* **request ids** — :func:`mint_request_id` mints a process-unique id;
  ``ServingHTTPServer`` honors/echoes ``x-request-id`` at ingress,
  ``ReplicaRouter`` propagates it, and
  ``ContinuousBatchingEngine.submit`` / ``MicroBatcher.submit`` accept
  it (minting one themselves when the caller didn't).
* **phase timelines** — :class:`RequestTimeline` accumulates one
  retired request's contiguous phase episodes (``queue`` -> ``prefill``
  -> per-step ``decode``, with post-preemption episodes rebadged
  ``replay`` until the request re-earns the tokens it lost). The engine
  creates a timeline ONLY when telemetry is enabled, so the disabled
  path keeps the PR 2 zero-alloc-per-step contract (every recording
  site guards on ``tel.enabled`` / ``seq.tl is not None`` first).
  :func:`emit_request` exports the episodes retroactively as
  ``serve_phase`` / ``serve_request`` Chrome-trace spans (explicit
  ``perf_counter_ns`` clocks through ``Telemetry.complete``) that
  ``merge_traces`` interleaves with the engine's own step spans, and
  ``python -m hetu_tpu.telemetry.doctor --serving`` attributes into
  conserving queue/prefill/decode/replay/overhead buckets.
* **in-flight dumps** — serving components :func:`register` themselves
  in a process-wide WeakSet; :func:`dump_inflight` (called from
  ``Telemetry.flush``, which the PR 4 crash handlers already invoke)
  writes ``requests_rank<r>.json`` beside the flight rings so a
  crashed/watchdogged engine names its stuck requests (id, phase,
  tokens, blocks held, age) in the black-box report.
"""
from __future__ import annotations

import itertools
import json
import os
import time
import weakref

__all__ = ["mint_request_id", "RequestTimeline", "emit_request",
           "register", "dump_inflight", "PHASES"]

# the disjoint per-request buckets the serving doctor attributes into;
# "overhead" is the exact residual (e2e minus recorded episodes), never
# an emitted span — conservation is by construction, then checked
PHASES = ("queue", "prefill", "decode", "replay", "overhead")

_RID = itertools.count(1)


def mint_request_id():
    """Process-unique request id (``req-<pid>-<n>``, both hex)."""
    return f"req-{os.getpid():x}-{next(_RID):x}"


class RequestTimeline:
    """Phase episodes of ONE request, on explicit ``perf_counter_ns``
    clocks. Created only when telemetry is enabled; recording is a
    tuple append (no locks — every writer is the scheduler thread)."""

    __slots__ = ("rid", "t_submit", "t_wait_start", "t_first_token",
                 "episodes")

    def __init__(self, rid, now_ns):
        self.rid = rid
        self.t_submit = now_ns
        # waiting-episode cursor: submit time initially, reset to the
        # preemption instant when a sequence bounces back to the queue
        self.t_wait_start = now_ns
        self.t_first_token = None       # TTFT point: last prefill end
        self.episodes = []              # (phase, t0_ns, t1_ns, attrs)

    def note(self, phase, t0_ns, t1_ns, attrs=None):
        """Record one episode; ``attrs`` (optional dict) rides onto the
        exported ``serve_phase`` span — the prefix/chunked-prefill path
        stamps ``cached_tokens`` / ``computed_tokens`` here so the
        doctor can attribute prompt work to the cache vs the chip."""
        self.episodes.append((phase, t0_ns, t1_ns, attrs))


def emit_request(tel, tl, t_retire_ns, tokens, preempts):
    """Export one retired request's timeline: one ``serve_phase`` span
    per episode plus the enclosing ``serve_request`` span (attrs typed
    in ``telemetry.check.SPAN_SCHEMA``)."""
    for phase, t0, t1, attrs in tl.episodes:
        args = {"request_id": tl.rid, "phase": phase}
        if attrs:
            args.update(attrs)
        tel.complete("serve_phase", t0, t1, args)
    tel.complete("serve_request", tl.t_submit, t_retire_ns,
                 {"request_id": tl.rid, "phase": "retired",
                  "tokens": int(tokens), "preempts": int(preempts)})


# ---------------------------------------------------------------------------
# in-flight registry: the crash-dump view of the serving plane
# ---------------------------------------------------------------------------

# live serving components exposing inflight_requests() (engines,
# batchers, routers); weak so a closed engine never pins itself here
_COMPONENTS = weakref.WeakSet()


def register(component):
    """Track a serving component for crash-time in-flight dumps."""
    _COMPONENTS.add(component)


def dump_inflight(out_dir, rank):
    """Write ``requests_rank<rank>.json`` — every registered
    component's in-flight request table (+ its ``stats()`` snapshot) —
    atomically (tmp+rename, flight-ring discipline). Returns the path,
    or None when no component is registered or the write failed; never
    raises (this runs inside crash handlers)."""
    entries = []
    for comp in list(_COMPONENTS):
        try:
            entry = {"name": getattr(comp, "name", None)
                     or type(comp).__name__,
                     "kind": type(comp).__name__,
                     "requests": comp.inflight_requests()}
            stats = getattr(comp, "stats", None)
            if callable(stats):
                entry["stats"] = stats()
            entries.append(entry)
        except Exception:       # noqa: BLE001 — never mask the crash
            continue
    if not entries:
        return None
    try:
        doc = {"rank": int(rank), "pid": os.getpid(),
               "wall": time.time(), "components": entries}
        os.makedirs(out_dir, exist_ok=True)
        path = os.path.join(out_dir, f"requests_rank{int(rank)}.json")
        tmp = f"{path}.{os.getpid()}.tmp"
        with open(tmp, "w") as f:
            json.dump(doc, f)
        os.replace(tmp, path)
        return path
    except OSError:
        return None
