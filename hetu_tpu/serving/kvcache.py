"""Paged KV cache: fixed-size block pools + a block allocator.

The dense decode path (``serving/decode.py``) preallocates one
``[B, H, S_max, D]`` K/V pair per layer per batch — every sequence pays
for ``S_max`` positions whether it uses 8 or 800, and a new batch means
a new allocation. This module is the vLLM/PagedAttention shape instead:

* **one pooled buffer per layer** — ``[num_blocks, block_size, H, D]``
  for K and V, allocated once and shared by every sequence the engine
  ever serves;
* **per-sequence block tables** — a sequence owns an ordered list of
  block ids; token position ``j`` lives in flat pool slot
  ``table[j // block_size] * block_size + j % block_size``. Sequences
  are contiguous *logically*, scattered *physically*;
* **a refcounted free-list allocator** with deterministic exhaustion
  behavior: ``alloc`` is all-or-nothing and raises
  :class:`KVCacheExhausted` (never partially allocates, never corrupts
  a neighbor's blocks); ``share`` bumps a live block's refcount so
  several sequences (or the prefix cache) can reference one physical
  block; ``free`` decrements and a block rejoins the free list only at
  refcount 0 — underflow / double-free of a shared block raises.
* **a prefix cache** (:class:`PrefixCache`): full prompt blocks key by
  a rolling hash of the token prefix, so a repeated system prompt
  resolves to the already-resident blocks — zero prefill compute, zero
  new blocks — and admission charges only the non-cached suffix.
  Blocks whose last sequence retired stay cached (refcount 1, held by
  the cache) on an LRU list and are evicted only under allocation
  pressure, never eagerly.
* **copy-on-write**: a sequence about to write into a block someone
  else also references (another sequence, or the cache's frozen tail
  entry) copies it first (:meth:`PagedKVCache.ensure_writable`), so
  shared partial tails are read-shared and write-private.

**Physical block 0 is the scratch block.** Padded batch lanes (the
bucketing that keeps jit signatures bounded) write their garbage K/V
rows to slot ``0..block_size-1`` and gather from them behind a length
mask; the allocator never hands block 0 to a real sequence, so padding
can never corrupt live cache rows.

Sizing rides the HT4xx machinery (``analysis/memory.py``): with
``num_blocks=None`` the pool sizes itself against the resolved HBM
budget (explicit argument > ``HETU_HBM_BUDGET`` > the device's
advertised ``bytes_limit``) minus the model's parameter bytes and a
headroom fraction. On a CPU harness with no budget resolvable, pass
``num_blocks`` explicitly.
"""
from __future__ import annotations

import collections
import hashlib
import math

import numpy as np

__all__ = ["KVCacheExhausted", "BlockAllocator", "PagedKVCache",
           "PrefixCache", "kv_block_bytes", "gpt_param_bytes",
           "blocks_for_budget", "DEFAULT_BLOCK_SIZE"]

DEFAULT_BLOCK_SIZE = 16

# fraction of the resolved HBM budget kept free for activations /
# compiler temps when auto-sizing the pool (the static HT4xx estimate
# is deliberately pessimistic the other way; serving steps are small)
_BUDGET_HEADROOM = 0.10


class KVCacheExhausted(RuntimeError):
    """Raised by :meth:`BlockAllocator.alloc` when the free list cannot
    cover a request. All-or-nothing: no blocks were allocated. The
    engine's admission plane turns this into queueing/rejection; seeing
    it escape means a caller bypassed admission control."""


def kv_block_bytes(config, block_size, dtype_bytes=4):
    """HBM bytes one cache block costs across ALL layers (K + V)."""
    return (2 * config.num_hidden_layers * int(block_size)
            * config.hidden_size * dtype_bytes)


def gpt_param_bytes(config, dtype_bytes=4):
    """Parameter bytes of a ``GPTLMHeadModel`` with this config (the
    serving-params pytree ``models/gpt.py:gpt_serving_params`` builds)
    — what the pool sizing subtracts from the HBM budget."""
    h = config.hidden_size
    i = config.intermediate_size
    per_layer = (2 * h                      # ln1
                 + h * 3 * h + 3 * h        # qkv
                 + h * h + h                # attn proj
                 + 2 * h                    # ln2
                 + h * i + i                # mlp fc
                 + i * h + h)               # mlp proj
    total = (config.vocab_size * h          # wte
             + config.max_position_embeddings * h   # wpe
             + config.num_hidden_layers * per_layer
             + 2 * h                        # ln_f
             + h * config.vocab_size)       # lm_head
    return total * dtype_bytes


def blocks_for_budget(config, block_size=DEFAULT_BLOCK_SIZE, budget=None,
                      headroom=_BUDGET_HEADROOM):
    """KV blocks the resolved HBM budget affords after the model's
    parameters and a headroom fraction. Returns ``None`` when no budget
    resolves (CPU harness without ``HETU_HBM_BUDGET``); raises when a
    budget resolves but can't fit even two blocks."""
    from ..analysis.memory import fmt_bytes, resolve_budget
    budget = resolve_budget(budget)
    if budget is None:
        return None
    avail = int(budget * (1.0 - headroom)) - gpt_param_bytes(config)
    nb = avail // kv_block_bytes(config, block_size)
    if nb < 2:
        raise ValueError(
            f"HBM budget {fmt_bytes(budget)} leaves room for {nb} KV "
            f"block(s) after {fmt_bytes(gpt_param_bytes(config))} of "
            f"parameters — the model doesn't fit a paged cache here")
    return int(nb)


class BlockAllocator:
    """Refcounted free-list over ``num_blocks`` usable block ids.

    ``alloc(n)`` is all-or-nothing (raises :class:`KVCacheExhausted`
    listing need vs. free, allocating nothing) and hands blocks out at
    refcount 1. ``share(blocks)`` bumps a live block's refcount — how
    the prefix cache and prefix-hit sequences reference one physical
    block. ``free(blocks)`` decrements; a block rejoins the free list
    only when its refcount reaches 0, and freeing a dead block (or
    decrementing past zero) raises ``ValueError`` without mutating
    anything. Blocks hand out lowest-id-first and freed blocks rejoin
    in sorted order, so identical alloc/share/free traces produce
    identical tables — exhaustion and reuse are deterministic, not
    load-dependent."""

    def __init__(self, num_blocks, block_size, first_id=0):
        self.num_blocks = int(num_blocks)
        self.block_size = int(block_size)
        self._first = int(first_id)
        self._free = collections.deque(
            range(self._first, self._first + self.num_blocks))
        self._ref = {}          # block id -> refcount (live blocks only)

    @property
    def available(self):
        return len(self._free)

    @property
    def used(self):
        return len(self._ref)

    def refcount(self, block):
        """Live refcount of one block (0 when free/unknown)."""
        return self._ref.get(block, 0)

    def blocks_for_tokens(self, ntokens):
        return max(1, math.ceil(int(ntokens) / self.block_size))

    def alloc(self, n):
        n = int(n)
        if n > len(self._free):
            raise KVCacheExhausted(
                f"KV cache exhausted: need {n} block(s), "
                f"{len(self._free)} free of {self.num_blocks}")
        out = [self._free.popleft() for _ in range(n)]
        for b in out:
            self._ref[b] = 1
        return out

    def share(self, blocks):
        """Add one reference to each (live) block — all-or-nothing:
        sharing a free/unknown block raises without mutating."""
        blocks = list(blocks)
        for b in blocks:
            if b not in self._ref:
                raise ValueError(f"share of non-live KV block {b}")
        for b in blocks:
            self._ref[b] += 1

    def free(self, blocks):
        """Drop one reference per listed block; blocks reaching
        refcount 0 rejoin the free list. Validated before any mutation:
        releasing more references than a block holds (double free /
        refcount underflow) raises ``ValueError`` and nothing changes.
        Returns the blocks that actually went free."""
        need = collections.Counter(blocks)
        for b, n in need.items():
            have = self._ref.get(b, 0)
            if n > have:
                raise ValueError(
                    f"double free of KV block {b}: releasing {n} "
                    f"reference(s) but it holds {have}")
        released = []
        for b, n in need.items():
            left = self._ref[b] - n
            if left == 0:
                del self._ref[b]
                released.append(b)
            else:
                self._ref[b] = left
        if released:
            # sorted re-insertion keeps reuse deterministic regardless
            # of the order sequences finished in
            self._free = collections.deque(
                sorted(list(self._free) + released))
        return released


def _chain_key(prev, tokens):
    """One rolling-hash step: digest of (previous chain key, this
    block's token ids). Position sensitivity is free — a chunk's key
    encodes every token before it, so identical token blocks at
    different offsets never collide."""
    h = hashlib.blake2b(prev, digest_size=16)
    h.update(np.ascontiguousarray(tokens, np.int32).tobytes())
    return h.digest()


class PrefixCache:
    """Token-chunk -> resident-block map for prompt prefix sharing.

    Two entry kinds, both keyed off the rolling hash chain:

    * **full-block entries** — chain key of blocks ``0..i`` -> the
      physical block holding positions ``i*bs..(i+1)*bs-1``. Inserted
      when a prompt's full blocks finish prefilling; immutable by
      construction (a sequence never rewrites a filled position).
    * **tail entries** — ``(chain key, tail token tuple)`` -> the block
      holding the prompt's trailing partial block. A later prompt whose
      next tokens start with the stored tail shares the block for those
      rows; the block is frozen the moment it's inserted — ANY sequence
      extending into it (the inserter included) copies first
      (:meth:`PagedKVCache.ensure_writable`), which is the whole
      copy-on-write story.

    The cache holds one allocator reference per cached block (bumped by
    :class:`PagedKVCache` at insert), so a cached block whose sequences
    all retired survives at refcount 1 on the LRU list — eviction
    happens under allocation pressure (:meth:`PagedKVCache`'s
    ``_evict_for``), never eagerly. This class is pure host-side
    bookkeeping: refcounts and device copies belong to the owner."""

    def __init__(self, block_size):
        self.block_size = int(block_size)
        self._full = {}         # chain key -> block id
        self._tails = {}        # chain key -> {tail token tuple: block}
        self._entry = {}        # block id -> (kind, key[, tail tuple])
        # blocks cached but referenced by no sequence, oldest first —
        # the eviction ladder
        self._lru = collections.OrderedDict()
        self.hit_tokens = 0
        self.miss_tokens = 0
        self.evictions = 0

    @property
    def cached_blocks(self):
        return len(self._entry)

    @property
    def evictable(self):
        return len(self._lru)

    def hit_rate(self):
        """Token-weighted lifetime hit rate over every match() call."""
        total = self.hit_tokens + self.miss_tokens
        return self.hit_tokens / total if total else 0.0

    def is_cached(self, block):
        return block in self._entry

    def match(self, prompt, count=True):
        """Longest cached prefix of ``prompt``: ``(blocks, ntokens)``
        — whole blocks first, then at most one partial tail. Pure
        lookup: refcounts and LRU state are untouched (``count=False``
        also skips the hit/miss accounting, for admission probes)."""
        prompt = np.asarray(prompt).reshape(-1)
        bs = self.block_size
        key = b""
        blocks, cached = [], 0
        for i in range(len(prompt) // bs):
            nxt = _chain_key(key, prompt[i * bs:(i + 1) * bs])
            b = self._full.get(nxt)
            if b is None:
                break
            blocks.append(b)
            cached += bs
            key = nxt
        # the partial tail: longest stored tail that prefixes the
        # remaining tokens (typically 0 or 1 candidates per key)
        best = None
        for tail, b in self._tails.get(key, {}).items():
            if len(tail) + cached <= len(prompt) and \
                    (best is None or len(tail) > len(best[0])) and \
                    tuple(int(t) for t in
                          prompt[cached:cached + len(tail)]) == tail:
                best = (tail, b)
        if best is not None:
            blocks.append(best[1])
            cached += len(best[0])
        if count:
            self.hit_tokens += cached
            self.miss_tokens += len(prompt) - cached
        return blocks, cached

    def insert_full(self, key_prefix_tokens, block):
        """Insert one full block under the chain key of every token up
        to and including its own. Returns True when inserted (False:
        the chunk was already cached — keep the existing block)."""
        prompt = np.asarray(key_prefix_tokens).reshape(-1)
        bs = self.block_size
        key = b""
        for i in range(len(prompt) // bs):
            key = _chain_key(key, prompt[i * bs:(i + 1) * bs])
        if key in self._full or block in self._entry:
            return False
        self._full[key] = block
        self._entry[block] = ("full", key)
        return True

    def insert_tail(self, full_prefix_tokens, tail_tokens, block):
        """Insert a partial-tail entry: ``tail_tokens`` at positions
        following the full-block prefix live in ``block`` rows
        ``0..len(tail)-1``. Returns True when inserted."""
        prompt = np.asarray(full_prefix_tokens).reshape(-1)
        bs = self.block_size
        key = b""
        for i in range(len(prompt) // bs):
            key = _chain_key(key, prompt[i * bs:(i + 1) * bs])
        tail = tuple(int(t) for t in np.asarray(tail_tokens).reshape(-1))
        if not tail or len(tail) >= bs:
            raise ValueError(f"tail must be 1..{bs - 1} tokens, "
                             f"got {len(tail)}")
        per_key = self._tails.setdefault(key, {})
        if tail in per_key or block in self._entry:
            return False
        per_key[tail] = block
        self._entry[block] = ("tail", key, tail)
        return True

    def mark_referenced(self, block):
        """A sequence took a reference to this cached block — it is no
        longer evictable."""
        self._lru.pop(block, None)

    def mark_unreferenced(self, block):
        """The last sequence referencing this cached block released it
        — it joins the evictable LRU tail (most recently used end)."""
        if block in self._entry:
            self._lru.pop(block, None)
            self._lru[block] = None

    def pop_lru(self):
        """Evict the least-recently-used unreferenced cached block:
        drops its map entry and returns the block id (caller releases
        the cache's allocator reference), or None when nothing is
        evictable."""
        if not self._lru:
            return None
        block, _ = self._lru.popitem(last=False)
        self.drop(block)
        self.evictions += 1
        return block

    def drop(self, block):
        """Remove a block's cache entry (eviction or CoW bookkeeping)."""
        ent = self._entry.pop(block, None)
        self._lru.pop(block, None)
        if ent is None:
            return
        if ent[0] == "full":
            self._full.pop(ent[1], None)
        else:
            per_key = self._tails.get(ent[1])
            if per_key is not None:
                per_key.pop(ent[2], None)
                if not per_key:
                    del self._tails[ent[1]]


def _cow_copy(pools, src, dst):
    """Copy one block's K/V rows across every layer (jitted with the
    pools donated, so the copy is an in-HBM row move, not a pool
    round-trip)."""
    return [{"k": p["k"].at[dst].set(p["k"][src]),
             "v": p["v"].at[dst].set(p["v"][src])} for p in pools]


class PagedKVCache:
    """Per-layer pooled K/V buffers + per-sequence block tables.

    The pools are jax arrays the engine threads through its (donated)
    jit calls; everything else — tables, the allocator, slot math — is
    host-side numpy. ``config`` is GPT-shaped (``num_hidden_layers``,
    ``num_attention_heads``, ``hidden_size``).

    With ``prefix_cache=True`` the cache grows the prefix-sharing
    plane: :meth:`add_seq_prefix` resolves a prompt's cached prefix to
    shared blocks (refcount bumped per sharer), :meth:`insert_prefix`
    publishes a prefilled prompt's blocks for later requests,
    :meth:`ensure_writable` copy-on-writes shared blocks before a
    sequence extends into them, and retiring sequences leave cached
    blocks resident (LRU-evicted only under allocation pressure).
    Everything stays single-threaded under the engine's scheduler —
    none of this is locked."""

    def __init__(self, config, num_blocks=None,
                 block_size=DEFAULT_BLOCK_SIZE, budget=None,
                 telemetry=None, prefix_cache=False):
        from .. import telemetry as _telemetry
        self.config = config
        self.block_size = int(block_size)
        if num_blocks is None:
            num_blocks = blocks_for_budget(config, self.block_size,
                                           budget)
            if num_blocks is None:
                raise ValueError(
                    "no HBM budget resolvable to size the KV pool "
                    "(CPU harness?): pass num_blocks= explicitly or "
                    "set HETU_HBM_BUDGET")
        self.num_blocks = int(num_blocks)
        self.telemetry = _telemetry.resolve(telemetry)
        # block 0 is the scratch block padded lanes target; real
        # sequences allocate from 1..num_blocks
        self.allocator = BlockAllocator(self.num_blocks, self.block_size,
                                        first_id=1)
        self.prefix = PrefixCache(self.block_size) if prefix_cache \
            else None
        self.pools = self._init_pools()
        self.tables = {}            # seq_id -> [block ids]
        self.peak_utilization = 0.0
        self.cow_copies = 0
        self._cow_fn = None         # jitted lazily (one signature)

    def _init_pools(self):
        import jax.numpy as jnp
        nh = self.config.num_attention_heads
        hs = self.config.hidden_size // nh
        shape = (self.num_blocks + 1, self.block_size, nh, hs)
        return [{"k": jnp.zeros(shape, jnp.float32),
                 "v": jnp.zeros(shape, jnp.float32)}
                for _ in range(self.config.num_hidden_layers)]

    # -- accounting ------------------------------------------------------
    @property
    def used_blocks(self):
        return self.allocator.used

    @property
    def cached_blocks(self):
        """Cached blocks referenced by NO live sequence (the
        LRU-evictable pool the prefix cache keeps resident)."""
        return self.prefix.evictable if self.prefix is not None else 0

    @property
    def referenced_blocks(self):
        """Blocks at least one live sequence references."""
        return self.allocator.used - self.cached_blocks

    @property
    def utilization(self):
        """Fraction of the (non-scratch) pool held by live sequences
        (cached-but-unreferenced blocks are reclaimable, so they don't
        count here — see :attr:`cached_utilization`)."""
        return self.referenced_blocks / self.num_blocks

    @property
    def cached_utilization(self):
        """Fraction of the pool holding cached-unreferenced blocks."""
        return self.cached_blocks / self.num_blocks

    def hbm_bytes(self):
        """Bytes the pools occupy (scratch block included)."""
        return kv_block_bytes(self.config, self.block_size) \
            * (self.num_blocks + 1)

    def can_admit(self, ntokens):
        return self.allocator.blocks_for_tokens(ntokens) \
            <= self.allocator.available + self.cached_blocks

    def fits_at_all(self, ntokens):
        """Whether a sequence of ``ntokens`` could EVER be served by
        this pool (the submit-time guard)."""
        return self.allocator.blocks_for_tokens(ntokens) \
            <= self.allocator.num_blocks

    def _note_util(self):
        u = self.utilization
        if u > self.peak_utilization:
            self.peak_utilization = u
        if self.telemetry.enabled:
            self.telemetry.set_gauge("kv_blocks_used",
                                     self.referenced_blocks)
            self.telemetry.set_gauge("kv_blocks_free",
                                     self.allocator.available)
            self.telemetry.set_gauge("kv_seqs", len(self.tables))
            self.telemetry.set_gauge("kv_hbm_utilization", u)
            if self.prefix is not None:
                self.telemetry.set_gauge("kv_blocks_cached",
                                         self.cached_blocks)
                self.telemetry.set_gauge("kv_hbm_utilization_cached",
                                         self.cached_utilization)
                self.telemetry.set_gauge("serve_prefix_hit_rate",
                                         self.prefix.hit_rate())

    # -- allocation under cache pressure --------------------------------
    def _evict_for(self, n):
        """Evict LRU cached-unreferenced blocks until ``n`` are free
        (or nothing is left to evict)."""
        if self.prefix is None:
            return
        while self.allocator.available < n:
            b = self.prefix.pop_lru()
            if b is None:
                return
            self.allocator.free([b])    # the cache's own reference
            if self.telemetry.enabled:
                self.telemetry.inc("serve_prefix_evictions")

    def _alloc(self, n):
        """Allocate ``n`` blocks, reclaiming cached-unreferenced blocks
        LRU-first when the free list alone can't cover it."""
        self._evict_for(n)
        return self.allocator.alloc(n)

    def _release_block(self, block):
        """Drop one reference; a cached block whose only remaining
        reference is the cache's moves to the evictable LRU."""
        self.allocator.free([block])
        if self.prefix is not None and self.prefix.is_cached(block) \
                and self.allocator.refcount(block) == 1:
            self.prefix.mark_unreferenced(block)

    # -- sequence lifecycle ---------------------------------------------
    def add_seq(self, seq_id, ntokens):
        """Allocate blocks covering ``ntokens`` positions for a new
        sequence (all-or-nothing; raises :class:`KVCacheExhausted`)."""
        if seq_id in self.tables:
            raise ValueError(f"sequence {seq_id} already has a table")
        blocks = self._alloc(self.allocator.blocks_for_tokens(ntokens))
        self.tables[seq_id] = blocks
        self._note_util()
        return blocks

    def match_prefix(self, prompt):
        """Pure admission probe: ``(shared_blocks, cached_tokens)`` the
        prompt would resolve against the prefix cache right now, with
        ``cached_tokens`` capped at ``len(prompt) - 1`` so prefill
        always recomputes at least the last prompt token (the logits
        the first sampled token needs)."""
        if self.prefix is None:
            return [], 0
        blocks, cached = self.prefix.match(prompt, count=False)
        return blocks, min(cached, len(np.asarray(prompt).reshape(-1)) - 1)

    def admit_blocks_needed(self, prompt, ntokens):
        """Blocks a prefix-aware admission must find for this request:
        the non-cached remainder of its table, plus the copy-on-write
        spares its writes into shared blocks will consume."""
        blocks, cached = self.match_prefix(prompt)
        need = self.allocator.blocks_for_tokens(ntokens) - len(blocks)
        p = len(np.asarray(prompt).reshape(-1))
        # suffix prefill's first write lands inside a shared block
        if blocks and cached // self.block_size < len(blocks):
            need += 1
        # the first decode write extends the (cache-frozen) prompt tail
        if self.prefix is not None and p % self.block_size != 0:
            need += 1
        return need

    def add_seq_prefix(self, seq_id, ntokens, prompt):
        """Prefix-aware :meth:`add_seq`: resolve the prompt's cached
        prefix to shared blocks (one reference each), allocate only the
        remainder, install the table. Returns ``(blocks,
        cached_tokens)`` — all-or-nothing (shared references roll back
        on exhaustion)."""
        if self.prefix is None:
            return self.add_seq(seq_id, ntokens), 0
        if seq_id in self.tables:
            raise ValueError(f"sequence {seq_id} already has a table")
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        shared, cached = self.prefix.match(prompt)
        cached = min(cached, len(prompt) - 1)
        self.allocator.share(shared)
        for b in shared:
            self.prefix.mark_referenced(b)
        try:
            fresh = self._alloc(
                self.allocator.blocks_for_tokens(ntokens) - len(shared))
        except KVCacheExhausted:
            for b in shared:
                self._release_block(b)
            raise
        self.tables[seq_id] = shared + fresh
        self._note_util()
        return self.tables[seq_id], cached

    def insert_prefix(self, seq_id, prompt):
        """Publish a fully-prefilled prompt's blocks into the prefix
        cache: every full block under its rolling-hash chain key, plus
        one frozen tail entry for the trailing partial block. The cache
        takes one reference per published block (that reference is what
        keeps a retired prompt resident). No-op without a prefix cache;
        already-cached chunks keep their existing blocks."""
        if self.prefix is None:
            return 0
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        table = self.tables[seq_id]
        bs = self.block_size
        inserted = 0
        for i in range(len(prompt) // bs):
            b = table[i]
            if self.prefix.insert_full(prompt[:(i + 1) * bs], b):
                self.allocator.share([b])
                inserted += 1
        f = len(prompt) % bs
        if f:
            b = table[len(prompt) // bs]
            if self.prefix.insert_tail(prompt[:len(prompt) - f],
                                       prompt[len(prompt) - f:], b):
                self.allocator.share([b])
                inserted += 1
        self._note_util()
        return inserted

    def ensure_writable(self, seq_id, start, stop):
        """Copy-on-write guard for writes to positions ``[start,
        stop)``: any touched block someone else also references (a
        concurrent sharer, or the prefix cache's frozen entry) is
        copied into a fresh block first and the table repointed. When
        allocation for the copy can't be covered and the ONLY other
        referent is the cache, the entry is dropped instead (write in
        place — the cache relinquishes rather than kill the sequence).
        Returns the number of blocks copied."""
        table = self.tables[seq_id]
        bs = self.block_size
        copied = 0
        for i in range(int(start) // bs, (int(stop) - 1) // bs + 1):
            b = table[i]
            if self.allocator.refcount(b) <= 1:
                continue
            cache_only = (self.prefix is not None
                          and self.prefix.is_cached(b)
                          and self.allocator.refcount(b) == 2)
            try:
                (fresh,) = self._alloc(1)
            except KVCacheExhausted:
                if cache_only:
                    # relinquish the cache entry: the block becomes
                    # privately ours, no copy needed
                    self.prefix.drop(b)
                    self.allocator.free([b])
                    continue
                raise
            self._copy_block(b, fresh)
            table[i] = fresh
            self._release_block(b)
            copied += 1
            self.cow_copies += 1
            if self.telemetry.enabled:
                self.telemetry.inc("serve_cow_copies")
        if copied:
            self._note_util()
        return copied

    def _copy_block(self, src, dst):
        import jax
        import jax.numpy as jnp
        if self._cow_fn is None:
            self._cow_fn = jax.jit(_cow_copy, donate_argnums=(0,))
        self.pools = self._cow_fn(self.pools,
                                  jnp.int32(src), jnp.int32(dst))

    def extend_seq(self, seq_id, ntokens):
        """Grow a sequence's table to cover ``ntokens`` total positions
        (no-op when it already does)."""
        table = self.tables[seq_id]
        need = self.allocator.blocks_for_tokens(ntokens) - len(table)
        if need > 0:
            table.extend(self._alloc(need))
            self._note_util()
        return table

    def free_seq(self, seq_id):
        """Release a sequence's references. Unshared blocks return to
        the free list; cached blocks stay resident (the cache's
        reference) and become evictable once no sequence holds them."""
        blocks = self.tables.pop(seq_id, None)
        if blocks:
            for b in blocks:
                self._release_block(b)
        self._note_util()

    def capacity_tokens(self, seq_id):
        return len(self.tables[seq_id]) * self.block_size

    def assert_consistent(self):
        """Debug invariant sweep (tests call this after churn): every
        allocator refcount equals the number of table references plus
        the cache's, the free list and live set partition the pool, and
        every LRU block is genuinely unreferenced."""
        refs = collections.Counter()
        for table in self.tables.values():
            refs.update(table)
        if self.prefix is not None:
            refs.update(self.prefix._entry.keys())
        alloc = self.allocator
        assert dict(refs) == alloc._ref, \
            f"dangling refcounts: expected {dict(refs)} got {alloc._ref}"
        assert len(alloc._free) + len(alloc._ref) == alloc.num_blocks
        assert not (set(alloc._free) & set(alloc._ref))
        if self.prefix is not None:
            for b in self.prefix._lru:
                assert alloc.refcount(b) == 1, \
                    f"LRU block {b} is still referenced"

    # -- slot math (host-side; the jit programs take these as inputs) ---
    def slot_of(self, seq_id, pos):
        """Flat pool slot of one position."""
        table = self.tables[seq_id]
        return table[pos // self.block_size] * self.block_size \
            + pos % self.block_size

    def slot_mapping(self, seq_id, start, stop):
        """Flat slots for positions ``[start, stop)`` as int32."""
        table = np.asarray(self.tables[seq_id], np.int32)
        pos = np.arange(start, stop)
        return (table[pos // self.block_size] * self.block_size
                + pos % self.block_size).astype(np.int32)

    def gather_slots(self, seq_ids, width):
        """``[len(seq_ids), width]`` int32 slot grid covering positions
        ``0..width-1`` per sequence; positions beyond a sequence's
        allocated blocks point at the scratch block (they sit behind
        the attention length mask anyway)."""
        bs = self.block_size
        off = np.arange(width, dtype=np.int64)
        out = np.zeros((len(seq_ids), width), np.int32)
        for i, sid in enumerate(seq_ids):
            table = np.asarray(self.tables[sid], np.int64)
            cap = len(table) * bs
            w = min(width, cap)
            out[i, :w] = (table[off[:w] // bs] * bs
                          + off[:w] % bs).astype(np.int32)
        return out
