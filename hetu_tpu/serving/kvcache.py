"""Paged KV cache: fixed-size block pools + a block allocator.

The dense decode path (``serving/decode.py``) preallocates one
``[B, H, S_max, D]`` K/V pair per layer per batch — every sequence pays
for ``S_max`` positions whether it uses 8 or 800, and a new batch means
a new allocation. This module is the vLLM/PagedAttention shape instead:

* **one pooled buffer per layer** — ``[num_blocks, block_size, H, D]``
  for K and V, allocated once and shared by every sequence the engine
  ever serves;
* **per-sequence block tables** — a sequence owns an ordered list of
  block ids; token position ``j`` lives in flat pool slot
  ``table[j // block_size] * block_size + j % block_size``. Sequences
  are contiguous *logically*, scattered *physically*;
* **a free-list allocator** with deterministic exhaustion behavior:
  ``alloc`` is all-or-nothing and raises :class:`KVCacheExhausted`
  (never partially allocates, never corrupts a neighbor's blocks);
  freed blocks return to the list in a deterministic order.

**Physical block 0 is the scratch block.** Padded batch lanes (the
bucketing that keeps jit signatures bounded) write their garbage K/V
rows to slot ``0..block_size-1`` and gather from them behind a length
mask; the allocator never hands block 0 to a real sequence, so padding
can never corrupt live cache rows.

Sizing rides the HT4xx machinery (``analysis/memory.py``): with
``num_blocks=None`` the pool sizes itself against the resolved HBM
budget (explicit argument > ``HETU_HBM_BUDGET`` > the device's
advertised ``bytes_limit``) minus the model's parameter bytes and a
headroom fraction. On a CPU harness with no budget resolvable, pass
``num_blocks`` explicitly.
"""
from __future__ import annotations

import collections
import math

import numpy as np

__all__ = ["KVCacheExhausted", "BlockAllocator", "PagedKVCache",
           "kv_block_bytes", "gpt_param_bytes", "blocks_for_budget",
           "DEFAULT_BLOCK_SIZE"]

DEFAULT_BLOCK_SIZE = 16

# fraction of the resolved HBM budget kept free for activations /
# compiler temps when auto-sizing the pool (the static HT4xx estimate
# is deliberately pessimistic the other way; serving steps are small)
_BUDGET_HEADROOM = 0.10


class KVCacheExhausted(RuntimeError):
    """Raised by :meth:`BlockAllocator.alloc` when the free list cannot
    cover a request. All-or-nothing: no blocks were allocated. The
    engine's admission plane turns this into queueing/rejection; seeing
    it escape means a caller bypassed admission control."""


def kv_block_bytes(config, block_size, dtype_bytes=4):
    """HBM bytes one cache block costs across ALL layers (K + V)."""
    return (2 * config.num_hidden_layers * int(block_size)
            * config.hidden_size * dtype_bytes)


def gpt_param_bytes(config, dtype_bytes=4):
    """Parameter bytes of a ``GPTLMHeadModel`` with this config (the
    serving-params pytree ``models/gpt.py:gpt_serving_params`` builds)
    — what the pool sizing subtracts from the HBM budget."""
    h = config.hidden_size
    i = config.intermediate_size
    per_layer = (2 * h                      # ln1
                 + h * 3 * h + 3 * h        # qkv
                 + h * h + h                # attn proj
                 + 2 * h                    # ln2
                 + h * i + i                # mlp fc
                 + i * h + h)               # mlp proj
    total = (config.vocab_size * h          # wte
             + config.max_position_embeddings * h   # wpe
             + config.num_hidden_layers * per_layer
             + 2 * h                        # ln_f
             + h * config.vocab_size)       # lm_head
    return total * dtype_bytes


def blocks_for_budget(config, block_size=DEFAULT_BLOCK_SIZE, budget=None,
                      headroom=_BUDGET_HEADROOM):
    """KV blocks the resolved HBM budget affords after the model's
    parameters and a headroom fraction. Returns ``None`` when no budget
    resolves (CPU harness without ``HETU_HBM_BUDGET``); raises when a
    budget resolves but can't fit even two blocks."""
    from ..analysis.memory import fmt_bytes, resolve_budget
    budget = resolve_budget(budget)
    if budget is None:
        return None
    avail = int(budget * (1.0 - headroom)) - gpt_param_bytes(config)
    nb = avail // kv_block_bytes(config, block_size)
    if nb < 2:
        raise ValueError(
            f"HBM budget {fmt_bytes(budget)} leaves room for {nb} KV "
            f"block(s) after {fmt_bytes(gpt_param_bytes(config))} of "
            f"parameters — the model doesn't fit a paged cache here")
    return int(nb)


class BlockAllocator:
    """Free-list over ``num_blocks`` usable block ids.

    ``alloc(n)`` is all-or-nothing (raises :class:`KVCacheExhausted`
    listing need vs. free, allocating nothing). Blocks hand out
    lowest-id-first and freed blocks rejoin in sorted order, so
    identical alloc/free traces produce identical tables — exhaustion
    and reuse are deterministic, not load-dependent."""

    def __init__(self, num_blocks, block_size, first_id=0):
        self.num_blocks = int(num_blocks)
        self.block_size = int(block_size)
        self._first = int(first_id)
        self._free = collections.deque(
            range(self._first, self._first + self.num_blocks))
        self._live = set()

    @property
    def available(self):
        return len(self._free)

    @property
    def used(self):
        return len(self._live)

    def blocks_for_tokens(self, ntokens):
        return max(1, math.ceil(int(ntokens) / self.block_size))

    def alloc(self, n):
        n = int(n)
        if n > len(self._free):
            raise KVCacheExhausted(
                f"KV cache exhausted: need {n} block(s), "
                f"{len(self._free)} free of {self.num_blocks}")
        out = [self._free.popleft() for _ in range(n)]
        self._live.update(out)
        return out

    def free(self, blocks):
        for b in blocks:
            if b not in self._live:
                raise ValueError(f"double free of KV block {b}")
            self._live.discard(b)
        # sorted re-insertion keeps reuse deterministic regardless of
        # the order sequences finished in
        self._free = collections.deque(
            sorted(list(self._free) + list(blocks)))


class PagedKVCache:
    """Per-layer pooled K/V buffers + per-sequence block tables.

    The pools are jax arrays the engine threads through its (donated)
    jit calls; everything else — tables, the allocator, slot math — is
    host-side numpy. ``config`` is GPT-shaped (``num_hidden_layers``,
    ``num_attention_heads``, ``hidden_size``).
    """

    def __init__(self, config, num_blocks=None,
                 block_size=DEFAULT_BLOCK_SIZE, budget=None,
                 telemetry=None):
        from .. import telemetry as _telemetry
        self.config = config
        self.block_size = int(block_size)
        if num_blocks is None:
            num_blocks = blocks_for_budget(config, self.block_size,
                                           budget)
            if num_blocks is None:
                raise ValueError(
                    "no HBM budget resolvable to size the KV pool "
                    "(CPU harness?): pass num_blocks= explicitly or "
                    "set HETU_HBM_BUDGET")
        self.num_blocks = int(num_blocks)
        self.telemetry = _telemetry.resolve(telemetry)
        # block 0 is the scratch block padded lanes target; real
        # sequences allocate from 1..num_blocks
        self.allocator = BlockAllocator(self.num_blocks, self.block_size,
                                        first_id=1)
        self.pools = self._init_pools()
        self.tables = {}            # seq_id -> [block ids]
        self.peak_utilization = 0.0

    def _init_pools(self):
        import jax.numpy as jnp
        nh = self.config.num_attention_heads
        hs = self.config.hidden_size // nh
        shape = (self.num_blocks + 1, self.block_size, nh, hs)
        return [{"k": jnp.zeros(shape, jnp.float32),
                 "v": jnp.zeros(shape, jnp.float32)}
                for _ in range(self.config.num_hidden_layers)]

    # -- accounting ------------------------------------------------------
    @property
    def used_blocks(self):
        return self.allocator.used

    @property
    def utilization(self):
        """Fraction of the (non-scratch) pool held by live sequences."""
        return self.allocator.used / self.num_blocks

    def hbm_bytes(self):
        """Bytes the pools occupy (scratch block included)."""
        return kv_block_bytes(self.config, self.block_size) \
            * (self.num_blocks + 1)

    def can_admit(self, ntokens):
        return self.allocator.blocks_for_tokens(ntokens) \
            <= self.allocator.available

    def fits_at_all(self, ntokens):
        """Whether a sequence of ``ntokens`` could EVER be served by
        this pool (the submit-time guard)."""
        return self.allocator.blocks_for_tokens(ntokens) \
            <= self.allocator.num_blocks

    def _note_util(self):
        u = self.utilization
        if u > self.peak_utilization:
            self.peak_utilization = u
        if self.telemetry.enabled:
            self.telemetry.set_gauge("kv_blocks_used",
                                     self.allocator.used)
            self.telemetry.set_gauge("kv_blocks_free",
                                     self.allocator.available)
            self.telemetry.set_gauge("kv_seqs", len(self.tables))
            self.telemetry.set_gauge("kv_hbm_utilization", u)

    # -- sequence lifecycle ---------------------------------------------
    def add_seq(self, seq_id, ntokens):
        """Allocate blocks covering ``ntokens`` positions for a new
        sequence (all-or-nothing; raises :class:`KVCacheExhausted`)."""
        if seq_id in self.tables:
            raise ValueError(f"sequence {seq_id} already has a table")
        blocks = self.allocator.alloc(
            self.allocator.blocks_for_tokens(ntokens))
        self.tables[seq_id] = blocks
        self._note_util()
        return blocks

    def extend_seq(self, seq_id, ntokens):
        """Grow a sequence's table to cover ``ntokens`` total positions
        (no-op when it already does)."""
        table = self.tables[seq_id]
        need = self.allocator.blocks_for_tokens(ntokens) - len(table)
        if need > 0:
            table.extend(self.allocator.alloc(need))
            self._note_util()
        return table

    def free_seq(self, seq_id):
        blocks = self.tables.pop(seq_id, None)
        if blocks:
            self.allocator.free(blocks)
        self._note_util()

    def capacity_tokens(self, seq_id):
        return len(self.tables[seq_id]) * self.block_size

    # -- slot math (host-side; the jit programs take these as inputs) ---
    def slot_of(self, seq_id, pos):
        """Flat pool slot of one position."""
        table = self.tables[seq_id]
        return table[pos // self.block_size] * self.block_size \
            + pos % self.block_size

    def slot_mapping(self, seq_id, start, stop):
        """Flat slots for positions ``[start, stop)`` as int32."""
        table = np.asarray(self.tables[seq_id], np.int32)
        pos = np.arange(start, stop)
        return (table[pos // self.block_size] * self.block_size
                + pos % self.block_size).astype(np.int32)

    def gather_slots(self, seq_ids, width):
        """``[len(seq_ids), width]`` int32 slot grid covering positions
        ``0..width-1`` per sequence; positions beyond a sequence's
        allocated blocks point at the scratch block (they sit behind
        the attention length mask anyway)."""
        bs = self.block_size
        off = np.arange(width, dtype=np.int64)
        out = np.zeros((len(seq_ids), width), np.int32)
        for i, sid in enumerate(seq_ids):
            table = np.asarray(self.tables[sid], np.int64)
            cap = len(table) * bs
            w = min(width, cap)
            out[i, :w] = (table[off[:w] // bs] * bs
                          + off[:w] % bs).astype(np.int32)
        return out
