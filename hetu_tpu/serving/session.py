"""Frozen-graph inference sessions.

``InferenceSession`` wraps an eval-only :class:`~hetu_tpu.executor.Executor`
(no optimizer state, no dataloader machinery) restored from an
``Executor.save`` checkpoint (one ``.npy`` per parameter + sidecar), and
serves ``predict(feed_dict)`` with MANDATORY shape bucketing: the batch
dim pads up to the next power-of-two bucket and (optionally) a ragged
sequence dim pads to a fixed bucket, so the number of distinct compiled
programs — visible as the ``jit_compiles`` telemetry counter — is bounded
by the bucket count no matter how ragged the traffic is. TF-Serving's
frozen-graph session is the shape; the executor's per-feed-shape jit
cache is the mechanism.
"""
from __future__ import annotations

import time

import numpy as np

from .. import telemetry as _telemetry
from ..executor import Executor, HetuConfig
from ..graph.autodiff import find_topo_sort
from ..graph.node import Op

__all__ = ["InferenceSession", "next_bucket"]


def next_bucket(n, buckets=None):
    """Smallest bucket >= n. ``buckets=None`` means the power-of-two
    ladder {1, 2, 4, 8, ...}; an explicit sequence must be sorted."""
    n = int(n)
    if buckets is None:
        b = 1
        while b < n:
            b *= 2
        return b
    for b in buckets:
        if b >= n:
            return int(b)
    raise ValueError(f"batch/seq of {n} exceeds the largest configured "
                     f"bucket {max(buckets)}")


def _pad_axis(arr, target, axis):
    """Pad by repeating the trailing slice (edge padding keeps ids in
    vocabulary range and dense features finite — zeros could be an
    out-of-distribution input for either)."""
    n = arr.shape[axis]
    if n == target:
        return arr
    take = [slice(None)] * arr.ndim
    take[axis] = slice(n - 1, n)
    pad = np.repeat(arr[tuple(take)], target - n, axis=axis)
    return np.concatenate([arr, pad], axis=axis)


class InferenceSession:
    """Serve ``predict()`` over a frozen eval graph.

    Parameters
    ----------
    eval_node_list : list[Op]
        Output nodes (logits, probabilities, ...). The graph must be
        inference-only: an optimizer, dataloader, or PS push op in the
        closure raises at construction — freezing is a contract, not a
        convention.
    checkpoint : str, optional
        ``Executor.save`` directory to restore parameters from.
    batch_buckets : sequence[int], optional
        Batch-dim buckets (default: powers of two).
    seq_buckets : sequence[int], optional
        When set, dim ``seq_axis`` of every feed with more than
        ``seq_axis`` dims also pads up to a bucket (causal LMs: extra
        trailing positions never change real positions' outputs).
    ps_read_only : bool
        Wrap the session's PS client so any push raises (default True).
    executor_kwargs :
        Forwarded to :class:`HetuConfig` (``ctx``, ``comm_mode``,
        ``mesh``, ``dtype``, ``telemetry``, ...).
    """

    def __init__(self, eval_node_list, checkpoint=None, *,
                 batch_buckets=None, seq_buckets=None, seq_axis=1,
                 ps_read_only=True, embed_cache_rows=0, telemetry=None,
                 **executor_kwargs):
        eval_node_list = list(eval_node_list)
        self._check_frozen(eval_node_list)
        self.telemetry = _telemetry.resolve(telemetry)
        self.batch_buckets = (tuple(sorted(batch_buckets))
                              if batch_buckets else None)
        self.seq_buckets = (tuple(sorted(seq_buckets))
                            if seq_buckets else None)
        self.seq_axis = int(seq_axis)

        config = HetuConfig(eval_node_list=eval_node_list,
                            telemetry=self.telemetry, **executor_kwargs)
        self.ps_client = None
        if config.ps_comm is not None and ps_read_only:
            from .embedding import ReadOnlyPSClient
            if not isinstance(config.ps_comm, ReadOnlyPSClient):
                config.ps_comm = ReadOnlyPSClient(
                    config.ps_comm, cache_rows=embed_cache_rows,
                    telemetry=self.telemetry)
            self.ps_client = config.ps_comm
        self.executor = Executor({"default": eval_node_list},
                                 config=config)
        sub = self.executor.subexecutors["default"]
        assert not sub.training
        self.feed_nodes = list(sub.feed_nodes)
        # the PS sparse-pull path consumes raw id feeds that are also
        # plain graph inputs — names resolve either way
        self._by_name = {n.name: n for n in self.feed_nodes}
        if checkpoint is not None:
            self.load(checkpoint)

    @staticmethod
    def _check_frozen(eval_node_list):
        # the frozen-graph contract (no optimizer / PS push / dataloader
        # ops) is an analysis pass (HT15x findings); construction keeps
        # raising ValueError so the session API is unchanged
        from ..analysis import Report, frozen_graph_pass
        report = Report()
        frozen_graph_pass(find_topo_sort(eval_node_list), report)
        if report.errors:
            raise ValueError("\n".join(f.message for f in report.errors))

    # ------------------------------------------------------------------
    def load(self, checkpoint):
        """Restore parameters from an ``Executor.save`` directory."""
        self.executor.load(checkpoint)
        return self

    def params_by_name(self):
        """{param name: device array} — the bridge to weight-level
        serving paths (GPTDecoder.from_session)."""
        return {node.name: self.executor.params[sid]
                for sid, node in self.executor._param_nodes.items()}

    def node_of(self, key):
        if isinstance(key, Op):
            return key
        try:
            return self._by_name[key]
        except KeyError:
            raise KeyError(
                f"unknown feed {key!r}; session feeds are "
                f"{sorted(self._by_name)}") from None

    # ------------------------------------------------------------------
    def predict(self, feed_dict, unpad=True):
        """Run the frozen forward on one (ragged) batch.

        Feeds pad up to the shape bucket, outputs slice back to the real
        batch (and sequence) before returning, as numpy arrays."""
        t0 = time.perf_counter()
        feeds = {self.node_of(k): np.asarray(v)
                 for k, v in feed_dict.items()}
        sizes = {v.shape[0] for v in feeds.values() if v.ndim}
        if len(sizes) != 1:
            raise ValueError(
                f"feeds disagree on batch size: {sorted(sizes)}")
        n = sizes.pop()
        b = next_bucket(n, self.batch_buckets)
        seq_pads = {}      # bucket -> set of real lengths padded to it
        padded = {}
        for node, v in feeds.items():
            v = _pad_axis(v, b, 0)
            if self.seq_buckets is not None and v.ndim > self.seq_axis:
                s = v.shape[self.seq_axis]
                sb = next_bucket(s, self.seq_buckets)
                seq_pads.setdefault(sb, set()).add(s)
                v = _pad_axis(v, sb, self.seq_axis)
            padded[node] = v
        # black box: a predict that never returns (wedged PS pull, hung
        # device) is a pending flight entry carrying the bucket size;
        # tag/byte-sum construction stays off the disabled hot path
        frec = None
        if self.telemetry.enabled:
            frec = self.telemetry.flight.start(
                "serve", "serve_predict", tag=f"bucket{b}",
                nbytes=sum(int(v.nbytes) for v in padded.values()))
        outs = self.executor.run("default", feed_dict=padded,
                                 convert_to_numpy_ret_vals=True)
        self.telemetry.flight_complete(frec)
        if unpad:
            outs = [self._trim(o, n, b, seq_pads) for o in outs]
        tel = self.telemetry
        if tel.enabled:
            tel.inc("serve_predictions")
            tel.observe("predict_ms", (time.perf_counter() - t0) * 1e3)
            tel.set_gauge("serve_batch_bucket", b)
        return outs

    def _trim(self, out, n, b, seq_pads):
        if out is None or not getattr(out, "ndim", 0):
            return out
        if out.shape[0] == b:
            out = out[:n]
        if seq_pads and out.ndim > self.seq_axis + 1:
            # ndim guard: only outputs with structure BEYOND
            # [batch, features] (e.g. logits [B, S, V]) are treated as
            # sequence-shaped — a [B, C] head whose class count happens
            # to equal a seq bucket must never be cut; per-position 2-D
            # outputs come back padded, callers slice themselves
            width = out.shape[self.seq_axis]
            reals = seq_pads.get(width)
            # trim ONLY when unambiguous: every feed padded to this
            # bucket had the same real length (two ragged feeds sharing
            # a bucket would make any cut a guess — return padded then)
            if reals is not None and len(reals) == 1:
                real = next(iter(reals))
                if real != width:
                    idx = [slice(None)] * out.ndim
                    idx[self.seq_axis] = slice(0, real)
                    out = out[tuple(idx)]
        return out

    # ------------------------------------------------------------------
    def close(self):
        self.executor.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False
