"""Minimal stdlib HTTP frontend for a session or micro-batcher.

JSON in / JSON out, five routes:

* ``POST /v1/predict`` — body ``{"inputs": {feed_name: nested_list}}``;
  each input carries its batch dim. Response
  ``{"outputs": [...], "latency_ms": ..., "request_id": ...}``.
* ``GET /healthz`` — liveness (an SLO probe when SLOs are configured).
* ``GET /metrics`` — Prometheus text scrape of the serving telemetry
  (404 when telemetry is disabled).
* ``GET /v1/requests`` — live in-flight table from the backend
  (``inflight_requests()``; 404 when the backend has none).
* ``GET /stats`` — frontend + backend snapshot (``stats()``), the
  queue-depth / KV-pressure / compile-accounting view a fleet
  dashboard scrapes.

**Request ids.** Ingress is where the end-to-end tracing id is born: a
client-supplied ``x-request-id`` header is honored, otherwise one is
minted (``serving/lifecycle.py``), and every response echoes it back in
the ``X-Request-Id`` header and the JSON body — including errors, so a
user-reported failure is greppable straight into the trace and the
in-flight dumps. Backends whose ``submit`` accepts ``request_id=``
(engine, batcher, router) get it passed through.

**Overload is not a 500.** ``EngineOverloaded`` maps to 429 and
``RouterOverloaded`` / ``KVCacheExhausted`` to 503, each with a
structured JSON body (``error``, ``request_id``, ``retry_after_ms``)
and a ``Retry-After`` header — the backpressure signal a client can
act on, where a bare 500 just looks broken. Shed requests still count
against the error SLO: a shedding replica *should* drain out of the
router rotation.

The backend is either an :class:`InferenceSession` (each request runs
its own forward) or anything with ``submit(...) -> Future`` (a
:class:`MicroBatcher`, a :class:`ContinuousBatchingEngine` front, a
:class:`ReplicaRouter` — the configuration the load driver in
``bench.py serving`` measures). A production frontend would speak gRPC;
this is deliberately the smallest thing that lets a multi-threaded
closed-loop client exercise the batching + bucketing stack end to end.
"""
from __future__ import annotations

import inspect
import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np

from .. import telemetry as _telemetry
from .kvcache import KVCacheExhausted
from .lifecycle import mint_request_id
from .router import RouterOverloaded, SLOWindow
from .scheduler import EngineOverloaded

__all__ = ["ServingHTTPServer"]


class ServingHTTPServer:
    """``slo_p99_ms`` / ``slo_error_rate`` make ``/healthz`` an SLO
    probe: over a rolling window of the last ``slo_window`` requests,
    a breached latency p99 or error rate flips the endpoint to 503 —
    the signal a load balancer needs to drain a degraded replica
    *before* users notice, instead of a liveness-only 200 that stays
    green while every request times out. With neither SLO configured
    ``/healthz`` keeps its plain-liveness behavior."""

    def __init__(self, backend, host="127.0.0.1", port=0, telemetry=None,
                 request_timeout_s=60.0, slo_p99_ms=None,
                 slo_error_rate=None, slo_window=128):
        self.backend = backend
        self.telemetry = _telemetry.resolve(telemetry)
        self.host = host
        self.port = int(port)
        self.request_timeout_s = float(request_timeout_s)
        self.slo_p99_ms = slo_p99_ms
        self.slo_error_rate = slo_error_rate
        # one shared breach definition with the replica router and the
        # decode engine (serving/router.py)
        self._slo = SLOWindow(slo_p99_ms, slo_error_rate, slo_window)
        self._httpd = None
        self._thread = None
        # the session backend is NOT thread-safe (shape inference writes
        # on shared graph nodes); ThreadingHTTPServer handlers must
        # single-flight it. The batcher backend serializes internally.
        self._backend_lock = threading.Lock()
        # does the backend's submit() take the tracing id? (engine,
        # batcher, router: yes; decided once, not per request)
        self._submit_takes_rid = False
        submit = getattr(backend, "submit", None)
        if callable(submit):
            try:
                params = inspect.signature(submit).parameters
                self._submit_takes_rid = "request_id" in params or any(
                    p.kind is inspect.Parameter.VAR_KEYWORD
                    for p in params.values())
            except (TypeError, ValueError):
                pass

    def _note_request(self, ok, ms):
        self._slo.note(ok, ms)

    def health(self):
        """(healthy, reason) under the configured SLOs."""
        return self._slo.health()

    # ------------------------------------------------------------------
    def _predict(self, inputs, request_id=None):
        feeds = {str(k): np.asarray(v) for k, v in inputs.items()}
        backend = self.backend
        if hasattr(backend, "submit"):      # batcher / engine / router
            if self._submit_takes_rid and request_id is not None:
                fut = backend.submit(feeds, request_id=request_id)
            else:
                fut = backend.submit(feeds)
            outs = fut.result(self.request_timeout_s)
        else:                                   # InferenceSession
            with self._backend_lock:
                outs = backend.predict(feeds)
        if not isinstance(outs, (list, tuple)):
            outs = [outs]
        return [np.asarray(o).tolist() for o in outs]

    # ------------------------------------------------------------------
    def start(self):
        """Bind + serve on a daemon thread; returns the bound port."""
        server = self

        class Handler(BaseHTTPRequestHandler):
            def _reply(self, code, body, ctype="application/json",
                       rid=None, retry_after_s=None):
                data = body if isinstance(body, bytes) \
                    else json.dumps(body).encode()
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(data)))
                if rid is not None:
                    self.send_header("X-Request-Id", rid)
                if retry_after_s is not None:
                    self.send_header("Retry-After", str(retry_after_s))
                self.end_headers()
                self.wfile.write(data)

            def do_GET(self):                           # noqa: N802
                path = self.path.rstrip("/")
                if path == "/healthz":
                    healthy, reason = server.health()
                    # healthy keeps the plain liveness body (pinned by
                    # tests); the breach reason rides the 503 only
                    self._reply(200 if healthy else 503,
                                {"ok": True} if healthy
                                else {"ok": False, "reason": reason})
                elif path == "/metrics":
                    tel = server.telemetry
                    if not tel.enabled:
                        self.send_error(404, "telemetry disabled")
                        return
                    self._reply(200, tel.metrics.to_prometheus().encode(),
                                ctype="text/plain; version=0.0.4")
                elif path == "/v1/requests":
                    fn = getattr(server.backend, "inflight_requests",
                                 None)
                    if not callable(fn):
                        self.send_error(
                            404, "backend has no in-flight introspection")
                        return
                    rows = fn()
                    self._reply(200, {"requests": rows,
                                      "count": len(rows)})
                elif path == "/stats":
                    healthy, reason = server.health()
                    body = {"healthy": healthy, "reason": reason,
                            "slo_p99_ms": server.slo_p99_ms,
                            "slo_error_rate": server.slo_error_rate}
                    fn = getattr(server.backend, "stats", None)
                    if callable(fn):
                        body["backend"] = fn()
                    self._reply(200, body)
                else:
                    self.send_error(404)

            def do_POST(self):                          # noqa: N802
                if self.path.rstrip("/") != "/v1/predict":
                    self.send_error(404)
                    return
                t0 = time.perf_counter()
                # ingress mints the end-to-end tracing id (or honors
                # the client's); EVERY reply below echoes it
                rid = self.headers.get("x-request-id") \
                    or mint_request_id()
                try:
                    n = int(self.headers.get("Content-Length", 0))
                    req = json.loads(self.rfile.read(n) or b"{}")
                    inputs = req.get("inputs", {})
                    if not isinstance(inputs, dict):
                        raise ValueError(
                            '"inputs" must be an object of '
                            "{feed_name: nested_list}")
                    outs = server._predict(inputs, request_id=rid)
                except (ValueError, KeyError, TypeError) as e:
                    # client errors don't count against the error SLO
                    self._reply(400,
                                {"error": f"{type(e).__name__}: {e}",
                                 "request_id": rid}, rid=rid)
                    return
                except (EngineOverloaded, RouterOverloaded,
                        KVCacheExhausted) as e:
                    # backpressure, not breakage: 429 when THIS
                    # engine's queue shed us (retry here soon), 503
                    # when the fleet/pool can't take it (retry later,
                    # ideally elsewhere). Counts against the error SLO
                    # so a shedding replica drains out of the router
                    # rotation.
                    server._note_request(
                        False, (time.perf_counter() - t0) * 1e3)
                    code, retry_s = (429, 1) \
                        if isinstance(e, EngineOverloaded) else (503, 2)
                    if server.telemetry.enabled:
                        server.telemetry.inc("http_shed_requests")
                    self._reply(code,
                                {"error": f"{type(e).__name__}: {e}",
                                 "request_id": rid,
                                 "retry_after_ms": retry_s * 1000},
                                rid=rid, retry_after_s=retry_s)
                    return
                except Exception as e:                  # noqa: BLE001
                    server._note_request(
                        False, (time.perf_counter() - t0) * 1e3)
                    self._reply(500,
                                {"error": f"{type(e).__name__}: {e}",
                                 "request_id": rid}, rid=rid)
                    return
                ms = (time.perf_counter() - t0) * 1e3
                server._note_request(True, ms)
                if server.telemetry.enabled:
                    server.telemetry.observe("http_request_ms", ms)
                self._reply(200, {"outputs": outs,
                                  "latency_ms": round(ms, 3),
                                  "request_id": rid}, rid=rid)

            def log_message(self, *a):                  # quiet
                pass

        self._httpd = ThreadingHTTPServer((self.host, self.port), Handler)
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True,
                                        name="serving-http")
        self._thread.start()
        return self.port

    def stop(self):
        if self._httpd is not None:
            from ..telemetry.metrics import stop_http_server
            stop_http_server(self._httpd, self._thread)
            self._thread = None
            self._httpd = None

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        self.stop()
        return False
