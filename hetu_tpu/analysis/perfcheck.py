"""Doctor-validated soundness twin of the efficiency verifier (HT910).

The static pass (``analysis/efficiency.py``) *prices* inefficiencies
in predicted ms/step; this module checks those prices against
reality — the racecheck/rangecheck idiom applied to performance. A
short telemetry-traced training window runs, the perf doctor
(``telemetry/doctor.py``) attributes every step to disjoint buckets,
and each priced static claim is held against the **measured** bucket
it charges (``efficiency.DOCTOR_BUCKET``):

* **soundness gate** — a claim's ``estimated_ms_per_step`` must not
  exceed what its measured bucket actually contains, past a documented
  bound (:data:`SOUND_FACTOR` x measured + :data:`SOUND_SLACK_MS`).
  A violation is an **HT910** error: the pricing model promised
  savings a real step has no room for, which would rot every report
  built on it.
* **constant-feed detection** (HT905's dynamic half) — feeds whose
  bytes are identical across every measured step are re-transferred
  h2d each step for nothing; statically unknowable, measured here.
* **A/B confirmation** — :func:`ab_bucketed_allreduce` measures the
  bucketed-vs-per-grad collective delta the HT904 pricing predicts,
  with the prediction made from a curve fitted on the *same machine's*
  measured points; the test gate holds the two within
  :data:`AB_TOLERANCE`.

CLI::

    python -m hetu_tpu.analysis.perfcheck [models...] [--steps N]
        [--json]

drives the default zoo pair (mlp + wdl_adult — a dense and a sparse
path), validates every surviving priced claim, and exits 1 on any
HT910 violation.
"""
from __future__ import annotations

import json
import os
import tempfile

import numpy as np

from .findings import Report
from .efficiency import DOCTOR_BUCKET, efficiency_pass

__all__ = ["measure_buckets", "soundness_pass", "perfcheck_model",
           "ab_bucketed_allreduce", "serving_claim_check",
           "SOUND_FACTOR", "SOUND_SLACK_MS", "AB_TOLERANCE", "main"]

# a priced claim survives while estimated_ms_per_step <= SOUND_FACTOR x
# measured-bucket ms/step + SOUND_SLACK_MS: the factor absorbs the
# cold-start model's class-level error (it must RANK, not predict
# walls), the slack absorbs sub-threshold buckets on fast steps. Past
# that, the static model is inventing time that the measured step does
# not contain.
SOUND_FACTOR = 3.0
SOUND_SLACK_MS = 0.5

# measured-vs-predicted agreement bound for the HT904 A/B: predictions
# come from a latency+bandwidth curve fitted on this machine's own
# measured collective points, so the two must agree within 4x either
# way (CPU-harness dispatch jitter dominates below the ms scale)
AB_TOLERANCE = 4.0

# feeds below this never matter for HT905's constant-feed check
_CONST_FEED_FLOOR = 64 << 10


def measure_buckets(executor, feed_fn, steps=8, name="default"):
    """Drive ``steps`` ``run()`` calls and return the doctor's
    per-step bucket attribution ``{bucket: ms/step}`` for the window
    (plus the raw attribution dict). The executor must have been built
    with a telemetry sink whose ``out_dir`` we can flush and read."""
    from ..telemetry import doctor

    tel = executor.config.telemetry
    assert tel.enabled and tel.out_dir, \
        "measure_buckets needs Telemetry(enabled=True, out_dir=...)"
    for i in range(steps):
        executor.run(name, feed_dict=feed_fn(i))
    tel.flush()
    per = doctor.attribute_trace(tel.out_dir)
    if not per:
        return {}, None
    label = next(iter(per))
    return dict(per[label]["per_step_ms"]), per[label]


def soundness_pass(findings, measured_buckets, report=None,
                   factor=SOUND_FACTOR, slack_ms=SOUND_SLACK_MS):
    """Hold every priced static claim against the measured bucket it
    charges. Emits HT910 errors into ``report``; returns (report,
    checked count). Claims with no bucket (HT908 advisories) and
    buckets the doctor did not measure are vacuous."""
    if report is None:
        report = Report()
    checked = 0
    for f in findings:
        bucket = f.data.get("bucket") or DOCTOR_BUCKET.get(f.code)
        claim = f.data.get("estimated_ms_per_step")
        if bucket is None or claim is None or \
                bucket not in measured_buckets:
            continue
        checked += 1
        measured = float(measured_buckets[bucket])
        bound = factor * measured + slack_ms
        if float(claim) > bound:
            report.add(
                "HT910", "error",
                f"{f.code} claims {float(claim):.4f} ms/step of "
                f"savings from the '{bucket}' bucket, but the measured "
                f"bucket holds only {measured:.4f} ms/step (bound "
                f"{bound:.4f} = {factor:g}x + {slack_ms:g}) — the "
                f"pricing model is unsound here; re-measure the "
                f"CostDB or fix the estimator", node=f.node,
                where=f.where, claim_code=f.code,
                claimed_ms=round(float(claim), 6),
                measured_ms=round(measured, 6))
    return report, checked


def serving_claim_check(claimed_tokens_per_s, counted_tokens, wall_s,
                        factor=SOUND_FACTOR):
    """The serving half of the HT910 attribution discipline: a bench's
    *claimed* tokens/sec must agree with the rate its own telemetry
    counters support — ``counted_tokens`` (the engine's ``<name>_tokens``
    counter delta over the measured window) divided by the window's
    wall clock. Within ``factor`` either way the claim is attributed;
    outside it, the bench's workload arithmetic and the engine's token
    accounting have drifted apart and the number is asserted, not
    measured. Returns ``(ok, measured_tokens_per_s)``."""
    wall_s = float(wall_s)
    if wall_s <= 0 or counted_tokens <= 0:
        return False, 0.0
    measured = float(counted_tokens) / wall_s
    claimed = float(claimed_tokens_per_s)
    if claimed <= 0:
        return False, measured
    ratio = claimed / measured
    return (1.0 / factor) <= ratio <= factor, measured


def _constant_feeds(feed_history, report, costdb=None):
    """HT905 dynamic half: feeds byte-identical across every measured
    step re-pay their h2d each step for nothing. ``feed_history`` is
    [{node: array}] per step."""
    from .efficiency import _db

    if len(feed_history) < 2:
        return report
    db = _db(costdb)
    first = feed_history[0]
    for node, arr in first.items():
        a0 = np.asarray(arr)
        if a0.nbytes < _CONST_FEED_FLOOR:
            continue
        same = all(np.array_equal(a0, np.asarray(h[node]))
                   for h in feed_history[1:] if node in h)
        if not same:
            continue
        ms, source = db.estimate_info("h2d", a0.nbytes)
        report.add(
            "HT905", "warn",
            f"feed {getattr(node, 'name', node)} was byte-identical "
            f"across {len(feed_history)} measured steps "
            f"({a0.nbytes / 1e6:.2f} MB) — a constant re-transferred "
            f"h2d every step; device_put it once (or make it a "
            f"Variable) instead of feeding it", node=node,
            estimated_ms_per_step=round(ms, 6),
            bucket=DOCTOR_BUCKET["HT905"], source=source,
            bytes=int(a0.nbytes))
    return report


def perfcheck_model(model, steps=8, costdb=None, feed_fn=None,
                    tel_dir=None):
    """Round-trip one zoo model: run the static priced lint, drive
    ``steps`` telemetry-traced training steps, doctor-attribute them,
    and gate every surviving claim (HT910) plus the dynamic
    constant-feed check. Returns ``(report, claims_checked, buckets,
    static_report)`` — ``report`` holds HT910 + dynamic findings."""
    from . import zoo
    from .rangecheck import _synth_feeds
    from .shapes import shape_pass, _resolve_feed_shapes
    from ..executor import Executor
    from ..graph.autodiff import find_topo_sort
    from ..telemetry import Telemetry

    eval_nodes, feed_shapes = zoo.build(model)
    specs = _resolve_feed_shapes(feed_shapes,
                                 find_topo_sort(list(eval_nodes)))
    if feed_fn is None:
        def feed_fn(i):                     # noqa: F811 — default feeds
            return _synth_feeds(specs, seed=i)

    own_dir = tel_dir is None
    if own_dir:
        tel_dir = tempfile.mkdtemp(prefix="perfcheck_")
    tel = Telemetry(enabled=True, out_dir=tel_dir, rank=0)
    exe = Executor(list(eval_nodes), telemetry=tel)
    history = []

    def recorded(i):
        feeds = feed_fn(i)
        history.append(feeds)
        return feeds

    try:
        buckets, _attr = measure_buckets(exe, recorded, steps=steps)
    finally:
        exe.close()
        if own_dir:
            # the attribution is already in memory; don't leak a trace
            # dir per invocation (out_dir=None disarms the atexit
            # flush that would otherwise re-write into the removed dir)
            import shutil
            shutil.rmtree(tel_dir, ignore_errors=True)
            tel.out_dir = None

    # static side over the EXECUTOR's topo (comm ops spliced), priced
    # with the same DB the runtime would plan against
    topo = exe.subexecutors["default"].topo_order
    dtypes = {}
    shapes = shape_pass(topo, Report(), feed_shapes=feed_shapes,
                        dtypes_out=dtypes)
    static = Report()
    efficiency_pass(topo, static, shapes=shapes, dtypes=dtypes,
                    config=exe.config, costdb=costdb,
                    eval_nodes=eval_nodes, steps=steps)
    report, checked = soundness_pass(static.findings, buckets)
    _constant_feeds(history, report, costdb=costdb)
    return report, checked, buckets, static


# ---------------------------------------------------------------------------
# HT904 measured A/B: per-grad vs bucketed collective emission
# ---------------------------------------------------------------------------

def ab_bucketed_allreduce(n_grads=12, nbytes=1 << 14, reps=8, db=None):
    """Measure the fragmented-vs-bucketed collective delta the HT904
    pricing predicts, on this machine's devices: ``n_grads`` separate
    psum dispatches of ``nbytes`` each, against one psum over the
    concatenation. The *prediction* comes from a latency+bandwidth
    curve fitted to collective points measured here first (the exact
    estimate_info path HT904 prices with), so predicted and measured
    deltas must agree within :data:`AB_TOLERANCE` either way.

    Returns ``{predicted_ms, measured_ms, per_grad_ms, bucketed_ms,
    points}`` — or None on single-device backends (no collective to
    measure)."""
    import jax
    import jax.numpy as jnp

    from ..telemetry.costdb import CostDB
    from ..tune.autotune import timeit

    ndev = len(jax.devices())
    if ndev < 2:
        return None
    if db is None:
        # in-memory only: never save()d, so no file/dir to clean up
        db = CostDB(os.path.join(tempfile.gettempdir(),
                                 "perfab_unwritten.json"))
        db._entries = {}        # don't read a stale file either
    rng = np.random.RandomState(0)

    def shard(total_bytes):
        n = max(ndev, (total_bytes // 4) // ndev * ndev)
        host = rng.randn(n).astype(np.float32).reshape(ndev, -1)
        return jax.device_put_sharded(list(host), jax.devices()[:ndev])

    psum = jax.pmap(lambda x: jax.lax.psum(x, "i"), axis_name="i")

    def sync(x):
        return float(np.asarray(x)[0, 0])

    # fit the curve from measured points at both size classes — the
    # same producer HT904's estimate_info consumes
    for sz in (nbytes, n_grads * nbytes):
        dev = shard(sz)
        ms = timeit(lambda: psum(dev), sync, reps=reps) * 1000.0
        db.record("allreduce", sz, "float32", ms, source="perfcheck",
                  nbytes=sz)

    predicted = (n_grads * db.estimate_info("allreduce", nbytes)[0]
                 - db.estimate_info("allreduce", n_grads * nbytes)[0])

    grads = [shard(nbytes) for _ in range(n_grads)]
    big = shard(n_grads * nbytes)

    def per_grad():
        outs = [psum(g) for g in grads]
        return outs[-1]

    per_grad_ms = timeit(per_grad, sync, reps=reps) * 1000.0
    bucketed_ms = timeit(lambda: psum(big), sync, reps=reps) * 1000.0
    measured = per_grad_ms - bucketed_ms
    return {"predicted_ms": round(predicted, 4),
            "measured_ms": round(measured, 4),
            "per_grad_ms": round(per_grad_ms, 4),
            "bucketed_ms": round(bucketed_ms, 4),
            "n_grads": n_grads, "nbytes": nbytes,
            "curve": db.curve("allreduce")}


DEFAULT_MODELS = ("mlp", "wdl_adult")


def main(argv=None):
    import argparse
    import sys

    parser = argparse.ArgumentParser(
        prog="python -m hetu_tpu.analysis.perfcheck",
        description="doctor-validated soundness twin: run zoo models "
                    "under the trace, attribute measured buckets, and "
                    "gate every priced HT9xx claim against them "
                    "(HT910)")
    parser.add_argument("models", nargs="*",
                        help=f"zoo models (default: "
                             f"{' '.join(DEFAULT_MODELS)})")
    parser.add_argument("--steps", type=int, default=8)
    parser.add_argument("--json", action="store_true")
    args = parser.parse_args(argv)

    models = args.models or list(DEFAULT_MODELS)
    rc = 0
    out = {}
    for model in models:
        report, checked, buckets, static = perfcheck_model(
            model, steps=args.steps)
        viol = [f for f in report.findings if f.code == "HT910"]
        out[model] = {
            "claims": len(static), "checked": checked,
            "violations": len(viol),
            "dynamic_findings": len(report) - len(viol),
            "buckets": {b: v for b, v in buckets.items() if v > 0}}
        if not args.json:
            print(f"== {model}: {'ok' if not viol else 'UNSOUND'} "
                  f"({len(static)} priced claim(s), {checked} checked "
                  f"against measured buckets, {len(viol)} "
                  f"violation(s))")
            for f in report.findings:
                print("   " + str(f))
        if viol:
            rc = 1
    if args.json:
        print(json.dumps(out, indent=2))
    return rc


if __name__ == "__main__":
    import sys
    sys.exit(main())
