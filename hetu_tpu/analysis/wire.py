"""Pass 7a — PS wire-contract checker (HT701/HT702).

The parameter-server plane crosses three unchecked boundaries: the C++
``Op`` enum and length-prefixed framing (``ps/native/ps_common.h``),
the client encoders / server handlers that serialize it
(``ps_client.cc`` / ``ps_server.cc`` / ``ps_cache.cc``), and the ctypes
bridge that Python calls through (``ps/native_lib.py``,
``cstable.py``, call sites in ``ps/client.py``). Nothing ties them
together: add a field to a request writer and the server reader decodes
garbage rows with status 0; drop a ``case`` and the client burns its
whole retry budget against ``-100``; re-order a ctypes prototype and
pointers reinterpret silently. This pass extracts the contract from all
three layers (pattern-level parse of the small, idiomatic native
sources — the same spirit as ``jit_purity.py``'s AST lint, and exactly
as fragile as the idioms it matches, which the round-trip tests in
``tests/test_wire_roundtrip.py`` pin against a live server) and
cross-checks:

=====  =====  ==============================================================
HT701  error  a client-encoded op has no server handler (the client
              would retry forever against status -100)
HT701  warn   dead wire surface: an ``Op`` with a handler but no client
              encoder, or an ``extern "C"`` symbol never ctypes-bound
              (and vice versa)
HT702  error  schema drift: the client's request field sequence differs
              from the server's read sequence, the server's response
              framing differs from what the client decodes, or a ctypes
              prototype disagrees with the C signature (arity or
              pointer/scalar types)
=====  =====  ==============================================================

The extraction also classifies each server handler — mutating?
accumulating (``apply_dense``/``apply_sparse``)? dedup-guarded
(``check_and_record`` on the ``(worker, seq)`` identity)? — which is
the input the consistency model checker (``protocol.py``) uses for its
HT705 retry-idempotence invariant: the model replays the client's
reconnect-and-retry loop against exactly the handlers this parse found.

Suppression: ``// ht-ok: HT701 <reason>`` on the involved line (C++
sources use ``//``; the shared :func:`~.findings.suppressed` helper
accepts both comment leaders).
"""
from __future__ import annotations

import ast
import os
import re

from .findings import Report, suppressed

__all__ = ["WireOp", "WireSpec", "parse_wire", "wire_pass",
           "rpc_contract", "NATIVE_DIR"]

NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "ps", "native")

# field kinds a Writer emits / a Reader consumes, in framing order.
# floats/longs/str are length-prefixed composites; scalars are raw.
_FIELD_RE = re.compile(
    r"\b(?:w|out|rd)\.(u32|i32|i64|u64|f32|f64|floats|longs|str|raw)\s*\(")
_ENUM_RE = re.compile(r"^\s*k(\w+)\s*=\s*(\d+)\s*,")
_CASE_RE = re.compile(r"^\s*case\s+Op::k(\w+)\s*:")
_CALL_RE = re.compile(r"\bcall\s*\(\s*([^,]+),\s*Op::k(\w+)\s*,")
_GUARD_RE = re.compile(r"op\s*==\s*Op::k(\w+)")
# an extern "C" function definition: ret name(args) {   (args may span
# lines; a trailing ';' instead of '{' is a declaration and skipped)
_CFN_RE = re.compile(
    r"^\s*(?:extern\s+\"C\"\s+)?"
    r"(void|int|uint64_t|int64_t)\s+(\w+)\s*\(([^)]*)\)\s*(\{|;)",
    re.M | re.S)

# C parameter type -> canonical ctypes-equivalence token
_CTYPE_OF = {
    "int": "c_int", "int32_t": "c_int", "int64_t": "c_int64",
    "uint64_t": "c_uint64", "double": "c_double", "float": "c_float",
    "const char*": "c_char_p", "char*": "c_char_p",
    "const float*": "ptr:c_float", "float*": "ptr:c_float",
    "const int64_t*": "ptr:c_int64", "int64_t*": "ptr:c_int64",
}

# python RPC kind (telemetry/flight ``ps`` events, ps/client.py) ->
# wire op; blocking=True means the caller synchronously reads the
# response, so a pending entry is a thread stuck in read_full()
RPC_KIND_OPS = {
    "ps_pull": ("DensePull", True),
    "ps_push": ("DensePush", False),
    "ps_dd_pushpull": ("DDPushPull", False),
    "ps_sparse_push": ("SparsePush", False),
    "ps_sparse_pull": ("SparsePull", True),
    "ps_sync_embedding": ("SyncEmbedding", True),
    "ps_push_embedding": ("PushEmbedding", False),
    "ps_push_sync_embedding": ("PushSyncEmbedding", True),
    "ps_barrier": ("Barrier", True),
}

# server-originated sends (the replication forwarder stamps the wrapped
# header itself) — such an op legitimately has no ps_client.cc encoder
_SEND_RE = re.compile(r"h\.op\s*=\s*static_cast<uint32_t>\(Op::k(\w+)\)")


class WireOp:
    """One wire op's contract, merged across the three layers."""

    __slots__ = ("name", "value", "enum_line", "server_cases",
                 "server_reads", "server_writes", "mutating",
                 "accumulating", "dedup_guarded", "client_sites",
                 "server_sends")

    def __init__(self, name, value, enum_line):
        self.name = name
        self.value = value
        self.enum_line = enum_line            # line in ps_common.h
        self.server_cases = []                # [(path, line)]
        self.server_sends = []                # [(path, line)] server-side
        self.server_reads = []                # request field sequence
        self.server_writes = []               # response field sequence
        self.mutating = False
        self.accumulating = False
        self.dedup_guarded = False
        # [{path, line, writes, reads, wants_resp}]
        self.client_sites = []

    def __repr__(self):
        return (f"WireOp(k{self.name}={self.value}, "
                f"req={self.server_reads}, resp={self.server_writes})")


class WireSpec:
    """The parsed contract: ops + the ctypes boundary."""

    def __init__(self, native_dir):
        self.native_dir = native_dir
        self.ops = {}             # name -> WireOp
        self.c_functions = {}     # name -> {path, line, params, ret}
        self.bindings = {}        # name -> {path, line, argtypes, restype}
        self.py_calls = []        # [{path, line, name, nargs}]
        self.sources = {}         # path -> splitlines() (suppression)

    def op(self, name):
        return self.ops.get(name)

    def retry_unsafe_ops(self):
        """Handlers the model checker must double-apply: accumulating
        mutations not guarded by the (worker, seq) dedup."""
        return [op for op in self.ops.values()
                if op.accumulating and not op.dedup_guarded]


def _read(path):
    with open(path, encoding="utf-8") as f:
        return f.read()


def _fields(text):
    """Ordered Writer/Reader field kinds in a code region, with the
    length-prefixed raw-buffer idiom (``out.i64(n)`` + ``out.buf.resize``
    + memcpy/gather into the tail) collapsed to one ``floats`` — the
    server's zero-copy way of writing what ``rd.floats`` decodes."""
    out = []
    for line in text.splitlines():
        if "out.buf.resize(" in line and out and out[-1] == "i64":
            out[-1] = "floats"
            continue
        for m in _FIELD_RE.finditer(line):
            out.append(m.group(1))
    return out


# ---------------------------------------------------------------------------
# layer 1: the Op enum (ps_common.h)
# ---------------------------------------------------------------------------

def _parse_enum(spec, path):
    in_enum = False
    for i, line in enumerate(spec.sources[path], 1):
        if "enum class Op" in line:
            in_enum = True
            continue
        if in_enum:
            if "}" in line:
                break
            m = _ENUM_RE.match(line)
            if m:
                spec.ops[m.group(1)] = WireOp(m.group(1),
                                              int(m.group(2)), i)


# ---------------------------------------------------------------------------
# layer 2a: server handlers (ps_server.cc handle() switch)
# ---------------------------------------------------------------------------

def _parse_server(spec, path):
    lines = spec.sources[path]
    # server-side senders (repl_send's forwarded-header stamp)
    for i, line in enumerate(lines, 1):
        m = _SEND_RE.search(line)
        if m:
            op = spec.ops.get(m.group(1))
            if op is not None:
                op.server_sends.append((path, i))
    # split the switch into case blocks; consecutive labels share one
    cases = [(i, _CASE_RE.match(line).group(1))
             for i, line in enumerate(lines, 1) if _CASE_RE.match(line)]
    # the switch's closing brace bounds the LAST case's body — without
    # it, the final case would absorb the rest of the file (trailing
    # member declarations like `bar_gen_` misclassified a last-case
    # handler as dedup-guarded)
    switch_end = len(lines) + 1
    if cases:
        last_line, _ = cases[-1]
        case_indent = len(lines[last_line - 1]) \
            - len(lines[last_line - 1].lstrip())
        for j in range(last_line, len(lines)):
            line = lines[j]
            if line.strip() == "}" and \
                    len(line) - len(line.lstrip()) < case_indent:
                switch_end = j + 1
                break
    for idx, (lineno, name) in enumerate(cases):
        op = spec.ops.get(name)
        if op is None:
            continue
        op.server_cases.append((path, lineno))
        # the shared block body: from this label to the start of the
        # NEXT group's body (labels with an empty gap fall through)
        end = switch_end
        for j in range(idx + 1, len(cases)):
            between = "".join(lines[lineno:cases[j][0] - 1]).strip()
            if between:                 # real code before that label
                end = cases[j][0]
                break
        body_lines = lines[lineno:end - 1]
        body = "\n".join(body_lines)

        reads, writes = [], []
        prev = ""
        guard_ops = None
        for line in body_lines:
            # a response write under `if (op == Op::kX)` belongs to X
            g = _GUARD_RE.search(line) or _GUARD_RE.search(prev)
            only = g.group(1) if g else None
            if "out.buf.resize(" in line and writes and \
                    writes[-1][0] == "i64":
                writes[-1] = ("floats", writes[-1][1])
            for m in _FIELD_RE.finditer(line):
                recv = m.group(0)
                if recv.startswith("rd."):
                    reads.append(m.group(1))
                elif recv.startswith("out."):
                    writes.append((m.group(1), only))
            prev = line if line.strip() else prev
        op.server_reads = reads
        op.server_writes = [k for k, only in writes
                            if only is None or only == name]
        op.mutating = bool(re.search(
            r"apply_dense|apply_sparse|memcpy\(t->data|std::fill\(t->data"
            r"|blobs_\[|t->ver\[[^\]]+\]\s*\+=|store_\[id\]", body))
        op.accumulating = bool(re.search(
            r"apply_dense|apply_sparse", body))
        op.dedup_guarded = ("check_and_record" in body
                            or "bar_gen" in body)


# ---------------------------------------------------------------------------
# layer 2b: client encoders (ps_client.cc call sites)
# ---------------------------------------------------------------------------

def _parse_client(spec, path):
    lines = spec.sources[path]
    n = len(lines)
    for i, line in enumerate(lines, 1):
        m = _CALL_RE.search(line)
        if not m:
            continue
        name = m.group(2)
        op = spec.ops.get(name)
        if op is None:
            continue
        # full call text (may span lines) to find the resp argument
        call_txt = line
        j = i
        while call_txt.count("(") > call_txt.count(")") and j < n:
            call_txt += lines[j]
            j += 1
        wants_resp = "&resp" in call_txt
        # request: Writer ops since the nearest preceding `Writer w;`
        w0 = None
        for k in range(i - 1, max(0, i - 40), -1):
            if re.search(r"\bWriter\s+w\s*;", lines[k - 1]):
                w0 = k
                break
        writes = _fields("\n".join(lines[w0:i - 1])) if w0 else []
        # response: Reader ops after `Reader rd(resp...)`, up to the
        # next Writer/call (per-part loops re-declare both)
        reads = []
        if wants_resp:
            for k in range(j, min(n, j + 40)):
                ln = lines[k]
                if re.search(r"\bWriter\s+w\s*;", ln) or \
                        _CALL_RE.search(ln):
                    break
                reads.extend(_fields(ln))
        op.client_sites.append({"path": path, "line": i,
                                "writes": writes, "reads": reads,
                                "wants_resp": wants_resp})


# ---------------------------------------------------------------------------
# layer 3: the ctypes boundary
# ---------------------------------------------------------------------------

def _extern_c_regions(src):
    """[(start, end)] char offsets inside ``extern "C" { ... }`` blocks
    (brace-counted), plus single-definition ``extern "C" ret name(...)``
    forms (handled by the caller's regex already matching them)."""
    regions = []
    for m in re.finditer(r'extern\s+"C"\s*\{', src):
        depth = 1
        i = m.end()
        while i < len(src) and depth:
            c = src[i]
            if c == "{":
                depth += 1
            elif c == "}":
                depth -= 1
            i += 1
        regions.append((m.end(), i))
    return regions


def _parse_c_functions(spec, path, src):
    regions = _extern_c_regions(src)
    for m in _CFN_RE.finditer(src):
        ret, name, args, tail = m.groups()
        if tail == ";":                 # declaration, not definition
            continue
        # only the extern "C" ABI: inside an extern block, or a
        # single-definition `extern "C" ret name(...)` form
        in_extern = any(a <= m.start() < b for a, b in regions) or \
            'extern "C"' in m.group(0)
        if not in_extern:
            continue
        params = []
        ok = True
        for raw in args.split(","):
            raw = " ".join(raw.split())
            if not raw:
                continue
            # drop the parameter name (last identifier)
            mm = re.match(r"(.+?)\s*(\w+)$", raw)
            t = (mm.group(1) if mm else raw).replace(" *", "*").strip()
            tok = _CTYPE_OF.get(t)
            if tok is None:
                ok = False
                break
            params.append(tok)
        if not ok:
            continue
        lineno = src.count("\n", 0, m.start()) + 1
        spec.c_functions[name] = {"path": path, "line": lineno,
                                  "params": params, "ret": ret}


class _BindWalk(ast.NodeVisitor):
    """lib.NAME.argtypes/restype assignments + local ctypes aliases."""

    def __init__(self, spec, path):
        self.spec = spec
        self.path = path
        self.aliases = {}       # local name -> ctype token

    def _tok(self, node):
        if isinstance(node, ast.Attribute):        # ctypes.c_int64
            return node.attr
        if isinstance(node, ast.Name):
            return self.aliases.get(node.id, node.id)
        if isinstance(node, ast.Call):             # ctypes.POINTER(X)
            f = node.func
            fname = f.attr if isinstance(f, ast.Attribute) else \
                getattr(f, "id", None)
            if fname == "POINTER" and node.args:
                return "ptr:" + (self._tok(node.args[0]) or "?")
        return None

    def visit_Assign(self, node):
        if len(node.targets) == 1 and \
                isinstance(node.targets[0], ast.Name):
            tok = self._tok(node.value)
            if tok:
                self.aliases[node.targets[0].id] = tok
        t = node.targets[0]
        if isinstance(t, ast.Attribute) and \
                isinstance(t.value, ast.Attribute) and \
                isinstance(t.value.value, ast.Name) and \
                t.value.value.id == "lib":
            fn = t.value.attr
            b = self.spec.bindings.setdefault(
                fn, {"path": self.path, "line": node.lineno,
                     "argtypes": None, "restype": None})
            if t.attr == "argtypes" and \
                    isinstance(node.value, ast.List):
                b["argtypes"] = [self._tok(e) or "?"
                                 for e in node.value.elts]
                b["line"] = node.lineno
            elif t.attr == "restype":
                b["restype"] = self._tok(node.value)
        self.generic_visit(node)


class _LibCallWalk(ast.NodeVisitor):
    """self.lib.NAME(...) call sites (ps/client.py, cstable.py)."""

    def __init__(self, spec, path):
        self.spec = spec
        self.path = path

    def visit_Call(self, node):
        f = node.func
        if isinstance(f, ast.Attribute) and \
                isinstance(f.value, ast.Attribute) and \
                f.value.attr == "lib":
            self.spec.py_calls.append(
                {"path": self.path, "line": node.lineno,
                 "name": f.attr, "nargs": len(node.args)})
        self.generic_visit(node)


# ---------------------------------------------------------------------------
# driver
# ---------------------------------------------------------------------------

_cache = {}


def parse_wire(native_dir=None, py_dir=None, use_cache=True):
    """Parse the full wire contract; cached per directory pair (the
    sources only change when a developer edits them mid-session)."""
    native_dir = native_dir or NATIVE_DIR
    py_dir = py_dir or os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))
    key = (native_dir, py_dir)
    if use_cache and key in _cache:
        return _cache[key]
    spec = WireSpec(native_dir)

    common = os.path.join(native_dir, "ps_common.h")
    server = os.path.join(native_dir, "ps_server.cc")
    client = os.path.join(native_dir, "ps_client.cc")
    cache = os.path.join(native_dir, "ps_cache.cc")
    texts = {p: _read(p) for p in (common, server, client, cache)}
    for p, src in texts.items():
        spec.sources[p] = src.splitlines()

    _parse_enum(spec, common)
    _parse_server(spec, server)
    _parse_client(spec, client)
    for p in (client, cache, server):
        _parse_c_functions(spec, p, texts[p])

    native_lib = os.path.join(py_dir, "ps", "native_lib.py")
    cstable = os.path.join(py_dir, "cstable.py")
    ps_client_py = os.path.join(py_dir, "ps", "client.py")
    trees = {}
    for p in (native_lib, cstable, ps_client_py):
        if os.path.exists(p):
            src = _read(p)
            spec.sources[p] = src.splitlines()
            trees[p] = ast.parse(src, filename=p)
    for p in (native_lib, cstable):
        if p in trees:
            _BindWalk(spec, p).visit(trees[p])
    for p in (ps_client_py, cstable):
        if p in trees:
            _LibCallWalk(spec, p).visit(trees[p])
    if use_cache:
        _cache[key] = spec
    return spec


def _add(spec, report, code, sev, msg, sites, **data):
    """Emit unless any involved (path, line) carries an ht-ok waiver."""
    for path, line in sites:
        lines = spec.sources.get(path)
        if lines and suppressed(lines, line, code, markers=("ht-ok",)):
            return None
    path, line = sites[0]
    return report.add(code, sev, msg,
                      where=f"{os.path.relpath(path)}:{line}", **data)


def wire_pass(report, native_dir=None, py_dir=None, spec=None):
    """HT701/HT702 over the parsed contract; returns the spec (the
    model checker's input)."""
    spec = spec or parse_wire(native_dir, py_dir)
    common = os.path.join(spec.native_dir, "ps_common.h")

    for op in spec.ops.values():
        enum_site = (common, op.enum_line)
        if op.client_sites and not op.server_cases:
            _add(spec, report, "HT701", "error",
                 f"client encodes Op::k{op.name} "
                 f"(ps_client.cc:{op.client_sites[0]['line']}) but the "
                 f"server switch has no case for it — every send burns "
                 f"the full retry budget against status -100",
                 [enum_site] + [(s["path"], s["line"])
                                for s in op.client_sites], op=op.name)
        elif not op.client_sites and op.server_cases \
                and not op.server_sends:
            # ops the SERVER originates (kReplForward: a primary stamps
            # the wrapped header in repl_send) have their encoder in
            # ps_server.cc by design — not a dead handler
            _add(spec, report, "HT701", "warn",
                 f"Op::k{op.name} has a server handler "
                 f"(ps_server.cc:{op.server_cases[0][1]}) but no client "
                 f"encoder — dead handler, or the encoder moved without "
                 f"its enum entry",
                 [enum_site, op.server_cases[0]], op=op.name)
        elif not op.client_sites and not op.server_cases:
            _add(spec, report, "HT701", "warn",
                 f"Op::k{op.name} is declared but neither encoded nor "
                 f"handled — dead wire surface", [enum_site], op=op.name)

        for site in op.client_sites:
            if not op.server_cases:
                continue
            if site["writes"] != op.server_reads:
                _add(spec, report, "HT702", "error",
                     f"Op::k{op.name} request schema drift: client "
                     f"writes [{', '.join(site['writes'])}] "
                     f"(ps_client.cc:{site['line']}) but the server "
                     f"reads [{', '.join(op.server_reads)}] "
                     f"(ps_server.cc:{op.server_cases[0][1]}) — the "
                     f"handler decodes garbage with status 0",
                     [(site["path"], site["line"]), op.server_cases[0]],
                     op=op.name, client=site["writes"],
                     server=op.server_reads)
            if site["wants_resp"] and \
                    site["reads"] != op.server_writes:
                _add(spec, report, "HT702", "error",
                     f"Op::k{op.name} response schema drift: server "
                     f"writes [{', '.join(op.server_writes)}] "
                     f"(ps_server.cc:{op.server_cases[0][1]}) but the "
                     f"client decodes [{', '.join(site['reads'])}] "
                     f"(ps_client.cc:{site['line']})",
                     [(site["path"], site["line"]), op.server_cases[0]],
                     op=op.name, client=site["reads"],
                     server=op.server_writes)
            if not site["wants_resp"] and op.server_writes and \
                    not op.accumulating:
                # async fire-and-forget pushes legitimately drop their
                # (empty) ack; a non-push op ignoring a real payload is
                # drift on the client side
                _add(spec, report, "HT702", "error",
                     f"Op::k{op.name}: server answers "
                     f"[{', '.join(op.server_writes)}] but the client "
                     f"never reads the response",
                     [(site["path"], site["line"]), op.server_cases[0]],
                     op=op.name)

    # -- ctypes boundary -------------------------------------------------
    for name, b in sorted(spec.bindings.items()):
        if b["argtypes"] is None:
            continue
        c = spec.c_functions.get(name)
        if c is None:
            _add(spec, report, "HT701", "error",
                 f"ctypes binds {name} but no extern \"C\" definition "
                 f"exists in the native sources — CDLL lookup raises at "
                 f"first use", [(b["path"], b["line"])], symbol=name)
            continue
        if b["argtypes"] != c["params"]:
            _add(spec, report, "HT702", "error",
                 f"ctypes prototype drift for {name}: python declares "
                 f"({', '.join(b['argtypes'])}) but C defines "
                 f"({', '.join(c['params'])}) at "
                 f"{os.path.basename(c['path'])}:{c['line']} — pointers "
                 f"reinterpret silently",
                 [(b["path"], b["line"]), (c["path"], c["line"])],
                 symbol=name)
        want_ret = {"void": None, "int": "c_int", "int64_t": "c_int64",
                    "uint64_t": "c_uint64"}.get(c["ret"], None)
        if b["restype"] is not None and want_ret is not None and \
                b["restype"] != want_ret:
            _add(spec, report, "HT702", "error",
                 f"ctypes restype drift for {name}: python declares "
                 f"{b['restype']} but C returns {c['ret']}",
                 [(b["path"], b["line"]), (c["path"], c["line"])],
                 symbol=name)
    for name, c in sorted(spec.c_functions.items()):
        if name not in spec.bindings:
            _add(spec, report, "HT701", "warn",
                 f"extern \"C\" {name} is exported by the native "
                 f"library but never ctypes-bound — dead ABI surface "
                 f"(or a binding the bridge forgot)",
                 [(c["path"], c["line"])], symbol=name)

    # -- python call sites vs prototypes ---------------------------------
    for call in spec.py_calls:
        b = spec.bindings.get(call["name"])
        if b is None or b["argtypes"] is None:
            continue
        if call["nargs"] != len(b["argtypes"]):
            _add(spec, report, "HT702", "error",
                 f"{call['name']} called with {call['nargs']} args at "
                 f"{os.path.basename(call['path'])}:{call['line']} but "
                 f"the prototype declares {len(b['argtypes'])}",
                 [(call["path"], call["line"]),
                  (b["path"], b["line"])], symbol=call["name"])
    return spec


def rpc_contract(spec=None):
    """{python RPC kind: {op, response, blocking}} — the black-box
    analyzer's lookup for pending flight-ring PS events (what was that
    RPC on the wire, and what response was the thread waiting for?)."""
    try:
        spec = spec or parse_wire()
    except OSError:
        return {}
    out = {}
    for kind, (opname, blocking) in RPC_KIND_OPS.items():
        op = spec.op(opname)
        if op is None:
            continue
        resp = ", ".join(op.server_writes) if op.server_writes \
            else "empty ack"
        out[kind] = {"op": f"k{opname}", "response": resp,
                     "blocking": blocking}
    return out
