"""Pass 2 — sharding consistency (HT2xx).

Re-runs the planner's ``deduce_states`` fixpoint (the exact propagation
``parallel/planner.py`` uses to lower DispatchOp markers to
PartitionSpecs) under the findings collector, so the failure modes that
today degrade to ``logger.warning`` at trace time become preflight
findings with node provenance:

HT201  distributed status has no mappable mesh axes (constraint
       silently dropped at run time — no memory/compute split)   error
HT202  an op's ``deduce_states`` rule raised (conflicting or
       malformed input statuses)                                 error
HT203  implicit reshard: producer and consumer disagree on
       partition state — XLA inserts collectives here            info
HT204  plan wants more devices than are attached                 error
"""
from __future__ import annotations

import os

import numpy as np

__all__ = ["sharding_pass"]


def _bytes_of(shape, itemsize=4):
    if not shape:
        return None
    try:
        return int(np.prod([int(s) for s in shape])) * itemsize
    except (TypeError, ValueError):
        return None


def sharding_pass(topo, report, shapes=None, ndevices=None):
    """Validate the TP plan; returns the node -> NodeStatus dict."""
    from .findings import collecting
    from ..ops.comm import DispatchOp, DispatchGradientOp
    from ..parallel.planner import propagate_statuses, spec_for_status
    from ..parallel.mesh import factorized_axes

    with collecting(report):
        status = propagate_statuses(topo)
    dist = {n: st for n, st in status.items()
            if st is not None and st.is_dist()}
    if not dist:
        return status

    # HT204: the plan must fit the attached device set. Under the
    # launcher's --preflight subprocess (HETU_PREFLIGHT) the script runs
    # on the launcher machine whose local devices say nothing about the
    # fleet's — skip the check rather than falsely reject a valid plan.
    if ndevices is None and "HETU_PREFLIGHT" not in os.environ:
        import jax
        try:
            ndevices = len(jax.devices())
        except RuntimeError:
            ndevices = None
    if ndevices is not None:
        for node, st in dist.items():
            need = st.device_num
            if need is not None and need > ndevices:
                report.add(
                    "HT204", "error",
                    f"{node.name} wants a {need}-device layout "
                    f"({st}) but only {ndevices} device(s) are "
                    f"attached", node=node)

    # HT201: every distributed status must lower to a PartitionSpec over
    # the mesh the planner would build (spec_for_status emits through
    # the active collector; outside analysis it keeps its warning)
    tp_degree = 1
    for st in dist.values():
        tp_degree = max(tp_degree,
                        int(np.prod([s for s in st.state])))
    model_axes = factorized_axes(tp_degree)
    with collecting(report):
        for node, st in dist.items():
            spec_for_status(st, model_axes, node=node)

    # HT203: edges where the producer's state differs from the
    # consumer's — an implicit reshard XLA materializes as collectives
    shapes = shapes or {}
    for node, st in status.items():
        if isinstance(node, (DispatchOp, DispatchGradientOp)):
            continue  # explicit repartition markers: resharding is the point
        if st is None or st.state is None:
            continue
        for inp in node.inputs:
            sti = status.get(inp)
            if sti is None or sti.state is None:
                continue
            if not (st.is_dist() or sti.is_dist()):
                continue
            if sti.state != st.state:
                nbytes = _bytes_of(shapes.get(inp))
                est = (f", ~{nbytes / 2 ** 20:.1f} MiB moved per step"
                       if nbytes else "")
                report.add(
                    "HT203", "info",
                    f"implicit reshard on edge {inp.name} -> "
                    f"{node.name}: producer state {sti.state} vs "
                    f"consumer state {st.state}{est} — insert an "
                    f"explicit dispatch if unintended", node=node)
    return status
