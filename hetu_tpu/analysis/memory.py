"""Pass 4 — memory preflight against an HBM budget (HT4xx).

Two tiers, sharing `telemetry/memory.py`'s accounting vocabulary:

* **Static estimate** (:func:`memory_pass`): from the shape pass's
  results alone — parameter bytes, gradient mirror, optimizer slots
  (per-optimizer-class multiplier), and a conservative forward
  activation sum — checked against the budget *before anything
  compiles*. Deliberately pessimistic about activations (no XLA fusion
  or rematerialization credit): a plan that fails HT401 statically is
  certain to OOM; one that passes may still need the compiled check.
* **Compiled check** (:func:`check_compiled`): when the executor's AOT
  path has real ``compiled.memory_analysis()`` numbers (the dict
  ``telemetry/memory.capture_compile`` builds), compare
  arg+out+temp bytes against the budget — exact, but only available
  once a step traced.

Budget resolution order: explicit argument > ``HETU_HBM_BUDGET`` env
(accepts ``8G`` / ``512MiB`` / plain bytes) > the device's advertised
``bytes_limit`` (TPU backends report it; CPU doesn't).

HT401  estimated footprint exceeds the HBM budget            error
HT402  footprint breakdown (always, when shapes are known)   info
HT403  estimate within 10% of the budget                     warn
HT404  compiled memory_analysis exceeds the budget           warn
"""
from __future__ import annotations

import os
import re

from ..telemetry.memory import fmt_bytes

__all__ = ["memory_pass", "check_compiled", "parse_bytes",
           "resolve_budget"]

_SLOTS_PER_PARAM = {
    "SGDOptimizer": 0,
    "MomentumOptimizer": 1,
    "NesterovOptimizer": 1,
    "AdaGradOptimizer": 1,
    "AdamOptimizer": 2,
    "AdamWOptimizer": 2,
}

_UNITS = {"": 1, "k": 2 ** 10, "m": 2 ** 20, "g": 2 ** 30, "t": 2 ** 40}


def parse_bytes(value):
    """'8G' / '512MiB' / '1073741824' -> bytes (int)."""
    if value is None:
        return None
    if isinstance(value, (int, float)):
        return int(value)
    m = re.fullmatch(r"\s*([0-9]*\.?[0-9]+)\s*([kKmMgGtT]?)i?[bB]?\s*",
                     str(value))
    if not m:
        raise ValueError(f"unparseable byte size {value!r}")
    return int(float(m.group(1)) * _UNITS[m.group(2).lower()])


def resolve_budget(budget=None):
    """Explicit budget > HETU_HBM_BUDGET > device bytes_limit > None."""
    if budget is not None:
        return parse_bytes(budget)
    env = os.environ.get("HETU_HBM_BUDGET")
    if env:
        return parse_bytes(env)
    try:
        import jax
        limits = [int(d.memory_stats().get("bytes_limit", 0))
                  for d in jax.local_devices() if d.memory_stats()]
        if limits and min(limits) > 0:
            return min(limits)
    except Exception:       # noqa: BLE001 — backend-optional API
        pass
    return None


def _nbytes(shape, itemsize=4):
    if shape is None:
        return None
    n = itemsize
    for s in shape:
        n *= int(s)
    return n


def memory_pass(topo, shapes, report, budget=None):
    """Static footprint estimate vs budget; returns the breakdown dict."""
    from ..optimizer import OptimizerOp
    from ..ops.variable import PlaceholderOp

    param_bytes = 0
    for n in topo:
        if isinstance(n, PlaceholderOp) and n.trainable:
            b = _nbytes(shapes.get(n))
            if b:
                param_bytes += b

    opt_ops = [n for n in topo if isinstance(n, OptimizerOp)]
    slot_mult = 0
    for op in opt_ops:
        cls = type(op.optimizer).__name__
        slot_mult = max(slot_mult, _SLOTS_PER_PARAM.get(cls, 1))
    training = bool(opt_ops)
    grad_bytes = param_bytes if training else 0
    slot_bytes = param_bytes * slot_mult

    act_bytes = 0
    unknown_acts = 0
    for n in topo:
        if isinstance(n, (PlaceholderOp, OptimizerOp)):
            continue
        b = _nbytes(shapes.get(n))
        if b is None:
            unknown_acts += 1
        else:
            act_bytes += b

    total = param_bytes + grad_bytes + slot_bytes + act_bytes
    breakdown = {"param_bytes": param_bytes, "grad_bytes": grad_bytes,
                 "opt_slot_bytes": slot_bytes,
                 "activation_bytes": act_bytes, "total_bytes": total}
    if total:
        caveat = (f" ({unknown_acts} node(s) unshaped and uncounted)"
                  if unknown_acts else "")
        report.add(
            "HT402", "info",
            f"static footprint estimate: params {fmt_bytes(param_bytes)}"
            f" + grads {fmt_bytes(grad_bytes)} + optimizer slots "
            f"{fmt_bytes(slot_bytes)} + activations "
            f"{fmt_bytes(act_bytes)} = {fmt_bytes(total)}{caveat}",
            **breakdown)

    budget = resolve_budget(budget)
    if budget is None or not total:
        return breakdown
    if total > budget:
        report.add(
            "HT401", "error",
            f"estimated device footprint {fmt_bytes(total)} exceeds "
            f"the HBM budget {fmt_bytes(budget)} — the plan OOMs "
            f"before the first step completes; shard parameters "
            f"(dispatch/PS), shrink the batch, or raise the budget",
            budget_bytes=budget, **breakdown)
    elif total > 0.9 * budget:
        report.add(
            "HT403", "warn",
            f"estimated footprint {fmt_bytes(total)} is within 10% of "
            f"the HBM budget {fmt_bytes(budget)} — fragmentation or "
            f"temp buffers can tip this over",
            budget_bytes=budget, **breakdown)
    return breakdown


def check_compiled(mem, budget=None):
    """Compare a ``capture_compile`` dict (arg/out/temp bytes from
    ``compiled.memory_analysis()``) against the budget. Returns a list
    of :class:`~.findings.Finding` (empty when within budget or no
    budget resolves)."""
    from .findings import Finding
    budget = resolve_budget(budget)
    if not mem or budget is None:
        return []
    used = (mem.get("arg_bytes", 0) + mem.get("out_bytes", 0)
            + mem.get("temp_bytes", 0) - mem.get("alias_bytes", 0))
    if used <= budget:
        return []
    return [Finding(
        "HT404", "warn",
        f"compiled program needs {fmt_bytes(used)} "
        f"(args {fmt_bytes(mem.get('arg_bytes', 0))} + outputs "
        f"{fmt_bytes(mem.get('out_bytes', 0))} + temps "
        f"{fmt_bytes(mem.get('temp_bytes', 0))}, aliasing credited) "
        f"but the HBM budget is {fmt_bytes(budget)}",
        budget_bytes=budget, **mem)]
