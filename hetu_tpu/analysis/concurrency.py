"""Static concurrency verifier for the threaded host runtime (HT6xx).

PRs 2-10 made the host side a genuinely concurrent program: the ingest
engine's worker, the micro-batcher's condition loop, p2p accept/
connection readers, the autotune sweep worker, three HTTP servers, the
PS push pool, signal/atexit crash handlers. The preflight stack
(HT1xx-HT5xx) statically refuses to launch broken *fleets*; this pass
extends the same philosophy to broken *threads* — the classic lockset
(Eraser, Savage et al. 1997) and lock-order-graph (GoodLock)
algorithms, implemented over our small, idiomatic threading surface.

Per module, the pass models:

* **thread entry points** — ``threading.Thread(target=f)`` /
  ``Timer(..., f)`` targets, ``pool.submit(f, ...)`` callees,
  ``signal.signal(sig, f)`` handlers, and ``do_*``/``handle`` methods
  of ``BaseHTTPRequestHandler`` subclasses (each HTTP request runs on
  its own server thread). Everything reachable from an entry through
  the intra-module call graph runs in that entry's context; a function
  with no in-module callers is assumed main/API context.
* **shared mutable state** — ``self.attr`` and module-global writes
  (assignments, augmented assigns, subscript stores, and mutating
  method calls like ``.append``/``.update``), excluding ``__init__``
  (pre-thread-start construction).
* **locks** — attributes/globals assigned ``threading.Lock`` /
  ``RLock`` / ``Condition`` / ``Semaphore``, with ``Condition(lock)``
  aliased to the lock it wraps; per-statement locksets from ``with``
  regions, plus locks a helper's *every* in-module call site holds
  (so a helper that is only ever called under the lock counts as
  guarded).

and emits:

=====  =====  ==============================================================
HT601  error  shared-state write from >=2 thread contexts with an empty
              common lockset (the Eraser condition)
HT602  error  lock-order inversion: opposite acquisition orders of a lock
              pair; names both locks and their ``defined_at`` lines
HT603  warn   blocking call while holding a lock: ``Condition.wait`` with
              no timeout (while other locks are held), ``queue.get``,
              ``join``, ``Future.result``, socket ops, ``time.sleep``
HT604  warn   thread/pool lifecycle leak: non-daemon thread that is never
              joined, executor pool with no ``shutdown``/``with`` path
HT605  warn   unguarded lazy-init check-then-create (``if x is None: x =
              ...``) on shared state in a threaded module
HT606  warn   async-signal-unsafe work — lock acquisition or file IO —
              inside an installed signal handler
=====  =====  ==============================================================

A line containing ``# ht-ok`` (or the historical ``# lock-ok`` alias)
suppresses its findings; the annotated form ``# ht-ok: HT603 <reason>``
suppresses only that code and is the house style (the reason is the
review artifact — the shared :func:`~.findings.suppressed` helper makes
every pass's waivers one grep surface). For multi-site findings
(HT601/HT602) the annotation may sit on any involved line.

CLI: ``python -m hetu_tpu.analysis.concurrency [paths...] [--json]``
(default: the ``hetu_tpu`` package) — exit 1 when any unsuppressed
finding exists; wired into CI as the ``concurrency-lint`` job. The
dynamic twin — instrumented locks measuring the *observed* acquisition
graph under real load — is ``hetu_tpu/analysis/racecheck.py``.

Scope limitation, by design: the pass is per-module and name-based.
A lock passed across modules, attribute aliasing, and data handed
between threads through containers are invisible; cycles longer than
two locks are not searched. The racecheck harness is the net under
those — and, like jit_purity, the direct layer is where our bugs have
actually lived.
"""
from __future__ import annotations

import ast
import os
import re
import sys

from .findings import Finding, Report, suppressed

__all__ = ["check_source", "check_paths", "main"]

_LOCK_FACTORIES = {"Lock", "RLock", "Condition", "Semaphore",
                   "BoundedSemaphore"}
_POOL_FACTORIES = {"ThreadPoolExecutor", "ProcessPoolExecutor"}
_MUTATORS = {"append", "appendleft", "extend", "extendleft", "insert",
             "add", "discard", "remove", "update", "clear", "pop",
             "popleft", "setdefault", "put", "put_nowait"}
_SOCKET_BLOCKING = {"accept", "recv", "recv_into", "recvfrom", "sendall",
                    "create_connection"}
_QUEUE_HINTS = re.compile(r"(queue|inbox|jobs|mailbox|^_?q$)", re.I)
_JOIN_EXEMPT_ROOTS = {"os", "posixpath", "ntpath", "str", "shutil"}
_INIT_METHODS = {"__init__", "__new__", "__post_init__", "__set_name__"}
_HTTP_HANDLER_BASES = {"BaseHTTPRequestHandler",
                       "SimpleHTTPRequestHandler", "BaseRequestHandler",
                       "StreamRequestHandler"}
_EVENT_HINTS = {"event", "ev", "done", "stop", "ready"}
_MAIN = "main"


def _dotted(node):
    """Attribute/Name chain -> tuple of names, ('self','_cond') etc."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return tuple(reversed(parts))
    return None


class _Fn:
    """Everything the fixpoints need about one function body."""

    __slots__ = ("qual", "node", "cls", "calls", "acquires", "writes",
                 "blocking", "lazy", "sigwork", "contexts",
                 "callee_held", "is_entry", "globals_decl")

    def __init__(self, qual, node, cls):
        self.qual = qual
        self.node = node
        self.cls = cls                  # enclosing class qualname or None
        self.calls = []                 # (callee_qual, locks, lineno)
        self.acquires = []              # (lock_key, lineno, held_before)
        self.writes = []                # (state_key, lineno, locks)
        self.blocking = []              # (desc, lineno, locks, waited)
        self.lazy = []                  # (state_key, lineno, locks)
        self.sigwork = []               # (desc, lineno) for HT606
        self.contexts = set()
        self.callee_held = None         # fixpoint: locks held at entry
        self.is_entry = False
        self.globals_decl = set()


class _Module:
    """One module's collected model (built by two AST passes)."""

    def __init__(self, path):
        self.path = path
        self.fns = {}                   # qual -> _Fn
        self.methods = {}               # class qual -> {name: fn qual}
        self.scope_defs = {}            # scope qual ('' = module) -> {name: qual}
        self.locks = {}                 # lock_key -> defined lineno
        self.lock_alias = {}            # lock_key -> canonical lock_key
        self.entries = {}               # fn qual -> set of context labels
        self.signal_handlers = set()    # quals registered via signal.signal
        self.threads = []               # thread/pool creations (HT604)
        self.joins = set()              # receiver chains .join()ed
        self.shutdowns = set()          # receiver chains .shutdown()ed
        self.has_threading = False

    def canon(self, key):
        seen = set()
        while key in self.lock_alias and key not in seen:
            seen.add(key)
            key = self.lock_alias[key]
        return key

    def lock_line(self, key):
        return self.locks.get(key) or self.locks.get(self.canon(key))


def _lock_name(key):
    if key[0] == "attr":
        return f"{key[1].rsplit('.', 1)[-1]}.{key[2]}"
    return key[-1]


def _state_name(key):
    if key[0] == "attr":
        return f"{key[1].rsplit('.', 1)[-1]}.{key[2]}"
    return f"global {key[1]}"


# ---------------------------------------------------------------------------
# pass 1: scopes, locks, thread/pool creations
# ---------------------------------------------------------------------------

class _Collector(ast.NodeVisitor):
    def __init__(self, mod):
        self.mod = mod
        self.cls_stack = []             # fully qualified class names
        self.fn_stack = []              # _Fn objects
        self.http_classes = set()

    def _scope(self):
        if self.fn_stack:
            return self.fn_stack[-1].qual
        if self.cls_stack:
            return self.cls_stack[-1]
        return ""

    def _qual(self, name):
        prefix = self._scope()
        return f"{prefix}.{name}" if prefix else name

    def visit_ClassDef(self, node):
        qual = self._qual(node.name)
        bases = {b[-1] for b in map(_dotted, node.bases) if b}
        if bases & _HTTP_HANDLER_BASES:
            self.http_classes.add(qual)
            self.mod.has_threading = True
        self.cls_stack.append(qual)
        self.mod.methods.setdefault(qual, {})
        self.generic_visit(node)
        self.cls_stack.pop()

    def _visit_fn(self, node):
        qual = self._qual(node.name)
        cls = self.cls_stack[-1] if self.cls_stack else None
        fn = _Fn(qual, node, cls)
        self.mod.fns[qual] = fn
        self.mod.scope_defs.setdefault(self._scope(), {})[node.name] = qual
        if cls is not None:
            self.mod.methods.setdefault(cls, {})[node.name] = qual
            if cls in self.http_classes and (
                    node.name.startswith("do_") or node.name == "handle"):
                # each HTTP request runs this on its own server thread
                self.mod.entries.setdefault(qual, set()).add(f"http:{qual}")
        for stmt in ast.walk(node):
            if isinstance(stmt, ast.Global):
                fn.globals_decl.update(stmt.names)
        self.fn_stack.append(fn)
        self.generic_visit(node)
        self.fn_stack.pop()

    visit_FunctionDef = _visit_fn
    visit_AsyncFunctionDef = _visit_fn

    # -- lock / thread / pool creation sites -----------------------------
    def _state_key_of_target(self, tgt):
        if isinstance(tgt, ast.Attribute) and \
                isinstance(tgt.value, ast.Name) and tgt.value.id == "self":
            cls = self.cls_stack[-1] if self.cls_stack else "?"
            return ("attr", cls, tgt.attr)
        if isinstance(tgt, ast.Name):
            if not self.fn_stack:
                return ("global", tgt.id)
            if tgt.id in self.fn_stack[-1].globals_decl:
                return ("global", tgt.id)
            return ("local", self.fn_stack[-1].qual, tgt.id)
        return None

    def visit_Assign(self, node):
        value = node.value
        chain = _dotted(value.func) if isinstance(value, ast.Call) else None
        if chain and chain[-1] in _LOCK_FACTORIES:
            for tgt in node.targets:
                key = self._state_key_of_target(tgt)
                if key is None:
                    continue
                self.mod.locks[key] = node.lineno
                self.mod.has_threading = True
                if chain[-1] == "Condition" and value.args:
                    wrapped = self._state_key_of_target(value.args[0]) \
                        if isinstance(value.args[0],
                                      (ast.Name, ast.Attribute)) else None
                    if wrapped is not None:
                        self.mod.lock_alias[key] = wrapped
        if chain and chain[-1] in _POOL_FACTORIES | {"Thread", "Timer"}:
            self._note_spawn(node.lineno, value, chain,
                             [k for k in (self._state_key_of_target(t)
                                          for t in node.targets) if k])
        self.generic_visit(node)

    def _note_spawn(self, lineno, call, chain, targets, in_with=False):
        self.mod.has_threading = True
        kind = "pool" if chain[-1] in _POOL_FACTORIES else "thread"
        daemon = None
        for kw in call.keywords:
            if kw.arg == "daemon":
                daemon = getattr(kw.value, "value", None)
        self.mod.threads.append({"kind": kind, "lineno": lineno,
                                 "daemon": daemon, "targets": targets,
                                 "in_with": in_with, "node": call})

    def visit_With(self, node):
        for item in node.items:
            expr = item.context_expr
            chain = _dotted(expr.func) if isinstance(expr, ast.Call) \
                else None
            if chain and chain[-1] in _POOL_FACTORIES:
                self._note_spawn(node.lineno, expr, chain, [],
                                 in_with=True)
        self.generic_visit(node)

    def visit_Call(self, node):
        chain = _dotted(node.func)
        if chain:
            if chain[-1] == "join" and chain[0] not in _JOIN_EXEMPT_ROOTS:
                self.mod.joins.add(chain[:-1])
            if chain[-1] in ("shutdown", "close", "cancel"):
                self.mod.shutdowns.add(chain[:-1])
            if chain[0] in ("threading", "concurrent") or \
                    chain[-1] in ("Thread", "Timer", "submit",
                                  "serve_forever"):
                self.mod.has_threading = True
            # bare threading.Thread(...).start() never passes an Assign
            if chain[-1] in ("Thread", "Timer") and not any(
                    t["node"] is node for t in self.mod.threads):
                self._note_spawn(node.lineno, node, chain, [])
        self.generic_visit(node)


# ---------------------------------------------------------------------------
# pass 2: per-function body analysis with lockset tracking
# ---------------------------------------------------------------------------

class _BodyWalker:
    """Walks one function body carrying the set of held locks; records
    writes, acquisitions, calls, blocking ops, and lazy-init shapes."""

    def __init__(self, mod, fn):
        self.mod = mod
        self.fn = fn

    # -- name resolution -------------------------------------------------
    def _resolve_callable(self, expr):
        """fn qualname for a Name / self.attr reference, or None."""
        chain = _dotted(expr)
        if chain is None:
            return None
        if chain[0] == "self" and len(chain) == 2 and self.fn.cls:
            return self.mod.methods.get(self.fn.cls, {}).get(chain[1])
        if len(chain) == 1:
            scope = self.fn.qual
            while True:
                # class scopes are not on the name-resolution path of
                # function bodies (Python scoping) — skip them
                if scope not in self.mod.methods:
                    hit = self.mod.scope_defs.get(scope, {}).get(chain[0])
                    if hit:
                        return hit
                if "." not in scope:
                    break
                scope = scope.rsplit(".", 1)[0]
            return self.mod.scope_defs.get("", {}).get(chain[0])
        return None

    def _lock_key(self, expr):
        chain = _dotted(expr)
        if chain is None:
            return None
        if chain[0] == "self" and len(chain) == 2 and self.fn.cls:
            key = ("attr", self.fn.cls, chain[1])
        elif len(chain) == 1:
            key = ("local", self.fn.qual, chain[0])
            if key not in self.mod.locks:
                key = ("global", chain[0])
        else:
            return None
        if key not in self.mod.locks and key not in self.mod.lock_alias:
            return None
        return self.mod.canon(key)

    def _state_key(self, tgt):
        if isinstance(tgt, ast.Attribute) and \
                isinstance(tgt.value, ast.Name) and tgt.value.id == "self":
            return ("attr", self.fn.cls or "?", tgt.attr)
        if isinstance(tgt, ast.Name) and tgt.id in self.fn.globals_decl:
            return ("global", tgt.id)
        return None

    # -- traversal -------------------------------------------------------
    def walk(self):
        for stmt in self.fn.node.body:
            self._stmt(stmt, frozenset())

    def _stmt(self, node, held):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            return                      # nested defs analyzed on their own
        if isinstance(node, ast.With):
            inner = set(held)
            for item in node.items:
                self._exprs(item.context_expr, frozenset(inner))
                lk = self._lock_key(item.context_expr)
                if lk is not None:
                    self.fn.acquires.append((lk, node.lineno,
                                             frozenset(inner)))
                    inner.add(lk)
            for child in node.body:
                self._stmt(child, frozenset(inner))
            return
        if isinstance(node, ast.If):
            self._maybe_lazy_init(node, held)
        # expressions attached directly to THIS statement
        if isinstance(node, ast.Assign):
            for tgt in node.targets:
                self._note_write_target(tgt, node.lineno, held)
        elif isinstance(node, ast.AugAssign):
            self._note_write_target(node.target, node.lineno, held)
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.expr):
                self._exprs(child, held)
        # nested statements (If/For/While/Try bodies)
        for field in ("body", "orelse", "finalbody"):
            for child in getattr(node, field, []) or []:
                if isinstance(child, ast.stmt):
                    self._stmt(child, held)
        for handler in getattr(node, "handlers", []) or []:
            for child in handler.body:
                self._stmt(child, held)

    def _exprs(self, expr, held):
        for node in ast.walk(expr):
            if isinstance(node, ast.Call):
                self._note_call(node, held)

    # -- lazy init (HT605) -----------------------------------------------
    def _maybe_lazy_init(self, node, held):
        """``if X is None: X = Call(...)`` / ``if not X: X = ...`` with
        no lock held — the check-then-create race. The double-checked
        form records nothing: the assignment's lockset is non-empty."""
        test = node.test
        name = None
        if isinstance(test, ast.Compare) and len(test.ops) == 1 and \
                isinstance(test.ops[0], ast.Is) and \
                isinstance(test.comparators[0], ast.Constant) and \
                test.comparators[0].value is None:
            name = test.left
        elif isinstance(test, ast.UnaryOp) and \
                isinstance(test.op, ast.Not):
            name = test.operand
        if name is None:
            return
        key = self._state_key(name)
        if key is None:
            return
        if self.fn.node.name in _INIT_METHODS:
            return                      # construction precedes threads
        for stmt in ast.walk(node):
            if isinstance(stmt, ast.Assign):
                for tgt in stmt.targets:
                    if self._state_key(tgt) == key:
                        locks = set(held) | self._locks_between(node, stmt)
                        self.fn.lazy.append((key, stmt.lineno,
                                             frozenset(locks)))

    def _locks_between(self, root, assign):
        """Locks acquired by With statements between root and assign
        (the inner ``with`` of double-checked locking)."""
        out = set()

        def scan(node, held):
            if node is assign:
                out.update(held)
                return True
            if isinstance(node, ast.With):
                inner = set(held)
                for item in node.items:
                    lk = self._lock_key(item.context_expr)
                    if lk is not None:
                        inner.add(lk)
                return any(scan(c, inner) for c in node.body)
            return any(scan(c, held) for c in ast.iter_child_nodes(node)
                       if isinstance(c, ast.stmt))

        scan(root, set())
        return out

    # -- writes / calls / blocking ----------------------------------------
    def _note_write_target(self, tgt, lineno, held):
        while isinstance(tgt, ast.Subscript):
            tgt = tgt.value             # self.x[k] = v mutates self.x
        if isinstance(tgt, ast.Tuple):
            for el in tgt.elts:
                self._note_write_target(el, lineno, held)
            return
        key = self._state_key(tgt)
        if key is not None:
            self.fn.writes.append((key, lineno, held))

    def _note_call(self, node, held):
        callee = self._resolve_callable(node.func)
        if callee is not None:
            self.fn.calls.append((callee, held, node.lineno))
        chain = _dotted(node.func)
        if chain is None:
            return
        last = chain[-1]
        # mutating method call on shared state: self.x.append(...)
        if len(chain) >= 3 and chain[0] == "self" and \
                last in _MUTATORS and self.fn.cls:
            self.fn.writes.append((("attr", self.fn.cls, chain[1]),
                                   node.lineno, held))
        elif len(chain) == 2 and last in _MUTATORS and \
                chain[0] in self.fn.globals_decl:
            self.fn.writes.append((("global", chain[0]), node.lineno,
                                   held))
        # entry registrations
        if last in ("Thread", "Timer"):
            target = None
            for kw in node.keywords:
                if kw.arg == "target":
                    target = kw.value
            if last == "Timer" and len(node.args) >= 2:
                target = node.args[1]
            self._register_entry(target, "thread")
        elif last == "submit" and node.args:
            self._register_entry(node.args[0], "pool")
        elif chain == ("signal", "signal") and len(node.args) >= 2:
            q = self._register_entry(node.args[1], "signal")
            if q:
                self.mod.signal_handlers.add(q)
        self._note_blocking(node, chain, last, held)
        if last == "acquire":
            lk = self._lock_key(node.func.value)
            if lk is not None:
                self.fn.sigwork.append(
                    (f"lock acquire on {_lock_name(lk)}", node.lineno))
        if chain == ("open",):
            self.fn.sigwork.append(("file IO (open)", node.lineno))

    def _register_entry(self, expr, kind):
        if expr is None:
            return None
        qual = self._resolve_callable(expr)
        if qual is None:
            return None
        self.mod.entries.setdefault(qual, set()).add(f"{kind}:{qual}")
        self.mod.has_threading = True
        return qual

    def _has_timeout(self, node):
        if any(kw.arg in ("timeout", "block") and
               not (isinstance(kw.value, ast.Constant)
                    and kw.value.value is None)
               for kw in node.keywords):
            return True
        return bool(node.args)          # wait(t) / join(t) / result(t)

    def _note_blocking(self, node, chain, last, held):
        waited = None
        desc = None
        recv = chain[:-1]
        if last in ("wait", "wait_for") and recv:
            if recv[-1].lower().lstrip("_") in _EVENT_HINTS:
                return                  # Event.wait: no lock to order
            if last == "wait_for" and len(node.args) > 1:
                return                  # wait_for(pred, timeout)
            if last == "wait" and self._has_timeout(node):
                return
            if any(kw.arg == "timeout" and
                   not (isinstance(kw.value, ast.Constant)
                        and kw.value.value is None)
                   for kw in node.keywords):
                return
            waited = self._lock_key(node.func.value)
            desc = f"{'.'.join(chain)}() with no timeout"
        elif last == "join" and chain[0] not in _JOIN_EXEMPT_ROOTS \
                and not self._has_timeout(node):
            desc = f"{'.'.join(chain)}()"
        elif last == "result" and not self._has_timeout(node):
            desc = f"{'.'.join(chain)}()"
        elif last == "get" and recv and _QUEUE_HINTS.search(recv[-1]) \
                and not node.args:
            # zero positional args: Queue.get() blocks; dict.get(k) is
            # a lookup and never does
            desc = f"blocking {'.'.join(chain)}()"
        elif last in _SOCKET_BLOCKING:
            desc = f"socket {'.'.join(chain)}()"
        elif chain == ("time", "sleep"):
            desc = "time.sleep()"
        if desc is not None:
            self.fn.blocking.append((desc, node.lineno, held, waited))


# ---------------------------------------------------------------------------
# fixpoints
# ---------------------------------------------------------------------------

def _propagate(mod):
    """Contexts flow entry -> callee; ``callee_held`` is the meet (set
    intersection) of locks held at every in-module call site."""
    for qual, labels in mod.entries.items():
        fn = mod.fns.get(qual)
        if fn is not None:
            fn.is_entry = True
            fn.contexts |= labels
    callers = {q: [] for q in mod.fns}
    for fn in mod.fns.values():
        for callee, locks, _ln in fn.calls:
            if callee in callers:
                callers[callee].append((fn.qual, locks))
    for fn in mod.fns.values():
        if callers[fn.qual] or fn.is_entry:
            continue
        parent = fn.qual.rsplit(".", 1)[0] if "." in fn.qual else ""
        if parent in mod.fns:
            continue                    # uncalled nested helper: no ctx
        fn.contexts.add(_MAIN)          # uncalled top-level: API surface
    for _ in range(len(mod.fns) + 2):
        changed = False
        for fn in mod.fns.values():
            for caller, _locks in callers[fn.qual]:
                add = mod.fns[caller].contexts - fn.contexts
                if add:
                    fn.contexts |= add
                    changed = True
        if not changed:
            break
    for _ in range(len(mod.fns) + 2):
        changed = False
        for fn in mod.fns.values():
            sites = callers[fn.qual]
            if not sites:
                new = frozenset()
            else:
                metas = []
                for caller, locks in sites:
                    ch = mod.fns[caller].callee_held
                    metas.append(set(locks) | (set(ch) if ch else set()))
                new = frozenset(set.intersection(*metas))
            if new != fn.callee_held:
                fn.callee_held = new
                changed = True
        if not changed:
            break
    for fn in mod.fns.values():
        if fn.callee_held is None:
            fn.callee_held = frozenset()


def _transitive_acquires(mod):
    """What calling f (transitively, in-module) acquires."""
    out = {q: {(lk, ln) for lk, ln, _h in fn.acquires}
           for q, fn in mod.fns.items()}
    for _ in range(len(mod.fns) + 2):
        changed = False
        for fn in mod.fns.values():
            for callee, _locks, _ln in fn.calls:
                if callee in out and not out[callee] <= out[fn.qual]:
                    out[fn.qual] |= out[callee]
                    changed = True
        if not changed:
            break
    return out


# ---------------------------------------------------------------------------
# finding emission
# ---------------------------------------------------------------------------

def _suppressed(lines, lineno, code):
    # shared helper (findings.suppressed): canonical ``# ht-ok`` plus
    # the historical ``# lock-ok`` alias this pass introduced
    return suppressed(lines, lineno, code, markers=("ht-ok", "lock-ok"))


def _emit(mod, lines, report):
    path = mod.path

    def add(code, sev, msg, lineno, anchors=(), **data):
        for ln in (lineno, *anchors):
            if _suppressed(lines, ln, code):
                return
        report.findings.append(Finding(code, sev, msg,
                                       where=f"{path}:{lineno}", **data))

    # -- HT601: unsynchronized shared-state writes -----------------------
    by_state = {}
    for fn in mod.fns.values():
        if fn.node.name in _INIT_METHODS:
            continue                    # pre-thread-start construction
        for key, lineno, locks in fn.writes:
            if key[0] == "local":
                continue
            eff = frozenset(set(locks) | set(fn.callee_held))
            by_state.setdefault(key, []).append((fn, lineno, eff))
    for key, sites in sorted(by_state.items(), key=str):
        ctxs = set()
        for fn, _ln, _locks in sites:
            ctxs |= fn.contexts
        if len(ctxs) < 2 or not any(c != _MAIN for c in ctxs):
            continue
        if frozenset.intersection(*(lk for _f, _l, lk in sites)):
            continue                    # a common lock guards every site
        anchor = next((s for s in sites if not s[2]), sites[0])
        where = sorted({f"{fn.node.name}():{ln}" for fn, ln, _lk in sites})
        add("HT601", "error",
            f"shared state {_state_name(key)} written from "
            f"{len(ctxs)} thread contexts ({', '.join(sorted(ctxs))}) "
            f"with an empty common lockset — write sites "
            f"{', '.join(where)}; hold one lock across all of them or "
            f"annotate '# lock-ok: HT601 <reason>'",
            anchor[1], anchors=[ln for _f, ln, _lk in sites],
            state=_state_name(key), contexts=sorted(ctxs), sites=where)

    # -- HT602: lock-order inversion -------------------------------------
    acq_all = _transitive_acquires(mod)
    edges = {}                          # (a, b) -> example lineno
    for fn in mod.fns.values():
        for lk, lineno, held in fn.acquires:
            for h in set(held) | set(fn.callee_held):
                if h != lk:
                    edges.setdefault((h, lk), lineno)
        for callee, held, lineno in fn.calls:
            hold = set(held) | set(fn.callee_held)
            if not hold:
                continue
            for lk, _ln in acq_all.get(callee, ()):
                for h in hold:
                    if h != lk:
                        edges.setdefault((h, lk), lineno)
    reported = set()
    for (a, b) in sorted(edges, key=str):
        if (b, a) not in edges or (b, a) in reported:
            continue
        reported.add((a, b))
        la, lb = mod.lock_line(a), mod.lock_line(b)
        add("HT602", "error",
            f"lock-order inversion between {_lock_name(a)} (defined "
            f"{path}:{la}) and {_lock_name(b)} (defined {path}:{lb}): "
            f"order {_lock_name(a)} -> {_lock_name(b)} at line "
            f"{edges[(a, b)]} but {_lock_name(b)} -> {_lock_name(a)} "
            f"at line {edges[(b, a)]} — two threads taking opposite "
            f"orders deadlock",
            edges[(a, b)], anchors=[edges[(b, a)]],
            locks=[_lock_name(a), _lock_name(b)],
            defined_at=[f"{path}:{la}", f"{path}:{lb}"])

    # -- HT603: blocking while holding a lock ----------------------------
    for fn in mod.fns.values():
        for desc, lineno, held, waited in fn.blocking:
            eff = set(held) | set(fn.callee_held)
            eff.discard(waited)         # cond.wait releases its own lock
            if not eff:
                continue
            add("HT603", "warn",
                f"blocking {desc} in {fn.node.name}() while holding "
                f"{', '.join(sorted(_lock_name(k) for k in eff))} — "
                f"every thread needing the lock stalls behind this "
                f"wait and teardown can deadlock; move the wait "
                f"outside the region or bound it with a timeout",
                lineno, locks=sorted(_lock_name(k) for k in eff))
        for callee, held, lineno in fn.calls:
            eff = set(held) | set(fn.callee_held)
            cfn = mod.fns.get(callee)
            if not eff or cfn is None:
                continue
            for desc, bln, bheld, waited in cfn.blocking:
                ceff = set(bheld) | set(cfn.callee_held)
                ceff.discard(waited)
                if ceff:
                    continue            # already reported in the callee
                if set(eff) == {waited}:
                    continue
                add("HT603", "warn",
                    f"{fn.node.name}() holds "
                    f"{', '.join(sorted(_lock_name(k) for k in eff))} "
                    f"across a call to {callee.rsplit('.', 1)[-1]}(), "
                    f"which does blocking {desc} (line {bln})",
                    lineno, locks=sorted(_lock_name(k) for k in eff))

    # -- HT604: thread/pool lifecycle ------------------------------------
    for th in mod.threads:
        if th["in_with"]:
            continue
        if th["kind"] == "thread" and th["daemon"] is True:
            continue
        names = set()
        for key in th["targets"]:
            if key[0] == "attr":
                names.add(("self", key[2]))
                names.add((key[2],))
            else:
                names.add((key[-1],))
        joined = any(not names or any(recv[-len(n):] == n for n in names)
                     for recv in mod.joins)
        closed = any(names and any(recv[-len(n):] == n for n in names)
                     for recv in mod.shutdowns)
        if th["kind"] == "pool" and not (closed or joined):
            add("HT604", "warn",
                "worker pool is never shut down — its non-daemon "
                "threads outlive the owner and interpreter exit hangs "
                "while a worker is wedged in a job; call .shutdown() "
                "on every teardown path (or use a with-block)",
                th["lineno"])
        elif th["kind"] == "thread" and not joined:
            add("HT604", "warn",
                "non-daemon thread with no join/close registration — "
                "it outlives its owner and hangs interpreter exit if "
                "its loop never returns; join it on close() or mark "
                "it daemon=True with a cooperative stop flag",
                th["lineno"])

    # -- HT605: unguarded lazy init --------------------------------------
    if mod.has_threading:
        for fn in mod.fns.values():
            for key, lineno, locks in fn.lazy:
                if set(locks) | set(fn.callee_held):
                    continue
                add("HT605", "warn",
                    f"unguarded lazy-init of {_state_name(key)} in "
                    f"{fn.node.name}(): two threads can both observe "
                    f"it unset and both construct (check-then-create "
                    f"race); guard with a lock (double-checked is "
                    f"fine)", lineno, state=_state_name(key))

    # -- HT606: async-signal-unsafe signal handlers ----------------------
    for qual in sorted(mod.signal_handlers):
        fn = mod.fns.get(qual)
        if fn is None:
            continue
        work = list(fn.sigwork)
        work += [(f"blocking {d}", ln) for d, ln, _h, _w in fn.blocking]
        work += [(f"lock acquisition of {_lock_name(lk)}", ln)
                 for lk, ln, _h in fn.acquires]
        for callee, _h, ln in fn.calls:
            cfn = mod.fns.get(callee)
            if cfn is not None and (cfn.acquires or cfn.sigwork):
                work.append((f"a call into {callee.rsplit('.', 1)[-1]}()"
                             f" which acquires locks / does IO", ln))
        for desc, lineno in sorted(set(work), key=lambda x: x[1]):
            add("HT606", "warn",
                f"signal handler {fn.node.name}() does {desc} — a "
                f"handler interrupting the lock's own holder "
                f"self-deadlocks and buffered IO is not reentrant; "
                f"set a flag and do the work on the main loop",
                lineno, handler=qual)


# ---------------------------------------------------------------------------
# drivers
# ---------------------------------------------------------------------------

def check_source(src, path="<string>"):
    """Lint one module's source for HT6xx findings; returns a Report."""
    report = Report()
    try:
        tree = ast.parse(src, filename=path)
    except SyntaxError as e:
        report.add("HT600", "error", f"unparseable module: {e}",
                   where=path)
        return report
    mod = _Module(path)
    _Collector(mod).visit(tree)
    for fn in list(mod.fns.values()):
        _BodyWalker(mod, fn).walk()
    _propagate(mod)
    _emit(mod, src.splitlines(), report)
    return report


def check_paths(paths):
    """Lint every ``.py`` under the given files/directories."""
    report = Report()
    files = []
    for p in paths:
        if os.path.isdir(p):
            for root, _dirs, names in os.walk(p):
                if "__pycache__" in root:
                    continue
                files.extend(os.path.join(root, n)
                             for n in sorted(names) if n.endswith(".py"))
        else:
            files.append(p)
    for f in files:
        with open(f, encoding="utf-8") as fh:
            report.extend(check_source(fh.read(), path=f).findings)
    return report


def main(argv=None):
    import argparse
    parser = argparse.ArgumentParser(
        prog="python -m hetu_tpu.analysis.concurrency",
        description="static lockset / lock-order / thread-lifecycle "
                    "verifier for the threaded host runtime (HT6xx)")
    parser.add_argument("paths", nargs="*",
                        help="files or directories (default: the "
                             "hetu_tpu package)")
    parser.add_argument("--json", action="store_true",
                        help="machine-readable output")
    args = parser.parse_args(argv)
    paths = args.paths or [os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))]
    report = check_paths(paths)
    print(report.to_json() if args.json else report.to_text())
    # ANY unsuppressed finding gates: a warn here is a deadlock in
    # waiting, not style — by-design sites carry explicit lock-ok
    # reasons instead
    return 1 if len(report) else 0


if __name__ == "__main__":
    sys.exit(main())
