"""Codebase self-lint: host impurity inside jit-compiled function bodies.

The bug class PR 5 hit — host-side state (thread-local trace flags,
wall clocks, ``np.random``) read inside a function that jax traces —
produces silently wrong programs: the call evaluates ONCE at trace time
and bakes a constant into the compiled step. This AST checker finds
function bodies that are statically known to be traced:

* ``@jax.jit`` / ``@jit`` / ``@partial(jax.jit, ...)`` decorated defs,
* local defs passed to ``jax.jit`` / ``jit`` / ``lax.scan`` /
  ``jax.vjp`` / ``jax.grad`` / ``jax.value_and_grad`` /
  ``shard_map`` / ``jax.checkpoint`` (and defs nested inside those),

and flags inside them:

HTP01  wall-clock reads (``time.*``, ``datetime.*``)          error
HTP02  host RNG (``np.random.*``, ``random.*``)               error
HTP03  host IO (``open``/``input``/``os.*``)                  error
HTP10  host ``numpy`` call (fine for static shape math; worth
       an eye when the operand is traced)                     warn
HTP20  Python ``if``/``while`` on a traced function parameter
       (use ``lax.cond`` / ``jnp.where``)                     warn

A line ending in ``# ht-ok`` / ``# jit-ok`` (optionally with a code and
reason, house style ``# ht-ok: HTP20 <reason>``) suppresses its
findings — for host math that is provably static at trace time. The
check is the shared :func:`~.findings.suppressed` helper, so every
pass's waivers share one grep surface.

CLI: ``python -m hetu_tpu.analysis.jit_purity [paths...]`` (default:
the ``hetu_tpu`` package) — exit 1 when errors exist; wired into CI as
its own job.

Scope limitation, by design: only *directly* traced bodies are checked.
A helper called from a jitted function is traced too, but a static
checker cannot know every call site's context without whole-program
inference — the direct layer is where PR 5's bug lived.
"""
from __future__ import annotations

import ast
import os
import sys

from .findings import Finding, Report, suppressed

__all__ = ["check_source", "check_paths", "main"]

_JIT_WRAPPERS = {"jit"}                      # jax.jit(f) / jit(f)
_TRACED_CALLS = {"jit", "scan", "vjp", "grad", "value_and_grad",
                 "checkpoint", "shard_map", "eval_shape", "remat"}
_CLOCK_MODULES = {"time", "datetime"}
_RNG_ROOTS = {("np", "random"), ("numpy", "random")}
_HOST_MODULES = {"os"}
_NUMPY_NAMES = {"np", "numpy"}


def _dotted(node):
    """Attribute/Name chain -> tuple of names ('jax','lax','scan')."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return tuple(reversed(parts))
    return None


def _is_traced_wrapper(call):
    """Is this Call one whose function argument gets traced?"""
    chain = _dotted(call.func)
    if chain is None:
        return False
    return chain[-1] in _TRACED_CALLS


def _decorated_jit(fn):
    for dec in fn.decorator_list:
        chain = _dotted(dec)
        if chain and chain[-1] in _JIT_WRAPPERS:
            return True
        if isinstance(dec, ast.Call):
            dchain = _dotted(dec.func)
            if dchain and dchain[-1] in _JIT_WRAPPERS:
                return True
            if dchain and dchain[-1] == "partial" and dec.args:
                achain = _dotted(dec.args[0])
                if achain and achain[-1] in _JIT_WRAPPERS:
                    return True
    return False


def _collect_traced_defs(tree):
    """FunctionDefs whose bodies jax traces: decorated ones, plus local
    defs referenced by name from a traced wrapper call in any scope."""
    defs_by_scope = {}   # scope node -> {name: FunctionDef}

    class ScopeWalk(ast.NodeVisitor):
        def __init__(self):
            self.stack = [tree]
            defs_by_scope[tree] = {}

        def _visit_fn(self, node):
            defs_by_scope[self.stack[-1]][node.name] = node
            self.stack.append(node)
            defs_by_scope[node] = {}
            self.generic_visit(node)
            self.stack.pop()

        visit_FunctionDef = _visit_fn
        visit_AsyncFunctionDef = _visit_fn

    ScopeWalk().visit(tree)

    traced = set()
    for scope, local in defs_by_scope.items():
        for fn in local.values():
            if _decorated_jit(fn):
                traced.add(fn)
        for node in ast.walk(scope):
            if not (isinstance(node, ast.Call)
                    and _is_traced_wrapper(node)):
                continue
            for arg in list(node.args) + [kw.value
                                          for kw in node.keywords]:
                if isinstance(arg, ast.Name) and arg.id in local:
                    traced.add(local[arg.id])
    return traced


def _check_body(fn, path, src_lines, report):
    params = {a.arg for a in (fn.args.args + fn.args.posonlyargs
                              + fn.args.kwonlyargs)}
    params.discard("self")

    def add(code, sev, msg, node):
        if suppressed(src_lines, node.lineno, code,
                      markers=("ht-ok", "jit-ok")):
            return
        report.findings.append(Finding(
            code, sev, msg, where=f"{path}:{node.lineno}",
            node=fn.name))

    for node in ast.walk(fn):
        if isinstance(node, ast.Call):
            chain = _dotted(node.func)
            if chain:
                root = chain[0]
                if root in _CLOCK_MODULES and len(chain) > 1:
                    add("HTP01", "error",
                        f"wall-clock read {'.'.join(chain)}() inside "
                        f"jit-traced {fn.name}() — evaluates once at "
                        f"trace time, bakes a constant into the "
                        f"compiled program", node)
                elif len(chain) >= 2 and chain[:2] in _RNG_ROOTS:
                    add("HTP02", "error",
                        f"host RNG {'.'.join(chain)}() inside "
                        f"jit-traced {fn.name}() — draws once at trace "
                        f"time; thread the jax PRNG key instead", node)
                elif root == "random" and len(chain) > 1:
                    add("HTP02", "error",
                        f"host RNG {'.'.join(chain)}() inside "
                        f"jit-traced {fn.name}()", node)
                elif root in _HOST_MODULES and len(chain) > 1:
                    add("HTP03", "error",
                        f"host call {'.'.join(chain)}() inside "
                        f"jit-traced {fn.name}() — IO/state reads do "
                        f"not re-execute per step", node)
                elif chain in (("open",), ("input",)):
                    add("HTP03", "error",
                        f"host IO {chain[0]}() inside jit-traced "
                        f"{fn.name}()", node)
                elif root in _NUMPY_NAMES and len(chain) > 1:
                    add("HTP10", "warn",
                        f"host numpy {'.'.join(chain)}() inside "
                        f"jit-traced {fn.name}() — fine on static "
                        f"values; a traced operand silently constant-"
                        f"folds", node)
        elif isinstance(node, (ast.If, ast.While)):
            names = {n.id for n in ast.walk(node.test)
                     if isinstance(n, ast.Name)}
            hit = names & params
            if hit:
                add("HTP20", "warn",
                    f"Python {'while' if isinstance(node, ast.While) else 'if'} "
                    f"on traced parameter(s) {sorted(hit)} inside "
                    f"jit-traced {fn.name}() — a tracer-dependent "
                    f"branch raises (or freezes one path); use "
                    f"lax.cond / jnp.where", node)


def check_source(src, path="<string>"):
    """Lint one module's source; returns a Report."""
    report = Report()
    try:
        tree = ast.parse(src, filename=path)
    except SyntaxError as e:
        report.add("HTP00", "error", f"unparseable module: {e}",
                   where=path)
        return report
    src_lines = src.splitlines()
    for fn in _collect_traced_defs(tree):
        _check_body(fn, path, src_lines, report)
    return report


def check_paths(paths):
    """Lint every ``.py`` under the given files/directories."""
    report = Report()
    files = []
    for p in paths:
        if os.path.isdir(p):
            for root, _dirs, names in os.walk(p):
                if "__pycache__" in root:
                    continue
                files.extend(os.path.join(root, n)
                             for n in sorted(names) if n.endswith(".py"))
        else:
            files.append(p)
    for f in files:
        with open(f, encoding="utf-8") as fh:
            report.extend(check_source(fh.read(), path=f).findings)
    return report


def main(argv=None):
    import argparse
    parser = argparse.ArgumentParser(
        prog="python -m hetu_tpu.analysis.jit_purity",
        description="flag host-side impurity inside jit-traced "
                    "function bodies")
    parser.add_argument("paths", nargs="*",
                        help="files or directories (default: the "
                             "hetu_tpu package)")
    parser.add_argument("--json", action="store_true",
                        help="machine-readable output")
    args = parser.parse_args(argv)
    paths = args.paths or [os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))]
    report = check_paths(paths)
    print(report.to_json() if args.json else report.to_text())
    return 1 if report.errors else 0


if __name__ == "__main__":
    sys.exit(main())
