"""Pass 3 — static deadlock detection for pipeline schedules (HT3xx).

The define-then-run model means the *entire* communication schedule is
known before launch: stage assignment comes from device contexts
(``parallel/pipeline.py _build_stages``), ownership from
``_owner_of``, and the issue order from the schedule drivers
(``_run_gpipe_multiproc`` / ``_drive_1f1b``). This pass rebuilds that
schedule symbolically — per rank, an ordered program of send/recv/
collective events with the same tags the runtime uses — and executes it
over a buffered channel model (the p2p channel's reader thread drains
sockets, so sends never block; only recvs do). A schedule that cannot
drain is a fleet that hangs at step 0 and gets killed by PR 4's
watchdog after ``--hang-timeout`` seconds; here it is a sub-second
finding naming the blocked ranks. The static complement of the runtime
flight recorder.

Error codes
-----------
HT301  recv never satisfied (no rank ever sends the tag)        error
HT302  cyclic wait between ranks (true deadlock)                error
HT303  collective issue order diverges across ranks             error
HT304  unpaired pipeline send/recv markers                      error
HT305  send never received (orphan boundary transfer)           warn
HT306  collective-pipeline contract violation (non-linear
       chain, loss off the last stage, ...)                     error
HT307  stage consumes a boundary produced by a LATER stage on
       the same rank (forward schedule order violation)         error
HT308  interleaved (virtual-stage) schedule without round-robin
       stage ownership — the runtime refuses it at construction
       and the bubble reduction is forfeited                    error

The ``interleaved_1f1b`` schedule (``pp_options virtual_stages > 1``,
stages placed round-robin so each rank owns V chunks) executes the
SAME per-microbatch 1F1B driver — its event programs are the 1f1b
replay over the interleaved ownership map (the channel's buffered
sends + blocking recvs realize the chunk interleaving at run time), so
HT301/302/305 coverage extends to it unchanged; HT308 is the
placement-shape check specific to it.
"""
from __future__ import annotations

import os
from collections import Counter

__all__ = ["build_plan", "rank_programs", "simulate",
           "collective_order_pass", "interleaved_placement_pass",
           "deadlock_pass", "Event", "PipelinePlan"]


class Event:
    """One symbolic schedule action of a rank."""

    __slots__ = ("kind", "peer", "tag", "label")

    def __init__(self, kind, peer=None, tag=None, label=""):
        self.kind = kind          # "send" | "recv" | "collective"
        self.peer = peer          # the other rank (send dst / recv src)
        self.tag = tag
        self.label = label

    def __repr__(self):
        return f"Event({self.kind}, peer={self.peer}, tag={self.tag})"


class _Stage:
    __slots__ = ("index", "owner", "hostname", "device_id", "nodes",
                 "in_nodes", "out_nodes", "consumed_outs")

    def __init__(self, index, hostname, device_id=0):
        self.index = index
        self.owner = 0
        self.hostname = hostname
        self.device_id = device_id
        self.nodes = []
        self.in_nodes = []
        self.out_nodes = []
        self.consumed_outs = []


class PipelinePlan:
    """Stage graph + ownership, mirrored from the pipeline executor's
    ``_build_stages`` without touching devices or jits."""

    def __init__(self, stages, assign, consumers, loss_node):
        self.stages = stages
        self.assign = assign            # node -> stage index
        self.consumers = consumers      # node -> [consuming stages]
        self.loss_node = loss_node

    @property
    def nranks(self):
        return len({s.owner for s in self.stages})


def build_plan(eval_nodes, nprocs=None):
    """Stage the forward graph exactly as ``PipelineSubExecutor`` would;
    returns None when fewer than two stages exist (no pipeline)."""
    from ..graph.autodiff import find_topo_sort
    from ..optimizer import OptimizerOp
    from ..ops.comm import PipelineReceiveOp, PipelineSendOp
    from ..ops.variable import PlaceholderOp
    from ..parallel.pipeline import _device_key, _owner_of

    eval_fwd = [n for n in eval_nodes if not isinstance(n, OptimizerOp)]
    if not eval_fwd:
        return None
    topo = [n for n in find_topo_sort(eval_fwd)
            if not isinstance(n, (PipelineSendOp, PipelineReceiveOp))]

    keys = []
    for node in topo:
        k = _device_key(node)
        if k is not None and k not in keys \
                and not isinstance(node, PlaceholderOp):
            keys.append(k)
    if len(keys) < 2:
        return None
    key_to_stage = {k: i for i, k in enumerate(keys)}
    stages = [_Stage(i, k[0][0], device_id=k[0][1])
              for i, k in enumerate(keys)]

    assign = {}
    for node in topo:
        if isinstance(node, PlaceholderOp):
            continue
        s = key_to_stage.get(_device_key(node))
        if s is None:
            s = max((assign.get(i, 0) for i in node.inputs), default=0)
        assign[node] = s
        stages[s].nodes.append(node)
    for node in topo:
        if isinstance(node, PlaceholderOp):
            consumers = [assign[n] for n in topo
                         if not isinstance(n, PlaceholderOp)
                         and node in n.inputs]
            assign[node] = min(consumers) if consumers else 0

    for node in topo:
        if isinstance(node, PlaceholderOp):
            continue
        s = assign[node]
        for inp in node.inputs:
            si = assign.get(inp, s)
            if si != s and not isinstance(inp, PlaceholderOp):
                if inp not in stages[s].in_nodes:
                    stages[s].in_nodes.append(inp)
                if inp not in stages[si].out_nodes:
                    stages[si].out_nodes.append(inp)
    loss_node = eval_fwd[0]
    for ev in eval_fwd:
        s = assign.get(ev)
        if s is not None and ev not in stages[s].out_nodes:
            stages[s].out_nodes.append(ev)
    all_ins = set()
    for st in stages:
        all_ins.update(st.in_nodes)
    for st in stages:
        st.consumed_outs = [n for n in st.out_nodes if n in all_ins]

    if nprocs is None:
        nprocs = int(os.environ.get("HETU_NUM_PROCS", "1"))
    for st in stages:
        st.owner = _owner_of(st.hostname, nprocs)

    consumers = {}
    for st in stages:
        for node in st.in_nodes:
            consumers.setdefault(node, []).append(st)
    return PipelinePlan(stages, assign, consumers, loss_node)


# ---------------------------------------------------------------------------
# schedule -> per-rank symbolic programs
# ---------------------------------------------------------------------------

def _fwd_events(plan, progs, report, m=None):
    """One forward sweep (stage order) — the shared shape of the GPipe
    block phase and each 1F1B ``forward(m)``."""
    stages = plan.stages
    for stage in stages:
        r = stage.owner
        for node in stage.in_nodes:
            src = stages[plan.assign[node]]
            if src.owner != r:
                progs[r].append(Event(
                    "recv", peer=src.owner, tag=("f", m, node.id,
                                                 stage.index),
                    label=f"{node.name} (stage {stage.index} <- stage "
                          f"{src.index})"))
            elif src.index > stage.index and report is not None \
                    and m in (None, 0):
                # structural finding: report once, not once per microbatch
                report.add(
                    "HT307", "error",
                    f"stage {stage.index} consumes {node.name} produced "
                    f"by LATER stage {src.index} on the same rank — the "
                    f"forward schedule runs stages in order and would "
                    f"read an unset boundary", node=node)
        for node in stage.consumed_outs:
            for cons in plan.consumers.get(node, ()):
                if cons.owner != r:
                    progs[r].append(Event(
                        "send", peer=cons.owner,
                        tag=("f", m, node.id, cons.index),
                        label=f"{node.name} (stage {stage.index} -> "
                              f"stage {cons.index})"))


def _bwd_events(plan, progs, m=None):
    """One backward sweep (reverse stage order): cotangent recvs from
    foreign consumers, cotangent sends to foreign producers."""
    stages = plan.stages
    for stage in reversed(stages):
        r = stage.owner
        for node in stage.out_nodes:
            for cons in plan.consumers.get(node, ()):
                if cons.owner != r:
                    progs[r].append(Event(
                        "recv", peer=cons.owner,
                        tag=("b", m, node.id, cons.index),
                        label=f"cotangent of {node.name} (stage "
                              f"{stage.index} <- stage {cons.index})"))
        for node in stage.in_nodes:
            src = stages[plan.assign[node]]
            if src.owner != r:
                progs[r].append(Event(
                    "send", peer=src.owner,
                    tag=("b", m, node.id, stage.index),
                    label=f"cotangent of {node.name} (stage "
                          f"{stage.index} -> stage {src.index})"))


def rank_programs(plan, schedule="gpipe", num_microbatches=None,
                  report=None):
    """Per-rank ordered event programs for a schedule, mirroring the
    runtime drivers (``_run_gpipe_multiproc`` issue order for gpipe;
    the exact ``_drive_1f1b`` interleaving for 1f1b)."""
    from ..ops.comm import AllReduceCommunicateOp
    from ..parallel.pipeline import _drive_1f1b

    ranks = sorted({s.owner for s in plan.stages})
    progs = {r: [] for r in ranks}
    S = len(plan.stages)
    M = num_microbatches or max(2, S)

    # in-stage collectives (TP inside a stage): record issue order so a
    # rank pair sharing a stage group can be cross-checked
    for stage in plan.stages:
        for node in stage.nodes:
            if isinstance(node, AllReduceCommunicateOp):
                progs[stage.owner].append(Event(
                    "collective", tag=node.op_type, label=node.name))

    if schedule == "gpipe":
        _fwd_events(plan, progs, report)
        _bwd_events(plan, progs)
    else:        # 1f1b / interleaved_1f1b — replay the real driver
        _drive_1f1b(lambda m: _fwd_events(plan, progs, report, m=m),
                    lambda m: _bwd_events(plan, progs, m=m), S, M)
    return progs


def interleaved_placement_pass(plan, report, virtual_stages=None):
    """HT308: a schedule declared interleaved (virtual_stages > 1)
    must place stages round-robin over the ranks — stage i on rank
    i mod nranks, V = nstages/nranks chunks per rank (what
    ``pipeline.virtual_stage_program`` models). The multiproc runtime
    REFUSES this configuration (``PipelineSubExecutor`` raises
    ``ValueError`` on non-round-robin ownership under virtual_stages),
    so the static form is an error: a preflight that passed it would
    approve a launch that dies on every rank at construction."""
    owners = [s.owner for s in plan.stages]
    ranks = sorted(set(owners))
    nr = len(ranks)
    S = len(owners)
    ok = (nr > 0 and S % nr == 0
          and all(o == owners[i % nr] for i, o in enumerate(owners)))
    v = S // nr if nr else 1
    if not ok:
        report.add(
            "HT308", "error",
            f"interleaved schedule (virtual_stages="
            f"{virtual_stages or v}) without round-robin placement: "
            f"stage owners are {owners}, expected stage i on rank "
            f"i mod {nr} — the pipeline executor refuses this at "
            f"construction (and consecutive chunks on one rank would "
            f"forfeit the ~1/V bubble reduction anyway); cycle the "
            f"worker contexts V times")
    return ok


# ---------------------------------------------------------------------------
# symbolic execution
# ---------------------------------------------------------------------------

def simulate(programs, report):
    """Execute the per-rank programs over a buffered channel; report
    HT301/HT302/HT305. Returns True when every program drains."""
    pcs = {r: 0 for r in programs}
    chan = Counter()
    progressed = True
    while progressed:
        progressed = False
        for r, prog in programs.items():
            while pcs[r] < len(prog):
                ev = prog[pcs[r]]
                if ev.kind == "send":
                    chan[(ev.peer, ev.tag)] += 1
                elif ev.kind == "recv":
                    if chan[(r, ev.tag)] <= 0:
                        break
                    chan[(r, ev.tag)] -= 1
                pcs[r] += 1
                progressed = True

    blocked = {r: prog[pcs[r]] for r, prog in programs.items()
               if pcs[r] < len(prog)}
    for r, ev in sorted(blocked.items()):
        # does ANY rank still hold a future send matching this recv?
        sender = None
        for r2, prog in programs.items():
            for e2 in prog[pcs[r2]:]:
                if e2.kind == "send" and e2.peer == r \
                        and e2.tag == ev.tag:
                    sender = r2
                    break
            if sender is not None:
                break
        if sender is None:
            report.add(
                "HT301", "error",
                f"rank {r} blocks forever waiting to receive "
                f"{ev.label} from rank {ev.peer}: no rank ever sends "
                f"it — mis-paired pipeline send/recv")
        else:
            peer_ev = blocked.get(sender)
            peer_txt = (f"rank {sender} is itself blocked waiting to "
                        f"receive {peer_ev.label} from rank "
                        f"{peer_ev.peer}" if peer_ev is not None
                        else f"rank {sender} would send it only later "
                        f"in its schedule")
            report.add(
                "HT302", "error",
                f"static deadlock: rank {r} waits to receive "
                f"{ev.label} from rank {sender}, while {peer_txt} — "
                f"cyclic wait, the fleet would hang at this step")
    if not blocked:
        for (dst, tag), n in chan.items():
            if n > 0:
                report.add(
                    "HT305", "warn",
                    f"{n} boundary send(s) to rank {dst} (tag {tag}) "
                    f"are never received — dead transfer, likely a "
                    f"stale or duplicated pipeline marker")
    return not blocked


def collective_order_pass(programs, report):
    """Every rank participating in collectives must issue the identical
    sequence — a divergence is a guaranteed cross-rank hang (HT303)."""
    seqs = {r: [ev.tag for ev in prog if ev.kind == "collective"]
            for r, prog in programs.items()}
    seqs = {r: s for r, s in seqs.items() if s}
    if len(seqs) < 2:
        return
    ranks = sorted(seqs)
    ref_rank, ref = ranks[0], seqs[ranks[0]]
    for r in ranks[1:]:
        s = seqs[r]
        if s == ref:
            continue
        k = next((i for i, (a, b) in enumerate(zip(ref, s)) if a != b),
                 min(len(ref), len(s)))
        a = ref[k] if k < len(ref) else "<end of schedule>"
        b = s[k] if k < len(s) else "<end of schedule>"
        report.add(
            "HT303", "error",
            f"collective issue order diverges: rank {ref_rank} issues "
            f"{a} as collective #{k} but rank {r} issues {b} — the "
            f"fleet deadlocks at the first mismatched collective")


def _collective_interleaved_pass(plan, report, virtual_stages):
    """HT308, collective form: virtual_stages=V folds S·V stages onto
    S devices, so the stage contexts' device ids must repeat
    round-robin (stage i on device i % S_dev, first S_dev distinct) —
    the exact check ``pipeline._build_collective`` enforces with a
    ``ValueError`` at first dispatch; here it refuses the launch
    statically instead."""
    V = int(virtual_stages)
    S = len(plan.stages)
    devs = [s.device_id for s in plan.stages]
    if S % V != 0:
        report.add(
            "HT308", "error",
            f"interleaved collective pipeline: virtual_stages={V} "
            f"must divide the stage count {S}")
        return False
    s_dev = S // V
    if len(set(devs[:s_dev])) != s_dev or any(
            devs[i] != devs[i % s_dev] for i in range(S)):
        report.add(
            "HT308", "error",
            f"interleaved collective pipeline (virtual_stages={V}) "
            f"needs round-robin placement: stage i on device "
            f"i % {s_dev}, got devices {devs} — the collective "
            f"builder refuses this at first dispatch; cycle the "
            f"ht.context(...) device list V times")
        return False
    return True


def _collective_chain_pass(plan, report):
    """Static form of CollectiveGPipe's linear-chain contract (the
    builder raises at trace time; preflight reports before launch)."""
    S = len(plan.stages)
    ls = plan.assign.get(plan.loss_node)
    if ls is not None and ls != S - 1:
        report.add(
            "HT306", "error",
            f"collective pipeline expects the loss on the last stage "
            f"(found on stage {ls})", node=plan.loss_node)
    for i, st in enumerate(plan.stages):
        if i == 0 and st.in_nodes:
            report.add("HT306", "error",
                       "collective pipeline: stage 0 must not consume "
                       "boundary tensors")
        if i > 0 and (len(st.in_nodes) != 1
                      or plan.assign[st.in_nodes[0]] != i - 1):
            report.add(
                "HT306", "error",
                f"collective pipeline needs a linear chain with one "
                f"boundary per stage; stage {i} consumes "
                f"{[(n.name, plan.assign[n]) for n in st.in_nodes]}")
        if i < S - 1 and len(st.consumed_outs) != 1:
            report.add(
                "HT306", "error",
                f"collective pipeline: stage {i} must export exactly "
                f"one boundary tensor (got {len(st.consumed_outs)})")


def deadlock_pass(eval_nodes, report, schedule="gpipe", nprocs=None,
                  num_microbatches=None, virtual_stages=None):
    """Full pass: marker pairing, staging, per-schedule symbolic run."""
    from ..graph.autodiff import find_topo_sort
    from ..ops.comm import PipelineReceiveOp, PipelineSendOp

    topo = find_topo_sort([n for n in eval_nodes])
    unbound = [n for n in topo if isinstance(n, PipelineReceiveOp)
               and n.bound_send is None]
    if unbound:
        pending = PipelineSendOp.pending()
        if len(pending) != len(unbound):
            report.add(
                "HT304", "error",
                f"unpaired pipeline markers: {len(pending)} pending "
                f"send(s) vs {len(unbound)} receive(s) — each recv "
                f"must pair with exactly one send built for this graph",
                node=unbound[0])
            return None

    plan = build_plan(eval_nodes, nprocs=nprocs)
    if plan is None:
        return None
    if schedule == "collective":
        _collective_chain_pass(plan, report)
        if virtual_stages and virtual_stages > 1:
            _collective_interleaved_pass(plan, report, virtual_stages)
        return plan
    if schedule == "interleaved_1f1b" or (virtual_stages
                                          and virtual_stages > 1):
        if plan.nranks > 1:
            interleaved_placement_pass(plan, report,
                                       virtual_stages=virtual_stages)
        schedule = "1f1b"       # same driver: replay its event order
    programs = rank_programs(plan, schedule=schedule,
                             num_microbatches=num_microbatches,
                             report=report)
    simulate(programs, report)
    collective_order_pass(programs, report)
    return plan
