"""Pass 1 — static shape/dtype propagation + graph lint (HT1xx).

Walks the topo order through the ops' existing ``infer_shape`` protocol
(the same code the executor's eager shape-inference pass runs at first
dispatch) but *catches* the assertion an op raises on mismatched inputs
and turns it into a finding carrying the op's construction provenance —
the user's model line — instead of a traceback from deep inside
``executor.py``. Feed placeholders have no shape until run time, so
propagation treats them as *unknown* unless the caller supplies
``feed_shapes``; unknown inputs simply stop propagation along that path
(no false positives), which is why the zoo preflights clean without
feeds while a CLI run with shapes checks everything.

Error codes
-----------
HT101  shape inference failed (mismatched operands)        error
HT102  dtype-kind mismatch between declared operand dtypes  warn
HT110  dead subgraph (reachable from extra roots only)      info
HT111  trainable variable not covered by any optimizer      warn
HT112  duplicate trainable parameter name                   warn
HT150  frozen-graph violation: optimizer op                 error
HT151  frozen-graph violation: PS push op                   error
HT152  frozen-graph violation: dataloader op                error
"""
from __future__ import annotations

import numpy as np

__all__ = ["shape_pass", "lint_pass", "frozen_graph_pass"]

# ops whose operands must agree in dtype *kind* (float vs int); lookup /
# indexing ops legitimately mix and are excluded
_DTYPE_STRICT = {
    "AddOp", "MulOp", "DivOp", "MatMulOp", "BatchMatMulOp", "Conv2dOp",
    "MatrixDotOp",
}


def _node_dtype(node):
    dt = getattr(node, "dtype", None)
    if dt is None:
        return None
    try:
        return np.dtype(dt)
    except TypeError:
        return None


def _result_dtype(node, in_dts):
    """Static result dtype for one op: declared dtype for leaves and
    casts, jax's promotion lattice over the known operand dtypes
    otherwise (``jnp.promote_types`` — NOT numpy's, whose int+float
    promotion would invent float64s the traced program never makes).
    None when nothing is known. The numerics pass (HT8xx) rides this
    to classify every node fp32/bf16/fp16/int."""
    if node.op_type == "CastOp":
        return _node_dtype(node)
    if node.op_type == "OneHotOp":
        return np.dtype(np.float32)     # jax.nn.one_hot(dtype=float32)
    known = [d for d in in_dts if d is not None]
    if not known:
        return _node_dtype(node)
    import jax.numpy as jnp
    out = known[0]
    for d in known[1:]:
        try:
            out = np.dtype(jnp.promote_types(out, d))
        except TypeError:
            return out
    return np.dtype(out)


def _resolve_feed_shapes(feed_shapes, topo):
    """Accept {node: shape} or {name: shape}; values may be a bare shape
    tuple or (shape, dtype)."""
    if not feed_shapes:
        return {}
    by_name = {n.name: n for n in topo}
    out = {}
    for key, val in feed_shapes.items():
        node = by_name.get(key) if isinstance(key, str) else key
        if node is None:
            continue
        if (isinstance(val, tuple) and len(val) == 2
                and isinstance(val[0], (tuple, list))):
            out[node] = (tuple(val[0]),
                         np.dtype(val[1]) if val[1] is not None else None)
        else:
            out[node] = (tuple(val), None)
    return out


_MISSING = object()


def shape_pass(topo, report, feed_shapes=None, dtypes_out=None):
    """Propagate shapes/dtypes; returns {node: shape or None}.

    ``dtypes_out`` (optional dict) receives ``{node: np.dtype or None}``
    — the propagated result dtypes the numerics pass (HT8xx) reads as
    its precision classes. Feed dtypes come from ``feed_shapes`` when
    declared there (id feeds are routinely built as default-float32
    Variables and fed integer arrays; the feed spec is the truth).

    Mirrors the executor's ``_infer_shapes`` protocol: gradient ops like
    ``BroadcastShapeGradSourceOp`` read a *non-input* forward node's
    ``inferred_shape`` attribute, so the pass sets it on each node as it
    walks (and deletes it where the shape is unknown, so a cross-
    reference to an unshaped node raises AttributeError and is treated
    as *unknown*, not as a user error). Prior values are restored on
    exit — analysis leaves the graph untouched.
    """
    from ..ops.variable import PlaceholderOp
    from ..ops.comm import PipelineReceiveOp, PipelineSendOp
    from ..dataloader import DataloaderOp, GNNDataLoaderOp
    from ..optimizer import OptimizerOp

    feeds = _resolve_feed_shapes(feed_shapes, topo)
    shapes = {}
    dtypes = dtypes_out if dtypes_out is not None else {}
    unknown = 0
    saved = {}

    def _mark(node, shape):
        shapes[node] = shape
        if id(node) not in saved:
            saved[id(node)] = (node,
                               getattr(node, "inferred_shape", _MISSING))
        if shape is not None:
            node.inferred_shape = shape
        elif hasattr(node, "inferred_shape"):
            del node.inferred_shape

    try:
        for node in topo:
            if node in feeds:
                shape, dt = feeds[node]
                _mark(node, shape)
                dtypes[node] = dt if dt is not None else _node_dtype(node)
                continue
            if isinstance(node, PlaceholderOp):
                _mark(node, (tuple(node.shape)
                             if node.shape is not None else None))
                dtypes[node] = _node_dtype(node)
                if shapes[node] is None:
                    unknown += 1
                continue
            if isinstance(node, (OptimizerOp, DataloaderOp,
                                 GNNDataLoaderOp, PipelineReceiveOp)):
                # host/schedule nodes carry no statically inferable shape
                # (a recv's shape comes from its bound send at run time)
                _mark(node, None)
                dtypes[node] = None
                continue
            in_shapes = [shapes.get(i) for i in node.inputs]
            if any(s is None for s in in_shapes):
                _mark(node, (in_shapes[0]
                             if isinstance(node, PipelineSendOp)
                             else None))
                dtypes[node] = _result_dtype(
                    node, [dtypes.get(i) for i in node.inputs])
                continue
            try:
                _mark(node, tuple(node.infer_shape(list(in_shapes))))
            except NotImplementedError:
                _mark(node, None)
            except AttributeError as e:
                if "inferred_shape" in str(e):
                    # cross-reference into an unshaped subgraph (a grad
                    # op's forward/target node fed by an unknown feed)
                    _mark(node, None)
                else:
                    report.add(
                        "HT101", "error",
                        f"shape inference failed for {node.op_type} "
                        f"{node.name}: {e}", node=node)
                    _mark(node, None)
            except Exception as e:  # noqa: BLE001 — the op's mismatch check
                report.add(
                    "HT101", "error",
                    f"shape inference failed for {node.op_type} "
                    f"{node.name}: {e} (inputs "
                    f"{[(i.name, shapes.get(i)) for i in node.inputs]})",
                    node=node)
                _mark(node, None)
            # dtype-kind check on strict arithmetic ops (declared only)
            in_dts = [dtypes.get(i) for i in node.inputs]
            known = [d for d in in_dts if d is not None]
            if node.op_type in _DTYPE_STRICT and len(known) >= 2:
                kinds = {d.kind for d in known}
                if len(kinds) > 1:
                    report.add(
                        "HT102", "warn",
                        f"{node.op_type} {node.name} mixes operand "
                        f"dtype kinds {sorted(str(d) for d in known)} — "
                        f"the traced program will promote silently",
                        node=node)
            dtypes[node] = _result_dtype(node, in_dts)
    finally:
        for node, old in saved.values():
            if old is _MISSING:
                if hasattr(node, "inferred_shape"):
                    del node.inferred_shape
            else:
                node.inferred_shape = old
    if unknown:
        report.add(
            "HT100", "info",
            f"{unknown} feed placeholder(s) have no static shape; pass "
            f"feed_shapes= to check the full graph")
    return shapes


def lint_pass(topo, report, eval_nodes=None, extra_roots=()):
    """Dead-subgraph / unused-variable / duplicate-param lint."""
    from ..graph.autodiff import find_topo_sort
    from ..ops.variable import PlaceholderOp
    from ..optimizer import OptimizerOp

    # HT112: duplicate trainable names (Executor.save would collide)
    seen = {}
    for n in topo:
        if isinstance(n, PlaceholderOp) and n.trainable:
            if n.name in seen:
                report.add(
                    "HT112", "warn",
                    f"two trainable parameters share the name "
                    f"{n.name!r} (node ids {seen[n.name].id} and "
                    f"{n.id}) — Executor.save will refuse this graph",
                    node=n)
            else:
                seen[n.name] = n

    # HT111: trainable variable no optimizer updates (frozen by accident)
    opts = [n for n in topo if isinstance(n, OptimizerOp)]
    if opts:
        covered = set()
        for op in opts:
            covered.update(id(p) for p in (op.optimizer.params or ()))
        for n in topo:
            if isinstance(n, PlaceholderOp) and n.trainable \
                    and id(n) not in covered:
                report.add(
                    "HT111", "warn",
                    f"trainable variable {n.name!r} is consumed by the "
                    f"graph but updated by no optimizer — it trains as "
                    f"a frozen constant",
                    node=n)

    # HT110: subgraphs reachable only from extra construction roots
    if extra_roots:
        live = {id(n) for n in topo}
        dead = [n for n in find_topo_sort(list(extra_roots))
                if id(n) not in live]
        if dead:
            names = ", ".join(n.name for n in dead[:6])
            report.add(
                "HT110", "info",
                f"{len(dead)} node(s) are reachable from constructed "
                f"roots but not from the eval outputs (dead subgraph): "
                f"{names}{'...' if len(dead) > 6 else ''}",
                node=dead[0])


def frozen_graph_pass(topo, report):
    """Serving contract: an inference graph must be optimizer-,
    dataloader- and PS-push-free (the checks ``serving/session.py``
    enforced ad hoc, as structured findings)."""
    from ..dataloader import DataloaderOp, GNNDataLoaderOp
    from ..optimizer import OptimizerOp
    from ..ops.comm import ParameterServerCommunicateOp

    for n in topo:
        if isinstance(n, OptimizerOp):
            report.add(
                "HT150", "error",
                "InferenceSession over a training graph: eval nodes "
                "reach an OptimizerOp — pass the model outputs only "
                "(no train_op)", node=n)
        elif isinstance(n, ParameterServerCommunicateOp):
            report.add(
                "HT151", "error",
                "InferenceSession graph contains a PS push op "
                "(ParameterServerCommunicate) — serving sessions "
                "never push gradients", node=n)
        elif isinstance(n, (DataloaderOp, GNNDataLoaderOp)):
            report.add(
                "HT152", "error",
                "InferenceSession graphs are feed-driven; replace "
                "dataloader ops with placeholder feeds", node=n)
