"""Dynamic twin of the HT6xx static pass: instrumented-lock harness.

The static verifier (``analysis/concurrency.py``) proves properties of
the locks it can *see*; this harness measures the locks that actually
run. Inside a ``racecheck()`` region, ``threading.Lock`` / ``RLock`` /
``Condition`` construct instrumented primitives that record, per lock:

* the **measured acquisition-order graph** — an edge A -> B each time a
  thread acquires B while holding A (instance-level, so two instances
  of the same creation site never fake a cycle);
* **held-while-blocking** time — how long a thread stalled acquiring
  another lock while already holding this one (the dynamic face of
  HT603);
* **contention** — acquisitions that could not take the fast path, with
  wait-time histograms, published through telemetry/metrics as
  ``lock_wait_ms`` / ``lock_hold_ms`` / ``lock_contended`` when a
  telemetry instance is passed.

On exit (or via :meth:`RaceCheck.assert_acyclic`) the observed graph is
checked for cycles: a cycle is a lock-order deadlock that merely hasn't
fired yet, reported with every lock's creation site. The stress tests
in ``tests/test_concurrency.py`` run the batcher, ingest engine,
autotune cache, and PS-client paths under this harness at >=8-thread
load; the pytest ``racecheck`` fixture (tests/conftest.py) dumps the
measured graph JSON beside the test for CI failure artifacts.

Scope: only locks *created* inside the region are instrumented — enter
the harness before constructing the object under test. Stdlib
internals that allocate raw ``_thread`` locks (Thread bookkeeping,
queue.SimpleQueue) are untouched; ``concurrent.futures.Future``
conditions are created through ``threading.Condition`` and so are
observed — which is exactly what the batcher/ingest tests need.
"""
from __future__ import annotations

import contextlib
import json
import threading
import time

__all__ = ["racecheck", "RaceCheck", "LockCycleError"]

_real_lock = threading.Lock
_real_rlock = threading.RLock
_real_condition = threading.Condition


class LockCycleError(AssertionError):
    """The measured acquisition-order graph has a cycle — a lock-order
    deadlock waiting for the right interleaving."""


def _creation_site():
    """file:line of the frame that called the lock factory, skipping
    this module and the threading machinery."""
    import sys
    f = sys._getframe(2)
    while f is not None:
        fname = f.f_code.co_filename
        if not fname.endswith(("racecheck.py", "threading.py",
                               "_base.py")):
            return f"{fname}:{f.f_lineno}"
        f = f.f_back
    return "<unknown>"


class _TracedLock:
    """Wrapper over a raw lock recording order edges, contention, and
    hold durations into the owning :class:`RaceCheck`. ``reentrant``
    gives RLock semantics (only the outermost acquire/release records,
    matching how lock *ordering* is defined)."""

    def __init__(self, harness, reentrant=False):
        self._h = harness
        self._reentrant = reentrant
        self._inner = _real_rlock() if reentrant else _real_lock()
        self.lid, self.site = harness._register(self)

    # -- lock protocol ---------------------------------------------------
    def acquire(self, blocking=True, timeout=-1):
        if self._reentrant and self._inner._is_owned():
            return self._inner.acquire(blocking, timeout)
        contended = False
        t0 = 0.0
        if not self._inner.acquire(False):
            if not blocking:
                return False
            contended = True
            t0 = time.perf_counter()
            if not self._inner.acquire(True, timeout):
                return False
        wait_ms = (time.perf_counter() - t0) * 1e3 if contended else 0.0
        self._h._note_acquire(self, contended, wait_ms)
        return True

    def release(self):
        if self._reentrant and self._inner._is_owned():
            # only the outermost release ends the "held" interval
            outermost = self._inner._recursion_count() == 1 \
                if hasattr(self._inner, "_recursion_count") else None
            if outermost is None:
                # pre-3.12: probe by releasing then checking ownership
                self._inner.release()
                if self._inner._is_owned():
                    return
                self._h._note_release(self)
                return
            if outermost:
                self._h._note_release(self)
            self._inner.release()
            return
        self._h._note_release(self)
        self._inner.release()

    def locked(self):
        return self._inner.locked()

    def _is_owned(self):
        """threading.Condition copies this at construction; without it
        the stdlib fallback probes with acquire(False), which SUCCEEDS
        on a reentrant lock the caller owns and makes cond.wait()
        raise 'cannot wait on un-acquired lock'."""
        if self._reentrant:
            return self._inner._is_owned()
        # plain lock: same locked-by-anyone approximation as stdlib
        return self._inner.locked()

    def _release_save(self):
        """Condition.wait() protocol: fully release (ALL recursion
        levels of an RLock) and return restore state. Without the
        passthrough, the stdlib fallback releases ONE level — a
        reentrantly-held traced RLock would stay held through wait()
        and deadlock every notifier, failing code that is correct
        under real locks."""
        self._h._note_release(self)
        if self._reentrant:
            return self._inner._release_save()
        self._inner.release()
        return None

    def _acquire_restore(self, state):
        if self._reentrant:
            self._inner._acquire_restore(state)
        else:
            self._inner.acquire()
        self._h._note_acquire(self, False, 0.0)

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def __repr__(self):
        return f"<TracedLock #{self.lid} {self.site}>"


class RaceCheck:
    """Recording sink + patcher; use through :func:`racecheck`."""

    def __init__(self, name="racecheck", telemetry=None):
        self.name = name
        self.telemetry = telemetry
        self._mu = _real_lock()         # leaf lock: never held while
        self._tls = threading.local()   # acquiring an instrumented one
        self._locks = {}                # lid -> stats dict
        self._edges = {}                # (lid_a, lid_b) -> count
        self._nextid = 0
        self._patched = False

    # -- recording -------------------------------------------------------
    def _register(self, lock):
        site = _creation_site()
        with self._mu:
            lid = self._nextid
            self._nextid += 1
            self._locks[lid] = {"site": site, "acquires": 0,
                                "contended": 0, "wait_ms_max": 0.0,
                                "wait_ms_sum": 0.0, "hold_ms_max": 0.0,
                                "hold_ms_sum": 0.0,
                                "held_blocking_ms": 0.0}
        return lid, site

    def _stack(self):
        st = getattr(self._tls, "stack", None)
        if st is None:
            st = self._tls.stack = []
        return st

    def _note_acquire(self, lock, contended, wait_ms):
        stack = self._stack()
        tel = self.telemetry
        with self._mu:
            rec = self._locks[lock.lid]
            rec["acquires"] += 1
            if contended:
                rec["contended"] += 1
                rec["wait_ms_sum"] += wait_ms
                rec["wait_ms_max"] = max(rec["wait_ms_max"], wait_ms)
            for held, _t in stack:
                if held.lid != lock.lid:
                    key = (held.lid, lock.lid)
                    self._edges[key] = self._edges.get(key, 0) + 1
                    if contended:
                        # the dynamic HT603: stalled on `lock` while
                        # holding `held`
                        self._locks[held.lid]["held_blocking_ms"] += \
                            wait_ms
        stack.append((lock, time.perf_counter()))
        if contended and tel is not None and tel.enabled:
            # contended acquires only: the fast path would flood the
            # wait histogram with zeros and bury the convoying lock
            self._tel_hook(lambda: (tel.observe("lock_wait_ms", wait_ms),
                                    tel.inc("lock_contended")))

    def _note_release(self, lock):
        stack = self._stack()
        hold_ms = None
        for i in range(len(stack) - 1, -1, -1):
            if stack[i][0] is lock:
                hold_ms = (time.perf_counter() - stack[i][1]) * 1e3
                del stack[i]
                break
        if hold_ms is None:
            return                      # released on a different thread
        with self._mu:
            rec = self._locks[lock.lid]
            rec["hold_ms_sum"] += hold_ms
            rec["hold_ms_max"] = max(rec["hold_ms_max"], hold_ms)
        tel = self.telemetry
        if tel is not None and tel.enabled:
            self._tel_hook(lambda: tel.observe("lock_hold_ms", hold_ms))

    def _tel_hook(self, fn):
        """Publish through telemetry without reentering ourselves: the
        registry's own (traced) lock would otherwise recurse
        acquire -> observe -> acquire and self-deadlock."""
        if getattr(self._tls, "in_hook", False):
            return
        self._tls.in_hook = True
        try:
            fn()
        finally:
            self._tls.in_hook = False

    # -- patching --------------------------------------------------------
    def _patch(self):
        harness = self

        def make_lock():
            return _TracedLock(harness, reentrant=False)

        def make_rlock():
            return _TracedLock(harness, reentrant=True)

        def make_condition(lock=None):
            return _real_condition(lock if lock is not None
                                   else make_rlock())

        threading.Lock = make_lock
        threading.RLock = make_rlock
        threading.Condition = make_condition
        self._patched = True

    def _unpatch(self):
        threading.Lock = _real_lock
        threading.RLock = _real_rlock
        threading.Condition = _real_condition
        self._patched = False

    # -- results ---------------------------------------------------------
    def result(self):
        """{locks: {lid: stats}, edges: [{from, to, site_from, site_to,
        count}]} — the measured lock graph artifact."""
        with self._mu:
            locks = {lid: dict(rec) for lid, rec in self._locks.items()}
            edges = [{"from": a, "to": b,
                      "site_from": locks[a]["site"],
                      "site_to": locks[b]["site"], "count": n}
                     for (a, b), n in sorted(self._edges.items())]
        return {"name": self.name, "locks": locks, "edges": edges}

    def to_json(self):
        return json.dumps(self.result(), indent=1, sort_keys=True)

    def find_cycle(self):
        """A list of lids forming a cycle in the measured acquisition
        graph, or None."""
        with self._mu:
            # snapshot under the lock: a daemon worker still inside the
            # patch window can _register mid-scan otherwise
            graph = {}
            for a, b in self._edges:
                graph.setdefault(a, set()).add(b)
            lids = list(self._locks)
        WHITE, GRAY, BLACK = 0, 1, 2
        color = {lid: WHITE for lid in lids}
        parent = {}

        def dfs(u):
            color[u] = GRAY
            for v in graph.get(u, ()):
                if color.get(v, WHITE) == GRAY:
                    cycle = [v, u]
                    w = u
                    while w != v:
                        w = parent[w]
                        cycle.append(w)
                    return list(reversed(cycle))
                if color.get(v, WHITE) == WHITE:
                    parent[v] = u
                    hit = dfs(v)
                    if hit:
                        return hit
            color[u] = BLACK
            return None

        for lid in list(graph):
            if color.get(lid, WHITE) == WHITE:
                hit = dfs(lid)
                if hit:
                    return hit
        return None

    def assert_acyclic(self):
        """Raise :class:`LockCycleError` when the *observed* lock graph
        has a cycle — the harness equivalent of a static HT602."""
        cycle = self.find_cycle()
        if cycle is None:
            return
        with self._mu:
            sites = {lid: self._locks[lid]["site"] for lid in cycle}
        names = " -> ".join(
            f"lock#{lid} ({sites[lid]})" for lid in cycle)
        raise LockCycleError(
            f"[{self.name}] measured lock acquisition graph has a "
            f"cycle: {names} — two threads taking these locks in "
            f"opposite orders will deadlock (dynamic HT602)")

    def contention(self):
        """{site: contended count} for quick assertions in tests."""
        with self._mu:
            out = {}
            for rec in self._locks.values():
                out[rec["site"]] = out.get(rec["site"], 0) \
                    + rec["contended"]
        return out


_active = None
_active_mu = _real_lock()


@contextlib.contextmanager
def racecheck(name="racecheck", telemetry=None, assert_acyclic=True):
    """Instrument every lock created in this region; on exit, verify
    the measured acquisition-order graph is acyclic (unless
    ``assert_acyclic=False`` — then call :meth:`RaceCheck.\
assert_acyclic` yourself after saving the artifact).

    ::

        with racecheck("batcher") as rc:
            b = MicroBatcher(fn)          # locks created here are traced
            hammer_from_many_threads(b)
            b.close()
        # exiting asserts acyclicity; rc.result() is the lock graph
    """
    global _active
    with _active_mu:
        if _active is not None:
            raise RuntimeError("racecheck() regions do not nest: the "
                               "lock patch is process-global")
        _active = rc = RaceCheck(name=name, telemetry=telemetry)
    rc._patch()
    try:
        yield rc
    finally:
        rc._unpatch()
        with _active_mu:
            _active = None
    if assert_acyclic:
        rc.assert_acyclic()
