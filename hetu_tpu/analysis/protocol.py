"""Pass 7b — PS consistency model checker (HT703-HT706) + CLI driver.

``wire.py`` proves the two sides of the PS plane *frame* requests the
same way; this module proves the protocol built on those frames keeps
its consistency promises. Abstract worker/server/cache state machines
mirror the real drivers — async pushes through the push pool
(``ps/runtime.py``), the BSP barrier, the bounded-staleness cache sync
(``ps_cache.cc`` / ``device_cache.py``), PR 7's speculative-pull
revalidation, the client's reconnect-and-retry loop
(``ps_client.cc call()``), and the drain-then-checkpoint save contract
— and a DFS with state hashing exhaustively explores every
interleaving over small scopes (2 workers x 2 servers x short
push/pull/barrier/sync programs; the same bounded-exhaustive philosophy
as ``deadlock.py``'s schedule replay, TLA+-style small-scope checking).
A consistency bug that would surface once a week under production load
is a counterexample trace here, before launch:

=====  =====  ==============================================================
HT703  error  BSP read misses a pre-barrier acknowledged push — the
              barrier did not establish the superstep frontier
HT704  error  bounded staleness violated: a sync leaves a row more than
              ``pull_bound`` versions behind, local pending updates
              exceed ``push_bound``, or a speculative pull is consumed
              without revalidating rows its own pushes dirtied
HT705  error  a retried mutating RPC double-applies: the handler
              accumulates but is not guarded by the (worker, seq) dedup
              (``check_and_record``) the retry loop relies on
HT706  error  a modeled server kill+restart loses an acknowledged
              update — the checkpoint/recovery contract does not cover
              every acked push
=====  =====  ==============================================================

The model is *parameterized by the extracted wire contract*: HT705
replays retries against exactly the handlers ``wire.parse_wire`` found
(dedup-guarded or not), so dropping ``check_and_record`` from a server
case flips the model red with that case's ``file:line``. HT706 is the
executable spec for ROADMAP item 2's failover work: the canonical
scenario passes because today's ``save()`` drains before
checkpointing and kills are modeled after a covering checkpoint;
``recovery_replays=True`` models the replay-acked-pushes recovery item
2 must implement to survive kills at arbitrary points.

CLI: ``python -m hetu_tpu.analysis.protocol [--json]`` — runs the wire
pass plus every canonical scenario, reports the explored-state count,
exits 1 on any unsuppressed finding. Suppression: ``# ht-ok: HT7xx
<reason>`` on the finding's anchor line.
"""
from __future__ import annotations

import os
import sys

from .findings import Report, suppressed
from . import wire as _wire

__all__ = ["Model", "explore", "canonical_scenarios", "check_protocol",
           "protocol_pass", "main"]

_PKG = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _anchor(relpath, pattern):
    """file:line of the first source line containing ``pattern`` — the
    real-code anchor a model-level finding points at."""
    path = os.path.join(_PKG, relpath)
    try:
        with open(path, encoding="utf-8") as f:
            for i, line in enumerate(f, 1):
                if pattern in line:
                    return path, i
    except OSError:
        pass
    return path, 1


# ---------------------------------------------------------------------------
# the model
# ---------------------------------------------------------------------------

class Model:
    """One small-scope scenario: per-worker instruction programs over a
    sharded table (row r on server ``r % nservers``), explored
    exhaustively.

    Instructions (tuples):

    * ``("push", row, ss)``   — async accumulate push (+1) tagged with
      its BSP superstep ``ss``; enqueued on the worker's in-flight
      queue, delivered by a separate scheduler action (the push pool).
    * ``("wait",)``           — ``client.wait``: enabled once the
      worker's queue drained.
    * ``("bar",)``            — the BSP barrier (server 0), releasing
      when all workers arrive.
    * ``("pull", row, ss)``   — synchronous read; under ``mode="bsp"``
      checks the superstep frontier (HT703).
    * ``("spec", row)``       — speculative SparsePull: snapshot the
      row now, consume later.
    * ``("use", row)``        — consume the speculative rows;
      ``revalidate`` models run_step's dirty re-pull (HT704).
    * ``("update", row)``     — cache-local gradient accumulate;
      flushes at ``push_bound`` when ``flush_on_bound`` (HT704).
    * ``("sync", row, bound)``— SyncEmbedding under ``bound``;
      ``sync_slack`` models a broken server bound check (HT704).
    * ``("save",)``           — drain-then-checkpoint (``save_drains``
      models skipping the drain).
    * ``("kill", server)``    — SIGKILL + restart from the last
      checkpoint; ``recovery_replays`` models item-2-style replay of
      acked pushes (HT706).

    State is a flat hashable tuple; ``explore`` DFS-walks every
    scheduler interleaving (worker steps x push deliveries x retry
    branches) with memoization.
    """

    def __init__(self, name, programs, *, nservers=2, rows=2,
                 mode="asp", retries=False, dedup=True,
                 unsafe_site=None, push_bound=2, flush_on_bound=True,
                 sync_slack=0, revalidate=True, save_drains=True,
                 recovery_replays=False):
        self.name = name
        self.programs = [tuple(p) for p in programs]
        self.nworkers = len(programs)
        self.nservers = nservers
        self.rows = rows
        self.mode = mode
        self.retries = retries
        self.dedup = dedup
        self.unsafe_site = unsafe_site       # (path, line) for HT705
        self.push_bound = push_bound
        self.flush_on_bound = flush_on_bound
        self.sync_slack = sync_slack
        self.revalidate = revalidate
        self.save_drains = save_drains
        self.recovery_replays = recovery_replays
        # static superstep frontier for HT703: tags expected visible to
        # a (row, ss) read = every push to that row on an earlier ss
        self._expected = {}
        for w, prog in enumerate(self.programs):
            for pc, ins in enumerate(prog):
                if ins[0] == "push":
                    self._expected.setdefault(
                        (ins[1],), []).append(((w, pc), ins[2]))

    def expected(self, row, ss):
        return {tag for tag, pss in self._expected.get((row,), ())
                if pss < ss}

    # -- state layout ---------------------------------------------------
    # workers: tuple of (pc, inflight tags, spec, pending, cver)
    # applied: tuple per row of ((tag, mult), ...)
    # snapshot: applied-like or None
    # barwait: frozenset of workers at the barrier
    def initial(self):
        w0 = (0, (), None, (0,) * self.rows, (0,) * self.rows)
        return ((w0,) * self.nworkers,
                ((),) * self.rows, None, frozenset())

    @staticmethod
    def _ver(row_applied):
        return sum(m for _t, m in row_applied)

    @staticmethod
    def _tags(row_applied):
        return {t for t, _m in row_applied}

    def _apply(self, applied, row, tag, mult):
        d = dict(applied[row])
        d[tag] = d.get(tag, 0) + mult
        new_row = tuple(sorted(d.items()))
        return applied[:row] + (new_row,) + applied[row + 1:], d[tag]

    # -- successors -----------------------------------------------------
    def successors(self, st, violate):
        """Yield (action label, next state); report invariant breaks
        through ``violate(code, message)``."""
        workers, applied, snapshot, barwait = st

        def set_w(w, ws):
            return workers[:w] + (ws,) + workers[w + 1:]

        # scheduler: deliver any element of any worker's in-flight
        # queue (the push pool runs 2 threads — submission order is
        # NOT delivery order, so the model must not assume FIFO)
        for w, (pc, inflight, spec, pend, cver) in enumerate(workers):
            for qi, tag in enumerate(inflight):
                mult = tag[2]
                row = self.programs[w][tag[1]][1]
                new_applied, _got = self._apply(applied, row,
                                                (tag[0], tag[1]), mult)
                ws = (pc, inflight[:qi] + inflight[qi + 1:], spec,
                      pend, cver)
                yield (f"deliver w{w}#{tag[1]}",
                       (set_w(w, ws), new_applied, snapshot, barwait))
                if self.retries and not self.dedup:
                    # the reconnect-and-retry loop re-sends the same
                    # (worker, seq) after a lost response; a dedup-
                    # guarded handler makes the retry a no-op (same
                    # state — pruned by the visited set), an unguarded
                    # one double-applies
                    violate(
                        "HT705",
                        f"[{self.name}] retried push w{w}#{tag[1]} "
                        f"applied twice: the handler accumulates but "
                        f"has no (worker, seq) dedup — a lost response "
                        f"turns into a double gradient apply")

        for w, (pc, inflight, spec, pend, cver) in enumerate(workers):
            prog = self.programs[w]
            if pc >= len(prog) or w in barwait:
                continue
            ins = prog[pc]
            kind = ins[0]
            label = f"w{w}:{kind}" + (f" r{ins[1]}" if len(ins) > 1
                                      and isinstance(ins[1], int) else "")

            if kind == "push":
                tag = (w, pc, 1)          # (worker, site, mult)
                ws = (pc + 1, inflight + (tag,), spec, pend, cver)
                yield label, (set_w(w, ws), applied, snapshot, barwait)

            elif kind == "wait":
                if inflight:
                    continue              # scheduler must deliver first
                ws = (pc + 1, inflight, spec, pend, cver)
                yield label, (set_w(w, ws), applied, snapshot, barwait)

            elif kind == "bar":
                if len(barwait | {w}) >= self.nworkers:
                    new_workers = tuple(
                        (p + 1, i, s, pe, cv) if (ww in barwait
                                                  or ww == w)
                        else (p, i, s, pe, cv)
                        for ww, (p, i, s, pe, cv) in enumerate(workers))
                    yield label, (new_workers, applied, snapshot,
                                  frozenset())
                else:
                    yield label, (workers, applied, snapshot,
                                  barwait | {w})

            elif kind == "pull":
                row, ss = ins[1], ins[2]
                if self.mode == "bsp":
                    missing = self.expected(row, ss) \
                        - self._tags(applied[row])
                    if missing:
                        names = ", ".join(
                            f"w{t[0]}#{t[1]}" for t in sorted(missing))
                        violate(
                            "HT703",
                            f"[{self.name}] BSP read of row {row} in "
                            f"superstep {ss} (w{w}) misses pre-barrier "
                            f"push(es) {names} — the program reads "
                            f"before the barrier established the "
                            f"superstep frontier")
                        continue
                ws = (pc + 1, inflight, spec, pend, cver)
                yield label, (set_w(w, ws), applied, snapshot, barwait)

            elif kind == "spec":
                row = ins[1]
                ws = (pc + 1, inflight,
                      (row, tuple(sorted(self._tags(applied[row])))),
                      pend, cver)
                yield label, (set_w(w, ws), applied, snapshot, barwait)

            elif kind == "use":
                row = ins[1]
                own = {(w, p) for p in range(pc)
                       if prog[p][0] == "push" and prog[p][1] == row}
                obs = set(spec[1]) if spec is not None else set()
                dirty = own - obs
                if self.revalidate and dirty:
                    if inflight:
                        continue          # _flush_pushes blocks first
                    obs = self._tags(applied[row])
                missing = own - obs
                if missing:
                    names = ", ".join(f"w{t[0]}#{t[1]}"
                                      for t in sorted(missing))
                    violate(
                        "HT704",
                        f"[{self.name}] speculative pull of row {row} "
                        f"consumed without revalidation: the fed rows "
                        f"miss this worker's own acked push(es) "
                        f"{names} — the overlapped pull must re-pull "
                        f"ids dirtied since issue")
                    continue
                ws = (pc + 1, inflight, None, pend, cver)
                yield label, (set_w(w, ws), applied, snapshot, barwait)

            elif kind == "update":
                row = ins[1]
                n = pend[row] + 1
                new_inflight = inflight
                if self.flush_on_bound and n >= self.push_bound:
                    new_inflight = inflight + ((w, pc, n),)
                    n = 0
                if n > self.push_bound:
                    violate(
                        "HT704",
                        f"[{self.name}] row {row} holds {n} local "
                        f"updates with push_bound={self.push_bound} — "
                        f"the cache never flushed at the bound, so "
                        f"other workers observe staleness past the "
                        f"contract")
                    continue
                new_pend = pend[:row] + (n,) + pend[row + 1:]
                ws = (pc + 1, new_inflight, spec, new_pend, cver)
                yield label, (set_w(w, ws), applied, snapshot, barwait)

            elif kind == "sync":
                row, bound = ins[1], ins[2]
                ver = self._ver(applied[row])
                if ver - cver[row] > bound + self.sync_slack:
                    new_cver = cver[:row] + (ver,) + cver[row + 1:]
                else:
                    new_cver = cver
                if ver - new_cver[row] > bound:
                    violate(
                        "HT704",
                        f"[{self.name}] SyncEmbedding(bound={bound}) "
                        f"left row {row} {ver - new_cver[row]} "
                        f"versions stale — the server's staleness "
                        f"comparison does not honour the bound")
                    continue
                ws = (pc + 1, inflight, spec, pend, new_cver)
                yield label, (set_w(w, ws), applied, snapshot, barwait)

            elif kind == "save":
                if self.save_drains and any(
                        ws2[1] for ws2 in workers):
                    continue              # drain() joins pushes first
                ws = (pc + 1, inflight, spec, pend, cver)
                yield label, (set_w(w, ws), applied, applied, barwait)

            elif kind == "kill":
                server = ins[1]
                restored = list(snapshot) if snapshot is not None \
                    else [()] * self.rows
                new_applied = tuple(
                    restored[r] if r % self.nservers == server
                    else applied[r] for r in range(self.rows))
                if self.recovery_replays:
                    # item-2 recovery: replay every acked push
                    for r in range(self.rows):
                        if r % self.nservers != server:
                            continue
                        merged = dict(new_applied[r])
                        for t, m in applied[r]:
                            merged.setdefault(t, m)
                        new_applied = new_applied[:r] + (
                            tuple(sorted(merged.items())),
                        ) + new_applied[r + 1:]
                lost = []
                for r in range(self.rows):
                    lost.extend(sorted(self._tags(applied[r])
                                       - self._tags(new_applied[r])))
                if lost:
                    names = ", ".join(f"w{t[0]}#{t[1]}"
                                      for t in sorted(set(lost)))
                    violate(
                        "HT706",
                        f"[{self.name}] server {server} kill+restart "
                        f"loses acknowledged push(es) {names}: the "
                        f"last checkpoint does not cover them and the "
                        f"modeled recovery replays nothing — a worker "
                        f"was told its update landed, and it is gone")
                    continue
                ws = (pc + 1, inflight, spec, pend, cver)
                yield label, (set_w(w, ws), new_applied, snapshot,
                              barwait)

            else:                         # pragma: no cover
                raise ValueError(f"unknown instruction {ins!r}")


def explore(model, max_states=200000):
    """DFS over every interleaving; returns (states_explored,
    violations, truncated) where violations is {code: (message,
    trace)} keeping the first counterexample per code and
    ``truncated`` flags a search stopped at ``max_states`` — an
    incomplete exploration must never read as "proved clean"."""
    seen = set()
    violations = {}
    stack = [(model.initial(), ())]

    while stack and len(seen) < max_states:
        st, path = stack.pop()
        if st in seen:
            continue
        seen.add(st)

        def violate(code, message, _path=path):
            if code not in violations:
                violations[code] = (message, _path)

        for label, nxt in model.successors(st, violate):
            if nxt not in seen:
                stack.append((nxt, path + (label,)))
    return len(seen), violations, bool(stack)


# ---------------------------------------------------------------------------
# canonical scenarios: the 2 workers x 2 servers scope the CLI holds
# the repo to
# ---------------------------------------------------------------------------

def _bsp_programs(reorder=False):
    """Two BSP supersteps per worker, two pushes per superstep (both
    table shards — so each step has multiple RPCs racing through the
    2-thread push pool, like a real multi-table step): push, drain,
    barrier, read the *other* worker's rows. ``reorder=True`` is the
    HT703 fixture — the superstep-1 read issued before the superstep-0
    barrier (the barrier-skipping program)."""
    progs = []
    for w in (0, 1):
        other = 1 - w
        clean = [("push", w, 0), ("push", other, 0), ("wait",),
                 ("bar",),
                 ("pull", other, 1), ("pull", w, 1),
                 ("push", w, 1), ("push", other, 1), ("wait",),
                 ("bar",),
                 ("pull", w, 2), ("pull", other, 2)]
        broken = [("push", w, 0), ("push", other, 0), ("wait",),
                  ("pull", other, 1), ("pull", w, 1),
                  ("bar",),
                  ("push", w, 1), ("push", other, 1), ("wait",),
                  ("bar",),
                  ("pull", w, 2), ("pull", other, 2)]
        progs.append(broken if (reorder and w == 0) else clean)
    return progs


def canonical_scenarios(spec=None, **overrides):
    """The scenario suite ``python -m hetu_tpu.analysis.protocol``
    explores; every one must come back clean on the unmodified repo.
    ``overrides`` (e.g. ``revalidate=False``) mutate every scenario —
    the injected-bug fixtures in tests drive them."""
    try:
        spec = spec or _wire.parse_wire()
    except OSError:
        spec = None
    unsafe = spec.retry_unsafe_ops() if spec is not None else []
    dedup = not unsafe
    unsafe_site = (unsafe[0].server_cases[0] if unsafe
                   and unsafe[0].server_cases else None)

    def mk(name, programs, **kw):
        kw.update(overrides)
        return Model(name, programs, **kw)

    return [
        # HT703: two-superstep BSP over the 2x2 scope, with retries on
        # so the barrier must also hold under duplicate delivery
        mk("bsp_2x2", _bsp_programs(), mode="bsp", retries=True,
           dedup=dedup, unsafe_site=unsafe_site),
        # HT705: concurrent accumulate pushes under the retry loop,
        # dedup taken from the parsed wire contract; ASP (no waits
        # between pushes), so up to 3 RPCs race per worker
        mk("retry_dedup",
           [[("push", 0, 0), ("push", 1, 0), ("push", 0, 0),
             ("wait",), ("pull", 0, 0)],
            [("push", 0, 0), ("push", 1, 0), ("push", 1, 0),
             ("wait",), ("pull", 1, 0)]],
           retries=True, dedup=dedup, unsafe_site=unsafe_site),
        # HT704: bounded-staleness sync racing ASP pushes on both shards
        mk("staleness_sync",
           [[("push", 0, 0), ("push", 0, 0), ("push", 1, 0),
             ("wait",), ("push", 0, 0), ("wait",)],
            [("sync", 0, 1), ("sync", 1, 1), ("sync", 0, 1),
             ("sync", 1, 0), ("sync", 0, 0)]]),
        # HT704: cache-local update accumulation against push_bound
        mk("staleness_push",
           [[("update", 0), ("update", 0), ("update", 0),
             ("wait",)],
            [("sync", 0, 2)]],
           push_bound=2),
        # HT704: PR 7 speculative pull with own pushes in flight on
        # both shards
        mk("spec_pull",
           [[("push", 0, 0), ("push", 1, 0), ("spec", 0),
             ("push", 0, 0), ("use", 0), ("spec", 1), ("push", 1, 0),
             ("use", 1), ("wait",)],
            [("push", 1, 0), ("push", 0, 0), ("wait",)]]),
        # HT706: drain-then-checkpoint save, then a kill of server 0 —
        # the acked pre-save pushes (both shards in flight) must
        # survive the restart. recovery_replays models the shipped
        # recovery: the client replays its acked (worker, seq) window
        # into the surviving replica on failover (ps_client.cc)
        mk("failover",
           [[("push", 0, 0), ("push", 1, 0), ("wait",), ("bar",),
             ("save",), ("kill", 0), ("pull", 0, 1), ("pull", 1, 1)],
            [("push", 0, 0), ("push", 1, 0), ("wait",), ("bar",)]],
           recovery_replays=True),
        # HT706: kill with NO covering checkpoint — before replicated
        # shards this scenario could only pass by checkpoint luck; now
        # acked pushes survive an arbitrary-point kill because the
        # replay window covers everything acked since the snapshot
        mk("failover_nosave",
           [[("push", 0, 0), ("push", 1, 0), ("wait",),
             ("kill", 0), ("pull", 0, 1), ("pull", 1, 1)],
            [("push", 0, 0), ("wait",)]],
           recovery_replays=True),
    ]


# real-code anchors for model-level findings (the invariant lives in
# the model; the contract it checks lives at these sites)
_ANCHORS = {
    "HT703": ("ps/runtime.py", "client.barrier()"),
    "HT704": ("ps/runtime.py", "def _settle_spec_pull"),
    "HT705": ("ps/native/ps_server.cc", "bool check_and_record"),
    "HT706": ("ps/runtime.py", "def save"),
}


def check_protocol(report, spec=None, scenarios=None, **overrides):
    """Run the model scenarios; returns stats
    ``{"states": int, "scenarios": int, "violations": int}``."""
    scenarios = scenarios if scenarios is not None \
        else canonical_scenarios(spec, **overrides)
    total = 0
    nviol = 0
    for model in scenarios:
        states, violations, truncated = explore(model)
        total += states
        if truncated:
            # an under-explored scenario must not pass as verified:
            # HT700 gates like every other finding (raise max_states
            # or shrink the scenario deliberately)
            nviol += 1
            report.add(
                "HT700", "warn",
                f"[{model.name}] state-space exploration truncated at "
                f"{states} states — coverage is incomplete, a "
                f"violation may hide in the unexplored region; raise "
                f"explore(max_states=) or shrink the scenario",
                scenario=model.name, states=states)
        for code, (message, trace) in sorted(violations.items()):
            site = None
            if code == "HT705" and model.unsafe_site:
                site = model.unsafe_site
            if site is None:
                site = _anchor(*_ANCHORS[code])
            path, line = site
            try:
                with open(path, encoding="utf-8") as f:
                    lines = f.read().splitlines()
            except OSError:
                lines = []
            if suppressed(lines, line, code, markers=("ht-ok",)):
                continue
            nviol += 1
            tail = "; ".join(trace[-8:])
            report.add(code, "error",
                       message + f" (counterexample: ...{tail})",
                       where=f"{os.path.relpath(path)}:{line}",
                       scenario=model.name, states=states)
    return {"states": total, "scenarios": len(scenarios),
            "violations": nviol}


def protocol_pass(report, native_dir=None, py_dir=None,
                  model_check=True):
    """Wire contract (HT701/HT702) plus, when ``model_check``, the
    consistency scenarios (HT703-HT706). Returns the stats dict."""
    spec = _wire.wire_pass(report, native_dir=native_dir,
                           py_dir=py_dir)
    stats = {"states": 0, "scenarios": 0, "violations": 0}
    if model_check:
        stats = check_protocol(report, spec=spec)
    return stats


def main(argv=None):
    import argparse
    import json as _json
    parser = argparse.ArgumentParser(
        prog="python -m hetu_tpu.analysis.protocol",
        description="PS distributed-protocol verifier: wire-contract "
                    "checking (HT701/HT702) + small-scope consistency "
                    "model checking (HT703-HT706)")
    parser.add_argument("--json", action="store_true",
                        help="machine-readable output")
    parser.add_argument("--no-model", action="store_true",
                        help="wire-contract checks only (skip the "
                             "state-space exploration)")
    args = parser.parse_args(argv)
    report = Report()
    stats = protocol_pass(report, model_check=not args.no_model)
    if args.json:
        doc = _json.loads(report.to_json())
        doc["model"] = stats
        print(_json.dumps(doc, indent=2))
    else:
        print(report.to_text())
        print(f"model checker: {stats['states']} states explored "
              f"across {stats['scenarios']} scenarios "
              f"({stats['violations']} violation(s))")
    # ANY unsuppressed finding gates (concurrency-lint precedent): a
    # warn here is silent protocol rot, not style
    return 1 if len(report) else 0


if __name__ == "__main__":
    sys.exit(main())
