"""Pass 5 — host-overlap advisory (HT5xx).

A PS-backed graph is feed-bound by construction: every step moves ids,
feeds and embedding rows over the host link (the BENCH_r04/r05
"feed-transfer-bound" caveat). The async ingest engine
(``hetu_tpu/ingest.py``) exists to hide exactly that — so a config that
is known feed-bound but runs with the engine off, or drives the session
through a plain per-step ``run()`` loop that never reaches the
engine, deserves a pointer at the fix before anyone reads a slow bench.

Codes
-----
HT501  PS-backed graph built with overlap_options ingest=False    info
HT502  PS-backed graph driven by a long plain run() loop          info
       (the ingest engine never engaged — use run_batches_stream)

Both are advisories (severity ``info``): they never fail
``validate="error"`` or ``heturun --preflight`` — a synchronous loop is
correct, just slow. See docs/performance.md, "Hiding the host".
"""
from __future__ import annotations

import logging

from .findings import Finding

__all__ = ["overlap_pass", "RunLoopAdvisor", "RUN_LOOP_ADVISORY_STEPS",
           "DOCS_POINTER"]

logger = logging.getLogger(__name__)

DOCS_POINTER = 'docs/performance.md § "Hiding the host"'

# plain run() steps on a PS-backed graph before the advisory fires —
# past any warmup/compile loop, clearly a training loop by then
RUN_LOOP_ADVISORY_STEPS = 32


def _ps_backed(topo):
    """True when the graph talks to a parameter server (sparse pulls,
    push/pull comm ops, or device-cached embedding tables) — the
    feed-bound family the ingest engine was built for."""
    from ..ops.comm import (ParameterServerCommunicateOp,
                            ParameterServerSparsePullOp)
    for node in topo:
        if isinstance(node, (ParameterServerCommunicateOp,
                             ParameterServerSparsePullOp)):
            return True
        if getattr(node, "device_cached", False):
            return True
    return False


def overlap_pass(topo, report, config=None):
    """Static half: the config itself is contradictory — a PS-backed
    (known feed-bound) graph built with the ingest engine switched off
    (``overlap_options={"ingest": False}``)."""
    overlap = getattr(config, "overlap", None)
    if overlap is None or overlap.ingest:
        return
    if not _ps_backed(topo):
        return
    report.add(
        "HT501", "info",
        "PS-backed graph with the async ingest engine disabled "
        "(overlap_options ingest=False): every pull and feed transfer "
        "will serialize with compute on a feed-bound config. Re-enable "
        f"ingest or see {DOCS_POINTER}.")


class RunLoopAdvisor:
    """Runtime half: a PS-backed session driven by a long plain
    ``run()`` loop never reaches the ingest engine — per-step pulls and
    feed transfers sit on the critical path even though the engine is
    nominally on. After :data:`RUN_LOOP_ADVISORY_STEPS` consecutive
    ``run()`` steps with no ``run_batches``/``run_batches_stream`` call,
    emit HT502 once (a log line, plus a finding into the session's
    analysis report when ``Executor(validate=...)`` keeps one).

    Cost when quiet: one integer increment per step.
    """

    def __init__(self, config):
        self.config = config
        self._consecutive = 0
        self._fired = False

    def on_run_step(self):
        if self._fired:
            return
        self._consecutive += 1
        if self._consecutive >= RUN_LOOP_ADVISORY_STEPS:
            self._fire()

    def on_stream(self):
        """A block/stream API engaged — the loop is not plain run()."""
        self._consecutive = 0

    def _fire(self):
        self._fired = True
        engine = "disabled (overlap_options ingest=False)" \
            if not self.config.overlap.ingest else "idle"
        f = Finding(
            "HT502", "info",
            f"PS-backed graph driven by {self._consecutive} consecutive "
            f"per-step run() calls — the async ingest engine is "
            f"{engine} and every SparsePull/feed transfer serializes "
            f"with compute. Batch the loop through "
            f"run_batches_stream(...) to overlap the host; see "
            f"{DOCS_POINTER}.")
        logger.warning("%s", f)
        report = getattr(self.config, "analysis_report", None)
        if report is not None:
            report.findings.append(f)
