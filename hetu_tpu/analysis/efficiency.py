"""Pass 7 — CostDB-priced static performance lint (HT9xx).

The verifier stack covers crash (HT3xx/HT6xx), wire/consistency
(HT7xx) and silently-wrong (HT8xx); this pass covers **slow**: the
inefficiency patterns the perf doctor keeps diagnosing *after* a fleet
burned a day — recompile storms, tile-padding waste, hot-path host
syncs, fragmented collectives, redundant reshards, dead compute,
untuned kernels — detected statically over the topo order + parallel
plan and **priced** through the measured CostDB
(``telemetry/costdb.py`` ``estimate_ms``/``estimate_info``/``curve``),
so every finding carries an ``estimated_ms_per_step`` a reviewer can
rank by instead of a vibe.

Codes (severity: ``warn`` when the priced cost clears the ms
threshold, ``info`` below it — an HT9xx finding is never an ``error``
and never blocks a launch; HT908 is always advisory)::

  HT901  recompile hazard: per-step-varying jit signature keys
         (unbucketed dynamic feed shapes reaching the executor's
         dispatch keys — the serving bucketing contract is the clean
         model; runtime half fires from SubExecutor._note_compile)
  HT902  TPU tiling/padding waste: matmul/conv/embedding hot-path
         dims misaligned to the per-dtype (sublane, lane) tile,
         priced as padded-FLOP fraction x op ms / padded HBM bytes
  HT903  host sync on the hot path: per-step device fetches beyond
         sampling cadence (scalar fetch lists; AST detection of
         .item()/device_get inside step loops — composing with
         jit_purity, which owns syncs inside *traced* bodies)
  HT904  fragmented collectives: optimizer-bound per-grad allreduces
         whose sizes sit in the CostDB latency regime while
         overlap_options.bucket_bytes is unset, priced as the
         latency-vs-bandwidth delta against bucketed emission
  HT905  redundant reshard/transfer: gather-then-resplit Dispatch
         chains (and, dynamically via perfcheck, per-step h2d of
         constant feeds), priced from the comm curves
  HT906  cost-weighted dead compute: the HT110 dead-subgraph lint
         with predicted ms attached
  HT907  untuned hot-path kernel: flash-attention call sites whose
         autotune cache has no entry for the key — the first step
         pays the whole sweep
  HT908  CostDB coverage gap (advisory): the plan's hot ops priced
         from guesses, not measurements

Every finding carries ``estimated_ms_per_step`` (CostDB-priced),
``estimated_pct`` (share of the predicted step), ``bucket`` (the perf
doctor bucket the claim charges — ``analysis/perfcheck.py`` holds each
priced claim against the *measured* bucket, HT910) and ``source``
(``measured``/``curve``/``cold_start``). ``# ht-ok: HT9xx <reason>``
on the construction line waives a finding (``findings.suppressed_at``).

CLI::

    python -m hetu_tpu.analysis.efficiency [models...] [--json]
        [--out efficiency_report.json] [--costdb PATH]
        [--scripts PATH...]     # HT903 AST lint over host step loops

runs every zoo model, prints findings sorted by predicted savings, and
exits 1 when any unsuppressed warn-or-error finding survives — the CI
``analysis`` job's efficiency gate.
"""
from __future__ import annotations

import ast
import json
import os

import numpy as np

from .findings import Report, suppressed, suppressed_at

__all__ = ["efficiency_pass", "predict", "EfficiencyResult", "op_costs",
           "recompile_pass", "check_host_sync_source", "check_zoo",
           "advise_recompiles", "sorted_by_savings", "DOCTOR_BUCKET",
           "DEFAULT_MS_THRESHOLD", "main"]

# warn-vs-info pricing threshold (ms/step); HETU_EFF_THRESHOLD_MS
# overrides per process
DEFAULT_MS_THRESHOLD = 0.05

# one-time costs (recompiles, autotune sweeps) amortize over this many
# steps for the per-step price when the caller knows no step count
_AMORTIZE_STEPS = 1000

# the perf-doctor bucket each code's claimed savings would come out of
# (telemetry/doctor.py BUCKETS) — perfcheck's soundness gate joins the
# static claim to the measured bucket through this map
DOCTOR_BUCKET = {"HT901": "jit", "HT902": "compute",
                 "HT903": "unaccounted", "HT904": "collective",
                 "HT905": "h2d_ingest", "HT906": "compute",
                 "HT907": "jit"}

# distinct compiled signatures a session may accumulate before HT901
# calls it churn (train + eval + a couple of block variants)
RECOMPILE_BUDGET = 4

# HT902 floors: below these, padding is real but not worth a finding
_FLOPS_FLOOR = 1e7              # 10 MFLOP/step on the op
_WASTE_FRAC_FLOOR = 0.3         # >=30% of the padded tile is padding
_EMBED_WASTE_FLOOR = 16 << 20   # >=16 MiB of padded table residency
_EMBED_WASTE_FRAC = 0.5
# assumed HBM sustained bandwidth for pricing padded gather traffic
# (GB/s; same conservative class as costdb._COLD_GBPS)
_HBM_GBPS = 100.0

# HT903: scalar fetches in the per-step eval list beyond this are
# host syncs the sampling cadence should own
_SCALAR_FETCH_BUDGET = 4

# HT907: dispatches one sweep candidate costs (1 warmup + 2 windows x
# 3 reps, pallas_attention._MEASURE_*)
_SWEEP_DISPATCHES = 7

# NOTE on pricing: unlike autoplan (fwd-only topo, x3 training
# factor), this pass prices the FULL step topo — gradient ops are
# their own nodes and price individually, so no factor applies.


def _db(costdb):
    if costdb is None:
        from ..telemetry.costdb import CostDB
        return CostDB()
    return costdb


def _threshold(ms_threshold):
    if ms_threshold is not None:
        return float(ms_threshold)
    env = os.environ.get("HETU_EFF_THRESHOLD_MS")
    return float(env) if env else DEFAULT_MS_THRESHOLD


def _suppressed_node(node, code):
    # a waiver anchors on the user construction line (defined_at) OR
    # the in-package line that composed the op (composed_at — the
    # models/ctr.py line for zoo-built graphs, whatever script called
    # the builder notwithstanding), and — because the fix for a
    # width/shape finding usually lives on the *parameter* line — on
    # either site of a trainable input too
    for n in (node, *(i for i in getattr(node, "inputs", ())
                      if getattr(i, "trainable", False))):
        for site in (getattr(n, "defined_at", None),
                     getattr(n, "composed_at", None)):
            if site and suppressed_at(site[0], site[1], code):
                return True
    return False


def _prod(shape):
    try:
        return int(np.prod([int(d) for d in shape])) if shape else 0
    except (TypeError, ValueError):
        return 0


def _itemsize(dt):
    try:
        return int(np.dtype(dt).itemsize) if dt is not None else 4
    except TypeError:
        return 4


def _nbytes(shape, dt=None):
    return _prod(shape) * _itemsize(dt)


def tile_for(dt):
    """(sublane, lane) tile for a dtype — the (8, 128)-per-dtype TPU
    layout unit the padding model prices against."""
    try:
        d = np.dtype(dt) if dt is not None else np.dtype(np.float32)
    except TypeError:
        d = np.dtype(np.float32)
    if d.itemsize == 2:
        return (16, 128)
    if d.itemsize == 1:
        return (32, 128)
    return (8, 128)


def _pad(d, m):
    d = max(1, int(d))
    return ((d + m - 1) // m) * m


def _flops(node, shapes):
    """Analytic per-op FLOPs: autoplan's model plus the attention
    family (4*B*H*S^2*D — QK^T and PV)."""
    if "Attention" in node.op_type:
        q = shapes.get(node.inputs[0]) if node.inputs else None
        if q and len(q) == 4:
            b, h, s, d = (int(x) for x in q)
            return 4.0 * b * h * s * s * d
    from ..parallel.autoplan import flops_of
    return flops_of(node, shapes)


_SKIP_COST_TYPES = ("OptimizerOp", "DataloaderOp", "GNNDataLoaderOp",
                    "DispatchOp", "PipelineSendOp", "PipelineReceiveOp")


def _is_compute(node):
    if node.op_type in _SKIP_COST_TYPES or "Communicate" in node.op_type \
            or "SparsePull" in node.op_type:
        return False
    from ..ops.variable import PlaceholderOp
    return not isinstance(node, PlaceholderOp)


def op_costs(topo, shapes, db):
    """({node: predicted ms}, {node: source}, total_ms) over the
    compute ops — measured CostDB entries preferred, FLOPs scaled
    against the measured anchors otherwise, the documented cold-start
    rate as the last resort (autoplan's calibration, applied to the
    full step graph so gradient ops price too)."""
    from ..telemetry import costdb as _costdb

    op_ms, sources = {}, {}
    measured = {}
    cal_fl = cal_ms = 0.0
    compute = [n for n in topo if _is_compute(n)]
    for node in compute:
        ent = db.get(node.op_type, shapes.get(node))
        if ent is not None:
            measured[node] = float(ent["ms"])
            fl = _flops(node, shapes)
            if fl > 0 and ent["ms"] > 0:
                cal_fl += fl
                cal_ms += float(ent["ms"])
    flops_per_ms = (cal_fl / cal_ms) if cal_ms > 0 else None
    for node in compute:
        if node in measured:
            op_ms[node] = measured[node]
            sources[node] = "measured"
            continue
        fl = _flops(node, shapes)
        if flops_per_ms:
            op_ms[node] = fl / flops_per_ms
            sources[node] = "flops_scaled"
        else:
            op_ms[node] = _costdb.cold_start_flops_ms(fl)
            sources[node] = "cold_start"
    return op_ms, sources, sum(op_ms.values())


class EfficiencyResult:
    """One graph's priced lint: the findings ``Report``, the per-node
    predicted ms map (graphboard's ``waste=`` overlay input), cost
    sources, and the predicted compute floor of a step."""

    __slots__ = ("report", "op_ms", "sources", "total_ms", "topo")

    def __init__(self, report, op_ms, sources, total_ms, topo):
        self.report = report
        self.op_ms = op_ms
        self.sources = sources
        self.total_ms = total_ms
        self.topo = topo

    @property
    def findings(self):
        return sorted_by_savings(self.report)

    def predicted_waste_ms(self):
        """Total priced ms/step across the findings — what the graph
        throws away per step if every finding is real. HT908 is
        excluded: its price is the ms *resting on guesses* (pricing
        uncertainty), not waste, and counting it would double-bill ops
        that also carry a real HT902/HT906 price."""
        return round(sum(f.data.get("estimated_ms_per_step", 0.0)
                         for f in self.report.findings
                         if f.code != "HT908"), 6)

    def to_dict(self):
        return {
            "total_predicted_ms": round(self.total_ms, 6),
            "predicted_waste_ms": self.predicted_waste_ms(),
            "findings": [f.to_dict() for f in self.findings],
        }


def sorted_by_savings(report):
    """Findings sorted by predicted savings, biggest first — the
    reading order of a priced report."""
    return sorted(report.findings,
                  key=lambda f: -float(
                      f.data.get("estimated_ms_per_step", 0.0)))


# ---------------------------------------------------------------------------
# the pass
# ---------------------------------------------------------------------------

def efficiency_pass(topo, report, shapes=None, dtypes=None, config=None,
                    costdb=None, eval_nodes=None, extra_roots=(),
                    shape_keys=None, steps=None, ms_threshold=None,
                    feed_shapes=None, op_ms_out=None, sources_out=None):
    """Run every HT90x check over a topo-sorted graph; returns the
    per-node predicted-ms map. ``shape_keys`` (observed dispatch
    signatures) enables HT901; ``extra_roots`` enables HT906;
    ``config`` (a HetuConfig) supplies the plan knobs HT904 reads.
    Findings land in ``report`` with ``estimated_ms_per_step`` /
    ``estimated_pct`` / ``bucket`` / ``source`` attached;
    ``op_ms_out``/``sources_out`` (dicts) receive the per-node pricing
    so callers never pay the cost sweep twice."""
    if shapes is None or dtypes is None:
        from .shapes import shape_pass
        dtypes = {} if dtypes is None else dtypes
        shapes = shape_pass(topo, Report(), feed_shapes=feed_shapes,
                            dtypes_out=dtypes)
    db = _db(costdb)
    threshold = _threshold(ms_threshold)
    op_ms, sources, total_ms = op_costs(topo, shapes, db)
    if op_ms_out is not None:
        op_ms_out.update({n: round(v, 6) for n, v in op_ms.items()})
    if sources_out is not None:
        sources_out.update(sources)

    def add(code, message, node, ms, source, extra_sev=None, **data):
        if node is not None and _suppressed_node(node, code):
            return None
        sev = extra_sev or ("warn" if ms >= threshold else "info")
        pct = round(ms / total_ms, 4) if total_ms > 0 else None
        return report.add(
            code, sev, message, node=node,
            estimated_ms_per_step=round(float(ms), 6),
            estimated_pct=pct, bucket=DOCTOR_BUCKET.get(code),
            source=source, **data)

    if shape_keys is not None:
        recompile_pass(shape_keys, report, costdb=db, steps=steps,
                       ms_threshold=threshold)
    _tiling_pass(topo, shapes, dtypes, op_ms, db, add)
    if eval_nodes is not None:
        _fetch_pass(topo, eval_nodes, shapes, db, add)
        _collective_pass(topo, eval_nodes, shapes, dtypes, config, db,
                         add)
        if extra_roots:
            _dead_compute_pass(topo, eval_nodes, extra_roots, db, add)
    _reshard_pass(topo, shapes, dtypes, db, add)
    _autotune_pass(topo, shapes, dtypes, db, steps, add)
    _coverage_pass(topo, shapes, op_ms, sources, db, add, threshold)
    return op_ms


def predict(eval_nodes, feed_shapes=None, config=None, costdb=None,
            extra_roots=(), shape_keys=None, steps=None,
            ms_threshold=None):
    """Priced lint over a graph in one call: shape-propagate, run
    :func:`efficiency_pass`, return an :class:`EfficiencyResult` —
    the CLI's, graphboard's and bench's entry point."""
    from .shapes import shape_pass
    from ..graph.autodiff import find_topo_sort

    topo = find_topo_sort(list(eval_nodes))
    dtypes = {}
    shapes = shape_pass(topo, Report(), feed_shapes=feed_shapes,
                        dtypes_out=dtypes)
    report = Report()
    sources = {}
    op_ms = efficiency_pass(
        topo, report, shapes=shapes, dtypes=dtypes, config=config,
        costdb=costdb, eval_nodes=eval_nodes, extra_roots=extra_roots,
        shape_keys=shape_keys, steps=steps, ms_threshold=ms_threshold,
        sources_out=sources)
    named = {n.name: round(v, 6) for n, v in op_ms.items()}
    return EfficiencyResult(report, named,
                            {n.name: s for n, s in sources.items()},
                            sum(op_ms.values()), topo)


# ---------------------------------------------------------------------------
# HT901 — recompile hazard
# ---------------------------------------------------------------------------

def _leaf_ints(key, out):
    if isinstance(key, (tuple, list)):
        for k in key:
            _leaf_ints(k, out)
    elif isinstance(key, (int, np.integer)):
        out.append(int(key))


def _bucketed(keys):
    """True when every dim that varies across the observed signatures
    only takes power-of-two values — the serving bucketing contract
    (serving/session.py): pow2 buckets bound distinct signatures by
    log2(range), which is the clean model for dynamic shapes."""
    flat = []
    for k in keys:
        ints = []
        _leaf_ints(k, ints)
        flat.append(tuple(ints))
    if len({len(f) for f in flat}) != 1:
        return False            # structurally different keys: not a
        # bucket ladder at all (e.g. feeds appearing and vanishing)
    for pos in range(len(flat[0])):
        vals = {f[pos] for f in flat}
        if len(vals) <= 1:
            continue
        if not all(v > 0 and (v & (v - 1)) == 0 for v in vals):
            return False
    return True


def recompile_pass(shape_keys, report, costdb=None, steps=None,
                   node=None, budget=RECOMPILE_BUDGET,
                   ms_threshold=None):
    """HT901 over a set of observed jit dispatch signatures (the
    executor's ``SubExecutor.compiled`` keys, or any recorded shape
    history): more than ``budget`` distinct signatures whose varying
    dims do *not* follow the pow2 bucketing contract is a recompile
    storm — every new signature pays a full XLA compile. Priced from
    the CostDB's measured ``jit_compile`` entries (cold-start: the
    documented 200 ms floor), amortized over ``steps``."""
    keys = list(dict.fromkeys(tuple(k) if isinstance(k, list) else k
                              for k in shape_keys))
    n = len(keys)
    if n <= budget or _bucketed(keys):
        return None
    if node is not None and _suppressed_node(node, "HT901"):
        return None
    db = _db(costdb)
    threshold = _threshold(ms_threshold)
    compile_ms, source = db.estimate_info("jit_compile", 0)
    excess = n - budget
    total = excess * compile_ms
    horizon = max(1, int(steps)) if steps else _AMORTIZE_STEPS
    ms = total / horizon
    sev = "warn" if ms >= threshold else "info"
    return report.add(
        "HT901", sev,
        f"recompile hazard: {n} distinct jit signatures observed "
        f"(budget {budget}) and the varying dims are not pow2-bucketed "
        f"— every new feed shape pays a full XLA compile "
        f"(~{compile_ms:.0f} ms each, {source}). Bucket dynamic dims "
        f"like serving does (pad up to pow2, trim outputs) or pin the "
        f"feed shapes", node=node,
        estimated_ms_per_step=round(ms, 6),
        estimated_ms_total=round(total, 3),
        bucket=DOCTOR_BUCKET["HT901"], source=source,
        signatures=n)


def advise_recompiles(sub):
    """Runtime half, called once from ``SubExecutor._note_compile``
    when a session crosses the compiled-signature threshold: run
    :func:`recompile_pass` over the real dispatch keys, log the
    finding, and append it to the session's analysis report when
    ``Executor(validate=...)`` keeps one."""
    import logging
    report = Report()
    f = recompile_pass(sub.compiled.keys(), report,
                       steps=max(1, sub.step_count))
    if f is None:
        return None
    logging.getLogger(__name__).warning("%s", f)
    session_report = getattr(sub.config, "analysis_report", None)
    if session_report is not None:
        session_report.findings.append(f)
    tel = getattr(sub.config, "telemetry", None)
    if tel is not None and tel.enabled:
        tel.inc("recompile_hazard_advisories")
    return f


# ---------------------------------------------------------------------------
# HT902 — tiling/padding waste
# ---------------------------------------------------------------------------

def _lane_waste(k, n, dt, count_k=True):
    """Padded-issue fraction over the ARCHITECTURAL matmul dims: the
    contraction (K) and output-feature (N) lane dims. The sublane/M
    dim scales with batch — a bench harness artifact, not a model
    property — so it never fires the lint on its own; ``count_k=False``
    additionally excludes K for weight-gradient matmuls, whose
    contraction rides the batch dim too."""
    _, lane = tile_for(dt)
    true, padded = n, _pad(n, lane)
    if count_k:
        true *= k
        padded *= _pad(k, lane)
    return 1.0 - true / padded if padded else 0.0


def _matmul_mkn(node, ins, out):
    """Effective (M, K, N) honoring the transpose flags (a gradient
    matmul is trans_A/trans_B; reading raw operand dims would price
    the wrong contraction)."""
    m, n = int(out[-2]), int(out[-1])
    k = int(ins[0][-2] if getattr(node, "matmul_attr_trans_A", False)
            else ins[0][-1])
    return m, k, n


def _tiling_pass(topo, shapes, dtypes, op_ms, db, add):
    for node in topo:
        kind = node.op_type
        out = shapes.get(node)
        ins = [shapes.get(i) for i in node.inputs]
        dt = dtypes.get(node)
        if kind in ("MatMulOp", "BatchMatMulOp") and len(ins) >= 2 \
                and ins[0] and ins[1] and out and len(out) >= 2:
            m, k, n = _matmul_mkn(node, ins, out)
            fl = 2.0 * m * k * n
            # trans_A = a weight-gradient matmul: K is the batch dim
            waste = _lane_waste(
                k, n, dt,
                count_k=not getattr(node, "matmul_attr_trans_A", False))
            if fl >= _FLOPS_FLOOR and waste >= _WASTE_FRAC_FLOOR:
                ms = op_ms.get(node, 0.0) * waste
                sub, lane = tile_for(dt)
                add("HT902",
                    f"{kind} {node.name}: dims [{m}x{k}]x[{k}x{n}] pad "
                    f"to the ({sub},{lane}) tile with {waste:.0%} of "
                    f"the MXU issue wasted on padding — align the "
                    f"lane dims (K={k}, N={n}) to {lane} or waive "
                    f"with a measured justification", node, ms,
                    "measured" if db.get(kind, out) else "cold_start",
                    waste_frac=round(waste, 4))
        elif kind == "Conv2dOp" and len(ins) >= 2 and ins[1] \
                and len(ins[1]) == 4 and out:
            cout, cin, kh, kw = (int(x) for x in ins[1])
            m = _prod(out) // max(1, cout)      # N*H*W rows of im2col
            k = cin * kh * kw
            fl = _flops(node, shapes)
            waste = _lane_waste(k, cout, dt)
            if fl >= _FLOPS_FLOOR and waste >= _WASTE_FRAC_FLOOR:
                ms = op_ms.get(node, 0.0) * waste
                sub, lane = tile_for(dt)
                add("HT902",
                    f"Conv2d {node.name}: im2col [{m}x{k}]x[{k}x{cout}] "
                    f"pads to the ({sub},{lane}) tile with {waste:.0%} "
                    f"padding waste (cout={cout}, cin*kh*kw={k}) — "
                    f"align channel counts to {lane} lanes or waive "
                    f"with a measured justification", node, ms,
                    "measured" if db.get(kind, out) else "cold_start",
                    waste_frac=round(waste, 4))
        elif kind == "EmbeddingLookUp" and ins and ins[0] \
                and len(ins[0]) == 2:
            rows, width = int(ins[0][0]), int(ins[0][1])
            tdt = dtypes.get(node.inputs[0])
            isz = _itemsize(tdt)
            _, lane = tile_for(tdt)
            padw = _pad(width, lane)
            delta = rows * (padw - width) * isz
            frac = 1.0 - width / padw
            if delta >= _EMBED_WASTE_FLOOR and frac >= _EMBED_WASTE_FRAC:
                nlook = _prod(ins[1]) if len(ins) > 1 and ins[1] else 1
                waste_bytes = nlook * (padw - width) * isz
                ms = waste_bytes / (_HBM_GBPS * 1e6)
                add("HT902",
                    f"EmbeddingLookUp {node.name}: table rows are "
                    f"{width} wide but store {padw}-lane tiles — "
                    f"{frac:.0%} of {delta / (1 << 20):.0f} MiB of HBM "
                    f"residency (and every gathered row's traffic) is "
                    f"padding. Widen to a multiple of {lane}, pack "
                    f"rows, or waive with a measured justification",
                    node, ms, "cold_start",
                    waste_frac=round(frac, 4), padded_mib=round(
                        delta / (1 << 20), 1))


# ---------------------------------------------------------------------------
# HT903 — host sync on the hot path
# ---------------------------------------------------------------------------

def _fetch_pass(topo, eval_nodes, shapes, db, add):
    """Graph half: a per-step fetch list carrying many scalar outputs
    is a per-step host sync per scalar — the sentinel/health pattern
    (one fused aux pytree, fetched at cadence) is the clean model."""
    from ..optimizer import OptimizerOp

    scalars = [n for n in eval_nodes
               if not isinstance(n, OptimizerOp)
               and shapes.get(n) is not None
               and _prod(shapes.get(n)) <= 1]
    extra = len(scalars) - _SCALAR_FETCH_BUDGET
    if extra <= 0:
        return
    per, source = db.estimate_info("d2h", 8)
    ms = extra * per
    add("HT903",
        f"{len(scalars)} scalar outputs in the per-step fetch list — "
        f"each is a device round-trip every step (budget "
        f"{_SCALAR_FETCH_BUDGET}). Fuse them into one aux fetch (the "
        f"health-sentinel pattern) or sample at cadence",
        scalars[_SCALAR_FETCH_BUDGET], ms, source,
        scalar_fetches=len(scalars))


class _LoopWalker(ast.NodeVisitor):
    """Find host step loops (For/While whose body calls .run/.predict/
    run_step) and the device syncs inside them. ``.item()`` /
    ``.block_until_ready()`` / ``device_get`` always sync;
    ``np.asarray``/``np.array`` only count when applied to (a subscript
    of) a name assigned from the run call — host-side feed construction
    with the same spelling is not a device round-trip."""

    _RUN_NAMES = {"run", "run_step", "run_batches",
                  "run_batches_stream", "predict"}
    _SYNC_ATTRS = {"item", "block_until_ready"}
    _SYNC_ALWAYS = {"device_get"}
    _SYNC_ON_RESULT = {"asarray", "array"}

    def __init__(self):
        self.loops = []         # (loop node, [sync nodes])

    @staticmethod
    def _is_run_call(node):
        return (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in _LoopWalker._RUN_NAMES)

    def _visit_loop(self, node):
        runs = False
        results = set()         # names bound to a run call's result
        for sub in ast.walk(node):
            if self._is_run_call(sub):
                runs = True
            elif isinstance(sub, ast.Assign) and \
                    self._is_run_call(sub.value):
                results.update(t.id for t in sub.targets
                               if isinstance(t, ast.Name))

        def on_result(arg):
            while isinstance(arg, ast.Subscript):
                arg = arg.value
            return isinstance(arg, ast.Name) and arg.id in results

        syncs = []
        for sub in ast.walk(node):
            if not isinstance(sub, ast.Call):
                continue
            fn = sub.func
            name = fn.attr if isinstance(fn, ast.Attribute) else (
                fn.id if isinstance(fn, ast.Name) else None)
            if name in self._SYNC_ATTRS or name in self._SYNC_ALWAYS:
                syncs.append(sub)
            elif name in self._SYNC_ON_RESULT and sub.args \
                    and on_result(sub.args[0]):
                syncs.append(sub)
        if runs and syncs:
            self.loops.append((node, syncs))
        self.generic_visit(node)

    visit_For = _visit_loop
    visit_While = _visit_loop


def _cadence_guarded(tree, sync):
    """True when ``sync`` sits under an ``if ... % n`` guard — sampled
    at cadence, the clean pattern."""
    guarded = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.If) and any(
                isinstance(b, ast.BinOp) and isinstance(b.op, ast.Mod)
                for b in ast.walk(node.test)):
            for sub in ast.walk(node):
                guarded.add(id(sub))
    return id(sync) in guarded


def check_host_sync_source(src, path="<string>", costdb=None,
                           ms_threshold=None):
    """HT903 AST half over a host training script: ``.item()`` /
    ``device_get`` / ``np.asarray`` / ``block_until_ready`` inside a
    step loop (a For/While that drives ``run()``/``predict()``),
    unless cadence-guarded (``if step % n``). Composes with
    ``jit_purity`` — that lint owns syncs inside *traced* bodies, this
    one owns the host loop around them. Returns a Report."""
    report = Report()
    try:
        tree = ast.parse(src, filename=path)
    except SyntaxError as e:
        report.add("HT900", "warn", f"unparseable script: {e}",
                   where=path)
        return report
    db = _db(costdb)
    threshold = _threshold(ms_threshold)
    per, source = db.estimate_info("d2h", 8)
    lines = src.splitlines()
    walker = _LoopWalker()
    walker.visit(tree)
    for loop, syncs in walker.loops:
        for sync in syncs:
            if _cadence_guarded(loop, sync):
                continue
            if suppressed(lines, sync.lineno, "HT903"):
                continue
            fn = sync.func
            name = fn.attr if isinstance(fn, ast.Attribute) else fn.id
            sev = "warn" if per >= threshold else "info"
            report.add(
                "HT903", sev,
                f"{name}() inside the step loop at line {loop.lineno} "
                f"forces a device sync every step (~{per:.3f} ms, "
                f"{source}) — guard it with a cadence (if step % n) "
                f"or fuse the value into the step's aux fetch",
                where=f"{path}:{sync.lineno}",
                estimated_ms_per_step=round(per, 6),
                bucket=DOCTOR_BUCKET["HT903"], source=source)
    return report


# ---------------------------------------------------------------------------
# HT904 — fragmented collectives
# ---------------------------------------------------------------------------

def _collective_pass(topo, eval_nodes, shapes, dtypes, config, db, add):
    from ..optimizer import OptimizerOp
    from ..ops.comm import optimizer_allreduce_ops
    from ..telemetry.costdb import (latency_crossover_bytes,
                                    recommend_bucket_bytes)

    overlap = getattr(config, "overlap", None) if config is not None \
        else None
    if overlap is not None and overlap.bucket_bytes:
        return                  # bucketing on: the pattern is handled
    optimizer_ops = [n for n in topo if isinstance(n, OptimizerOp)]
    if not optimizer_ops:
        return
    ars = optimizer_allreduce_ops(topo, optimizer_ops, eval_nodes)
    if len(ars) < 2:
        return
    sizes = []
    for op in sorted(ars, key=lambda n: n.id):
        shape = shapes.get(op) or shapes.get(op.inputs[0])
        sizes.append((op, _nbytes(shape, dtypes.get(op))))
    crossover = latency_crossover_bytes(db, "allreduce")
    frag = [(op, s) for op, s in sizes if 0 < s < crossover]
    if len(frag) < 2:
        return
    per_grad = 0.0
    source = "cold_start"
    for _, s in sizes:
        ms, src = db.estimate_info("allreduce", s)
        per_grad += ms
        if src in ("measured", "curve"):
            source = src
    bucket_bytes = recommend_bucket_bytes(db)
    # greedy size-targeted packing, the settle_deferred_allreduce shape
    buckets, cur = [], 0
    for _, s in sizes:
        if cur and cur + s > bucket_bytes:
            buckets.append(cur)
            cur = 0
        cur += s
    if cur:
        buckets.append(cur)
    bucketed = sum(db.estimate_info("allreduce", b)[0] for b in buckets)
    delta = per_grad - bucketed
    if delta <= 0:
        return
    add("HT904",
        f"{len(sizes)} per-gradient allreduces ({len(frag)} below the "
        f"{crossover / 1e6:.2f} MB latency crossover) with "
        f"overlap_options.bucket_bytes unset — {len(sizes)} latency "
        f"payments per step where {len(buckets)} would do. Set "
        f"bucket_bytes={bucket_bytes} (CostDB-derived; "
        f"autoplan applies it to dp plans automatically)",
        frag[0][0], delta, source,
        collectives=len(sizes), buckets=len(buckets),
        recommended_bucket_bytes=bucket_bytes)


# ---------------------------------------------------------------------------
# HT905 — redundant reshard / transfer
# ---------------------------------------------------------------------------

def _reshard_pass(topo, shapes, dtypes, db, add):
    from ..ops.comm import DispatchOp

    def is_gather(n):
        return isinstance(n, DispatchOp) and all(
            p <= 1 for p in n.parts)

    def is_split(n):
        return isinstance(n, DispatchOp) and any(
            p > 1 for p in n.parts)

    consumers = {}
    for op in topo:
        for inp in op.inputs:
            consumers.setdefault(id(inp), []).append(op)

    for node in topo:
        if not (is_split(node) and node.inputs):
            continue
        g = node.inputs[0]
        if not (is_gather(g) and g.inputs):
            continue
        s = g.inputs[0]
        if not (is_split(s) and s.parts == node.parts):
            continue
        if len(consumers.get(id(g), ())) > 1:
            continue            # the gathered value is used elsewhere
        shape = shapes.get(s) or shapes.get(g)
        nb = _nbytes(shape, dtypes.get(s))
        # gather-then-identical-resplit: the bytes ride the links twice
        # for a no-op — price both hops off the collective curve
        ms, source = db.estimate_info("allreduce", nb)
        add("HT905",
            f"gather-then-resplit Dispatch chain {s.name} -> {g.name} "
            f"-> {node.name} re-creates the same {tuple(node.parts)} "
            f"partition it gathered — "
            f"{nb / 1e6:.2f} MB resharded round-trip per step for a "
            f"no-op; drop the pair and keep the split output",
            node, 2 * ms, source, bytes=nb)


# ---------------------------------------------------------------------------
# HT906 — cost-weighted dead compute
# ---------------------------------------------------------------------------

def _dead_compute_pass(topo, eval_nodes, extra_roots, db, add):
    from ..graph.autodiff import find_topo_sort
    from .shapes import shape_pass

    live = {id(n) for n in topo}
    dead_topo = [n for n in find_topo_sort(list(extra_roots))
                 if id(n) not in live]
    dead = [n for n in dead_topo if _is_compute(n)]
    if not dead:
        return
    dshapes = shape_pass(dead_topo, Report())
    dms, _src, _tot = op_costs(dead_topo, dshapes, db)
    ms = sum(dms.get(n, 0.0) for n in dead)
    names = ", ".join(n.name for n in dead[:5])
    add("HT906",
        f"{len(dead)} dead compute op(s) reachable from constructed "
        f"roots but not from the eval outputs ({names}"
        f"{'...' if len(dead) > 5 else ''}) — if a step function "
        f"evaluates them they burn ~{ms:.4f} ms/step for nothing; "
        f"delete the subgraph or fetch its outputs",
        dead[0], ms, "cold_start", dead_ops=len(dead))


# ---------------------------------------------------------------------------
# HT907 — untuned hot-path kernel
# ---------------------------------------------------------------------------

def _autotune_pass(topo, shapes, dtypes, db, steps, add):
    from ..ops.attention import FlashAttentionOp
    from ..tune.autotune import AutotuneTable, tuning_mode

    mode = tuning_mode()
    if mode in ("off", "cache"):
        return                  # no sweep will ever run at dispatch
    table = None
    for node in topo:
        if not isinstance(node, FlashAttentionOp):
            continue            # grad ops share the forward's key
        q = shapes.get(node.inputs[0]) if node.inputs else None
        if not q or len(q) != 4:
            continue
        b, h, s, d = (int(x) for x in q)
        from ..ops.pallas_attention import _candidates, tune_key
        cands = [(bq, bk) for bq in _candidates(s)
                 for bk in _candidates(s)]
        if len(cands) < 2:
            continue            # nothing to sweep (short sequences)
        dt = dtypes.get(node.inputs[0]) or np.dtype(np.float32)
        causal = bool(getattr(node, "causal", False))
        has_mask = bool(getattr(node, "has_mask", False))
        missing = []
        if table is None:
            table = AutotuneTable()
        for kind in ("fwd", "fwd_lse", "bwd"):
            name, key = tune_key(kind, s, d, np.dtype(dt), causal,
                                 has_mask)
            if table.get(name, key) is None:
                missing.append(kind)
        if not missing:
            continue
        ent = db.get(node.op_type, q)
        if ent is not None:
            op_ms, source = float(ent["ms"]), "measured"
        else:
            from ..telemetry.costdb import cold_start_flops_ms
            op_ms = cold_start_flops_ms(_flops(node, shapes))
            source = "cold_start"
        sweep_ms = len(cands) * _SWEEP_DISPATCHES * op_ms * len(missing)
        horizon = max(1, int(steps)) if steps else _AMORTIZE_STEPS
        add("HT907",
            f"flash-attention S={s} D={d} has no autotune cache entry "
            f"for {missing} — the first step pays a "
            f"{len(cands)}-candidate sweep (~{sweep_ms:.1f} ms, "
            f"{source}-priced). Warm the cache (HETU_AUTOTUNE=1 after "
            f"one tuning run) so measured steps never sweep",
            node, sweep_ms / horizon, source,
            estimated_ms_first_step=round(sweep_ms, 3),
            sweep_candidates=len(cands))


# ---------------------------------------------------------------------------
# HT908 — CostDB coverage gap (advisory)
# ---------------------------------------------------------------------------

_COVERAGE_TOP = 5


def _coverage_pass(topo, shapes, op_ms, sources, db, add, threshold):
    if db is None or len(db) == 0:
        # a fully cold DB guesses everything; the doctor's global
        # "run costdb --sweep" hint owns that case — an advisory per
        # graph would be noise
        return
    guessed = [(n, m) for n, m in op_ms.items()
               if sources.get(n) != "measured" and m >= threshold]
    if not guessed:
        return
    guessed.sort(key=lambda nm: -nm[1])
    top = guessed[:_COVERAGE_TOP]
    at_stake = sum(m for _, m in guessed)
    keys = ", ".join(f"({n.op_type}, "
                     f"{'x'.join(str(d) for d in (shapes.get(n) or ()))})"
                     for n, _ in top)
    add("HT908",
        f"{len(guessed)} hot op(s) priced from guesses, not "
        f"measurements ({keys}"
        f"{'...' if len(guessed) > _COVERAGE_TOP else ''}) — "
        f"~{at_stake:.3f} ms/step of this report rests on the "
        f"cold-start model. profile_ops(costdb=...) one real run to "
        f"replace them", top[0][0], at_stake, "cold_start",
        extra_sev="info", guessed_ops=len(guessed))


# ---------------------------------------------------------------------------
# CLI: zoo sweep gating on unsuppressed warn/error findings
# ---------------------------------------------------------------------------

def check_zoo(names=None, costdb=None, ms_threshold=None):
    """{model: EfficiencyResult} over the zoo graphs."""
    from . import zoo

    out = {}
    for name in names or sorted(zoo.ZOO):
        eval_nodes, feed_shapes = zoo.build(name)
        out[name] = predict(eval_nodes, feed_shapes=feed_shapes,
                            costdb=costdb, ms_threshold=ms_threshold)
    return out


def main(argv=None):
    import argparse
    import sys

    parser = argparse.ArgumentParser(
        prog="python -m hetu_tpu.analysis.efficiency",
        description="CostDB-priced static performance lint (HT9xx) "
                    "over the zoo graphs; exits 1 on any unsuppressed "
                    "warn-or-error finding")
    parser.add_argument("models", nargs="*",
                        help="zoo model names (default: all)")
    parser.add_argument("--json", action="store_true")
    parser.add_argument("--out", default=None, metavar="FILE",
                        help="write the priced report JSON here (the "
                             "CI artifact)")
    parser.add_argument("--costdb", default=None, metavar="PATH",
                        help="cost DB (default: $HETU_COSTDB or the "
                             "standard path; cold-start pricing when "
                             "absent)")
    parser.add_argument("--threshold-ms", type=float, default=None,
                        help=f"warn-vs-info pricing threshold "
                             f"(default {DEFAULT_MS_THRESHOLD} or "
                             f"$HETU_EFF_THRESHOLD_MS)")
    parser.add_argument("--scripts", nargs="*", default=(),
                        metavar="PATH",
                        help="also run the HT903 host-sync AST lint "
                             "over these training scripts")
    args = parser.parse_args(argv)

    from . import zoo
    names = args.models or sorted(zoo.ZOO)
    unknown = [n for n in names if n not in zoo.ZOO]
    if unknown:
        parser.error(f"unknown zoo model(s) {unknown}")

    db = None
    if args.costdb:
        from ..telemetry.costdb import CostDB
        db = CostDB(args.costdb)
    results = check_zoo(names, costdb=db,
                        ms_threshold=args.threshold_ms)
    script_reports = {}
    for path in args.scripts:
        with open(path, encoding="utf-8") as f:
            script_reports[path] = check_host_sync_source(
                f.read(), path=path, costdb=db,
                ms_threshold=args.threshold_ms)

    gate = 0
    doc = {}
    for name, res in results.items():
        gating = [f for f in res.report.findings
                  if f.severity in ("warn", "error")]
        doc[name] = res.to_dict()
        if gating:
            gate = 1
        if not args.json:
            status = "FAIL" if gating else "ok"
            print(f"== {name}: {status} ({len(res.report)} finding(s), "
                  f"predicted waste {res.predicted_waste_ms():.4f} "
                  f"ms/step of {res.total_ms:.4f})")
            for f in res.findings:
                print(f"   {f}  "
                      f"[{f.data.get('estimated_ms_per_step', 0):.4f} "
                      f"ms/step]")
    for path, rep in script_reports.items():
        gating = [f for f in rep.findings
                  if f.severity in ("warn", "error")]
        doc[path] = {"findings": [f.to_dict()
                                  for f in sorted_by_savings(rep)]}
        if gating:
            gate = 1
        if not args.json:
            print(f"== {path}: "
                  f"{'FAIL' if gating else 'ok'} "
                  f"({len(rep)} finding(s))")
            for f in sorted_by_savings(rep):
                print("   " + str(f))
    if args.json:
        print(json.dumps(doc, indent=2))
    if args.out:
        with open(args.out, "w") as f:
            json.dump(doc, f, indent=2)
        print(f"priced report written to {args.out}", file=sys.stderr)
    total = sum(len(r.report) for r in results.values()) + \
        sum(len(r) for r in script_reports.values())
    if not args.json:
        print(f"efficiency: {total} finding(s) across {len(names)} "
              f"zoo model(s)"
              + (f" + {len(script_reports)} script(s)"
                 if script_reports else ""))
    if gate:
        print("efficiency: FAILED — fix the inefficiency, or waive "
              "with '# ht-ok: HT9xx <reason>' on the construction "
              "line", file=sys.stderr)
    return gate


if __name__ == "__main__":
    import sys
    sys.exit(main())
