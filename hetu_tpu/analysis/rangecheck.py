"""Measured-range harness — the numerics verifier's dynamic twin.

The static pass (``analysis/numerics.py``) derives per-node value
intervals; this module measures them. A :class:`RangeRecorder`
attached to an executor makes the compiled step reduce every
float-valued node to ``(min, max)`` inside the trace (the same
fused-sentinel pattern PR 9's health monitor established — the
reductions run every step, the host fetch happens at the ``every_n``
cadence and costs one ``device_get`` of two scalars per node). The
twin relationship is enforced both ways:

* **soundness gate** — every measured per-op range must lie inside
  the static interval; an escape is an ``HT810`` error (the static
  model lied, which would silence every HT801/HT804 built on it), and
* **measured-range DB** — measured ranges persist in an
  autotune-style atomic-JSON :class:`RangeDB` keyed by
  ``numerics.stable_keys`` (topo position + op type, stable across
  rebuilds), and ``numerics_pass(measured=...)`` re-seeds from them,
  turning loose initializer bounds into tight measured ones on
  re-analysis.

CLI::

    python -m hetu_tpu.analysis.rangecheck [models...] [--db PATH]

drives a few training steps of the named zoo models (default: mlp +
wdl_adult — a dense and a sparse path) on synthetic feeds, validates
the soundness gate, and persists the DB. Exit 1 on any violation.
"""
from __future__ import annotations

import json
import os
import tempfile

import numpy as np

from .findings import Report
from .numerics import stable_keys

__all__ = ["RangeDB", "RangeRecorder", "measure_ranges",
           "soundness_pass", "rangecheck_model", "main"]

# measured values may touch the static bound exactly; compare with a
# hair of slack so float32 round-trips don't fabricate violations
_SLACK_ABS = 1e-6
_SLACK_REL = 1e-5


def default_db_path():
    p = os.environ.get("HETU_RANGEDB")
    if p:
        return p
    return os.path.join(os.path.expanduser("~"), ".cache", "hetu_tpu",
                        "ranges.json")


class RangeDB:
    """Persistent measured-range database (the autotune/CostDB atomic-
    JSON idiom): ``{model: {stable_key: {"lo", "hi", "n"}}}`` with
    running min/max merge across runs."""

    def __init__(self, path=None):
        self.path = path or default_db_path()
        self.data = {}
        self._load()

    def _load(self):
        try:
            with open(self.path) as f:
                raw = json.load(f)
            if isinstance(raw, dict):
                self.data = raw.get("models", {})
        except (OSError, ValueError):
            self.data = {}          # corrupt/absent: cold start

    def get(self, model):
        """{stable_key: (lo, hi)} for one model, or None."""
        ent = self.data.get(model)
        if not ent:
            return None
        return {k: (v["lo"], v["hi"]) for k, v in ent.items()
                if isinstance(v, dict) and "lo" in v and "hi" in v}

    def update(self, model, measured):
        """Merge ``{stable_key: (lo, hi)}`` with running min/max."""
        ent = self.data.setdefault(model, {})
        for key, (lo, hi) in measured.items():
            cur = ent.get(key)
            if cur is None:
                ent[key] = {"lo": float(lo), "hi": float(hi), "n": 1}
            else:
                cur["lo"] = min(cur["lo"], float(lo))
                cur["hi"] = max(cur["hi"], float(hi))
                cur["n"] = int(cur.get("n", 0)) + 1

    def save(self):
        d = os.path.dirname(os.path.abspath(self.path))
        os.makedirs(d, exist_ok=True)
        fd, tmp = tempfile.mkstemp(dir=d, suffix=".tmp")
        try:
            with os.fdopen(fd, "w") as f:
                json.dump({"version": 1, "models": self.data}, f,
                          indent=1)
            os.replace(tmp, self.path)
        except OSError:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise


class RangeRecorder:
    """Attach to an executor subgraph; fetch fused per-node ranges at
    cadence; accumulate the running measured min/max per node."""

    def __init__(self, executor, name="default", every_n=1):
        self.executor = executor
        self.name = name
        self.every_n = max(1, int(every_n))
        self.sub = executor.subexecutors[name]
        self.measured = {}          # node name -> [lo, hi]
        self.fetches = 0
        self._attached = False

    def attach(self):
        sub = self.sub
        sub._range_capture = True
        sub.compiled.clear()        # force a rebuild with the capture
        self._attached = True
        return self

    def detach(self):
        if self._attached:
            self.sub._range_capture = False
            self.sub.compiled.clear()
            self._attached = False

    def sample(self):
        """Fetch the last step's fused ranges (call after run(); the
        cadence check is one modulo, exactly the sentinel pattern)."""
        if self.sub.step_count % self.every_n:
            return
        h = getattr(self.sub, "_last_health", None)
        if not h or "ranges" not in h:
            return
        import jax
        host = jax.device_get(h["ranges"])
        self.fetches += 1
        tel = self.executor.config.telemetry
        if tel is not None and tel.enabled:
            tel.inc("rangecheck_fetches")
            tel.set_gauge("rangecheck_nodes", len(host))
        for name, (lo, hi) in host.items():
            # the block (lax.scan) path stacks the capture [nsteps]:
            # reduce over the scan axis
            lo, hi = float(np.min(lo)), float(np.max(hi))
            cur = self.measured.get(name)
            if cur is None:
                self.measured[name] = [lo, hi]
            else:
                cur[0] = min(cur[0], lo)
                cur[1] = max(cur[1], hi)

    def by_stable_key(self):
        """Measured ranges re-keyed by ``numerics.stable_keys`` (the
        DB key space; node names embed the process-global id counter
        and do not survive a rebuild)."""
        topo = self.sub.topo_order
        keys = stable_keys(topo)
        out = {}
        for node, key in zip(topo, keys):
            m = self.measured.get(node.name)
            if m is not None:
                out[key] = (m[0], m[1])
        return out


def measure_ranges(executor, feed_fn, steps=4, name="default",
                   every_n=1):
    """Drive ``steps`` ``run()`` calls feeding ``feed_fn(step)`` with a
    recorder attached; returns ``{stable_key: (lo, hi)}``."""
    rec = RangeRecorder(executor, name=name, every_n=every_n).attach()
    try:
        for i in range(steps):
            executor.run(name, feed_dict=feed_fn(i))
            rec.sample()
    finally:
        rec.detach()
    return rec.by_stable_key()


def soundness_pass(topo, static_ranges, measured, report=None):
    """Every measured range must lie inside its static interval —
    unknown static intervals are vacuous (reported in the summary, not
    as findings). Emits HT810 errors; returns (report, checked count).
    """
    if report is None:
        report = Report()
    keys = stable_keys(topo)
    by_key = {k: n for k, n in zip(keys, topo)}
    static_by_key = {k: static_ranges.get(n)
                     for k, n in zip(keys, topo)}
    import math
    checked = 0
    for key, m in measured.items():
        s = static_by_key.get(key)
        if s is None:
            continue
        checked += 1
        # per-endpoint slack from the FINITE endpoint being checked: a
        # half-bounded static interval (exp's [lo, inf)) must still
        # enforce its finite side, and a NaN measurement — the very
        # failure this verifier exists for — is always a violation
        viol = math.isnan(m[0]) or math.isnan(m[1])
        if not viol and math.isfinite(s[0]) \
                and m[0] < s[0] - (_SLACK_ABS + _SLACK_REL * abs(s[0])):
            viol = True
        if not viol and math.isfinite(s[1]) \
                and m[1] > s[1] + (_SLACK_ABS + _SLACK_REL * abs(s[1])):
            viol = True
        if viol:
            node = by_key.get(key)
            report.add(
                "HT810", "error",
                f"measured range [{m[0]:.4g}, {m[1]:.4g}] escapes the "
                f"static interval [{s[0]:.4g}, {s[1]:.4g}] for {key} — "
                f"the abstract interpretation is unsound here (fix the "
                f"transfer rule or the seed)", node=node)
    return report, checked


def _synth_feeds(feed_shapes, seed=0):
    """Deterministic synthetic feeds per (shape, dtype) spec: modest
    normals for floats, small non-negative ids for ints (always valid
    row indices for the zoo's tables)."""
    rng = np.random.RandomState(seed)
    feeds = {}
    for node, (shape, dt) in feed_shapes.items():
        dt = np.dtype(dt if dt is not None else np.float32)
        if dt.kind in "iu":
            feeds[node] = rng.randint(0, 8, size=shape).astype(dt)
        else:
            feeds[node] = (rng.standard_normal(shape) * 0.5).astype(dt)
    return feeds


def rangecheck_model(model, steps=4, every_n=1, db=None, seed=0):
    """Round-trip one zoo model: run ``steps`` training steps with the
    fused capture, soundness-check measured vs static, fold into the
    DB. Returns (report, measured, checked)."""
    from . import zoo
    from .numerics import numerics_pass
    from .shapes import shape_pass
    from ..executor import Executor
    from ..graph.autodiff import find_topo_sort

    eval_nodes, feed_shapes = zoo.build(model)
    from .shapes import _resolve_feed_shapes
    specs = _resolve_feed_shapes(feed_shapes, find_topo_sort(eval_nodes))

    exe = Executor(eval_nodes)
    measured = measure_ranges(
        exe, lambda i: _synth_feeds(specs, seed=seed + i), steps=steps,
        every_n=every_n)

    # the static side runs over the EXECUTOR's topo order (comm ops
    # spliced), so stable keys line up with the measured capture
    topo = exe.subexecutors["default"].topo_order
    dtypes = {}
    shapes = shape_pass(topo, Report(), feed_shapes=feed_shapes,
                        dtypes_out=dtypes)
    static = numerics_pass(topo, Report(), shapes=shapes, dtypes=dtypes)
    report, checked = soundness_pass(topo, static, measured)
    if db is not None:
        db.update(model, measured)
    return report, measured, checked


DEFAULT_MODELS = ("mlp", "wdl_adult")


def main(argv=None):
    import argparse
    import sys

    parser = argparse.ArgumentParser(
        prog="python -m hetu_tpu.analysis.rangecheck",
        description="measured-range harness: run zoo models with fused "
                    "per-op range capture, validate every measured "
                    "range against the static interval, persist the "
                    "range DB")
    parser.add_argument("models", nargs="*",
                        help=f"zoo models (default: "
                             f"{' '.join(DEFAULT_MODELS)})")
    parser.add_argument("--steps", type=int, default=4)
    parser.add_argument("--every-n", type=int, default=1)
    parser.add_argument("--db", default=None, metavar="PATH",
                        help="range DB path (default: $HETU_RANGEDB or "
                             "~/.cache/hetu_tpu/ranges.json)")
    parser.add_argument("--json", action="store_true")
    args = parser.parse_args(argv)

    models = args.models or list(DEFAULT_MODELS)
    db = RangeDB(args.db)
    rc = 0
    out = {}
    for model in models:
        report, measured, checked = rangecheck_model(
            model, steps=args.steps, every_n=args.every_n, db=db,
            seed=0)
        ok = not report.errors
        out[model] = {"measured": len(measured), "checked": checked,
                      "violations": len(report.errors)}
        if not args.json:
            print(f"== {model}: {'ok' if ok else 'UNSOUND'} "
                  f"({len(measured)} node(s) measured, {checked} "
                  f"checked against a static interval, "
                  f"{len(report.errors)} violation(s))")
            for f in report.errors:
                print("   " + str(f))
        if not ok:
            rc = 1
    db.save()
    if args.json:
        print(json.dumps({"db": db.path, "models": out}, indent=2))
    else:
        print(f"range DB written to {db.path}")
    return rc


if __name__ == "__main__":
    import sys
    sys.exit(main())
