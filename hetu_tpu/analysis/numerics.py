"""Pass 8 — numerics & precision verifier (HT8xx).

Abstract interpretation over the dataflow graph, in the spirit of the
FPTaylor/Herbie class of floating-point analyses but scoped to what a
define-then-run training graph can prove cheaply: every node carries

* a **value interval** ``(lo, hi)`` bounding every element of its
  output, seeded from initializer distributions
  (``initializers.BaseInit.interval``), constant feeds, and known op
  semantics (softmax/sigmoid/tanh in their ranges, exp/log/rsqrt
  monotone, norm outputs bounded by ``sqrt(n)·|scale| + |bias|``,
  attention outputs inside the value hull, matmul/conv/reductions by
  ``K·max|A|·max|B|``), propagated through the per-op ``infer_range``
  protocol (``ops/*.py``) with the shape-aware cases handled centrally
  here, and
* a **precision class** (fp32 / bf16 / fp16 / int) riding the HT1xx
  dtype propagation in ``analysis/shapes.py``.

Unknown feeds propagate as *unknown* (no claim, no false positive) —
the same philosophy as the shape pass — and the measured-range DB the
dynamic twin (``analysis/rangecheck.py``) persists tightens them on
re-analysis.

Error codes
-----------
HT801  overflow-prone op in low precision: the derived interval
       exceeds the dtype's max-representable (un-shifted exp / square
       in fp16 being the classic)                       error (lp) / warn
HT802  low-precision accumulation: a reduction/matmul/conv
       accumulating in bf16/fp16 over N elements whose worst-case
       error N·eps/2 exceeds the bound — remediation is
       ``preferred_element_type``/fp32 accumulation      warn
HT803  integer-exactness loss: float-dtype ids (exact only to
       2^mantissa — the trillion-row cliff), an id dtype narrower
       than the declared table, or an int-to-float cast past the
       target's exact range                             error / warn
HT804  div/log/sqrt/rsqrt whose operand interval contains zero with
       no eps/clip guard on the path (interval arithmetic IS the
       guard detector: ``x*x + eps`` excludes zero, raw softmax
       output does not); also norm eps <= 0 and optimizer eps <= 0   warn
HT805  low-precision cross-replica/pipeline boundary: bf16/fp16
       ppermute or allreduce edges whose derived error bound
       (hops · eps/2) exceeds the declared tolerance, or an fp16
       boundary whose halved exponent range was never retuned   error/warn
HT806  gradient-underflow risk: a backward path entirely in fp16
       with no loss scale (interval below fp16 min-normal upgrades
       the severity)                                    warn / error
HT807  PRNG stream reuse: one key consumed by two independent random
       ops (correlated dropout masks — silent wrongness)      error

Waivers: ``# ht-ok: HT8xx <reason>`` on the **user construction line**
a finding's provenance points at (``Op.defined_at``) — the same one
grep surface as every other pass.

CLI: ``python -m hetu_tpu.analysis.numerics [models...] [--json]``
sweeps the zoo and exits 1 on ANY unsuppressed finding (the CI
``analysis`` job's gate, via the ``--all`` aggregate driver).
"""
from __future__ import annotations

import math

import numpy as np

from .findings import suppressed_at

__all__ = ["numerics_pass", "seed_interval", "stable_keys",
           "boundary_error_bound", "accum_error_bound", "prec_class",
           "dtype_max", "dtype_eps", "dtype_tiny", "exact_int_limit",
           "TRAINING_DRIFT", "MEASURED_EXPAND", "ACC_TOL", "main"]

_INF = float("inf")

# trainable parameters drift during training: their initializer seed
# interval widens to ± max(TRAINING_DRIFT · init_absmax, 1.0) so a
# rangecheck run a few steps in still lands inside the static interval;
# the measured-range DB replaces the heuristic after a real run
TRAINING_DRIFT = 16.0

# a measured (lo, hi) from the range DB is re-seeded widened about its
# center by this factor — measured ranges are samples, not bounds
MEASURED_EXPAND = 4.0

# HT802 fires when the worst-case accumulation error N·eps/2 exceeds
# this relative bound
ACC_TOL = 0.05


# ---------------------------------------------------------------------------
# dtype tables
# ---------------------------------------------------------------------------

def _np_dtype(dt):
    if dt is None:
        return None                 # np.dtype(None) is float64 — no
    try:
        return np.dtype(dt)
    except TypeError:
        return None


def prec_class(dtype):
    """'fp64' | 'fp32' | 'bf16' | 'fp16' | 'int' | None."""
    dt = _np_dtype(dtype)
    if dt is None:
        return None
    name = dt.name
    return {"float64": "fp64", "float32": "fp32", "bfloat16": "bf16",
            "float16": "fp16"}.get(
        name, "int" if dt.kind in "iub" else None)


def _finfo(dtype):
    import jax.numpy as jnp
    return jnp.finfo(dtype)


def dtype_max(dtype):
    """Largest finite value of a float dtype (fp16's 65504 cliff)."""
    return float(_finfo(dtype).max)


def dtype_eps(dtype):
    """Machine epsilon (bf16: 2^-7 — 8 significand bits total)."""
    return float(_finfo(dtype).eps)


def dtype_tiny(dtype):
    """Smallest positive normal (fp16: 6.1e-5 — the underflow knee
    Micikevicius et al.'s loss scaling exists to clear)."""
    return float(_finfo(dtype).tiny)


def exact_int_limit(dtype):
    """Largest N with every integer in [0, N] exactly representable
    (float32: 2^24 — the id-through-float exactness cliff)."""
    return 2 ** (int(_finfo(dtype).nmant) + 1)


def accum_error_bound(dtype, n):
    """Worst-case relative error of summing ``n`` same-sign terms in
    ``dtype``: n·eps/2 (standard recursive-summation bound)."""
    return float(n) * dtype_eps(dtype) / 2.0


def boundary_error_bound(dtype, hops=1):
    """Relative error bound for a value crossing ``hops`` low-precision
    cast boundaries (each round-trip cast contributes eps/2) — the
    HT805 interval math the bf16 pipeline-boundary tolerance test pins
    against the runtime's declared rtol."""
    return float(max(1, hops)) * dtype_eps(dtype) / 2.0


# ---------------------------------------------------------------------------
# interval plumbing
# ---------------------------------------------------------------------------

def _absmax(rng):
    return max(abs(rng[0]), abs(rng[1]))


def _hull(*rngs):
    known = [r for r in rngs if r is not None]
    if len(known) != len(rngs):
        return None
    return (min(r[0] for r in known), max(r[1] for r in known))


def _intersect(a, b):
    if a is None:
        return b
    if b is None:
        return a
    lo, hi = max(a[0], b[0]), min(a[1], b[1])
    return (lo, hi) if lo <= hi else a   # disjoint: trust the transfer


def _expand_measured(rng):
    lo, hi = float(rng[0]), float(rng[1])
    c = (lo + hi) / 2.0
    half = max((hi - lo) / 2.0, 1e-6 + 1e-3 * max(abs(lo), abs(hi)))
    return (c - MEASURED_EXPAND * half, c + MEASURED_EXPAND * half)


def stable_keys(topo):
    """Per-node keys stable across rebuilds of the same graph (node
    *names* embed the process-global id counter, so they differ between
    two builds in one process): topo position + op type. The
    measured-range DB (rangecheck.RangeDB) is keyed on these."""
    return [f"{i:04d}:{n.op_type}" for i, n in enumerate(topo)]


def seed_interval(node, measured=None):
    """Interval seed for a leaf placeholder: exact min/max for constant
    values, the initializer's distribution bound (widened by
    TRAINING_DRIFT for trainables), measured DB entry when present,
    else unknown."""
    iv = None
    value = getattr(node, "tensor_value", None)
    if value is not None:
        try:
            arr = value.asnumpy() if hasattr(value, "asnumpy") \
                else np.asarray(value)
            iv = (float(arr.min()), float(arr.max()))
        except (TypeError, ValueError):
            iv = None
    elif getattr(node, "initializer", None) is not None:
        got = node.initializer.interval()
        iv = (float(got[0]), float(got[1])) if got is not None else None
    if iv is not None and getattr(node, "trainable", False):
        m = max(TRAINING_DRIFT * _absmax(iv), 1.0)
        iv = (-m, m)
    if measured is not None:
        iv = _intersect(iv, _expand_measured(measured))
    return iv


# ---------------------------------------------------------------------------
# central transfer table: structural + shape-aware ops
# ---------------------------------------------------------------------------

_PASS_THROUGH = {
    "ArrayReshapeOp", "ArrayReshapeGradientOp", "TransposeOp",
    "FlattenOp", "SqueezeOp", "UnsqueezeOp", "BroadcastToOp",
    "BroadcastShapeOp", "SliceOp", "SplitOp", "SplitGradientOp",
    "PadGradientOp", "ConcatGradientOp", "ConcatenateGradientOp",
    "DataH2DOp", "DataD2HOp", "PipelineSendOp",
    "AllReduceCommunicateOp", "GroupAllReduceCommunicateOp",
    "ParameterServerCommunicateOp", "EmbeddingLookUpGradient",
    "DispatchOp",
}

_CONST_RANGE = {
    "OnesLikeOp": (1.0, 1.0),
    "ZerosLikeOp": (0.0, 0.0),
    "OptimizerOp": (0.0, 0.0),
}


def _matmul_k(node, in_shapes):
    a = in_shapes[0]
    if a is None or len(a) < 2:
        return None
    if node.op_type == "MatMulOp":
        return a[0] if node.matmul_attr_trans_A else a[1]
    return a[-2] if node.trans_A else a[-1]


def _transfer(node, in_rngs, in_shapes):
    """Range for shape-aware / structural ops the per-op protocol
    doesn't cover. None = unknown."""
    ot = node.op_type
    if ot in _CONST_RANGE:
        return _CONST_RANGE[ot]
    if ot in _PASS_THROUGH:
        return in_rngs[0] if in_rngs else None
    if ot in ("ConcatOp", "ConcatenateOp"):
        return _hull(*in_rngs)
    if ot == "PadOp":
        a = in_rngs[0]
        if a is None:
            return None
        c = float(getattr(node, "constant_values", 0) or 0)
        return (min(a[0], c), max(a[1], c))
    if ot == "SliceGradientOp":
        a = in_rngs[0]
        return None if a is None else (min(a[0], 0.0), max(a[1], 0.0))
    if ot in ("MatMulOp", "BatchMatMulOp"):
        a, b = in_rngs[0], in_rngs[1]
        k = _matmul_k(node, in_shapes)
        if a is None or b is None or k is None:
            return None
        m = float(k) * _absmax(a) * _absmax(b)
        if a[0] >= 0 and b[0] >= 0:
            return (float(k) * a[0] * b[0], m)
        return (-m, m)
    if ot == "Conv2dOp":
        a, w = in_rngs[0], in_rngs[1]
        f = in_shapes[1]
        if a is None or w is None or f is None or len(f) != 4:
            return None
        k = f[1] * f[2] * f[3]
        m = float(k) * _absmax(a) * _absmax(w)
        return (-m, m)
    if ot in ("ReduceSumOp", "ReduceSumAxisZeroOp"):
        a = in_rngs[0]
        s = in_shapes[0]
        if a is None or s is None:
            return None
        if ot == "ReduceSumAxisZeroOp":
            n = s[0] if s else 1
        else:
            n = 1
            for ax in node.axes:
                if ax < len(s):
                    n *= s[ax]
        return (min(n * a[0], a[0]), max(n * a[1], a[1]))
    if ot == "ReduceMeanOp":
        return in_rngs[0]
    if ot in ("BroadcastShapeGradSourceOp", "UnbroadcastOp"):
        # sums the adjoint over the broadcast axes; without the exact
        # fan-in keep only a sign-preserving unknown
        return None
    if ot in ("FlashAttentionOp", "RingAttentionOp",
              "UlyssesAttentionOp"):
        # softmax rows are convex weights: output lies in v's hull
        return in_rngs[2] if len(in_rngs) > 2 else None
    if ot == "PipelineReceiveOp":
        return None
    return None


# HT804 domain table: op type -> (operand index, predicate, what)
def _domain_violation(node, in_rngs):
    ot = node.op_type
    if ot == "LogOp":
        a = in_rngs[0]
        if a is not None and a[0] <= 0.0:
            return ("log", a, "operand interval reaches <= 0")
    elif ot == "SqrtOp":
        a = in_rngs[0]
        if a is not None and a[0] < 0.0:
            return ("sqrt", a, "operand interval reaches < 0")
    elif ot == "ReciprocalSqrtOp":
        a = in_rngs[0]
        if a is not None and a[0] <= 0.0:
            return ("rsqrt", a, "operand interval reaches <= 0")
    elif ot == "DivOp":
        b = in_rngs[1]
        if b is not None and b[0] <= 0.0 <= b[1]:
            return ("div", b, "denominator interval contains 0")
    elif ot == "DivConstOp":
        a = in_rngs[0]
        if a is not None and a[0] <= 0.0 <= a[1]:
            return ("div", a, "denominator interval contains 0")
    elif ot == "PowerOp":
        a = in_rngs[0]
        if getattr(node, "p", 1) < 0 and a is not None \
                and a[0] <= 0.0 <= a[1]:
            return ("pow", a, "negative power over an interval "
                              "containing 0")
    return None


# ---------------------------------------------------------------------------
# source-line waivers on the construction provenance
# ---------------------------------------------------------------------------

def _suppressed_node(node, code):
    site = getattr(node, "defined_at", None)
    if not site:
        return False
    return suppressed_at(site[0], site[1], code)


# ---------------------------------------------------------------------------
# the pass
# ---------------------------------------------------------------------------

def _stage_count(topo):
    """Distinct device contexts across the graph — the collective
    pipeline's stage count for the HT805 hop bound."""
    seen = set()
    for n in topo:
        ctxs = getattr(getattr(n, "raw_ctx", None), "_contexts", None)
        if not ctxs:
            continue
        for c in ctxs:
            for cc in (c if isinstance(c, tuple) else (c,)):
                seen.add((getattr(cc, "hostname", None),
                          getattr(cc, "device_id", None)))
    return max(1, len(seen))


def _canon_low_prec(spec):
    """'bfloat16' | 'float16' | None from any spelling the runtime's
    ``_canon_boundary_dtype`` accepts — strings OR dtype objects
    (``np.float16``, ``jnp.bfloat16``)."""
    if spec is None:
        return None
    if isinstance(spec, str):
        name = spec.lower()
        if name in ("bf16", "bfloat16"):
            return "bfloat16"
        if name in ("fp16", "f16", "float16", "half"):
            return "float16"
        return None
    try:
        name = np.dtype(spec).name
    except TypeError:
        return None
    return name if name in ("bfloat16", "float16") else None


def numerics_pass(topo, report, shapes=None, dtypes=None,
                  feed_shapes=None, config=None, measured=None,
                  acc_tol=ACC_TOL, boundary_rtol=None):
    """Run the HT8xx checks over a topo-sorted graph; returns the
    derived ``{node: (lo, hi) or None}`` interval map.

    ``shapes``/``dtypes`` are the shape pass's outputs (recomputed here
    when absent); ``measured`` is a ``{stable_key: (lo, hi)}`` map from
    the rangecheck DB that tightens the seeds; ``config`` (a
    HetuConfig) enables the mixed-precision flow checks (HT805/HT806).
    Findings whose construction line carries ``# ht-ok: HT8xx`` are
    waived."""
    from ..ops.variable import PlaceholderOp
    from ..optimizer import OptimizerOp

    if shapes is None or dtypes is None:
        from .findings import Report
        from .shapes import shape_pass
        dtypes = {} if dtypes is None else dtypes
        shapes = shape_pass(topo, Report(), feed_shapes=feed_shapes,
                            dtypes_out=dtypes)

    keys = stable_keys(topo)
    measured = measured or {}

    # Executor(dtype="bfloat16"/"float16") casts every float param and
    # feed to the compute dtype inside the traced step: the session's
    # EFFECTIVE precision for float nodes is config.dtype, not the
    # declared fp32 the graph was built with — without this the
    # headline low-precision checks (HT801/HT802) are blind on the
    # repo's own mixed-precision path
    cfg_dt = _np_dtype(getattr(config, "dtype", None)) \
        if config is not None else None
    if prec_class(cfg_dt) not in ("bf16", "fp16"):
        cfg_dt = None

    def eff_dtype(n):
        dt = dtypes.get(n)
        if cfg_dt is not None and dt is not None and dt.kind == "f":
            return cfg_dt
        return dt

    def add(code, severity, message, node):
        if _suppressed_node(node, code):
            return
        report.add(code, severity, message, node=node)

    ranges = {}
    for i, node in enumerate(topo):
        in_rngs = [ranges.get(x) for x in node.inputs]
        in_shapes = [shapes.get(x) for x in node.inputs]
        if isinstance(node, PlaceholderOp):
            rng = seed_interval(node, measured=measured.get(keys[i]))
        else:
            rng = None
            infer = getattr(node, "infer_range", None)
            if infer is not None:
                try:
                    rng = infer(in_rngs, in_shapes)
                except Exception:   # noqa: BLE001 — a bad bound is no bound
                    rng = None
            if rng is None:
                rng = _transfer(node, in_rngs, in_shapes)
            if keys[i] in measured:
                rng = _intersect(rng, _expand_measured(measured[keys[i]]))
        if rng is not None and (math.isnan(rng[0]) or math.isnan(rng[1])
                                or rng[0] > rng[1]):
            rng = None      # a degenerate bound is no bound — a NaN
            # interval would compare False everywhere and silently
            # disarm every downstream check
        ranges[node] = rng

        dt = eff_dtype(node)
        prec = prec_class(dt)

        # HT804 — domain hazards (zero-crossing operand, missing guard)
        hit = _domain_violation(node, in_rngs)
        if hit is not None:
            what, iv, why = hit
            add("HT804", "warn",
                f"{node.op_type} {node.name}: {why} "
                f"([{iv[0]:.3g}, {iv[1]:.3g}]) with no eps/clip guard "
                f"on the path — add a clip/+eps (interval arithmetic "
                f"recognizes the guard and clears this)", node)
        eps = getattr(node, "eps", None)
        if eps is not None and "Normalization" in node.op_type \
                and "Gradient" not in node.op_type and eps <= 0:
            add("HT804", "warn",
                f"{node.op_type} {node.name}: eps={eps} — the rsqrt "
                f"of the variance is unguarded at zero variance", node)
        if isinstance(node, OptimizerOp):
            oeps = getattr(node.optimizer, "epsilon",
                           getattr(node.optimizer, "eps", None))
            if oeps is not None and oeps <= 0:
                add("HT804", "warn",
                    f"{node.name}: optimizer eps={oeps} — the "
                    f"sqrt(v)+eps denominator is unguarded", node)

        # HT801 — derived interval exceeds the dtype's representable max
        if prec in ("fp16", "bf16", "fp32", "fp64") and rng is not None:
            am = _absmax(rng)
            fmax = dtype_max(dt)
            explosive = node.op_type in ("ExpOp", "PowerOp", "MulOp",
                                         "MatMulOp", "BatchMatMulOp")
            # only the node that CREATES the overflow fires — an input
            # already past ITS OWN dtype's max re-reports the same root
            # cause on every downstream consumer otherwise. Each input
            # is judged against its own precision: a fp32 interval past
            # 65504 cast to fp16 is overflow CREATED by the cast, not
            # propagated through it.
            def _in_bounds(inp, r):
                if r is None:
                    return True
                idt = eff_dtype(inp)
                if prec_class(idt) not in ("fp16", "bf16", "fp32",
                                           "fp64"):
                    return True
                return _absmax(r) <= dtype_max(idt)
            created = all(_in_bounds(inp, r)
                          for inp, r in zip(node.inputs, in_rngs))
            if created and ((math.isfinite(am) and am > fmax) or
                            (math.isinf(am) and explosive
                             and all(r is not None for r in in_rngs))):
                sev = "error" if prec in ("fp16", "bf16") else "warn"
                add("HT801", sev,
                    f"{node.op_type} {node.name}: derived interval "
                    f"[{rng[0]:.3g}, {rng[1]:.3g}] exceeds {dt} max "
                    f"{fmax:.3g} — overflow-prone in {prec} (shift the "
                    f"operand, e.g. subtract the max before exp, or "
                    f"compute in fp32)", node)

        # HT802 — low-precision accumulation over N elements
        if prec in ("fp16", "bf16"):
            n_acc = None
            if node.op_type in ("MatMulOp", "BatchMatMulOp"):
                n_acc = _matmul_k(node, in_shapes)
            elif node.op_type == "Conv2dOp" and in_shapes[1] is not None \
                    and len(in_shapes[1]) == 4:
                f = in_shapes[1]
                n_acc = f[1] * f[2] * f[3]
            elif node.op_type in ("ReduceSumOp", "ReduceMeanOp") \
                    and in_shapes[0] is not None:
                n_acc = 1
                for ax in node.axes:
                    if ax < len(in_shapes[0]):
                        n_acc *= in_shapes[0][ax]
            elif node.op_type == "ReduceSumAxisZeroOp" \
                    and in_shapes[0]:
                n_acc = in_shapes[0][0]
            if n_acc is not None and accum_error_bound(dt, n_acc) > acc_tol:
                add("HT802", "warn",
                    f"{node.op_type} {node.name}: accumulates {n_acc} "
                    f"elements in {prec} (worst-case relative error "
                    f"{accum_error_bound(dt, n_acc):.2g} > {acc_tol:g})"
                    f" — accumulate in fp32 "
                    f"(preferred_element_type=jnp.float32) and cast the"
                    f" result", node)

        # HT803 — integer-exactness loss on the id paths
        if node.op_type == "EmbeddingLookUp":
            tbl, idx = node.inputs
            rows = None
            tshape = shapes.get(tbl) or getattr(tbl, "shape", None)
            if tshape:
                rows = tshape[0]
            idt = dtypes.get(idx)
            if idt is not None and idt.kind == "f":
                limit = exact_int_limit(idt)
                if rows is not None and rows > limit:
                    add("HT803", "error",
                        f"{node.name}: ids arrive as {idt} but the "
                        f"table declares {rows} rows — float ids are "
                        f"exact only to 2^{int(_finfo(idt).nmant) + 1}"
                        f" = {limit}; feed integer ids", node)
                else:
                    add("HT803", "warn",
                        f"{node.name}: float-dtype ids ({idt}) — "
                        f"exactness is lost past {exact_int_limit(idt)}"
                        f" ids; the runtime now rejects float id "
                        f"feeds (feed int32/int64)", node)
            elif idt is not None and idt.kind in "iu" and rows is not None \
                    and rows - 1 > np.iinfo(idt).max:
                add("HT803", "error",
                    f"{node.name}: id dtype {idt} cannot address the "
                    f"declared {rows}-row table — widen the id dtype",
                    node)
            elif rows is not None and rows - 1 > np.iinfo(np.int32).max:
                import jax
                if not jax.config.jax_enable_x64:
                    add("HT803", "warn",
                        f"{node.name}: the declared {rows}-row table "
                        f"needs 64-bit ids, but jax x64 is disabled — "
                        f"device feeds canonicalize int64 to int32 and "
                        f"wrap; route the lookup through the PS host "
                        f"path (64-bit ids end-to-end) or enable "
                        f"jax_enable_x64", node)
        if node.op_type == "CastOp" and prec in ("fp16", "bf16", "fp32"):
            src = dtypes.get(node.inputs[0])
            src_rng = in_rngs[0]
            if src is not None and src.kind in "iu" \
                    and src_rng is not None \
                    and _absmax(src_rng) > exact_int_limit(dt):
                add("HT803", "error",
                    f"{node.name}: casts integers up to "
                    f"{_absmax(src_rng):.3g} through {dt}, which is "
                    f"exact only to {exact_int_limit(dt)} — ids pass "
                    f"2^{int(_finfo(dt).nmant) + 1} and collide", node)

    # HT807 — PRNG stream reuse across independent random ops
    fams = {}
    for node in topo:
        if not (hasattr(node, "keep_prob") or hasattr(node, "rng_key")):
            continue
        fwd = getattr(node, "forward_node", None)
        key = getattr(node, "rng_key", None)
        if key is None:
            key = fwd.id if fwd is not None else node.id
        fam = fwd.id if fwd is not None else node.id
        fams.setdefault(key, []).append((fam, node))
    for key, members in fams.items():
        owners = {}
        for fam, node in members:
            owners.setdefault(fam, node)
        if len(owners) > 1:
            names = ", ".join(n.name for n in owners.values())
            first = next(iter(owners.values()))
            if not any(_suppressed_node(n, "HT807")
                       for n in owners.values()):
                report.add(
                    "HT807", "error",
                    f"PRNG key {key} is consumed by {len(owners)} "
                    f"independent random ops ({names}) — their masks "
                    f"are CORRELATED, not independent; give each op "
                    f"its own key (fold_in of a distinct op id)",
                    node=first)

    _config_checks(topo, report, ranges, dtypes, config, boundary_rtol,
                   add)
    return ranges


def _config_checks(topo, report, ranges, dtypes, config, boundary_rtol,
                   add):
    """HT805/HT806 — mixed-precision flow checks that need the session
    config (pipeline boundary dtype, executor compute dtype)."""
    from ..optimizer import OptimizerOp

    opt_nodes = [n for n in topo if isinstance(n, OptimizerOp)]

    # HT806: backward path entirely in fp16 with no loss scale
    cfg_dt = _np_dtype(getattr(config, "dtype", None)) \
        if config is not None else None
    for opt_op in opt_nodes:
        fp16_grads = [g for g in opt_op.inputs
                      if prec_class(dtypes.get(g)) == "fp16"]
        all_fp16 = (cfg_dt is not None and cfg_dt.name == "float16") or \
            (fp16_grads and len(fp16_grads) == len(opt_op.inputs))
        if not all_fp16:
            continue
        scale = getattr(opt_op.optimizer, "loss_scale", None)
        if scale is not None and scale > 1:
            continue
        sev = "warn"
        tiny = dtype_tiny("float16")
        small = [g for g in fp16_grads
                 if ranges.get(g) is not None
                 and _absmax(ranges[g]) < tiny]
        if small:
            sev = "error"
        add("HT806", sev,
            f"{opt_op.name}: the backward path runs entirely in fp16 "
            f"with no loss scale — gradients below {tiny:.2g} (fp16 "
            f"min-normal) flush to zero"
            + (f"; {len(small)} gradient(s) derive an interval below "
               f"it already" if small else "")
            + " — pass loss_scale= to the optimizer (gradients are "
              "unscaled inside the update)", opt_op)

    if config is None:
        return
    ppo = getattr(config, "pp_options", None) or {}
    bdt = _canon_low_prec(ppo.get("boundary_dtype"))
    if getattr(config, "pipeline_mode", None) == "collective" and bdt:
        if boundary_rtol is None:
            boundary_rtol = ppo.get("boundary_rtol")
        if boundary_rtol is None:
            from ..parallel.collective_pp import BOUNDARY_RTOL
            boundary_rtol = BOUNDARY_RTOL
        hops = max(1, _stage_count(topo) - 1)
        bound = boundary_error_bound(bdt, hops)
        # through the suppression-aware closure: a deliberately
        # retuned boundary gets waived with '# ht-ok: HT805' on the
        # anchor's construction line like every other HT8xx finding
        anchor = topo[-1]
        if bound > boundary_rtol:
            add("HT805", "error",
                f"collective-pipeline boundary in {bdt}: derived "
                f"relative error bound {bound:.2e} over {hops} hop(s) "
                f"exceeds the declared tolerance {boundary_rtol:g} — "
                f"retune boundary_rtol or keep fp32 boundaries",
                anchor)
        if bdt == "float16":
            add("HT805", "warn",
                f"collective-pipeline boundary widened to fp16: the "
                f"exponent range halves (max {dtype_max(bdt):.0f}) — "
                f"activations beyond it overflow at the stage "
                f"boundary; verify measured activation absmax "
                f"(rangecheck) and retune before shipping", anchor)

    # HT805: explicit low-precision cross-replica reduction edges
    for node in topo:
        if node.op_type in ("AllReduceCommunicateOp",
                            "GroupAllReduceCommunicateOp"):
            prec = prec_class(dtypes.get(node))
            if prec in ("bf16", "fp16"):
                add("HT805", "warn",
                    f"{node.name}: cross-replica reduction in {prec} — "
                    f"per-hop relative error ~{dtype_eps(dtypes.get(node)) / 2:.2e} "
                    f"compounds with replica count; reduce in fp32 or "
                    f"declare the tolerance", node)


# ---------------------------------------------------------------------------
# CLI: zoo sweep gating on ANY unsuppressed finding
# ---------------------------------------------------------------------------

def check_zoo(names=None, measured_db=None):
    """{model: Report} of numerics-only findings over zoo graphs."""
    from . import zoo
    from .findings import Report
    from .shapes import shape_pass
    from ..graph.autodiff import find_topo_sort

    out = {}
    for name in names or sorted(zoo.ZOO):
        eval_nodes, feed_shapes = zoo.build(name)
        topo = find_topo_sort(list(eval_nodes))
        dtypes = {}
        shapes = shape_pass(topo, Report(), feed_shapes=feed_shapes,
                            dtypes_out=dtypes)
        measured = None
        if measured_db is not None:
            measured = measured_db.get(name)
        report = Report()
        numerics_pass(topo, report, shapes=shapes, dtypes=dtypes,
                      config=None, measured=measured)
        out[name] = report
    return out


def main(argv=None):
    import argparse
    import json
    import sys

    parser = argparse.ArgumentParser(
        prog="python -m hetu_tpu.analysis.numerics",
        description="interval + dtype abstract interpretation over the "
                    "zoo graphs (HT8xx); exits 1 on any unsuppressed "
                    "finding")
    parser.add_argument("models", nargs="*",
                        help="zoo model names (default: all)")
    parser.add_argument("--json", action="store_true")
    parser.add_argument("--db", default=None, metavar="PATH",
                        help="measured-range DB (rangecheck output) "
                             "that tightens the interval seeds")
    args = parser.parse_args(argv)

    from . import zoo
    names = args.models or sorted(zoo.ZOO)
    unknown = [n for n in names if n not in zoo.ZOO]
    if unknown:
        parser.error(f"unknown zoo model(s) {unknown}")

    db = None
    if args.db:
        from .rangecheck import RangeDB
        db = RangeDB(args.db)
    reports = check_zoo(names, measured_db=db)
    total = sum(len(r) for r in reports.values())
    if args.json:
        print(json.dumps(
            {name: json.loads(r.to_json())
             for name, r in reports.items()}, indent=2))
    else:
        for name, r in reports.items():
            status = "FAIL" if len(r) else "ok"
            print(f"== {name}: {status} ({len(r)} finding(s))")
            for f in r.findings:
                print("   " + str(f))
        print(f"numerics: {total} unsuppressed finding(s) across "
              f"{len(names)} zoo model(s)")
    if total:
        print("numerics: FAILED — guard the op, or waive with "
              "'# ht-ok: HT8xx <reason>' on the construction line",
              file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    import sys
    sys.exit(main())
