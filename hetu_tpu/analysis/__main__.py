"""``python -m hetu_tpu.analysis`` — preflight the model zoo (or a
saved graph) from the command line.

Builds each registered zoo graph with canonical feed shapes, runs every
static pass, and prints per-model findings. Exit status 1 when any
model has errors — the CI preflight job's gate. ``--jit-purity`` chains
the codebase self-lint in the same invocation.
"""
from __future__ import annotations

import argparse
import sys


def main(argv=None):
    parser = argparse.ArgumentParser(
        prog="python -m hetu_tpu.analysis",
        description="static preflight over the model zoo")
    parser.add_argument("models", nargs="*",
                        help="zoo model names (default: all)")
    parser.add_argument("--list", action="store_true",
                        help="list registered zoo models and exit")
    parser.add_argument("--json", action="store_true",
                        help="machine-readable findings")
    parser.add_argument("--hbm-budget", default=None, metavar="BYTES",
                        help="HBM budget for the memory pass "
                             "(e.g. 8G, 512MiB; default: "
                             "$HETU_HBM_BUDGET or the device limit)")
    parser.add_argument("--jit-purity", action="store_true",
                        help="also run the jit-purity codebase lint")
    args = parser.parse_args(argv)

    from . import analyze, zoo
    if args.list:
        print("\n".join(sorted(zoo.ZOO)))
        return 0

    names = args.models or sorted(zoo.ZOO)
    unknown = [n for n in names if n not in zoo.ZOO]
    if unknown:
        parser.error(f"unknown zoo model(s) {unknown}; "
                     f"--list shows the registry")

    failed = []
    for name in names:
        eval_nodes, feed_shapes = zoo.build(name)
        report = analyze(eval_nodes, feed_shapes=feed_shapes,
                         hbm_budget=args.hbm_budget)
        status = "FAIL" if report.errors else "ok"
        print(f"== {name}: {status} ({len(report.errors)} errors, "
              f"{len(report.warnings)} warnings)")
        if args.json:
            print(report.to_json())
        else:
            for f in report.errors + report.warnings:
                print("   " + str(f))
        if report.errors:
            failed.append(name)

    rc = 0
    if failed:
        print(f"preflight: {len(failed)}/{len(names)} zoo model(s) "
              f"failed: {', '.join(failed)}", file=sys.stderr)
        rc = 1
    if args.jit_purity:
        from .jit_purity import main as purity_main
        rc = max(rc, purity_main([]))
    return rc


if __name__ == "__main__":
    sys.exit(main())
