"""``python -m hetu_tpu.analysis`` — preflight the model zoo (or a
saved graph) from the command line.

Builds each registered zoo graph with canonical feed shapes, runs every
static pass, and prints per-model findings. Exit status 1 when any
model has errors — the CI preflight job's gate. ``--jit-purity`` chains
the codebase self-lint in the same invocation.

``--all`` is the aggregate driver: zoo preflight + jit-purity +
concurrency + protocol (wire contract and consistency model checking)
+ numerics + efficiency in one invocation with a single merged report
and exit code — the CI ``analysis`` job, which uploads the merged
JSON (``--out``) as its artifact. Per-pass gates keep their own
semantics (zoo/jit-purity gate on errors; concurrency/protocol/
numerics gate on ANY unsuppressed finding; efficiency on unsuppressed
warn/error findings).
"""
from __future__ import annotations

import argparse
import json
import sys


def _run_zoo(names, json_out, hbm_budget, quiet=False):
    from . import analyze, zoo
    failed = []
    models = {}
    for name in names:
        eval_nodes, feed_shapes = zoo.build(name)
        report = analyze(eval_nodes, feed_shapes=feed_shapes,
                         hbm_budget=hbm_budget)
        status = "FAIL" if report.errors else "ok"
        models[name] = report
        if not quiet:
            print(f"== {name}: {status} ({len(report.errors)} errors, "
                  f"{len(report.warnings)} warnings)")
            if json_out:
                print(report.to_json())
            else:
                for f in report.errors + report.warnings:
                    print("   " + str(f))
        if report.errors:
            failed.append(name)
    return models, failed


def main(argv=None):
    parser = argparse.ArgumentParser(
        prog="python -m hetu_tpu.analysis",
        description="static preflight over the model zoo")
    parser.add_argument("models", nargs="*",
                        help="zoo model names (default: all)")
    parser.add_argument("--list", action="store_true",
                        help="list registered zoo models and exit")
    parser.add_argument("--json", action="store_true",
                        help="machine-readable findings")
    parser.add_argument("--hbm-budget", default=None, metavar="BYTES",
                        help="HBM budget for the memory pass "
                             "(e.g. 8G, 512MiB; default: "
                             "$HETU_HBM_BUDGET or the device limit)")
    parser.add_argument("--jit-purity", action="store_true",
                        help="also run the jit-purity codebase lint")
    parser.add_argument("--all", action="store_true",
                        help="aggregate driver: zoo preflight + "
                             "jit-purity + concurrency + protocol with "
                             "one merged report and exit code")
    parser.add_argument("--out", default=None, metavar="FILE",
                        help="with --all: write the merged JSON report "
                             "here (the CI artifact)")
    args = parser.parse_args(argv)

    from . import zoo
    if args.list:
        print("\n".join(sorted(zoo.ZOO)))
        return 0

    names = args.models or sorted(zoo.ZOO)
    unknown = [n for n in names if n not in zoo.ZOO]
    if unknown:
        parser.error(f"unknown zoo model(s) {unknown}; "
                     f"--list shows the registry")

    if args.all:
        return _main_all(names, args)

    models, failed = _run_zoo(names, args.json, args.hbm_budget)
    rc = 0
    if failed:
        print(f"preflight: {len(failed)}/{len(names)} zoo model(s) "
              f"failed: {', '.join(failed)}", file=sys.stderr)
        rc = 1
    if args.jit_purity:
        from .jit_purity import main as purity_main
        rc = max(rc, purity_main([]))
    return rc


def _main_all(names, args):
    import os
    from .jit_purity import check_paths as jit_check
    from .concurrency import check_paths as conc_check
    from .findings import Report
    from .protocol import protocol_pass

    pkg = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    sections = {}
    gates = {}

    models, failed = _run_zoo(names, False, args.hbm_budget,
                              quiet=True)
    sections["zoo"] = {n: json.loads(r.to_json())
                       for n, r in models.items()}
    gates["zoo"] = 1 if failed else 0

    jit = jit_check([pkg])
    sections["jit_purity"] = json.loads(jit.to_json())
    gates["jit_purity"] = 1 if jit.errors else 0

    conc = conc_check([pkg])
    sections["concurrency"] = json.loads(conc.to_json())
    gates["concurrency"] = 1 if len(conc) else 0

    proto = Report()
    stats = protocol_pass(proto)
    sections["protocol"] = json.loads(proto.to_json())
    sections["protocol"]["model"] = stats
    gates["protocol"] = 1 if len(proto) else 0

    # numerics & precision verifier (HT8xx): zoo sweep gating on ANY
    # unsuppressed finding — same semantics as its standalone CLI
    # (python -m hetu_tpu.analysis.numerics)
    from .numerics import check_zoo
    num = check_zoo(names)
    sections["numerics"] = {n: json.loads(r.to_json())
                            for n, r in num.items()}
    num_total = sum(len(r) for r in num.values())
    gates["numerics"] = 1 if num_total else 0

    # efficiency verifier (HT9xx): CostDB-priced performance lint,
    # gating on unsuppressed warn/error findings (info pricings and
    # HT908 coverage advisories print but never gate) — same
    # semantics as python -m hetu_tpu.analysis.efficiency
    from .efficiency import check_zoo as eff_zoo
    eff = eff_zoo(names)
    sections["efficiency"] = {n: r.to_dict() for n, r in eff.items()}
    eff_gating = sum(
        1 for r in eff.values() for f in r.report.findings
        if f.severity in ("warn", "error"))
    eff_total = sum(len(r.report) for r in eff.values())
    gates["efficiency"] = 1 if eff_gating else 0

    rc = max(gates.values())
    merged = {"ok": rc == 0, "gates": gates, "sections": sections}
    if args.json:
        print(json.dumps(merged, indent=2))
    else:
        print(f"analysis --all: zoo {len(names) - len(failed)}/"
              f"{len(names)} clean"
              + (f" (failed: {', '.join(failed)})" if failed else "")
              + f"; jit-purity {len(jit.errors)} error(s); "
              f"concurrency {len(conc)} finding(s); protocol "
              f"{len(proto)} finding(s), {stats['states']} model "
              f"states explored; numerics {num_total} finding(s); "
              f"efficiency {eff_total} finding(s) "
              f"({eff_gating} gating)")
        for name, rep in models.items():
            for f in rep.errors:
                print(f"   zoo/{name}: {f}")
        for rep in (jit, conc, proto):
            for f in rep.findings:
                print("   " + str(f))
        for name, rep in num.items():
            for f in rep.findings:
                print(f"   numerics/{name}: {f}")
        for name, res in eff.items():
            for f in res.findings:
                print(f"   efficiency/{name}: {f}")
    if args.out:
        with open(args.out, "w") as f:
            json.dump(merged, f, indent=2)
        # stderr: --json keeps stdout a single parseable document
        print(f"merged report written to {args.out}", file=sys.stderr)
    if rc:
        print("analysis --all: FAILED — fix or ht-ok-annotate the "
              "findings above", file=sys.stderr)
    return rc


if __name__ == "__main__":
    sys.exit(main())
