"""Structured findings: the common currency of every preflight pass.

A :class:`Finding` is one diagnostic with a stable code (HT1xx shapes,
HT2xx sharding, HT3xx comm/deadlock, HT4xx memory, HTPxx jit purity), a
severity, and — when the graph node that caused it is known — the node
name and the *user's* construction site (``file:line`` captured by
``graph/node.py Op.__init__``), so a deep-graph error reports the model
line that built it instead of a framework traceback.

The module also hosts the **collector stack**: runtime code that today
degrades gracefully with a ``logger.warning`` (e.g.
``parallel/planner.py spec_for_status``) calls :func:`emit`; when an
analysis pass is active (``with collecting(report):``) the diagnostic
becomes a structured finding, otherwise ``emit`` returns False and the
caller keeps its warning fallback — analysis off costs one list check.
"""
from __future__ import annotations

import contextlib
import json
import re

__all__ = ["Finding", "Report", "GraphValidationError", "collecting",
           "emit", "provenance", "suppressed", "suppressed_at",
           "SEVERITIES"]

SEVERITIES = ("error", "warn", "info")

# ---------------------------------------------------------------------------
# suppression comments: one grep surface for every waived finding
# ---------------------------------------------------------------------------

# an HT finding code: HT601, HT702, HTP01, HT001, ...
_SUPPRESS_CODE_RE = re.compile(r"HT[A-Z]?\d+")

# canonical marker + per-pass aliases kept for existing annotations
SUPPRESS_MARKERS = ("ht-ok", "jit-ok", "lock-ok")


def suppressed(lines, lineno, code=None, markers=SUPPRESS_MARKERS):
    """Shared suppression-comment check for every source-level pass.

    True when source line ``lineno`` (1-based, ``lines`` =
    ``src.splitlines()``) carries a suppression marker that waives
    ``code``. The house style is ``# ht-ok: <CODE> <reason>`` — the
    annotated form suppresses only that code (the reason is the review
    artifact); a bare marker suppresses every finding on the line.
    ``// ht-ok`` works the same way in C/C++ sources (the wire-contract
    pass lints the native PS files). ``jit-ok`` and ``lock-ok`` are
    kept as pass-local aliases so existing annotations stay valid;
    ``grep -rn 'ht-ok\\|jit-ok\\|lock-ok'`` is the one audit surface.
    """
    if not (0 < lineno <= len(lines)):
        return False
    line = lines[lineno - 1]
    for marker in markers:
        for lead in ("# ", "#", "// ", "//"):
            i = line.find(lead + marker)
            if i < 0:
                continue
            codes = _SUPPRESS_CODE_RE.findall(line[i:])
            if not codes or code is None or code in codes:
                return True
    return False


_SRC_CACHE = {}


def suppressed_at(path, lineno, code=None, markers=SUPPRESS_MARKERS):
    """:func:`suppressed` over a source FILE, with the read cached per
    process — the shared file-layer for passes whose findings anchor at
    a ``defined_at`` construction site rather than an already-parsed
    source (numerics; the wire/protocol passes keep their own parsed
    lines)."""
    lines = _SRC_CACHE.get(path)
    if lines is None:
        try:
            with open(path) as f:
                lines = f.read().splitlines()
        except OSError:
            lines = []
        _SRC_CACHE[path] = lines
    return suppressed(lines, lineno, code, markers=markers)


def provenance(node):
    """``file:line`` where the user's code constructed ``node`` (the
    ``Op.defined_at`` capture), or None for nodes built before the
    provenance hook existed / outside any user frame."""
    site = getattr(node, "defined_at", None)
    if not site:
        return None
    return f"{site[0]}:{site[1]}"


class Finding:
    """One diagnostic: code + severity + message (+ node provenance)."""

    __slots__ = ("code", "severity", "message", "node", "where", "data")

    def __init__(self, code, severity, message, node=None, where=None,
                 **data):
        assert severity in SEVERITIES, severity
        self.code = code
        self.severity = severity
        self.message = message
        # accept an Op (name + provenance extracted) or a plain string
        if node is not None and not isinstance(node, str):
            if where is None:
                where = provenance(node)
            node = getattr(node, "name", str(node))
        self.node = node
        self.where = where
        self.data = data

    def to_dict(self):
        out = {"code": self.code, "severity": self.severity,
               "message": self.message}
        if self.node is not None:
            out["node"] = self.node
        if self.where is not None:
            out["where"] = self.where
        if self.data:
            out.update(self.data)
        return out

    def __str__(self):
        loc = ""
        if self.node or self.where:
            parts = [p for p in (self.node, self.where) if p]
            loc = "  (" + " @ ".join(parts) + ")"
        return f"[{self.code}] {self.severity}: {self.message}{loc}"

    def __repr__(self):
        return f"Finding({self})"


class Report:
    """Ordered collection of findings from one analysis run."""

    def __init__(self, findings=None):
        self.findings = list(findings or [])

    def add(self, code, severity, message, node=None, where=None, **data):
        f = Finding(code, severity, message, node=node, where=where,
                    **data)
        self.findings.append(f)
        return f

    def extend(self, findings):
        self.findings.extend(findings)

    def _sev(self, severity):
        return [f for f in self.findings if f.severity == severity]

    @property
    def errors(self):
        return self._sev("error")

    @property
    def warnings(self):
        return self._sev("warn")

    @property
    def infos(self):
        return self._sev("info")

    @property
    def ok(self):
        return not self.errors

    def by_node(self):
        """{node name: worst severity} — the graphboard overlay index."""
        rank = {s: i for i, s in enumerate(SEVERITIES)}
        out = {}
        for f in self.findings:
            if f.node is None:
                continue
            cur = out.get(f.node)
            if cur is None or rank[f.severity] < rank[cur]:
                out[f.node] = f.severity
        return out

    def to_json(self):
        return json.dumps({
            "errors": len(self.errors), "warnings": len(self.warnings),
            "infos": len(self.infos),
            "findings": [f.to_dict() for f in self.findings]}, indent=2)

    def to_text(self):
        lines = [f"preflight: {len(self.errors)} error(s), "
                 f"{len(self.warnings)} warning(s), "
                 f"{len(self.infos)} info(s)"]
        for sev in SEVERITIES:
            for f in self._sev(sev):
                lines.append("  " + str(f))
        return "\n".join(lines)

    def __str__(self):
        return self.to_text()

    def __len__(self):
        return len(self.findings)


class GraphValidationError(ValueError):
    """Raised by ``Executor(validate='error')`` when preflight finds
    errors; carries the full report."""

    def __init__(self, report):
        self.report = report
        super().__init__("graph preflight failed:\n" + report.to_text())


# ---------------------------------------------------------------------------
# collector stack: runtime warning sites upgrade to structured findings
# ---------------------------------------------------------------------------

_collectors = []


@contextlib.contextmanager
def collecting(report):
    """Route :func:`emit` calls into ``report`` for the duration."""
    _collectors.append(report)
    try:
        yield report
    finally:
        _collectors.pop()


def emit(code, severity, message, node=None, **data):
    """Add a finding to the innermost active collector. Returns True if
    one was active (caller can skip its logging fallback)."""
    if not _collectors:
        return False
    _collectors[-1].add(code, severity, message, node=node, **data)
    return True
