"""hetu_tpu.analysis — preflight graph verifier for define-then-run
sessions.

The define-then-run model hands us the *whole* program — graph,
partition states, pipeline schedule, placement — before a single byte
moves. This package runs its battery of static passes over the
topo-sorted graph between construction and first dispatch, each
emitting structured :class:`~.findings.Finding` objects with stable
codes and per-op user provenance:

1. **shapes** (HT1xx) — shape/dtype propagation through the existing
   ``Op.infer_shape`` protocol + dead-subgraph/unused-variable/
   duplicate-param lint,
2. **sharding** (HT2xx) — the planner's ``deduce_states`` fixpoint
   validated; unmappable or conflicting specs rejected, implicit
   reshards surfaced with comm-byte estimates,
3. **deadlock** (HT3xx) — the GPipe/1F1B/collective schedules executed
   symbolically rank-by-rank; unmatched sends/recvs and cyclic waits
   become findings instead of fleet hangs,
4. **memory** (HT4xx) — static footprint estimate (and, at compile
   time, ``memory_analysis()`` numbers) against an HBM budget,
5. **overlap** (HT5xx, advisory) — feed-bound (PS-backed) configs that
   run with the async ingest engine off, or through plain per-step
   ``run()`` loops that never engage it (runtime half in
   ``executor.py``),
6. **numerics** (HT8xx) — interval + dtype abstract interpretation:
   per-node value intervals seeded from initializer distributions and
   op semantics, precision classes riding the dtype propagation;
   overflow-prone low-precision ops, unguarded div/log/rsqrt domains,
   integer-exactness cliffs on the id paths, low-precision
   accumulation/boundary/underflow risks, PRNG stream reuse — with
   ``analysis/rangecheck.py`` as its measured-range dynamic twin
   (soundness gate + persistent range DB that tightens re-analysis),
7. **efficiency** (HT9xx) — CostDB-priced static performance lint:
   recompile hazards, tile-padding waste, hot-path host syncs,
   fragmented collectives, redundant reshards, cost-weighted dead
   compute, untuned kernels, coverage-gap advisories — every finding
   priced in predicted ms/step through the measured CostDB, with
   ``analysis/perfcheck.py`` as its doctor-validated soundness twin
   (measured bucket attribution gates every priced claim, HT910).

Two codebase self-lints ride beside the graph passes: **jit_purity**
(HTPxx — host impurity inside jit-traced bodies) and **concurrency**
(HT6xx — lockset/lock-order/lifecycle verification of the threaded
host runtime, with ``racecheck.py`` as its dynamic instrumented-lock
twin). The distributed plane gets the same treatment from **wire** +
**protocol** (HT7xx — PS wire-contract checking across the C++/ctypes
boundary, and small-scope consistency model checking of the
BSP/staleness/retry/failover protocol); PS-backed graphs get the wire
check inside :func:`analyze` too.

Surfaces: ``Executor(validate="error"|"warn"|"off")``,
``heturun --preflight``, ``python -m hetu_tpu.analysis`` (zoo CLI;
``--all`` aggregates every pass with one merged report),
``python -m hetu_tpu.analysis.jit_purity``,
``python -m hetu_tpu.analysis.concurrency`` and
``python -m hetu_tpu.analysis.protocol`` (codebase self-lints), and
a graphboard finding overlay. See ``docs/analysis.md``.
"""
from __future__ import annotations

import os
import sys

from .findings import (Finding, Report, GraphValidationError, collecting,
                       emit, provenance)
from .shapes import shape_pass, lint_pass, frozen_graph_pass
from .sharding import sharding_pass
from .deadlock import deadlock_pass
from .memory import memory_pass, check_compiled
from .overlap import overlap_pass, RunLoopAdvisor
from .numerics import numerics_pass
from .efficiency import efficiency_pass
from .findings import suppressed

__all__ = ["Finding", "Report", "GraphValidationError", "collecting",
           "emit", "provenance", "suppressed", "analyze",
           "finish_preflight",
           "shape_pass", "lint_pass", "frozen_graph_pass",
           "sharding_pass", "deadlock_pass", "memory_pass",
           "overlap_pass", "numerics_pass", "efficiency_pass",
           "RunLoopAdvisor", "check_compiled", "EXIT_PREFLIGHT"]

# distinct exit code for "preflight found errors" (cf. the watchdog's
# 117): the launcher refuses to spawn the fleet when it sees it
EXIT_PREFLIGHT = 121


def _schedule_of(config):
    if config is None:
        return "gpipe"
    if getattr(config, "pipeline_mode", None) == "collective":
        return "collective"
    if getattr(config, "use_pipedream", False):
        v = (getattr(config, "pp_options", None) or {}).get(
            "virtual_stages", 1) or 1
        return "interleaved_1f1b" if int(v) > 1 else "1f1b"
    return "gpipe"


def analyze(eval_node_list, feed_shapes=None, config=None, schedule=None,
            nprocs=None, num_microbatches=None, hbm_budget=None,
            extra_roots=(), frozen=False, virtual_stages=None):
    """Run every static pass over a graph; returns a :class:`Report`.

    ``config`` (a HetuConfig) refines the passes — pipeline schedule
    selection, microbatch count — but is optional: the passes derive
    staging and statuses from the graph itself. A pass that crashes is
    downgraded to an HT001 warning so one broken analyzer never blocks
    a launch the others would have cleared.
    """
    from ..graph.autodiff import find_topo_sort

    report = Report()
    topo = find_topo_sort(list(eval_node_list))
    if config is not None:
        schedule = schedule or _schedule_of(config)
        num_microbatches = (num_microbatches
                            or getattr(config, "num_microbatches", None))
        if virtual_stages is None:
            virtual_stages = (getattr(config, "pp_options", None)
                              or {}).get("virtual_stages")

    def _guard(name, fn, *a, **kw):
        try:
            return fn(*a, **kw)
        except Exception as e:  # noqa: BLE001 — analysis must not kill a launch
            report.add("HT001", "warn",
                       f"analysis pass {name!r} crashed "
                       f"({type(e).__name__}: {e}) — its findings are "
                       f"incomplete")
            return None

    dtypes = {}
    shapes = _guard("shapes", shape_pass, topo, report,
                    feed_shapes=feed_shapes, dtypes_out=dtypes) or {}
    _guard("lint", lint_pass, topo, report,
           eval_nodes=eval_node_list, extra_roots=extra_roots)
    _guard("sharding", sharding_pass, topo, report, shapes=shapes)
    _guard("numerics", numerics_pass, topo, report, shapes=shapes,
           dtypes=dtypes, feed_shapes=feed_shapes, config=config)
    _guard("deadlock", deadlock_pass, eval_node_list, report,
           schedule=schedule or "gpipe", nprocs=nprocs,
           num_microbatches=num_microbatches,
           virtual_stages=virtual_stages)
    _guard("memory", memory_pass, topo, shapes, report,
           budget=hbm_budget)
    _guard("overlap", overlap_pass, topo, report, config=config)
    # priced performance lint (HT9xx): warn above the ms threshold,
    # info below, never error — slow is advisory at launch time, the
    # zoo CLI (python -m hetu_tpu.analysis.efficiency) owns the gate
    _guard("efficiency", efficiency_pass, topo, report, shapes=shapes,
           dtypes=dtypes, config=config, eval_nodes=eval_node_list,
           extra_roots=extra_roots)
    # PS-backed graphs will drive the native wire protocol: cross-check
    # the C++/ctypes contract (HT701/HT702) before the first RPC. The
    # parse is cached per process, so repeated preflights cost a dict
    # lookup; the full consistency model checker stays on the CLI
    # (python -m hetu_tpu.analysis.protocol).
    def _wire_if_ps():
        from .overlap import _ps_backed
        if _ps_backed(topo):
            from .wire import wire_pass
            wire_pass(report)
    _guard("protocol", _wire_if_ps)
    if frozen:
        _guard("frozen", frozen_graph_pass, topo, report)
    return report


def finish_preflight(report, out_path=None):
    """Terminal preflight action (the ``HETU_PREFLIGHT`` env contract):
    print the report, write JSON when ``out_path`` names a file, and
    exit the process — 0 on a clean graph, :data:`EXIT_PREFLIGHT` when
    errors exist — *before* any fleet/PS machinery spins up."""
    text = report.to_text()
    print(text, file=sys.stderr if report.errors else sys.stdout)
    if out_path and out_path not in ("1", "true"):
        try:
            os.makedirs(os.path.dirname(os.path.abspath(out_path)),
                        exist_ok=True)
            with open(out_path, "w") as f:
                f.write(report.to_json() + "\n")
        except OSError as e:
            print(f"preflight: could not write {out_path}: {e}",
                  file=sys.stderr)
    if report.errors:
        print("preflight: FAILED — fix the errors above before "
              "launching", file=sys.stderr)
        raise SystemExit(EXIT_PREFLIGHT)
    print("preflight: OK")
    raise SystemExit(0)
