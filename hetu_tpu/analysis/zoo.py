"""Preflight targets: every model-zoo graph with canonical feed shapes.

Each builder constructs a small training instance of a zoo model and
returns ``(eval_nodes, feed_shapes)`` — the two arguments
:func:`hetu_tpu.analysis.analyze` needs for a *fully shaped* preflight
(feeds included, so shape propagation covers the whole graph, not just
the parameter-parameter edges). The ``python -m hetu_tpu.analysis``
CLI and the CI preflight job iterate this registry; the zoo staying
error-free under the verifier is a pinned invariant.
"""
from __future__ import annotations

import numpy as np

__all__ = ["ZOO", "build"]

ZOO = {}


def _register(name):
    def deco(fn):
        ZOO[name] = fn
        return fn
    return deco


def build(name):
    """(eval_nodes, feed_shapes) for a registered zoo model."""
    return ZOO[name]()


def _xy(xshape, num_classes=10):
    import hetu_tpu as ht
    x = ht.Variable("x", trainable=False)
    y_ = ht.Variable("y_", trainable=False)
    return x, y_, {x: (tuple(xshape), np.float32),
                   y_: ((xshape[0], num_classes), np.float32)}


def _train(model_fn, xshape, num_classes=10):
    import hetu_tpu as ht
    x, y_, feeds = _xy(xshape, num_classes)
    loss, _y = model_fn(x, y_)
    train_op = ht.optim.SGDOptimizer(learning_rate=0.01).minimize(loss)
    return [loss, train_op], feeds


@_register("logreg")
def _logreg():
    from ..models import logreg
    return _train(logreg, (8, 784))


@_register("mlp")
def _mlp():
    from ..models import mlp
    return _train(mlp, (8, 3072))


@_register("cnn_3_layers")
def _cnn():
    from ..models import cnn_3_layers
    return _train(cnn_3_layers, (4, 784))


@_register("lenet")
def _lenet():
    from ..models import lenet
    return _train(lenet, (4, 784))


@_register("alexnet")
def _alexnet():
    from ..models import alexnet
    return _train(alexnet, (2, 3, 32, 32))


@_register("vgg16")
def _vgg16():
    from ..models import vgg16
    return _train(vgg16, (2, 3, 32, 32))


@_register("resnet18")
def _resnet18():
    from ..models import resnet18
    return _train(resnet18, (2, 3, 32, 32))


@_register("rnn")
def _rnn():
    from ..models import rnn
    return _train(rnn, (4, 784))


@_register("lstm")
def _lstm():
    from ..models import lstm
    return _train(lstm, (4, 784))


@_register("bert_tiny")
def _bert_tiny():
    import hetu_tpu as ht
    from ..models import BertConfig, BertForPreTraining
    bs, sl = 4, 16
    config = BertConfig(
        vocab_size=64, hidden_size=32, num_hidden_layers=2,
        num_attention_heads=4, intermediate_size=64,
        max_position_embeddings=16)
    model = BertForPreTraining(config)
    ids = ht.Variable("input_ids", trainable=False)
    tok = ht.Variable("token_type_ids", trainable=False)
    mask = ht.Variable("attention_mask", trainable=False)
    mlm = ht.Variable("masked_lm_labels", trainable=False)
    nsp = ht.Variable("next_sentence_label", trainable=False)
    _, _, mlm_loss, nsp_loss = model(ids, tok, mask, mlm, nsp)
    loss = ht.reduce_mean_op(mlm_loss, [0, 1]) + \
        ht.reduce_mean_op(nsp_loss, [0])
    train_op = ht.optim.AdamOptimizer(learning_rate=1e-3).minimize(loss)
    feeds = {ids: ((bs, sl), np.int32), tok: ((bs, sl), np.int32),
             mask: ((bs, sl), np.float32), mlm: ((bs, sl), np.int32),
             nsp: ((bs,), np.int32)}
    return [loss, train_op], feeds


@_register("gpt_tiny")
def _gpt_tiny():
    import hetu_tpu as ht
    from ..models import GPTConfig, GPTLMHeadModel
    bs, sl = 2, 16
    cfg = GPTConfig(vocab_size=64, hidden_size=32, num_hidden_layers=2,
                    num_attention_heads=8, max_position_embeddings=sl,
                    hidden_dropout_prob=0.0)
    model = GPTLMHeadModel(cfg)
    ids = ht.Variable("input_ids", trainable=False)
    labels = ht.Variable("labels", trainable=False)
    _logits, loss = model(ids, labels)
    lm = ht.reduce_mean_op(loss, [0, 1])
    train_op = ht.optim.AdamOptimizer(1e-3).minimize(lm)
    return [lm, train_op], {ids: ((bs, sl), np.int32),
                            labels: ((bs, sl), np.int64)}


@_register("wdl_adult")
def _wdl_adult():
    import hetu_tpu as ht
    from ..models.ctr import wdl_adult
    dense = ht.Variable("dense_input", trainable=False)
    sparse = ht.Variable("sparse_input", trainable=False)
    y_ = ht.Variable("y_", trainable=False)
    loss, _y, y_, train_op = wdl_adult(dense, sparse, y_)
    return [loss, train_op], {dense: ((16, 6), np.float32),
                              sparse: ((16, 8), np.int32),
                              y_: ((16, 2), np.float32)}


@_register("ncf")
def _ncf():
    import hetu_tpu as ht
    from ..models import neural_mf
    user = ht.Variable("user_input", trainable=False)
    item = ht.Variable("item_input", trainable=False)
    y_ = ht.Variable("y_", trainable=False)
    loss, _y, train_op = neural_mf(user, item, y_, num_users=50,
                                   num_items=80)
    return [loss, train_op], {user: ((16,), np.int32),
                              item: ((16,), np.int32),
                              y_: ((16, 1), np.float32)}


@_register("gcn")
def _gcn():
    import hetu_tpu as ht
    from ..models import gcn
    n, fdim, ncls = 40, 12, 3
    feat = ht.Variable("feat", trainable=False)
    y_ = ht.Variable("y_", trainable=False)
    mask_ = ht.Variable("mask_", trainable=False)
    norm_adj = ht.Variable("norm_adj", trainable=False)
    loss, _y, train_op = gcn(feat, y_, mask_, norm_adj, fdim, 16, ncls)
    return ([ht.reduce_mean_op(loss, [0]), train_op],
            {feat: ((n, fdim), np.float32), y_: ((n, ncls), np.float32),
             mask_: ((n,), np.float32), norm_adj: ((n, n), np.float32)})
