"""CacheSparseTable — Python facade over the C++ embedding cache
(reference parity: python/hetu/cstable.py:19-211 over the hetu_cache
pybind module).

Policies: LRU / LFU / LFUOpt. Perf counters mirror the reference's
miss-rate helpers (cstable.py:163-187).
"""
from __future__ import annotations

import ctypes

import numpy as np

from .ps.native_lib import as_f32, as_i64, fptr, get_lib, lptr
from .telemetry import health as _health

__all__ = ["CacheSparseTable"]

_POLICIES = {"LRU": 0, "LFU": 1, "LFUOpt": 2}


def _bind(lib):
    if getattr(lib, "_cache_bound", False):
        return lib
    i64 = ctypes.c_int64
    lib.CacheCreate.argtypes = [ctypes.c_int, i64, i64, ctypes.c_int, i64,
                                i64]
    lib.CacheCreate.restype = ctypes.c_int
    lib.CacheDestroy.argtypes = [ctypes.c_int]
    lib.CacheLookup.argtypes = [ctypes.c_int,
                                ctypes.POINTER(i64), i64,
                                ctypes.POINTER(ctypes.c_float)]
    lib.CacheUpdate.argtypes = [ctypes.c_int, ctypes.POINTER(i64),
                                ctypes.POINTER(ctypes.c_float), i64]
    lib.CacheFlush.argtypes = [ctypes.c_int]
    lib.CachePerf.argtypes = [ctypes.c_int, ctypes.c_int]
    lib.CachePerf.restype = ctypes.c_uint64
    lib._cache_bound = True
    return lib


class CacheSparseTable:
    """Bounded-staleness cached view of one PS embedding table."""

    def __init__(self, node_id, length, width, limit, policy="LFUOpt",
                 pull_bound=100, push_bound=100):
        assert policy in _POLICIES, f"unknown cache policy {policy}"
        self.node_id = node_id
        self.length = length
        self.width = int(width)
        self.limit = int(limit)
        self.policy = policy
        self.lib = _bind(get_lib())
        self.handle = self.lib.CacheCreate(
            node_id, self.limit, self.width, _POLICIES[policy],
            int(pull_bound), int(push_bound))
        self.push_bound = int(push_bound)
        # observed-staleness shadow (telemetry/health.py): per-key
        # pending-update counts since the last explicit flush. The C
        # cache also flushes internally on its own bound, so these are
        # an UPPER bound on true staleness — histogram-only, never a
        # trip. Maintained only while a health monitor is live.
        # ``health_monitor`` (stamped by the PS runtime at
        # registration) scopes observations to the owning executor.
        self._upd_pending = {}
        self.health_monitor = None

    def embedding_lookup(self, keys):
        idx = as_i64(keys).ravel()
        out = np.empty((idx.size, self.width), np.float32)
        self.lib.CacheLookup(self.handle, lptr(idx), idx.size, fptr(out))
        if self._upd_pending and (self.health_monitor is not None
                                  or _health.active()):
            uniq = np.unique(idx)
            obs = np.fromiter(
                (self._upd_pending.get(int(i), 0) for i in uniq),
                np.int64, count=len(uniq))
            obs = obs[obs > 0]
            if len(obs):
                _health.observe_staleness("cstable", self.node_id, obs,
                                          self.push_bound,
                                          monitor=self.health_monitor)
        return out.reshape(tuple(np.shape(keys)) + (self.width,))

    def embedding_update(self, keys, grads):
        idx = as_i64(keys).ravel()
        g = as_f32(grads).reshape(idx.size, self.width)
        self.lib.CacheUpdate(self.handle, lptr(idx), fptr(g), idx.size)
        if self.health_monitor is not None or _health.active():
            uniq, counts = np.unique(idx, return_counts=True)
            pend = self._upd_pending
            for i, n in zip(uniq, counts):
                i = int(i)
                pend[i] = pend.get(i, 0) + int(n)
            if len(pend) > (1 << 16):
                pend.clear()     # bound memory; counts restart (approx)

    def flush(self):
        self.lib.CacheFlush(self.handle)
        self._upd_pending.clear()

    # -- perf counters (reference cstable.py:126-187) -------------------
    @property
    def perf(self):
        names = ["hits", "misses", "evicts", "size", "pushed_rows",
                 "pulled_rows"]
        return {n: int(self.lib.CachePerf(self.handle, i))
                for i, n in enumerate(names)}

    def miss_rate(self):
        p = self.perf
        total = p["hits"] + p["misses"]
        return p["misses"] / total if total else 0.0

    def __del__(self):
        try:
            self.lib.CacheDestroy(self.handle)
        except Exception:
            pass
