"""CacheSparseTable — Python facade over the C++ embedding cache
(reference parity: python/hetu/cstable.py:19-211 over the hetu_cache
pybind module).

Policies: LRU / LFU / LFUOpt. Perf counters mirror the reference's
miss-rate helpers (cstable.py:163-187).
"""
from __future__ import annotations

import ctypes

import numpy as np

from .ps.native_lib import as_f32, as_i64, fptr, get_lib, lptr

__all__ = ["CacheSparseTable"]

_POLICIES = {"LRU": 0, "LFU": 1, "LFUOpt": 2}


def _bind(lib):
    if getattr(lib, "_cache_bound", False):
        return lib
    i64 = ctypes.c_int64
    lib.CacheCreate.argtypes = [ctypes.c_int, i64, i64, ctypes.c_int, i64,
                                i64]
    lib.CacheCreate.restype = ctypes.c_int
    lib.CacheDestroy.argtypes = [ctypes.c_int]
    lib.CacheLookup.argtypes = [ctypes.c_int,
                                ctypes.POINTER(i64), i64,
                                ctypes.POINTER(ctypes.c_float)]
    lib.CacheUpdate.argtypes = [ctypes.c_int, ctypes.POINTER(i64),
                                ctypes.POINTER(ctypes.c_float), i64]
    lib.CacheFlush.argtypes = [ctypes.c_int]
    lib.CachePerf.argtypes = [ctypes.c_int, ctypes.c_int]
    lib.CachePerf.restype = ctypes.c_uint64
    lib._cache_bound = True
    return lib


class CacheSparseTable:
    """Bounded-staleness cached view of one PS embedding table."""

    def __init__(self, node_id, length, width, limit, policy="LFUOpt",
                 pull_bound=100, push_bound=100):
        assert policy in _POLICIES, f"unknown cache policy {policy}"
        self.node_id = node_id
        self.length = length
        self.width = int(width)
        self.limit = int(limit)
        self.policy = policy
        self.lib = _bind(get_lib())
        self.handle = self.lib.CacheCreate(
            node_id, self.limit, self.width, _POLICIES[policy],
            int(pull_bound), int(push_bound))

    def embedding_lookup(self, keys):
        idx = as_i64(keys).ravel()
        out = np.empty((idx.size, self.width), np.float32)
        self.lib.CacheLookup(self.handle, lptr(idx), idx.size, fptr(out))
        return out.reshape(tuple(np.shape(keys)) + (self.width,))

    def embedding_update(self, keys, grads):
        idx = as_i64(keys).ravel()
        g = as_f32(grads).reshape(idx.size, self.width)
        self.lib.CacheUpdate(self.handle, lptr(idx), fptr(g), idx.size)

    def flush(self):
        self.lib.CacheFlush(self.handle)

    # -- perf counters (reference cstable.py:126-187) -------------------
    @property
    def perf(self):
        names = ["hits", "misses", "evicts", "size", "pushed_rows",
                 "pulled_rows"]
        return {n: int(self.lib.CachePerf(self.handle, i))
                for i, n in enumerate(names)}

    def miss_rate(self):
        p = self.perf
        total = p["hits"] + p["misses"]
        return p["misses"] / total if total else 0.0

    def __del__(self):
        try:
            self.lib.CacheDestroy(self.handle)
        except Exception:
            pass
