"""Parameter initializers.

Reference parity: python/hetu/initializers.py — constant/zeros/ones/
uniform/normal/truncated_normal/xavier (glorot)/he (kaiming)/lecun
variants, each returning a Variable whose value materializes at executor
setup. (The reference can also initialize directly on the PS server,
PSFHandle.h:277-342; our PS client mirrors that with ParamInit requests.)
"""
from __future__ import annotations

import numpy as np

__all__ = [
    "BaseInit", "ConstantInit", "ZerosInit", "OnesInit", "UniformInit",
    "NormalInit", "TruncatedNormalInit", "XavierNormalInit",
    "XavierUniformInit", "HeNormalInit", "HeUniformInit", "LecunNormalInit",
    "LecunUniformInit", "constant", "zeros", "ones", "random_uniform",
    "random_normal", "truncated_normal", "xavier_normal", "xavier_uniform",
    "he_normal", "he_uniform", "lecun_normal", "lecun_uniform",
    "GenEmpty", "GenConstant",
]


def _fans(shape):
    shape = tuple(shape)
    if len(shape) < 1:
        return 1, 1
    if len(shape) == 1:
        return shape[0], shape[0]
    receptive = int(np.prod(shape[2:])) if len(shape) > 2 else 1
    fan_in = shape[-2] * receptive if len(shape) == 2 else shape[1] * receptive
    fan_out = shape[-1] * receptive if len(shape) == 2 else shape[0] * receptive
    return fan_in, fan_out


class BaseInit:
    def __init__(self, shape):
        self.shape = tuple(shape)

    def init_numpy(self, seed=0):
        raise NotImplementedError

    def dist_spec(self):
        """(init_kind, a, b) for on-server initialization (the PS server
        mirrors reference PSFHandle.h:277-342). init_kind: 0=constant(a),
        1=uniform(a,b), 2=normal(mean=a, std=b), 3=truncated normal."""
        return None

    def interval(self):
        """Static (lo, hi) bound on the initial draw — the numerics
        verifier's interval seed (analysis/numerics.py). Constants and
        uniforms are exact; normals are bounded at mean ± 4σ (the draw
        escapes with probability < 1e-4 per element — the verifier
        widens trainable seeds for training drift anyway) and truncated
        normals at their hard ± 2σ clip. None when unknown."""
        spec = self.dist_spec()
        if spec is None:
            return None
        kind, a, b = spec
        if kind == 0:
            return (a, a)
        if kind == 1:
            return (a, b)
        if kind == 2:
            return (a - 4.0 * b, a + 4.0 * b)
        if kind == 3:
            return (a - 2.0 * b, a + 2.0 * b)
        return None

    def __call__(self, name, trainable=True, dtype=np.float32, ctx=None):
        from .ops.variable import placeholder_op
        return placeholder_op(name, value=None, initializer=self,
                              trainable=trainable, dtype=dtype, ctx=ctx)


class ConstantInit(BaseInit):
    def __init__(self, constant, shape):
        super().__init__(shape)
        self.constant = constant

    def init_numpy(self, seed=0):
        return np.full(self.shape, self.constant, dtype=np.float32)

    def dist_spec(self):
        return (0, float(self.constant), 0.0)


class ZerosInit(ConstantInit):
    def __init__(self, shape):
        super().__init__(0.0, shape)


class OnesInit(ConstantInit):
    def __init__(self, shape):
        super().__init__(1.0, shape)


class UniformInit(BaseInit):
    def __init__(self, shape, minval=-0.05, maxval=0.05):
        super().__init__(shape)
        self.minval = minval
        self.maxval = maxval

    def init_numpy(self, seed=0):
        rng = np.random.RandomState(seed)
        return rng.uniform(self.minval, self.maxval,
                           self.shape).astype(np.float32)

    def dist_spec(self):
        return (1, float(self.minval), float(self.maxval))


class NormalInit(BaseInit):
    def __init__(self, shape, mean=0.0, stddev=0.05):
        super().__init__(shape)
        self.mean = mean
        self.stddev = stddev

    def init_numpy(self, seed=0):
        rng = np.random.RandomState(seed)
        return rng.normal(self.mean, self.stddev,
                          self.shape).astype(np.float32)

    def dist_spec(self):
        return (2, float(self.mean), float(self.stddev))


class TruncatedNormalInit(BaseInit):
    def __init__(self, shape, mean=0.0, stddev=0.05):
        super().__init__(shape)
        self.mean = mean
        self.stddev = stddev

    def init_numpy(self, seed=0):
        rng = np.random.RandomState(seed)
        out = rng.normal(self.mean, self.stddev, self.shape)
        # resample outside 2 sigma (curand-style truncation,
        # src/ops/Initializers.cu)
        for _ in range(8):
            bad = np.abs(out - self.mean) > 2 * self.stddev
            if not bad.any():
                break
            out[bad] = rng.normal(self.mean, self.stddev, bad.sum())
        np.clip(out, self.mean - 2 * self.stddev,
                self.mean + 2 * self.stddev, out=out)
        return out.astype(np.float32)

    def dist_spec(self):
        return (3, float(self.mean), float(self.stddev))


class _VarianceScaling(BaseInit):
    scale_mode = "fan_avg"
    distribution = "normal"
    gain = 1.0

    def __init__(self, shape, gain=None):
        super().__init__(shape)
        if gain is not None:
            self.gain = gain

    def init_numpy(self, seed=0):
        fan_in, fan_out = _fans(self.shape)
        denom = {"fan_in": fan_in, "fan_out": fan_out,
                 "fan_avg": (fan_in + fan_out) / 2}[self.scale_mode]
        rng = np.random.RandomState(seed)
        if self.distribution == "normal":
            std = self.gain * np.sqrt(1.0 / denom)
            return rng.normal(0.0, std, self.shape).astype(np.float32)
        limit = self.gain * np.sqrt(3.0 / denom)
        return rng.uniform(-limit, limit, self.shape).astype(np.float32)

    def dist_spec(self):
        fan_in, fan_out = _fans(self.shape)
        denom = {"fan_in": fan_in, "fan_out": fan_out,
                 "fan_avg": (fan_in + fan_out) / 2}[self.scale_mode]
        if self.distribution == "normal":
            return (2, 0.0, float(self.gain * np.sqrt(1.0 / denom)))
        limit = float(self.gain * np.sqrt(3.0 / denom))
        return (1, -limit, limit)


class XavierNormalInit(_VarianceScaling):
    scale_mode, distribution = "fan_avg", "normal"


class XavierUniformInit(_VarianceScaling):
    scale_mode, distribution = "fan_avg", "uniform"


class HeNormalInit(_VarianceScaling):
    scale_mode, distribution, gain = "fan_in", "normal", np.sqrt(2.0)


class HeUniformInit(_VarianceScaling):
    scale_mode, distribution, gain = "fan_in", "uniform", np.sqrt(2.0)


class LecunNormalInit(_VarianceScaling):
    scale_mode, distribution = "fan_in", "normal"


class LecunUniformInit(_VarianceScaling):
    scale_mode, distribution = "fan_in", "uniform"


# -- reference-named convenience builders (initializers.py:203-295) ---------

def constant(shape, fill_value=0.0, name="constant_var", trainable=True,
             dtype=np.float32, ctx=None):
    return ConstantInit(fill_value, shape)(name, trainable, dtype, ctx)


def zeros(shape, name="zeros_var", trainable=True, dtype=np.float32,
          ctx=None):
    return ZerosInit(shape)(name, trainable, dtype, ctx)


def ones(shape, name="ones_var", trainable=True, dtype=np.float32, ctx=None):
    return OnesInit(shape)(name, trainable, dtype, ctx)


def random_uniform(shape, minval=-0.05, maxval=0.05, name="uniform_var",
                   trainable=True, dtype=np.float32, ctx=None):
    return UniformInit(shape, minval, maxval)(name, trainable, dtype, ctx)


def random_normal(shape, mean=0.0, stddev=0.05, name="normal_var",
                  trainable=True, dtype=np.float32, ctx=None):
    return NormalInit(shape, mean, stddev)(name, trainable, dtype, ctx)


def truncated_normal(shape, mean=0.0, stddev=0.05,
                     name="truncated_normal_var", trainable=True,
                     dtype=np.float32, ctx=None):
    return TruncatedNormalInit(shape, mean, stddev)(name, trainable, dtype,
                                                    ctx)


def xavier_normal(shape, gain=1.0, name="xavier_normal_var", trainable=True,
                  dtype=np.float32, ctx=None):
    return XavierNormalInit(shape, gain)(name, trainable, dtype, ctx)


def xavier_uniform(shape, gain=1.0, name="xavier_uniform_var",
                   trainable=True, dtype=np.float32, ctx=None):
    return XavierUniformInit(shape, gain)(name, trainable, dtype, ctx)


def he_normal(shape, name="he_normal_var", trainable=True, dtype=np.float32,
              ctx=None):
    return HeNormalInit(shape)(name, trainable, dtype, ctx)


def he_uniform(shape, name="he_uniform_var", trainable=True,
               dtype=np.float32, ctx=None):
    return HeUniformInit(shape)(name, trainable, dtype, ctx)


def lecun_normal(shape, name="lecun_normal_var", trainable=True,
                 dtype=np.float32, ctx=None):
    return LecunNormalInit(shape)(name, trainable, dtype, ctx)


def lecun_uniform(shape, name="lecun_uniform_var", trainable=True,
                  dtype=np.float32, ctx=None):
    return LecunUniformInit(shape)(name, trainable, dtype, ctx)


# aliases used by some reference examples
GenEmpty = ZerosInit
GenConstant = ConstantInit
