"""Parallelism subsystems.

TPU-native replacements for the reference's planner/executors
(context.py:256-726, executor.py:457-1337). Submodules land milestone by
milestone:

  * ``planner``  — NodeStatus propagation from ``ht.dispatch`` markers,
                   lowered to PartitionSpec sharding constraints (TP).
  * ``mesh``     — device-mesh construction helpers (dp/tp/pp/sp axes).
  * ``pipeline`` — GPipe and PipeDream(1F1B) pipeline executors, incl.
                   the interleaved (virtual-stage) schedule helpers.
  * ``autoplan`` — cost-model auto-parallelism planner: declarative
                   rules tables compiled to Dispatch specs, candidate
                   (dp, tp, pp, M, V) plans scored on the measured
                   CostDB (``Executor(parallel="auto")``).
  * ``ring``     — ring attention / sequence parallelism (new capability,
                   absent in the reference — SURVEY.md §5).
"""
from .mesh import build_mesh, factorized_axes, mesh_for_statuses
from .planner import assign_states, spec_for_status
