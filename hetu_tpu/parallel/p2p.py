"""Host-mediated pipeline boundary transport between worker processes.

Reference parity: PipelineSendOp/PipelineReceiveOp move stage boundaries
over NCCL p2p (reference gpu_ops/PipelineSend.py:8-74,
mpi_nccl_communication.cu:166-230). On TPU pods, in-process stage
boundaries ride ICI via device placement; when stages span *worker
processes* (pods/hosts), the boundary crosses DCN — here a direct TCP
channel carrying numpy buffers between the owning hosts, the same
host-mediated role the reference's vans play for PS traffic.

Addressing: rank k listens on ``HETU_PIPE_HOSTS[k] : HETU_PIPE_BASE_PORT
+ k`` (launcher-exported; defaults cover the single-machine case).
Messages are tagged; ``recv(tag)`` blocks until a matching message
arrives, so the pipeline's data dependencies double as cross-process
synchronization — no separate barrier protocol.

Flow control (VERDICT r4 weak #2): the inbox is bounded at
``HETU_PIPE_MAX_BUF_MB`` (default 256). When a slow consumer lets the
buffer fill, reader threads stop draining their sockets, so TCP's own
window pushes back on the sender — host RSS stays bounded instead of
growing with every in-flight boundary tensor. Large payloads stream
from the array's buffer in 4MB chunks (no whole-message copy on send).
"""
from __future__ import annotations

import os
import socket
import struct
import threading
from collections import deque

import numpy as np

from .. import telemetry as _telemetry

__all__ = ["PipeChannel", "get_channel"]

_MAGIC = 0x48503250  # "HP2P"
_HDR = struct.Struct("<IHHQ")  # magic, taglen, dtypelen, payload bytes
_CHUNK = 4 << 20


class PipeChannel:
    def __init__(self, rank, nprocs):
        self.rank = rank
        self.nprocs = nprocs
        hosts = os.environ.get(
            "HETU_PIPE_HOSTS",
            ",".join(["127.0.0.1"] * nprocs)).split(",")
        base = int(os.environ.get("HETU_PIPE_BASE_PORT", "19500"))
        self.addrs = [(hosts[i % len(hosts)], base + i)
                      for i in range(nprocs)]
        self._inbox = {}          # tag -> deque[np.ndarray]
        self._mu = threading.Lock()
        self._cv = threading.Condition(self._mu)
        self._buffered = 0        # inbox bytes (flow-control accounting)
        self._wanted = set()      # tags an active recv() is blocked on
        self._sending = 0         # sends in flight (see backpressure)
        self.max_buffered = int(os.environ.get(
            "HETU_PIPE_MAX_BUF_MB", "256")) << 20
        self._out = {}            # dst rank -> (socket, send lock)
        self._out_mu = threading.Lock()   # guards the MAP only
        self._closing = False
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR,
                                  1)
        self._listener.bind(("0.0.0.0", self.addrs[rank][1]))
        self._listener.listen(8)
        self._accept_thread = threading.Thread(target=self._accept_loop,
                                               daemon=True)
        self._accept_thread.start()

    # -- receive side ----------------------------------------------------
    def _accept_loop(self):
        while not self._closing:
            try:
                conn, _ = self._listener.accept()
            except OSError:
                return
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            threading.Thread(target=self._conn_loop, args=(conn,),
                             daemon=True).start()

    def _read_full(self, conn, n):
        buf = bytearray(n)
        view = memoryview(buf)
        got = 0
        while got < n:
            r = conn.recv_into(view[got:], n - got)
            if r == 0:
                return None
            got += r
        return bytes(buf)

    def _conn_loop(self, conn):
        with conn:
            while True:
                hdr = self._read_full(conn, _HDR.size)
                if hdr is None:
                    return
                magic, taglen, dtlen, nbytes = _HDR.unpack(hdr)
                if magic != _MAGIC:
                    return
                meta = self._read_full(conn, taglen + dtlen + 4)
                if meta is None:
                    return
                tag = meta[:taglen].decode()
                dtype = np.dtype(meta[taglen:taglen + dtlen].decode())
                ndim = struct.unpack_from("<i", meta, taglen + dtlen)[0]
                dims = self._read_full(conn, 8 * ndim)
                if dims is None and ndim:
                    return
                shape = struct.unpack(f"<{ndim}q", dims) if ndim else ()
                body = self._read_full(conn, nbytes) if nbytes else b""
                if body is None:
                    return
                arr = np.frombuffer(body, dtype=dtype).reshape(shape)
                with self._cv:
                    # backpressure: hold THIS reader (and via unread TCP
                    # bytes, its sender) while the consumer lags — i.e.
                    # while it is neither in recv() nor in send(). While
                    # it is, always admit: a blocked recv's message may
                    # be behind any other message on any connection, and
                    # a consumer blocked in send() (peer's inbox full,
                    # TCP window closed) with its own inbox also at cap
                    # would otherwise deadlock both ranks of a
                    # bidirectional pipeline. The cap thus bounds RSS
                    # exactly in the runaway case (producer far ahead,
                    # consumer busy computing), which is the case that
                    # grows RSS.
                    self._cv.wait_for(
                        lambda: self._buffered < self.max_buffered
                        or self._wanted or self._sending
                        or self._closing)
                    if self._closing:
                        return
                    self._inbox.setdefault(tag, deque()).append(arr)
                    self._buffered += arr.nbytes
                    self._cv.notify_all()

    def recv(self, tag, timeout=None):
        """Block until a message tagged ``tag`` arrives; FIFO per tag.
        Default timeout is HETU_PIPE_TIMEOUT_S (600s — the peer may be
        XLA-compiling its stage block on the first step). With telemetry
        on, the wait is recorded as a ``p2p_recv`` span with the payload
        byte count (the cross-rank half of pipeline-bubble accounting;
        pipeline.py attributes the same wait to its stage)."""
        tel = _telemetry.get_telemetry()
        if not tel.enabled:
            return self._recv(tag, timeout)
        # black box: a recv that never completes is the signature of a
        # dead/diverged peer — the pending flight entry names the tag
        # (and the blackbox CLI names the rank it implies)
        frec = tel.flight_start("p2p", "p2p_recv",
                                peer=self._peer_of_tag(tag), tag=tag)
        t0 = tel.clock()
        arr = self._recv(tag, timeout)
        t1 = tel.clock()
        tel.flight_complete(frec)
        tel.complete("p2p_recv", t0, t1,
                     {"tag": tag, "bytes": int(arr.nbytes)})
        tel.inc("p2p_recv_bytes", int(arr.nbytes))
        tel.observe("p2p_recv_wait_ms", (t1 - t0) / 1e6)
        return arr

    def _peer_of_tag(self, tag):
        """Best-effort peer rank for a recv: in a 2-process fleet the
        sender is unambiguous; beyond that the tag itself is the
        diagnostic and the peer stays unknown (None)."""
        if self.nprocs == 2:
            return 1 - self.rank
        return None

    def _recv(self, tag, timeout=None):
        if timeout is None:
            timeout = float(os.environ.get("HETU_PIPE_TIMEOUT_S", "600"))
        with self._cv:
            self._wanted.add(tag)
            self._cv.notify_all()   # readers holding this tag may admit
            try:
                ok = self._cv.wait_for(
                    lambda: self._inbox.get(tag), timeout=timeout)
            finally:
                self._wanted.discard(tag)
            if not ok:
                raise TimeoutError(
                    f"pipeline recv timed out waiting for '{tag}' on "
                    f"rank {self.rank}")
            q = self._inbox[tag]
            arr = q.popleft()
            if not q:
                del self._inbox[tag]   # tags are step-unique: don't leak
            self._buffered -= arr.nbytes
            self._cv.notify_all()      # wake readers held by backpressure
            return arr

    # -- send side -------------------------------------------------------
    def _conn_to(self, dst):
        """(socket, per-destination send lock) for ``dst``."""
        with self._out_mu:
            ent = self._out.get(dst)
        if ent is not None:
            return ent
        # connect OUTSIDE the map lock (HT603 finding): the 60s retry
        # loop against a not-yet-listening peer must not stall sends to
        # every OTHER rank behind _out_mu
        host, port = self.addrs[dst]
        deadline = 60.0
        import time
        t0 = time.time()
        while True:
            try:
                s = socket.create_connection((host, port), timeout=5)
                break
            except OSError:
                if time.time() - t0 > deadline:
                    raise
                time.sleep(0.1)
        s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        drop = None
        with self._out_mu:
            ent = self._out.get(dst)
            if ent is not None:
                # two senders raced the first connect: keep the socket
                # already in the map (its peer may have received bytes)
                drop = s
            elif self._closing:
                # close() already cleared the map: storing now would
                # leak a socket nothing will ever close
                drop = s
            else:
                ent = self._out[dst] = (s, threading.Lock())
        if drop is not None:
            try:
                drop.close()
            except OSError:
                pass
        if ent is None:
            raise OSError("PipeChannel is closed")
        return ent

    def send(self, dst, tag, arr):
        tel = _telemetry.get_telemetry()
        if not tel.enabled:
            return self._send(dst, tag, arr)
        nbytes = int(getattr(arr, "nbytes", 0))
        frec = tel.flight_start("p2p", "p2p_send", peer=dst, tag=tag,
                                nbytes=nbytes)
        with tel.span("p2p_send", tag=tag, dst=dst, bytes=nbytes):
            self._send(dst, tag, arr)
        tel.flight_complete(frec)
        tel.inc("p2p_send_bytes", nbytes)

    def _send(self, dst, tag, arr):
        arr = np.ascontiguousarray(arr)
        tb = tag.encode()
        db = arr.dtype.str.encode()
        hdr = (_HDR.pack(_MAGIC, len(tb), len(db), arr.nbytes) + tb + db
               + struct.pack("<i", arr.ndim)
               + struct.pack(f"<{arr.ndim}q", *arr.shape))
        view = memoryview(arr).cast("B")
        s, send_lk = self._conn_to(dst)
        with self._cv:
            self._sending += 1
            self._cv.notify_all()   # readers may admit while we send
        try:
            # per-DESTINATION send lock: frames on one socket must not
            # interleave, but a huge boundary tensor to one rank (or
            # its TCP-backpressure stall) must not block sends to every
            # other rank behind a channel-wide lock
            with send_lk:
                s.sendall(hdr)
                # stream the payload from the array's own buffer in
                # chunks: no whole-message copy, and large boundary
                # tensors interleave with TCP flow control instead of
                # one giant blob
                for off in range(0, arr.nbytes, _CHUNK):
                    s.sendall(view[off:off + _CHUNK])
        finally:
            with self._cv:
                self._sending -= 1

    def close(self):
        self._closing = True
        with self._cv:
            self._cv.notify_all()   # release readers held by backpressure
        try:
            self._listener.close()
        except OSError:
            pass
        with self._out_mu:
            for s, _lk in self._out.values():
                try:
                    s.close()
                except OSError:
                    pass
            self._out.clear()


_channel = None
_channel_mu = threading.Lock()


def get_channel():
    """Process-wide channel, built from the launcher env on first use.
    Double-checked: two pipeline runner threads first-touching the
    channel must not both bind the listener (HT605)."""
    global _channel
    if _channel is None:
        with _channel_mu:
            if _channel is None:
                rank = int(os.environ.get("HETU_PROC_ID", "0"))
                nprocs = int(os.environ.get("HETU_NUM_PROCS", "1"))
                _channel = PipeChannel(rank, nprocs)
    return _channel
