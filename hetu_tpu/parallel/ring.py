"""Ring attention — sequence/context parallelism over an ICI ring.

NEW capability, absent in the reference (SURVEY.md §5: no sequence
parallelism anywhere; BERT caps at 512 tokens). The sequence axis shards
over a mesh axis; each device keeps its Q shard resident and rotates K/V
shards around the ring with ``lax.ppermute`` while merging partial
attention with the online-softmax rule — the distributed form of flash
attention. Peak memory per chip is O(S/n · D) and the KV transfers ride
ICI neighbor links, overlapping with the block matmuls.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

__all__ = ["ring_attention", "ring_attention_sharded"]


def ring_attention(q, k, v, axis_name, sm_scale=1.0, mask=None):
    """Per-shard ring attention body (call inside shard_map).

    q, k, v: local shards [B, H, S_local, D] (sequence dim sharded over
    ``axis_name``). mask: optional additive [B, 1, 1, S_local] shard.
    Non-causal (bidirectional-encoder semantics).
    """
    axis_size = lax.psum(1, axis_name)

    def partial_attn(q_, k_, v_, mask_):
        s = jnp.einsum("bhqd,bhkd->bhqk", q_, k_,
                       preferred_element_type=jnp.float32) * sm_scale
        if mask_ is not None:
            s = s + mask_
        m = jnp.max(s, axis=-1, keepdims=True)
        p = jnp.exp(s - m)
        l = jnp.sum(p, axis=-1, keepdims=True)
        o = jnp.einsum("bhqk,bhkd->bhqd", p.astype(v_.dtype), v_)
        return m, l, o.astype(jnp.float32)

    perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]

    def step(i, carry):
        m_acc, l_acc, o_acc, k_cur, v_cur, mask_cur = carry
        m_blk, l_blk, o_blk = partial_attn(q, k_cur, v_cur, mask_cur)
        m_new = jnp.maximum(m_acc, m_blk)
        a_old = jnp.exp(m_acc - m_new)
        a_blk = jnp.exp(m_blk - m_new)
        l_new = l_acc * a_old + l_blk * a_blk
        o_new = o_acc * a_old + o_blk * a_blk
        k_nxt = lax.ppermute(k_cur, axis_name, perm)
        v_nxt = lax.ppermute(v_cur, axis_name, perm)
        mask_nxt = (lax.ppermute(mask_cur, axis_name, perm)
                    if mask_cur is not None else None)
        return m_new, l_new, o_new, k_nxt, v_nxt, mask_nxt

    b, h, s_loc, d = q.shape
    m0 = jnp.full((b, h, s_loc, 1), -1e30, jnp.float32)
    l0 = jnp.zeros((b, h, s_loc, 1), jnp.float32)
    o0 = jnp.zeros((b, h, s_loc, d), jnp.float32)
    carry = (m0, l0, o0, k, v, mask)
    # static python loop: axis_size rotations; each iteration's ppermute
    # overlaps with the next block's matmuls under XLA latency hiding
    for i in range(axis_size):
        carry = step(i, carry)
    _, l, o = carry[0], carry[1], carry[2]
    return (o / l).astype(q.dtype)


def shard_map_qkv(body_fn, q, k, v, mesh, axis_name, mask=None):
    """Shared shard_map plumbing for sequence-parallel attention bodies
    (ring and Ulysses): q/k/v are global [B, H, S, D] with the sequence
    dim sharded over ``axis_name``; the additive key mask shards on its
    last dim. ``body_fn(q, k, v, mask=...)`` runs per shard."""
    from jax.sharding import PartitionSpec as P
    try:
        from jax import shard_map
    except ImportError:                   # older jax
        from jax.experimental.shard_map import shard_map

    spec = P(None, None, axis_name, None)
    mask_spec = P(None, None, None, axis_name)
    if mask is not None:
        body = lambda q_, k_, v_, m_: body_fn(q_, k_, v_, mask=m_)  # noqa: E731
        return shard_map(body, mesh=mesh,
                         in_specs=(spec, spec, spec, mask_spec),
                         out_specs=spec)(q, k, v, mask)
    body = lambda q_, k_, v_: body_fn(q_, k_, v_)                   # noqa: E731
    return shard_map(body, mesh=mesh, in_specs=(spec, spec, spec),
                     out_specs=spec)(q, k, v)


def ring_attention_sharded(q, k, v, mesh, axis_name="sp", sm_scale=1.0,
                           mask=None):
    """shard_map wrapper: q/k/v are global [B, H, S, D]; the sequence dim
    shards over ``axis_name`` of ``mesh`` and the ring runs over ICI."""
    fn = functools.partial(ring_attention, axis_name=axis_name,
                           sm_scale=sm_scale)
    return shard_map_qkv(fn, q, k, v, mesh, axis_name, mask=mask)
