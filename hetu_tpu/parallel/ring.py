"""Ring attention — sequence/context parallelism over an ICI ring.

NEW capability, absent in the reference (SURVEY.md §5: no sequence
parallelism anywhere; BERT caps at 512 tokens). The sequence axis shards
over a mesh axis; each device keeps its Q shard resident and rotates K/V
shards around the ring with ``lax.ppermute`` while merging partial
attention with the online-softmax rule — the distributed form of flash
attention. Peak memory per chip is O(S/n · D) and the KV transfers ride
ICI neighbor links, overlapping with the block matmuls.

Causal (decoder) attention uses the zigzag layout: with a contiguous
sequence split the causal mask leaves device 0 nearly idle and device
n-1 doing n× its share, so instead each device owns chunks ``(r,
2n-1-r)`` of a 2n-chunk split. Every ring step then does exactly half a
block's worth of useful scores on every device — the first-half keys
against both local query chunks when the incoming KV originates earlier
in the sequence, or the full keys against the second query chunk when it
originates later — so the chips stay load-balanced in lockstep
(ring-flash-attention's zigzag schedule, re-derived for ppermute).
"""
from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

__all__ = ["ring_attention", "ring_attention_sharded",
           "zigzag_ring_attention", "zigzag_indices"]


def _partial_attn(q_, k_, v_, bias, sm_scale):
    """One attention block: scores, running max m, normalizer l, and the
    unnormalized output o — the quantities the online-softmax merge
    combines (shared by the non-causal ring, the zigzag causal ring, and
    Ulysses' local blocking in ulysses.py)."""
    s = jnp.einsum("bhqd,bhkd->bhqk", q_, k_,
                   preferred_element_type=jnp.float32) * sm_scale
    if bias is not None:
        s = s + bias
    m = jnp.max(s, axis=-1, keepdims=True)
    p = jnp.exp(s - m)
    l = jnp.sum(p, axis=-1, keepdims=True)
    o = jnp.einsum("bhqk,bhkd->bhqd", p.astype(v_.dtype), v_)
    return m, l, o.astype(jnp.float32)


def _merge(acc, blk):
    """Online-softmax merge of two (m, l, o) partials — the flash
    attention rescale rule."""
    m_acc, l_acc, o_acc = acc
    m_blk, l_blk, o_blk = blk
    m_new = jnp.maximum(m_acc, m_blk)
    a_old = jnp.exp(m_acc - m_new)
    a_blk = jnp.exp(m_blk - m_new)
    return (m_new, l_acc * a_old + l_blk * a_blk,
            o_acc * a_old + o_blk * a_blk)


def ring_attention(q, k, v, axis_name, sm_scale=1.0, mask=None):
    """Per-shard ring attention body (call inside shard_map).

    q, k, v: local shards [B, H, S_local, D] (sequence dim sharded over
    ``axis_name``). mask: optional additive [B, 1, 1, S_local] shard.
    Non-causal (bidirectional-encoder semantics).
    """
    axis_size = lax.psum(1, axis_name)
    perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]

    def step(carry):
        acc, k_cur, v_cur, mask_cur = carry
        acc = _merge(acc, _partial_attn(q, k_cur, v_cur, mask_cur,
                                        sm_scale))
        k_nxt = lax.ppermute(k_cur, axis_name, perm)
        v_nxt = lax.ppermute(v_cur, axis_name, perm)
        mask_nxt = (lax.ppermute(mask_cur, axis_name, perm)
                    if mask_cur is not None else None)
        return acc, k_nxt, v_nxt, mask_nxt

    b, h, s_loc, d = q.shape
    m0 = jnp.full((b, h, s_loc, 1), -1e30, jnp.float32)
    l0 = jnp.zeros((b, h, s_loc, 1), jnp.float32)
    o0 = jnp.zeros((b, h, s_loc, d), jnp.float32)
    carry = ((m0, l0, o0), k, v, mask)
    # static python loop: axis_size rotations; each iteration's ppermute
    # overlaps with the next block's matmuls under XLA latency hiding
    for _ in range(axis_size):
        carry = step(carry)
    _, l, o = carry[0]
    return (o / l).astype(q.dtype)


def shard_map_qkv(body_fn, q, k, v, mesh, axis_name, mask=None):
    """Shared shard_map plumbing for sequence-parallel attention bodies
    (ring and Ulysses): q/k/v are global [B, H, S, D] with the sequence
    dim sharded over ``axis_name``; the additive key mask shards on its
    last dim. ``body_fn(q, k, v, mask=...)`` runs per shard."""
    from jax.sharding import PartitionSpec as P
    from .mesh import shard_map_unchecked

    spec = P(None, None, axis_name, None)
    mask_spec = P(None, None, None, axis_name)
    # unchecked: the causal bodies branch per ring hop (lax.cond), which
    # jax 0.4.x's replication checker rejects inside shard_map
    if mask is not None:
        body = lambda q_, k_, v_, m_: body_fn(q_, k_, v_, mask=m_)  # noqa: E731
        return shard_map_unchecked(body, mesh=mesh,
                                   in_specs=(spec, spec, spec, mask_spec),
                                   out_specs=spec)(q, k, v, mask)
    body = lambda q_, k_, v_: body_fn(q_, k_, v_)                   # noqa: E731
    return shard_map_unchecked(body, mesh=mesh,
                               in_specs=(spec, spec, spec),
                               out_specs=spec)(q, k, v)


def zigzag_indices(s, n):
    """Index permutation mapping the natural sequence order to the zigzag
    shard layout: shard r holds chunks (r, 2n-1-r) of a 2n-chunk split.
    Returns (perm, inv): ``x[perm]`` is zigzag order, ``y[inv]`` undoes it.
    """
    if s % (2 * n):
        raise ValueError(
            f"causal ring needs seq len ({s}) divisible by 2*sp axis "
            f"({2 * n})")
    c = s // (2 * n)
    perm = np.concatenate([
        np.concatenate([np.arange(r * c, (r + 1) * c),
                        np.arange((2 * n - 1 - r) * c, (2 * n - r) * c)])
        for r in range(n)])
    return perm, np.argsort(perm)


def zigzag_ring_attention(q, k, v, axis_name, sm_scale=1.0, mask=None):
    """Causal ring attention body over the zigzag layout (call inside
    shard_map; inputs must already be zigzag-permuted — the sharded
    wrapper below does both permutes).

    q, k, v: local shards [B, H, 2c, D] — chunks (r, 2n-1-r) of the
    2n-chunk global sequence. mask: optional additive [B, 1, 1, 2c]
    key-padding shard (also zigzag order). At step t the KV block from
    src=(r-t)%n is, per the causal order, either entirely earlier than
    both local query chunks in its first half and entirely later in its
    second (src < r: attend q_full x k_first), or straddles so that only
    the second query chunk sees it (src > r: attend q_second x k_full).
    Both branches score 2c*c pairs — every device does identical work
    every step.
    """
    n = lax.psum(1, axis_name)
    r = lax.axis_index(axis_name)
    b, h, s2, d = q.shape
    c = s2 // 2

    def partial_attn(q_, k_, v_, bias):
        return _partial_attn(q_, k_, v_, bias, sm_scale)

    # global positions of the local query rows under the zigzag layout
    ar = jnp.arange(c)
    q_pos = jnp.concatenate([r * c + ar, (2 * n - 1 - r) * c + ar])

    # t = 0: diagonal — causal mask within the local 2-chunk block
    diag_bias = jnp.where(q_pos[:, None] >= q_pos[None, :],
                          0.0, -1e9)[None, None]
    if mask is not None:
        diag_bias = diag_bias + mask
    acc = partial_attn(q, k, v, diag_bias)

    perm = [(i, (i + 1) % n) for i in range(n)]
    k_cur, v_cur, mask_cur = k, v, mask
    neg = jnp.float32(-1e30)
    for t in range(1, n):
        k_cur = lax.ppermute(k_cur, axis_name, perm)
        v_cur = lax.ppermute(v_cur, axis_name, perm)
        if mask_cur is not None:
            mask_cur = lax.ppermute(mask_cur, axis_name, perm)
        src = (r - t) % n

        def earlier(k_, v_, m_):
            # src < r: first KV half precedes both q chunks (fully
            # visible), second half follows both (fully masked — skip).
            bias = None if m_ is None else m_[..., :c]
            return partial_attn(q, k_[:, :, :c], v_[:, :, :c], bias)

        def later(k_, v_, m_):
            # src > r: only the second q chunk (global chunk 2n-1-r)
            # sees this KV block, and sees all of it. Rows of the first
            # q chunk contribute nothing: pad with m=-inf / l,o=0.
            m_blk, l_blk, o_blk = partial_attn(q[:, :, c:], k_, v_, m_)
            pad = jnp.full((b, h, c, 1), neg)
            return (jnp.concatenate([pad, m_blk], axis=2),
                    jnp.concatenate([jnp.zeros((b, h, c, 1)), l_blk],
                                    axis=2),
                    jnp.concatenate([jnp.zeros((b, h, c, d)), o_blk],
                                    axis=2))

        if mask_cur is None:
            blk = lax.cond(src < r,
                           lambda kv: earlier(kv[0], kv[1], None),
                           lambda kv: later(kv[0], kv[1], None),
                           (k_cur, v_cur))
        else:
            blk = lax.cond(src < r,
                           lambda kv: earlier(*kv),
                           lambda kv: later(*kv),
                           (k_cur, v_cur, mask_cur))
        acc = _merge(acc, blk)

    _, l, o = acc
    return (o / l).astype(q.dtype)


def ring_attention_sharded(q, k, v, mesh, axis_name="sp", sm_scale=1.0,
                           mask=None, causal=False):
    """shard_map wrapper: q/k/v are global [B, H, S, D]; the sequence dim
    shards over ``axis_name`` of ``mesh`` and the ring runs over ICI.

    ``causal=True`` routes through the load-balanced zigzag schedule:
    the global arrays are permuted into zigzag order (one resharding
    shuffle — a real ingest pipeline would pre-permute at the loader),
    the causal ring runs, and the output is permuted back.
    """
    if not causal:
        fn = functools.partial(ring_attention, axis_name=axis_name,
                               sm_scale=sm_scale)
        return shard_map_qkv(fn, q, k, v, mesh, axis_name, mask=mask)
    n = mesh.shape[axis_name]
    perm, inv = zigzag_indices(q.shape[2], n)
    qz = jnp.take(q, perm, axis=2)
    kz = jnp.take(k, perm, axis=2)
    vz = jnp.take(v, perm, axis=2)
    maskz = None if mask is None else jnp.take(mask, perm, axis=3)
    fn = functools.partial(zigzag_ring_attention, axis_name=axis_name,
                           sm_scale=sm_scale)
    out = shard_map_qkv(fn, qz, kz, vz, mesh, axis_name, mask=maskz)
    return jnp.take(out, inv, axis=2)
