"""Tensor-parallel planner: NodeStatus propagation → PartitionSpec.

Reference parity: ``assign_context_by_traverse_nodes`` (context.py:256-726)
— there, a NodeStatus per node is realized by rewriting the graph with
split/concat/add ops and NCCL p2p send/recv (cross_send/cross_receive). On
TPU the planner only *annotates*: statuses propagate through the ops'
``deduce_states`` (same tables, e.g. the matmul row/col/k mapping,
MatrixMult.py:88-141), then lower to ``PartitionSpec`` constraints over a
named mesh; XLA's SPMD partitioner materializes every repartition as ICI
collectives. Sharding constraints never change numerics — a status the
planner cannot map is simply left unconstrained (XLA picks a layout), so
parallel runs stay loss-equivalent with single-device runs by
construction, which the reference has to *test* for
(examples/runner/parallel/validate_results.py).
"""
from __future__ import annotations

import logging

import numpy as np

from ..context import NodeStatus
from .mesh import mesh_for_statuses

__all__ = ["assign_states", "spec_for_status"]

logger = logging.getLogger(__name__)


def _prime_factors(n):
    out = []
    d = 2
    while n > 1:
        while n % d == 0:
            out.append(d)
            n //= d
        d += 1 if d == 2 else 2
    return out


def spec_for_status(status, model_axes, node=None):
    """Lower a NodeStatus to a PartitionSpec over prime-factored model
    axes; returns None when the status is unmappable (leave unconstrained).

    Each split dim claims unused axes whose sizes multiply to its split
    count; the duplicate (replica) axis stays unsharded. Dropping a
    *distributed* status is numerically safe (XLA picks a layout) but it
    silently forfeits the memory/compute split the user asked for — so
    it warns, naming the node and status (VERDICT r5 #7).
    """
    from jax.sharding import PartitionSpec
    if status is None or status.state is None or not status.is_dist():
        return PartitionSpec() if status is not None else None
    avail = {name: size for name, size in model_axes.items()}
    spec = []
    for parts in status.state:
        if parts == 1:
            spec.append(None)
            continue
        take = []
        for p in _prime_factors(parts):
            cand = next((n for n, s in avail.items()
                         if s == p and n not in take), None)
            if cand is None:
                # under an active analysis pass this is a structured
                # HT201 finding with node provenance; the bare warning
                # stays as the fallback when analysis is off
                from ..analysis.findings import emit
                msg = (f"TP constraint unmappable: "
                       f"{node if node is not None else '<node>'} "
                       f"wants status {status} but the {parts}-way "
                       f"split has no free mesh axis of size {p} in "
                       f"{dict(model_axes)} — the node would run "
                       f"unconstrained (replicated layout, no "
                       f"memory/compute split)")
                if not emit("HT201", "error", msg, node=node):
                    logger.warning("%s", msg)
                return None
            take.append(cand)
        del_names = list(take)
        for n in del_names:
            avail.pop(n, None)
        spec.append(tuple(take) if len(take) > 1 else take[0])
    while spec and spec[-1] is None:
        spec.pop()
    return PartitionSpec(*spec)


def propagate_statuses(topo, sweeps=3):
    """Seed statuses from DispatchOp markers and propagate through
    ``deduce_states`` in topo order to a fixpoint.

    Returns the node -> NodeStatus dict (empty when no dispatch present).
    Mesh-independent: callers lower the statuses to specs over whatever
    mesh fits their device set (global for SPMD, per-stage for PP+TP).
    """
    from ..ops.comm import DispatchOp, DispatchGradientOp
    from ..ops.variable import PlaceholderOp

    dispatch_ops = [n for n in topo if isinstance(n, DispatchOp)]
    if not dispatch_ops:
        return {}

    status = {}
    for d in dispatch_ops:
        st = d.target_status()
        status[d] = st
        # a parameter feeding a dispatch is stored sharded (the TP memory
        # win — reference Variable.reshape_in_mp slices it per device,
        # Variable.py:82-108; here device_put with the spec shards it)
        if isinstance(d.inputs[0], PlaceholderOp):
            status[d.inputs[0]] = st

    # forward propagation to a fixpoint: ops without an explicit rule use
    # the elementwise default (Op.deduce_states)
    for _ in range(sweeps):
        changed = False
        for node in topo:
            if node in status and isinstance(
                    node, (DispatchOp, PlaceholderOp)):
                continue
            in_sts = [status.get(i) for i in node.inputs]
            if all(s is None for s in in_sts):
                continue
            st = NodeStatus()
            try:
                node.deduce_states(
                    [NodeStatus.from_other(s) if s is not None else None
                     for s in in_sts], st, False)
            except Exception as e:
                # the node stays unconstrained (numerics unaffected — XLA
                # picks a layout) but a broken rule must not be silent:
                # structured HT202 under an analysis pass, warning else
                from ..analysis.findings import emit
                msg = (f"deduce_states failed for {node} "
                       f"({type(e).__name__}: {e}) — conflicting or "
                       f"malformed input partition statuses; the node "
                       f"runs unconstrained")
                if not emit("HT202", "error", msg, node=node):
                    logger.warning("%s", msg)
                continue
            if st.state is None:
                continue
            if st.duplicate is None or st.order is None:
                st.get_default()
            if status.get(node) != st:
                status[node] = st
                changed = True
        if not changed:
            break

    # gradient side: DispatchGradientOp mirrors its forward input's status
    for node in topo:
        if isinstance(node, DispatchGradientOp) and \
                node.forward_input in status:
            status[node] = status[node.forward_input]
    return status


def assign_states(eval_node_list, config):
    """Whole-graph planning for the SPMD executor: propagate statuses,
    build the mesh, assign specs.

    Fills ``config.node_status`` (node -> NodeStatus) and
    ``config.node_spec`` (node -> PartitionSpec); sets ``config.mesh``
    and ``config.model_axes`` when TP is present.
    """
    from ..graph.autodiff import find_topo_sort

    topo = find_topo_sort(eval_node_list)
    status = propagate_statuses(topo)
    if not status or not any(
            st is not None and st.is_dist() for st in status.values()):
        # only degenerate (1,1) dispatches: nothing is actually split —
        # an empty mesh would poison every constraint site
        return False

    # mesh + specs
    dp = config.nrank if config.mesh is not None and \
        "dp" in getattr(config.mesh, "axis_names", ()) else 1
    mesh, model_axes = mesh_for_statuses(status.values(), dp=dp)
    config.mesh = mesh
    config.model_axes = model_axes
    config.node_status = status
    config.node_spec = {}
    for node, st in status.items():
        spec = spec_for_status(st, model_axes, node=node)
        if spec is not None:
            config.node_spec[node] = spec
    return True
