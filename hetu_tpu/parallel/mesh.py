"""Device-mesh construction helpers.

Reference counterpart: DeviceGroup device lists + NodeStatus device-order
algebra (context.py:7-193). On TPU the physical topology is expressed once
as a named ``jax.sharding.Mesh``; every parallelism axis (dp/tp/pp/sp) is a
mesh axis and all communication lowers to XLA collectives over ICI.
"""
from __future__ import annotations

import numpy as np

__all__ = ["build_mesh", "factorized_axes", "mesh_for_statuses"]


def build_mesh(axis_sizes, devices=None):
    """Mesh from an {axis_name: size} dict (insertion order = major→minor).

    >>> build_mesh({"dp": 2, "tp": 4})   # 8 devices
    """
    import jax
    from jax.sharding import Mesh
    names = list(axis_sizes)
    sizes = [axis_sizes[n] for n in names]
    need = int(np.prod(sizes)) if sizes else 1
    if devices is None:
        devices = jax.devices()
    assert len(devices) >= need, \
        f"mesh {axis_sizes} needs {need} devices, have {len(devices)}"
    arr = np.asarray(devices[:need]).reshape(sizes)
    return Mesh(arr, axis_names=tuple(names))


def factorized_axes(n, prefix="tp"):
    """Factor n into prime-power axes, largest first — a mesh that can
    express any split whose per-dim factors multiply subsets of these.

    >>> factorized_axes(8) -> {"tp0": 2, "tp1": 2, "tp2": 2}
    """
    axes = {}
    i = 0
    d = 2
    while n > 1:
        while n % d == 0:
            axes[f"{prefix}{i}"] = d
            n //= d
            i += 1
        d += 1 if d == 2 else 2
    return axes


def mesh_for_statuses(statuses, dp=1, devices=None):
    """Build a mesh able to express every NodeStatus in ``statuses``.

    The model axes come from prime-factorizing the max TP degree; an
    optional leading "dp" axis carries data parallelism. Returns
    (mesh, model_axes) where model_axes is the {name: size} dict of the
    TP axes (used by the planner's spec assignment).
    """
    tp_degree = 1
    for st in statuses:
        if st is not None and st.state is not None:
            tp_degree = max(tp_degree,
                            int(np.prod([s for s in st.state])))
    model_axes = factorized_axes(tp_degree)
    axes = {}
    if dp > 1:
        axes["dp"] = dp
    axes.update(model_axes)
    return build_mesh(axes, devices), model_axes
