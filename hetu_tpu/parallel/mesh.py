"""Device-mesh construction helpers.

Reference counterpart: DeviceGroup device lists + NodeStatus device-order
algebra (context.py:7-193). On TPU the physical topology is expressed once
as a named ``jax.sharding.Mesh``; every parallelism axis (dp/tp/pp/sp) is a
mesh axis and all communication lowers to XLA collectives over ICI.
"""
from __future__ import annotations

import numpy as np

__all__ = ["build_mesh", "factorized_axes", "mesh_for_statuses",
           "shard_map_unchecked"]


def shard_map_unchecked(f, mesh, in_specs, out_specs):
    """shard_map with the static replication checker off: jax 0.4.x's
    check_rep rejects ``lax.cond``/``lax.switch`` branches inside
    shard_map with "mismatched replication types" even when every
    branch's outputs are device-varying (its own error text recommends
    this workaround; jax versions with ``lax.pvary`` renamed the flag
    to check_vma). Numerics are unaffected — the flag gates a static
    check and a transpose optimization, not the computation."""
    import inspect
    try:
        from jax import shard_map
    except ImportError:                   # older jax
        from jax.experimental.shard_map import shard_map
    try:
        params = inspect.signature(shard_map).parameters
    except (TypeError, ValueError):
        params = {}
    kw = {}
    if "check_rep" in params:
        kw["check_rep"] = False
    elif "check_vma" in params:
        kw["check_vma"] = False
    return shard_map(f, mesh=mesh, in_specs=in_specs,
                     out_specs=out_specs, **kw)


def build_mesh(axis_sizes, devices=None):
    """Mesh from an {axis_name: size} dict (insertion order = major→minor).

    >>> build_mesh({"dp": 2, "tp": 4})   # 8 devices
    """
    import jax
    from jax.sharding import Mesh
    names = list(axis_sizes)
    sizes = [axis_sizes[n] for n in names]
    need = int(np.prod(sizes)) if sizes else 1
    if devices is None:
        devices = jax.devices()
    assert len(devices) >= need, \
        f"mesh {axis_sizes} needs {need} devices, have {len(devices)}"
    arr = np.asarray(devices[:need]).reshape(sizes)
    return Mesh(arr, axis_names=tuple(names))


def factorized_axes(n, prefix="tp"):
    """Factor n into prime-power axes, largest first — a mesh that can
    express any split whose per-dim factors multiply subsets of these.

    >>> factorized_axes(8) -> {"tp0": 2, "tp1": 2, "tp2": 2}
    """
    axes = {}
    i = 0
    d = 2
    while n > 1:
        while n % d == 0:
            axes[f"{prefix}{i}"] = d
            n //= d
            i += 1
        d += 1 if d == 2 else 2
    return axes


def mesh_for_statuses(statuses, dp=1, devices=None):
    """Build a mesh able to express every NodeStatus in ``statuses``.

    The model axes come from prime-factorizing the max TP degree; an
    optional leading "dp" axis carries data parallelism. Returns
    (mesh, model_axes) where model_axes is the {name: size} dict of the
    TP axes (used by the planner's spec assignment).
    """
    tp_degree = 1
    for st in statuses:
        if st is not None and st.state is not None:
            tp_degree = max(tp_degree,
                            int(np.prod([s for s in st.state])))
    model_axes = factorized_axes(tp_degree)
    axes = {}
    if dp > 1:
        axes["dp"] = dp
    axes.update(model_axes)
    return build_mesh(axes, devices), model_axes
