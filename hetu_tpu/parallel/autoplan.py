"""Cost-model auto-parallelism planner (ROADMAP item 4's endpoint).

Hand-written parallel configs — per-node ``ht.dispatch`` specs, stage
contexts, microbatch counts — become one declarative call::

    exe = Executor([loss, train_op], parallel="auto",
                   rules={"out": "tp", "vocab": "tp", "embed": None})

The planner:

1. **enumerates candidates** — factorizations of the world size into
   ``(dp, tp, pp)`` mesh shapes, pruned against the graph (a tp that
   divides no rule-splittable parameter dim is invalid; a pp deeper
   than the graph's cuttable layer chain is invalid),
2. **compiles the rules table** down to the existing partition-state
   machinery: ``rules={logical_axis: mesh_axis|None}`` (the
   Alpa/GSPMD ``DEFAULT_RULES`` idiom, SNIPPETS.md [2]/[3]) maps each
   parameter's inferred logical axes onto per-dim split counts — i.e.
   exactly the ``Dispatch`` specs ``parallel/planner.py`` already
   lowers through ``propagate_statuses`` / ``spec_for_status``; a
   hand-written Dispatch that contradicts the compiled rule is an
   HT205 finding (plan-vs-rules conflict),
3. **scores each candidate** with a closed-form cost model built on
   PR 8's measured :class:`~hetu_tpu.telemetry.costdb.CostDB`:

   * compute from per-op DB entries (``profile_ops`` populated),
     FLOPs-proportional fallback on a miss — calibrated against the
     ops the DB *did* measure, cold-start ``cold_start_flops_ms``
     when it measured none;
   * comm from the DB's latency+bandwidth curves applied to the dp
     gradient-allreduce bytes, the implicit-reshard byte volumes the
     HT203 sharding pass computes, and the pipeline boundary bytes;
   * pipeline bubble from the schedule's analytic fill/drain fraction
     (``pipeline.analytic_bubble_fraction`` — the interleaved V>1
     form included), with per-tick overhead from the p2p latency
     curve, which is what auto-picks M, V and fuse_ticks;

4. **optionally refines** the top-k finalists by measurement through
   the ``tune/autotune.py`` engine (same thread-safe sweep-once
   cache, keyed ``platform|autoplan|model|nworld`` — deterministic
   under ``HETU_AUTOTUNE=1`` with a warm cache), and
5. **applies** the winner: Dispatch markers spliced for tp, stage
   contexts assigned over the balanced per-op-cost cut for pp, and
   the executor kwargs (schedule, M, ``pp_options``) returned.

Estimates are labeled ``measured`` / ``curve`` / ``cold_start`` per
input (``CostDB.coverage``), and the report — printed by
``heturun --autoplan`` — carries the split, so a ranking that rests on
guesses says so on its face.
"""
from __future__ import annotations

import logging

import numpy as np

__all__ = ["DEFAULT_RULES", "Plan", "AutoPlanResult", "logical_axes_of",
           "compile_rules", "apply_rules", "enumerate_candidates",
           "balance_stages", "graph_costs", "score_plan", "choose_plan",
           "apply_plan", "plan_key"]

logger = logging.getLogger(__name__)

# the exemplar rules shape (SNIPPETS.md [2]/[3]): logical axis -> mesh
# axis or None (replicated). "dp"/"tp" are the planner's mesh axes; a
# model can extend the vocabulary via Variable(..).logical_axes.
DEFAULT_RULES = {
    "batch": "dp",      # feed batch dim (data parallelism)
    "in": None,         # matmul contraction dim: replicated
    "out": "tp",        # matmul output features: column-split
    "vocab": "tp",      # embedding rows / output vocab
    "embed": None,      # embedding width / bias dims
    "cout": "tp",       # conv output channels
}

_M_CANDIDATES = (2, 4, 8, 16, 32)
_V_CANDIDATES = (1, 2, 4)
_TRAIN_FLOP_FACTOR = 3.0    # fwd + ~2x fwd for the backward
# at Executor construction feeds are unshaped, so activation shapes are
# unknown; weight-touching ops then assume this batch for their FLOPs
# (pass feed_shapes / autoplan_options={"feed_shapes": ...} for exact
# numbers — ranking only needs relative mass, which params dominate)
_DEFAULT_BATCH = 32


class Plan:
    """One candidate parallel configuration plus its predicted cost."""

    __slots__ = ("dp", "tp", "pp", "M", "V", "fuse_ticks", "schedule",
                 "stage_cut", "predicted_ms", "measured_ms",
                 "breakdown", "bindings", "rules", "notes")

    def __init__(self, dp=1, tp=1, pp=1, M=1, V=1, fuse_ticks=1,
                 schedule="spmd", stage_cut=(), predicted_ms=None,
                 breakdown=None, bindings=(), rules=None, notes=()):
        self.dp, self.tp, self.pp = int(dp), int(tp), int(pp)
        self.M, self.V = int(M), int(V)
        self.fuse_ticks = int(fuse_ticks)
        self.schedule = schedule
        self.stage_cut = tuple(stage_cut)
        self.predicted_ms = predicted_ms
        self.measured_ms = None
        self.breakdown = dict(breakdown or {})
        self.bindings = tuple(bindings)
        self.rules = dict(rules) if rules is not None else None
        self.notes = tuple(notes)

    @property
    def nworld(self):
        return self.dp * self.tp * self.pp

    def key(self):
        return (self.dp, self.tp, self.pp, self.M, self.V,
                self.fuse_ticks)

    def describe(self):
        s = f"dp{self.dp}·tp{self.tp}·pp{self.pp}"
        if self.pp > 1:
            s += f" {self.schedule} M={self.M}"
            if self.V > 1:
                s += f" V={self.V}"
            if self.fuse_ticks > 1:
                s += f" fuse={self.fuse_ticks}"
        return s

    def to_dict(self):
        return {"dp": self.dp, "tp": self.tp, "pp": self.pp,
                "M": self.M, "V": self.V, "fuse_ticks": self.fuse_ticks,
                "schedule": self.schedule, "stage_cut": list(self.stage_cut),
                "predicted_ms": self.predicted_ms,
                "measured_ms": self.measured_ms,
                "breakdown": self.breakdown, "notes": list(self.notes)}

    def __repr__(self):
        return f"Plan({self.describe()}, predicted={self.predicted_ms})"


def plan_key(plan):
    """Stable string form of a plan's knobs — the CI snapshot unit and
    the autotune-refinement candidate id."""
    return "dp{}-tp{}-pp{}-M{}-V{}-f{}".format(*plan.key())


# ---------------------------------------------------------------------------
# rules -> Dispatch specs
# ---------------------------------------------------------------------------

def logical_axes_of(param, topo):
    """Per-dim logical axis names of a trainable parameter: an explicit
    ``param.logical_axes`` wins; otherwise inferred from the consuming
    op (the same classification the TP examples hand-annotate): matmul
    weights are ("in", "out"), embedding tables ("vocab", "embed"),
    conv filters ("cout", "cin", "kh", "kw"), 1-D params ("embed",)."""
    explicit = getattr(param, "logical_axes", None)
    if explicit:
        return tuple(explicit)
    from ..ops.comm import DispatchOp
    from ..ops.embedding import EmbeddingLookUp
    from ..ops.linalg import MatMulOp, BatchMatMulOp
    try:
        from ..ops.conv import Conv2dOp
    except ImportError:         # pragma: no cover - conv always present
        Conv2dOp = ()
    ndim = len(getattr(param, "shape", ()) or ())
    # see through hand Dispatch wrappers: the classifying consumer of
    # dispatch(param, ...) is the param's consumer
    alias = {param}
    for node in topo:
        if isinstance(node, DispatchOp) and node.inputs \
                and node.inputs[0] in alias:
            alias.add(node)

    def feeds(node, pos=None):
        ins = getattr(node, "inputs", ())
        if pos is not None:
            return len(ins) > pos and ins[pos] in alias
        return any(i in alias for i in ins)

    for node in topo:
        if not feeds(node):
            continue
        if isinstance(node, EmbeddingLookUp) and feeds(node, 0):
            return ("vocab", "embed")
        if isinstance(node, (MatMulOp, BatchMatMulOp)) \
                and feeds(node, 1) and ndim == 2:
            return ("in", "out")
        if Conv2dOp and isinstance(node, Conv2dOp) \
                and feeds(node, 1) and ndim == 4:
            return ("cout", "cin", "kh", "kw")
    if ndim == 1:
        return ("embed",)
    return None


class RuleBinding:
    """One parameter's compiled split: the Dispatch spec the rules table
    implies (``parts`` is the DispatchOp constructor tuple)."""

    __slots__ = ("param", "axes", "parts", "dim", "axis_name")

    def __init__(self, param, axes, parts, dim, axis_name):
        self.param = param
        self.axes = axes
        self.parts = parts
        self.dim = dim
        self.axis_name = axis_name

    def __repr__(self):
        return (f"RuleBinding({self.param.name}: {self.axes} -> "
                f"parts {self.parts})")


def compile_rules(eval_nodes, rules=None, tp=1, topo=None):
    """Compile a ``{logical_axis: mesh_axis|None}`` table down to
    per-parameter Dispatch ``parts`` tuples (the hand-spec equivalent).

    Returns ``(bindings, conflicts)``: one :class:`RuleBinding` per
    parameter the rules split ``tp`` ways, and an HT205 conflict entry
    per parameter whose graph ALREADY carries a hand Dispatch that
    disagrees with the compiled rule (the hand spec wins at apply
    time — silent double-splitting would corrupt the plan the user
    asked for, so it is a structured finding)."""
    from ..graph.autodiff import find_topo_sort
    from ..ops.comm import DispatchOp
    from ..ops.variable import PlaceholderOp
    from ..analysis.findings import emit

    rules = dict(DEFAULT_RULES) if rules is None else dict(rules)
    if topo is None:
        topo = find_topo_sort(list(eval_nodes))
    hand = {}       # param -> existing DispatchOp parts
    for node in topo:
        if isinstance(node, DispatchOp) and node.inputs \
                and isinstance(node.inputs[0], PlaceholderOp):
            hand[node.inputs[0]] = node.parts
    bindings, conflicts = [], []
    if tp <= 1:
        return bindings, conflicts
    for node in topo:
        if not (isinstance(node, PlaceholderOp) and node.trainable):
            continue
        axes = logical_axes_of(node, topo)
        if not axes:
            continue
        shape = tuple(getattr(node, "shape", ()) or ())
        for dim, axis in enumerate(axes):
            if rules.get(axis) != "tp":
                continue
            if dim >= len(shape) or shape[dim] % tp != 0:
                continue
            parts = tuple(tp if d == dim else 1
                          for d in range(len(shape)))
            if node in hand:
                if tuple(hand[node]) != parts:
                    msg = (f"plan-vs-rules conflict on {node.name}: "
                           f"hand-written dispatch {tuple(hand[node])} "
                           f"vs rules-compiled {parts} (axis "
                           f"{axis!r} -> tp={tp}) — the hand spec "
                           f"wins; drop it or fix the rules table")
                    conflicts.append((node, tuple(hand[node]), parts))
                    if not emit("HT205", "warn", msg, node=node):
                        logger.warning("%s", msg)
                break       # hand spec present: never double-split
            bindings.append(RuleBinding(node, axes, parts, dim, axis))
            break           # one split dim per param
    return bindings, conflicts


def apply_rules(eval_nodes, bindings, shapes=None):
    """Splice the compiled Dispatch markers into the graph: for each
    binding, consumers of the parameter are rewired through a fresh
    ``DispatchOp(param, parts)``, and each split op's OUTPUT is rewired
    through an all-ones gather dispatch — the hand-TP idiom's
    ``act = ht.dispatch(act, (1, 1))`` between layers, without which
    consecutive splits compound through ``deduce_states`` into a
    tp^depth-way plan. From here the existing planner
    (``propagate_statuses`` -> ``spec_for_status``) owns everything,
    exactly as if the user had written the specs by hand."""
    from ..graph.autodiff import find_topo_sort
    from ..ops.comm import dispatch

    if not bindings:
        return []
    topo = find_topo_sort(list(eval_nodes))
    shapes = shapes or {}
    spliced = []
    consumers_of = {}
    for node in topo:
        for i in getattr(node, "inputs", ()):
            consumers_of.setdefault(id(i), []).append(node)
    for b in bindings:
        d = dispatch(b.param, b.parts, ctx=b.param.raw_ctx)
        split_ops = []
        for node in consumers_of.get(id(b.param), ()):
            if node is d:
                continue
            node.inputs = [d if i is b.param else i
                           for i in node.inputs]
            split_ops.append(node)
        spliced.append(d)
        for op in split_ops:
            out_shape = shapes.get(op)
            ndim = len(out_shape) if out_shape else 2
            g = dispatch(op, (1,) * ndim, ctx=op.raw_ctx)
            for cons in consumers_of.get(id(op), ()):
                if cons is g:
                    continue
                cons.inputs = [g if i is op else i
                               for i in cons.inputs]
            spliced.append(g)
    return spliced


# ---------------------------------------------------------------------------
# per-op cost extraction
# ---------------------------------------------------------------------------

def flops_of(node, shapes):
    """Analytic forward FLOPs of one op (the fallback scale when the
    CostDB has no measured entry): matmul/conv count multiply-adds,
    everything else counts one op per output element."""
    out = shapes.get(node) or ()
    ins = [shapes.get(i) for i in getattr(node, "inputs", ())]
    kind = type(node).__name__

    def prod(s):
        try:
            return int(np.prod([int(d) for d in s])) if s else 0
        except (TypeError, ValueError):
            return 0

    if kind == "MatMulOp" and len(ins) == 2 and ins[1]:
        if ins[0] and out:
            # contraction dim honors the transpose flag: a gradient
            # matmul (trans_A=True) contracts over ins[0][-2], and
            # reading [-1] there inflates its FLOPs by the weight dim
            k = int(ins[0][-2]
                    if getattr(node, "matmul_attr_trans_A", False)
                    else ins[0][-1])
            return 2.0 * prod(out) * k
        # activation shape unknown (construction-time planning):
        # assume the default batch over the known weight
        return 2.0 * _DEFAULT_BATCH * prod(ins[1])
    if kind == "BatchMatMulOp" and len(ins) == 2 and ins[0] and out:
        return 2.0 * prod(out) * int(ins[0][-1])
    if kind == "Conv2dOp" and len(ins) == 2 and ins[1] and len(
            ins[1]) == 4:
        cin, kh, kw = int(ins[1][1]), int(ins[1][2]), int(ins[1][3])
        base = prod(out) if out else \
            _DEFAULT_BATCH * int(ins[1][0])
        return 2.0 * base * cin * kh * kw
    if kind in ("EmbeddingLookUp", "EmbeddingLookUpGradient"):
        return float(prod(out)) if out else \
            float(_DEFAULT_BATCH * (ins[0][-1] if ins[0] else 1))
    return float(prod(out))


def _bytes_of(shape, itemsize=4):
    try:
        return int(np.prod([int(d) for d in shape])) * itemsize \
            if shape else 0
    except (TypeError, ValueError):
        return 0


def graph_costs(eval_nodes, db=None, feed_shapes=None, topo=None):
    """Per-op compute costs + the volumes the comm model needs.

    Returns a dict:

    * ``op_ms``      — {node: ms} per forward op (training factor
      applied), measured entries preferred, FLOPs-scaled otherwise;
    * ``sources``    — {node: "measured"|"flops_scaled"|"cold_start"};
    * ``fwd_order``  — the forward (non-placeholder, non-optimizer)
      ops in topo order (the stage-cut axis);
    * ``shapes``     — the shape map (for comm-byte estimates);
    * ``param_bytes``— total trainable parameter bytes;
    * ``splittable`` — {tp candidate divisor -> True} probe source:
      per-param dim sizes the rules could split.
    """
    from ..graph.autodiff import find_topo_sort
    from ..optimizer import OptimizerOp
    from ..ops.variable import PlaceholderOp
    from ..analysis.findings import Report
    from ..analysis.shapes import shape_pass
    from ..telemetry import costdb as _costdb

    if topo is None:
        topo = find_topo_sort(list(eval_nodes))
    shapes = shape_pass(topo, Report(), feed_shapes=feed_shapes) or {}

    # the stage-cut axis is the FORWARD graph only (pipeline stages
    # place forward ops; each stage's backward is its own vjp) — the
    # _TRAIN_FLOP_FACTOR on forward op costs accounts for the backward,
    # so costing grad ops separately would double-count it
    fwd_roots = [n for n in eval_nodes
                 if not isinstance(n, OptimizerOp)]
    fwd_topo = find_topo_sort(fwd_roots) if fwd_roots else []
    fwd = [n for n in fwd_topo if not isinstance(n, PlaceholderOp)]
    params = [n for n in topo
              if isinstance(n, PlaceholderOp) and n.trainable]

    # measured-vs-flops calibration: ops the DB measured anchor the
    # FLOPs scale for the ones it did not
    op_ms, sources = {}, {}
    cal_fl, cal_ms = 0.0, 0.0
    measured = {}
    if db is not None:
        for node in fwd:
            ent = db.get(type(node).__name__, shapes.get(node))
            if ent is not None:
                measured[node] = float(ent["ms"])
                fl = flops_of(node, shapes)
                if fl > 0 and ent["ms"] > 0:
                    cal_fl += fl
                    cal_ms += float(ent["ms"])
    flops_per_ms = (cal_fl / cal_ms) if cal_ms > 0 else None
    for node in fwd:
        if node in measured:
            op_ms[node] = measured[node] * _TRAIN_FLOP_FACTOR
            sources[node] = "measured"
            continue
        fl = flops_of(node, shapes) * _TRAIN_FLOP_FACTOR
        if flops_per_ms:
            op_ms[node] = fl / flops_per_ms
            sources[node] = "flops_scaled"
        else:
            op_ms[node] = _costdb.cold_start_flops_ms(fl)
            sources[node] = "cold_start"

    splittable = set()
    for p in params:
        for d in tuple(getattr(p, "shape", ()) or ()):
            # divisors up to a practical mesh width — a million-row
            # embedding table must not cost a million-iteration scan
            for q in range(2, min(int(d), 512) + 1):
                if d % q == 0:
                    splittable.add(q)
    return {
        "op_ms": op_ms,
        "sources": sources,
        "fwd_order": fwd,
        "shapes": shapes,
        "params": params,
        "param_bytes": sum(_bytes_of(p.shape) for p in params),
        "splittable": splittable,
        "topo": topo,
    }


def balance_stages(costs, fwd_order, pp):
    """Contiguous pp-way cut of the forward op chain minimizing the max
    stage cost (greedy over prefix sums — the per-op measured costs are
    what makes "balanced" mean milliseconds, not op counts). Returns
    (cut_indices, stage_ms): ``cut_indices`` are the pp-1 topo
    positions where a new stage starts."""
    ms = [max(0.0, costs.get(n, 0.0)) for n in fwd_order]
    total = sum(ms)
    if pp <= 1 or not ms:
        return (), [total]
    target = total / pp
    cuts, acc, stage_ms = [], 0.0, []
    for i, v in enumerate(ms):
        remaining_stages = pp - len(cuts)
        if len(cuts) < pp - 1 and acc >= target and \
                len(ms) - i >= remaining_stages - 1:
            cuts.append(i)
            stage_ms.append(acc)
            acc = 0.0
        acc += v
    stage_ms.append(acc)
    while len(stage_ms) < pp:       # degenerate: not enough mass
        stage_ms.append(0.0)
    return tuple(cuts), stage_ms


# ---------------------------------------------------------------------------
# candidate enumeration + scoring
# ---------------------------------------------------------------------------

def enumerate_candidates(nworld, info=None, rules=None, max_pp=None):
    """``(dp, tp, pp)`` factorizations of every device count up to
    ``nworld`` (a tiny model's best plan is often to use FEWER devices
    than the world — the single-device (1,1,1) baseline is always a
    candidate), pruned against the graph. Returns (valid, rejected)
    where rejected pairs each pruned tuple with its reason — the
    enumeration must be auditable, not just correct."""
    valid, rejected = [], []
    rules = dict(DEFAULT_RULES) if rules is None else dict(rules)
    tp_on = any(v == "tp" for v in rules.values())
    splittable = (info or {}).get("splittable", set())
    n_ops = len((info or {}).get("fwd_order", ()))
    seen = set()
    for world in _divisors(nworld):
        for dp in _divisors(world):
            for tp in _divisors(world // dp):
                pp = world // dp // tp
                cand = (dp, tp, pp)
                if cand in seen:
                    continue
                seen.add(cand)
                if tp > 1 and not tp_on:
                    rejected.append(
                        (cand, "rules bind no axis to tp"))
                    continue
                if tp > 1 and info is not None \
                        and tp not in splittable:
                    rejected.append(
                        (cand,
                         f"no parameter dim divisible by tp={tp}"))
                    continue
                if max_pp is not None and pp > max_pp:
                    rejected.append(
                        (cand, f"pp={pp} > max_pp={max_pp}"))
                    continue
                if pp > 1 and info is not None and pp > max(n_ops, 1):
                    rejected.append(
                        (cand,
                         f"pp={pp} deeper than the {n_ops}-op chain"))
                    continue
                valid.append(cand)
    return valid, rejected


def _divisors(n):
    return [d for d in range(1, n + 1) if n % d == 0]


def _comm_est(db, kind, nbytes):
    """(ms, source) from the CostDB with the cold-start floor."""
    from ..telemetry import costdb as _costdb
    if db is None:
        return _costdb.cold_start_ms(kind, nbytes), "cold_start"
    return db.estimate_info(kind, nbytes, cold_start=True)


def score_plan(dp, tp, pp, info, db=None, schedule=None,
               num_microbatches=None):
    """Closed-form cost of one mesh factorization; picks the best
    (M, V, fuse_ticks, stage cut) for the pipeline dimension and
    returns the resulting :class:`Plan` with its breakdown.

    The model (docs/parallelism.md "Cost-model inputs"):

    * compute: sum of per-op ms / dp (batch split), split ops
      additionally / tp;
    * dp comm: one gradient allreduce of the (tp-reduced) parameter
      bytes;
    * tp comm: implicit-reshard volume — for each split parameter, its
      consumer's activation row is partially reduced across tp (the
      HT203 edge set), costed on the allreduce curve;
    * pp: per-stage compute from the balanced cut, wall multiplied by
      the analytic fill/drain factor ``(V·M + S - 1)/(V·M)``, plus
      boundary p2p bytes and a per-tick latency term that penalizes
      large M·V when the p2p latency curve says ticks are expensive —
      the argmin over (M, V) IS the auto-pick.
    """
    from .pipeline import analytic_bubble_fraction

    op_ms = info["op_ms"]
    shapes = info["shapes"]
    fwd = info["fwd_order"]
    notes = []

    # tp: which ops the rules-compiled split accelerates
    split_ops = set()
    if tp > 1:
        bindings = info.get("bindings") or ()
        split_params = {b.param for b in bindings}
        for node in fwd:
            if any(i in split_params for i in
                   getattr(node, "inputs", ())):
                split_ops.add(node)
    eff_ms = {n: (v / tp if n in split_ops else v)
              for n, v in op_ms.items()}
    compute_ms = sum(eff_ms.values()) / max(1, dp)

    comm_ms = 0.0
    srcs = set(info["sources"].values())
    if dp > 1:
        grad_bytes = info["param_bytes"]
        if tp > 1:
            grad_bytes = int(grad_bytes / tp)
        ms, src = _comm_est(db, "allreduce", grad_bytes)
        comm_ms += ms
        srcs.add(src)
    if tp > 1:
        # partial-sum reduction per split matmul's output row (the
        # HT203 implicit-reshard edges the sharding pass reports)
        reshard = sum(_bytes_of(shapes.get(n)) for n in split_ops)
        ms, src = _comm_est(db, "allreduce", max(1, reshard))
        comm_ms += ms
        srcs.add(src)

    if pp <= 1:
        plan = Plan(dp, tp, pp, M=1, V=1, schedule="spmd",
                    predicted_ms=compute_ms + comm_ms,
                    breakdown={"compute_ms": round(compute_ms, 4),
                               "comm_ms": round(comm_ms, 4),
                               "bubble_fraction": 0.0,
                               "sources": sorted(srcs)},
                    notes=notes)
        return plan

    cut, stage_ms = balance_stages(eff_ms, fwd, pp)
    stage_max = max(stage_ms) if stage_ms else 0.0
    # boundary tensor: the activation crossing the first cut (uniform
    # chains have one size; fall back to the largest activation)
    if cut:
        bnode = fwd[cut[0] - 1]
        bbytes = _bytes_of(shapes.get(bnode)) or 4
    else:
        bbytes = max((_bytes_of(shapes.get(n)) for n in fwd),
                     default=4)
    bbytes = max(1, bbytes // max(1, dp))

    best = None
    m_fixed = [num_microbatches] if num_microbatches else _M_CANDIDATES
    for M in m_fixed:
        for V in _V_CANDIDATES:
            if V > 1 and (M < pp or schedule == "gpipe"):
                continue        # interleaving requires M >= S devices
            bubble = analytic_bubble_fraction(pp * V, M, V)
            wall = stage_max / max(1e-9, (1.0 - bubble))
            # per-microbatch boundary transfer (fwd + cotangent) and a
            # per-tick latency term: more ticks cost more dispatch
            per_mb, src = _comm_est(db, "p2p", max(1, bbytes // M) * 2)
            ticks = V * M + pp - 1
            lat_ms, lsrc = _comm_est(db, "p2p", 1)
            pipe_comm = per_mb * M * max(1, pp - 1) / max(1, pp) \
                + ticks * lat_ms
            total = wall + comm_ms + pipe_comm
            cand = (total, M, V, bubble, pipe_comm,
                    {src, lsrc})
            if best is None or total < best[0]:
                best = cand
    total, M, V, bubble, pipe_comm, psrc = best
    srcs |= psrc
    sched = schedule or ("collective" if V > 1 else "gpipe")
    fuse = 2 if M * V >= 8 and sched == "collective" else 1
    plan = Plan(dp, tp, pp, M=M, V=V, fuse_ticks=fuse, schedule=sched,
                stage_cut=cut,
                predicted_ms=total,
                breakdown={"compute_ms": round(compute_ms, 4),
                           "stage_max_ms": round(stage_max, 4),
                           "comm_ms": round(comm_ms + pipe_comm, 4),
                           "bubble_fraction": round(bubble, 4),
                           "sources": sorted(srcs)},
                notes=notes)
    return plan


# ---------------------------------------------------------------------------
# the planner front door
# ---------------------------------------------------------------------------

class AutoPlanResult:
    """Chosen plan + the full scored candidate table + the DB coverage
    split — everything the ``--autoplan`` report prints."""

    def __init__(self, plan, candidates, rejected, coverage, model,
                 nworld, info=None):
        self.plan = plan
        self.candidates = candidates
        self.rejected = rejected
        self.coverage = coverage        # (measured kinds, guessed kinds)
        self.model = model
        self.nworld = nworld
        self.info = info                # graph_costs() output (apply reuse)

    def to_dict(self):
        measured, guessed = self.coverage
        return {"model": self.model, "nworld": self.nworld,
                "chosen": self.plan.to_dict(),
                "candidates": [p.to_dict() for p in self.candidates],
                "rejected": [{"mesh": list(c), "reason": r}
                             for c, r in self.rejected],
                "coverage": {"measured": [str(k) for k in measured],
                             "guessed": [str(k) for k in guessed]}}

    def render(self):
        """The predicted-vs-measured cost table (text)."""
        lines = [f"autoplan: {self.model} over {self.nworld} device(s)"]
        lines.append(f"{'candidate':<30} {'predicted':>12} "
                     f"{'measured':>12}  breakdown")
        for p in self.candidates:
            mark = " *" if p is self.plan else "  "
            meas = (f"{p.measured_ms:.2f} ms"
                    if p.measured_ms is not None else "-")
            bd = p.breakdown
            det = (f"compute {bd.get('compute_ms', 0):.2f} / comm "
                   f"{bd.get('comm_ms', 0):.2f} / bubble "
                   f"{bd.get('bubble_fraction', 0):.3f}")
            lines.append(f"{mark}{p.describe():<28} "
                         f"{p.predicted_ms:>9.2f} ms {meas:>12}  {det}")
        for cand, reason in self.rejected:
            lines.append(f"  pruned dp{cand[0]}·tp{cand[1]}"
                         f"·pp{cand[2]}: {reason}")
        measured, guessed = self.coverage
        lines.append(f"cost inputs measured: "
                     f"{[str(k) for k in measured] or '-'}")
        lines.append(f"cost inputs guessed (cold start): "
                     f"{[str(k) for k in guessed] or 'none'} — run "
                     f"`python -m hetu_tpu.telemetry.costdb --sweep` "
                     f"to measure")
        lines.append(f"chosen: {self.plan.describe()} "
                     f"(predicted {self.plan.predicted_ms:.2f} ms)")
        return "\n".join(lines)


def choose_plan(eval_nodes, nworld=None, rules=None, db=None,
                feed_shapes=None, num_microbatches=None, model="model",
                measure=None, topk=3, max_pp=None):
    """Enumerate, score, and (optionally) measure candidates; returns
    an :class:`AutoPlanResult` with the argmin plan.

    ``measure(plan) -> seconds`` activates the top-``topk`` refinement
    through the autotune engine: the winner is cached under
    ``platform|autoplan|<model>|<nworld>`` exactly like a kernel block
    sweep, so a fleet of ranks plans once and CI replays
    deterministically under ``HETU_AUTOTUNE=1``."""
    import jax

    from ..telemetry.costdb import CostDB, COMM_KINDS

    if nworld is None:
        try:
            nworld = len(jax.devices())
        except RuntimeError:
            nworld = 1
    if db is None:
        db = CostDB()
    info = graph_costs(eval_nodes, db=db, feed_shapes=feed_shapes)
    info["db"] = db             # apply_plan derives dp knob defaults
    # (bucket_bytes) from the same DB the plan was scored on
    rules = dict(DEFAULT_RULES) if rules is None else dict(rules)

    cands, rejected = enumerate_candidates(nworld, info=info,
                                           rules=rules, max_pp=max_pp)
    plans = []
    compiled_by_tp = {}     # rules compilation depends only on tp
    for dp, tp, pp in cands:
        if tp not in compiled_by_tp:
            compiled_by_tp[tp] = compile_rules(eval_nodes, rules, tp,
                                               topo=info["topo"])
        bindings, _conf = compiled_by_tp[tp]
        if tp > 1 and not bindings:
            rejected.append(((dp, tp, pp),
                             "rules compile to no split at this tp"))
            continue
        info["bindings"] = bindings
        plan = score_plan(dp, tp, pp, info, db=db,
                          num_microbatches=num_microbatches)
        plan.bindings = tuple(bindings)
        plan.rules = dict(rules)
        plans.append(plan)
    if not plans:
        plans = [Plan(predicted_ms=sum(info["op_ms"].values()),
                      rules=rules)]
    plans.sort(key=lambda p: p.predicted_ms)

    if measure is not None and len(plans) > 1:
        winner_key = _refine_measured(plans[:max(1, topk)], measure,
                                      model, nworld)
        plans.sort(key=lambda p: (p.measured_ms
                                  if p.measured_ms is not None
                                  else p.predicted_ms))
        if winner_key is not None:
            # a warm autotune cache returns the winner WITHOUT
            # re-measuring (times empty): honor it anyway, or re-runs
            # would silently fall back to the predicted argmin
            for i, p in enumerate(plans):
                if plan_key(p) == winner_key:
                    plans.insert(0, plans.pop(i))
                    break

    comm_cov = db.coverage(COMM_KINDS)
    # fold the per-op compute coverage into the same report the doctor
    # prints: how many op costs were measured vs guessed
    n_meas = sum(1 for s in info["sources"].values()
                 if s == "measured")
    n_all = max(1, len(info["sources"]))
    measured_k, guessed_k = list(comm_cov[0]), list(comm_cov[1])
    if n_meas:
        measured_k.append(f"op-compute:{n_meas}/{n_all}")
    else:
        guessed_k.append("op-compute (FLOPs cold start)")
    return AutoPlanResult(plans[0], plans, rejected,
                          (measured_k, guessed_k), model, nworld,
                          info=info)


def _refine_measured(finalists, measure, model, nworld):
    """Measure the finalists through tune/autotune: candidates are
    plan keys, the winner persists in the shared autotune cache.
    Returns the winner's plan key (the cached one on a warm-cache
    replay, where ``measure`` never runs) or None when tuning is
    off / the sweep produced nothing."""
    from ..tune.autotune import autotune, tuning_mode

    if tuning_mode() == "off":
        return None
    by_key = {plan_key(p): p for p in finalists}
    times = {}

    def measure_rec(key):
        dt = float(measure(by_key[key]))
        times[key] = dt
        return dt

    winner = autotune("autoplan", (model, nworld), list(by_key),
                      measure_rec, default=None)
    for key, dt in times.items():
        by_key[key].measured_ms = dt * 1000.0
    return winner if winner in by_key else None


# ---------------------------------------------------------------------------
# plan application (the Executor(parallel="auto") path)
# ---------------------------------------------------------------------------

def apply_plan(eval_nodes, plan, info=None, _splice_rules=True):
    """Mutate the graph per the chosen plan and return the executor
    kwargs overrides ``HetuConfig`` merges in:

    * tp: the compiled Dispatch markers splice in (``apply_rules``) —
      the existing planner lowers them from here;
    * pp: forward ops get stage device contexts over the balanced cut
      (``v<chunk>:...:<device>`` keys, so V>1 chunks fold round-robin
      onto pp devices exactly like hand-written interleaved contexts);
    * dp: rides the existing executor machinery (worker contexts /
      launcher fleet) — the plan reports it, application is a no-op in
      a single-process session.

    Returns ``{"gpipe"/"pipedream": ..., "pipeline_mode": ...,
    "num_microbatches": ..., "pp_options": ...}`` (empty for pure
    dp/tp plans)."""
    from ..graph.autodiff import find_topo_sort
    from ..context import DeviceGroup
    from ..ndarray import rcpu, rtpu
    import jax

    overrides = {}
    if info is None:
        info = graph_costs(eval_nodes)
    if plan.dp > 1:
        # dp plans bucket their gradient allreduce by default: the
        # CostDB-derived bucket_bytes (4x the measured latency-
        # bandwidth crossover, costdb.recommend_bucket_bytes) keeps
        # `parallel="auto"` off the per-grad latency-regime pattern
        # the HT904 lint prices — a user-supplied overlap_options
        # value still wins in the executor's merge
        from ..telemetry.costdb import recommend_bucket_bytes
        overrides["overlap_options"] = {
            "bucket_bytes": recommend_bucket_bytes(info.get("db"))}
    bindings = plan.bindings
    if plan.tp > 1 and _splice_rules:
        # a plan is often applied to a REBUILT graph (the bench's
        # measure-per-candidate loop, a fresh training process reusing
        # a cached plan): stored bindings reference the scored graph's
        # nodes, so recompile the rules against THIS graph whenever
        # the stored params aren't its nodes — silently splicing
        # nothing would report a tp plan while running unsplit
        here = set(info["topo"])
        if not bindings or not all(b.param in here for b in bindings):
            bindings, _conf = compile_rules(eval_nodes, plan.rules,
                                            plan.tp,
                                            topo=info["topo"])
            plan.bindings = tuple(bindings)
    if bindings and _splice_rules:
        apply_rules(eval_nodes, bindings, shapes=info.get("shapes"))
    if plan.pp <= 1:
        return overrides

    topo = find_topo_sort(list(eval_nodes))
    fwd = info["fwd_order"]
    n_chunks = plan.pp * plan.V
    cuts = plan.stage_cut
    if len(cuts) != n_chunks - 1:
        # the score pass cut pp ways; V>1 application needs pp*V chunks
        cuts = balance_stages(info["op_ms"], fwd, n_chunks)[0]
    try:
        on_cpu = all(d.platform == "cpu" for d in jax.local_devices())
    except RuntimeError:
        on_cpu = True
    mk = rcpu if on_cpu else rtpu

    def ctx_for(chunk):
        v, dev = chunk // plan.pp, chunk % plan.pp
        host = "localhost" if plan.V == 1 else f"v{v}"
        return DeviceGroup(mk(host, dev))

    chunk = 0
    bounds = set(cuts)
    chunk_of = {}
    for i, node in enumerate(fwd):
        if i in bounds and chunk < n_chunks - 1:
            chunk += 1
        node.raw_ctx = ctx_for(chunk)
        chunk_of[node] = chunk
    if plan.schedule == "collective":
        # the collective builder's contract (linear chain, homogeneous
        # per-stage params) raises at trace time; downgrade to the
        # staged runner when the auto cut can't satisfy the cheap half
        # of it (equal per-chunk param-shape lists), rather than ship
        # a plan that dies on first dispatch
        from ..ops.comm import DispatchOp
        from ..ops.variable import PlaceholderOp

        def _param_of(inp):
            # the tp splice above rewired params behind DispatchOps:
            # resolve through them, or every chunk list is vacuously
            # empty and the guard never fires
            while isinstance(inp, DispatchOp) and inp.inputs:
                inp = inp.inputs[0]
            return inp if (isinstance(inp, PlaceholderOp)
                           and inp.trainable) else None

        per_chunk = [[] for _ in range(n_chunks)]
        for node in fwd:
            for inp in getattr(node, "inputs", ()):
                p = _param_of(inp)
                if p is not None:
                    per_chunk[chunk_of[node]].append(
                        tuple(p.shape or ()))
        uniform = all(sorted(c) == sorted(per_chunk[0])
                      for c in per_chunk)
        if not uniform:
            plan.schedule = "gpipe"
            if plan.V > 1:
                # re-place with V folded out (staged gpipe has no
                # virtual stages; contexts must be one per device);
                # the rules were already spliced above, so the
                # recursion only redoes stage placement
                plan.V = 1
                return apply_plan(eval_nodes, plan, info=info,
                                  _splice_rules=False)
    if plan.schedule == "collective":
        overrides["pipeline_mode"] = "collective"
    elif plan.schedule == "1f1b":
        overrides["pipedream"] = True
    else:
        overrides["gpipe"] = True
    overrides["num_microbatches"] = plan.M
    pp_opts = {"virtual_stages": plan.V}
    if plan.schedule == "collective":
        pp_opts["fuse_ticks"] = plan.fuse_ticks
    overrides["pp_options"] = pp_opts
    return overrides
