"""Ulysses sequence parallelism — all-to-all head/sequence exchange.

The second context-parallel flavor next to ring attention (SURVEY §2.3
names both; the reference has neither). Where the ring rotates K/V
shards around ICI neighbors, Ulysses re-shards with two collectives:
an all-to-all turns sequence-sharded [B, H, S/n, D] projections into
head-sharded [B, H/n, S, D], each device computes full-sequence
attention for its head subset, and a second all-to-all restores the
sequence sharding. Two all-to-alls per attention instead of n-1
ppermutes — the better trade when H >= n and the interconnect is fast
relative to S (DeepSpeed-Ulysses's observation); requires H % n == 0,
which the ring does not.

The per-head-subset attention is blocked with the same online-softmax
merge as the ring (never materializing the S x S score matrix), so the
long-context memory profile survives the re-shard: O(S * block) scores
per chip.
"""
from __future__ import annotations

import functools

import jax.numpy as jnp
from jax import lax

from .ring import shard_map_qkv

__all__ = ["ulysses_attention", "ulysses_attention_sharded"]


def _blocked_attention(q, k, v, sm_scale, mask, block=1024):
    """Full-sequence attention via a lax.scan over key blocks with the
    online-softmax merge (the same rule parallel/ring.py applies across
    devices, applied locally) — O(S*block) score memory."""
    b, h, s, d = q.shape
    if s % block:
        block = s                      # odd lengths: single block
    nblk = s // block
    kb = k.reshape(b, h, nblk, block, d).transpose(2, 0, 1, 3, 4)
    vb = v.reshape(b, h, nblk, block, d).transpose(2, 0, 1, 3, 4)
    if mask is not None:
        maskb = mask.reshape(b, 1, 1, nblk, block).transpose(3, 0, 1, 2, 4)
    else:
        maskb = jnp.zeros((nblk, 1, 1, 1, block), jnp.float32)

    def step(carry, xs):
        m_acc, l_acc, o_acc = carry
        k_, v_, mask_ = xs
        sc = jnp.einsum("bhqd,bhkd->bhqk", q, k_,
                        preferred_element_type=jnp.float32) * sm_scale
        sc = sc + mask_
        m_blk = jnp.max(sc, axis=-1, keepdims=True)
        p = jnp.exp(sc - m_blk)
        l_blk = jnp.sum(p, axis=-1, keepdims=True)
        o_blk = jnp.einsum("bhqk,bhkd->bhqd", p.astype(v_.dtype), v_)
        m_new = jnp.maximum(m_acc, m_blk)
        a_old = jnp.exp(m_acc - m_new)
        a_blk = jnp.exp(m_blk - m_new)
        l_new = l_acc * a_old + l_blk * a_blk
        o_new = o_acc * a_old + o_blk.astype(jnp.float32) * a_blk
        return (m_new, l_new, o_new), None

    # init carries derive from q so they inherit its varying-over-mesh
    # type (a fresh constant would be unvarying and shard_map's scan
    # rejects the carry-type mismatch)
    m0 = jnp.zeros_like(q[..., :1], dtype=jnp.float32) - 1e30
    l0 = jnp.zeros_like(q[..., :1], dtype=jnp.float32)
    o0 = jnp.zeros_like(q, dtype=jnp.float32)
    (m, l, o), _ = lax.scan(step, (m0, l0, o0), (kb, vb, maskb))
    return (o / l).astype(q.dtype)


def ulysses_attention(q, k, v, axis_name, sm_scale=1.0, mask=None):
    """Per-shard body (call inside shard_map).

    q, k, v: local shards [B, H, S_local, D] (sequence sharded over
    ``axis_name``); mask: optional additive [B, 1, 1, S_local] shard.
    Non-causal (bidirectional-encoder semantics, like the ring body).
    """
    n = lax.psum(1, axis_name)
    h = q.shape[1]
    assert h % n == 0, \
        f"Ulysses needs heads ({h}) divisible by the sp axis ({n})"

    def seq_to_heads(x):
        # [B, H, S/n, D] -> [B, H/n, S, D]
        return lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                              tiled=True)

    q_, k_, v_ = seq_to_heads(q), seq_to_heads(k), seq_to_heads(v)
    if mask is not None:
        # the additive key mask needs the full sequence on every device
        mask_full = lax.all_gather(mask, axis_name, axis=-1, tiled=True)
    else:
        mask_full = None

    o = _blocked_attention(q_, k_, v_, sm_scale, mask_full)

    # [B, H/n, S, D] -> [B, H, S/n, D]
    return lax.all_to_all(o, axis_name, split_axis=2, concat_axis=1,
                          tiled=True).astype(q.dtype)


def ulysses_attention_sharded(q, k, v, mesh, axis_name="sp", sm_scale=1.0,
                              mask=None):
    """shard_map wrapper: q/k/v are global [B, H, S, D]; the sequence
    dim shards over ``axis_name`` of ``mesh``."""
    fn = functools.partial(ulysses_attention, axis_name=axis_name,
                           sm_scale=sm_scale)
    return shard_map_qkv(fn, q, k, v, mesh, axis_name, mask=mask)
