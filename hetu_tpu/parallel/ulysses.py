"""Ulysses sequence parallelism — all-to-all head/sequence exchange.

The second context-parallel flavor next to ring attention (SURVEY §2.3
names both; the reference has neither). Where the ring rotates K/V
shards around ICI neighbors, Ulysses re-shards with two collectives:
an all-to-all turns sequence-sharded [B, H, S/n, D] projections into
head-sharded [B, H/n, S, D], each device computes full-sequence
attention for its head subset, and a second all-to-all restores the
sequence sharding. Two all-to-alls per attention instead of n-1
ppermutes — the better trade when H >= n and the interconnect is fast
relative to S (DeepSpeed-Ulysses's observation); requires H % n == 0,
which the ring does not.

The per-head-subset attention is blocked with the same online-softmax
merge as the ring (never materializing the S x S score matrix), so the
long-context memory profile survives the re-shard: O(S * block) scores
per chip.
"""
from __future__ import annotations

import functools

import jax.numpy as jnp
from jax import lax

from .ring import shard_map_qkv, _partial_attn, _merge

__all__ = ["ulysses_attention", "ulysses_attention_sharded"]


def _blocked_attention(q, k, v, sm_scale, mask, block=1024, causal=False):
    """Full-sequence attention via a lax.scan over key blocks with the
    online-softmax merge (parallel/ring.py's _partial_attn/_merge,
    applied locally) — O(S*block) score memory. When S is not a block
    multiple, K/V pad up to one and the tail is masked out, so the
    block size (and the memory bound) holds for any length. ``causal``
    adds the decoder mask per block from global positions (q and k both
    cover the full sequence here — Ulysses shards heads, not length)."""
    b, h, s, d = q.shape
    block = min(block, s)
    pad = -s % block
    if pad:
        k = jnp.pad(k, ((0, 0), (0, 0), (0, pad), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, 0), (0, pad), (0, 0)))
        if mask is None:
            mask = jnp.zeros((b, 1, 1, s), jnp.float32)
        mask = jnp.pad(mask, ((0, 0), (0, 0), (0, 0), (0, pad)),
                       constant_values=-1e9)
    s_k = s + pad
    nblk = s_k // block
    kb = k.reshape(b, h, nblk, block, d).transpose(2, 0, 1, 3, 4)
    vb = v.reshape(b, h, nblk, block, d).transpose(2, 0, 1, 3, 4)
    if mask is not None:
        maskb = mask.reshape(b, 1, 1, nblk, block).transpose(3, 0, 1, 2, 4)
    else:
        maskb = jnp.zeros((nblk, 1, 1, 1, block), jnp.float32)
    starts = jnp.arange(nblk) * block
    q_pos = jnp.arange(s)

    def step(carry, xs):
        k_, v_, mask_, start = xs
        bias = mask_
        if causal:
            k_pos = start + jnp.arange(block)
            bias = bias + jnp.where(q_pos[:, None] >= k_pos[None, :],
                                    0.0, -1e9)[None, None]
        blk = _partial_attn(q, k_, v_, bias, sm_scale)
        return _merge(carry, blk), None

    # init carries derive from q so they inherit its varying-over-mesh
    # type (a fresh constant would be unvarying and shard_map's scan
    # rejects the carry-type mismatch)
    m0 = jnp.zeros_like(q[..., :1], dtype=jnp.float32) - 1e30
    l0 = jnp.zeros_like(q[..., :1], dtype=jnp.float32)
    o0 = jnp.zeros_like(q, dtype=jnp.float32)
    (m, l, o), _ = lax.scan(step, (m0, l0, o0), (kb, vb, maskb, starts))
    return (o / l).astype(q.dtype)


def ulysses_attention(q, k, v, axis_name, sm_scale=1.0, mask=None,
                      causal=False):
    """Per-shard body (call inside shard_map).

    q, k, v: local shards [B, H, S_local, D] (sequence sharded over
    ``axis_name``); mask: optional additive [B, 1, 1, S_local] shard.
    ``causal=True`` is the straightforward case for Ulysses: after the
    all-to-all each device holds the full sequence for its head subset,
    so the decoder mask applies blockwise from global positions.
    """
    n = lax.psum(1, axis_name)
    h = q.shape[1]
    assert h % n == 0, \
        f"Ulysses needs heads ({h}) divisible by the sp axis ({n})"

    def seq_to_heads(x):
        # [B, H, S/n, D] -> [B, H/n, S, D]
        return lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                              tiled=True)

    q_, k_, v_ = seq_to_heads(q), seq_to_heads(k), seq_to_heads(v)
    if mask is not None:
        # the additive key mask needs the full sequence on every device
        mask_full = lax.all_gather(mask, axis_name, axis=-1, tiled=True)
    else:
        mask_full = None

    o = _blocked_attention(q_, k_, v_, sm_scale, mask_full, causal=causal)

    # [B, H/n, S, D] -> [B, H, S/n, D]
    return lax.all_to_all(o, axis_name, split_axis=2, concat_axis=1,
                          tiled=True).astype(q.dtype)


def ulysses_attention_sharded(q, k, v, mesh, axis_name="sp", sm_scale=1.0,
                              mask=None, causal=False):
    """shard_map wrapper: q/k/v are global [B, H, S, D]; the sequence
    dim shards over ``axis_name`` of ``mesh``."""
    fn = functools.partial(ulysses_attention, axis_name=axis_name,
                           sm_scale=sm_scale, causal=causal)
    return shard_map_qkv(fn, q, k, v, mesh, axis_name, mask=mask)
