"""Collective (SPMD) pipeline: GPipe as ONE shard_map program.

The staged runners in pipeline.py dispatch per-stage jits and move
boundaries with device_put (in-process) or the host TCP channel
(cross-process). This module is the third mode — the whole pipeline is a
single XLA program over a ``stage`` mesh axis: every device holds one
stage's parameters (stacked ``[S, ...]`` arrays sharded on the stage
axis), and each schedule tick shifts the boundary activation to the next
stage with ``lax.ppermute``, so stage transfers ride ICI with no host in
the loop at all. The reference moves stage boundaries device-to-device
over NCCL p2p driven from Python (PipelineSend.py:8-74,
mpi_nccl_communication.cu:166-230); here the transfer is a compiler-
scheduled collective inside one jit — zero dispatches per boundary.

Heterogeneous-but-shape-compatible stages dispatch through ``lax.switch``
on the stage index (each device runs its own stage's subgraph).
Requirements, checked loudly at build time:

  * a linear chain: stage i consumes exactly one boundary tensor,
    produced by stage i-1, and all boundary tensors share one
    shape/dtype;
  * per-stage parameter lists of matching length and shapes, so
    position j of every stage stacks into one ``[S, ...]`` array.

That is the shape of every real pipelined model (uniform transformer
blocks); models that violate it keep the staged runners. The host TCP
channel (parallel/p2p.py) remains the cross-slice/DCN transport — this
mode covers the in-slice (single SPMD program) case.

Schedule math matches the staged GPipe runner exactly: microbatch m's
forward folds the same RNG (step*131 + m), the loss is the mean over
microbatches, and one optimizer step applies the summed gradients — so
losses are bit-comparable with pipeline.py's ``_run_gpipe_compiled``
(tests/test_collective_pp.py asserts it).

Tick-loop tuning knobs (the round-6 perf rewrite; every combination is
loss-equivalent to the staged runner, asserted per-variant by
tests/test_collective_pp.py — bf16 boundaries under a looser, documented
tolerance):

  * ``feed_mode`` — "sharded" (default) packs each stage's microbatch
    feeds into one byte row of a ``[S, row_bytes]`` uint8 array sharded
    over the stage axis, so a device receives ONLY its own stage's feed
    bytes (branch s decodes its slices at static offsets). "replicated"
    is the old transport: every feed enters with a replicated ``P()``
    spec, so all M microbatches of every stage's feeds stream through
    every device — S x the h2d bytes of the sharded path.
  * ``fuse_ticks=K`` — the schedule scan advances K ticks per iteration
    (XLA fuses across the tick boundary); trailing padded ticks compute
    masked garbage, which is safe at the END of the schedule only (the
    loss mask drops them and x_last is discarded).
  * ``unroll_fill_drain`` — the S-1 fill and S-1 drain ticks unroll out
    of the scan (they can fuse with program entry/exit); only the
    steady-state ticks loop.
  * ``boundary_dtype`` — "bf16" casts the ppermute payload at stage
    boundaries (halving boundary bytes on the wire); compute and the
    loss/gradient/optimizer math stay fp32.
  * ``virtual_stages=V`` — **interleaved schedule** (Megatron-style
    virtual stages, the round-10 bubble attack): the user's S*V stage
    contexts map onto S devices, device r owning chunks
    ``{v*S + r : v < V}`` stacked as a ``[S, V, ...]`` parameter axis.
    Each tick computes ONE chunk per device and the boundary rides a
    full ring ``ppermute`` (the S-1 -> 0 wraparound carries a chunk
    group transition); chunk ``v*S + r`` of microbatch ``m`` runs at
    tick ``r + v*M + m``, so the whole schedule is ``V*M + S - 1``
    ticks of 1/V-stage work each — fill/drain shrinks from ``(S-1)``
    stage-times to ``(S-1)/V``, i.e. bubble fraction
    ``(S-1)/(V*M + S - 1)`` vs GPipe's ``(S-1)/(M + S - 1)``. The
    wraparound arrives ``M - S + 1`` ticks before its consumer turn,
    buffered in a ``[M-S+1, ...]`` ring carry (read-before-write at
    slot ``t mod (M-S+1)`` is exactly the needed delay), which is why
    the schedule requires ``M >= S``. Losses/gradients are identical
    to GPipe on the same S*V-stage graph (the schedule only reorders
    work; every microbatch still traverses every stage once and one
    optimizer step applies the summed gradients).
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from .mesh import shard_map_unchecked as _shard_map_unchecked
from .. import telemetry as _telemetry

__all__ = ["CollectiveGPipe", "BOUNDARY_RTOL"]

# the declared loss tolerance of an opt-in low-precision boundary
# (PR 1's tested bf16 rtol): the numerics verifier's HT805 check holds
# the derived cast-error bound (hops * eps/2, numerics.
# boundary_error_bound) against this — widening the boundary dtype
# without retuning it trips statically before a run ships wrong losses.
# Overridable per session via pp_options={"boundary_rtol": ...}.
BOUNDARY_RTOL = 5e-3


def _canon_boundary_dtype(boundary_dtype):
    if boundary_dtype in (None, "fp32", "f32", "float32"):
        return None
    if boundary_dtype in ("bf16", "bfloat16"):
        return jnp.bfloat16
    return np.dtype(boundary_dtype)


class CollectiveGPipe:
    """Compiled SPMD GPipe step over a ``stage`` mesh axis.

    branches: list of S callables with the uniform signature
    ``branch(plist, x, feeds, rng) -> (boundary_out, loss)`` — plist is
    the device-local per-position parameter list, x the incoming boundary
    activation, feeds the per-microbatch feed list for that stage
    (already sliced at microbatch m by the feed transport), and loss a
    scalar (zero except the last stage).
    """

    def __init__(self, branches, boundary_aval, num_microbatches, mesh,
                 axis_name, optimizer, feed_mode="sharded", fuse_ticks=2,
                 unroll_fill_drain=True, boundary_dtype=None,
                 virtual_stages=1, telemetry=None):
        if feed_mode not in ("sharded", "replicated"):
            raise ValueError(
                f"feed_mode must be 'sharded' or 'replicated', got "
                f"{feed_mode!r}")
        self.branches = branches
        self.S = len(branches)          # total chunks (user stages)
        self.M = num_microbatches
        self.V = max(1, int(virtual_stages or 1))
        if self.S % self.V != 0:
            raise ValueError(
                f"virtual_stages={self.V} must divide the stage count "
                f"{self.S} (each device owns exactly V chunks)")
        self.S_dev = self.S // self.V   # devices on the stage axis
        if self.V > 1:
            if self.S_dev < 2:
                raise ValueError(
                    "interleaved schedule needs >= 2 devices after "
                    f"folding {self.S} stages by V={self.V}")
            if self.M < self.S_dev:
                raise ValueError(
                    f"interleaved schedule requires M >= device count "
                    f"({self.M} < {self.S_dev}): the S-1 -> 0 wraparound "
                    f"buffer depth is M - S + 1; raise num_microbatches "
                    f"or drop virtual_stages")
        self.mesh = mesh
        self.axis_name = axis_name
        self.optimizer = optimizer
        self.boundary_aval = boundary_aval
        self.feed_mode = feed_mode
        self.fuse_ticks = max(1, int(fuse_ticks))
        self.unroll_fill_drain = bool(unroll_fill_drain)
        self.boundary_dtype = _canon_boundary_dtype(boundary_dtype)
        self.telemetry = (telemetry if telemetry is not None
                          else _telemetry.NULL)
        self._step = None
        self._feed_cache = {}     # (stage, j) -> (src array, replicated)
        self._packed_cache = None  # (leaf refs, packed [S, row_bytes])
        self._layout = None       # per stage: [(offset, shape, dtype)]
        self._row_bytes = 1

    @property
    def n_ticks(self):
        """Schedule length in ticks (V*M + S_dev - 1; the V=1 case is
        the classic M + S - 1)."""
        return self.V * self.M + self.S_dev - 1

    # -- stage-sharded feed transport -----------------------------------
    def _build_layout(self, feeds_all):
        """Byte layout of each stage's feed bundle inside its row of the
        packed ``[S_dev, row_bytes]`` array: per feed, (byte offset,
        stacked [M, mb, ...] shape, dtype). Offsets are static per
        stage, so branch s decodes its feeds with static slices +
        bitcasts. Under V>1 the V chunks sharing a device concatenate
        into one row (chunk v*S_dev + r at increasing offsets of row
        r), so a device still receives only ITS chunks' feed bytes."""
        layout = [None] * self.S
        row_bytes = 0
        for r in range(self.S_dev):
            off = 0
            for v in range(self.V):
                c = v * self.S_dev + r
                stage = []
                for f in feeds_all[c]:
                    shape = tuple(int(d) for d in f.shape)
                    dt = np.dtype(f.dtype)
                    stage.append((off, shape, dt))
                    off += int(np.prod(shape)) * dt.itemsize
                layout[c] = stage
            row_bytes = max(row_bytes, off)
        self._layout = layout
        self._row_bytes = max(row_bytes, 1)

    def _pack_feeds(self, feeds_all):
        """Stage feeds -> one ``[S, row_bytes]`` uint8 array sharded over
        the stage axis: device s receives only stage s's feed bytes (the
        replicated transport moved every stage's feeds to every device).
        Identity-cached so pinned feeds pack + transfer once, not once
        per step. Packing is a host-side byte copy (jax feed arrays sync
        d2h once on first pack; steady-state steps hit the cache)."""
        from jax.sharding import NamedSharding, PartitionSpec as P
        leaves = tuple(f for fs in feeds_all for f in fs)
        hit = self._packed_cache
        if hit is not None and len(hit[0]) == len(leaves) and \
                all(a is b for a, b in zip(hit[0], leaves)):
            return hit[1]
        rows = np.zeros((self.S_dev, self._row_bytes), np.uint8)
        for s, fs in enumerate(feeds_all):
            if len(fs) != len(self._layout[s]):
                raise ValueError(
                    f"collective pipeline stage {s} got {len(fs)} feeds; "
                    f"built for {len(self._layout[s])}")
            for j, ((off, shape, dt), f) in enumerate(
                    zip(self._layout[s], fs)):
                if tuple(np.shape(f)) != shape or np.dtype(f.dtype) != dt:
                    # the byte layout is compiled into the program, so a
                    # shape change cannot retrace its way to correctness
                    # (the packed array stays [S, row_bytes]) — fail
                    # loudly instead of decoding garbage
                    raise ValueError(
                        f"collective pipeline feed changed shape/dtype "
                        f"after build: stage {s} feed {j} is "
                        f"{tuple(np.shape(f))}/{np.dtype(f.dtype)}, built "
                        f"for {shape}/{dt} — keep the batch size fixed "
                        f"or rebuild the executor")
                b = np.ascontiguousarray(np.asarray(f), dtype=dt)
                b = b.view(np.uint8).ravel()
                rows[s % self.S_dev, off:off + b.size] = b
        packed = jax.device_put(
            rows, NamedSharding(self.mesh, P(self.axis_name)))
        self._packed_cache = (leaves, packed)
        return packed

    def _decode_feeds(self, words, s, mc):
        """Stage s's microbatch-mc feed list out of its local byte row
        (static offsets/shapes; only the microbatch index is dynamic)."""
        out = []
        for off, shape, dt in self._layout[s]:
            M = shape[0]
            nb = int(np.prod(shape)) * dt.itemsize
            blk = words[off:off + nb].reshape((M, nb // M))
            row = jnp.take(blk, mc, axis=0)
            if dt.itemsize == 1:
                row = row.reshape(shape[1:])
            else:
                row = row.reshape(tuple(shape[1:]) + (dt.itemsize,))
            out.append(lax.bitcast_convert_type(row, dt))
        return out

    # -- the per-device schedule body (runs inside shard_map) -----------
    def _body(self, params_local, feed_arg, base_rng, step):
        """Forward schedule AND backward, differentiated per device: the
        body returns (partial loss, local param grads). Taking the grad
        INSIDE the shard_map is what makes the one-program design hold
        up — the transpose of each tick's ``ppermute`` is the inverse
        permute, so cotangents flow stage S-1 -> 0 across devices inside
        the same compiled program, and no jax AD machinery ever crosses
        the shard_map boundary (jax 0.4.x's partial-eval of shard_map
        mis-specs scan residuals under check_rep=False)."""
        axis = self.axis_name
        S, M, K = self.S, self.M, self.fuse_ticks
        r = lax.axis_index(axis)
        if self.feed_mode == "sharded":
            feed_local = jnp.squeeze(feed_arg, 0)
        else:
            feed_local = feed_arg
        shift = [(i, i + 1) for i in range(S - 1)]
        carry_dt = self.boundary_dtype or self.boundary_aval.dtype
        x0 = jnp.zeros(self.boundary_aval.shape, carry_dt)
        loss0 = jnp.float32(0.0)
        if hasattr(lax, "pvary"):
            # loop carries change varying-over-mesh type inside the
            # tick loop; the initial values must already carry it
            x0 = lax.pvary(x0, (axis,))
            loss0 = lax.pvary(loss0, (axis,))

        if self.feed_mode == "sharded":
            def stage_call(s):
                br = self.branches[s]

                def call(plist, x, words, mc, rng):
                    return br(plist, x,
                              self._decode_feeds(words, s, mc), rng)
                return call
        else:
            def stage_call(s):
                br = self.branches[s]

                def call(plist, x, feeds_all, mc, rng):
                    feeds = [jnp.take(f, mc, axis=0)
                             for f in feeds_all[s]]
                    return br(plist, x, feeds, rng)
                return call
        wrapped = [stage_call(s) for s in range(S)]

        def schedule_loss(params_loc):
            plist = [jnp.squeeze(p, 0) for p in params_loc]

            def tick(carry, t):
                x_cur, loss_acc = carry
                m = t - r
                mc = jnp.clip(m, 0, M - 1)
                rng = jax.random.fold_in(base_rng, step * 131 + mc)
                # fill/drain ticks compute on zero lanes rather than
                # branching them out: an A/B with a lax.cond skip
                # measured ~1.5x SLOWER end-to-end (the per-tick branch
                # blocks fusion and costs more than the saved compute);
                # the garbage lanes' outputs receive zero cotangents, so
                # they contribute nothing to gradients. The inherent
                # overhead is (M+S-1)/M — amortize with M >> S.
                xin = x_cur.astype(self.boundary_aval.dtype)
                y, loss = lax.switch(r, wrapped, plist, xin, feed_local,
                                     mc, rng)
                valid = (m >= 0) & (m < M) & (r == S - 1)
                loss_acc = loss_acc + jnp.where(valid, loss, 0.0)
                y = y.astype(carry_dt)
                if shift:
                    y = lax.ppermute(y, axis, shift)
                return (y, loss_acc)

            # schedule driver: optional unrolled fill/drain around a
            # scan that advances K ticks per iteration. Padded extra
            # ticks (when K does not divide the looped count) spill
            # PAST the end of the region the scan covers — in-order, so
            # the schedule stays exact; ticks beyond M+S-2 only touch
            # the masked loss and the discarded x_last, never an
            # in-flight boundary.
            T = M + S - 1
            carry = (x0, loss0)
            n_pre = min(S - 1, T) if self.unroll_fill_drain else 0
            n_mid = max(M - S + 1, 0) if self.unroll_fill_drain else T
            niters = -(-n_mid // K) if n_mid else 0
            for t in range(n_pre):
                carry = tick(carry, t)
            if niters:
                def body(c, t0):
                    for k in range(K):
                        c = tick(c, t0 + k)
                    return c, None
                carry, _ = lax.scan(
                    body, carry, n_pre + K * jnp.arange(niters))
            for t in range(n_pre + K * niters, T):
                carry = tick(carry, t)
            # per-device partial of the mean-over-microbatches loss
            # (only the last stage's lane is nonzero): the cross-stage
            # reduction happens OUTSIDE the shard_map as a plain sum
            # over the [S] output — no in-body collective needed
            return carry[1] / M

        loss_part, grads_local = jax.value_and_grad(
            schedule_loss)(params_local)
        return loss_part[None], grads_local

    # -- interleaved (virtual-stage) schedule body ----------------------
    def _body_interleaved(self, params_local, feed_arg, base_rng, step):
        """The V>1 tick loop (see module docstring): one CHUNK per
        device per tick, boundary on a full-ring ppermute, the S-1 -> 0
        wraparound delayed through a [M-S+1] ring buffer in the carry
        (read slot ``t mod B`` before writing it — the value written
        there B ticks ago is exactly the chunk-group predecessor the
        device-0 lane consumes now). Differentiated in-body exactly
        like ``_body``: the transpose of the ring ppermute is the
        inverse ring, and the buffer's dynamic-slice transposes to a
        scatter-add, so cotangents retrace the schedule backwards
        inside the same compiled program."""
        axis = self.axis_name
        M, K, V, S = self.M, self.fuse_ticks, self.V, self.S_dev
        r = lax.axis_index(axis)
        if self.feed_mode == "sharded":
            feed_local = jnp.squeeze(feed_arg, 0)
        else:
            feed_local = feed_arg
        ring = [(i, (i + 1) % S) for i in range(S)]
        B = M - S + 1                   # wraparound delay (ticks)
        carry_dt = self.boundary_dtype or self.boundary_aval.dtype
        x0 = jnp.zeros(self.boundary_aval.shape, carry_dt)
        wbuf0 = jnp.zeros((B,) + tuple(self.boundary_aval.shape),
                          carry_dt)
        loss0 = jnp.float32(0.0)
        if hasattr(lax, "pvary"):
            x0 = lax.pvary(x0, (axis,))
            wbuf0 = lax.pvary(wbuf0, (axis,))
            loss0 = lax.pvary(loss0, (axis,))

        if self.feed_mode == "sharded":
            def chunk_call(c):
                br = self.branches[c]
                v = c // S              # static per branch

                def call(pstack, x, words, mc, rng):
                    plist = [p[v] for p in pstack]
                    return br(plist, x,
                              self._decode_feeds(words, c, mc), rng)
                return call
        else:
            def chunk_call(c):
                br = self.branches[c]
                v = c // S

                def call(pstack, x, feeds_all, mc, rng):
                    plist = [p[v] for p in pstack]
                    feeds = [jnp.take(f, mc, axis=0)
                             for f in feeds_all[c]]
                    return br(plist, x, feeds, rng)
                return call
        wrapped = [chunk_call(c) for c in range(self.S)]

        def schedule_loss(params_loc):
            # local leaves are [1, V, ...]: drop the stage-axis slice
            pstack = [jnp.squeeze(p, 0) for p in params_loc]

            def tick(carry, t):
                x_dir, wbuf, loss_acc = carry
                u = t - r
                vc = jnp.clip(u // M, 0, V - 1)
                mc = jnp.clip(u - vc * M, 0, M - 1)
                rng = jax.random.fold_in(base_rng, step * 131 + mc)
                slot = jnp.mod(t, B)
                # read BEFORE this tick's write: the slot holds the
                # value received B ticks ago — the device-0 lane's
                # chunk-group predecessor output
                x_wrap = lax.dynamic_index_in_dim(wbuf, slot, 0,
                                                  keepdims=False)
                x_in = jnp.where(r == 0, x_wrap, x_dir)
                xin = x_in.astype(self.boundary_aval.dtype)
                c = vc * S + r
                y, loss = lax.switch(c, wrapped, pstack, xin,
                                     feed_local, mc, rng)
                # the loss lane: last chunk (v = V-1) on the last
                # device, microbatch in range
                valid = ((u >= (V - 1) * M) & (u < V * M)
                         & (r == S - 1))
                loss_acc = loss_acc + jnp.where(valid, loss, 0.0)
                y = y.astype(carry_dt)
                y = lax.ppermute(y, axis, ring)
                wbuf = lax.dynamic_update_index_in_dim(wbuf, y, slot, 0)
                return (y, wbuf, loss_acc)

            T = V * M + S - 1
            niters = -(-T // K)
            carry = (x0, wbuf0, loss0)

            def body(cc, t0):
                for k in range(K):
                    cc = tick(cc, t0 + k)
                return cc, None

            carry, _ = lax.scan(body, carry, K * jnp.arange(niters))
            return carry[2] / M

        loss_part, grads_local = jax.value_and_grad(
            schedule_loss)(params_local)
        return loss_part[None], grads_local

    @staticmethod
    def _norm_feeds(feeds_all):
        return tuple(tuple(fs) for fs in feeds_all)

    def build(self, stacked_params, feeds_all):
        """Jit the full training step (forward schedule + backward +
        optimizer) with donated param/slot buffers."""
        from jax.sharding import PartitionSpec as P
        feeds_all = self._norm_feeds(feeds_all)
        p_specs = tuple(P(self.axis_name) for _ in stacked_params)
        if self.feed_mode == "sharded":
            self._build_layout(feeds_all)
            f_specs = P(self.axis_name)
        else:
            f_specs = jax.tree_util.tree_map(lambda _: P(), feeds_all)
        body = self._body if self.V == 1 else self._body_interleaved
        loss_and_grads = _shard_map_unchecked(
            body, mesh=self.mesh,
            in_specs=(p_specs, f_specs, P(), P()),
            out_specs=(P(self.axis_name), p_specs))
        opt = self.optimizer

        def train_step(params, opt_state, feeds, base_rng, step, lr):
            loss_parts, grads = loss_and_grads(params, feeds, base_rng,
                                               step)
            loss = jnp.sum(loss_parts)
            new_p, new_s = [], []
            for p, g, slots in zip(params, grads, opt_state):
                # stacked [S, ...] leaves: the optimizers are
                # elementwise, so one update IS the per-stage update
                pj, sj = opt.update_one(p, opt._apply_l2(p, g), slots,
                                        lr, step)
                new_p.append(pj)
                new_s.append(sj)
            return loss, new_p, new_s

        self._step = jax.jit(train_step, donate_argnums=(0, 1))
        return self._step

    def _replicate(self, feeds_all):
        """Replicated feed transport (feed_mode="replicated"): every
        feed enters the SPMD program on every device. Identity-cached so
        pinned feeds transfer once, not once per step."""
        from jax.sharding import NamedSharding, PartitionSpec as P
        sh = NamedSharding(self.mesh, P())
        out = []
        for s, fs in enumerate(feeds_all):
            row = []
            for j, f in enumerate(fs):
                key = (s, j)
                hit = self._feed_cache.get(key)
                if hit is not None and hit[0] is f:
                    row.append(hit[1])
                    continue
                fr = jax.device_put(f, sh)
                self._feed_cache[key] = (f, fr)
                row.append(fr)
            out.append(tuple(row))
        return tuple(out)

    def step(self, stacked_params, opt_state, feeds_all, base_rng, step,
             lr):
        tel = self.telemetry
        if self._step is None:
            with tel.span("cpp_build"):
                self.build(stacked_params, feeds_all)
            tel.inc("jit_compiles")
        if not tel.enabled:
            if self.feed_mode == "sharded":
                feeds = self._pack_feeds(feeds_all)
            else:
                feeds = self._replicate(feeds_all)
            return self._step(tuple(stacked_params), tuple(opt_state),
                              feeds, base_rng, jnp.int32(step),
                              jnp.float32(lr))
        # the whole schedule is ONE program — host-side spans can't see
        # individual ticks, so the dispatch span carries the tick-loop
        # structure (fill/steady/drain counts) as attributes instead
        if self.feed_mode == "sharded":
            with tel.span("cpp_pack_feeds",
                          bytes=self.S_dev * self._row_bytes):
                feeds = self._pack_feeds(feeds_all)
        else:
            with tel.span("cpp_replicate_feeds"):
                feeds = self._replicate(feeds_all)
        S, M = self.S, self.M
        fill = (S - 1 if self.unroll_fill_drain and self.V == 1
                else 0)
        # black box: the schedule is one SPMD program dispatched by
        # every rank in lockstep — a "collective"-group flight entry per
        # dispatch gives the blackbox CLI an aligned seq stream, so the
        # rank that stops dispatching (or dispatches one more than the
        # rest) is nameable by its first seq divergence
        frec = tel.flight_start("collective", "cpp_dispatch",
                                tag=f"step{int(step)}",
                                nbytes=self.S_dev * self._row_bytes)
        with tel.span("cpp_dispatch", ticks=self.n_ticks, fill=fill,
                      drain=fill, fuse_ticks=self.fuse_ticks,
                      stages=S, microbatches=M,
                      virtual_stages=self.V,
                      bytes=self.S_dev * self._row_bytes):
            out = self._step(tuple(stacked_params), tuple(opt_state),
                             feeds, base_rng, jnp.int32(step),
                             jnp.float32(lr))
        tel.flight_complete(frec)
        return out

    # -- placement helpers ----------------------------------------------
    def stack_stage_values(self, per_stage):
        """Host-stack one per-stage value list into the schedule's
        layout: [S, ...] for V=1, [S_dev, V, ...] with chunk
        ``v*S_dev + r`` at position ``[r, v]`` for the interleaved
        schedule — dim 0 is the stage mesh axis either way."""
        if self.V == 1:
            return np.stack([np.asarray(x) for x in per_stage])
        return np.stack([
            np.stack([np.asarray(per_stage[v * self.S_dev + r])
                      for v in range(self.V)])
            for r in range(self.S_dev)])

    def place_stacked(self, arrs_by_stage):
        """Stack per-stage host/device arrays into [S(,V), ...] sharded
        over the stage axis."""
        from jax.sharding import NamedSharding, PartitionSpec as P
        sh = NamedSharding(self.mesh, P(self.axis_name))
        out = []
        nper = len(arrs_by_stage[0])
        for j in range(nper):
            stacked = self.stack_stage_values(
                [arrs_by_stage[s][j] for s in range(self.S)])
            out.append(jax.device_put(stacked, sh))
        return out
