"""Collective (SPMD) pipeline: GPipe as ONE shard_map program.

The staged runners in pipeline.py dispatch per-stage jits and move
boundaries with device_put (in-process) or the host TCP channel
(cross-process). This module is the third mode — the whole pipeline is a
single XLA program over a ``stage`` mesh axis: every device holds one
stage's parameters (stacked ``[S, ...]`` arrays sharded on the stage
axis), and each schedule tick shifts the boundary activation to the next
stage with ``lax.ppermute``, so stage transfers ride ICI with no host in
the loop at all. The reference moves stage boundaries device-to-device
over NCCL p2p driven from Python (PipelineSend.py:8-74,
mpi_nccl_communication.cu:166-230); here the transfer is a compiler-
scheduled collective inside one jit — zero dispatches per boundary.

Heterogeneous-but-shape-compatible stages dispatch through ``lax.switch``
on the stage index (each device runs its own stage's subgraph).
Requirements, checked loudly at build time:

  * a linear chain: stage i consumes exactly one boundary tensor,
    produced by stage i-1, and all boundary tensors share one
    shape/dtype;
  * per-stage parameter lists of matching length and shapes, so
    position j of every stage stacks into one ``[S, ...]`` array.

That is the shape of every real pipelined model (uniform transformer
blocks); models that violate it keep the staged runners. The host TCP
channel (parallel/p2p.py) remains the cross-slice/DCN transport — this
mode covers the in-slice (single SPMD program) case.

Schedule math matches the staged GPipe runner exactly: microbatch m's
forward folds the same RNG (step*131 + m), the loss is the mean over
microbatches, and one optimizer step applies the summed gradients — so
losses are bit-comparable with pipeline.py's ``_run_gpipe_compiled``
(tests/test_collective_pp.py asserts it).
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

__all__ = ["CollectiveGPipe"]


def _shard_map():
    try:
        from jax import shard_map
    except ImportError:                   # older jax
        from jax.experimental.shard_map import shard_map
    return shard_map


class CollectiveGPipe:
    """Compiled SPMD GPipe step over a ``stage`` mesh axis.

    branches: list of S callables with the uniform signature
    ``branch(plist, x, feeds_all, m, rng) -> (boundary_out, loss)`` —
    plist is the device-local per-position parameter list, x the incoming
    boundary activation, feeds_all the tuple of every stage's stacked
    ``[M, mb, ...]`` feeds (branch s reads only feeds_all[s], sliced at
    microbatch m), and loss a scalar (zero except the last stage).
    """

    def __init__(self, branches, boundary_aval, num_microbatches, mesh,
                 axis_name, optimizer):
        self.branches = branches
        self.S = len(branches)
        self.M = num_microbatches
        self.mesh = mesh
        self.axis_name = axis_name
        self.optimizer = optimizer
        self.boundary_aval = boundary_aval
        self._step = None
        self._feed_cache = {}     # (stage, j) -> (src array, replicated)

    # -- the per-device schedule body (runs inside shard_map) -----------
    def _body(self, params_local, feeds_all, base_rng, step):
        axis = self.axis_name
        S, M = self.S, self.M
        r = lax.axis_index(axis)
        plist = [jnp.squeeze(p, 0) for p in params_local]
        shift = [(i, i + 1) for i in range(S - 1)]
        x0 = jnp.zeros(self.boundary_aval.shape, self.boundary_aval.dtype)
        loss0 = jnp.float32(0.0)
        if hasattr(lax, "pvary"):
            # scan carries change varying-over-mesh type inside the loop;
            # the initial values must already carry it
            x0 = lax.pvary(x0, (axis,))
            loss0 = lax.pvary(loss0, (axis,))

        def tick(carry, t):
            x_cur, loss_acc = carry
            m = t - r
            mc = jnp.clip(m, 0, M - 1)
            rng = jax.random.fold_in(base_rng, step * 131 + mc)
            # fill/drain ticks compute on zero lanes rather than
            # branching them out: an A/B with a lax.cond skip measured
            # ~1.5x SLOWER end-to-end (the per-tick branch blocks
            # fusion and costs more than the saved compute); the
            # garbage lanes' outputs receive zero cotangents, so they
            # contribute nothing to gradients. The inherent overhead is
            # (M+S-1)/M — amortize with M >> S.
            y, loss = lax.switch(r, self.branches, plist, x_cur,
                                 feeds_all, mc, rng)
            valid = (m >= 0) & (m < M) & (r == S - 1)
            loss_acc = loss_acc + jnp.where(valid, loss, 0.0)
            if shift:
                y = lax.ppermute(y, axis, shift)
            return (y, loss_acc), None

        (x_last, loss_acc), _ = lax.scan(
            tick, (x0, loss0), jnp.arange(M + S - 1))
        del x_last
        return lax.psum(loss_acc, axis) / M

    @staticmethod
    def _norm_feeds(feeds_all):
        return tuple(tuple(fs) for fs in feeds_all)

    def build(self, stacked_params, feeds_all):
        """Jit the full training step (forward schedule + backward +
        optimizer) with donated param/slot buffers."""
        from jax.sharding import PartitionSpec as P
        shard_map = _shard_map()
        feeds_all = self._norm_feeds(feeds_all)
        p_specs = tuple(P(self.axis_name) for _ in stacked_params)
        f_specs = jax.tree_util.tree_map(lambda _: P(), feeds_all)
        pipeline_loss = shard_map(
            self._body, mesh=self.mesh,
            in_specs=(p_specs, f_specs, P(), P()),
            out_specs=P())
        opt = self.optimizer

        def train_step(params, opt_state, feeds, base_rng, step, lr):
            loss, grads = jax.value_and_grad(
                lambda ps: pipeline_loss(ps, feeds, base_rng, step)
            )(params)
            new_p, new_s = [], []
            for p, g, slots in zip(params, grads, opt_state):
                # stacked [S, ...] leaves: the optimizers are
                # elementwise, so one update IS the per-stage update
                pj, sj = opt.update_one(p, opt._apply_l2(p, g), slots,
                                        lr, step)
                new_p.append(pj)
                new_s.append(sj)
            return loss, new_p, new_s

        self._step = jax.jit(train_step, donate_argnums=(0, 1))
        return self._step

    def _replicate(self, feeds_all):
        """Feeds enter the one SPMD program replicated over the stage
        mesh (each stage reads only its own slice inside). Identity-
        cached so pinned feeds transfer once, not once per step."""
        from jax.sharding import NamedSharding, PartitionSpec as P
        sh = NamedSharding(self.mesh, P())
        out = []
        for s, fs in enumerate(feeds_all):
            row = []
            for j, f in enumerate(fs):
                key = (s, j)
                hit = self._feed_cache.get(key)
                if hit is not None and hit[0] is f:
                    row.append(hit[1])
                    continue
                fr = jax.device_put(f, sh)
                self._feed_cache[key] = (f, fr)
                row.append(fr)
            out.append(tuple(row))
        return tuple(out)

    def step(self, stacked_params, opt_state, feeds_all, base_rng, step,
             lr):
        if self._step is None:
            self.build(stacked_params, feeds_all)
        return self._step(tuple(stacked_params), tuple(opt_state),
                          self._replicate(feeds_all),
                          base_rng, jnp.int32(step), jnp.float32(lr))

    # -- placement helpers ----------------------------------------------
    def place_stacked(self, arrs_by_stage):
        """Stack per-stage host/device arrays into [S, ...] sharded over
        the stage axis."""
        from jax.sharding import NamedSharding, PartitionSpec as P
        sh = NamedSharding(self.mesh, P(self.axis_name))
        out = []
        nper = len(arrs_by_stage[0])
        for j in range(nper):
            stacked = np.stack([np.asarray(arrs_by_stage[s][j])
                                for s in range(self.S)])
            out.append(jax.device_put(stacked, sh))
        return out
