"""DistGCN-1.5D: distributed full-graph GCN SpMM over a 2D device mesh.

Reference parity: python/hetu/gpu_ops/DistGCN_15d.py:19-156 — A·(H·W)
with H blocks broadcast stage-by-stage inside column subgroups, each
process multiplying its CSR slice and accumulating, then an allreduce
over row subgroups combining the replicated partials.

TPU-native formulation: mesh axes ("gr", "gc") with gr = size/replication
graph-row shards and gc = replication. H shards over gr (replicated over
gc). Instead of NCCL broadcasts, H blocks rotate around the gr ring with
``lax.ppermute`` (neighbor ICI links, overlapping with the SpMM blocks —
the same schedule ring attention uses); each gc column multiplies only
the column blocks assigned to it (block b belongs to column b mod gc),
so SpMM flops divide by gc, and ``lax.psum`` over gc plays the
reference's row-group allreduce. Per-device adjacency travels as padded
COO stages so shapes stay static under jit.
"""
from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

__all__ = ["DistCSR15d", "partition_csr_15d", "dist_gcn_spmm"]


class DistCSR15d:
    """Padded per-(device, ring-step) COO stages of the adjacency.

    data:  [gr, gc, gr, nnz_max] float32
    rows:  [gr, gc, gr, nnz_max] int32   (row within the device's shard)
    cols:  [gr, gc, gr, nnz_max] int32   (row within the incoming block)
    ``n_per`` rows per shard (graph padded to gr * n_per)."""

    def __init__(self, data, rows, cols, n_per, n_nodes, gr, gc):
        self.data = data
        self.rows = rows
        self.cols = cols
        self.n_per = int(n_per)
        self.n_nodes = int(n_nodes)
        self.gr = int(gr)
        self.gc = int(gc)

    def tree_flatten(self):
        return ((self.data, self.rows, self.cols),
                (self.n_per, self.n_nodes, self.gr, self.gc))

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children, *aux)


jax.tree_util.register_pytree_node(
    DistCSR15d, DistCSR15d.tree_flatten, DistCSR15d.tree_unflatten)


def partition_csr_15d(adj, gr, gc):
    """scipy CSR -> DistCSR15d for a (gr, gc) mesh.

    Device (r, c) at ring step k multiplies A[rows_r, block_b] where
    b = (r + k) mod gr, but only when b mod gc == c (its column
    assignment) — other steps carry zero padding."""
    import scipy.sparse as sp

    n = adj.shape[0]
    n_per = -(-n // gr)
    padded = n_per * gr
    if padded != n:
        adj = sp.csr_matrix((adj.data, adj.indices, adj.indptr),
                            shape=(n, n))
        adj.resize((padded, padded))

    stages = {}
    nnz_max = 1
    for r in range(gr):
        rows_lo, rows_hi = r * n_per, (r + 1) * n_per
        a_r = adj[rows_lo:rows_hi]
        for c in range(gc):
            for k in range(gr):
                b = (r + k) % gr
                if b % gc != c:
                    continue
                blk = a_r[:, b * n_per:(b + 1) * n_per].tocoo()
                stages[(r, c, k)] = (
                    blk.data.astype(np.float32),
                    blk.row.astype(np.int32),
                    blk.col.astype(np.int32))
                nnz_max = max(nnz_max, len(blk.data))

    data = np.zeros((gr, gc, gr, nnz_max), np.float32)
    rows = np.zeros((gr, gc, gr, nnz_max), np.int32)
    cols = np.zeros((gr, gc, gr, nnz_max), np.int32)
    for (r, c, k), (d, ri, ci) in stages.items():
        data[r, c, k, :len(d)] = d
        rows[r, c, k, :len(d)] = ri
        cols[r, c, k, :len(d)] = ci
    return DistCSR15d(data, rows, cols, n_per, n, gr, gc)


def dist_gcn_spmm(adj, h, mesh):
    """z = A @ h over the ("gr", "gc") mesh; h, z are [N, F] global
    (sharded over gr, replicated over gc)."""
    from jax.sharding import PartitionSpec as P
    try:
        from jax import shard_map
    except ImportError:                   # older jax
        from jax.experimental.shard_map import shard_map

    gr, gc, n_per, n = adj.gr, adj.gc, adj.n_per, adj.n_nodes
    padded = gr * n_per
    if h.shape[0] != padded:
        h = jnp.pad(h, ((0, padded - h.shape[0]), (0, 0)))

    def body(data, rows, cols, h_local):
        # h_local: [n_per, F] (gr dim consumed by the spec); adj stages
        # keep size-1 leading mesh dims: [1, 1, gr, nnz]
        perm = [(i, (i - 1) % gr) for i in range(gr)]

        def accum(z, k, h_cur):
            d = data[0, 0, k]
            return z + jax.ops.segment_sum(
                h_cur[cols[0, 0, k]] * d[:, None], rows[0, 0, k],
                num_segments=n_per)

        def step(k, carry):
            z, h_cur = carry
            return accum(z, k, h_cur), lax.ppermute(h_cur, "gr", perm)

        # z accumulates data-derived (gc-varying) terms; mark the zero
        # init as gc-varying too or the scan carry types disagree
        z0 = jnp.zeros_like(h_local)
        try:
            z0 = lax.pcast(z0, to="varying", axis_name=("gc",))
        except (AttributeError, TypeError):
            try:
                z0 = lax.pvary(z0, ("gc",))
            except AttributeError:  # older jax: vma tracking absent
                pass
        # gr-1 rotations in the loop; the last block accumulates outside
        # (a gr-th ppermute would rotate into a discarded carry)
        z, h_last = lax.fori_loop(0, gr - 1, step, (z0, h_local))
        z = accum(z, gr - 1, h_last)
        return lax.psum(z, "gc")  # reference row-group allreduce

    spec_adj = P("gr", "gc", None, None)
    spec_h = P("gr", None)
    z = shard_map(body, mesh=mesh,
                  in_specs=(spec_adj, spec_adj, spec_adj, spec_h),
                  out_specs=spec_h)(adj.data, adj.rows, adj.cols, h)
    return z[:n]
