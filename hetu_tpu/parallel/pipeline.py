"""Pipeline-parallel executors: GPipe and PipeDream (1F1B).

Reference parity: SubExecutor4Gpipe (executor.py:457-809) and
SubExecutor4Pipedream (executor.py:812-1337). Users assign stages exactly
like the reference — ``with ht.context(ht.tpu(i)):`` around layer blocks —
and pass ``gpipe=True`` / ``pipedream=True`` to the Executor.

TPU-native architecture, instead of a translated scheduler:

  * The graph splits into stages at device boundaries; each stage's
    forward subgraph traces into ONE jitted function pinned to its chip.
    Boundary values move by ``jax.device_put`` (ICI DMA); async dispatch
    overlaps stages across in-flight microbatches without the reference's
    NCCL group-call pairing dance (executor.py:1246-1277).
  * Backward is the stage-level ``jax.vjp`` with forward recomputation
    inside the jitted backward — per-stage activation rematerialization,
    the memory policy GPipe's paper prescribes, for free.
  * PipeDream weight stashing (reference deep-copies weights per in-flight
    microbatch, executor.py:896-1020) is just *keeping the old params
    pytree* for the microbatch's backward — functional updates make
    stashing a reference-count, not a copy.
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from ..graph.autodiff import find_topo_sort
from ..graph.node import ExecContext
from ..optimizer import OptimizerOp
from ..ops.variable import PlaceholderOp
from ..ops.comm import PipelineSendOp, PipelineReceiveOp

__all__ = ["PipelineSubExecutor"]


class _Stage:
    __slots__ = ("index", "device", "devices", "mesh", "node_spec",
                 "nodes", "param_nodes", "feed_nodes",
                 "in_nodes", "out_nodes", "fwd", "bwd", "params")

    def __init__(self, index, device, devices=None):
        self.index = index
        self.device = device
        self.devices = devices or [device]  # >1 => TP/DP inside the stage
        self.mesh = None                    # per-stage mesh when sharded
        self.node_spec = {}                 # node -> PartitionSpec
        self.nodes = []
        self.param_nodes = []
        self.feed_nodes = []
        self.in_nodes = []       # boundary inputs (produced by earlier stages)
        self.out_nodes = []      # boundary outputs + eval nodes here
        self.fwd = None
        self.bwd = None
        self.params = {}

    def put(self, val, spec=None):
        """Move a value onto this stage: its single device, or its mesh
        (replicated unless a spec is given)."""
        if self.mesh is None:
            return jax.device_put(val, self.device)
        from jax.sharding import NamedSharding, PartitionSpec
        return jax.device_put(val, NamedSharding(
            self.mesh, spec if spec is not None else PartitionSpec()))


class _StageConfig:
    """Config view a TP/DP stage traces under: the stage's own mesh and
    spec table, everything else from the executor config (the composed
    PP+TP mode of reference context.py:652-656 — equal-width stage groups,
    each internally model-parallel)."""

    def __init__(self, base, mesh, node_spec):
        self._base = base
        self.mesh = mesh
        self.node_spec = node_spec

    def spec_for(self, node):
        return self.node_spec.get(node)

    def __getattr__(self, name):
        return getattr(self._base, name)


def _device_key(node):
    """Stage identity of a node from its raw_ctx (reference assigns stages
    by `with ht.context(gpu(i))`; a tuple context means the stage's devices
    cooperate on one model-parallel copy, context.py:652-656)."""
    ctx = node.raw_ctx
    if ctx is None or ctx.worker_num + ctx.server_num == 0:
        return None
    first = ctx[0]
    if isinstance(first, tuple):
        return tuple((d.hostname, d.device_id) for d in first)
    return ((first.hostname, first.device_id),)


def splice_send_recv(eval_nodes, topo=None):
    """Reference-style explicit PipelineSend/Receive markers: pair them
    in construction order (send k <-> recv k), bind each recv to its
    send, and splice consumers through to the payload — the boundary
    transfer itself is the stage executor's job (device_put over ICI
    in-process; DCN send/recv when stages span hosts), so the markers
    carry placement intent, not data. Mutates the graph; call before
    parameter materialization (HetuConfig does, for pipeline modes)."""
    if topo is None:
        topo = find_topo_sort(eval_nodes)
    recvs = [n for n in topo if isinstance(n, PipelineReceiveOp)]
    if not recvs:
        return
    # a recv has no input edge, so its send is unreachable from the
    # eval nodes — pull unconsumed sends from the construction registry.
    # Exact pairing: a count mismatch (e.g. stale sends from an
    # abandoned graph build) fails loudly rather than silently wiring
    # receives to another graph's payloads.
    sends = PipelineSendOp.pending()
    assert len(sends) == len(recvs), (
        f"unpaired pipeline markers: {len(sends)} pending sends vs "
        f"{len(recvs)} receives — stale sends from an abandoned graph? "
        f"build and run pipeline graphs one at a time")
    PipelineSendOp.consume(sends)
    payload = {}
    for s, r in zip(sorted(sends, key=lambda n: n.id),
                    sorted(recvs, key=lambda n: n.id)):
        r.bound_send = s
        payload[r] = s.inputs[0]
        payload[s] = s.inputs[0]
    for node in topo:
        if node in payload or not node.inputs:
            continue
        node.inputs = [payload.get(i, i) for i in node.inputs]


class PipelineSubExecutor:
    """Runs one training subgraph under a pipeline schedule."""

    def __init__(self, name, eval_node_list, config, schedule="gpipe",
                 num_microbatches=None):
        self.name = name
        self.config = config
        self.schedule = schedule
        self.optimizer_ops = [n for n in eval_node_list
                              if isinstance(n, OptimizerOp)]
        assert len(self.optimizer_ops) == 1, \
            "pipeline executor expects exactly one train_op"
        self.optimizer = self.optimizer_ops[0].optimizer
        self.eval_nodes = [n for n in eval_node_list
                           if not isinstance(n, OptimizerOp)]
        self.loss_node = self.eval_nodes[0]

        # forward graph only: the pipeline differentiates per stage with
        # jax.vjp — the graph-level adjoint subgraph is not traced here
        topo = find_topo_sort(self.eval_nodes)
        topo = self._splice_send_recv(topo)
        self._build_stages(topo)
        self.num_microbatches = num_microbatches or max(
            2, len(self.stages))
        self.step_count = 0
        self.batch_num = None
        self._losses_ema = None

    # ------------------------------------------------------------------
    def _build_stages(self, topo):
        devices = jax.devices()
        keys = []
        for node in topo:
            k = _device_key(node)
            if k is not None and k not in keys and not isinstance(
                    node, PlaceholderOp):
                keys.append(k)
        if not keys:
            keys = [(("localhost", 0),)]
        key_to_stage = {k: i for i, k in enumerate(keys)}
        nstages = len(keys)
        stages = []
        for i in range(nstages):
            devs = [devices[d[1] % len(devices)] for d in keys[i]]
            stages.append(_Stage(i, devs[0], devs))

        assign = {}
        for node in topo:
            if isinstance(node, PlaceholderOp):
                continue
            k = _device_key(node)
            s = key_to_stage.get(k)
            if s is None:
                # unplaced compute follows its deepest input's stage
                s = max((assign.get(i, 0) for i in node.inputs), default=0)
            assign[node] = s
            stages[s].nodes.append(node)
        for node in topo:
            if isinstance(node, PlaceholderOp):
                consumers = [assign[n] for n in topo
                             if not isinstance(n, PlaceholderOp)
                             and node in n.inputs]
                s = min(consumers) if consumers else 0
                assign[node] = s
                if node.tensor_value is not None or \
                        node.initializer is not None:
                    stages[s].param_nodes.append(node)
                else:
                    stages[s].feed_nodes.append(node)

        # boundary edges
        for node in topo:
            if isinstance(node, PlaceholderOp):
                continue
            s = assign[node]
            for inp in node.inputs:
                si = assign[inp]
                if si != s and not isinstance(inp, PlaceholderOp):
                    if inp not in stages[s].in_nodes:
                        stages[s].in_nodes.append(inp)
                    if inp not in stages[si].out_nodes:
                        stages[si].out_nodes.append(inp)
        for ev in self.eval_nodes:
            s = assign[ev]
            if ev not in stages[s].out_nodes:
                stages[s].out_nodes.append(ev)
        self.assign = assign
        self.stages = stages
        self._plan_stage_tp(topo)

    def _plan_stage_tp(self, topo):
        """PP+TP / PP+DP composition: propagate NodeStatus over the whole
        graph once, then build one mesh per multi-device stage and lower
        that stage's statuses to PartitionSpecs over it (reference pairs
        equal-width stage device groups the same way, context.py:652-656;
        here XLA's SPMD partitioner supplies the in-stage collectives)."""
        from .mesh import mesh_for_statuses
        from .planner import propagate_statuses, spec_for_status

        status = propagate_statuses(topo)
        if not status:
            return
        for stage in self.stages:
            if len(stage.devices) < 2:
                continue
            stage_nodes = set(stage.nodes) | set(stage.param_nodes)
            sts = {n: st for n, st in status.items() if n in stage_nodes}
            if not any(st is not None and st.is_dist()
                       for st in sts.values()):
                continue  # degenerate (1,1)-only stage: no mesh needed
            mesh, model_axes = mesh_for_statuses(
                sts.values(), devices=stage.devices)
            stage.mesh = mesh
            for node, st in sts.items():
                spec = spec_for_status(st, model_axes)
                if spec is not None:
                    stage.node_spec[node] = spec

    # ------------------------------------------------------------------
    def _make_stage_fns(self, stage):
        """Trace this stage's subgraph into jitted fwd and (remat) bwd."""
        nodes = stage.nodes
        param_order = list(stage.param_nodes)
        feed_order = list(stage.feed_nodes)
        in_order = list(stage.in_nodes)
        out_order = list(stage.out_nodes)
        # Always trace under the stage's own mesh view (None for plain
        # stages) — the executor's global mesh/spec table must not leak
        # into a stage jit, or a dispatch in a single-device stage would
        # be constrained onto foreign devices.
        config = _StageConfig(self.config, stage.mesh, stage.node_spec)

        def stage_fn(params, boundary_in, feeds, rng):
            ectx = ExecContext(training=True, base_rng=rng, config=config)
            ectx.params = {n: params[str(n.id)] for n in param_order}
            env = {}
            env.update(zip(in_order, boundary_in))
            env.update(zip(feed_order, feeds))
            for n in param_order:
                env[n] = ectx.params[n]
            for node in nodes:
                if node in env:
                    continue
                env[node] = node.compute([env[i] for i in node.inputs],
                                         ectx)
            return [env[o] for o in out_order]

        fwd = jax.jit(stage_fn)

        def bwd_fn(params, boundary_in, feeds, rng, cotangents):
            def f(p, b):
                return stage_fn(p, b, feeds, rng)
            outs, vjp = jax.vjp(f, params, boundary_in)
            cots = [jnp.zeros_like(o) if c is None else c
                    for o, c in zip(outs, cotangents)]
            dparams, dins = vjp(cots)
            return dparams, dins

        stage.fwd = fwd
        stage.bwd = jax.jit(bwd_fn)

    # ------------------------------------------------------------------
    def _place_params(self, executor):
        for stage in self.stages:
            for p in stage.param_nodes:
                sid = str(p.id)
                arr = executor.params[sid]
                # dispatched params store sharded over the stage mesh
                stage.params[sid] = stage.put(arr, stage.node_spec.get(p))
            if stage.fwd is None:
                self._make_stage_fns(stage)

    def _split_feeds(self, feed_dict, m_total):
        """Global batch -> per-microbatch feed lists per stage."""
        per_stage = []
        for stage in self.stages:
            feeds_m = []
            for m in range(m_total):
                vals = []
                for node in stage.feed_nodes:
                    v = np.asarray(feed_dict[node])
                    mb = v.shape[0] // m_total
                    assert mb * m_total == v.shape[0], \
                        (f"batch {v.shape[0]} not divisible into "
                         f"{m_total} microbatches")
                    vals.append(stage.put(v[m * mb:(m + 1) * mb]))
                feeds_m.append(vals)
            per_stage.append(feeds_m)
        return per_stage

    # ------------------------------------------------------------------
    def run(self, executor, feed_dict=None, convert_to_numpy_ret_vals=False):
        if not self.stages[0].params and not any(
                s.params for s in self.stages):
            self._place_params(executor)
        feed_dict = feed_dict or {}
        M = self.num_microbatches
        feeds = self._split_feeds(feed_dict, M)
        if self.schedule == "gpipe":
            losses = self._run_gpipe(executor, feeds, M)
        else:
            losses = self._run_1f1b(executor, feeds, M)
        self.step_count += 1
        # mean on device — the only sync is the caller's (asnumpy/convert)
        loss = jnp.mean(jnp.stack([jnp.asarray(l) for l in losses]))
        results = []
        for ev in self.eval_nodes:
            results.append(loss if ev is self.loss_node else None)
        results.append(None)     # train_op slot
        from .. import ndarray
        out = []
        for r in results:
            if r is None:
                out.append(None)
            elif convert_to_numpy_ret_vals:
                out.append(np.asarray(r))
            else:
                out.append(ndarray.NDArray(r, None))
        return out

    # -- forward/backward of one microbatch through one stage ------------
    def _fwd_stage(self, stage, m, feeds, env_out, rng):
        ins = []
        for node in stage.in_nodes:
            src_stage = self.assign[node]
            val = env_out[(m, src_stage)][
                self.stages[src_stage].out_nodes.index(node)]
            ins.append(stage.put(val))
        outs = stage.fwd(stage.params, ins, feeds[stage.index][m], rng)
        env_out[(m, stage.index)] = outs
        return ins

    # ------------------------------------------------------------------
    def _splice_send_recv(self, topo):
        splice_send_recv(self.eval_nodes, topo)
        topo = find_topo_sort(self.eval_nodes)
        return [n for n in topo
                if not isinstance(n, (PipelineSendOp, PipelineReceiveOp))]

    # ------------------------------------------------------------------
    def _run_gpipe(self, executor, feeds, M):
        """All forwards, then all backwards, one optimizer apply
        (reference SubExecutor4Gpipe, executor.py:716-784)."""
        env_out = {}
        stage_ins = {}
        rngs = [executor.rngkey(self.step_count * 131 + m)
                for m in range(M)]
        for m in range(M):
            for stage in self.stages:
                ins = self._fwd_stage(stage, m, feeds, env_out, rngs[m])
                stage_ins[(m, stage.index)] = ins

        grads = [None] * len(self.stages)
        losses = []
        loss_stage = self.assign[self.loss_node]
        for m in range(M):
            losses.append(env_out[(m, loss_stage)][
                self.stages[loss_stage].out_nodes.index(self.loss_node)])
        cot_map = {}
        for m in range(M):
            for stage in reversed(self.stages):
                cots = []
                for node in stage.out_nodes:
                    if node is self.loss_node:
                        cots.append(jnp.full_like(
                            env_out[(m, stage.index)][
                                stage.out_nodes.index(node)], 1.0 / M))
                    else:
                        c = cot_map.get((m, node))
                        cots.append(c)
                dparams, dins = stage.bwd(
                    stage.params, stage_ins[(m, stage.index)],
                    feeds[stage.index][m], rngs[m], cots)
                for node, d in zip(stage.in_nodes, dins):
                    # a boundary node feeding several later stages gets one
                    # cotangent per consumer — sum them, don't overwrite
                    d = self.stages[self.assign[node]].put(d)
                    prev = cot_map.get((m, node))
                    cot_map[(m, node)] = d if prev is None else prev + d
                if grads[stage.index] is None:
                    grads[stage.index] = dparams
                else:
                    grads[stage.index] = jax.tree_util.tree_map(
                        jnp.add, grads[stage.index], dparams)

        self._apply(executor, grads)
        return losses           # device values: no host sync per loss

    def _run_1f1b(self, executor, feeds, M):
        """1F1B: warmup forwards then alternate, per-microbatch updates
        with stashed weights (reference SubExecutor4Pipedream)."""
        env_out = {}
        stage_ins = {}
        stash = {}
        losses = []
        rngs = [executor.rngkey(self.step_count * 131 + m)
                for m in range(M)]
        nstages = len(self.stages)
        warmup = min(nstages, M)
        cot_map = {}

        def forward(m):
            stash[m] = [dict(s.params) for s in self.stages]
            for stage in self.stages:
                ins = self._fwd_stage(stage, m, feeds, env_out, rngs[m])
                stage_ins[(m, stage.index)] = ins
            loss_stage = self.assign[self.loss_node]
            losses.append(env_out[(m, loss_stage)][
                self.stages[loss_stage].out_nodes.index(self.loss_node)])

        def backward(m):
            grads = [None] * nstages
            for stage in reversed(self.stages):
                cots = []
                for node in stage.out_nodes:
                    if node is self.loss_node:
                        cots.append(jnp.ones_like(
                            env_out[(m, stage.index)][
                                stage.out_nodes.index(node)]))
                    else:
                        cots.append(cot_map.get((m, node)))
                dparams, dins = stage.bwd(
                    stash[m][stage.index], stage_ins[(m, stage.index)],
                    feeds[stage.index][m], rngs[m], cots)
                for node, d in zip(stage.in_nodes, dins):
                    d = self.stages[self.assign[node]].put(d)
                    prev = cot_map.get((m, node))
                    cot_map[(m, node)] = d if prev is None else prev + d
                grads[stage.index] = dparams
            del stash[m]
            self._apply(executor, grads)

        done_f = done_b = 0
        for _ in range(warmup):
            forward(done_f)
            done_f += 1
        while done_f < M:
            backward(done_b)
            done_b += 1
            forward(done_f)
            done_f += 1
        while done_b < M:
            backward(done_b)
            done_b += 1
        return losses           # device values: no host sync per loss

    # ------------------------------------------------------------------
    def _apply(self, executor, grads):
        """Per-stage optimizer update as ONE jitted dispatch per stage
        (host-driven per-param eager ops would serialize the 1F1B
        schedule against dispatch latency)."""
        opt = self.optimizer
        lr = np.float32(opt.learning_rate)
        if not hasattr(self, "_apply_jits"):
            self._apply_jits = {}
        for stage, dp in zip(self.stages, grads):
            if dp is None or not stage.param_nodes:
                continue
            fn = self._apply_jits.get(stage.index)
            if fn is None:
                nodes = {str(n.id): n for n in stage.param_nodes}

                def apply_fn(params_sid, grads_sid, opt_state, lr_, step,
                             _nodes=nodes):
                    pv = {_nodes[sid]: v for sid, v in params_sid.items()}
                    gv = {_nodes[sid]: v for sid, v in grads_sid.items()}
                    new_p, new_s = opt.update(pv, gv, opt_state, lr_,
                                              step)
                    return ({str(n.id): v for n, v in new_p.items()},
                            new_s)

                # no donation: 1F1B weight stashes may still reference
                # the pre-update buffers of in-flight microbatches
                fn = self._apply_jits[stage.index] = jax.jit(apply_fn)
            param_vals = {str(n.id): stage.params[str(n.id)]
                          for n in stage.param_nodes}
            grad_vals = {str(n.id): dp[str(n.id)]
                         for n in stage.param_nodes}
            new_params, new_state = fn(
                param_vals, grad_vals, executor.opt_state or {}, lr,
                np.int32(self.step_count))
            for sid, v in new_params.items():
                stage.params[sid] = v
                executor.params[sid] = v
            executor.opt_state = {**(executor.opt_state or {}),
                                  **new_state}
        opt.lr_sched.step()
