"""Pipeline-parallel executors: GPipe and PipeDream (1F1B).

Reference parity: SubExecutor4Gpipe (executor.py:457-809) and
SubExecutor4Pipedream (executor.py:812-1337). Users assign stages exactly
like the reference — ``with ht.context(ht.tpu(i)):`` around layer blocks —
and pass ``gpipe=True`` / ``pipedream=True`` to the Executor.

TPU-native architecture, instead of a translated scheduler:

  * The graph splits into stages at device boundaries; each stage's
    subgraph traces into jitted programs pinned to its chip. Boundary
    values move by ``jax.device_put`` (ICI DMA); async dispatch overlaps
    stages without the reference's NCCL group-call pairing dance
    (executor.py:1246-1277).
  * **GPipe is compiled**: each stage's whole microbatch loop is ONE
    ``lax.scan`` program — one forward dispatch per producing stage and
    one fused backward+optimizer dispatch per stage per step (2S-1
    dispatches for a linear S-stage pipeline), instead of one dispatch
    per microbatch per phase. The backward block rematerializes the
    forward inside ``jax.vjp`` — per-stage activation recomputation, the
    memory policy GPipe's paper prescribes, so only the stacked boundary
    tensors persist between dispatches.
  * Backward everywhere is the stage-level ``jax.vjp`` with forward
    recomputation inside the jitted program.
  * PipeDream weight stashing (reference deep-copies weights per in-flight
    microbatch, executor.py:896-1020) is just *keeping the old params
    pytree* for the microbatch's backward — functional updates make
    stashing a reference-count, not a copy. 1F1B's per-microbatch updates
    create a true cross-stage dependency zigzag (stage s's next forward
    needs the update from its last backward), so its schedule stays
    host-driven, with backward+apply fused into one dispatch per stage
    per microbatch.

LR-scheduler semantics (pinned round 4): the scheduler advances once per
**global step** under both schedules. 1F1B still applies one optimizer
update per microbatch (PipeDream semantics) but all M updates within a
step share the step's learning rate, so StepScheduler decays identically
under GPipe and PipeDream on the same config.
"""
from __future__ import annotations

import os

import numpy as np

import jax
import jax.numpy as jnp

from ..graph.autodiff import find_topo_sort
from ..graph.node import ExecContext
from ..optimizer import OptimizerOp
from ..ops.variable import PlaceholderOp
from ..ops.comm import PipelineSendOp, PipelineReceiveOp
from .. import telemetry as _telemetry

__all__ = ["PipelineSubExecutor", "analytic_bubble_fraction",
           "virtual_stage_program"]

_NULL_CM = _telemetry.NULL.span("")     # shared no-op context manager


class _FlightSpan:
    """Span context manager that also completes a flight-ring record on
    exit — one object so stage-block call sites stay a single `with`."""

    __slots__ = ("_tel", "_span", "_rec")

    def __init__(self, tel, span, rec):
        self._tel = tel
        self._span = span
        self._rec = rec

    def __enter__(self):
        self._span.__enter__()
        return self

    def __exit__(self, *exc):
        self._tel.flight_complete(self._rec)
        return self._span.__exit__(*exc)


class _Stage:
    __slots__ = ("index", "device", "devices", "mesh", "node_spec",
                 "nodes", "param_nodes", "feed_nodes",
                 "in_nodes", "out_nodes", "consumed_outs",
                 "fwd", "bwd_apply", "fwd_block", "bwd_block",
                 "fwd_block_raw", "bwd_block_raw", "params", "owner")

    def __init__(self, index, device, devices=None):
        self.index = index
        self.device = device
        self.owner = 0           # owning worker-process rank (multi-host)
        self.devices = devices or [device]  # >1 => TP/DP inside the stage
        self.mesh = None                    # per-stage mesh when sharded
        self.node_spec = {}                 # node -> PartitionSpec
        self.nodes = []
        self.param_nodes = []
        self.feed_nodes = []
        self.in_nodes = []       # boundary inputs (produced by earlier stages)
        self.out_nodes = []      # boundary outputs + eval nodes here
        self.consumed_outs = []  # out_nodes consumed by other stages
        self.fwd = None          # per-microbatch jit (1F1B)
        self.bwd_apply = None    # fused bwd+optimizer jit (1F1B)
        self.fwd_block = None    # scan-over-microbatches jit (GPipe)
        self.bwd_block = None    # scan bwd + optimizer jit (GPipe)
        self.fwd_block_raw = None   # untraced block fns — composed into a
        self.bwd_block_raw = None   # whole-step jit when stages co-reside
        self.params = {}

    def put(self, val, spec=None):
        """Move a value onto this stage: its single device, or its mesh
        (replicated unless a spec is given)."""
        if self.mesh is None:
            return jax.device_put(val, self.device)
        from jax.sharding import NamedSharding, PartitionSpec
        return jax.device_put(val, NamedSharding(
            self.mesh, spec if spec is not None else PartitionSpec()))


class _StageConfig:
    """Config view a TP/DP stage traces under: the stage's own mesh and
    spec table, everything else from the executor config (the composed
    PP+TP mode of reference context.py:652-656 — equal-width stage groups,
    each internally model-parallel)."""

    def __init__(self, base, mesh, node_spec):
        self._base = base
        self.mesh = mesh
        self.node_spec = node_spec

    def spec_for(self, node):
        return self.node_spec.get(node)

    def __getattr__(self, name):
        return getattr(self._base, name)


def _device_key(node):
    """Stage identity of a node from its raw_ctx (reference assigns stages
    by `with ht.context(gpu(i))`; a tuple context means the stage's devices
    cooperate on one model-parallel copy, context.py:652-656)."""
    ctx = node.raw_ctx
    if ctx is None or ctx.worker_num + ctx.server_num == 0:
        return None
    first = ctx[0]
    if isinstance(first, tuple):
        return tuple((d.hostname, d.device_id) for d in first)
    return ((first.hostname, first.device_id),)


def _drive_1f1b(forward, backward, nstages, M, telemetry=None):
    """The 1F1B order: min(nstages, M) warmup forwards, then alternate
    backward/forward, then drain. ONE definition — the in-process,
    fused (trace-time), and cross-process runners all execute exactly
    this sequence, which is what makes their losses bit-equivalent.
    ``telemetry`` (host-driven runners only — the fused runner replays
    this at trace time where wall clocks mean nothing) brackets the
    fill / steady-state / drain phases as spans, so the pipeline's
    bubble structure is visible on the Perfetto timeline."""
    warmup = min(nstages, M)
    tel = telemetry
    span = (tel.span if tel is not None and tel.enabled
            else lambda *a, **k: _NULL_CM)
    done_f = done_b = 0
    with span("pp_fill", warmup=warmup):
        for _ in range(warmup):
            forward(done_f)
            done_f += 1
    with span("pp_steady", ticks=max(M - warmup, 0)):
        while done_f < M:
            backward(done_b)
            done_b += 1
            forward(done_f)
            done_f += 1
    with span("pp_drain", ticks=M - done_b):
        while done_b < M:
            backward(done_b)
            done_b += 1


def analytic_bubble_fraction(nstages, M, V=1, schedule="1f1b"):
    """Inherent idle fraction of a pipeline schedule: ``nstages`` is
    the TOTAL user stage count; with ``V`` virtual stages per
    device/rank the pipeline depth folds to ``nstages/V`` and the
    schedule runs ``V*M`` chunk-ticks — the Megatron interleaving
    result, bubble ~ 1/V smaller at small M. GPipe and 1F1B share the
    same fill/drain analytics (1F1B reduces peak memory, not bubble).
    The cost-model planner and the telemetry both use this ONE
    definition."""
    del schedule
    V = max(1, int(V))
    S = max(1, int(nstages))
    if V > 1 and S % V == 0:
        sd = S // V
        return (sd - 1) / (V * M + sd - 1)
    return (S - 1) / (M + S - 1)


def virtual_stage_program(nranks, nstages, M):
    """Per-rank symbolic (phase, microbatch, stage) event program of
    the interleaved staged schedule: stages placed round-robin (stage s
    on rank s % nranks, i.e. V = nstages/nranks chunks per rank),
    driven by the SAME ``_drive_1f1b`` order the runtime executes —
    forward(m) visits a rank's chunks in ascending stage order,
    backward(m) in descending. This is the event-program form
    ``analysis/deadlock.py`` verifies (HT3xx) before a fleet launches
    with ``virtual_stages > 1``."""
    progs = {r: [] for r in range(nranks)}

    def forward(m):
        for s in range(nstages):
            progs[s % nranks].append(("fwd", m, s))

    def backward(m):
        for s in reversed(range(nstages)):
            progs[s % nranks].append(("bwd", m, s))

    _drive_1f1b(forward, backward, nstages, M)
    return progs


def _owner_of(hostname, nprocs):
    """Worker-process rank that owns a stage hostname (reference device
    specs 'hostname:gpu:i', context.py:59-63). Conventions:
      * 'worker<k>' -> rank k (unambiguous on shared machines),
      * a hostname listed in HETU_HOSTS -> its index,
      * 'localhost'/'127.0.0.1' (or any name, single-process) -> rank 0.
    In a multi-process run any OTHER hostname is a loud error — and
    deliberately so for the LOCAL nodename too (ADVICE round-5 #1): rank
    k's nodename is not rank j's, so a nodename escape hatch would
    resolve the same stage to different owners on different ranks and
    silently split the pipeline. Only names every rank maps identically
    ('worker<k>', HETU_HOSTS entries, localhost) are accepted; the
    launcher exports HETU_HOSTS for real multi-host fleets."""
    if hostname.startswith("worker") and hostname[6:].isdigit():
        return int(hostname[6:]) % max(nprocs, 1)
    hosts = os.environ.get("HETU_HOSTS", "")
    if hosts:
        names = hosts.split(",")
        if hostname in names:
            return names.index(hostname)
    if nprocs > 1 and hostname not in ("localhost", "127.0.0.1"):
        raise ValueError(
            f"stage hostname {hostname!r} does not map to any worker "
            f"rank (nprocs={nprocs}): use 'worker<k>' names or list it "
            "in HETU_HOSTS — refusing a rank-local fallback that would "
            "resolve differently on other ranks")
    return 0


def splice_send_recv(eval_nodes, topo=None):
    """Reference-style explicit PipelineSend/Receive markers: pair them
    in construction order (send k <-> recv k), bind each recv to its
    send, and splice consumers through to the payload — the boundary
    transfer itself is the stage executor's job (device_put over ICI
    in-process; DCN send/recv when stages span hosts), so the markers
    carry placement intent, not data. Mutates the graph; call before
    parameter materialization (HetuConfig does, for pipeline modes)."""
    if topo is None:
        topo = find_topo_sort(eval_nodes)
    recvs = [n for n in topo if isinstance(n, PipelineReceiveOp)]
    if not recvs:
        return
    # a recv has no input edge, so its send is unreachable from the
    # eval nodes — pull unconsumed sends from the construction registry.
    # Exact pairing: a count mismatch (e.g. stale sends from an
    # abandoned graph build) fails loudly rather than silently wiring
    # receives to another graph's payloads.
    sends = PipelineSendOp.pending()
    assert len(sends) == len(recvs), (
        f"unpaired pipeline markers: {len(sends)} pending sends vs "
        f"{len(recvs)} receives — stale sends from an abandoned graph? "
        f"build and run pipeline graphs one at a time")
    PipelineSendOp.consume(sends)
    payload = {}
    for s, r in zip(sorted(sends, key=lambda n: n.id),
                    sorted(recvs, key=lambda n: n.id)):
        r.bound_send = s
        payload[r] = s.inputs[0]
        payload[s] = s.inputs[0]
    for node in topo:
        if node in payload or not node.inputs:
            continue
        node.inputs = [payload.get(i, i) for i in node.inputs]


class PipelineSubExecutor:
    """Runs one training subgraph under a pipeline schedule."""

    def __init__(self, name, eval_node_list, config, schedule="gpipe",
                 num_microbatches=None):
        self.name = name
        self.config = config
        self.schedule = schedule
        self.optimizer_ops = [n for n in eval_node_list
                              if isinstance(n, OptimizerOp)]
        assert len(self.optimizer_ops) == 1, \
            "pipeline executor expects exactly one train_op"
        self.optimizer = self.optimizer_ops[0].optimizer
        self.eval_nodes = [n for n in eval_node_list
                           if not isinstance(n, OptimizerOp)]
        self.loss_node = self.eval_nodes[0]

        # forward graph only: the pipeline differentiates per stage with
        # jax.vjp — the graph-level adjoint subgraph is not traced here
        topo = find_topo_sort(self.eval_nodes)
        topo = self._splice_send_recv(topo)
        self._build_stages(topo)
        # interleaved (virtual-stage) schedule: V > 1 means the user's
        # S stages fold onto S/V devices (collective mode) or S/V
        # worker ranks (staged 1F1B with round-robin contexts); the
        # analytic bubble shrinks to (S/V - 1)/(V*M + S/V - 1)
        self.virtual_stages = max(1, int(
            (getattr(config, "pp_options", None) or {})
            .get("virtual_stages", 1) or 1))
        self.num_microbatches = num_microbatches or max(
            2, len(self.stages))
        if self.virtual_stages > 1 and self.multiproc:
            # staged interleaved 1F1B = round-robin stage->rank
            # placement under the unchanged 1F1B driver (the channel's
            # blocking recvs realize the interleaving); a blocked
            # placement would silently forfeit the bubble reduction
            owners = [s.owner for s in self.stages]
            nr = len(set(owners))
            if len(owners) % nr != 0 or any(
                    o != owners[i % nr] for i, o in enumerate(owners)):
                raise ValueError(
                    f"virtual_stages={self.virtual_stages} needs "
                    f"round-robin stage ownership (stage i on rank "
                    f"i % {nr}); got owners {owners} — cycle the "
                    f"worker contexts V times")
        self.step_count = 0
        self.batch_num = None
        self._losses_ema = None
        self._fused_step = None   # whole-step jit when stages co-reside
        self._feed_cache = {}     # (stage, node) -> (src jax.Array, stacked)
        self._cpp = None          # CollectiveGPipe (schedule="collective")
        self._cpp_params = None   # stacked [S, ...] param leaves
        self._cpp_slots = None    # stacked optimizer slots per position

    # ------------------------------------------------------------------
    def _build_stages(self, topo):
        devices = jax.devices()
        keys = []
        for node in topo:
            k = _device_key(node)
            if k is not None and k not in keys and not isinstance(
                    node, PlaceholderOp):
                keys.append(k)
        if not keys:
            keys = [(("localhost", 0),)]
        key_to_stage = {k: i for i, k in enumerate(keys)}
        nstages = len(keys)
        stages = []
        for i in range(nstages):
            devs = [devices[d[1] % len(devices)] for d in keys[i]]
            stages.append(_Stage(i, devs[0], devs))

        assign = {}
        for node in topo:
            if isinstance(node, PlaceholderOp):
                continue
            k = _device_key(node)
            s = key_to_stage.get(k)
            if s is None:
                # unplaced compute follows its deepest input's stage
                s = max((assign.get(i, 0) for i in node.inputs), default=0)
            assign[node] = s
            stages[s].nodes.append(node)
        for node in topo:
            if isinstance(node, PlaceholderOp):
                consumers = [assign[n] for n in topo
                             if not isinstance(n, PlaceholderOp)
                             and node in n.inputs]
                s = min(consumers) if consumers else 0
                assign[node] = s
                if node.tensor_value is not None or \
                        node.initializer is not None:
                    stages[s].param_nodes.append(node)
                else:
                    stages[s].feed_nodes.append(node)

        # boundary edges
        for node in topo:
            if isinstance(node, PlaceholderOp):
                continue
            s = assign[node]
            for inp in node.inputs:
                si = assign[inp]
                if si != s and not isinstance(inp, PlaceholderOp):
                    if inp not in stages[s].in_nodes:
                        stages[s].in_nodes.append(inp)
                    if inp not in stages[si].out_nodes:
                        stages[si].out_nodes.append(inp)
        for ev in self.eval_nodes:
            s = assign[ev]
            if ev not in stages[s].out_nodes:
                stages[s].out_nodes.append(ev)
        all_ins = set()
        for st in stages:
            all_ins.update(st.in_nodes)
        for st in stages:
            st.consumed_outs = [n for n in st.out_nodes if n in all_ins]
        self.assign = assign
        self.stages = stages
        # node -> consuming stages, precomputed once (both multiproc
        # runners walk boundary consumers per node)
        self._consumers = {}
        for st in stages:
            for node in st.in_nodes:
                self._consumers.setdefault(node, []).append(st)
        # multi-process ownership: stages whose hostname maps to another
        # worker rank execute there; boundaries cross via the p2p channel
        self.my_rank = int(os.environ.get("HETU_PROC_ID", "0"))
        nprocs = int(os.environ.get("HETU_NUM_PROCS", "1"))
        for st, key in zip(stages, keys):
            st.owner = _owner_of(key[0][0], nprocs)
        self.multiproc = (nprocs > 1
                          and len({s.owner for s in stages}) > 1)
        if self.multiproc:
            # a stage's device indexes the OWNER's local devices (after
            # jax.distributed, jax.devices() is global and remote entries
            # are not addressable here); unowned stages never dispatch
            local = jax.local_devices()
            for st, key in zip(stages, keys):
                if st.owner == self.my_rank:
                    st.devices = [local[d[1] % len(local)] for d in key]
                    st.device = st.devices[0]
        self._plan_stage_tp(topo)

    def _plan_stage_tp(self, topo):
        """PP+TP / PP+DP composition: propagate NodeStatus over the whole
        graph once, then build one mesh per multi-device stage and lower
        that stage's statuses to PartitionSpecs over it (reference pairs
        equal-width stage device groups the same way, context.py:652-656;
        here XLA's SPMD partitioner supplies the in-stage collectives)."""
        from .mesh import mesh_for_statuses
        from .planner import propagate_statuses, spec_for_status

        status = propagate_statuses(topo)
        if not status:
            return
        for stage in self.stages:
            if self.multiproc and stage.owner != self.my_rank:
                continue   # a remote process plans its own stages
            if len(stage.devices) < 2:
                continue
            stage_nodes = set(stage.nodes) | set(stage.param_nodes)
            sts = {n: st for n, st in status.items() if n in stage_nodes}
            if not any(st is not None and st.is_dist()
                       for st in sts.values()):
                continue  # degenerate (1,1)-only stage: no mesh needed
            mesh, model_axes = mesh_for_statuses(
                sts.values(), devices=stage.devices)
            stage.mesh = mesh
            for node, st in sts.items():
                spec = spec_for_status(st, model_axes, node=node)
                if spec is not None:
                    stage.node_spec[node] = spec

    # ------------------------------------------------------------------
    def _stage_machinery(self, stage):
        """Shared tracing machinery for a stage: the raw subgraph function,
        the in-jit optimizer apply, and the loss-cotangent injection."""
        nodes = stage.nodes
        param_order = list(stage.param_nodes)
        feed_order = list(stage.feed_nodes)
        in_order = list(stage.in_nodes)
        out_order = list(stage.out_nodes)
        # Always trace under the stage's own mesh view (None for plain
        # stages) — the executor's global mesh/spec table must not leak
        # into a stage jit, or a dispatch in a single-device stage would
        # be constrained onto foreign devices.
        config = _StageConfig(self.config, stage.mesh, stage.node_spec)
        opt = self.optimizer
        loss_idx = (out_order.index(self.loss_node)
                    if self.loss_node in out_order else -1)
        nodes_by_sid = {str(n.id): n for n in param_order}

        def stage_fn(params, boundary_in, feeds, rng):
            ectx = ExecContext(training=True, base_rng=rng, config=config)
            ectx.params = {n: params[str(n.id)] for n in param_order}
            env = {}
            env.update(zip(in_order, boundary_in))
            env.update(zip(feed_order, feeds))
            for n in param_order:
                env[n] = ectx.params[n]
            for node in nodes:
                if node in env:
                    continue
                env[node] = node.compute([env[i] for i in node.inputs],
                                         ectx)
            return [env[o] for o in out_order]

        def one_bwd(params, ins, feeds, rng, ext_cots, loss_scale):
            """vjp of the stage over one microbatch; forward rematerialized
            inside. ext_cots align with out_order; None entries mean
            zero cotangent, except the loss slot which gets loss_scale."""
            def f(p, b):
                return stage_fn(p, b, feeds, rng)
            outs, vjp = jax.vjp(f, params, ins)
            cots = []
            for i, (o, c) in enumerate(zip(outs, ext_cots)):
                if i == loss_idx:
                    base = jnp.full_like(o, loss_scale)
                    cots.append(base if c is None else c + base)
                else:
                    cots.append(jnp.zeros_like(o) if c is None else c)
            dparams, dins = vjp(cots)
            loss_val = outs[loss_idx] if loss_idx >= 0 else None
            return dparams, dins, loss_val

        def apply_params(params, gsum, opt_state, lr, step):
            if not param_order:
                return params, opt_state
            pv = {nodes_by_sid[sid]: v for sid, v in params.items()}
            gv = {nodes_by_sid[sid]: v for sid, v in gsum.items()}
            new_p, new_s = opt.update(pv, gv, opt_state, lr, step)
            return {str(n.id): v for n, v in new_p.items()}, new_s

        return stage_fn, one_bwd, apply_params, loss_idx

    def _make_stage_fns(self, stage):
        """Per-microbatch jitted fwd and fused bwd+apply (1F1B path).
        RNG derivation (fold_in of the constant base key by step and
        microbatch) happens inside the jit — no per-step host key
        dispatches."""
        stage_fn, one_bwd, apply_params, _ = self._stage_machinery(stage)

        def fwd_fn(params, boundary_in, feeds, base_rng, step, m):
            rng = jax.random.fold_in(base_rng, step * 131 + m)
            return stage_fn(params, boundary_in, feeds, rng)

        stage.fwd = jax.jit(fwd_fn)

        def bwd_apply_fn(stash_params, cur_params, boundary_in, feeds,
                         base_rng, step, m, cotangents, opt_state, lr):
            # backward against the *stashed* weights (PipeDream semantics:
            # the microbatch's forward weights), update the *current*
            # weights — fused so the 1F1B inner loop costs one dispatch
            # per stage per microbatch instead of two.
            rng = jax.random.fold_in(base_rng, step * 131 + m)
            dparams, dins, _ = one_bwd(stash_params, boundary_in, feeds,
                                       rng, cotangents, 1.0)
            new_p, new_s = apply_params(cur_params, dparams, opt_state,
                                        lr, step)
            return dins, new_p, new_s

        stage.bwd_apply = jax.jit(bwd_apply_fn)

    def _make_stage_blocks(self, stage):
        """Compiled GPipe phase programs (round-4 VERDICT #1): the stage's
        whole microbatch loop runs as ONE jitted ``lax.scan`` dispatch.

        * ``fwd_block`` scans the forward over M stacked microbatches and
          returns stacked boundary outputs — built only for stages whose
          outputs other stages consume.
        * ``bwd_block`` rematerializes the forward per microbatch inside
          ``jax.vjp``, accumulates parameter gradients in the scan carry,
          emits stacked input-cotangents, and finishes with the stage's
          optimizer apply — forward+backward+update of a terminal stage
          is a single dispatch.

        The raw (untraced) block functions are also kept: when every
        stage resolves to the same physical device, `_build_fused_step`
        composes them into ONE whole-step jit — a single dispatch per
        training step.
        """
        stage_fn, one_bwd, apply_params, loss_idx = \
            self._stage_machinery(stage)
        M = self.num_microbatches

        def fwd_block(params, stacked_ins, stacked_feeds, base_rng, step):
            def body(_, xs):
                ins, feeds, m = xs
                rng = jax.random.fold_in(base_rng, step * 131 + m)
                return None, stage_fn(params, ins, feeds, rng)
            _, outs = jax.lax.scan(
                body, None, (stacked_ins, stacked_feeds, jnp.arange(M)))
            return outs

        def bwd_block(params, stacked_ins, stacked_feeds, base_rng, step,
                      stacked_cots, opt_state, lr):
            gzero = jax.tree_util.tree_map(jnp.zeros_like, params)

            def body(acc, xs):
                ins, feeds, m, cots = xs
                rng = jax.random.fold_in(base_rng, step * 131 + m)
                dparams, dins, loss_val = one_bwd(params, ins, feeds, rng,
                                                  cots, 1.0 / M)
                acc = jax.tree_util.tree_map(jnp.add, acc, dparams)
                return acc, (dins, loss_val)

            gsum, (stacked_dins, losses) = jax.lax.scan(
                body, gzero,
                (stacked_ins, stacked_feeds, jnp.arange(M), stacked_cots))
            new_params, new_state = apply_params(params, gsum, opt_state,
                                                 lr, step)
            loss_mean = jnp.mean(losses) if losses is not None else None
            return new_params, new_state, stacked_dins, loss_mean

        stage.fwd_block_raw = fwd_block
        stage.bwd_block_raw = bwd_block
        stage.fwd_block = jax.jit(fwd_block)
        stage.bwd_block = jax.jit(bwd_block)

    # ------------------------------------------------------------------
    def _place_params(self, executor):
        for stage in self.stages:
            if self.multiproc and stage.owner != self.my_rank:
                continue   # remote stages materialize on their owner
            for p in stage.param_nodes:
                sid = str(p.id)
                arr = executor.params[sid]
                # dispatched params store sharded over the stage mesh
                stage.params[sid] = stage.put(arr, stage.node_spec.get(p))
            if self.schedule == "gpipe":
                if stage.bwd_block is None:
                    self._make_stage_blocks(stage)
                    # two jitted programs per stage (fwd/bwd blocks)
                    self.config.telemetry.inc("jit_compiles", 2)
            elif stage.fwd is None:
                self._make_stage_fns(stage)
                self.config.telemetry.inc("jit_compiles", 2)
        # when every stage resolves to the same physical chip (e.g. a
        # pipeline program exercised on one real device), boundary
        # transfers are no-ops and the whole schedule fuses into ONE
        # jitted program — a single dispatch per training step
        single = (not self.multiproc
                  and len(self.stages) > 0
                  and all(s.mesh is None for s in self.stages)
                  and all(s.device == self.stages[0].device
                          for s in self.stages))
        if single and self._fused_step is None:
            if self.schedule == "gpipe":
                self._build_fused_gpipe()
            else:
                self._build_fused_1f1b()
            self.config.telemetry.inc("jit_compiles")

    # ------------------------------------------------------------------
    def _build_fused_gpipe(self):
        """Whole-step GPipe program: the per-stage raw scan blocks
        composed into one jit (valid because all stages co-reside, so
        inter-stage movement is the identity)."""
        stages = self.stages
        assign = self.assign

        def step_fn(params_list, feeds_list, base_rng, step, opt_list,
                    lr):
            env = {}
            ins_store = {}
            for st in stages:
                ins = [env[assign[n]][
                    stages[assign[n]].out_nodes.index(n)]
                    for n in st.in_nodes]
                ins_store[st.index] = ins
                if st.consumed_outs:
                    env[st.index] = st.fwd_block_raw(
                        params_list[st.index], ins, feeds_list[st.index],
                        base_rng, step)
            cot_map = {}
            loss_mean = None
            new_params = [None] * len(stages)
            new_states = [None] * len(stages)
            for st in reversed(stages):
                cots = [cot_map.get(n) for n in st.out_nodes]
                np_, ns_, dins, lm = st.bwd_block_raw(
                    params_list[st.index], ins_store[st.index],
                    feeds_list[st.index], base_rng, step, cots,
                    opt_list[st.index], lr)
                if lm is not None:
                    loss_mean = lm
                for node, d in zip(st.in_nodes, dins):
                    prev = cot_map.get(node)
                    cot_map[node] = d if prev is None else prev + d
                new_params[st.index] = np_
                new_states[st.index] = ns_
            return new_params, new_states, loss_mean

        self._fused_step = jax.jit(step_fn)

    def _build_fused_1f1b(self):
        """Whole-step PipeDream program for co-resident stages: the exact
        host 1F1B schedule — per-microbatch weight stashing and updates —
        replayed as a pure function and compiled once. Stashing is free
        under functional updates: the 'stash' is just the params value
        captured at forward-trace time."""
        stages = self.stages
        assign = self.assign
        M = self.num_microbatches
        machinery = [self._stage_machinery(st) for st in stages]
        loss_node = self.loss_node

        def step_fn(params_list, feeds_list, base_rng, step, opt_list,
                    lr):
            cur = list(params_list)
            opt = list(opt_list)
            env_out = {}
            stage_ins = {}
            stash = {}
            losses = []
            cot_map = {}

            def rng_for(m):
                return jax.random.fold_in(base_rng, step * 131 + m)

            def forward(m):
                stash[m] = list(cur)
                for st in stages:
                    stage_fn = machinery[st.index][0]
                    ins = [env_out[(m, assign[n])][
                        stages[assign[n]].out_nodes.index(n)]
                        for n in st.in_nodes]
                    feeds_m = [f[m] for f in feeds_list[st.index]]
                    env_out[(m, st.index)] = stage_fn(
                        cur[st.index], ins, feeds_m, rng_for(m))
                    stage_ins[(m, st.index)] = ins
                ls = assign[loss_node]
                losses.append(env_out[(m, ls)][
                    stages[ls].out_nodes.index(loss_node)])

            def backward(m):
                for st in reversed(stages):
                    _, one_bwd, apply_params, _ = machinery[st.index]
                    cots = [cot_map.get((m, n)) for n in st.out_nodes]
                    feeds_m = [f[m] for f in feeds_list[st.index]]
                    dparams, dins, _ = one_bwd(
                        stash[m][st.index], stage_ins[(m, st.index)],
                        feeds_m, rng_for(m), cots, 1.0)
                    new_p, new_s = apply_params(
                        cur[st.index], dparams, opt[st.index], lr, step)
                    cur[st.index] = new_p
                    opt[st.index] = new_s
                    for node, d in zip(st.in_nodes, dins):
                        prev = cot_map.get((m, node))
                        cot_map[(m, node)] = (d if prev is None
                                              else prev + d)
                del stash[m]

            _drive_1f1b(forward, backward, len(stages), M)
            return cur, opt, jnp.mean(jnp.stack(losses))

        self._fused_step = jax.jit(step_fn)

    def _run_fused(self, executor, stacked_feeds):
        new_params, new_states, loss = self._fused_step(
            [dict(s.params) for s in self.stages], stacked_feeds,
            executor.base_rng, np.int32(self.step_count),
            [self._stage_opt_state(executor, s) for s in self.stages],
            np.float32(self.optimizer.learning_rate))
        for st, np_, ns_ in zip(self.stages, new_params, new_states):
            self._commit_stage_update(executor, st, np_, ns_)
        return loss

    @staticmethod
    def _feed_value(feed_dict, node):
        """Feed as a host array or, if already device-resident (pinned
        inputs / dataloader output), as the jax.Array itself — slicing
        and reshaping then happen on device instead of forcing a
        device->host sync per step."""
        v = feed_dict[node]
        if isinstance(v, jax.Array):
            return v
        from .. import ndarray
        if isinstance(v, ndarray.NDArray):
            return v.value
        return np.asarray(v)

    def _split_feeds(self, feed_dict, m_total):
        """Global batch -> per-microbatch feed lists per stage."""
        per_stage = []
        for stage in self.stages:
            if self.multiproc and stage.owner != self.my_rank:
                per_stage.append([])     # remote stage feeds itself
                continue
            feeds_m = []
            for m in range(m_total):
                vals = []
                for node in stage.feed_nodes:
                    v = self._feed_value(feed_dict, node)
                    mb = v.shape[0] // m_total
                    assert mb * m_total == v.shape[0], \
                        (f"batch {v.shape[0]} not divisible into "
                         f"{m_total} microbatches")
                    vals.append(stage.put(v[m * mb:(m + 1) * mb]))
                feeds_m.append(vals)
            per_stage.append(feeds_m)
        return per_stage

    def _stack_feeds(self, feed_dict, m_total, place=True):
        """Global batch -> per-stage [M, mb, ...] stacked feeds, one
        device transfer per feed node per step (GPipe compiled path).
        ``place=False`` skips the per-stage device placement — the
        collective mode replicates feeds over its own mesh instead, and
        placing them on a stage device first would double the
        host->device traffic."""
        per_stage = []
        for stage in self.stages:
            vals = []
            if self.multiproc and stage.owner != self.my_rank:
                per_stage.append(vals)   # remote stage feeds itself
                continue
            for node in stage.feed_nodes:
                v = self._feed_value(feed_dict, node)
                mb = v.shape[0] // m_total
                assert mb * m_total == v.shape[0], \
                    (f"batch {v.shape[0]} not divisible into "
                     f"{m_total} microbatches")
                stacked_shape = (m_total, mb) + v.shape[1:]
                if isinstance(v, jax.Array):
                    # jax.Arrays are immutable, so identity-keyed caching
                    # of the stacked view is sound — a pinned feed costs
                    # its reshape dispatch once, not once per step
                    ck = (stage.index, node)
                    hit = self._feed_cache.get(ck)
                    if hit is not None and hit[0] is v:
                        vals.append(hit[1])
                        continue
                    stacked = jnp.reshape(v[:mb * m_total], stacked_shape)
                    if place:
                        stacked = stage.put(stacked)
                    self._feed_cache[ck] = (v, stacked)
                else:
                    stacked = v[:mb * m_total].reshape(stacked_shape)
                    if place:
                        stacked = stage.put(stacked)
                vals.append(stacked)
            per_stage.append(vals)
        return per_stage

    # ------------------------------------------------------------------
    def run(self, executor, feed_dict=None, convert_to_numpy_ret_vals=False):
        if self.schedule == "collective":
            feed_dict = feed_dict or {}
            loss = self._run_collective(
                executor, self._stack_feeds(feed_dict,
                                            self.num_microbatches,
                                            place=False))
            return self._finish_step(executor, loss,
                                     convert_to_numpy_ret_vals)
        if not self.stages[0].params and not any(
                s.params for s in self.stages):
            self._place_params(executor)
        feed_dict = feed_dict or {}
        M = self.num_microbatches
        if self._fused_step is not None:
            loss = self._run_fused(executor,
                                   self._stack_feeds(feed_dict, M))
        elif self.multiproc and self.schedule != "gpipe":
            feeds = self._split_feeds(feed_dict, M)
            loss = self._run_1f1b_multiproc(executor, feeds, M)
        elif self.multiproc:
            loss = self._run_gpipe_multiproc(
                executor, self._stack_feeds(feed_dict, M), M)
        elif self.schedule == "gpipe":
            loss = self._run_gpipe_compiled(
                executor, self._stack_feeds(feed_dict, M), M)
        else:
            feeds = self._split_feeds(feed_dict, M)
            losses = self._run_1f1b(executor, feeds, M)
            loss = jnp.mean(jnp.stack([jnp.asarray(l) for l in losses]))
        return self._finish_step(executor, loss, convert_to_numpy_ret_vals)

    def _stage_span(self, name, stage_index):
        """Span for one stage-level dispatch (no-op when telemetry is
        off — the kwargs dict only builds on the enabled path). The
        enabled path also feeds the flight ring (group ``sched``): a
        fleet that hangs mid-schedule leaves "how far each rank's
        schedule got" in the black box even though the span never
        exports."""
        tel = self.config.telemetry
        if not tel.enabled:
            return _NULL_CM
        rec = tel.flight_start("sched", name, tag=f"stage{stage_index}")
        return _FlightSpan(tel, tel.span(name, stage=stage_index), rec)

    def _recv_traced(self, ch, tag, stage_index):
        """Blocking channel recv, recorded as that stage's idle (bubble)
        interval: the time a stage spends waiting on a boundary tensor
        from another rank IS its pipeline bubble."""
        tel = self.config.telemetry
        if not tel.enabled:
            return ch.recv(tag)
        t0 = tel.clock()
        val = ch.recv(tag)
        t1 = tel.clock()
        tel.complete("pp_stage_idle", t0, t1,
                     {"stage": stage_index, "tag": tag,
                      "bytes": int(val.nbytes)})
        tel.observe(f"pp_stage{stage_index}_idle_ms", (t1 - t0) / 1e6)
        return val

    def _finish_step(self, executor, loss, convert_to_numpy_ret_vals):
        # the LR scheduler advances once per GLOBAL step under all
        # schedules (pinned semantics; see module docstring)
        self.optimizer.lr_sched.step()
        self.step_count += 1
        tel = self.config.telemetry
        if tel.enabled:
            # analytic bubble at this (S, M, V): the inherent
            # (S-1)/(M+S-1) idle fraction, shrinking to
            # (S/V - 1)/(V*M + S/V - 1) under the interleaved
            # schedule; measured per-stage idle comes from the
            # pp_stage_idle spans on cross-process runs
            S, M = len(self.stages), self.num_microbatches
            V = self.virtual_stages
            if V > 1 and S % V == 0:
                sd = S // V
                tel.observe("pp_bubble_fraction",
                            (sd - 1) / (V * M + sd - 1))
            else:
                tel.observe("pp_bubble_fraction", (S - 1) / (M + S - 1))
        results = []
        for ev in self.eval_nodes:
            results.append(loss if ev is self.loss_node else None)
        results.append(None)     # train_op slot
        from .. import ndarray
        out = []
        for r in results:
            if r is None:
                out.append(None)
            elif convert_to_numpy_ret_vals:
                out.append(np.asarray(r))
            else:
                out.append(ndarray.NDArray(r, None))
        return out

    # -- forward of one microbatch through one stage (1F1B) --------------
    def _fwd_stage(self, stage, m, feeds, env_out, base_rng, step):
        ins = []
        for node in stage.in_nodes:
            src_stage = self.assign[node]
            val = env_out[(m, src_stage)][
                self.stages[src_stage].out_nodes.index(node)]
            ins.append(stage.put(val))
        outs = stage.fwd(stage.params, ins, feeds[stage.index][m],
                         base_rng, step, np.int32(m))
        env_out[(m, stage.index)] = outs
        return ins

    # ------------------------------------------------------------------
    def _splice_send_recv(self, topo):
        splice_send_recv(self.eval_nodes, topo)
        topo = find_topo_sort(self.eval_nodes)
        return [n for n in topo
                if not isinstance(n, (PipelineSendOp, PipelineReceiveOp))]

    # -- per-stage slices of the global optimizer state ------------------
    @staticmethod
    def _stage_opt_state(executor, stage):
        full = executor.opt_state or {}
        return {n.id: full[n.id] for n in stage.param_nodes
                if n.id in full}

    def _commit_stage_update(self, executor, stage, new_params, new_state):
        for sid, v in new_params.items():
            stage.params[sid] = v
            executor.params[sid] = v
        if new_state:
            executor.opt_state = {**(executor.opt_state or {}),
                                  **new_state}

    # ------------------------------------------------------------------
    def _run_gpipe_compiled(self, executor, stacked_feeds, M):
        """GPipe as compiled per-stage scan blocks: forward blocks in
        stage order, then fused backward+apply blocks in reverse — 2S-1
        dispatches for a linear pipeline (reference SubExecutor4Gpipe
        semantics, executor.py:716-784: all microbatch forwards, all
        backwards, one optimizer apply)."""
        base_rng = executor.base_rng
        lr = np.float32(self.optimizer.learning_rate)
        step = np.int32(self.step_count)

        env = {}        # stage.index -> stacked outs (aligned out_nodes)
        ins_store = {}  # stage.index -> stacked boundary ins
        for stage in self.stages:
            ins = []
            for node in stage.in_nodes:
                src = self.assign[node]
                val = env[src][self.stages[src].out_nodes.index(node)]
                ins.append(stage.put(val))
            ins_store[stage.index] = ins
            if stage.consumed_outs:
                with self._stage_span("pp_fwd_block", stage.index):
                    env[stage.index] = stage.fwd_block(
                        stage.params, ins, stacked_feeds[stage.index],
                        base_rng, step)

        cot_map = {}    # boundary node -> stacked cotangent (consumer-sum)
        loss_mean = None
        for stage in reversed(self.stages):
            cots = [cot_map.get(n) for n in stage.out_nodes]
            with self._stage_span("pp_bwd_block", stage.index):
                new_params, new_state, stacked_dins, lm = stage.bwd_block(
                    stage.params, ins_store[stage.index],
                    stacked_feeds[stage.index], base_rng, step, cots,
                    self._stage_opt_state(executor, stage), lr)
            if lm is not None:
                loss_mean = lm
            for node, d in zip(stage.in_nodes, stacked_dins):
                # a boundary node feeding several later stages gets one
                # cotangent per consumer — sum them, don't overwrite
                d = self.stages[self.assign[node]].put(d)
                prev = cot_map.get(node)
                cot_map[node] = d if prev is None else prev + d
            self._commit_stage_update(executor, stage, new_params,
                                      new_state)
        return loss_mean

    # ------------------------------------------------------------------
    def _build_collective(self, executor, stacked_feeds):
        """Lower the stage graph onto one SPMD program (collective_pp.py):
        validate the linear-chain/homogeneity contract, build uniform
        switch branches from the per-stage subgraph functions, stack
        params and optimizer slots over the stage axis."""
        from jax.sharding import Mesh
        from .collective_pp import CollectiveGPipe

        stages = self.stages
        S = len(stages)
        if self.multiproc:
            raise ValueError(
                "pipeline_mode='collective' is the in-slice SPMD mode; "
                "stages spanning worker processes keep the staged "
                "runners (the p2p channel is the DCN transport)")
        if S < 2:
            raise ValueError(
                "pipeline_mode='collective' needs >= 2 stages (wrap "
                "layer blocks in distinct ht.context(...) scopes)")
        devs = [s.device for s in stages]
        V = self.virtual_stages
        if V > 1:
            # interleaved schedule: S = S_dev * V user stages placed
            # round-robin (stage i on device i % S_dev), each device
            # owning V chunks — the Megatron virtual-stage layout
            if S % V != 0:
                raise ValueError(
                    f"virtual_stages={V} must divide the stage count "
                    f"{S}: build V chunks per device (contexts "
                    f"cycling over the same device list V times)")
            s_dev = S // V
            if len(set(devs[:s_dev])) != s_dev or any(
                    devs[i] != devs[i % s_dev] for i in range(S)):
                raise ValueError(
                    f"interleaved collective pipeline needs round-robin "
                    f"placement: stage i on device i % {s_dev} "
                    f"(got {devs}) — cycle the ht.context(...) device "
                    f"list V={V} times over the same devices")
        else:
            s_dev = S
            if len(set(devs)) != S:
                raise ValueError(
                    "pipeline_mode='collective' needs one distinct "
                    f"device per stage; got {devs} — on a single chip "
                    "use the staged/fused runners instead (or fold "
                    "stages with pp_options virtual_stages)")
        if any(s.mesh is not None for s in stages):
            raise ValueError(
                "pipeline_mode='collective' does not compose with "
                "in-stage TP/DP meshes yet; use the staged runners")
        loss_stage = self.assign[self.loss_node]
        if loss_stage != S - 1:
            raise ValueError(
                f"collective pipeline expects the loss on the last "
                f"stage (found on stage {loss_stage})")
        for i, st in enumerate(stages):
            if i == 0 and st.in_nodes:
                raise ValueError("stage 0 must not consume boundaries")
            if i > 0 and (len(st.in_nodes) != 1 or
                          self.assign[st.in_nodes[0]] != i - 1):
                raise ValueError(
                    f"collective pipeline needs a linear chain with one "
                    f"boundary tensor per stage; stage {i} consumes "
                    f"{[(n.name, self.assign[n]) for n in st.in_nodes]}")
            if i < S - 1 and len(st.consumed_outs) != 1:
                raise ValueError(
                    f"stage {i} must export exactly one boundary tensor "
                    f"(got {len(st.consumed_outs)})")
        shapes0 = [np.shape(executor.params[str(p.id)])
                   for p in stages[0].param_nodes]
        for st in stages[1:]:
            shp = [np.shape(executor.params[str(p.id)])
                   for p in st.param_nodes]
            if shp != shapes0:
                raise ValueError(
                    "collective pipeline needs homogeneous stages: "
                    f"stage {st.index} params {shp} != stage 0 "
                    f"{shapes0} — make the stage blocks uniform or use "
                    "the staged runners")

        machinery = [self._stage_machinery(st)[0] for st in stages]
        # boundary aval: trace the stage chain abstractly once
        rng_aval = jax.eval_shape(lambda: jax.random.PRNGKey(0))
        b_aval = None
        for i, st in enumerate(stages):
            p_avals = {str(p.id): jax.ShapeDtypeStruct(
                np.shape(executor.params[str(p.id)]),
                executor.params[str(p.id)].dtype)
                for p in st.param_nodes}
            f_avals = [jax.ShapeDtypeStruct(f.shape[1:], f.dtype)
                       for f in stacked_feeds[i]]
            ins = [b_aval] if st.in_nodes else []
            outs = jax.eval_shape(machinery[i], p_avals, ins, f_avals,
                                  rng_aval)
            if i < S - 1:
                out_aval = outs[st.out_nodes.index(st.consumed_outs[0])]
                if b_aval is not None and (
                        out_aval.shape != b_aval.shape
                        or out_aval.dtype != b_aval.dtype):
                    raise ValueError(
                        "collective pipeline needs one uniform boundary "
                        f"shape; stage {i} emits {out_aval} after "
                        f"{b_aval}")
                b_aval = out_aval

        loss_node = self.loss_node

        def make_branch(s):
            st = stages[s]
            stage_fn = machinery[s]
            pnodes = list(st.param_nodes)

            def branch(plist, x, feeds, rng):
                params = {str(n.id): v for n, v in zip(pnodes, plist)}
                ins = [x] if st.in_nodes else []
                outs = stage_fn(params, ins, feeds, rng)
                if s < S - 1:
                    y = outs[st.out_nodes.index(st.consumed_outs[0])]
                    # zero loss derived from y so every branch's outputs
                    # share the same varying-over-mesh type (shard_map
                    # rejects mixed unvarying/varying switch branches)
                    return y, (jnp.mean(y) * 0.0).astype(jnp.float32)
                loss = outs[st.out_nodes.index(loss_node)]
                loss = jnp.mean(loss).astype(jnp.float32)
                y = jnp.zeros(b_aval.shape, b_aval.dtype) + \
                    (loss * 0.0).astype(b_aval.dtype)
                return y, loss

            return branch

        mesh = Mesh(np.asarray(devs[:s_dev]), axis_names=("stage",))
        # tick-loop/feed-transport/boundary-dtype/virtual-stage knobs
        # (see CollectiveGPipe docstring); Executor(pp_options={...})
        opts = dict(getattr(self.config, "pp_options", None) or {})
        opts.setdefault("virtual_stages", V)
        cpp = CollectiveGPipe([make_branch(s) for s in range(S)],
                              b_aval, self.num_microbatches, mesh,
                              "stage", self.optimizer,
                              telemetry=self.config.telemetry, **opts)
        self._cpp = cpp
        self._cpp_params = cpp.place_stacked(
            [[executor.params[str(p.id)] for p in st.param_nodes]
             for st in stages])
        # stacked optimizer slots per position (same elementwise
        # update; the interleaved layout folds stages to [S_dev, V])
        from jax.sharding import NamedSharding, PartitionSpec as P
        sh = NamedSharding(mesh, P("stage"))
        slots = []
        full = executor.opt_state or {}
        for j, p0 in enumerate(stages[0].param_nodes):
            keys = sorted(full.get(p0.id, {}))
            slots.append({
                k: jax.device_put(cpp.stack_stage_values(
                    [full[st.param_nodes[j].id][k] for st in stages]),
                    sh)
                for k in keys})
        self._cpp_slots = slots

    def _run_collective(self, executor, stacked_feeds):
        if self._cpp is None:
            self._build_collective(executor, stacked_feeds)
            # ONE jitted unstack for the whole write-back (S*P*slots
            # individual slice dispatches per step would re-introduce
            # the host-dispatch overhead this mode exists to remove).
            # Interleaved layout: stage s lives at [s % S_dev, s // S_dev]
            sd, v = self._cpp.S_dev, self._cpp.V

            def _at(arr, s):
                return arr[s] if v == 1 else arr[s % sd][s // sd]

            self._cpp_unstack = jax.jit(
                lambda ps, ss: (
                    [[_at(p, s) for p in ps]
                     for s in range(len(self.stages))],
                    [[{k: _at(x, s) for k, x in slot.items()}
                      for slot in ss]
                     for s in range(len(self.stages))]))
        loss, new_p, new_s = self._cpp.step(
            self._cpp_params, self._cpp_slots, stacked_feeds,
            executor.base_rng, self.step_count,
            self.optimizer.learning_rate)
        self._cpp_params, self._cpp_slots = new_p, new_s
        # async write-back so save()/tests read fresh values (no host
        # sync: the unstacked views materialize on demand)
        per_stage_p, per_stage_s = self._cpp_unstack(new_p, new_s)
        for s, st in enumerate(self.stages):
            for j, p in enumerate(st.param_nodes):
                executor.params[str(p.id)] = per_stage_p[s][j]
                if per_stage_s[s][j]:
                    executor.opt_state[p.id] = per_stage_s[s][j]
        return loss

    def _run_gpipe_multiproc(self, executor, stacked_feeds, M):
        """GPipe with stages spanning worker processes: each rank runs
        only the stages it owns; boundary activations and cotangents
        cross ranks through the host-mediated p2p channel (reference
        PipelineSend/Recv over NCCL p2p -> numpy over TCP/DCN here).
        Channel recv order doubles as the cross-rank schedule — no
        separate synchronization. Only the rank owning the loss stage
        returns a loss value."""
        from .p2p import get_channel
        ch = get_channel()
        base_rng = executor.base_rng
        lr = np.float32(self.optimizer.learning_rate)
        step = np.int32(self.step_count)
        sc = self.step_count

        consumers_of = lambda node: self._consumers.get(node, ())  # noqa: E731

        env = {}
        ins_store = {}
        for stage in self.stages:
            if stage.owner != self.my_rank:
                continue
            ins = []
            for node in stage.in_nodes:
                src = self.stages[self.assign[node]]
                if src.owner == self.my_rank:
                    val = env[src.index][src.out_nodes.index(node)]
                else:
                    val = self._recv_traced(
                        ch, f"f{sc}:{node.id}:{stage.index}",
                        stage.index)
                ins.append(stage.put(val))
            ins_store[stage.index] = ins
            if stage.consumed_outs:
                with self._stage_span("pp_fwd_block", stage.index):
                    outs = stage.fwd_block(stage.params, ins,
                                           stacked_feeds[stage.index],
                                           base_rng, step)
                env[stage.index] = outs
                for node in stage.consumed_outs:
                    val = None
                    for cons in consumers_of(node):
                        if cons.owner == self.my_rank:
                            continue
                        if val is None:   # one d2h sync per boundary
                            val = np.asarray(
                                outs[stage.out_nodes.index(node)])
                        ch.send(cons.owner,
                                f"f{sc}:{node.id}:{cons.index}", val)

        cot_map = {}
        loss_mean = None
        for stage in reversed(self.stages):
            if stage.owner != self.my_rank:
                continue
            cots = []
            for node in stage.out_nodes:
                c = cot_map.get(node)
                for cons in consumers_of(node):
                    if cons.owner == self.my_rank:
                        continue   # local consumers summed via cot_map
                    d = stage.put(self._recv_traced(
                        ch, f"b{sc}:{node.id}:{cons.index}",
                        stage.index))
                    c = d if c is None else c + d
                cots.append(c)
            with self._stage_span("pp_bwd_block", stage.index):
                new_params, new_state, stacked_dins, lm = stage.bwd_block(
                    stage.params, ins_store[stage.index],
                    stacked_feeds[stage.index], base_rng, step, cots,
                    self._stage_opt_state(executor, stage), lr)
            if lm is not None:
                loss_mean = lm
            for node, d in zip(stage.in_nodes, stacked_dins):
                src = self.stages[self.assign[node]]
                if src.owner == self.my_rank:
                    d = src.put(d)
                    prev = cot_map.get(node)
                    cot_map[node] = d if prev is None else prev + d
                else:
                    ch.send(src.owner,
                            f"b{sc}:{node.id}:{stage.index}",
                            np.asarray(d))
            self._commit_stage_update(executor, stage, new_params,
                                      new_state)
        return loss_mean

    def _run_1f1b_multiproc(self, executor, feeds, M):
        """1F1B across worker processes: each rank executes its
        projection of the SAME global schedule as the in-process
        `_run_1f1b` (uniform warmup, then alternate), so the math —
        which weight version each microbatch's forward sees — is
        bit-identical to single-process PipeDream; blocking channel
        recvs turn the data dependencies into the cross-rank schedule
        (the channel's reader thread drains sockets, so sends never
        rendezvous and the projected order cannot deadlock). Returns
        the per-step mean loss on the loss-owning rank, None elsewhere
        (same contract as `_run_gpipe_multiproc`)."""
        from .p2p import get_channel
        ch = get_channel()
        sc = self.step_count
        base_rng = executor.base_rng
        lr = np.float32(self.optimizer.learning_rate)
        step = np.int32(self.step_count)
        own = [s for s in self.stages if s.owner == self.my_rank]
        loss_sidx = self.assign[self.loss_node]
        env_out, stage_ins, stash, cot_map = {}, {}, {}, {}
        losses = []

        consumers_of = lambda node: self._consumers.get(node, ())  # noqa: E731

        def forward(m):
            stash[m] = {s.index: dict(s.params) for s in own}
            for stage in own:
                ins = []
                for node in stage.in_nodes:
                    src = self.stages[self.assign[node]]
                    if src.owner == self.my_rank:
                        val = env_out[(m, src.index)][
                            src.out_nodes.index(node)]
                    else:
                        val = self._recv_traced(
                            ch, f"pf{sc}:{m}:{node.id}:{stage.index}",
                            stage.index)
                    ins.append(stage.put(val))
                outs = stage.fwd(stage.params, ins,
                                 feeds[stage.index][m], base_rng, step,
                                 np.int32(m))
                env_out[(m, stage.index)] = outs
                stage_ins[(m, stage.index)] = ins
                for node in stage.consumed_outs:
                    val = None
                    for cons in consumers_of(node):
                        if cons.owner == self.my_rank:
                            continue
                        if val is None:   # one d2h per boundary tensor
                            val = np.asarray(
                                outs[stage.out_nodes.index(node)])
                        ch.send(cons.owner,
                                f"pf{sc}:{m}:{node.id}:{cons.index}",
                                val)
            if self.stages[loss_sidx].owner == self.my_rank:
                losses.append(env_out[(m, loss_sidx)][
                    self.stages[loss_sidx].out_nodes.index(
                        self.loss_node)])

        def backward(m):
            for stage in reversed(own):
                cots = []
                for node in stage.out_nodes:
                    c = cot_map.get((m, node))
                    for cons in consumers_of(node):
                        if cons.owner == self.my_rank:
                            continue   # local consumers summed in map
                        d = stage.put(self._recv_traced(
                            ch, f"pb{sc}:{m}:{node.id}:{cons.index}",
                            stage.index))
                        c = d if c is None else c + d
                    cots.append(c)
                dins, new_params, new_state = stage.bwd_apply(
                    stash[m][stage.index], stage.params,
                    stage_ins.pop((m, stage.index)),
                    feeds[stage.index][m], base_rng, step, np.int32(m),
                    cots, self._stage_opt_state(executor, stage), lr)
                for node, d in zip(stage.in_nodes, dins):
                    src = self.stages[self.assign[node]]
                    if src.owner == self.my_rank:
                        d = src.put(d)
                        prev = cot_map.get((m, node))
                        cot_map[(m, node)] = d if prev is None \
                            else prev + d
                    else:
                        ch.send(src.owner,
                                f"pb{sc}:{m}:{node.id}:{stage.index}",
                                np.asarray(d))
                self._commit_stage_update(executor, stage, new_params,
                                          new_state)
            del stash[m]
            for s in own:
                env_out.pop((m, s.index), None)
            # boundary cotangents were consumed within this backward
            # (reversed stage order): free them with the stash
            for key in [k for k in cot_map if k[0] == m]:
                del cot_map[key]

        _drive_1f1b(forward, backward, len(self.stages), M,
                    telemetry=self.config.telemetry)
        if losses:
            return jnp.mean(jnp.stack([jnp.asarray(l) for l in losses]))
        return None

    def _run_1f1b(self, executor, feeds, M):
        """1F1B: warmup forwards then alternate, per-microbatch updates
        with stashed weights (reference SubExecutor4Pipedream)."""
        env_out = {}
        stage_ins = {}
        stash = {}
        losses = []
        base_rng = executor.base_rng
        lr = np.float32(self.optimizer.learning_rate)
        step = np.int32(self.step_count)
        nstages = len(self.stages)
        cot_map = {}

        def forward(m):
            stash[m] = [dict(s.params) for s in self.stages]
            for stage in self.stages:
                ins = self._fwd_stage(stage, m, feeds, env_out,
                                      base_rng, step)
                stage_ins[(m, stage.index)] = ins
            loss_stage = self.assign[self.loss_node]
            losses.append(env_out[(m, loss_stage)][
                self.stages[loss_stage].out_nodes.index(self.loss_node)])

        def backward(m):
            for stage in reversed(self.stages):
                cots = [cot_map.get((m, n)) for n in stage.out_nodes]
                dins, new_params, new_state = stage.bwd_apply(
                    stash[m][stage.index], stage.params,
                    stage_ins[(m, stage.index)], feeds[stage.index][m],
                    base_rng, step, np.int32(m), cots,
                    self._stage_opt_state(executor, stage), lr)
                for node, d in zip(stage.in_nodes, dins):
                    d = self.stages[self.assign[node]].put(d)
                    prev = cot_map.get((m, node))
                    cot_map[(m, node)] = d if prev is None else prev + d
                self._commit_stage_update(executor, stage, new_params,
                                          new_state)
            del stash[m]
            # free this microbatch's activations/cotangents with its
            # stash — 1F1B's bounded in-flight memory depends on it
            for stage in self.stages:
                env_out.pop((m, stage.index), None)
                stage_ins.pop((m, stage.index), None)
            for key in [k for k in cot_map if k[0] == m]:
                del cot_map[key]

        _drive_1f1b(forward, backward, nstages, M,
                    telemetry=self.config.telemetry)
        return losses           # device values: no host sync per loss
