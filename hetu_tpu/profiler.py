"""Tracing / profiling (reference parity: the per-op profiler hooks in
gpu_ops/executor.py's p32/p16 timer paths and the HetuProfiler).

Three levels:

* ``StepLogger`` — per-step wall-time timeline appended as JSON lines
  (plus the PS runtime's phase counters when a PS session is active);
  enabled by ``Executor(..., log_path=...)``.
* ``profile_ops(executor, feed_dict)`` — per-op timing: runs the step
  eagerly op by op with a sync after each, returning (and optionally
  printing) the cost ranking. Eager timing is orders slower than the
  jitted step — it attributes cost, it does not measure the fused step.
* ``trace(logdir)`` — context manager over ``jax.profiler`` for XLA/TPU
  traces viewable in TensorBoard/Perfetto.
"""
from __future__ import annotations

import contextlib
import json
import time

import numpy as np

__all__ = ["StepLogger", "profile_ops", "profile_op_records", "trace"]


class StepLogger:
    """Appends one JSON line per step: wall ms, step index, optional
    extra phase dict. Kept as a compat wrapper over the telemetry layer
    (hetu_tpu/telemetry): when constructed with a Telemetry instance it
    mirrors each step into the span trace and the ``step_wall_ms``
    histogram, so the JSONL timeline and the Perfetto trace agree."""

    def __init__(self, path, telemetry=None):
        self.path = path
        self._f = open(path, "a")
        self._t0 = None
        self._phase_snap = {}
        self.step = 0
        self.telemetry = telemetry

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    def begin(self):
        self._t0 = time.perf_counter()

    def end(self, executor=None, **extra):
        dt = (time.perf_counter() - self._t0) * 1000 \
            if self._t0 is not None else None
        # `dt is not None`, NOT truthiness: a clock-granularity 0.0 ms
        # step is a real measurement, null means begin() never ran
        rec = {"step": self.step,
               "wall_ms": round(dt, 3) if dt is not None else None}
        rt = getattr(executor, "ps_runtime", None) if executor else None
        if rt is not None:
            # rt.times accumulates for the runtime's life: log the DELTA
            # since the previous step, which is this step's cost
            delta = {k: v - self._phase_snap.get(k, 0.0)
                     for k, v in rt.times.items()}
            self._phase_snap = dict(rt.times)
            rec["ps_phases_ms"] = {k: round(v * 1000, 3)
                                   for k, v in delta.items() if v > 0}
        rec.update(extra)
        self._f.write(json.dumps(rec) + "\n")
        self._f.flush()
        tel = self.telemetry
        if tel is not None and tel.enabled and dt is not None:
            tel.instant("step_logged", step=self.step,
                        wall_ms=rec["wall_ms"])
            tel.observe("steplogger_wall_ms", dt)
        self.step += 1

    def close(self):
        if not self._f.closed:
            self._f.close()

    @property
    def closed(self):
        return self._f.closed


def profile_op_records(executor, feed_dict=None, name="default",
                       costdb=None):
    """Per-op cost attribution with full op *identity*: execute the
    step's topo order eagerly, blocking after each op, and return one
    record per op — ``{"name", "kind", "shape", "dtype", "ms"}`` —
    with exactly the fields a ``telemetry.costdb.CostDB`` entry is
    keyed on. ``costdb=`` (a CostDB instance or a path) folds every
    record straight into the persistent database, source-tagged
    ``profile_ops``."""
    import jax

    from .graph.node import ExecContext
    from .ops.variable import PlaceholderOp

    sub = executor.subexecutors[name]
    feed_map = {}
    for node, value in (feed_dict or {}).items():
        feed_map[node] = sub._ingest(value)
    for dl in sub.dataloader_ops:
        feed_map[dl] = sub._ingest(dl.get_arr(sub.name))
    sub._infer_shapes(feed_map)
    sub._ensure_state(executor)

    ectx = ExecContext(training=False, base_rng=executor.base_rng,
                       config=sub.config)
    ectx.params = {n: executor.params[str(n.id)] for n in sub.param_nodes}
    ectx.state = {n: executor.state.get(str(n.id), {})
                  for n in sub.stateful_ops}
    ectx.opt_state = executor.opt_state
    ectx.lr = np.float32(0.0)
    ectx.step = 0

    env = dict(feed_map)
    records = []
    for node in sub.topo_order:
        if node in env or node in sub.optimizer_ops:
            continue
        if node in ectx.params:
            env[node] = ectx.params[node]
            continue
        if isinstance(node, PlaceholderOp):
            env[node] = None
            continue
        ins = [env[i] for i in node.inputs]
        t0 = time.perf_counter()
        out = node.compute(ins, ectx)
        try:
            jax.block_until_ready(out)
        except Exception:
            pass                      # pytree values (IndexedSlices etc.)
        ms = (time.perf_counter() - t0) * 1000
        dtype = getattr(out, "dtype", None)
        records.append({
            "name": node.name,
            "kind": type(node).__name__,
            "shape": getattr(node, "inferred_shape", None),
            "dtype": str(dtype) if dtype is not None else "float32",
            "ms": ms})
        env[node] = out
    records.sort(key=lambda r: -r["ms"])
    if costdb is not None:
        from .telemetry.costdb import CostDB, record_profile
        db = costdb if isinstance(costdb, CostDB) else CostDB(costdb)
        record_profile(db, records)
    return records


def profile_ops(executor, feed_dict=None, name="default", top=20,
                printout=True, costdb=None):
    """Per-op cost attribution: execute the step's topo order eagerly,
    blocking after each op (reference HetuProfiler's per-node timers).
    Returns [(op_name, ms)] sorted by cost; ``costdb=`` additionally
    persists each measurement (see ``profile_op_records``)."""
    records = profile_op_records(executor, feed_dict, name=name,
                                 costdb=costdb)
    times = [(r["name"], r["ms"]) for r in records]
    if printout:
        total = sum(t for _, t in times)
        print(f"per-op profile ({len(times)} ops, eager total "
              f"{total:.1f} ms — attribution only; the jitted step "
              f"fuses these):")
        for opname, ms in times[:top]:
            print(f"  {ms:8.3f} ms  {opname}")
    return times


@contextlib.contextmanager
def trace(logdir):
    """XLA/TPU trace via jax.profiler (TensorBoard/Perfetto viewable)."""
    import jax

    jax.profiler.start_trace(logdir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
