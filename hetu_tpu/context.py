"""Device placement and partition state.

Reference parity: python/hetu/context.py — ``DeviceGroup`` (device-spec
parsing, worker/server split), the ``with ht.context(...)`` stack, and
``NodeStatus`` (per-node partition state: split counts per dim, replica
count, device order).

TPU-native twist: the reference *realizes* a NodeStatus by rewriting the
graph with split/concat/add + NCCL send/recv (context.py:256-726). Here a
NodeStatus lowers to a ``jax.sharding.PartitionSpec`` over a named mesh and
XLA's SPMD partitioner materializes any resharding as ICI collectives —
``NodeStatus.to_partition_spec`` is the entire planner.
"""
from __future__ import annotations

import contextlib
import re

import numpy as np

from .ndarray import DLContext, rcpu, rtpu, is_gpu_ctx

__all__ = ["DeviceGroup", "NodeStatus", "context", "get_current_context",
           "get_launch_config_by_traverse_nodes", "check_worker"]


class DeviceGroup:
    """An ordered set of device contexts; a tuple entry means those devices
    cooperate on one model-parallel copy (reference context.py:7-96)."""

    def __init__(self, ctxs):
        self._contexts = self.parse_contexts(ctxs)
        self._classify()

    @classmethod
    def parse_contexts(cls, ctxs):
        if isinstance(ctxs, DeviceGroup):
            return list(ctxs._contexts)
        if isinstance(ctxs, str):
            ctxs = re.split(";|,| +", ctxs.lower())
        if not isinstance(ctxs, list):
            ctxs = [ctxs]
        parsed = []
        for c in ctxs:
            if isinstance(c, tuple):
                c = tuple(x for x in (cls.str2ctx(cc) for cc in c)
                          if x is not None)
            else:
                c = cls.str2ctx(c)
            if c is not None:
                parsed.append(c)
        return parsed

    @classmethod
    def str2ctx(cls, c):
        if isinstance(c, str):
            parts = c.lower().split(":")
            assert parts[-2] in ("cpu", "gpu", "tpu"), f"bad context: {c}"
            hostname = "localhost" if len(parts) == 2 else parts[0]
            idx = int(parts[-1])
            if parts[-2] == "cpu":
                return rcpu(hostname, idx)
            return rtpu(hostname, idx)
        assert c is None or isinstance(c, DLContext), f"bad context: {c}"
        return c

    def _classify(self):
        self._workers, self._servers = [], []
        for ctx in self._contexts:
            if isinstance(ctx, tuple) or is_gpu_ctx(ctx):
                self._workers.append(ctx)
            else:
                self._servers.append(ctx)

    def index(self, ctx):
        return self._contexts.index(ctx)

    def __getitem__(self, key):
        return self._contexts[key]

    def __iter__(self):
        return iter(self._contexts)

    def __len__(self):
        return len(self._contexts)

    @property
    def worker_num(self):
        return len(self._workers)

    @property
    def server_num(self):
        return len(self._servers)

    @property
    def workers(self):
        return self._workers

    @property
    def servers(self):
        return self._servers

    def flat_workers(self):
        """All worker device contexts, model-parallel tuples flattened."""
        out = []
        for w in self._workers:
            out.extend(w if isinstance(w, tuple) else (w,))
        return out

    def get_sorted(self):
        return DeviceGroup(sorted(
            self._contexts, key=lambda x: hash(x.hostname) + hash(x.device_id)))

    def __repr__(self):
        body = []
        for c in self._contexts:
            body.append("(" + ", ".join(map(str, c)) + ")"
                        if isinstance(c, tuple) else str(c))
        return "DeviceGroup(" + ", ".join(body) + ")"

    def __hash__(self):
        if not hasattr(self, "_hash"):
            self._hash = hash(tuple(self._contexts))
        return self._hash

    def __eq__(self, other):
        return isinstance(other, DeviceGroup) and hash(self) == hash(other)


class _ContextStack:
    def __init__(self):
        self._stack = []

    def peek(self):
        return self._stack[-1] if self._stack else None

    def push(self, ctx):
        self._stack.append(ctx)

    def pop(self):
        self._stack.pop()


_default_ctx_stack = _ContextStack()


def get_current_context():
    return _default_ctx_stack.peek()


@contextlib.contextmanager
def context(ctx):
    try:
        ctx = DeviceGroup(ctx)
        _default_ctx_stack.push(ctx)
        yield ctx
    finally:
        _default_ctx_stack.pop()


def check_worker(ctx):
    return isinstance(ctx, tuple) or is_gpu_ctx(ctx)


class NodeStatus:
    """Partition state of one graph node (reference context.py:116-193).

    * ``state``     — tuple of split counts per tensor dim, e.g. (1, 2)
                      splits dim 1 two ways.
    * ``duplicate`` — number of replicas of each shard.
    * ``order``     — device-order permutation over dims, -1 = replica axis.

    ``to_partition_spec`` maps this onto mesh axis names: split dims bind to
    model axes, the replica axis stays unsharded. XLA then inserts whatever
    collectives a state transition needs — the TPU-native replacement for
    the reference's cross_send/cross_receive planner (context.py:352-512).
    """

    def __init__(self, state=None, duplicate=None, order=None):
        if isinstance(state, dict):
            ndim = max(state) + 1 if state else 0
            state = tuple(state.get(i, 1) for i in range(ndim))
        self._state = tuple(state) if state is not None else None
        self._duplicate = duplicate
        self._order = tuple(order) if order is not None else None
        self._defaulted = False
        self._try_device_num()

    @classmethod
    def from_other(cls, other):
        if other is None:
            return cls(None, None, None)
        return cls(other._state, other._duplicate, other._order)

    # -- accessors ----------------------------------------------------------
    @property
    def state(self):
        return self._state

    @property
    def duplicate(self):
        return self._duplicate

    @property
    def order(self):
        return self._order

    @property
    def device_num(self):
        return self._device_num

    def is_dist(self):
        return not (self._state is None or all(x == 1 for x in self._state))

    def get_default(self):
        self._defaulted = True
        if self._duplicate is None:
            self._duplicate = 1
        if self._order is None:
            self._order = (-1,) + tuple(range(len(self._state)))
        self._try_device_num()
        return self._state, self._duplicate, self._order

    def set_attr(self, duplicate, order):
        if self._defaulted:
            assert self._duplicate == duplicate and self._order == tuple(order)
        else:
            self._duplicate = duplicate
            self._order = tuple(order)
            self._try_device_num()

    def set_state(self, state):
        if isinstance(state, dict):
            ndim = max(state) + 1 if state else 0
            state = tuple(state.get(i, 1) for i in range(ndim))
        self._state = tuple(state)
        self._try_device_num()

    def _try_device_num(self):
        self._device_num = (
            None if self._duplicate is None or self._state is None
            else int(np.prod(self._state, dtype=int)) * self._duplicate)

    def check_devices(self, devices):
        assert self._device_num == len(devices), \
            f"status wants {self._device_num} devices, got {len(devices)}"

    # -- device-index algebra ----------------------------------------------
    # Verified against jax.sharding.NamedSharding's device->shard map in
    # tests/test_parallel.py::test_order_algebra_matches_named_sharding:
    # a mesh whose axes follow ``order`` (major->minor) places shards on
    # exactly the devices this algebra predicts.
    def map_dev_to_index(self, global_index):
        """Which shard coordinates the global_index-th device holds."""
        coords = [0] * len(self._state)
        for dim in self._order[::-1]:
            if dim < 0:
                global_index //= self._duplicate
            else:
                coords[dim] = global_index % self._state[dim]
                global_index //= self._state[dim]
        return coords

    def get_loop_sizes(self):
        loop_sizes = [1]
        for dim in self._order[::-1]:
            step = self._duplicate if dim < 0 else self._state[dim]
            loop_sizes.insert(0, loop_sizes[0] * step)
        loop_sizes.pop(0)
        return loop_sizes

    # -- TPU lowering -------------------------------------------------------
    def to_partition_spec(self, mesh_axes=None):
        """Lower to a jax PartitionSpec.

        mesh_axes: mapping from tensor dim -> mesh axis name. By default the
        i-th split dim (in order) binds to axis ``'mp%d' % k``; callers in
        parallel/ pass explicit names ('dp', 'tp', ...).
        """
        from jax.sharding import PartitionSpec
        if self._state is None or not self.is_dist():
            return PartitionSpec()
        spec = []
        k = 0
        for dim, parts in enumerate(self._state):
            if parts > 1:
                if mesh_axes and dim in mesh_axes:
                    spec.append(mesh_axes[dim])
                else:
                    spec.append(f"mp{k}")
                k += 1
            else:
                spec.append(None)
        while spec and spec[-1] is None:
            spec.pop()
        return PartitionSpec(*spec)

    def mesh_shape(self):
        """(axis_names, sizes) for building a Mesh that fits this status."""
        names, sizes = [], []
        k = 0
        for parts in self._state or ():
            if parts > 1:
                names.append(f"mp{k}")
                sizes.append(parts)
                k += 1
        if self._duplicate and self._duplicate > 1:
            names.append("dup")
            sizes.append(self._duplicate)
        return names, sizes

    def __eq__(self, other):
        return (isinstance(other, NodeStatus)
                and self._state == other._state
                and self._duplicate == other._duplicate
                and self._order == other._order)

    def __hash__(self):
        return hash((self._state, self._duplicate, self._order))

    def __repr__(self):
        return (f"NodeStatus(state={self._state}, "
                f"duplicate={self._duplicate}, order={self._order})")


def get_launch_config_by_traverse_nodes(node_list, default_ctx):
    """Infer per-node comm strategy + the device set (reference
    context.py:216-254): a node whose group has servers uses PS; a node on
    >1 workers uses AllReduce; else local."""
    node_strategy = {}
    devices = set()
    for ctx in default_ctx:
        devices.update(ctx if isinstance(ctx, tuple) else (ctx,))
    launch_ps = default_ctx.server_num > 0 and default_ctx.worker_num > 0
    launch_mpi = (not launch_ps) and default_ctx.worker_num > 1
    nrank = default_ctx.worker_num

    def visit(node):
        if node in node_strategy:
            return
        strategy = None
        raw = node.raw_ctx
        if raw is not None and raw.server_num > 0 and raw.worker_num > 0:
            strategy = "PS"
        elif raw is not None and raw.worker_num > 1:
            strategy = "AllReduce"
        node_strategy[node] = strategy
        if raw is not None:
            for ctx in raw:
                devices.update(ctx if isinstance(ctx, tuple) else (ctx,))
            local_nrank = raw.worker_num
            # nrank == 0: single-process SPMD (e.g. a PP+TP pipeline whose
            # stages are device tuples) — there is no worker fleet to match
            assert nrank == 0 or local_nrank in (0, nrank), \
                f"inconsistent worker counts: ({local_nrank}, {nrank})"
        for n in node.inputs:
            visit(n)

    for node in node_list:
        visit(node)
    launch_ps = launch_ps or any(s == "PS" for s in node_strategy.values())
    launch_mpi = launch_mpi or any(
        s == "AllReduce" for s in node_strategy.values())
    return launch_mpi, launch_ps, node_strategy, devices
