"""Evaluation metrics (reference parity: python/hetu/metrics.py — numpy
confusion-matrix metrics and AUC)."""
from __future__ import annotations

import numpy as np

__all__ = ["accuracy", "precision", "recall", "f1_score", "auc",
           "confusion_matrix", "ConfusionMatrix"]


def _to_labels(y, axis=-1):
    y = np.asarray(y)
    if y.ndim > 1 and y.shape[axis] > 1:
        return np.argmax(y, axis=axis)
    return y.reshape(-1).astype(np.int64)


def confusion_matrix(y_pred, y_true, num_classes=None):
    p = _to_labels(y_pred)
    t = _to_labels(y_true)
    if num_classes is None:
        num_classes = int(max(p.max(), t.max())) + 1
    cm = np.zeros((num_classes, num_classes), dtype=np.int64)
    np.add.at(cm, (t, p), 1)
    return cm


def accuracy(y_pred, y_true):
    p = _to_labels(y_pred)
    t = _to_labels(y_true)
    return float((p == t).mean())


def precision(y_pred, y_true, cls=1):
    cm = confusion_matrix(y_pred, y_true)
    denom = cm[:, cls].sum()
    return float(cm[cls, cls] / denom) if denom else 0.0


def recall(y_pred, y_true, cls=1):
    cm = confusion_matrix(y_pred, y_true)
    denom = cm[cls, :].sum()
    return float(cm[cls, cls] / denom) if denom else 0.0


def f1_score(y_pred, y_true, cls=1):
    p = precision(y_pred, y_true, cls)
    r = recall(y_pred, y_true, cls)
    return 2 * p * r / (p + r) if (p + r) else 0.0


def auc(y_score, y_true):
    """ROC AUC by rank statistic (reference metrics.py AUC)."""
    y_score = np.asarray(y_score).reshape(-1)
    y_true = np.asarray(y_true).reshape(-1)
    order = np.argsort(y_score)
    ranks = np.empty_like(order, dtype=np.float64)
    ranks[order] = np.arange(1, len(y_score) + 1)
    # average ranks for ties
    uniq, inv, counts = np.unique(y_score, return_inverse=True,
                                  return_counts=True)
    cum = np.cumsum(counts)
    avg_rank = (cum - (counts - 1) / 2.0)
    ranks = avg_rank[inv]
    n_pos = y_true.sum()
    n_neg = len(y_true) - n_pos
    if n_pos == 0 or n_neg == 0:
        return 0.5
    return float((ranks[y_true == 1].sum()
                  - n_pos * (n_pos + 1) / 2) / (n_pos * n_neg))


class ConfusionMatrix:
    """Streaming confusion-matrix accumulator."""

    def __init__(self, num_classes):
        self.num_classes = num_classes
        self.cm = np.zeros((num_classes, num_classes), dtype=np.int64)

    def update(self, y_pred, y_true):
        self.cm += confusion_matrix(y_pred, y_true, self.num_classes)

    def accuracy(self):
        total = self.cm.sum()
        return float(np.trace(self.cm) / total) if total else 0.0

    def reset(self):
        self.cm[:] = 0
