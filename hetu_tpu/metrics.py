"""Evaluation metrics (reference parity: python/hetu/metrics.py — numpy
confusion-matrix metrics, thresholded confusion series, ROC/PR curves and
Riemann-sum AUC, one-hot precision/recall/F with micro/macro averaging),
plus a streaming thresholded-AUC accumulator for epoch-scale evaluation
without keeping every score in memory."""
from __future__ import annotations

import numpy as np

__all__ = ["accuracy", "precision", "recall", "f1_score", "auc",
           "confusion_matrix", "ConfusionMatrix", "softmax",
           "confusion_matrix_at_thresholds", "roc_pr_curve",
           "auc_at_thresholds", "confusion_matrix_one_hot",
           "precision_score", "recall_score", "f_score", "StreamingAUC"]


def _to_labels(y, axis=-1):
    y = np.asarray(y)
    if y.ndim > 1 and y.shape[axis] > 1:
        return np.argmax(y, axis=axis)
    return y.reshape(-1).astype(np.int64)


def confusion_matrix(y_pred, y_true, num_classes=None):
    p = _to_labels(y_pred)
    t = _to_labels(y_true)
    if num_classes is None:
        num_classes = int(max(p.max(), t.max())) + 1
    cm = np.zeros((num_classes, num_classes), dtype=np.int64)
    np.add.at(cm, (t, p), 1)
    return cm


def accuracy(y_pred, y_true):
    p = _to_labels(y_pred)
    t = _to_labels(y_true)
    return float((p == t).mean())


def precision(y_pred, y_true, cls=1):
    cm = confusion_matrix(y_pred, y_true)
    denom = cm[:, cls].sum()
    return float(cm[cls, cls] / denom) if denom else 0.0


def recall(y_pred, y_true, cls=1):
    cm = confusion_matrix(y_pred, y_true)
    denom = cm[cls, :].sum()
    return float(cm[cls, cls] / denom) if denom else 0.0


def f1_score(y_pred, y_true, cls=1):
    p = precision(y_pred, y_true, cls)
    r = recall(y_pred, y_true, cls)
    return 2 * p * r / (p + r) if (p + r) else 0.0


def auc(y_score, y_true):
    """ROC AUC by rank statistic (reference metrics.py AUC)."""
    y_score = np.asarray(y_score).reshape(-1)
    y_true = np.asarray(y_true).reshape(-1)
    order = np.argsort(y_score)
    ranks = np.empty_like(order, dtype=np.float64)
    ranks[order] = np.arange(1, len(y_score) + 1)
    # average ranks for ties
    uniq, inv, counts = np.unique(y_score, return_inverse=True,
                                  return_counts=True)
    cum = np.cumsum(counts)
    avg_rank = (cum - (counts - 1) / 2.0)
    ranks = avg_rank[inv]
    n_pos = y_true.sum()
    n_neg = len(y_true) - n_pos
    if n_pos == 0 or n_neg == 0:
        return 0.5
    return float((ranks[y_true == 1].sum()
                  - n_pos * (n_pos + 1) / 2) / (n_pos * n_neg))


def softmax(logits, axis=-1):
    """Row-wise softmax (reference metrics.py softmax_func)."""
    z = np.asarray(logits, dtype=np.float64)
    z = z - z.max(axis=axis, keepdims=True)
    e = np.exp(z)
    return e / e.sum(axis=axis, keepdims=True)


def _threshold_counts(y_score, y_true, thresholds):
    """Vectorized tp/fp counts per threshold via sorted cumulative sums
    (O(n log n) instead of the reference's O(n*T) tiling,
    metrics.py:17-76 — same counts)."""
    s = np.asarray(y_score, dtype=np.float64).reshape(-1)
    t_raw = np.asarray(y_true).reshape(-1)
    # argument order is (scores, labels) — the reverse of the reference's
    # (labels, predictions); a swapped call passes continuous scores
    # here, so insist on binary labels rather than computing garbage
    if not np.isin(t_raw, (0, 1)).all():
        raise ValueError(
            "y_true must be binary 0/1 labels — note hetu_tpu's "
            "threshold metrics take (y_score, y_true), the reverse of "
            "the reference's (labels, predictions) order")
    t = t_raw.astype(bool)
    order = np.argsort(s)
    s_sorted = s[order]
    pos_cum = np.concatenate([[0], np.cumsum(t[order])]).astype(np.float64)
    n, n_pos = len(s), float(t.sum())
    thr = np.asarray(thresholds, dtype=np.float64)
    # predictions > thr are positive: count of scores <= thr per thr
    idx = np.searchsorted(s_sorted, thr, side="right")
    pos_below = pos_cum[idx]               # positives predicted negative
    tp = n_pos - pos_below
    fp = (n - idx) - tp
    fn = pos_below
    tn = idx - fn
    return tp, fp, fn, tn


def confusion_matrix_at_thresholds(y_score, y_true, thresholds,
                                   includes=None):
    """Dict of tp/fn/tn/fp arrays of shape [len(thresholds)] — scores
    above a threshold count as predicted-positive (reference
    metrics.py:17-76)."""
    all_keys = ("tp", "fn", "tn", "fp")
    includes = all_keys if includes is None else tuple(includes)
    for k in includes:
        if k not in all_keys:
            raise ValueError(f"invalid key: {k}")
    tp, fp, fn, tn = _threshold_counts(y_score, y_true, thresholds)
    values = {"tp": tp, "fp": fp, "fn": fn, "tn": tn}
    return {k: values[k] for k in includes}


def roc_pr_curve(values, curve="ROC"):
    """(x, y) of the ROC (fpr, tpr) or PR (recall, precision) curve from
    thresholded confusion counts (reference metrics.py:79-117)."""
    for k in ("tp", "fp", "fn", "tn"):
        if k not in values:
            raise ValueError(f"values must have the key {k}")
    eps = 1.0e-6
    tp, fp, fn, tn = (values[k] for k in ("tp", "fp", "fn", "tn"))
    rec = (tp + eps) / (tp + fn + eps)
    if curve == "ROC":
        return (fp + eps) / (fp + tn + eps), rec
    return rec, (tp + eps) / (tp + fp + eps)


def _default_thresholds(num_thresholds):
    eps = 1e-7
    inner = [(i + 1) / (num_thresholds - 1)
             for i in range(num_thresholds - 2)]
    return np.asarray([-eps] + inner + [1.0 + eps])


def _trapezoid_auc(values, curve):
    x, y = roc_pr_curve(values, curve=curve)
    return float(np.sum((x[:-1] - x[1:]) * (y[:-1] + y[1:]) / 2.0))


def auc_at_thresholds(y_score, y_true, num_thresholds=200, curve="ROC"):
    """Riemann-sum AUC over a threshold grid — ROC or PR (reference
    metrics.py:120-151; the rank-statistic :func:`auc` is exact for ROC,
    this one also covers PR and matches the reference's discretized
    estimate)."""
    thr = _default_thresholds(num_thresholds)
    return _trapezoid_auc(
        confusion_matrix_at_thresholds(y_score, y_true, thr), curve)


def confusion_matrix_one_hot(y_pred, y_true):
    """Per-class tp/fp/tn/fn from score rows and one-hot labels
    (argmax prediction; reference metrics.py:170-217; argument order
    follows this module's pred-first convention)."""
    t = np.asarray(y_true).astype(bool)
    p = np.eye(t.shape[1], dtype=bool)[np.argmax(y_pred, axis=1)]
    return {
        "tp": (t & p).sum(0).astype(np.float64),
        "fp": (~t & p).sum(0).astype(np.float64),
        "tn": (~t & ~p).sum(0).astype(np.float64),
        "fn": (t & ~p).sum(0).astype(np.float64),
    }


def _prf(values, num_key, den_key, average):
    eps = 1.0e-6
    a, b = values[num_key], values[den_key]
    if average == "micro":
        a, b = a.sum(), b.sum()
    score = (a + eps) / (a + b + eps)
    if average == "macro":
        return float(np.mean(score))
    return float(score) if average == "micro" else score


def precision_score(y_pred, y_true, average=None):
    """One-hot precision, per-class / 'micro' / 'macro' (reference
    metrics.py:220-265)."""
    if average not in (None, "micro", "macro"):
        raise ValueError(f"invalid average: {average}")
    return _prf(confusion_matrix_one_hot(y_pred, y_true),
                "tp", "fp", average)


def recall_score(y_pred, y_true, average=None):
    """One-hot recall, per-class / 'micro' / 'macro' (reference
    metrics.py:268-312)."""
    if average not in (None, "micro", "macro"):
        raise ValueError(f"invalid average: {average}")
    return _prf(confusion_matrix_one_hot(y_pred, y_true),
                "tp", "fn", average)


def f_score(y_pred, y_true, beta=1.0, average=None):
    """One-hot F-beta from precision/recall; macro averages the
    per-class F values (reference metrics.py:315-359)."""
    if beta < 0:
        raise ValueError("beta should be >=0 in the F-beta score")
    beta2 = beta * beta
    p = precision_score(y_pred, y_true,
                        average=None if average == "macro" else average)
    r = recall_score(y_pred, y_true,
                     average=None if average == "macro" else average)
    f = (1 + beta2) * p * r / (beta2 * p + r)
    return float(np.mean(f)) if average == "macro" else f


class StreamingAUC:
    """Thresholded-AUC accumulator: per-batch updates add confusion
    counts on a fixed grid, so epoch AUC needs O(num_thresholds) memory
    instead of every score (new capability — the reference recomputes
    from full arrays)."""

    def __init__(self, num_thresholds=200, curve="ROC"):
        self.thresholds = _default_thresholds(num_thresholds)
        self.curve = curve
        self.reset()

    def reset(self):
        z = np.zeros(len(self.thresholds))
        self.counts = {"tp": z.copy(), "fp": z.copy(),
                       "fn": z.copy(), "tn": z.copy()}

    def update(self, y_score, y_true):
        tp, fp, fn, tn = _threshold_counts(y_score, y_true,
                                           self.thresholds)
        self.counts["tp"] += tp
        self.counts["fp"] += fp
        self.counts["fn"] += fn
        self.counts["tn"] += tn

    def result(self):
        return _trapezoid_auc(self.counts, self.curve)


class ConfusionMatrix:
    """Streaming confusion-matrix accumulator."""

    def __init__(self, num_classes):
        self.num_classes = num_classes
        self.cm = np.zeros((num_classes, num_classes), dtype=np.int64)

    def update(self, y_pred, y_true):
        self.cm += confusion_matrix(y_pred, y_true, self.num_classes)

    def accuracy(self):
        total = self.cm.sum()
        return float(np.trace(self.cm) / total) if total else 0.0

    def reset(self):
        self.cm[:] = 0
