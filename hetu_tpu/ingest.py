"""Async host-ingest engine: hide the host behind the device.

The recurring red number in the WDL/NCF benches is the host — feed
stacking, H2D transfer and PS pulls serialize with compute whenever a
path falls back to per-step execution (BENCH_r04/r05 "feed-transfer-
bound" caveats). This module is the shared machinery that takes the
host off the critical path:

* :class:`OverlapOptions` — the ``Executor(overlap_options=...)`` knob
  set: ``ingest`` (the engine on/off master switch), ``lookahead`` (how
  many blocks/steps of host work run ahead of the device) and
  ``bucket_bytes`` (gradient-allreduce bucketing on the dense dp path,
  see ``ops/comm.py``).
* :class:`IngestEngine` — ONE ordered background worker thread plus a
  bounded queue of pending ingest jobs. One worker keeps stateful host
  work ordered; the bounded queue keeps it ``lookahead`` jobs ahead of
  the device. Consumers measure their stall on :meth:`pop` — the
  ``ingest_wait_ms`` histogram this PR drives to ~0 — while the worker
  measures its busy time (``ingest_ms``); ``overlap_fraction`` is the
  share of that busy time the consumer did NOT wait for.
* :func:`on_worker` — true on the engine's worker thread, so transfer
  sites (``SubExecutor._ingest``) can stamp their ``h2d_transfer``
  spans with ``overlapped=True`` and the merged trace shows the
  transfer riding under compute instead of between dispatches.

Error contract (the round-6 stream leak): a failing ingest job
surfaces as :class:`IngestError` naming the offending block index, and
an error anywhere in the stream cancels the not-yet-started jobs
(``shutdown(cancel_futures=True)``) instead of waiting them out.
"""
from __future__ import annotations

import threading
import time
from collections import deque
from concurrent.futures import CancelledError, Future

__all__ = ["OverlapOptions", "IngestEngine", "IngestError", "DaemonPool",
           "on_worker", "overlap_fraction", "new_stats", "merge_stats",
           "stats_fields"]

_worker_local = threading.local()


def on_worker():
    """True when the calling thread is an IngestEngine worker — used to
    mark transfers/pulls issued by the lookahead as ``overlapped``."""
    return getattr(_worker_local, "active", False)


class OverlapOptions:
    """Resolved ``Executor(overlap_options=...)`` knobs.

    ``ingest``       — master switch for the async ingest engine
                       (default True; False restores fully synchronous
                       block execution on every ``run_batches_stream``
                       path).
    ``lookahead``    — how many blocks (scan-block paths) or steps
                       (pipelined host-path PS) of host work stay in
                       flight ahead of the device; also the depth of the
                       ``run()`` dataloader staging ring. Default 2.
    ``bucket_bytes`` — when set, gradients reduced by explicit
                       collectives (``AllReduceCommunicateOp`` under a
                       bound mesh axis) are grouped into size-targeted
                       buckets emitted in reverse-backward order — one
                       collective per bucket — so XLA's latency-hiding
                       scheduler overlaps comm with the remaining
                       backward. Default None (per-grad collectives,
                       exactly the pre-existing behavior).
    """

    __slots__ = ("ingest", "lookahead", "bucket_bytes")
    _DEFAULTS = {"ingest": True, "lookahead": 2, "bucket_bytes": None}

    def __init__(self, ingest=True, lookahead=2, bucket_bytes=None):
        if lookahead < 1:
            raise ValueError(f"lookahead must be >= 1, got {lookahead}")
        if bucket_bytes is not None and int(bucket_bytes) <= 0:
            raise ValueError(
                f"bucket_bytes must be a positive byte count or None, "
                f"got {bucket_bytes}")
        self.ingest = bool(ingest)
        self.lookahead = int(lookahead)
        self.bucket_bytes = None if bucket_bytes is None \
            else int(bucket_bytes)

    @classmethod
    def resolve(cls, arg):
        """None / dict / OverlapOptions -> OverlapOptions (validated)."""
        if arg is None:
            return cls()
        if isinstance(arg, cls):
            return arg
        if not isinstance(arg, dict):
            raise TypeError(
                f"overlap_options must be a dict or OverlapOptions, got "
                f"{type(arg).__name__}")
        unknown = set(arg) - set(cls._DEFAULTS)
        if unknown:
            raise ValueError(
                f"unknown overlap_options keys {sorted(unknown)}; "
                f"expected {sorted(cls._DEFAULTS)}")
        kw = dict(cls._DEFAULTS)
        kw.update(arg)
        return cls(**kw)

    def __repr__(self):
        return (f"OverlapOptions(ingest={self.ingest}, "
                f"lookahead={self.lookahead}, "
                f"bucket_bytes={self.bucket_bytes})")


class IngestError(RuntimeError):
    """An async ingest job failed; names the block/step it belonged to
    (the bare ``fut.result()`` error of the round-6 stream had no
    index to debug from)."""

    def __init__(self, tag, cause):
        self.tag = tag
        super().__init__(
            f"async ingest of block {tag} failed: "
            f"{type(cause).__name__}: {cause}")


def new_stats():
    """Fresh per-executor ingest accounting (wait/busy milliseconds)."""
    return {"wait_ms": [], "busy_ms": 0.0, "pops": 0}


def merge_stats(sink, wait_ms=None, busy_ms=0.0, pops=0):
    if sink is None:
        return
    if wait_ms:
        sink["wait_ms"].extend(wait_ms)
    sink["busy_ms"] += busy_ms
    sink["pops"] += pops


def overlap_fraction(wait_ms_sum, busy_ms_sum):
    """Share of host ingest time hidden behind the device: the worker
    was busy ``busy_ms_sum`` while the consumer only stalled
    ``wait_ms_sum`` — 1.0 means the device never waited for the host,
    0.0 means fully serialized (or nothing to overlap)."""
    if busy_ms_sum <= 0.0:
        return 0.0
    return max(0.0, min(1.0, 1.0 - wait_ms_sum / busy_ms_sum))


def stats_fields(stats):
    """Bench/metric fields from a ``new_stats`` accumulator."""
    import numpy as np
    wait = stats["wait_ms"]
    p50 = float(np.percentile(wait, 50)) if wait else 0.0
    return {
        "ingest_wait_ms": round(p50, 3),
        "ingest_wait_ms_sum": round(float(sum(wait)), 3),
        "ingest_busy_ms_sum": round(stats["busy_ms"], 3),
        "overlap_fraction": round(
            overlap_fraction(sum(wait), stats["busy_ms"]), 4),
    }


class DaemonPool:
    """Tiny ``submit()``/``shutdown()`` pool over **daemon** threads.

    Why not ``ThreadPoolExecutor``: its workers are non-daemon and
    ``concurrent.futures`` joins every one of them at interpreter exit.
    A worker wedged in a blocking job — a PS push retrying against a
    dead server, an ingest job stuck in ``queue.get`` — therefore hangs
    the *interpreter*, not just the owner (the HT603/HT604 class the
    concurrency verifier flags). Here workers are daemon threads with a
    cooperative stop flag, ``shutdown(wait=True)`` bounds its join with
    a timeout, and a wedged worker is abandoned to die with the process
    instead of deadlocking teardown.

    Jobs return ``concurrent.futures.Future`` with the standard
    cancel/result/exception semantics; one worker (the default) keeps
    submission order — the IngestEngine ordering contract.
    """

    def __init__(self, max_workers=1, thread_name_prefix="hetu-pool"):
        self._jobs = deque()
        self._cv = threading.Condition()
        self._closed = False
        self._threads = [
            threading.Thread(target=self._worker, daemon=True,
                             name=f"{thread_name_prefix}-{i}")
            for i in range(max(1, int(max_workers)))]
        for t in self._threads:
            t.start()

    def submit(self, fn, *args, **kwargs):
        fut = Future()
        with self._cv:
            if self._closed:
                raise RuntimeError("submit after DaemonPool.shutdown()")
            self._jobs.append((fut, fn, args, kwargs))
            self._cv.notify()
        return fut

    def _worker(self):
        while True:
            with self._cv:
                while not self._jobs:
                    if self._closed:
                        return
                    self._cv.wait()
                fut, fn, args, kwargs = self._jobs.popleft()
            if not fut.set_running_or_notify_cancel():
                continue                # cancelled while queued
            try:
                fut.set_result(fn(*args, **kwargs))
            except BaseException as e:  # noqa: BLE001 — future carries it
                fut.set_exception(e)

    def shutdown(self, wait=True, cancel_futures=False, timeout=30.0):
        """Stop the workers. ``cancel_futures`` drops queued-but-
        unstarted jobs (their futures raise CancelledError); ``wait``
        joins the workers but — unlike ThreadPoolExecutor — bounded by
        ``timeout`` per pool, so a job wedged in a blocking call can
        never deadlock teardown or interpreter exit. Returns True when
        every worker actually exited."""
        with self._cv:
            self._closed = True
            if cancel_futures:
                while self._jobs:
                    fut, _fn, _a, _kw = self._jobs.popleft()
                    fut.cancel()
            self._cv.notify_all()
        ok = True
        if wait:
            deadline = None if timeout is None \
                else time.monotonic() + timeout
            for t in self._threads:
                t.join(None if deadline is None
                       else max(0.0, deadline - time.monotonic()))
                ok = ok and not t.is_alive()
        return ok

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        self.shutdown(cancel_futures=exc_type is not None)
        return False


class IngestEngine:
    """Ordered background ingest worker with a bounded pending queue.

    One worker thread keeps ingest jobs ordered (slot assignment and
    dataloader advancement stay deterministic); the deque holds up to
    ``lookahead`` submitted-but-unconsumed jobs so job i+lookahead
    starts the moment job i+1 finishes instead of waiting for the
    device. ``pop()`` joins the oldest job and records the consumer's
    stall; exceptions from the worker re-raise wrapped as
    :class:`IngestError` with the job's tag.
    """

    def __init__(self, telemetry=None, lookahead=2, name="ingest",
                 sink=None):
        if lookahead < 1:
            raise ValueError(f"lookahead must be >= 1, got {lookahead}")
        self.tel = telemetry
        self.lookahead = int(lookahead)
        self.name = name
        self.sink = sink
        self._pool = DaemonPool(
            max_workers=1, thread_name_prefix=f"hetu-{name}")
        self._pending = deque()
        self.wait_ms = []
        self.busy_ms = 0.0
        self._closed = False

    # -- submission ------------------------------------------------------
    def submit(self, fn, *args, tag=None):
        """Queue one ingest job; returns immediately."""
        assert not self._closed, "IngestEngine used after close()"
        fut = self._pool.submit(self._run_job, fn, args)
        self._pending.append((tag, fut))
        self._gauge()

    def _run_job(self, fn, args):
        _worker_local.active = True
        t0 = time.perf_counter()
        try:
            return fn(*args)
        finally:
            _worker_local.active = False
            dt = (time.perf_counter() - t0) * 1000.0
            self.busy_ms += dt
            if self.tel is not None and self.tel.enabled:
                self.tel.observe("ingest_ms", dt)

    @property
    def depth(self):
        return len(self._pending)

    def _gauge(self):
        if self.tel is not None and self.tel.enabled:
            self.tel.set_gauge("ingest_queue_depth", len(self._pending))

    # -- consumption -----------------------------------------------------
    def pop(self, record_wait=True):
        """Join the oldest pending job -> (tag, result). The time spent
        blocked here is the device-waited-on-host number
        (``ingest_wait_ms``); ``record_wait=False`` skips recording for
        pipeline-fill pops that are expected to wait."""
        tag, fut = self._pending.popleft()
        self._gauge()
        tel_on = self.tel is not None and self.tel.enabled
        t0n = self.tel.clock() if tel_on else 0
        t0 = time.perf_counter()
        try:
            result = fut.result()
        except CancelledError:
            raise
        except Exception as e:              # noqa: BLE001 — re-tagged
            raise IngestError(tag, e) from e
        if record_wait:
            dt = (time.perf_counter() - t0) * 1000.0
            self.wait_ms.append(dt)
            if tel_on:
                # the stall is a first-class span, not just a histogram:
                # the doctor attributes it to the h2d_ingest bucket as
                # EXPOSED host time (it rides the consumer thread, on
                # the critical path — unlike the worker's overlapped=
                # transfers)
                self.tel.complete("ingest_wait", t0n, self.tel.clock(),
                                  {"tag": tag})
                self.tel.observe("ingest_wait_ms", dt)
        return tag, result

    # -- teardown --------------------------------------------------------
    def close(self, cancel=False):
        """Shut the worker down. ``cancel=True`` (the error path) drops
        queued-but-unstarted jobs instead of waiting them out — the
        round-6 stream leaked here by waiting for every pending ingest
        before re-raising. Teardown can never deadlock on a worker
        wedged in a blocking job (``queue.get``, a PS RPC against a
        dead server): the worker is a daemon thread and the clean-path
        join is bounded, so both mid-error teardown and interpreter
        exit proceed while the wedged job dies with the process."""
        if self._closed:
            return
        self._closed = True
        ok = self._pool.shutdown(wait=not cancel, cancel_futures=cancel)
        if not cancel and not ok:
            # the bounded join expired on the CLEAN path: a job is
            # still running past the old wait-it-out guarantee — say
            # so instead of silently abandoning it mid-side-effect
            import sys
            print(f"[hetu-ingest] close(): worker '{self.name}' still "
                  f"busy after the shutdown timeout; abandoning the "
                  f"daemon worker", file=sys.stderr)
        merge_stats(self.sink, wait_ms=self.wait_ms, busy_ms=self.busy_ms,
                    pops=len(self.wait_ms))

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        self.close(cancel=exc_type is not None)
        return False
