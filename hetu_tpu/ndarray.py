"""Array and device layer for hetu-tpu.

TPU-native equivalent of the reference's DLArray/NDArray runtime
(reference: python/hetu/ndarray.py, src/common/c_runtime_api.h). Instead of a
ctypes handle into a CUDA allocator, an :class:`NDArray` owns a ``jax.Array``
(device memory managed by XLA/PJRT) plus a :class:`DLContext` describing the
logical placement. Host<->device copies map to ``jax.device_put`` /
``np.asarray``; CUDA streams/events map to XLA async dispatch +
``block_until_ready`` (see stream.py).
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

__all__ = [
    "DLContext", "cpu", "gpu", "tpu", "rcpu", "rgpu", "rtpu",
    "is_gpu_ctx", "is_tpu_ctx", "device_backend",
    "NDArray", "array", "empty", "sparse_array", "ND_Sparse_Array",
    "IndexedSlices",
]


# ---------------------------------------------------------------------------
# Device contexts
# ---------------------------------------------------------------------------

_DEVICE_KINDS = ("cpu", "tpu")


def _accelerator_platform():
    """Best accelerator platform available in this process."""
    try:
        backends = jax.local_devices()
    except RuntimeError:
        return "cpu"
    for d in backends:
        if d.platform != "cpu":
            return d.platform
    return "cpu"


class DLContext:
    """A logical device: (hostname, kind, device_id).

    Mirrors the reference DLContext (python/hetu/ndarray.py:17) but device
    kinds are cpu/tpu. ``gpu(i)`` is kept as a compatibility alias that maps
    onto the i-th accelerator so reference example scripts run unchanged.
    """

    __slots__ = ("hostname", "kind", "device_id")

    def __init__(self, kind, device_id=0, hostname="localhost"):
        assert kind in _DEVICE_KINDS, f"unknown device kind {kind}"
        self.kind = kind
        self.device_id = int(device_id)
        self.hostname = hostname

    @property
    def local(self):
        return self.hostname == "localhost"

    def is_accelerator(self):
        return self.kind != "cpu"

    def jax_device(self):
        """Resolve to a concrete local jax device (best effort)."""
        platform = self.kind if self.kind != "tpu" else _accelerator_platform()
        try:
            devs = [d for d in jax.local_devices() if
                    (d.platform == platform or
                     (self.kind == "tpu" and d.platform != "cpu"))]
        except RuntimeError:
            devs = []
        if not devs:
            devs = jax.local_devices()
        return devs[self.device_id % len(devs)]

    def relocalize(self):
        self.hostname = "localhost"

    def __eq__(self, other):
        return (isinstance(other, DLContext)
                and self.hostname == other.hostname
                and self.kind == other.kind
                and self.device_id == other.device_id)

    def __hash__(self):
        return hash((self.hostname, self.kind, self.device_id))

    def __repr__(self):
        prefix = "" if self.local else self.hostname + ":"
        return f"{prefix}{self.kind}:{self.device_id}"


def cpu(dev_id=0):
    return DLContext("cpu", dev_id)


def tpu(dev_id=0):
    return DLContext("tpu", dev_id)


def gpu(dev_id=0):
    """Compatibility alias: reference scripts say ``ht.gpu(i)``; on this
    framework that means the i-th TPU chip."""
    return DLContext("tpu", dev_id)


def rcpu(hostname, dev_id=0):
    return DLContext("cpu", dev_id, hostname=hostname)


def rtpu(hostname, dev_id=0):
    return DLContext("tpu", dev_id, hostname=hostname)


def rgpu(hostname, dev_id=0):
    return DLContext("tpu", dev_id, hostname=hostname)


def is_gpu_ctx(ctx):
    """Reference-compat name (ndarray.py:84): true if ctx is an accelerator."""
    return ctx is not None and ctx.is_accelerator()


def is_tpu_ctx(ctx):
    return is_gpu_ctx(ctx)


def device_backend(ctx=None):
    if ctx is None or ctx.is_accelerator():
        return _accelerator_platform()
    return "cpu"


# ---------------------------------------------------------------------------
# NDArray
# ---------------------------------------------------------------------------

class NDArray:
    """Device array handle: a jax.Array + logical context.

    The executor's boundary type. Feed values, fetched results and saved
    parameters travel as NDArray; inside a compiled step everything is raw
    jax values.
    """

    __slots__ = ("_value", "ctx")

    def __init__(self, value, ctx=None):
        self._value = value
        self.ctx = ctx if ctx is not None else cpu(0)

    # -- properties ---------------------------------------------------------
    @property
    def shape(self):
        return tuple(self._value.shape)

    @property
    def dtype(self):
        return self._value.dtype

    @property
    def jax_array(self):
        return self._value

    @property
    def lazy(self):
        return False

    # -- host/device movement ----------------------------------------------
    def asnumpy(self):
        return np.asarray(self._value)

    def copyto(self, target):
        if isinstance(target, DLContext):
            return NDArray(jax.device_put(self._value, target.jax_device()),
                           target)
        assert isinstance(target, NDArray)
        target._value = jax.device_put(self._value, target.ctx.jax_device())
        return target

    def async_h2d(self, source, stream_handle=None, event_handle=None):
        # jax.device_put is asynchronous already; completion is observed via
        # block_until_ready (stream.Event.sync).
        if isinstance(source, np.ndarray):
            self._value = jax.device_put(source, self.ctx.jax_device())
        else:
            self._value = jax.device_put(source._value, self.ctx.jax_device())

    def async_d2h(self, source, stream_handle=None, event_handle=None):
        self._value = np.asarray(source._value)

    def block_until_ready(self):
        if isinstance(self._value, jax.Array):
            self._value.block_until_ready()
        return self

    # -- numpy-ish sugar ----------------------------------------------------
    def __getitem__(self, idx):
        return NDArray(self._value[idx], self.ctx)

    def __repr__(self):
        return f"NDArray(shape={self.shape}, dtype={self.dtype}, ctx={self.ctx})"


def array(arr, ctx=None, dtype=np.float32):
    """Create an NDArray from array-like data on the given context
    (reference ndarray.py:407)."""
    ctx = ctx if ctx is not None else cpu(0)
    arr = np.asarray(arr, dtype=dtype)
    value = jax.device_put(arr, ctx.jax_device())
    return NDArray(value, ctx)


def empty(shape, ctx=None, dtype=np.float32):
    ctx = ctx if ctx is not None else cpu(0)
    value = jax.device_put(jnp.zeros(shape, dtype=dtype), ctx.jax_device())
    return NDArray(value, ctx)


# ---------------------------------------------------------------------------
# Sparse containers
# ---------------------------------------------------------------------------

class ND_Sparse_Array:
    """CSR sparse matrix (reference ndarray.py:435). Stored as three device
    arrays; consumed by csrmm/csrmv ops which lower to gather/segment-sum —
    XLA-friendly replacements for cuSPARSE."""

    __slots__ = ("data", "row", "col", "nrow", "ncol", "ctx")

    def __init__(self, data, row, col, nrow, ncol, ctx=None):
        self.data = data            # NDArray [nnz]
        self.row = row              # NDArray [nrow+1] indptr (int32)
        self.col = col              # NDArray [nnz]   indices (int32)
        self.nrow = nrow
        self.ncol = ncol
        self.ctx = ctx if ctx is not None else cpu(0)

    @property
    def shape(self):
        return (self.nrow, self.ncol)

    def asnumpy(self):
        import scipy.sparse as sp
        return sp.csr_matrix(
            (self.data.asnumpy(), self.col.asnumpy(), self.row.asnumpy()),
            shape=self.shape).toarray()


def sparse_array(values, indices, shape, ctx=None, dtype=np.float32):
    """Build CSR from COO (values, (rows, cols)) like reference
    ndarray.py:469."""
    import scipy.sparse as sp
    mat = sp.csr_matrix((values, indices), shape=shape, dtype=dtype)
    return ND_Sparse_Array(
        array(mat.data, ctx=ctx, dtype=dtype),
        array(mat.indptr, ctx=ctx, dtype=np.int32),
        array(mat.indices, ctx=ctx, dtype=np.int32),
        shape[0], shape[1], ctx=ctx)


class IndexedSlices:
    """Sparse gradient of an embedding lookup: (indices, values) pair
    (reference ndarray.py:482). ``dedup`` merges duplicate rows with a
    segment-sum so downstream optimizers apply each row once."""

    __slots__ = ("indices", "values", "dense_shape")

    def __init__(self, indices=None, values=None, dense_shape=None):
        self.indices = indices      # jnp int array, any shape
        self.values = values        # jnp float array, indices.shape + [dim]
        self.dense_shape = dense_shape

    def get_dense_rows(self):
        return self.values.reshape(-1, self.dense_shape[-1])

    def get_flat_indices(self):
        return self.indices.reshape(-1)

    def dedup(self):
        """Merge duplicate indices (reference: IndexedSlices.deduplicate,
        src/ops/IndexedSlices.cu). Returns (unique_indices, summed_values)
        with static shapes (padded with dense_shape[0] sentinel)."""
        flat_idx = self.get_flat_indices()
        rows = self.get_dense_rows()
        uniq, inv = jnp.unique(
            flat_idx, return_inverse=True, size=flat_idx.shape[0],
            fill_value=self.dense_shape[0])
        summed = jax.ops.segment_sum(rows, inv, num_segments=flat_idx.shape[0])
        return uniq, summed

    def to_dense(self):
        out = jnp.zeros(self.dense_shape, dtype=self.values.dtype)
        return out.at[self.get_flat_indices()].add(self.get_dense_rows())


class CSRValue:
    """Traced CSR triple with static shape — the in-graph value form of
    ND_Sparse_Array (nrow/ncol stay static so segment_sum sizes are
    compile-time constants).

    ``row_ids`` (the per-nnz row index, i.e. the COO row array) is a pure
    function of ``indptr``; it is precomputed once at ingest so csrmm /
    csrmv never re-derive it with a searchsorted over nnz inside every
    forward and backward call (the reference's cuSPARSE kernels get it for
    free from the CSR walk, src/ops/CuSparseCsrmm.cu).

    ``t_data/t_indices/t_row_ids`` hold A^T in the same COO-sorted form
    (entries sorted by column). The transposed product in every csrmm
    backward then lowers to a gather + *sorted* segment-sum instead of a
    general scatter — the TPU analogue of cuSPARSE keeping a CSC copy for
    the transposed kernels."""

    __slots__ = ("data", "indptr", "indices", "nrow", "ncol", "row_ids",
                 "t_data", "t_indices", "t_row_ids")

    def __init__(self, data, indptr, indices, nrow, ncol, row_ids=None,
                 t_data=None, t_indices=None, t_row_ids=None):
        self.data = data
        self.indptr = indptr
        self.indices = indices
        self.nrow = nrow
        self.ncol = ncol
        self.row_ids = row_ids
        self.t_data = t_data          # data sorted by column
        self.t_indices = t_indices    # original row per entry (A^T's cols)
        self.t_row_ids = t_row_ids    # sorted columns (A^T's rows)

    @classmethod
    def from_sparse_array(cls, sp: "ND_Sparse_Array"):
        def as_jax(v):
            return v.jax_array if isinstance(v, NDArray) else jnp.asarray(v)
        def host(v):
            return np.asarray(v.asnumpy() if isinstance(v, NDArray) else v)
        indptr_host = host(sp.row)
        indices_host = host(sp.indices if hasattr(sp, "indices") else sp.col)
        data_host = host(sp.data)
        row_ids = np.repeat(
            np.arange(sp.nrow, dtype=np.int32), np.diff(indptr_host))
        perm = np.argsort(indices_host, kind="stable")
        return cls(as_jax(sp.data), as_jax(sp.row), as_jax(sp.col),
                   sp.nrow, sp.ncol, jnp.asarray(row_ids),
                   jnp.asarray(data_host[perm]),
                   jnp.asarray(row_ids[perm]),
                   jnp.asarray(indices_host[perm].astype(np.int32)))


jax.tree_util.register_pytree_node(
    CSRValue,
    lambda s: ((s.data, s.indptr, s.indices, s.row_ids,
                s.t_data, s.t_indices, s.t_row_ids), (s.nrow, s.ncol)),
    lambda aux, leaves: CSRValue(leaves[0], leaves[1], leaves[2],
                                 aux[0], aux[1], *leaves[3:]),
)


# IndexedSlices values flow through jitted step functions, so they must be
# a pytree (indices/values are leaves, dense_shape is static metadata).
jax.tree_util.register_pytree_node(
    IndexedSlices,
    lambda s: ((s.indices, s.values), s.dense_shape),
    lambda shape, leaves: IndexedSlices(leaves[0], leaves[1], shape),
)
