"""PS server process management — implemented with the C++ parameter
server in the PS milestone; these stubs fail loudly until then."""
from __future__ import annotations

_NOT_READY = ("the C++ parameter server is not built yet; PS/Hybrid "
              "communication modes land with hetu_tpu/ps/native")


def ensure_scheduler():
    raise RuntimeError(_NOT_READY)


def shutdown_scheduler():
    pass


def ensure_server():
    raise RuntimeError(_NOT_READY)


def shutdown_server():
    pass
