"""PS server / scheduler process management.

Reference parity: python/hetu/launcher.py forks scheduler/server/worker
roles from a yaml config, wiring DMLC_* env vars. Here the server is the
C++ ``hetu_ps_run_server`` loop launched as a subprocess; addressing is
direct (env HETU_PS_HOSTS/HETU_PS_PORTS) so no scheduler rendezvous
process is needed — ensure_scheduler is kept as an API no-op.
"""
from __future__ import annotations

import os
import socket
import subprocess
import sys
import time

_server_procs = []
_atexit_registered = False


def default_port():
    return int(os.environ.get("HETU_PS_PORTS", "18590").split(",")[0])


def _port_open(host, port):
    try:
        with socket.create_connection((host, port), timeout=0.2):
            return True
    except OSError:
        return False


def pick_free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def ensure_server(port=None, nworkers=None, wait_s=10.0):
    """Start a PS server subprocess on ``port`` if none is listening."""
    port = port or default_port()
    nworkers = nworkers or int(os.environ.get("HETU_PS_NWORKERS", "1"))
    if _port_open("127.0.0.1", port):
        return None
    pkg_root = os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    pypath = pkg_root + os.pathsep + os.environ.get("PYTHONPATH", "")
    proc = subprocess.Popen(
        [sys.executable, "-m", "hetu_tpu.ps.run_server", str(port),
         str(nworkers)],
        env={**os.environ, "JAX_PLATFORMS": "cpu", "PYTHONPATH": pypath},
        # a fresh fd table: the child must not hold the parent's stdio
        # pipes open past the parent's death (a `script | tail` would
        # otherwise never see EOF while the server lives)
        stdin=subprocess.DEVNULL)
    _server_procs.append(proc)
    if not _atexit_registered:
        # single-process convenience runs (examples' ensure_local_ps)
        # must not leak the fleet past interpreter exit
        import atexit
        atexit.register(shutdown_server)
        globals()["_atexit_registered"] = True
    deadline = time.time() + wait_s
    while time.time() < deadline:
        if _port_open("127.0.0.1", port):
            return proc
        if proc.poll() is not None:
            raise RuntimeError(
                f"PS server exited with {proc.returncode} during startup")
        time.sleep(0.05)
    raise RuntimeError(f"PS server did not come up on :{port}")


def shutdown_server():
    for proc in _server_procs:
        if proc.poll() is None:
            proc.terminate()
            try:
                proc.wait(timeout=3)
            except subprocess.TimeoutExpired:
                proc.kill()
    _server_procs.clear()


def ensure_scheduler():
    """Direct-addressed transport needs no rendezvous scheduler; kept for
    reference API parity (launcher.py scheduler role)."""


def shutdown_scheduler():
    pass
