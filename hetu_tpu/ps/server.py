"""PS server / scheduler process management.

Reference parity: python/hetu/launcher.py forks scheduler/server/worker
roles from a yaml config, wiring DMLC_* env vars. Here the server is the
C++ ``hetu_ps_run_server`` loop launched as a subprocess; addressing is
direct (env HETU_PS_HOSTS/HETU_PS_PORTS) so no scheduler rendezvous
process is needed — ensure_scheduler is kept as an API no-op.
"""
from __future__ import annotations

import os
import socket
import subprocess
import sys
import time

_server_procs = []
_atexit_registered = False


def default_port():
    return int(os.environ.get("HETU_PS_PORTS", "18590").split(",")[0])


def _port_open(host, port):
    try:
        with socket.create_connection((host, port), timeout=0.2):
            return True
    except OSError:
        return False


def pick_free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def ensure_server(port=None, nworkers=None, wait_s=10.0, extra_env=None):
    """Start a PS server subprocess on ``port`` if none is listening.

    ``extra_env`` adds to the child's environment — the replication
    hook: a primary is armed with its backup target via
    ``HETU_PS_MY_BACKUP_HOST``/``HETU_PS_MY_BACKUP_PORT``.

    Startup races are resolved by an atomic port claim (ISSUE 13
    satellite): two processes — e.g. two workers of one fleet hitting
    the in-process convenience path at once — can both observe the
    port closed and both try to spawn. Both used to spawn; the loser's
    child then failed its ``bind()`` and ensure_server raised a bogus
    "server exited during startup" even though a perfectly good server
    had just come up. Now the *parent* claims the port by binding and
    listening a socket before it spawns — the kernel makes exactly one
    claimant win (a second bind against a listening socket fails even
    under SO_REUSEADDR; bind alone is NOT exclusive) — and hands it to
    the child (``HETU_PS_LISTEN_FD``), whose accept loop serves it;
    connections arriving before that queue in the listen backlog. The
    loser's ``bind()`` fails in the parent, which simply waits for the
    winner's port and adopts it (returns None, like the
    port-already-open fast path)."""
    port = port or default_port()
    nworkers = nworkers or int(os.environ.get("HETU_PS_NWORKERS", "1"))
    if _port_open("127.0.0.1", port):
        return None
    lsock = socket.socket()
    lsock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    try:
        lsock.bind(("0.0.0.0", port))
        lsock.listen(64)
    except OSError:
        # lost the claim: another spawner (or a just-started server)
        # owns the port — wait for it and adopt
        lsock.close()
        deadline = time.time() + wait_s
        while time.time() < deadline:
            if _port_open("127.0.0.1", port):
                return None
            time.sleep(0.05)
        raise RuntimeError(
            f"port {port} is claimed by another process but no PS "
            f"server came up on it")
    pkg_root = os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    pypath = pkg_root + os.pathsep + os.environ.get("PYTHONPATH", "")
    lsock.set_inheritable(True)
    # readiness pipe: the parent pre-listened the port, so "port open"
    # no longer means "child is serving" — the child writes one byte
    # when its accept loop is about to run, and a child that dies
    # during startup EOFs the pipe instead (without this, a crashed
    # child would be handed back as a live server because connections
    # queue happily in the claimed socket's backlog)
    rfd, wfd = os.pipe()
    try:
        try:
            proc = subprocess.Popen(
                [sys.executable, "-m", "hetu_tpu.ps.run_server",
                 str(port), str(nworkers)],
                env={**os.environ, "JAX_PLATFORMS": "cpu",
                     "PYTHONPATH": pypath,
                     "HETU_PS_LISTEN_FD": str(lsock.fileno()),
                     "HETU_PS_READY_FD": str(wfd),
                     **(extra_env or {})},
                pass_fds=(lsock.fileno(), wfd),
                # a fresh fd table otherwise: the child must not hold
                # the parent's stdio pipes open past the parent's
                # death (a `script | tail` would otherwise never see
                # EOF while the server lives)
                stdin=subprocess.DEVNULL)
        except BaseException:
            os.close(rfd)       # spawn failed: nothing will read it
            raise
    finally:
        # the child inherited its own copies; keeping ours would hold
        # the port (and the claim, and the pipe's EOF) for life
        lsock.close()
        os.close(wfd)
    _server_procs.append(proc)
    if not _atexit_registered:
        # single-process convenience runs (examples' ensure_local_ps)
        # must not leak the fleet past interpreter exit
        import atexit
        atexit.register(shutdown_server)
        globals()["_atexit_registered"] = True
    import select
    deadline = time.time() + wait_s
    try:
        while time.time() < deadline:
            readable, _, _ = select.select([rfd], [], [], 0.05)
            if readable:
                if os.read(rfd, 1):
                    return proc          # child reached its serve loop
                # EOF without the readiness byte: died during startup
                try:
                    rc = proc.wait(timeout=5)
                except subprocess.TimeoutExpired:
                    rc = "no exit (readiness pipe closed unready)"
                raise RuntimeError(
                    f"PS server exited with {rc} during startup")
    finally:
        os.close(rfd)
    raise RuntimeError(f"PS server did not come up on :{port}")


def shutdown_server():
    for proc in _server_procs:
        if proc.poll() is None:
            proc.terminate()
            try:
                proc.wait(timeout=3)
            except subprocess.TimeoutExpired:
                proc.kill()
    _server_procs.clear()


def ensure_scheduler():
    """Direct-addressed transport needs no rendezvous scheduler; kept for
    reference API parity (launcher.py scheduler role)."""


def shutdown_scheduler():
    pass
