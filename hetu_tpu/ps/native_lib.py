"""ctypes binding to libhetu_ps.so (reference parity: python/hetu/_base.py
loading libc_runtime_api.so + the python_binding.cc C ABI).

The shared object builds lazily from hetu_tpu/ps/native/ via make on first
use — mirroring how the reference expects a prebuilt build/lib but staying
self-contained.
"""
from __future__ import annotations

import ctypes
import os
import subprocess

import numpy as np

_NATIVE_DIR = os.path.join(os.path.dirname(__file__), "native")
_SO_PATH = os.path.join(_NATIVE_DIR, "libhetu_ps.so")
_lib = None


def build_lib():
    sources = ["ps_server.cc", "ps_client.cc", "ps_cache.cc",
               "ps_store.cc", "ps_common.h", "ps_store.h", "Makefile"]
    newest = max(os.path.getmtime(os.path.join(_NATIVE_DIR, s))
                 for s in sources)
    if not os.path.exists(_SO_PATH) or \
            os.path.getmtime(_SO_PATH) < newest:
        subprocess.run(["make", "-C", _NATIVE_DIR], check=True,
                       capture_output=True)
    return _SO_PATH


def get_lib():
    global _lib
    if _lib is not None:
        return _lib
    lib = ctypes.CDLL(build_lib())

    i64 = ctypes.c_int64
    fp = ctypes.POINTER(ctypes.c_float)
    lp = ctypes.POINTER(ctypes.c_int64)

    lib.PSInit.argtypes = [ctypes.c_char_p, ctypes.c_char_p, ctypes.c_int,
                           ctypes.c_int]
    lib.PSInit.restype = ctypes.c_int
    lib.PSRank.argtypes = []
    lib.PSRank.restype = ctypes.c_int
    lib.PSNumWorkers.argtypes = []
    lib.PSNumWorkers.restype = ctypes.c_int
    lib.PSFinalize.argtypes = []
    lib.InitTensor.argtypes = [ctypes.c_int, ctypes.c_int, i64, i64,
                               ctypes.c_int, ctypes.c_double,
                               ctypes.c_double, ctypes.c_uint64,
                               ctypes.c_int, fp, ctypes.c_int]
    lib.InitTensor.restype = ctypes.c_int
    lib.Pull.argtypes = [ctypes.c_int, fp, i64]
    lib.Pull.restype = ctypes.c_int
    lib.Push.argtypes = [ctypes.c_int, fp, i64]
    lib.DDPushPull.argtypes = [ctypes.c_int, fp, fp, i64]
    lib.SparsePush.argtypes = [ctypes.c_int, lp, fp, i64, i64]
    lib.SparsePull.argtypes = [ctypes.c_int, lp, fp, i64, i64]
    lib.SparsePull.restype = ctypes.c_int
    lib.SDPushPull.argtypes = [ctypes.c_int, lp, fp, i64, fp, i64, i64]
    lib.SSPushPull.argtypes = [ctypes.c_int, lp, fp, i64, lp, i64, fp, i64]
    lib.SyncEmbedding.argtypes = [ctypes.c_int, i64, lp, lp, i64, fp, i64]
    lib.SyncEmbedding.restype = ctypes.c_int
    lib.PushEmbedding.argtypes = [ctypes.c_int, lp, fp, lp, i64, i64]
    lib.PushSyncEmbedding.argtypes = [ctypes.c_int, i64, lp, fp, lp,
                                      i64, lp, lp, i64, fp, i64]
    lib.PushSyncEmbedding.restype = ctypes.c_int
    lib.StoreConfig.argtypes = [ctypes.c_int, ctypes.c_int, i64,
                                ctypes.c_char_p, lp, i64]
    lib.StoreConfig.restype = ctypes.c_int
    lib.StoreStats.argtypes = [ctypes.c_int, lp, i64]
    lib.StoreStats.restype = ctypes.c_int
    lib.PSNumReplicas.argtypes = []
    lib.PSNumReplicas.restype = ctypes.c_int
    lib.Wait.argtypes = [ctypes.c_int]
    lib.WaitAll.argtypes = []
    lib.BarrierWorker.argtypes = []
    lib.SetParam.argtypes = [ctypes.c_int, fp, i64]
    lib.SetParam.restype = ctypes.c_int
    lib.Clear.argtypes = [ctypes.c_int]
    lib.Clear.restype = ctypes.c_int
    lib.SaveParam.argtypes = [ctypes.c_int, ctypes.c_char_p]
    lib.SaveParam.restype = ctypes.c_int
    lib.LoadParam.argtypes = [ctypes.c_int, ctypes.c_char_p]
    lib.LoadParam.restype = ctypes.c_int
    lib.PushData.argtypes = [i64, fp, i64]
    lib.PushData.restype = ctypes.c_int
    lib.PullData.argtypes = [i64, fp, i64]
    lib.PullData.restype = ctypes.c_int
    lib.GetLoads.argtypes = []
    lib.GetLoads.restype = ctypes.c_uint64
    lib.ShutdownServers.argtypes = []
    lib.hetu_ps_run_server.argtypes = [ctypes.c_int, ctypes.c_int]
    lib.hetu_ps_run_server.restype = ctypes.c_int
    lib.hetu_ps_run_server_fd.argtypes = [ctypes.c_int, ctypes.c_int,
                                          ctypes.c_int]
    lib.hetu_ps_run_server_fd.restype = ctypes.c_int

    _lib = lib
    return lib


def as_f32(arr):
    return np.ascontiguousarray(arr, dtype=np.float32)


def as_i64(arr):
    return np.ascontiguousarray(arr, dtype=np.int64)


def fptr(arr):
    return arr.ctypes.data_as(ctypes.POINTER(ctypes.c_float))


def lptr(arr):
    return arr.ctypes.data_as(ctypes.POINTER(ctypes.c_int64))
