"""Device-resident (HBM) embedding cache with bounded staleness.

TPU-native analogue of the reference's cache-enabled embedding path
(python/hetu/cstable.py over hetu_cache; the HET design the reference
implements for trillion-parameter tables). The reference caches hot rows
in GPU memory and syncs with the parameter server under a staleness
bound; here the cache rows live in HBM as a regular jit-threaded
parameter, so the steady-state training step touches them with zero
host<->device traffic:

  * lookups gather from the cache array inside the compiled step,
  * the worker optimizer applies the local sparse update in-graph,
  * raw gradients also scatter-add into an HBM accumulator (``acc``
    state), and every ``push_bound`` steps the accumulated rows drain to
    the PS server on a background thread (PushEmbedding applies the
    server optimizer and bumps per-row versions),
  * misses / stale rows are fetched with SparsePull / SyncEmbedding and
    scattered into the cache by an async dispatched fill — the transfer
    rides the dispatch queue, never a blocking round trip.

Host side this module keeps only the id<->slot mapping, per-slot
versions and dirty counters (numpy); all row data stays on device.

Reference parity: python/hetu/cstable.py:19-211 (facade),
ps-lite cache semantics via SyncEmbedding/PushEmbedding
(hetu_tpu/ps/native/ps_server.cc kSyncEmbedding/kPushEmbedding).
"""
from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp

from ..telemetry import health as _health


# -- device-side helpers (shape-bucketed so jit cache stays small) ---------

@functools.partial(jax.jit, donate_argnums=0)
def _fill_rows(cache, slots, rows):
    return cache.at[slots].set(rows)


@jax.jit
def _gather_rows(arr, slots):
    return arr[slots]


@jax.jit
def _gather_rows_bf16(arr, slots):
    # drain compression: the gradient sums leave HBM as bf16 (half the
    # D2H bytes; the remote-tunnel D2H link is the drain's bottleneck)
    return arr[slots].astype(jnp.bfloat16)


@functools.partial(jax.jit, donate_argnums=0)
def _zero_rows(arr, slots):
    return arr.at[slots].set(0.0)


def _pad_pow2(n, minimum=8):
    """Next power-of-two bucket >= n (bounds jit-cache churn from
    variable miss/drain counts)."""
    b = minimum
    while b < n:
        b *= 2
    return b


class DeviceCacheTable:
    """Host-side bookkeeping for one device-cached embedding table.

    The cache array itself lives in ``executor.params[cache_sid]`` (shape
    ``[capacity + 1, width]``; the last row is a scratch slot used as the
    scatter target for padding) and the push accumulator in
    ``executor.state[cache_sid]["acc"]``.
    """

    def __init__(self, table_node, cache_node, client, *, capacity, width,
                 rows, push_bound=100, pull_bound=100, nworkers=1,
                 drain_compress=False):
        self.table_node = table_node
        self.cache_node = cache_node
        self.cache_sid = str(cache_node.id)
        self.tid = table_node.id
        self.client = client
        self.capacity = int(capacity)
        self.width = int(width)
        self.rows = int(rows)
        self.push_bound = int(push_bound)
        self.pull_bound = int(pull_bound)
        self.nworkers = int(nworkers)
        self.drain_compress = bool(drain_compress)
        # owning executor's health monitor (stamped by the PS runtime
        # at registration) — scopes staleness observations so two
        # executors in one process never cross-attribute; None falls
        # back to the module broadcast (single-executor processes)
        self.health_monitor = None

        # id -> slot map: direct-indexed for tables that fit, dict above
        # (a 33.7M-row Criteo map is a 135MB int32 array; a trillion-row
        # table falls back to hashing)
        if self.rows <= (1 << 26):
            self._slot_of = np.full(self.rows, -1, np.int32)
        else:
            self._slot_of = None
            self._slot_dict = {}
        self.id_of = np.full(self.capacity, -1, np.int64)
        self.ver = np.zeros(self.capacity, np.int64)    # client row version
        self.upd = np.zeros(self.capacity, np.int64)    # updates since push
        self.dirty = np.zeros(self.capacity, bool)
        self._clock = np.zeros(self.capacity, bool)     # recency bit
        self._pinned = np.zeros(self.capacity, bool)    # current batch's rows
        self._hand = 0
        self._n_used = 0
        self.steps_since_drain = 0
        # perf counters (reference cstable.py:163-187)
        self.hits = 0
        self.misses = 0
        self.evicts = 0
        self.pushed_rows = 0
        self.pulled_rows = 0

    # -- id<->slot -------------------------------------------------------
    def _lookup_slots(self, uniq_ids):
        if self._slot_of is not None:
            return self._slot_of[uniq_ids]
        d = self._slot_dict
        return np.fromiter((d.get(int(i), -1) for i in uniq_ids),
                           np.int32, count=len(uniq_ids))

    def _set_slot(self, eid, slot):
        if self._slot_of is not None:
            self._slot_of[eid] = slot
        elif slot < 0:
            self._slot_dict.pop(int(eid), None)
        else:
            self._slot_dict[int(eid)] = slot

    def _alloc(self, n, inline_drain):
        """Allocate ``n`` slots, evicting clean rows by CLOCK. Rows the
        current batch touches are pinned and never candidates; dirty rows
        are never evicted silently — if only dirty rows remain, the
        caller drains first (``inline_drain`` callback)."""
        out = np.empty(n, np.int64)
        got = 0
        # fast path: never-used slots
        while got < n and self._n_used < self.capacity:
            s = self._n_used
            self._n_used += 1
            self._pinned[s] = True
            out[got] = s
            got += 1
        scanned = 0
        drained = False
        limit = 2 * self.capacity
        while got < n:
            if scanned >= limit:
                if drained:
                    raise RuntimeError(
                        f"device cache for tensor {self.tid} has capacity "
                        f"{self.capacity} but one batch needs more unique "
                        f"rows — raise cache_capacity")
                # every candidate is dirty: push pending updates, retry
                inline_drain()
                drained = True
                scanned = 0
                continue
            s = self._hand
            self._hand = (self._hand + 1) % self.capacity
            scanned += 1
            if self._pinned[s]:
                continue
            if self._clock[s]:
                self._clock[s] = False
                continue
            if self.dirty[s]:
                continue
            old = self.id_of[s]
            if old >= 0:
                self._set_slot(old, -1)
                self.evicts += 1
            self.id_of[s] = -1
            self._pinned[s] = True
            out[got] = s
            got += 1
        return out

    # -- per-step assignment ----------------------------------------------
    def assign(self, ids, inline_drain):
        """Map a batch of ids to slots, allocating for misses.

        Returns ``(slots, miss_ids, miss_slots, uniq_slots)`` — slots has
        ids' shape (int32); miss rows must be fetched and scattered into
        the cache before (in dispatch order) the step consumes it.
        """
        flat = np.asarray(ids).ravel().astype(np.int64)
        uniq, inv = np.unique(flat, return_inverse=True)
        slots = self._lookup_slots(uniq)
        miss = slots < 0
        n_miss = int(miss.sum())
        self.hits += len(uniq) - n_miss
        self.misses += n_miss
        # this batch's resident rows must survive its own miss evictions
        self._pinned[slots[~miss]] = True
        if n_miss:
            miss_ids = uniq[miss]
            new_slots = self._alloc(n_miss, inline_drain)
            if self._slot_of is not None:
                self._slot_of[miss_ids] = new_slots.astype(np.int32)
            else:
                for eid, s in zip(miss_ids, new_slots):
                    self._slot_dict[int(eid)] = int(s)
            self.id_of[new_slots] = miss_ids
            self.ver[new_slots] = 0
            self.upd[new_slots] = 0
            slots[miss] = new_slots
            self.pulled_rows += n_miss
        else:
            miss_ids = np.empty(0, np.int64)
            new_slots = np.empty(0, np.int64)
        self._clock[slots] = True
        # pins persist until release_pins(): a table consumed by several
        # lookups in one step must not evict slots an earlier assign()
        # already baked into its slots feed
        full = slots[inv].reshape(np.shape(ids)).astype(np.int32)
        return full, miss_ids, new_slots, slots

    def assign_block(self, ids_arr, inline_drain):
        """Vectorized :meth:`assign` for a whole scan block (VERDICT r3
        weak #6: the per-step unique/scatter slot map was the next WDL
        host hotspot). The block executes as ONE compiled scan with the
        cache array threaded through it, so every row any step touches
        must be resident for the whole block — the residency set is
        identical to running :meth:`assign` per step with pins held,
        which is exactly what this replaces (one unique / one alloc /
        one miss-fill instead of ``nsteps`` of each).

        ``ids_arr`` is ``[nsteps, ...]``.  Returns ``(slots int32 of
        ids_arr's shape, miss_ids, miss_slots, uniq_slots, counts)``
        where ``counts[i]`` is the number of steps touching unique row
        ``i`` — per-step upd/version accounting for the staleness
        protocol is preserved bit-for-bit.
        """
        ids_arr = np.asarray(ids_arr)
        nsteps = ids_arr.shape[0]
        flat = ids_arr.reshape(nsteps, -1).astype(np.int64)
        uniq, inv = np.unique(flat, return_inverse=True)
        inv = inv.reshape(flat.shape)
        nuniq = len(uniq)
        # dedup (step, row) pairs -> how many steps touch each row
        pairs = np.unique(inv + np.arange(nsteps)[:, None] * nuniq)
        counts = np.bincount(pairs % nuniq, minlength=nuniq)
        slots = self._lookup_slots(uniq)
        miss = slots < 0
        n_miss = int(miss.sum())
        # a block row's first touch is the miss; later steps re-hit it
        self.hits += int(counts.sum()) - n_miss
        self.misses += n_miss
        self._pinned[slots[~miss]] = True
        if n_miss:
            miss_ids = uniq[miss]
            new_slots = self._alloc(n_miss, inline_drain)
            if self._slot_of is not None:
                self._slot_of[miss_ids] = new_slots.astype(np.int32)
            else:
                for eid, s in zip(miss_ids, new_slots):
                    self._slot_dict[int(eid)] = int(s)
            self.id_of[new_slots] = miss_ids
            self.ver[new_slots] = 0
            self.upd[new_slots] = 0
            slots[miss] = new_slots
            self.pulled_rows += n_miss
        else:
            miss_ids = np.empty(0, np.int64)
            new_slots = np.empty(0, np.int64)
        self._clock[slots] = True
        full = slots[inv].reshape(ids_arr.shape).astype(np.int32)
        return full, miss_ids, new_slots, slots, counts

    def release_pins(self):
        """End-of-step: this step's resident rows become evictable."""
        self._pinned[:] = False

    def note_update(self, uniq_slots, counts=1):
        """Record that the step (or block: ``counts`` from
        :meth:`assign_block`) just dispatched updates to these rows
        (called once per lookup; step accounting is ``note_step``)."""
        self.dirty[uniq_slots] = True
        self.upd[uniq_slots] += counts
        self.ver[uniq_slots] += counts

    def note_step(self):
        self.steps_since_drain += 1

    # -- staleness refresh (multi-worker) ----------------------------------
    def stale_check(self, uniq_ids, uniq_slots):
        """SyncEmbedding: rows whose server version ran more than
        ``pull_bound`` ahead of ours come back refreshed. Returns
        ``(slots_to_fill, rows)`` or ``(None, None)``. Single-worker
        tables skip the RPC — no other writer exists."""
        if self.nworkers <= 1:
            return None, None
        vers = self.ver[uniq_slots].copy()
        out = np.zeros((len(uniq_ids), self.width), np.float32)
        n_ref = self.client.sync_embedding(
            self.tid, self.pull_bound, uniq_ids, vers, out, self.width)
        if not n_ref:
            return None, None
        pos = np.nonzero(vers != self.ver[uniq_slots])[0]
        if len(pos) and (self.health_monitor is not None
                         or _health.active()):
            # observed read staleness: how many server updates each
            # refreshed row actually ran behind before SyncEmbedding
            # caught it up — the paper's consistency knob, measured
            # (telemetry/health.py; pull_bound is the configured bound)
            _health.observe_staleness(
                "pull", self.tid, vers[pos] - self.ver[uniq_slots][pos],
                self.pull_bound, monitor=self.health_monitor)
        self.ver[uniq_slots[pos]] = vers[pos]
        self.pulled_rows += len(pos)
        return uniq_slots[pos], out[pos]

    # -- combined drain + refresh (kPushSyncEmbedding) ---------------------
    def push_sync(self, push_ids, push_rows, upds, uniq_ids, uniq_slots):
        """One RPC per shard that both applies the accumulated grads
        (PushEmbedding semantics: server optimizer runs, per-row
        versions bump by ``upds``) and refreshes the rows whose server
        version ran more than ``pull_bound`` ahead (SyncEmbedding
        semantics). The caller already claimed the dirty set with
        :meth:`take_dirty`; read bookkeeping mirrors
        :meth:`stale_check`. Returns ``(slots_to_fill, rows)`` or
        ``(None, None)``."""
        if self.nworkers > 1:
            push_rows = push_rows / self.nworkers
        vers = self.ver[uniq_slots].copy()
        out = np.zeros((len(uniq_ids), self.width), np.float32)
        n_ref = self.client.push_sync_embedding(
            self.tid, push_ids, push_rows, upds, self.pull_bound,
            uniq_ids, vers, out, self.width)
        if not n_ref:
            return None, None
        pos = np.nonzero(vers != self.ver[uniq_slots])[0]
        if len(pos) and (self.health_monitor is not None
                         or _health.active()):
            _health.observe_staleness(
                "pull", self.tid, vers[pos] - self.ver[uniq_slots][pos],
                self.pull_bound, monitor=self.health_monitor)
        self.ver[uniq_slots[pos]] = vers[pos]
        self.pulled_rows += len(pos)
        return uniq_slots[pos], out[pos]

    # -- drain --------------------------------------------------------------
    def take_dirty(self):
        """Claim the dirty set for a push; resets counters. Returns
        ``(slots int64[n], ids int64[n], upd_counts int64[n])``."""
        slots = np.nonzero(self.dirty)[0]
        ids = self.id_of[slots]
        upds = self.upd[slots].copy()
        self.dirty[slots] = False
        self.upd[slots] = 0
        self.steps_since_drain = 0
        keep = ids >= 0
        if keep.any() and (self.health_monitor is not None
                           or _health.active()):
            # observed write staleness: per-row local updates the
            # server had not seen when this drain claimed them. A count
            # past push_bound means the drain cadence failed to hold
            # the configured bound (deferred drains, long scan blocks)
            # — the health monitor trips on those (kind="staleness")
            _health.observe_staleness("push", self.tid, upds[keep],
                                      self.push_bound,
                                      monitor=self.health_monitor)
        return slots[keep].astype(np.int64), ids[keep], upds[keep]

    def invalidate(self):
        """Drop every cached row (e.g. after a checkpoint load replaced
        the server values). Pending updates must be drained first."""
        assert not self.dirty.any(), \
            "invalidate() with un-drained updates would lose them"
        if self._slot_of is not None:
            self._slot_of[:] = -1
        else:
            self._slot_dict.clear()
        self.id_of[:] = -1
        self.ver[:] = 0
        self.upd[:] = 0
        self._clock[:] = False
        self._pinned[:] = False
        self._hand = 0
        self._n_used = 0

    @property
    def perf(self):
        total = self.hits + self.misses
        return {"hits": self.hits, "misses": self.misses,
                "evicts": self.evicts, "size": self._n_used,
                "pushed_rows": self.pushed_rows,
                "pulled_rows": self.pulled_rows,
                "miss_rate": self.misses / total if total else 0.0}


def pad_fill(cache, slots, rows, scratch_slot):
    """Scatter ``rows`` into ``cache`` at ``slots``, padding the batch to
    a power-of-two bucket (pad entries target the scratch row) so the jit
    cache sees O(log n) distinct shapes."""
    n = len(slots)
    b = _pad_pow2(n)
    pslots = np.full(b, scratch_slot, np.int32)
    pslots[:n] = slots
    prows = np.zeros((b, rows.shape[1]), np.float32)
    prows[:n] = rows
    return _fill_rows(cache, pslots, prows)


def pad_gather_zero(acc, slots, scratch_slot, compress=False):
    """Gather accumulator rows at ``slots`` then zero them, padded to a
    bucket. Returns (new_acc, gathered_rows_device, n_real).

    ``compress=True`` casts the gathered grad sums to bf16 on device —
    the drain's device->host transfer is the HET path's dominant link
    cost (notably over a remote TPU tunnel), and the server applies SGD
    at f32 after widening, so the worker's own full-precision cache is
    untouched."""
    n = len(slots)
    b = _pad_pow2(n)
    pslots = np.full(b, scratch_slot, np.int64)
    pslots[:n] = slots
    pslots_dev = jnp.asarray(pslots)
    gather = _gather_rows_bf16 if compress else _gather_rows
    rows = gather(acc, pslots_dev)
    new_acc = _zero_rows(acc, pslots_dev)
    # transfer only the claimed rows, padded to a coarse chunk (a pow2
    # pad can double the D2H bytes; a 2048-row chunk wastes <1 chunk
    # while keeping the slice's jit cache small)
    m = min(b, -(-n // 2048) * 2048)
    if m < b:
        rows = rows[:m]
    return new_acc, rows, n
