"""Server-role entrypoint: ``python -m hetu_tpu.ps.run_server PORT
NWORKERS`` (the reference's DMLC_ROLE=server process)."""
import sys

from .native_lib import get_lib


def main():
    port = int(sys.argv[1]) if len(sys.argv) > 1 else 18590
    nworkers = int(sys.argv[2]) if len(sys.argv) > 2 else 1
    sys.exit(get_lib().hetu_ps_run_server(port, nworkers))


if __name__ == "__main__":
    main()
