"""Server-role entrypoint: ``python -m hetu_tpu.ps.run_server PORT
NWORKERS`` (the reference's DMLC_ROLE=server process).

With ``HETU_TELEMETRY_PORT`` set (``heturun --telemetry`` exports it),
the process serves a Prometheus text-format ``/metrics`` scrape on a
daemon thread beside the native request loop — liveness (uptime),
identity (port, nworkers, pid) and RSS, so a degraded server host is
visible to the same scrape infrastructure the workers feed.
"""
import os
import sys

from .native_lib import get_lib


def _serve_metrics(ps_port, nworkers):
    scrape_port = int(os.environ.get("HETU_TELEMETRY_PORT", "0"))
    if not scrape_port:
        return None
    from ..telemetry import MetricsRegistry
    from ..telemetry.metrics import uptime_gauge

    reg = MetricsRegistry()
    uptime_gauge(reg, "hetu_ps_server_uptime_seconds")
    reg.gauge("hetu_ps_server_port").set(ps_port)
    reg.gauge("hetu_ps_server_nworkers").set(nworkers)
    reg.gauge("hetu_ps_server_pid").set(os.getpid())

    def _rss_bytes():
        try:
            with open("/proc/self/statm") as f:
                return int(f.read().split()[1]) * os.sysconf("SC_PAGESIZE")
        except (OSError, ValueError, IndexError):
            return 0

    reg.gauge("hetu_ps_server_rss_bytes", fn=_rss_bytes)
    # bind on all interfaces: the scrape may come from another host
    reg.serve(scrape_port, host="0.0.0.0")
    return reg


def _register_faulthandler(port):
    """SIGUSR1 -> all-thread stack dump, so a wedged server process is
    inspectable live (and the fleet watchdog's diagnose-then-kill
    sequence collects server stacks too). Dumps land in the telemetry
    dir when the launcher exported one, stderr otherwise."""
    import faulthandler
    import signal
    try:
        tdir = os.environ.get("HETU_TELEMETRY")
        if tdir:
            os.makedirs(tdir, exist_ok=True)
            f = open(os.path.join(tdir, f"stacks_server{port}.log"), "a")
        else:
            f = sys.stderr
        faulthandler.register(signal.SIGUSR1, file=f, all_threads=True)
    except (ValueError, OSError, AttributeError):
        pass


def main():
    port = int(sys.argv[1]) if len(sys.argv) > 1 else 18590
    nworkers = int(sys.argv[2]) if len(sys.argv) > 2 else 1
    _register_faulthandler(port)
    try:
        _serve_metrics(port, nworkers)
    except OSError as e:
        # observability must never take down the data path: a scrape
        # port collision (second fleet on the same host) logs and the
        # PS request loop starts anyway
        print(f"[hetu-ps] telemetry scrape disabled: {e}",
              file=sys.stderr)
    # HETU_PS_LISTEN_FD: an already-bound socket inherited from
    # ensure_server's atomic port claim (startup-race fix) — serve on
    # it instead of binding a fresh one
    lfd = int(os.environ.get("HETU_PS_LISTEN_FD", "-1"))
    lib = get_lib()          # lazy native build: the slow failure mode
    # readiness signal: with the port pre-listened by the parent,
    # connectability no longer means "serving" — write one byte on the
    # inherited pipe once imports + the native build survived, i.e.
    # the accept loop is about to run. A child that dies earlier
    # EOFs the pipe instead, which ensure_server turns into the
    # "exited during startup" error (it would otherwise see the open
    # port and return a dead Popen as a live server).
    ready = int(os.environ.get("HETU_PS_READY_FD", "-1"))
    if ready >= 0:
        try:
            os.write(ready, b"1")
            os.close(ready)
        except OSError:
            pass
    if lfd >= 0:
        sys.exit(lib.hetu_ps_run_server_fd(lfd, port, nworkers))
    sys.exit(lib.hetu_ps_run_server(port, nworkers))


if __name__ == "__main__":
    main()
