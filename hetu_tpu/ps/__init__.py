"""Parameter-server subsystem.

TPU-native equivalent of the reference's ps-lite stack (C++ server over
ZMQ/P3/IB-verbs). Here: a C++ host-side key-value server with typed PSF
requests (dense/sparse push-pull, server-side optimizers, save/load) over
TCP, a Python client bound via ctypes, and an embedding cache with bounded
staleness. See ps/README.md for the protocol.
"""
