// hetu-tpu parameter server (host-side C++).
//
// TPU-native equivalent of the reference's ps-lite server
// (ps-lite/include/ps/server/PSFHandle.h KVServerMatrixHandle +
// ps/server/optimizer.h server-side optimizers + PSFhandle_embedding.cc
// versioned cache tables): tensors live in host RAM behind per-tensor
// reader/writer locks, updates apply OpenMP-parallel, sparse tables keep
// per-row versions for the bounded-staleness embedding-cache protocol.
// Transport is plain TCP threads (the reference's ZMQ/P3/IBVerbs vans
// collapse to this on a TPU pod: workers talk to host PS over DCN).
#include <arpa/inet.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <unistd.h>

#include <atomic>
#include <cmath>
#include <condition_variable>
#include <cstdio>
#include <deque>
#include <map>
#include <memory>
#include <mutex>
#include <random>
#include <shared_mutex>
#include <thread>
#include <unordered_map>
#include <unordered_set>
#include <vector>

#include "ps_common.h"

namespace hetups {

static bool read_full(int fd, void* buf, size_t n) {
  uint8_t* p = static_cast<uint8_t*>(buf);
  while (n) {
    ssize_t r = ::read(fd, p, n);
    if (r <= 0) return false;
    p += r;
    n -= static_cast<size_t>(r);
  }
  return true;
}

static bool write_full(int fd, const void* buf, size_t n) {
  const uint8_t* p = static_cast<const uint8_t*>(buf);
  while (n) {
    ssize_t r = ::write(fd, p, n);
    if (r <= 0) return false;
    p += r;
    n -= static_cast<size_t>(r);
  }
  return true;
}

using version_t = int64_t;

struct Tensor {
  ParamKind kind = ParamKind::kParam;
  OptKind opt = OptKind::kNone;
  int64_t len = 0;    // rows (or flat length for dense)
  int64_t width = 1;  // row width for 2-D tables
  std::vector<float> data;
  std::vector<version_t> ver;       // per-row versions (cache tables)
  std::vector<float> lrs;           // [lr, momentum/beta1, beta2, eps...]
  // optimizer slots
  std::vector<float> m, v;
  int64_t step = 0;
  mutable std::shared_mutex mu;

  int64_t nelem() const { return len * width; }
  float lr() const { return lrs.empty() ? 0.1f : lrs[0]; }

  void init_slots() {
    switch (opt) {
      case OptKind::kMomentum:
      case OptKind::kNesterov:
      case OptKind::kAdaGrad:
        m.assign(nelem(), 0.f);
        break;
      case OptKind::kAdam:
        m.assign(nelem(), 0.f);
        v.assign(nelem(), 0.f);
        break;
      default:
        break;
    }
  }

  // dense update over the full buffer (reference ApplyDense)
  void apply_dense(const float* g) {
    const int64_t n = nelem();
    const float a = lr();
    switch (opt) {
      case OptKind::kNone:
#pragma omp parallel for
        for (int64_t i = 0; i < n; ++i) data[i] += g[i];
        break;
      case OptKind::kSGD:
#pragma omp parallel for
        for (int64_t i = 0; i < n; ++i) data[i] -= a * g[i];
        break;
      case OptKind::kMomentum:
#pragma omp parallel for
        for (int64_t i = 0; i < n; ++i) {
          m[i] = lrs[1] * m[i] - a * g[i];
          data[i] += m[i];
        }
        break;
      case OptKind::kNesterov:
#pragma omp parallel for
        for (int64_t i = 0; i < n; ++i) {
          float vel = lrs[1] * m[i] - a * g[i];
          data[i] += lrs[1] * vel - a * g[i];
          m[i] = vel;
        }
        break;
      case OptKind::kAdaGrad:
#pragma omp parallel for
        for (int64_t i = 0; i < n; ++i) {
          m[i] += g[i] * g[i];
          data[i] -= a * g[i] / (std::sqrt(m[i]) + lrs[1]);
        }
        break;
      case OptKind::kAdam: {
        ++step;
        const float b1 = lrs[1], b2 = lrs[2], eps = lrs[3];
        const float wd = lrs.size() > 4 ? lrs[4] : 0.f;  // AdamW decay
        const float bc1 = 1.f - std::pow(b1, static_cast<float>(step));
        const float bc2 = 1.f - std::pow(b2, static_cast<float>(step));
        const float scale = a * std::sqrt(bc2) / bc1;
#pragma omp parallel for
        for (int64_t i = 0; i < n; ++i) {
          m[i] = b1 * m[i] + (1 - b1) * g[i];
          v[i] = b2 * v[i] + (1 - b2) * g[i] * g[i];
          data[i] -= scale * m[i] / (std::sqrt(v[i]) + eps)
                     + a * wd * data[i];
        }
        break;
      }
    }
  }

  // one row's optimizer update from an (already aggregated) gradient
  inline void apply_row(int64_t row, const float* src, float a) {
    const int64_t w = width;
    float* dst = data.data() + row * w;
    switch (opt) {
      case OptKind::kNone:
        for (int64_t k = 0; k < w; ++k) dst[k] += src[k];
        break;
      case OptKind::kSGD:
        for (int64_t k = 0; k < w; ++k) dst[k] -= a * src[k];
        break;
      case OptKind::kAdaGrad: {
        float* acc = m.data() + row * w;
        for (int64_t k = 0; k < w; ++k) {
          acc[k] += src[k] * src[k];
          dst[k] -= a * src[k] / (std::sqrt(acc[k]) + lrs[1]);
        }
        break;
      }
      case OptKind::kAdam: {
        // row-wise adam without global bias correction (matches the
        // reference's AdamOptimizer::ApplySparse per-row treatment)
        const float b1 = lrs[1], b2 = lrs[2], eps = lrs[3];
        float* mi = m.data() + row * w;
        float* vi = v.data() + row * w;
        for (int64_t k = 0; k < w; ++k) {
          mi[k] = b1 * mi[k] + (1 - b1) * src[k];
          vi[k] = b2 * vi[k] + (1 - b2) * src[k] * src[k];
          dst[k] -= a * mi[k] / (std::sqrt(vi[k]) + eps);
        }
        break;
      }
      default:  // Momentum variants fall back to SGD row update
        for (int64_t k = 0; k < w; ++k) dst[k] -= a * src[k];
    }
  }

  // sparse row update (reference ApplySparse/ApplyCache); bumps versions.
  // Duplicate row ids within one push are aggregated (summed) first so the
  // parallel apply touches each row exactly once — otherwise two omp
  // threads race on the same row's data/slots/version (lost updates).
  // Versions advance by occurrence count, matching the cache push path
  // (kPushEmbedding), so bounded-staleness accounting stays consistent.
  void apply_sparse(const int64_t* idx, size_t nidx, const float* g) {
    const int64_t w = width;
    const float a = lr();
    // cheap duplicate scan first: the common cache-drained push has all
    // unique ids, where we can apply straight from g with no copy
    std::unordered_map<int64_t, int64_t> occ;  // row -> occurrence count
    occ.reserve(nidx * 2);
    bool has_dup = false;
    for (size_t j = 0; j < nidx; ++j) {
      int64_t row = idx[j];
      if (row < 0 || row >= len) continue;
      if (++occ[row] > 1) has_dup = true;
    }
    if (!has_dup) {
      const int64_t n = static_cast<int64_t>(nidx);
#pragma omp parallel for
      for (int64_t j = 0; j < n; ++j) {
        int64_t row = idx[j];
        if (row < 0 || row >= len) continue;
        apply_row(row, g + j * w, a);
        if (!ver.empty()) ++ver[row];
      }
      return;
    }
    std::unordered_map<int64_t, size_t> slot;  // row -> index into uniq
    slot.reserve(occ.size() * 2);
    std::vector<int64_t> uniq_rows;
    std::vector<float> agg;  // aggregated gradients, uniq-major
    uniq_rows.reserve(occ.size());
    agg.reserve(occ.size() * w);
    for (size_t j = 0; j < nidx; ++j) {
      int64_t row = idx[j];
      if (row < 0 || row >= len) continue;
      const float* src = g + j * w;
      auto it = slot.find(row);
      if (it == slot.end()) {
        slot.emplace(row, uniq_rows.size());
        uniq_rows.push_back(row);
        agg.insert(agg.end(), src, src + w);
      } else {
        float* acc = agg.data() + it->second * w;
        for (int64_t k = 0; k < w; ++k) acc[k] += src[k];
      }
    }
    const int64_t nuniq = static_cast<int64_t>(uniq_rows.size());
#pragma omp parallel for
    for (int64_t j = 0; j < nuniq; ++j) {
      int64_t row = uniq_rows[j];
      apply_row(row, agg.data() + j * w, a);
      if (!ver.empty()) ver[row] += occ[row];
    }
  }

  void gather(const int64_t* idx, size_t nidx, float* out) const {
    const int64_t w = width;
#pragma omp parallel for
    for (size_t j = 0; j < nidx; ++j) {
      int64_t row = idx[j];
      if (row < 0 || row >= len) {
        std::memset(out + j * w, 0, w * sizeof(float));
      } else {
        std::memcpy(out + j * w, data.data() + row * w, w * sizeof(float));
      }
    }
  }
};

class Server {
 public:
  Server(int port, int nworkers) : port_(port), nworkers_(nworkers) {}

  int run() { return run_fd(-1); }

  // ``lfd >= 0``: an already-bound, already-listening socket inherited
  // from the launcher — the atomic port claim of ensure_server
  // (ps/server.py): whoever bind+listens it owns the port, so two
  // racing spawners can never both start a server. The re-listen below
  // is a harmless backlog update on that path.
  int run_fd(int lfd) {
    if (lfd < 0) {
      lfd = ::socket(AF_INET, SOCK_STREAM, 0);
      int one = 1;
      ::setsockopt(lfd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof one);
      sockaddr_in addr{};
      addr.sin_family = AF_INET;
      addr.sin_addr.s_addr = htonl(INADDR_ANY);
      addr.sin_port = htons(static_cast<uint16_t>(port_));
      if (::bind(lfd, reinterpret_cast<sockaddr*>(&addr),
                 sizeof addr) != 0) {
        std::perror("hetu-ps bind");
        return 1;
      }
    }
    if (::listen(lfd, 64) != 0) {
      std::perror("hetu-ps listen");
      return 1;
    }
    std::fprintf(stderr, "[hetu-ps] serving on :%d (%d workers)\n", port_,
                 nworkers_);
    while (!stop_.load()) {
      int cfd = ::accept(lfd, nullptr, nullptr);
      if (cfd < 0) break;
      int nd = 1;
      ::setsockopt(cfd, IPPROTO_TCP, TCP_NODELAY, &nd, sizeof nd);
      std::thread(&Server::serve_conn, this, cfd).detach();
    }
    ::close(lfd);
    return 0;
  }

 private:
  Tensor* get(int32_t id) {
    std::shared_lock<std::shared_mutex> l(store_mu_);
    auto it = store_.find(id);
    return it == store_.end() ? nullptr : it->second.get();
  }

  void serve_conn(int fd) {
    std::vector<uint8_t> payload;
    for (;;) {
      MsgHeader h;
      if (!read_full(fd, &h, sizeof h) || h.magic != 0x48505332) break;
      payload.resize(h.payload_len);
      if (h.payload_len && !read_full(fd, payload.data(), h.payload_len))
        break;
      Writer out;
      int32_t status = handle(static_cast<Op>(h.op), h.tensor_id,
                              payload, out, h.worker, h.seq);
      MsgHeader rh;
      rh.op = h.op;
      rh.tensor_id = h.tensor_id;
      rh.status = status;
      rh.payload_len = out.buf.size();
      if (!write_full(fd, &rh, sizeof rh)) break;
      if (!out.buf.empty() &&
          !write_full(fd, out.buf.data(), out.buf.size()))
        break;
      if (static_cast<Op>(h.op) == Op::kShutdown) {
        stop_.store(true);
        // poke the accept loop
        int s = ::socket(AF_INET, SOCK_STREAM, 0);
        sockaddr_in a{};
        a.sin_family = AF_INET;
        a.sin_addr.s_addr = htonl(INADDR_LOOPBACK);
        a.sin_port = htons(static_cast<uint16_t>(port_));
        ::connect(s, reinterpret_cast<sockaddr*>(&a), sizeof a);
        ::close(s);
        break;
      }
    }
    ::close(fd);
  }

  // at-most-once retry protection (reference ps-lite resender.h): a
  // client retries a request whose connection died after the server may
  // already have applied it; mutating ops are deduped on (worker, seq)
  // so the retry serves only the read part. Returns true if duplicate.
  bool check_and_record(uint32_t worker, uint64_t seq) {
    std::lock_guard<std::mutex> l(dedup_mu_);
    auto& d = dedup_[worker];
    if (d.seen.count(seq)) return true;
    d.seen.insert(seq);
    d.order.push_back(seq);
    if (d.order.size() > 65536) {
      d.seen.erase(d.order.front());
      d.order.pop_front();
    }
    return false;
  }

  int32_t handle(Op op, int32_t id, const std::vector<uint8_t>& payload,
                 Writer& out, uint32_t worker, uint64_t seq) {
    Reader rd(payload.data(), payload.size());
    switch (op) {
      case Op::kInitTensor: {
        auto t = std::make_unique<Tensor>();
        t->kind = static_cast<ParamKind>(rd.i32());
        t->len = rd.i64();
        t->width = rd.i64();
        InitKind ik = static_cast<InitKind>(rd.i32());
        double a = rd.f64(), b = rd.f64();
        uint64_t seed = rd.u64();
        t->opt = static_cast<OptKind>(rd.i32());
        size_t nlr;
        const float* lrp = rd.floats(&nlr);
        t->lrs.assign(lrp, lrp + nlr);
        t->data.resize(t->nelem());
        // on-server init (reference PSFHandle.h:277-342)
        std::mt19937_64 gen(seed ? seed : 0x9e3779b9);
        switch (ik) {
          case InitKind::kConstant:
            std::fill(t->data.begin(), t->data.end(),
                      static_cast<float>(a));
            break;
          case InitKind::kUniform: {
            std::uniform_real_distribution<float> d(
                static_cast<float>(a), static_cast<float>(b));
            for (auto& x : t->data) x = d(gen);
            break;
          }
          case InitKind::kNormal: {
            std::normal_distribution<float> d(static_cast<float>(a),
                                              static_cast<float>(b));
            for (auto& x : t->data) x = d(gen);
            break;
          }
          case InitKind::kTruncatedNormal: {
            std::normal_distribution<float> d(static_cast<float>(a),
                                              static_cast<float>(b));
            for (auto& x : t->data) {
              do {
                x = d(gen);
              } while (std::fabs(x - a) > 2 * b);
            }
            break;
          }
        }
        t->init_slots();
        if (t->kind == ParamKind::kCacheTable) t->ver.assign(t->len, 0);
        {
          std::unique_lock<std::shared_mutex> l(store_mu_);
          // idempotent across workers: first init wins (reference
          // PSFHandle ParamInit re-registration is a no-op)
          if (!store_.count(id)) store_[id] = std::move(t);
        }
        return 0;
      }
      case Op::kDensePull: {
        Tensor* t = get(id);
        if (!t) return -1;
        std::shared_lock<std::shared_mutex> l(t->mu);
        out.floats(t->data.data(), t->data.size());
        return 0;
      }
      case Op::kDensePush:
      case Op::kDDPushPull: {
        Tensor* t = get(id);
        if (!t) return -1;
        size_t n;
        const float* g = rd.floats(&n);
        bool dup = check_and_record(worker, seq);
        std::unique_lock<std::shared_mutex> l(t->mu);
        if (!dup && static_cast<int64_t>(n) == t->nelem())
          t->apply_dense(g);
        if (op == Op::kDDPushPull)
          out.floats(t->data.data(), t->data.size());
        bytes_in_ += n * 4;
        return 0;
      }
      case Op::kSparsePull: {
        Tensor* t = get(id);
        if (!t) return -1;
        size_t nidx;
        const int64_t* idx = rd.longs(&nidx);
        std::shared_lock<std::shared_mutex> l(t->mu);
        out.i64(static_cast<int64_t>(nidx * t->width));
        size_t off = out.buf.size();
        out.buf.resize(off + nidx * t->width * sizeof(float));
        t->gather(idx, nidx,
                  reinterpret_cast<float*>(out.buf.data() + off));
        return 0;
      }
      case Op::kSparsePush: {
        Tensor* t = get(id);
        if (!t) return -1;
        size_t nidx, nval;
        const int64_t* idx = rd.longs(&nidx);
        const float* g = rd.floats(&nval);
        bool dup = check_and_record(worker, seq);
        std::unique_lock<std::shared_mutex> l(t->mu);
        if (!dup) t->apply_sparse(idx, nidx, g);
        bytes_in_ += nval * 4;
        return 0;
      }
      case Op::kSDPushPull: {
        // push sparse grad rows, pull the full dense tensor
        Tensor* t = get(id);
        if (!t) return -1;
        size_t nidx, nval;
        const int64_t* idx = rd.longs(&nidx);
        const float* g = rd.floats(&nval);
        bool dup = check_and_record(worker, seq);
        std::unique_lock<std::shared_mutex> l(t->mu);
        if (!dup) t->apply_sparse(idx, nidx, g);
        out.floats(t->data.data(), t->data.size());
        return 0;
      }
      case Op::kSSPushPull: {
        // push grad rows at in-indices, pull rows at out-indices (the
        // prefetch pipeline: pull next batch's rows — reference
        // SSPushPull, PSFHandle.h:217-268)
        Tensor* t = get(id);
        if (!t) return -1;
        size_t nin, nval, nout;
        const int64_t* in_idx = rd.longs(&nin);
        const float* g = rd.floats(&nval);
        const int64_t* out_idx = rd.longs(&nout);
        bool dup = check_and_record(worker, seq);
        std::unique_lock<std::shared_mutex> l(t->mu);
        if (!dup) t->apply_sparse(in_idx, nin, g);
        out.i64(static_cast<int64_t>(nout * t->width));
        size_t off = out.buf.size();
        out.buf.resize(off + nout * t->width * sizeof(float));
        t->gather(out_idx, nout,
                  reinterpret_cast<float*>(out.buf.data() + off));
        return 0;
      }
      case Op::kSyncEmbedding: {
        // bounded staleness: return only rows whose server version
        // exceeds the client's by more than `bound`
        // (reference hetu_client.cc:6-38 / PSFhandle_embedding.cc)
        Tensor* t = get(id);
        if (!t || t->ver.empty()) return -1;
        int64_t bound = rd.i64();
        size_t nidx, nver;
        const int64_t* idx = rd.longs(&nidx);
        const int64_t* cver = rd.longs(&nver);
        std::shared_lock<std::shared_mutex> l(t->mu);
        std::vector<int64_t> stale_pos, stale_ver;
        std::vector<float> rows;
        for (size_t j = 0; j < nidx; ++j) {
          int64_t row = idx[j];
          if (row < 0 || row >= t->len) continue;
          if (t->ver[row] - cver[j] > bound) {
            stale_pos.push_back(static_cast<int64_t>(j));
            stale_ver.push_back(t->ver[row]);
            size_t o = rows.size();
            rows.resize(o + t->width);
            std::memcpy(rows.data() + o, t->data.data() + row * t->width,
                        t->width * sizeof(float));
          }
        }
        out.longs(stale_pos.data(), stale_pos.size());
        out.longs(stale_ver.data(), stale_ver.size());
        out.floats(rows.data(), rows.size());
        return 0;
      }
      case Op::kPushEmbedding: {
        Tensor* t = get(id);
        if (!t) return -1;
        size_t nidx, nval, nupd;
        const int64_t* idx = rd.longs(&nidx);
        const float* g = rd.floats(&nval);
        const int64_t* upd = rd.longs(&nupd);  // per-row update counts
        bool dup = check_and_record(worker, seq);
        if (dup) return 0;
        std::unique_lock<std::shared_mutex> l(t->mu);
        t->apply_sparse(idx, nidx, g);
        if (!t->ver.empty())
          for (size_t j = 0; j < nupd && j < nidx; ++j)
            if (idx[j] >= 0 && idx[j] < t->len)
              t->ver[idx[j]] += upd[j] - 1;  // apply_sparse added 1
        return 0;
      }
      case Op::kPushSyncEmbedding: {
        Tensor* t = get(id);
        if (!t || t->ver.empty()) return -1;
        int64_t bound = rd.i64();
        size_t npidx, nval, nupd, nsidx, nsver;
        const int64_t* pidx = rd.longs(&npidx);
        const float* g = rd.floats(&nval);
        const int64_t* upd = rd.longs(&nupd);
        const int64_t* sidx = rd.longs(&nsidx);
        const int64_t* sver = rd.longs(&nsver);
        bool dup = check_and_record(worker, seq);
        std::unique_lock<std::shared_mutex> l(t->mu);
        if (!dup) {
          t->apply_sparse(pidx, npidx, g);
          for (size_t j = 0; j < nupd && j < npidx; ++j)
            if (pidx[j] >= 0 && pidx[j] < t->len)
              t->ver[pidx[j]] += upd[j] - 1;
        }
        std::vector<int64_t> stale_pos, stale_ver;
        std::vector<float> rows;
        for (size_t j = 0; j < nsidx; ++j) {
          int64_t row = sidx[j];
          if (row < 0 || row >= t->len) continue;
          if (t->ver[row] - sver[j] > bound) {
            stale_pos.push_back(static_cast<int64_t>(j));
            stale_ver.push_back(t->ver[row]);
            size_t o = rows.size();
            rows.resize(o + t->width);
            std::memcpy(rows.data() + o, t->data.data() + row * t->width,
                        t->width * sizeof(float));
          }
        }
        out.longs(stale_pos.data(), stale_pos.size());
        out.longs(stale_ver.data(), stale_ver.size());
        out.floats(rows.data(), rows.size());
        return 0;
      }
      case Op::kParamSet: {
        Tensor* t = get(id);
        if (!t) return -1;
        size_t n;
        const float* p = rd.floats(&n);
        std::unique_lock<std::shared_mutex> l(t->mu);
        if (static_cast<int64_t>(n) != t->nelem()) return -3;
        std::memcpy(t->data.data(), p, n * sizeof(float));
        return 0;
      }
      case Op::kParamClear: {
        Tensor* t = get(id);
        if (!t) return -1;
        std::unique_lock<std::shared_mutex> l(t->mu);
        std::fill(t->data.begin(), t->data.end(), 0.f);
        return 0;
      }
      case Op::kParamSave: {
        Tensor* t = get(id);
        if (!t) return -1;
        std::string path = rd.str();
        std::shared_lock<std::shared_mutex> l(t->mu);
        FILE* f = std::fopen(path.c_str(), "wb");
        if (!f) return -2;
        std::fwrite(&t->len, sizeof t->len, 1, f);
        std::fwrite(&t->width, sizeof t->width, 1, f);
        std::fwrite(t->data.data(), sizeof(float), t->data.size(), f);
        std::fclose(f);
        return 0;
      }
      case Op::kParamLoad: {
        Tensor* t = get(id);
        if (!t) return -1;
        std::string path = rd.str();
        std::unique_lock<std::shared_mutex> l(t->mu);
        FILE* f = std::fopen(path.c_str(), "rb");
        if (!f) return -2;
        int64_t len, width;
        if (std::fread(&len, sizeof len, 1, f) != 1 ||
            std::fread(&width, sizeof width, 1, f) != 1 ||
            len != t->len || width != t->width) {
          std::fclose(f);
          return -3;
        }
        size_t got = std::fread(t->data.data(), sizeof(float),
                                t->data.size(), f);
        std::fclose(f);
        return got == t->data.size() ? 0 : -3;
      }
      case Op::kBarrier: {
        std::unique_lock<std::mutex> l(bar_mu_);
        // a retried barrier (first registration's response was lost)
        // must not count the worker twice: wait out the generation the
        // original registration joined, then succeed
        bool is_dup = false;
        int reg_gen = 0;
        {
          std::lock_guard<std::mutex> dl(dedup_mu_);
          auto& d = dedup_[worker];
          auto it = d.bar_gen.find(seq);
          if (it != d.bar_gen.end()) {
            is_dup = true;
            reg_gen = it->second;
          } else {
            d.bar_gen[seq] = bar_gen_;
            d.bar_order.push_back(seq);
            if (d.bar_order.size() > 1024) {
              // evict the OLDEST registration — live retries target
              // recent barriers, so insertion-order pruning never
              // drops an in-flight retry's dedup entry
              d.bar_gen.erase(d.bar_order.front());
              d.bar_order.pop_front();
            }
          }
        }
        if (is_dup) {
          bar_cv_.wait(l, [&] { return bar_gen_ != reg_gen; });
          return 0;
        }
        int gen = bar_gen_;
        if (++bar_count_ >= nworkers_) {
          bar_count_ = 0;
          ++bar_gen_;
          bar_cv_.notify_all();
        } else {
          bar_cv_.wait(l, [&] { return bar_gen_ != gen; });
        }
        return 0;
      }
      case Op::kPushData: {
        int64_t key = rd.i64();
        size_t n;
        const float* p = rd.floats(&n);
        std::unique_lock<std::shared_mutex> l(blob_mu_);
        blobs_[key].assign(p, p + n);
        return 0;
      }
      case Op::kPullData: {
        int64_t key = rd.i64();
        std::shared_lock<std::shared_mutex> l(blob_mu_);
        auto it = blobs_.find(key);
        if (it == blobs_.end()) return -1;
        out.floats(it->second.data(), it->second.size());
        return 0;
      }
      case Op::kGetLoads: {
        out.u64(bytes_in_.load());
        return 0;
      }
      case Op::kShutdown:
        return 0;
    }
    return -100;
  }

  int port_;
  int nworkers_;
  std::atomic<bool> stop_{false};
  std::unordered_map<int32_t, std::unique_ptr<Tensor>> store_;
  std::shared_mutex store_mu_;
  std::unordered_map<int64_t, std::vector<float>> blobs_;
  std::shared_mutex blob_mu_;
  std::mutex bar_mu_;
  std::condition_variable bar_cv_;
  int bar_count_ = 0;
  int bar_gen_ = 0;
  // per-worker (seq) dedup for at-most-once mutating ops
  struct WorkerDedup {
    std::unordered_set<uint64_t> seen;
    std::deque<uint64_t> order;
    std::unordered_map<uint64_t, int> bar_gen;  // barrier seq -> gen
    std::deque<uint64_t> bar_order;             // insertion order
  };
  std::mutex dedup_mu_;
  std::unordered_map<uint32_t, WorkerDedup> dedup_;
  std::atomic<uint64_t> bytes_in_{0};
};

}  // namespace hetups

extern "C" int hetu_ps_run_server(int port, int nworkers) {
  hetups::Server s(port, nworkers);
  return s.run();
}

// launcher-claimed-socket form: serve on an inherited bound fd (the
// ensure_server startup-race fix); ``port`` is still needed for the
// shutdown self-connect poke.
extern "C" int hetu_ps_run_server_fd(int lfd, int port, int nworkers) {
  hetups::Server s(port, nworkers);
  return s.run_fd(lfd);
}
