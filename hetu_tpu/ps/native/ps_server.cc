// hetu-tpu parameter server (host-side C++).
//
// TPU-native equivalent of the reference's ps-lite server
// (ps-lite/include/ps/server/PSFHandle.h KVServerMatrixHandle +
// ps/server/optimizer.h server-side optimizers + PSFhandle_embedding.cc
// versioned cache tables): tensors live in host RAM behind per-tensor
// reader/writer locks, updates apply OpenMP-parallel, sparse tables keep
// per-row versions for the bounded-staleness embedding-cache protocol.
// Transport is plain TCP threads (the reference's ZMQ/P3/IBVerbs vans
// collapse to this on a TPU pod: workers talk to host PS over DCN).
#include <arpa/inet.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <unistd.h>

#include <atomic>
#include <cmath>
#include <condition_variable>
#include <cstdio>
#include <cstdlib>
#include <ctime>
#include <deque>
#include <map>
#include <memory>
#include <mutex>
#include <random>
#include <shared_mutex>
#include <thread>
#include <unordered_map>
#include <unordered_set>
#include <vector>

#include "ps_common.h"
#include "ps_store.h"

namespace hetups {

static bool read_full(int fd, void* buf, size_t n) {
  uint8_t* p = static_cast<uint8_t*>(buf);
  while (n) {
    ssize_t r = ::read(fd, p, n);
    if (r <= 0) return false;
    p += r;
    n -= static_cast<size_t>(r);
  }
  return true;
}

static bool write_full(int fd, const void* buf, size_t n) {
  const uint8_t* p = static_cast<const uint8_t*>(buf);
  while (n) {
    ssize_t r = ::write(fd, p, n);
    if (r <= 0) return false;
    p += r;
    n -= static_cast<size_t>(r);
  }
  return true;
}

using version_t = int64_t;

struct Tensor {
  ParamKind kind = ParamKind::kParam;
  OptKind opt = OptKind::kNone;
  int64_t len = 0;    // rows (or flat length for dense)
  int64_t width = 1;  // row width for 2-D tables
  std::vector<float> data;
  std::vector<version_t> ver;       // per-row versions (cache tables)
  std::vector<float> lrs;           // [lr, momentum/beta1, beta2, eps...]
  // optimizer slots
  std::vector<float> m, v;
  int64_t step = 0;
  // tiered/quantized row storage (kStoreConfig): when set, ``data`` is
  // empty and every row lives in the DRAM pool or the spill file;
  // restricted to SGD/None optimizers (no m/v slot tiering)
  std::unique_ptr<TieredStore> store;
  mutable std::shared_mutex mu;

  int64_t nelem() const { return len * width; }
  float lr() const { return lrs.empty() ? 0.1f : lrs[0]; }

  // one row for the read paths: direct pointer for dense tables, a
  // dequantized copy in ``scratch`` (caller-sized to width) for tiered
  inline const float* row_src(int64_t row, float* scratch) const {
    if (!store) return data.data() + row * width;
    store->read_row(row, scratch);
    return scratch;
  }

  // materialized full-table view for the dense pull/save paths (tiered
  // tables pay one dequant sweep; dense tables alias ``data``)
  const float* dense_view(std::vector<float>& snap) const {
    if (!store) return data.data();
    snap.resize(nelem());
    for (int64_t r = 0; r < len; ++r)
      store->read_row(r, snap.data() + r * width);
    return snap.data();
  }

  void init_slots() {
    switch (opt) {
      case OptKind::kMomentum:
      case OptKind::kNesterov:
      case OptKind::kAdaGrad:
        m.assign(nelem(), 0.f);
        break;
      case OptKind::kAdam:
        m.assign(nelem(), 0.f);
        v.assign(nelem(), 0.f);
        break;
      default:
        break;
    }
  }

  // dense update over the full buffer (reference ApplyDense)
  void apply_dense(const float* g) {
    const int64_t n = nelem();
    const float a = lr();
    if (store) {
      // tiered: read-modify-write per row (SGD/None only, enforced at
      // kStoreConfig); the dequant/requant round trip is the quantized
      // storage contract, not an accident
      std::vector<float> buf(width);
      for (int64_t r = 0; r < len; ++r) {
        store->read_row(r, buf.data());
        const float* src = g + r * width;
        if (opt == OptKind::kSGD) {
          for (int64_t k = 0; k < width; ++k) buf[k] -= a * src[k];
        } else {
          for (int64_t k = 0; k < width; ++k) buf[k] += src[k];
        }
        store->write_row(r, buf.data());
      }
      return;
    }
    switch (opt) {
      case OptKind::kNone:
#pragma omp parallel for
        for (int64_t i = 0; i < n; ++i) data[i] += g[i];
        break;
      case OptKind::kSGD:
#pragma omp parallel for
        for (int64_t i = 0; i < n; ++i) data[i] -= a * g[i];
        break;
      case OptKind::kMomentum:
#pragma omp parallel for
        for (int64_t i = 0; i < n; ++i) {
          m[i] = lrs[1] * m[i] - a * g[i];
          data[i] += m[i];
        }
        break;
      case OptKind::kNesterov:
#pragma omp parallel for
        for (int64_t i = 0; i < n; ++i) {
          float vel = lrs[1] * m[i] - a * g[i];
          data[i] += lrs[1] * vel - a * g[i];
          m[i] = vel;
        }
        break;
      case OptKind::kAdaGrad:
#pragma omp parallel for
        for (int64_t i = 0; i < n; ++i) {
          m[i] += g[i] * g[i];
          data[i] -= a * g[i] / (std::sqrt(m[i]) + lrs[1]);
        }
        break;
      case OptKind::kAdam: {
        ++step;
        const float b1 = lrs[1], b2 = lrs[2], eps = lrs[3];
        const float wd = lrs.size() > 4 ? lrs[4] : 0.f;  // AdamW decay
        const float bc1 = 1.f - std::pow(b1, static_cast<float>(step));
        const float bc2 = 1.f - std::pow(b2, static_cast<float>(step));
        const float scale = a * std::sqrt(bc2) / bc1;
#pragma omp parallel for
        for (int64_t i = 0; i < n; ++i) {
          m[i] = b1 * m[i] + (1 - b1) * g[i];
          v[i] = b2 * v[i] + (1 - b2) * g[i] * g[i];
          data[i] -= scale * m[i] / (std::sqrt(v[i]) + eps)
                     + a * wd * data[i];
        }
        break;
      }
    }
  }

  // one row's optimizer update from an (already aggregated) gradient
  inline void apply_row(int64_t row, const float* src, float a) {
    const int64_t w = width;
    if (store) {
      thread_local std::vector<float> buf;
      buf.resize(w);
      store->read_row(row, buf.data());
      if (opt == OptKind::kSGD) {
        for (int64_t k = 0; k < w; ++k) buf[k] -= a * src[k];
      } else {
        for (int64_t k = 0; k < w; ++k) buf[k] += src[k];
      }
      store->write_row(row, buf.data());
      return;
    }
    float* dst = data.data() + row * w;
    switch (opt) {
      case OptKind::kNone:
        for (int64_t k = 0; k < w; ++k) dst[k] += src[k];
        break;
      case OptKind::kSGD:
        for (int64_t k = 0; k < w; ++k) dst[k] -= a * src[k];
        break;
      case OptKind::kAdaGrad: {
        float* acc = m.data() + row * w;
        for (int64_t k = 0; k < w; ++k) {
          acc[k] += src[k] * src[k];
          dst[k] -= a * src[k] / (std::sqrt(acc[k]) + lrs[1]);
        }
        break;
      }
      case OptKind::kAdam: {
        // row-wise adam without global bias correction (matches the
        // reference's AdamOptimizer::ApplySparse per-row treatment)
        const float b1 = lrs[1], b2 = lrs[2], eps = lrs[3];
        float* mi = m.data() + row * w;
        float* vi = v.data() + row * w;
        for (int64_t k = 0; k < w; ++k) {
          mi[k] = b1 * mi[k] + (1 - b1) * src[k];
          vi[k] = b2 * vi[k] + (1 - b2) * src[k] * src[k];
          dst[k] -= a * mi[k] / (std::sqrt(vi[k]) + eps);
        }
        break;
      }
      default:  // Momentum variants fall back to SGD row update
        for (int64_t k = 0; k < w; ++k) dst[k] -= a * src[k];
    }
  }

  // sparse row update (reference ApplySparse/ApplyCache); bumps versions.
  // Duplicate row ids within one push are aggregated (summed) first so the
  // parallel apply touches each row exactly once — otherwise two omp
  // threads race on the same row's data/slots/version (lost updates).
  // Versions advance by occurrence count, matching the cache push path
  // (kPushEmbedding), so bounded-staleness accounting stays consistent.
  void apply_sparse(const int64_t* idx, size_t nidx, const float* g) {
    const int64_t w = width;
    const float a = lr();
    // cheap duplicate scan first: the common cache-drained push has all
    // unique ids, where we can apply straight from g with no copy
    std::unordered_map<int64_t, int64_t> occ;  // row -> occurrence count
    occ.reserve(nidx * 2);
    bool has_dup = false;
    for (size_t j = 0; j < nidx; ++j) {
      int64_t row = idx[j];
      if (row < 0 || row >= len) continue;
      if (++occ[row] > 1) has_dup = true;
    }
    if (!has_dup) {
      const int64_t n = static_cast<int64_t>(nidx);
#pragma omp parallel for
      for (int64_t j = 0; j < n; ++j) {
        int64_t row = idx[j];
        if (row < 0 || row >= len) continue;
        apply_row(row, g + j * w, a);
        if (!ver.empty()) ++ver[row];
      }
      return;
    }
    std::unordered_map<int64_t, size_t> slot;  // row -> index into uniq
    slot.reserve(occ.size() * 2);
    std::vector<int64_t> uniq_rows;
    std::vector<float> agg;  // aggregated gradients, uniq-major
    uniq_rows.reserve(occ.size());
    agg.reserve(occ.size() * w);
    for (size_t j = 0; j < nidx; ++j) {
      int64_t row = idx[j];
      if (row < 0 || row >= len) continue;
      const float* src = g + j * w;
      auto it = slot.find(row);
      if (it == slot.end()) {
        slot.emplace(row, uniq_rows.size());
        uniq_rows.push_back(row);
        agg.insert(agg.end(), src, src + w);
      } else {
        float* acc = agg.data() + it->second * w;
        for (int64_t k = 0; k < w; ++k) acc[k] += src[k];
      }
    }
    const int64_t nuniq = static_cast<int64_t>(uniq_rows.size());
#pragma omp parallel for
    for (int64_t j = 0; j < nuniq; ++j) {
      int64_t row = uniq_rows[j];
      apply_row(row, agg.data() + j * w, a);
      if (!ver.empty()) ver[row] += occ[row];
    }
  }

  void gather(const int64_t* idx, size_t nidx, float* out) const {
    const int64_t w = width;
    if (store) {
      // serial: TieredStore serializes on its own mutex anyway, and
      // read_row zero-fills out-of-range rows like the dense branch
      for (size_t j = 0; j < nidx; ++j)
        store->read_row(idx[j], out + j * w);
      return;
    }
#pragma omp parallel for
    for (size_t j = 0; j < nidx; ++j) {
      int64_t row = idx[j];
      if (row < 0 || row >= len) {
        std::memset(out + j * w, 0, w * sizeof(float));
      } else {
        std::memcpy(out + j * w, data.data() + row * w, w * sizeof(float));
      }
    }
  }
};

class Server {
 public:
  Server(int port, int nworkers) : port_(port), nworkers_(nworkers) {}

  int run() { return run_fd(-1); }

  // ``lfd >= 0``: an already-bound, already-listening socket inherited
  // from the launcher — the atomic port claim of ensure_server
  // (ps/server.py): whoever bind+listens it owns the port, so two
  // racing spawners can never both start a server. The re-listen below
  // is a harmless backlog update on that path.
  int run_fd(int lfd) {
    if (lfd < 0) {
      lfd = ::socket(AF_INET, SOCK_STREAM, 0);
      int one = 1;
      ::setsockopt(lfd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof one);
      sockaddr_in addr{};
      addr.sin_family = AF_INET;
      addr.sin_addr.s_addr = htonl(INADDR_ANY);
      addr.sin_port = htons(static_cast<uint16_t>(port_));
      if (::bind(lfd, reinterpret_cast<sockaddr*>(&addr),
                 sizeof addr) != 0) {
        std::perror("hetu-ps bind");
        return 1;
      }
    }
    if (::listen(lfd, 64) != 0) {
      std::perror("hetu-ps listen");
      return 1;
    }
    std::fprintf(stderr, "[hetu-ps] serving on :%d (%d workers)\n", port_,
                 nworkers_);
    // primary role: asynchronously forward acked mutations to the
    // shard's backup replica (ROADMAP item 2 failover)
    const char* bh = std::getenv("HETU_PS_MY_BACKUP_HOST");
    const char* bp = std::getenv("HETU_PS_MY_BACKUP_PORT");
    if (bh && bp && *bh && *bp) {
      backup_host_ = bh;
      backup_port_ = std::atoi(bp);
      const char* lag = std::getenv("HETU_PS_REPL_LAG");
      if (lag && *lag) repl_cap_ = static_cast<size_t>(std::atoi(lag));
      if (repl_cap_ < 1) repl_cap_ = 1;
      has_backup_ = true;
      repl_thread_ = std::thread(&Server::repl_loop, this);
      std::fprintf(stderr,
                   "[hetu-ps] replicating to backup %s:%d (lag %zu)\n",
                   backup_host_.c_str(), backup_port_, repl_cap_);
    }
    while (!stop_.load()) {
      int cfd = ::accept(lfd, nullptr, nullptr);
      if (cfd < 0) break;
      int nd = 1;
      ::setsockopt(cfd, IPPROTO_TCP, TCP_NODELAY, &nd, sizeof nd);
      std::thread(&Server::serve_conn, this, cfd).detach();
    }
    ::close(lfd);
    if (has_backup_) {
      {
        std::lock_guard<std::mutex> l(repl_mu_);
        repl_stop_.store(true);
      }
      repl_cv_.notify_all();
      repl_space_cv_.notify_all();
      repl_thread_.join();
    }
    return 0;
  }

 private:
  Tensor* get(int32_t id) {
    std::shared_lock<std::shared_mutex> l(store_mu_);
    auto it = store_.find(id);
    return it == store_.end() ? nullptr : it->second.get();
  }

  void serve_conn(int fd) {
    std::vector<uint8_t> payload;
    for (;;) {
      MsgHeader h;
      if (!read_full(fd, &h, sizeof h) || h.magic != 0x48505332) break;
      payload.resize(h.payload_len);
      if (h.payload_len && !read_full(fd, payload.data(), h.payload_len))
        break;
      Writer out;
      int32_t status = handle(static_cast<Op>(h.op), h.tensor_id,
                              payload, out, h.worker, h.seq);
      // forward acked mutations to the backup BEFORE acking the client
      // (blocking when the bounded queue is full): every update the
      // client saw acked is either applied on the backup already or in
      // this queue, so a client replay window >= the queue cap covers
      // all possible loss on primary death
      if (has_backup_ && status == 0 &&
          mutating_op(static_cast<Op>(h.op)))
        repl_enqueue(static_cast<Op>(h.op), h.tensor_id, h.worker,
                     h.seq, payload);
      MsgHeader rh;
      rh.op = h.op;
      rh.tensor_id = h.tensor_id;
      rh.status = status;
      rh.payload_len = out.buf.size();
      if (!write_full(fd, &rh, sizeof rh)) break;
      if (!out.buf.empty() &&
          !write_full(fd, out.buf.data(), out.buf.size()))
        break;
      if (static_cast<Op>(h.op) == Op::kShutdown) {
        stop_.store(true);
        // poke the accept loop
        int s = ::socket(AF_INET, SOCK_STREAM, 0);
        sockaddr_in a{};
        a.sin_family = AF_INET;
        a.sin_addr.s_addr = htonl(INADDR_LOOPBACK);
        a.sin_port = htons(static_cast<uint16_t>(port_));
        ::connect(s, reinterpret_cast<sockaddr*>(&a), sizeof a);
        ::close(s);
        break;
      }
    }
    ::close(fd);
  }

  // ------------------------------------------------------------------
  // primary -> backup replication (ROADMAP item 2): the ops whose
  // acked effect must survive a primary SIGKILL
  // ------------------------------------------------------------------
  // ==-chain, not a switch: analysis/wire.py treats `case Op::kX:`
  // labels as handler cases, and this helper is not one
  static bool mutating_op(Op op) {
    return op == Op::kInitTensor || op == Op::kDensePush ||
           op == Op::kDDPushPull || op == Op::kSparsePush ||
           op == Op::kSDPushPull || op == Op::kSSPushPull ||
           op == Op::kPushEmbedding || op == Op::kPushSyncEmbedding ||
           op == Op::kParamSet || op == Op::kParamClear ||
           op == Op::kParamLoad || op == Op::kPushData ||
           op == Op::kStoreConfig;
  }

  struct ReplItem {
    uint32_t op;
    int32_t id;
    uint32_t worker;
    uint64_t seq;
    std::vector<uint8_t> payload;
  };

  void repl_enqueue(Op op, int32_t id, uint32_t worker, uint64_t seq,
                    const std::vector<uint8_t>& payload) {
    std::unique_lock<std::mutex> l(repl_mu_);
    // blocking when full IS the bounded replication-lag window
    repl_space_cv_.wait(l, [&] {
      return repl_q_.size() < repl_cap_ || repl_stop_.load();
    });
    if (repl_stop_.load()) return;
    repl_q_.push_back(
        {static_cast<uint32_t>(op), id, worker, seq, payload});
    repl_cv_.notify_one();
  }

  int repl_dial() {
    int fd = ::socket(AF_INET, SOCK_STREAM, 0);
    sockaddr_in a{};
    a.sin_family = AF_INET;
    a.sin_port = htons(static_cast<uint16_t>(backup_port_));
    if (::inet_pton(AF_INET, backup_host_.c_str(), &a.sin_addr) != 1)
      a.sin_addr.s_addr = htonl(INADDR_LOOPBACK);
    if (::connect(fd, reinterpret_cast<sockaddr*>(&a), sizeof a) != 0) {
      ::close(fd);
      return -1;
    }
    int nd = 1;
    ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &nd, sizeof nd);
    return fd;
  }

  // relay one acked mutation: header carries the ORIGINAL (worker,
  // seq) identity so the backup's dedup covers client replays
  bool repl_send(int fd, const ReplItem& it) {
    MsgHeader h;
    h.op = static_cast<uint32_t>(Op::kReplForward);
    h.tensor_id = it.id;
    h.worker = it.worker;
    h.seq = it.seq;
    Writer w;
    w.u32(it.op);
    w.raw(it.payload.data(), it.payload.size());
    h.payload_len = w.buf.size();
    if (!write_full(fd, &h, sizeof h)) return false;
    if (!w.buf.empty() && !write_full(fd, w.buf.data(), w.buf.size()))
      return false;
    MsgHeader rh;
    if (!read_full(fd, &rh, sizeof rh) || rh.magic != 0x48505332)
      return false;
    std::vector<uint8_t> resp(rh.payload_len);
    if (rh.payload_len && !read_full(fd, resp.data(), rh.payload_len))
      return false;
    return true;
  }

  void repl_loop() {
    int fd = -1;
    for (;;) {
      ReplItem it;
      {
        std::unique_lock<std::mutex> l(repl_mu_);
        repl_cv_.wait(l, [&] {
          return !repl_q_.empty() || repl_stop_.load();
        });
        if (repl_q_.empty()) break;  // stopped and drained
        it = std::move(repl_q_.front());
        repl_q_.pop_front();
        repl_space_cv_.notify_one();
      }
      bool sent = false;
      for (int tries = 0; tries < 50 && !sent; ++tries) {
        if (fd < 0) fd = repl_dial();
        if (fd >= 0 && repl_send(fd, it)) {
          sent = true;
        } else {
          if (fd >= 0) ::close(fd);
          fd = -1;
          if (repl_stop_.load()) break;
          struct timespec ts {0, 100 * 1000 * 1000};
          ::nanosleep(&ts, nullptr);
        }
      }
      if (!sent && !repl_warned_) {
        repl_warned_ = true;
        std::fprintf(stderr,
                     "[hetu-ps] backup %s:%d unreachable; replication "
                     "degraded (client replay still covers failover)\n",
                     backup_host_.c_str(), backup_port_);
      }
    }
    if (fd >= 0) ::close(fd);
  }

  // at-most-once retry protection (reference ps-lite resender.h): a
  // client retries a request whose connection died after the server may
  // already have applied it; mutating ops are deduped on (worker, seq)
  // so the retry serves only the read part. Returns true if duplicate.
  bool check_and_record(uint32_t worker, uint64_t seq) {
    std::lock_guard<std::mutex> l(dedup_mu_);
    auto& d = dedup_[worker];
    if (d.seen.count(seq)) return true;
    d.seen.insert(seq);
    d.order.push_back(seq);
    if (d.order.size() > 65536) {
      d.seen.erase(d.order.front());
      d.order.pop_front();
    }
    return false;
  }

  int32_t handle(Op op, int32_t id, const std::vector<uint8_t>& payload,
                 Writer& out, uint32_t worker, uint64_t seq) {
    Reader rd(payload.data(), payload.size());
    switch (op) {
      case Op::kInitTensor: {
        auto t = std::make_unique<Tensor>();
        t->kind = static_cast<ParamKind>(rd.i32());
        t->len = rd.i64();
        t->width = rd.i64();
        InitKind ik = static_cast<InitKind>(rd.i32());
        double a = rd.f64(), b = rd.f64();
        uint64_t seed = rd.u64();
        t->opt = static_cast<OptKind>(rd.i32());
        size_t nlr;
        const float* lrp = rd.floats(&nlr);
        t->lrs.assign(lrp, lrp + nlr);
        t->data.resize(t->nelem());
        // on-server init (reference PSFHandle.h:277-342)
        std::mt19937_64 gen(seed ? seed : 0x9e3779b9);
        switch (ik) {
          case InitKind::kConstant:
            std::fill(t->data.begin(), t->data.end(),
                      static_cast<float>(a));
            break;
          case InitKind::kUniform: {
            std::uniform_real_distribution<float> d(
                static_cast<float>(a), static_cast<float>(b));
            for (auto& x : t->data) x = d(gen);
            break;
          }
          case InitKind::kNormal: {
            std::normal_distribution<float> d(static_cast<float>(a),
                                              static_cast<float>(b));
            for (auto& x : t->data) x = d(gen);
            break;
          }
          case InitKind::kTruncatedNormal: {
            std::normal_distribution<float> d(static_cast<float>(a),
                                              static_cast<float>(b));
            for (auto& x : t->data) {
              do {
                x = d(gen);
              } while (std::fabs(x - a) > 2 * b);
            }
            break;
          }
        }
        t->init_slots();
        if (t->kind == ParamKind::kCacheTable) t->ver.assign(t->len, 0);
        {
          std::unique_lock<std::shared_mutex> l(store_mu_);
          // idempotent across workers: first init wins (reference
          // PSFHandle ParamInit re-registration is a no-op)
          if (!store_.count(id)) store_[id] = std::move(t);
        }
        return 0;
      }
      case Op::kDensePull: {
        Tensor* t = get(id);
        if (!t) return -1;
        std::shared_lock<std::shared_mutex> l(t->mu);
        std::vector<float> snap;
        out.floats(t->dense_view(snap), t->nelem());
        return 0;
      }
      case Op::kDensePush:
      case Op::kDDPushPull: {
        Tensor* t = get(id);
        if (!t) return -1;
        size_t n;
        const float* g = rd.floats(&n);
        bool dup = check_and_record(worker, seq);
        std::unique_lock<std::shared_mutex> l(t->mu);
        if (!dup && static_cast<int64_t>(n) == t->nelem())
          t->apply_dense(g);
        if (op == Op::kDDPushPull) {
          std::vector<float> snap;
          out.floats(t->dense_view(snap), t->nelem());
        }
        bytes_in_ += n * 4;
        return 0;
      }
      case Op::kSparsePull: {
        Tensor* t = get(id);
        if (!t) return -1;
        size_t nidx;
        const int64_t* idx = rd.longs(&nidx);
        std::shared_lock<std::shared_mutex> l(t->mu);
        out.i64(static_cast<int64_t>(nidx * t->width));
        size_t off = out.buf.size();
        out.buf.resize(off + nidx * t->width * sizeof(float));
        t->gather(idx, nidx,
                  reinterpret_cast<float*>(out.buf.data() + off));
        return 0;
      }
      case Op::kSparsePush: {
        Tensor* t = get(id);
        if (!t) return -1;
        size_t nidx, nval;
        const int64_t* idx = rd.longs(&nidx);
        const float* g = rd.floats(&nval);
        bool dup = check_and_record(worker, seq);
        std::unique_lock<std::shared_mutex> l(t->mu);
        if (!dup) t->apply_sparse(idx, nidx, g);
        bytes_in_ += nval * 4;
        return 0;
      }
      case Op::kSDPushPull: {
        // push sparse grad rows, pull the full dense tensor
        Tensor* t = get(id);
        if (!t) return -1;
        size_t nidx, nval;
        const int64_t* idx = rd.longs(&nidx);
        const float* g = rd.floats(&nval);
        bool dup = check_and_record(worker, seq);
        std::unique_lock<std::shared_mutex> l(t->mu);
        if (!dup) t->apply_sparse(idx, nidx, g);
        std::vector<float> snap;
        out.floats(t->dense_view(snap), t->nelem());
        return 0;
      }
      case Op::kSSPushPull: {
        // push grad rows at in-indices, pull rows at out-indices (the
        // prefetch pipeline: pull next batch's rows — reference
        // SSPushPull, PSFHandle.h:217-268)
        Tensor* t = get(id);
        if (!t) return -1;
        size_t nin, nval, nout;
        const int64_t* in_idx = rd.longs(&nin);
        const float* g = rd.floats(&nval);
        const int64_t* out_idx = rd.longs(&nout);
        bool dup = check_and_record(worker, seq);
        std::unique_lock<std::shared_mutex> l(t->mu);
        if (!dup) t->apply_sparse(in_idx, nin, g);
        out.i64(static_cast<int64_t>(nout * t->width));
        size_t off = out.buf.size();
        out.buf.resize(off + nout * t->width * sizeof(float));
        t->gather(out_idx, nout,
                  reinterpret_cast<float*>(out.buf.data() + off));
        return 0;
      }
      case Op::kSyncEmbedding: {
        // bounded staleness: return only rows whose server version
        // exceeds the client's by more than `bound`
        // (reference hetu_client.cc:6-38 / PSFhandle_embedding.cc)
        Tensor* t = get(id);
        if (!t || t->ver.empty()) return -1;
        int64_t bound = rd.i64();
        size_t nidx, nver;
        const int64_t* idx = rd.longs(&nidx);
        const int64_t* cver = rd.longs(&nver);
        std::shared_lock<std::shared_mutex> l(t->mu);
        std::vector<int64_t> stale_pos, stale_ver;
        std::vector<float> rows;
        std::vector<float> scratch(t->width);
        for (size_t j = 0; j < nidx; ++j) {
          int64_t row = idx[j];
          if (row < 0 || row >= t->len) continue;
          if (t->ver[row] - cver[j] > bound) {
            stale_pos.push_back(static_cast<int64_t>(j));
            stale_ver.push_back(t->ver[row]);
            size_t o = rows.size();
            rows.resize(o + t->width);
            std::memcpy(rows.data() + o, t->row_src(row, scratch.data()),
                        t->width * sizeof(float));
          }
        }
        out.longs(stale_pos.data(), stale_pos.size());
        out.longs(stale_ver.data(), stale_ver.size());
        out.floats(rows.data(), rows.size());
        return 0;
      }
      case Op::kPushEmbedding: {
        Tensor* t = get(id);
        if (!t) return -1;
        size_t nidx, nval, nupd;
        const int64_t* idx = rd.longs(&nidx);
        const float* g = rd.floats(&nval);
        const int64_t* upd = rd.longs(&nupd);  // per-row update counts
        bool dup = check_and_record(worker, seq);
        if (dup) return 0;
        std::unique_lock<std::shared_mutex> l(t->mu);
        t->apply_sparse(idx, nidx, g);
        if (!t->ver.empty())
          for (size_t j = 0; j < nupd && j < nidx; ++j)
            if (idx[j] >= 0 && idx[j] < t->len)
              t->ver[idx[j]] += upd[j] - 1;  // apply_sparse added 1
        return 0;
      }
      case Op::kPushSyncEmbedding: {
        Tensor* t = get(id);
        if (!t || t->ver.empty()) return -1;
        int64_t bound = rd.i64();
        size_t npidx, nval, nupd, nsidx, nsver;
        const int64_t* pidx = rd.longs(&npidx);
        const float* g = rd.floats(&nval);
        const int64_t* upd = rd.longs(&nupd);
        const int64_t* sidx = rd.longs(&nsidx);
        const int64_t* sver = rd.longs(&nsver);
        bool dup = check_and_record(worker, seq);
        std::unique_lock<std::shared_mutex> l(t->mu);
        if (!dup) {
          t->apply_sparse(pidx, npidx, g);
          for (size_t j = 0; j < nupd && j < npidx; ++j)
            if (pidx[j] >= 0 && pidx[j] < t->len)
              t->ver[pidx[j]] += upd[j] - 1;
        }
        std::vector<int64_t> stale_pos, stale_ver;
        std::vector<float> rows;
        std::vector<float> scratch(t->width);
        for (size_t j = 0; j < nsidx; ++j) {
          int64_t row = sidx[j];
          if (row < 0 || row >= t->len) continue;
          if (t->ver[row] - sver[j] > bound) {
            stale_pos.push_back(static_cast<int64_t>(j));
            stale_ver.push_back(t->ver[row]);
            size_t o = rows.size();
            rows.resize(o + t->width);
            std::memcpy(rows.data() + o, t->row_src(row, scratch.data()),
                        t->width * sizeof(float));
          }
        }
        out.longs(stale_pos.data(), stale_pos.size());
        out.longs(stale_ver.data(), stale_ver.size());
        out.floats(rows.data(), rows.size());
        return 0;
      }
      case Op::kParamSet: {
        Tensor* t = get(id);
        if (!t) return -1;
        size_t n;
        const float* p = rd.floats(&n);
        // overwrites need the dedup too: a post-failover REPLAY of an
        // old overwrite arriving after forwarded accumulating updates
        // would rewind the surviving replica (retries alone would not
        // care — re-overwriting is idempotent)
        bool dup = check_and_record(worker, seq);
        if (dup) return 0;
        std::unique_lock<std::shared_mutex> l(t->mu);
        if (static_cast<int64_t>(n) != t->nelem()) return -3;
        if (t->store) {
          for (int64_t r = 0; r < t->len; ++r)
            t->store->write_row(r, p + r * t->width);
        } else {
          std::memcpy(t->data.data(), p, n * sizeof(float));
        }
        return 0;
      }
      case Op::kParamClear: {
        Tensor* t = get(id);
        if (!t) return -1;
        bool dup = check_and_record(worker, seq);
        if (dup) return 0;
        std::unique_lock<std::shared_mutex> l(t->mu);
        if (t->store) {
          std::vector<float> z(t->width, 0.f);
          for (int64_t r = 0; r < t->len; ++r)
            t->store->write_row(r, z.data());
        } else {
          std::fill(t->data.begin(), t->data.end(), 0.f);
        }
        return 0;
      }
      case Op::kParamSave: {
        Tensor* t = get(id);
        if (!t) return -1;
        std::string path = rd.str();
        std::shared_lock<std::shared_mutex> l(t->mu);
        FILE* f = std::fopen(path.c_str(), "wb");
        if (!f) return -2;
        std::fwrite(&t->len, sizeof t->len, 1, f);
        std::fwrite(&t->width, sizeof t->width, 1, f);
        std::vector<float> snap;
        std::fwrite(t->dense_view(snap), sizeof(float),
                    static_cast<size_t>(t->nelem()), f);
        std::fclose(f);
        return 0;
      }
      case Op::kParamLoad: {
        Tensor* t = get(id);
        if (!t) return -1;
        std::string path = rd.str();
        bool dup = check_and_record(worker, seq);
        if (dup) return 0;
        std::unique_lock<std::shared_mutex> l(t->mu);
        FILE* f = std::fopen(path.c_str(), "rb");
        if (!f) return -2;
        int64_t len, width;
        if (std::fread(&len, sizeof len, 1, f) != 1 ||
            std::fread(&width, sizeof width, 1, f) != 1 ||
            len != t->len || width != t->width) {
          std::fclose(f);
          return -3;
        }
        if (t->store) {
          std::vector<float> tmp(t->nelem());
          size_t got = std::fread(tmp.data(), sizeof(float), tmp.size(),
                                  f);
          std::fclose(f);
          if (got != tmp.size()) return -3;
          for (int64_t r = 0; r < t->len; ++r)
            t->store->write_row(r, tmp.data() + r * t->width);
          return 0;
        }
        size_t got = std::fread(t->data.data(), sizeof(float),
                                t->data.size(), f);
        std::fclose(f);
        return got == t->data.size() ? 0 : -3;
      }
      case Op::kBarrier: {
        std::unique_lock<std::mutex> l(bar_mu_);
        // a retried barrier (first registration's response was lost)
        // must not count the worker twice: wait out the generation the
        // original registration joined, then succeed
        bool is_dup = false;
        int reg_gen = 0;
        {
          std::lock_guard<std::mutex> dl(dedup_mu_);
          auto& d = dedup_[worker];
          auto it = d.bar_gen.find(seq);
          if (it != d.bar_gen.end()) {
            is_dup = true;
            reg_gen = it->second;
          } else {
            d.bar_gen[seq] = bar_gen_;
            d.bar_order.push_back(seq);
            if (d.bar_order.size() > 1024) {
              // evict the OLDEST registration — live retries target
              // recent barriers, so insertion-order pruning never
              // drops an in-flight retry's dedup entry
              d.bar_gen.erase(d.bar_order.front());
              d.bar_order.pop_front();
            }
          }
        }
        if (is_dup) {
          bar_cv_.wait(l, [&] { return bar_gen_ != reg_gen; });
          return 0;
        }
        int gen = bar_gen_;
        if (++bar_count_ >= nworkers_) {
          bar_count_ = 0;
          ++bar_gen_;
          bar_cv_.notify_all();
        } else {
          bar_cv_.wait(l, [&] { return bar_gen_ != gen; });
        }
        return 0;
      }
      case Op::kPushData: {
        int64_t key = rd.i64();
        size_t n;
        const float* p = rd.floats(&n);
        bool dup = check_and_record(worker, seq);
        if (dup) return 0;
        std::unique_lock<std::shared_mutex> l(blob_mu_);
        blobs_[key].assign(p, p + n);
        return 0;
      }
      case Op::kPullData: {
        int64_t key = rd.i64();
        std::shared_lock<std::shared_mutex> l(blob_mu_);
        auto it = blobs_.find(key);
        if (it == blobs_.end()) return -1;
        out.floats(it->second.data(), it->second.size());
        return 0;
      }
      case Op::kGetLoads: {
        out.u64(bytes_in_.load());
        return 0;
      }
      case Op::kReplForward: {
        // relay from a primary: re-dispatch the wrapped op under its
        // ORIGINAL (worker, seq) identity, so this replica's dedup
        // covers the client's post-failover replay window exactly once
        if (payload.size() < sizeof(uint32_t)) return -3;
        uint32_t orig = rd.u32();
        std::vector<uint8_t> inner(payload.begin() + sizeof(uint32_t),
                                   payload.end());
        return handle(static_cast<Op>(orig), id, inner, out, worker,
                      seq);
      }
      case Op::kStoreConfig: {
        // convert an existing table to tiered/quantized row storage:
        // the spill file name folds in this server's port so primary
        // and backup replicas on one host never share a file
        Tensor* t = get(id);
        if (!t) return -1;
        int32_t dt = rd.i32();
        int64_t dram_rows = rd.i64();
        std::string dir = rd.str();
        size_t nhot;
        const int64_t* hot = rd.longs(&nhot);
        bool dup = check_and_record(worker, seq);
        std::unique_lock<std::shared_mutex> l(t->mu);
        if (dup) return 0;
        if (t->store) {
          // already tiered: re-pin only — reading promotes, so the
          // freshest measured-hot set ends resident in DRAM
          std::vector<float> tmp(t->width);
          for (size_t j = 0; j < nhot; ++j)
            if (hot[j] >= 0 && hot[j] < t->len)
              t->store->read_row(hot[j], tmp.data());
          return 0;
        }
        if (t->opt != OptKind::kSGD && t->opt != OptKind::kNone)
          return -4;  // slot-carrying optimizers are not tiered
        std::string path = dir + "/ps_spill_" + std::to_string(id) +
                           "_" + std::to_string(port_) + ".bin";
        auto st = std::make_unique<TieredStore>(
            t->len, t->width, static_cast<StoreDtype>(dt), dram_rows,
            path);
        if (!st->ok()) return -2;
        // migrate: cold rows stream through (and out of) the pool;
        // measured-hot ids (PR 9 skew telemetry) re-read LAST so they
        // end resident in DRAM
        for (int64_t r = 0; r < t->len; ++r)
          st->write_row(r, t->data.data() + r * t->width);
        std::vector<float> tmp(t->width);
        for (size_t j = 0; j < nhot; ++j)
          if (hot[j] >= 0 && hot[j] < t->len)
            st->read_row(hot[j], tmp.data());
        t->store = std::move(st);
        t->data.clear();
        t->data.shrink_to_fit();
        return 0;
      }
      case Op::kStoreStats: {
        Tensor* t = get(id);
        if (!t) return -1;
        // replication backlog sampled BEFORE taking t->mu: the repl
        // worker's repl_mu_ sections never take a tensor lock, and
        // keeping the two disjoint here preserves that (no new lock
        // order is introduced by this read-only stat)
        size_t repl_depth = 0;
        {
          std::lock_guard<std::mutex> rl(repl_mu_);
          repl_depth = repl_q_.size();
        }
        std::shared_lock<std::shared_mutex> l(t->mu);
        TieredStore::Stats s;
        if (t->store)
          s = t->store->stats();
        else
          s.row_bytes = t->width * 4;
        out.u64(s.dram_hits);
        out.u64(s.spill_hits);
        out.u64(s.spill_writes);
        out.i64(s.dram_rows);
        out.i64(s.row_bytes);
        out.i64(static_cast<int64_t>(repl_depth));
        return 0;
      }
      case Op::kShutdown:
        return 0;
    }
    return -100;
  }

  int port_;
  int nworkers_;
  std::atomic<bool> stop_{false};
  // replication state (primary role only)
  bool has_backup_ = false;
  bool repl_warned_ = false;
  std::string backup_host_;
  int backup_port_ = 0;
  std::deque<ReplItem> repl_q_;
  size_t repl_cap_ = 128;
  std::mutex repl_mu_;
  std::condition_variable repl_cv_, repl_space_cv_;
  std::atomic<bool> repl_stop_{false};
  std::thread repl_thread_;
  std::unordered_map<int32_t, std::unique_ptr<Tensor>> store_;
  std::shared_mutex store_mu_;
  std::unordered_map<int64_t, std::vector<float>> blobs_;
  std::shared_mutex blob_mu_;
  std::mutex bar_mu_;
  std::condition_variable bar_cv_;
  int bar_count_ = 0;
  int bar_gen_ = 0;
  // per-worker (seq) dedup for at-most-once mutating ops
  struct WorkerDedup {
    std::unordered_set<uint64_t> seen;
    std::deque<uint64_t> order;
    std::unordered_map<uint64_t, int> bar_gen;  // barrier seq -> gen
    std::deque<uint64_t> bar_order;             // insertion order
  };
  std::mutex dedup_mu_;
  std::unordered_map<uint32_t, WorkerDedup> dedup_;
  std::atomic<uint64_t> bytes_in_{0};
};

}  // namespace hetups

extern "C" int hetu_ps_run_server(int port, int nworkers) {
  hetups::Server s(port, nworkers);
  return s.run();
}

// launcher-claimed-socket form: serve on an inherited bound fd (the
// ensure_server startup-race fix); ``port`` is still needed for the
// shutdown self-connect poke.
extern "C" int hetu_ps_run_server_fd(int lfd, int port, int nworkers) {
  hetups::Server s(port, nworkers);
  return s.run_fd(lfd);
}
