// Client-side embedding cache with bounded staleness.
//
// TPU-native counterpart of the reference's hetu_cache
// (src/hetu_cache/include/{cache.h,embedding.h}, src/hetu_client.cc):
// cached rows carry (version, pending-update count, grad accumulator);
// lookups sync stale rows against the server under a pull bound; updates
// accumulate locally and push with their update counts so the server's
// row version advances by the number of folded gradients — the version
// algebra that gives bounded-staleness consistency across workers.
// Policies: LRU / LFU / LFUOpt (reference cache.h policy subclasses).
#include <cstdint>
#include <cstring>
#include <limits>
#include <list>
#include <map>
#include <memory>
#include <mutex>
#include <unordered_map>
#include <vector>

// RPC helpers from ps_client.cc (same shared object)
extern "C" {
int SyncEmbedding(int id, int64_t bound, const int64_t* idx, int64_t* ver,
                  int64_t nidx, float* out, int64_t width);
void PushEmbedding(int id, const int64_t* idx, const float* vals,
                   const int64_t* updates, int64_t nidx, int64_t width);
int SparsePull(int id, const int64_t* idx, float* out, int64_t nidx,
               int64_t width);
void Wait(int id);
}

namespace hetucache {

constexpr int64_t kNeverSynced = std::numeric_limits<int64_t>::min() / 2;

struct Line {
  int64_t key;
  int64_t version = kNeverSynced;  // forces first sync to pull
  int64_t updates = 0;
  std::vector<float> data;
  std::vector<float> grad;
  // policy bookkeeping
  uint64_t freq = 0;
  std::list<int64_t>::iterator pos;   // LRU order / LFU bucket position
  uint64_t bucket = 0;                // LFU frequency bucket
};

enum Policy { kLRU = 0, kLFU = 1, kLFUOpt = 2 };

class EmbedCache {
 public:
  EmbedCache(int tid, int64_t limit, int64_t width, int policy,
             int64_t pull_bound, int64_t push_bound)
      : tid_(tid), limit_(limit), width_(width), policy_(policy),
        pull_bound_(pull_bound), push_bound_(push_bound) {}

  // Batched lookup: hits sync under the pull bound, misses pull
  // unconditionally (version = -inf), victims flush their gradients.
  void lookup(const int64_t* keys, int64_t n, float* out) {
    std::lock_guard<std::mutex> l(mu_);
    // Distinct lines for this batch hold shared ownership, so a line
    // evicted while later keys insert stays valid for this batch (the
    // reference keeps EmbeddingPT shared_ptrs for the same reason,
    // cache.h batchedLookup).
    std::unordered_map<int64_t, std::shared_ptr<Line>> batch;
    for (int64_t i = 0; i < n; ++i) {
      if (batch.count(keys[i])) { ++hits_; continue; }
      auto it = map_.find(keys[i]);
      if (it != map_.end()) {
        ++hits_;
        touch(it->second.get());
        batch[keys[i]] = it->second;
      } else {
        ++misses_;
        batch[keys[i]] = insert_line(keys[i]);
      }
    }
    sync_lines(batch);
    for (int64_t i = 0; i < n; ++i)
      std::memcpy(out + i * width_, batch.at(keys[i])->data.data(),
                  width_ * sizeof(float));
  }

  // Accumulate gradients locally; rows past the push bound flush.
  void update(const int64_t* keys, const float* grads, int64_t n) {
    std::lock_guard<std::mutex> l(mu_);
    std::unordered_map<int64_t, std::shared_ptr<Line>> due;
    std::unordered_map<int64_t, std::shared_ptr<Line>> batch;
    for (int64_t i = 0; i < n; ++i) {
      std::shared_ptr<Line> ln;
      auto bit = batch.find(keys[i]);
      if (bit != batch.end()) {
        ln = bit->second;
      } else {
        auto it = map_.find(keys[i]);
        ln = (it == map_.end()) ? insert_line(keys[i]) : it->second;
        batch[keys[i]] = ln;
      }
      if (ln->grad.empty()) ln->grad.assign(width_, 0.f);
      const float* g = grads + i * width_;
      for (int64_t k = 0; k < width_; ++k) ln->grad[k] += g[k];
      ++ln->updates;
      if (ln->updates >= push_bound_) due[ln->key] = ln;
    }
    flush_lines_shared(due);
  }

  void flush() {
    std::lock_guard<std::mutex> l(mu_);
    std::unordered_map<int64_t, std::shared_ptr<Line>> due;
    for (auto& kv : map_)
      if (kv.second->updates > 0) due[kv.first] = kv.second;
    flush_lines_shared(due);
    Wait(tid_);
  }

  uint64_t perf(int what) const {
    switch (what) {
      case 0: return hits_;
      case 1: return misses_;
      case 2: return evicts_;
      case 3: return map_.size();
      case 4: return pushed_rows_;
      case 5: return pulled_rows_;
    }
    return 0;
  }

 private:
  void touch(Line* ln) {
    ++tick_;
    if (policy_ == kLRU) {
      lru_.splice(lru_.begin(), lru_, ln->pos);
    } else {
      // move to next frequency bucket
      lfu_[ln->bucket].erase(ln->pos);
      if (lfu_[ln->bucket].empty()) lfu_.erase(ln->bucket);
      ++ln->freq;
      ln->bucket = ln->freq;
      lfu_[ln->bucket].push_front(ln->key);
      ln->pos = lfu_[ln->bucket].begin();
    }
  }

  std::shared_ptr<Line> insert_line(int64_t key) {
    auto found = map_.find(key);
    if (found != map_.end()) return found->second;
    while (static_cast<int64_t>(map_.size()) >= limit_) evict_one();
    auto ln = std::make_shared<Line>();
    ln->key = key;
    ln->data.assign(width_, 0.f);
    if (policy_ == kLRU) {
      lru_.push_front(key);
      ln->pos = lru_.begin();
    } else {
      // LFU starts new lines at frequency 1; LFUOpt starts them at the
      // current minimum bucket so one-shot keys can't flush the working
      // set (the reference's LFUOpt refinement)
      uint64_t b = 1;
      if (policy_ == kLFUOpt && !lfu_.empty())
        b = lfu_.begin()->first;
      ln->freq = b;
      ln->bucket = b;
      lfu_[b].push_front(key);
      ln->pos = lfu_[b].begin();
    }
    map_[key] = ln;
    return ln;
  }

  void evict_one() {
    int64_t victim;
    if (policy_ == kLRU) {
      victim = lru_.back();
    } else {
      victim = lfu_.begin()->second.back();
    }
    std::shared_ptr<Line> ln = map_.at(victim);
    if (ln->updates > 0) {
      std::unordered_map<int64_t, std::shared_ptr<Line>> due{{victim, ln}};
      flush_lines_shared(due);
    }
    if (policy_ == kLRU) {
      lru_.pop_back();
    } else {
      lfu_.begin()->second.pop_back();
      if (lfu_.begin()->second.empty()) lfu_.erase(lfu_.begin());
    }
    map_.erase(victim);
    ++evicts_;
  }

  void sync_lines(std::unordered_map<int64_t, std::shared_ptr<Line>>& lines) {
    if (lines.empty()) return;
    std::vector<int64_t> keys, vers;
    std::vector<Line*> order;
    keys.reserve(lines.size());
    for (auto& kv : lines) {
      keys.push_back(kv.first);
      vers.push_back(kv.second->version);
      order.push_back(kv.second.get());
    }
    std::vector<float> rows(keys.size() * width_);
    // one RPC: rows whose server version exceeds ours by > pull_bound
    // come back refreshed (reference syncEmbedding, hetu_client.cc:6-38)
    int refreshed = SyncEmbedding(tid_, pull_bound_, keys.data(),
                                  vers.data(), keys.size(), rows.data(),
                                  width_);
    if (refreshed > 0) {
      for (size_t j = 0; j < order.size(); ++j) {
        if (vers[j] != order[j]->version) {
          order[j]->version = vers[j];
          std::memcpy(order[j]->data.data(), rows.data() + j * width_,
                      width_ * sizeof(float));
          ++pulled_rows_;
        }
      }
    }
  }

  void flush_lines_shared(
      std::unordered_map<int64_t, std::shared_ptr<Line>>& due) {
    if (due.empty()) return;
    std::vector<int64_t> keys, updates;
    std::vector<float> grads;
    for (auto& kv : due) {
      Line* ln = kv.second.get();
      keys.push_back(ln->key);
      updates.push_back(ln->updates);
      grads.insert(grads.end(), ln->grad.begin(), ln->grad.end());
      ln->updates = 0;
      std::fill(ln->grad.begin(), ln->grad.end(), 0.f);
    }
    PushEmbedding(tid_, keys.data(), grads.data(), updates.data(),
                  keys.size(), width_);
    pushed_rows_ += keys.size();
  }

  int tid_;
  int64_t limit_, width_;
  int policy_;
  int64_t pull_bound_, push_bound_;
  std::unordered_map<int64_t, std::shared_ptr<Line>> map_;
  std::list<int64_t> lru_;
  std::map<uint64_t, std::list<int64_t>> lfu_;
  uint64_t tick_ = 0;
  uint64_t hits_ = 0, misses_ = 0, evicts_ = 0;
  uint64_t pushed_rows_ = 0, pulled_rows_ = 0;
  std::mutex mu_;
};

static std::mutex g_mu;
static std::unordered_map<int, std::unique_ptr<EmbedCache>> g_caches;
static int g_next = 1;

}  // namespace hetucache

extern "C" {

int CacheCreate(int tid, int64_t limit, int64_t width, int policy,
                int64_t pull_bound, int64_t push_bound) {
  std::lock_guard<std::mutex> l(hetucache::g_mu);
  int h = hetucache::g_next++;
  hetucache::g_caches[h] = std::make_unique<hetucache::EmbedCache>(
      tid, limit, width, policy, pull_bound, push_bound);
  return h;
}

void CacheDestroy(int h) {
  std::lock_guard<std::mutex> l(hetucache::g_mu);
  hetucache::g_caches.erase(h);
}

void CacheLookup(int h, const int64_t* keys, int64_t n, float* out) {
  hetucache::g_caches.at(h)->lookup(keys, n, out);
}

void CacheUpdate(int h, const int64_t* keys, const float* grads,
                 int64_t n) {
  hetucache::g_caches.at(h)->update(keys, grads, n);
}

void CacheFlush(int h) { hetucache::g_caches.at(h)->flush(); }

uint64_t CachePerf(int h, int what) {
  return hetucache::g_caches.at(h)->perf(what);
}

}  // extern "C"
