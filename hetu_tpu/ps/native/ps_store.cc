// TieredStore: DRAM slot pool over an mmap'd sparse spill file with
// fp16/int8 row quantization. See ps_store.h for the contract. This
// translation unit carries no wire ops — the protocol surface stays in
// ps_server.cc / ps_client.cc where analysis/wire.py parses it.
#include "ps_store.h"

#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

#include <cmath>
#include <cstring>

namespace hetups {

// IEEE 754 binary16 conversion (round-to-nearest-even via the float
// intermediate; no <stdfloat> dependency)
static inline uint16_t f32_to_f16(float f) {
  uint32_t x;
  std::memcpy(&x, &f, 4);
  uint32_t sign = (x >> 16) & 0x8000u;
  uint32_t mant = x & 0x007fffffu;
  int32_t exp = static_cast<int32_t>((x >> 23) & 0xffu) - 127 + 15;
  if (exp >= 31) return static_cast<uint16_t>(sign | 0x7c00u);  // inf
  if (exp <= 0) {
    if (exp < -10) return static_cast<uint16_t>(sign);          // -> 0
    mant |= 0x00800000u;                                        // hidden 1
    uint32_t shift = static_cast<uint32_t>(14 - exp);
    uint32_t half = (mant >> shift)
        + ((mant >> (shift - 1)) & 1u);                         // round
    return static_cast<uint16_t>(sign | half);
  }
  uint32_t half = (static_cast<uint32_t>(exp) << 10) | (mant >> 13);
  half += (mant >> 12) & 1u;                                    // round
  return static_cast<uint16_t>(sign | half);
}

static inline float f16_to_f32(uint16_t h) {
  uint32_t sign = static_cast<uint32_t>(h & 0x8000u) << 16;
  uint32_t exp = (h >> 10) & 0x1fu;
  uint32_t mant = h & 0x3ffu;
  uint32_t x;
  if (exp == 0) {
    if (mant == 0) {
      x = sign;
    } else {                        // subnormal: normalize
      int e = -1;
      do {
        ++e;
        mant <<= 1;
      } while (!(mant & 0x400u));
      x = sign | ((112u - static_cast<uint32_t>(e)) << 23)
          | ((mant & 0x3ffu) << 13);
    }
  } else if (exp == 31) {
    x = sign | 0x7f800000u | (mant << 13);
  } else {
    x = sign | ((exp + 112u) << 23) | (mant << 13);
  }
  float f;
  std::memcpy(&f, &x, 4);
  return f;
}

int64_t TieredStore::elem_bytes() const {
  switch (dtype_) {
    case StoreDtype::kF16: return 2;
    case StoreDtype::kI8: return 1;
    default: return 4;
  }
}

TieredStore::TieredStore(int64_t rows, int64_t width, StoreDtype dtype,
                         int64_t dram_rows, const std::string& spill_path)
    : rows_(rows), width_(width), dtype_(dtype), path_(spill_path) {
  // uniform layout across dtypes: per-row f32 scale first (unused for
  // f32/f16 but keeps offsets dtype-independent), then quantized lanes
  stride_ = 4 + width_ * elem_bytes();
  dram_cap_ = dram_rows < 0 ? rows_ : dram_rows;
  if (dram_cap_ > rows_) dram_cap_ = rows_;
  fd_ = ::open(path_.c_str(), O_RDWR | O_CREAT, 0644);
  if (fd_ < 0) return;
  map_len_ = static_cast<size_t>(rows_) * static_cast<size_t>(stride_);
  if (::ftruncate(fd_, static_cast<off_t>(map_len_)) != 0) {
    ::close(fd_);
    fd_ = -1;
    return;
  }
  void* m = ::mmap(nullptr, map_len_, PROT_READ | PROT_WRITE, MAP_SHARED,
                   fd_, 0);
  if (m == MAP_FAILED) {
    ::close(fd_);
    fd_ = -1;
    return;
  }
  base_ = static_cast<uint8_t*>(m);
  pool_.assign(static_cast<size_t>(dram_cap_) * stride_, 0);
  slot_row_.assign(dram_cap_, -1);
  slot_ref_.assign(dram_cap_, 0);
  row_slot_.reserve(static_cast<size_t>(dram_cap_) * 2);
}

TieredStore::~TieredStore() {
  if (base_) ::munmap(base_, map_len_);
  if (fd_ >= 0) ::close(fd_);
  if (!path_.empty()) ::unlink(path_.c_str());
}

void TieredStore::encode(const float* vals, uint8_t* dst) const {
  float scale = 0.f;
  switch (dtype_) {
    case StoreDtype::kF32:
      std::memcpy(dst, &scale, 4);
      std::memcpy(dst + 4, vals, width_ * 4);
      break;
    case StoreDtype::kF16: {
      std::memcpy(dst, &scale, 4);
      uint16_t* q = reinterpret_cast<uint16_t*>(dst + 4);
      for (int64_t k = 0; k < width_; ++k) q[k] = f32_to_f16(vals[k]);
      break;
    }
    case StoreDtype::kI8: {
      float maxabs = 0.f;
      for (int64_t k = 0; k < width_; ++k) {
        float a = std::fabs(vals[k]);
        if (a > maxabs) maxabs = a;
      }
      scale = maxabs / 127.f;
      std::memcpy(dst, &scale, 4);
      int8_t* q = reinterpret_cast<int8_t*>(dst + 4);
      if (scale == 0.f) {
        std::memset(q, 0, width_);
      } else {
        for (int64_t k = 0; k < width_; ++k) {
          float r = std::nearbyint(vals[k] / scale);
          if (r > 127.f) r = 127.f;
          if (r < -127.f) r = -127.f;
          q[k] = static_cast<int8_t>(r);
        }
      }
      break;
    }
  }
}

void TieredStore::decode(const uint8_t* src, float* out) const {
  float scale;
  std::memcpy(&scale, src, 4);
  switch (dtype_) {
    case StoreDtype::kF32:
      std::memcpy(out, src + 4, width_ * 4);
      break;
    case StoreDtype::kF16: {
      const uint16_t* q = reinterpret_cast<const uint16_t*>(src + 4);
      for (int64_t k = 0; k < width_; ++k) out[k] = f16_to_f32(q[k]);
      break;
    }
    case StoreDtype::kI8: {
      const int8_t* q = reinterpret_cast<const int8_t*>(src + 4);
      for (int64_t k = 0; k < width_; ++k) out[k] = q[k] * scale;
      break;
    }
  }
}

int64_t TieredStore::ensure_slot(int64_t r) {
  auto it = row_slot_.find(r);
  if (it != row_slot_.end()) return it->second;
  if (dram_cap_ == 0) return -1;
  // free slot first, then CLOCK second-chance eviction
  int64_t victim = -1;
  for (int64_t scanned = 0; scanned < 2 * dram_cap_; ++scanned) {
    int64_t s = hand_;
    hand_ = (hand_ + 1) % dram_cap_;
    if (slot_row_[s] < 0) {
      victim = s;
      break;
    }
    if (slot_ref_[s]) {
      slot_ref_[s] = 0;
    } else {
      victim = s;
      break;
    }
  }
  if (victim < 0) victim = hand_;     // all referenced: take the hand
  int64_t old = slot_row_[victim];
  if (old >= 0) {
    // demote: the pool copy is the authoritative one — write it down
    std::memcpy(base_ + old * stride_, pool_.data() + victim * stride_,
                stride_);
    ++st_.spill_writes;
    row_slot_.erase(old);
  }
  slot_row_[victim] = r;
  row_slot_[r] = victim;
  return victim;
}

void TieredStore::read_row(int64_t r, float* out) {
  std::lock_guard<std::mutex> l(mu_);
  if (r < 0 || r >= rows_ || !base_) {
    std::memset(out, 0, width_ * 4);
    return;
  }
  auto it = row_slot_.find(r);
  if (it != row_slot_.end()) {
    ++st_.dram_hits;
    slot_ref_[it->second] = 1;
    decode(pool_.data() + it->second * stride_, out);
    return;
  }
  ++st_.spill_hits;
  decode(base_ + r * stride_, out);
  // promote: a touched cold row moves up (CLOCK victim moves down)
  int64_t s = ensure_slot(r);
  if (s >= 0) {
    std::memcpy(pool_.data() + s * stride_, base_ + r * stride_, stride_);
    slot_ref_[s] = 1;
  }
}

void TieredStore::write_row(int64_t r, const float* vals) {
  std::lock_guard<std::mutex> l(mu_);
  if (r < 0 || r >= rows_ || !base_) return;
  int64_t s = ensure_slot(r);
  if (s >= 0) {
    encode(vals, pool_.data() + s * stride_);
    slot_ref_[s] = 1;
  } else {
    encode(vals, base_ + r * stride_);
    ++st_.spill_writes;
  }
}

TieredStore::Stats TieredStore::stats() const {
  std::lock_guard<std::mutex> l(mu_);
  Stats s = st_;
  s.dram_rows = static_cast<int64_t>(row_slot_.size());
  s.row_bytes = stride_;
  return s;
}

}  // namespace hetups
